"""ChatGLM4V (THUDM glm-4v-9b remote-code schema): EVA2-CLIP vision
tower + conv/GLU adapter over the chatglm decoder.

TPU-native counterpart of the reference's chatglm4v support
(/root/reference/python/llm/src/ipex_llm/transformers/models/chatglm4v.py:
patch_embedding_forward :293-300 — conv proj, cls token, absolute
position embedding; visual_attention_forward :261-290 — fused
query_key_value; chatglm4v_model_forward :43-93 — image features
replace the [boi, placeholder, eoi] span and every patch shares ONE
rope position). Architecture per THUDM's visual.py:

- tower: conv patch embed + cls + learned positions; transformer blocks
  apply LayerNorm to each SUBLAYER OUTPUT (x + ln(attn(x)), then
  x + ln(mlp(x)) — EVA2-CLIP's post-sublayer norm, unlike CLIP/SigLIP
  pre-LN);
- adapter: drop cls, regrid, 2x2 stride-2 conv into the text hidden
  size, then the GLU projector (linear -> LN -> gelu -> silu(gate) *
  up -> down), learned boi/eoi embeddings concatenated around the
  patches, all divided by scaling_factor;
- insertion: features (boi + patches + eoi) replace the prompt's
  3-token [boi_token_id, placeholder, eoi_token_id] span; rope
  positions repeat boi_pos+1 across every patch (llama.forward's
  `positions` override), and the cache's rope_base carries the true
  next position so decode continues correctly;
- text: the chatglm decoder (interleaved half-dim rope) — the llama
  family via the "chatglm" ModelConfig translation.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache
from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import layer_norm

# the text side delegates wholesale to the llama family (chatglm flags)
init_params = llama.init_params
quantize_params = llama.quantize_params
forward = llama.forward
merge_fused_params = llama.merge_fused_params
unmerge_fused_params = llama.unmerge_fused_params


@dataclasses.dataclass(frozen=True)
class EvaVisionConfig:
    hidden_size: int = 1792
    num_hidden_layers: int = 63
    num_heads: int = 16
    intermediate_size: int = 15360
    image_size: int = 1120
    patch_size: int = 14
    scaling_factor: float = 8.0
    layer_norm_eps: float = 1e-6
    text_hidden_size: int = 4096  # adapter output dim
    ffn_hidden_size: int = 13696  # GLU inner dim (text config's)

    @classmethod
    def from_hf(cls, vision: dict, text_hidden: int, ffn_hidden: int
                ) -> "EvaVisionConfig":
        return cls(
            hidden_size=vision["hidden_size"],
            num_hidden_layers=vision["num_hidden_layers"],
            num_heads=vision["num_heads"],
            intermediate_size=vision["intermediate_size"],
            image_size=vision["image_size"],
            patch_size=vision["patch_size"],
            scaling_factor=vision.get("scaling_factor", 8.0),
            layer_norm_eps=vision.get("layer_norm_eps", 1e-6),
            text_hidden_size=text_hidden,
            ffn_hidden_size=ffn_hidden,
        )

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def n_patches(self) -> int:  # after the 2x2 conv downsample
        return (self.grid // 2) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size ** 2


def vision_params_from_state_dict(
    vcfg: EvaVisionConfig, get, prefix: str = "transformer.vision."
) -> dict:
    """THUDM glm-4v-9b `transformer.vision.*` names -> our tree."""
    def g(name):
        return np.asarray(get(prefix + name), np.float32)

    E = vcfg.hidden_size
    blocks: dict[str, list] = {}
    names = [
        ("ln1_w", "input_layernorm.weight"), ("ln1_b", "input_layernorm.bias"),
        ("ln2_w", "post_attention_layernorm.weight"),
        ("ln2_b", "post_attention_layernorm.bias"),
        ("wqkv", "attention.query_key_value.weight"),
        ("bqkv", "attention.query_key_value.bias"),
        ("wo", "attention.dense.weight"), ("bo", "attention.dense.bias"),
        ("fc1_w", "mlp.fc1.weight"), ("fc1_b", "mlp.fc1.bias"),
        ("fc2_w", "mlp.fc2.weight"), ("fc2_b", "mlp.fc2.bias"),
    ]
    for i in range(vcfg.num_hidden_layers):
        for key, suffix in names:
            blocks.setdefault(key, []).append(
                g(f"transformer.layers.{i}.{suffix}")
            )
    params = {
        "patch_proj": g("patch_embedding.proj.weight").reshape(E, -1),
        "patch_bias": g("patch_embedding.proj.bias"),
        "cls_token": g("patch_embedding.cls_embedding").reshape(1, E),
        "pos_embed": g("patch_embedding.position_embedding.weight"),
        "blocks": {k: jnp.asarray(np.stack(v)) for k, v in blocks.items()},
        # adapter
        "conv_w": g("conv.weight"),  # [text_E, E, 2, 2]
        "conv_b": g("conv.bias"),
        "glu_in": g("linear_proj.linear_proj.weight"),
        "glu_ln_w": g("linear_proj.norm1.weight"),
        "glu_ln_b": g("linear_proj.norm1.bias"),
        "glu_gate": g("linear_proj.gate_proj.weight"),
        "glu_up": g("linear_proj.dense_h_to_4h.weight"),
        "glu_down": g("linear_proj.dense_4h_to_h.weight"),
        "boi": g("boi").reshape(1, -1),
        "eoi": g("eoi").reshape(1, -1),
    }
    return jax.tree.map(jnp.asarray, params)


def vision_forward(
    vcfg: EvaVisionConfig,
    vparams: dict,
    patches: jax.Array,  # [B, N, patch_dim] flattened pixel patches
) -> jax.Array:
    """[B, N, patch_dim] -> [B, N+1, E] tower hidden states (cls first).
    EVA2-CLIP block: x + LN(attn(x)), then x + LN(mlp(x)) — the norm
    wraps the sublayer OUTPUT (reference visual layout)."""
    B, N, _ = patches.shape
    E, Hh, D = vcfg.hidden_size, vcfg.num_heads, vcfg.head_dim
    eps = vcfg.layer_norm_eps

    h = (
        jnp.einsum("bnd,ed->bne", patches.astype(jnp.float32),
                   vparams["patch_proj"])
        + vparams["patch_bias"]
    )
    cls = jnp.broadcast_to(vparams["cls_token"][None], (B, 1, E))
    h = jnp.concatenate([cls, h], axis=1)
    h = h + vparams["pos_embed"][None, : N + 1]
    S = N + 1
    scale = D ** -0.5

    def block(h, p):
        qkv = jnp.einsum("bne,fe->bnf", h, p["wqkv"]) + p["bqkv"]
        q, k, v = jnp.split(qkv.reshape(B, S, 3, Hh, D), 3, axis=2)
        q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]
        att = jnp.einsum("bnhd,bmhd->bhnm", q, k) * scale
        att = jax.nn.softmax(att, axis=-1)
        ctx = jnp.einsum("bhnm,bmhd->bnhd", att, v).reshape(B, S, E)
        out = jnp.einsum("bne,fe->bnf", ctx, p["wo"]) + p["bo"]
        h = h + layer_norm(out, p["ln1_w"], p["ln1_b"], eps)

        x = jnp.einsum("bne,fe->bnf", h, p["fc1_w"]) + p["fc1_b"]
        x = jax.nn.gelu(x, approximate=False)
        x = jnp.einsum("bnf,ef->bne", x, p["fc2_w"]) + p["fc2_b"]
        h = h + layer_norm(x, p["ln2_w"], p["ln2_b"], eps)
        return h, None

    h, _ = jax.lax.scan(block, h, vparams["blocks"])
    return h


def image_features(
    vcfg: EvaVisionConfig,
    vparams: dict,
    patches: jax.Array,  # [B, N, patch_dim]
    out_dtype=jnp.float32,
) -> jax.Array:
    """Tower -> drop cls -> 2x2 conv -> GLU -> boi/eoi wrap ->
    / scaling_factor. Returns [B, n_patches + 2, text_hidden]."""
    h = vision_forward(vcfg, vparams, patches)[:, 1:]  # drop cls
    B, N, E = h.shape
    g = int(round(float(np.sqrt(N))))
    grid = h.reshape(B, g, g, E)  # NHWC
    x = jax.lax.conv_general_dilated(
        grid, jnp.transpose(vparams["conv_w"], (2, 3, 1, 0)),  # HWIO
        window_strides=(2, 2), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + vparams["conv_b"]
    x = x.reshape(B, -1, x.shape[-1])  # [B, (g/2)^2, text_E]

    x = jnp.einsum("bnk,fk->bnf", x, vparams["glu_in"])
    x = jax.nn.gelu(
        layer_norm(x, vparams["glu_ln_w"], vparams["glu_ln_b"], 1e-5),
        approximate=False,
    )
    x = (jax.nn.silu(jnp.einsum("bnf,gf->bng", x, vparams["glu_gate"]))
         * jnp.einsum("bnf,gf->bng", x, vparams["glu_up"]))
    x = jnp.einsum("bng,fg->bnf", x, vparams["glu_down"])

    boi = jnp.broadcast_to(vparams["boi"][None], (B, 1, x.shape[-1]))
    eoi = jnp.broadcast_to(vparams["eoi"][None], (B, 1, x.shape[-1]))
    x = jnp.concatenate([boi, x, eoi], axis=1) / vcfg.scaling_factor
    return x.astype(out_dtype)


def build_multimodal_inputs(
    config: ModelConfig,
    params: dict,
    input_ids: np.ndarray,  # [B, T] with a [boi, placeholder, eoi] span
    feats: jax.Array,  # [B, P+2, H] image_features output
    boi_token_id: int,
    eoi_token_id: int,
    compute_dtype=jnp.bfloat16,
):
    """Reference insertion semantics (chatglm4v_model_forward :60-93):
    features replace the 3-token span; every patch repeats rope position
    boi_pos+1. Returns (embeds [B, T'], positions [B, T']). Rows must
    carry the span at the same offset (one image per row, HF batch
    contract)."""
    B, T = input_ids.shape
    P2 = feats.shape[1]  # P + 2
    ids = np.asarray(input_ids)
    boi_pos = [int(np.nonzero(ids[b] == boi_token_id)[0][0]) for b in range(B)]
    eoi_pos = [int(np.nonzero(ids[b] == eoi_token_id)[0][0]) for b in range(B)]
    if len(set(boi_pos)) != 1 or len(set(eoi_pos)) != 1:
        raise ValueError("all rows must carry the image span at the same "
                         f"offset; got boi {boi_pos}, eoi {eoi_pos}")
    a, b = boi_pos[0], eoi_pos[0]
    if b - a != 2:
        raise ValueError(f"expected [boi, placeholder, eoi]; eoi-boi = {b - a}")

    h = llama.embed_tokens(config, params, jnp.asarray(ids), compute_dtype)
    embeds = jnp.concatenate(
        [h[:, :a], feats.astype(compute_dtype), h[:, b + 1:]], axis=1
    )
    base = np.arange(T, dtype=np.int32)
    positions = np.concatenate([
        base[: a + 1],
        np.full((P2 - 2,), a + 1, np.int32),  # every patch shares a+1
        base[b:],
    ])
    return embeds, jnp.asarray(np.tile(positions[None], (B, 1)))


def multimodal_prefill(
    config: ModelConfig,
    vcfg: EvaVisionConfig,
    params: dict,
    vparams: dict,
    input_ids: np.ndarray,
    patches: jax.Array,
    cache_len: int,
    boi_token_id: int,
    eoi_token_id: int,
    compute_dtype=jnp.bfloat16,
):
    """Image prefill: tower + adapter, span insertion, one text forward
    with the position override; the returned cache's rope_base carries
    the true next position so plain decode continues correctly."""
    feats = image_features(vcfg, vparams, patches, out_dtype=compute_dtype)
    embeds, positions = build_multimodal_inputs(
        config, params, input_ids, feats, boi_token_id, eoi_token_id,
        compute_dtype,
    )
    B = embeds.shape[0]
    cache = kvcache.init_cache(
        config.num_hidden_layers, B, cache_len,
        config.num_key_value_heads, config.head_dim_,
    )
    logits, cache = llama.forward(
        config, params, embeds, cache, mode="prefill",
        compute_dtype=compute_dtype, input_is_hidden=True,
        positions=positions,
    )
    cache = dataclasses.replace(
        cache, rope_base=positions[:, -1] + 1
    )
    return logits, cache
