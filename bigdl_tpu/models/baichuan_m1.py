"""Baichuan-M1 family — llama-shaped decoder with conv-enhanced KV.

TPU-native re-design of the reference's patched forward
(/root/reference/python/llm/src/ipex_llm/transformers/models/baichuan_m1.py):
before rope, the per-head K and V streams pass a kernel-2 causal
convolution over time (custom_convolution, baichuan_m1.py:41-55) —
K'[t] = w0*K[t-1] + w1*K[t] with zero padding at the sequence start —
and decode carries the PRE-conv K/V of the previous token so the next
step can finish its convolution (the reference stashes them as
`self.last_k/last_v`, baichuan_m1.py:186-203). A kernel-2 conv over time
is a shift + two broadcast multiplies here, no conv op.

`BaichuanM1Cache` composes the standard KV pool (which stores the
CONVOLVED k/v — what attention reads) with the [L, B, Hkv, D] pre-conv
tails, like yuan's filter state. The reference ignores the config's
sliding window (baichuan_m1.py:216 "ignore sliding window"); so do we.

Left padding: pad positions zero their pre-conv k/v, so the first real
token's convolution sees zeros — exactly HF's zero-padded, unpadded
single-sequence semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import kvcache
from bigdl_tpu.kvcache import KVCache
from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import apply_rotary_emb, attention, linear, rms_norm, rope_cos_sin
from bigdl_tpu.ops.rope import make_inv_freq_scaled

Params = dict[str, Any]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BaichuanM1Cache:
    kv: KVCache  # stores the CONVOLVED k/v
    last_k: jax.Array  # [L, B, Hkv, D] f32: pre-conv K of the last token
    last_v: jax.Array
    start: jax.Array  # [B]

    @property
    def pos(self):
        return self.kv.pos


def init_cache(
    config: ModelConfig,
    batch: int,
    cache_len: int,
    quantize_kv: bool = False,
    dtype=jnp.bfloat16,
) -> BaichuanM1Cache:
    L, Hkv, D = (config.num_hidden_layers, config.num_key_value_heads,
                 config.head_dim_)
    kv = kvcache.init_cache(
        L, batch, cache_len, Hkv, D, quantize_kv=quantize_kv, dtype=dtype,
    )
    # two distinct buffers: the engine donates the whole cache, and jax
    # rejects donating one aliased buffer through two arguments
    return BaichuanM1Cache(
        kv=kv,
        last_k=jnp.zeros((L, batch, Hkv, D), jnp.float32),
        last_v=jnp.zeros((L, batch, Hkv, D), jnp.float32),
        start=kv.start,
    )


# --- serving-engine adapter (serving/engine.py custom-cache protocol) ---

def engine_pool(config: ModelConfig, n_slots: int, max_len: int):
    cache = init_cache(config, n_slots, max_len)
    kv = dataclasses.replace(cache.kv, pos=jnp.zeros((n_slots,), jnp.int32))
    return dataclasses.replace(cache, kv=kv)


def engine_insert(cache, pcache, slot, pad):
    kv = kvcache.insert_row(cache.kv, pcache.kv, slot, pad)
    return dataclasses.replace(
        cache, kv=kv,
        last_k=cache.last_k.at[:, slot].set(pcache.last_k[:, 0]),
        last_v=cache.last_v.at[:, slot].set(pcache.last_v[:, 0]),
        start=kv.start,
    )


def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> Params:
    """Random dense init (tests/benchmarks run without checkpoints)."""
    L, H, I = (config.num_hidden_layers, config.hidden_size,
               config.intermediate_size)
    V, QD, KD = config.vocab_size, config.q_dim, config.kv_dim
    Hkv, D = config.num_key_value_heads, config.head_dim_
    keys = iter(jax.random.split(key, 16))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "attn_norm": jnp.ones((L, H), dtype),
        "mlp_norm": jnp.ones((L, H), dtype),
        "wqkv": w((L, QD + 2 * KD, H)),  # W_pack, fused
        "wo": w((L, H, QD)),
        "w_gate": w((L, I, H)), "w_up": w((L, I, H)), "w_down": w((L, H, I)),
        # per-kv-head kernel-2 conv taps (HF conv_k/conv_v [1,1,h,1,2])
        "conv_k": jnp.full((L, Hkv, 2), 0.5, jnp.float32),
        "conv_v": jnp.full((L, Hkv, 2), 0.5, jnp.float32),
    }
    return {
        "embed": w((V, H)),
        "layers": layers,
        "final_norm": jnp.ones((H,), dtype),
        "lm_head": w((V, H)),
    }


def quantize_params(params: Params, qtype: str, lm_head_qtype: Optional[str] = None) -> Params:
    """llama's quantizer covers the tree (wqkv/wo/gate/up/down); the tiny
    f32 conv taps stay dense."""
    return llama.quantize_params(params, qtype, lm_head_qtype)


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cache: Optional[BaichuanM1Cache],
    mode: str = "prefill",
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = False,
) -> tuple[jax.Array, Optional[BaichuanM1Cache]]:
    """Returns (logits [B, T, V] float32, advanced cache)."""
    assert mode in ("prefill", "decode")
    B, T = tokens.shape
    Hq, Hkv, D = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim_)
    QD, KD = config.q_dim, config.kv_dim
    eps = config.rms_norm_eps

    fresh = cache is None
    if fresh:
        cache = init_cache(config, B, T)
    kv = dataclasses.replace(cache.kv, start=cache.start)

    pos_col = kv.pos[:, None] if kv.pos.ndim == 1 else kv.pos
    slots = pos_col + jnp.arange(T)[None, :]  # [B|1, T]
    positions = kv.next_positions(T)  # [B, T]
    real = (slots >= cache.start[:, None]).astype(jnp.float32)
    if real.shape[0] != B:
        real = jnp.broadcast_to(real, (B, T))

    from bigdl_tpu.embedding import embed_lookup

    h = embed_lookup(params["embed"], tokens, compute_dtype)

    inv_freq, att_scale = make_inv_freq_scaled(
        config.rotary_dim, config.rope_theta, config.rope_scaling_dict,
        seq_len=kv.max_len,
    )
    cos, sin = rope_cos_sin(positions, inv_freq, scale=att_scale)

    S = kv.max_len
    sj = jnp.arange(S)
    mask = (sj[None, None, :] <= slots[..., None]) & (
        sj[None, None, :] >= cache.start[:, None, None]
    )  # [B, T, S]
    mask = mask[:, None, None]  # [B,1,1,T,S]

    realc = real[:, :, None, None]  # [B, T, 1, 1]

    def conv2(u, taps, last):
        """Kernel-2 causal conv over time, per kv head: u [B,T,Hkv,D] f32
        (pads already zeroed), taps [Hkv, 2], last [B,Hkv,D] the pre-conv
        value at slot pos-1 (zeros on fresh prefill)."""
        prev = jnp.concatenate([last[:, None], u[:, :-1]], axis=1)
        w0 = taps[None, None, :, 0, None]
        w1 = taps[None, None, :, 1, None]
        return w0 * prev + w1 * u

    def body(carry, xs):
        hidden, c, idx = carry
        p, lk, lv = xs

        x = rms_norm(hidden, p["attn_norm"], eps)
        qkv = linear(x, p["wqkv"], None, compute_dtype)
        q = qkv[..., :QD].reshape(B, T, Hq, D)
        k = qkv[..., QD:QD + KD].reshape(B, T, Hkv, D).astype(jnp.float32)
        v = qkv[..., QD + KD:].reshape(B, T, Hkv, D).astype(jnp.float32)

        # zero pads BEFORE the conv so the first real token convolves
        # against zeros (HF's zero padding at the true sequence start)
        k = k * realc
        v = v * realc
        kc = conv2(k, p["conv_k"], lk).astype(compute_dtype)
        vc = conv2(v, p["conv_v"], lv).astype(compute_dtype)
        new_lk, new_lv = k[:, -1], v[:, -1]

        q, kc = apply_rotary_emb(q, kc, cos, sin, False)

        c = kvcache.update_layer(c, idx, kc, vc)
        k_att, v_att = kvcache.read_layer(c, idx, compute_dtype)
        attn = attention(q, k_att, v_att, mask)
        out = linear(attn.reshape(B, T, Hq * D), p["wo"], None, compute_dtype)
        hidden = hidden + out

        x2 = rms_norm(hidden, p["mlp_norm"], eps)
        gate = linear(x2, p["w_gate"], None, compute_dtype)
        up = linear(x2, p["w_up"], None, compute_dtype)
        hidden = hidden + linear(
            jax.nn.silu(gate) * up, p["w_down"], None, compute_dtype
        )
        return (hidden, c, idx + 1), (new_lk, new_lv)

    (h, kv, _), (new_lk, new_lv) = jax.lax.scan(
        body, (h, kv, jnp.zeros((), jnp.int32)),
        (params["layers"], cache.last_k, cache.last_v),
    )

    if last_logits_only:
        h = h[:, -1:]
    hN = rms_norm(h, params["final_norm"], eps)
    logits = linear(hN, params["lm_head"], None, compute_dtype).astype(jnp.float32)

    if fresh:
        return logits, None
    kv = kvcache.advance(kv, T)
    return logits, BaichuanM1Cache(
        kv=kv, last_k=new_lk, last_v=new_lv, start=cache.start
    )
