"""Qwen-VL: OpenCLIP-style ViT + cross-attention resampler over the
qwen v1 decoder.

TPU-native counterpart of the reference's qwen_vl support
(/root/reference/python/llm/src/ipex_llm/transformers/models/qwen_vl.py:
qwen_vl_vision_transformer_forward :195-217, qwen_vl_resampler_forward
:178-192, image insertion in qwen_vl_model_forward :268-380). Pipeline:

- vision tower: conv patch embed (no cls token) + absolute positional
  embedding, pre-LN residual blocks (fused in_proj attention, gelu MLP);
- resampler: 256 learned queries cross-attend to the projected vision
  features — q = ln_q(query) + pos_embed, k = ln_kv(kv_proj(x)) +
  pos_embed, v WITHOUT positions (torch MultiheadAttention semantics);
- head: ln_post then a final [E, E] projection matrix;
- text: the qwen v1 decoder (fused c_attn, halved-ff MLP, logn) — the
  llama family via the "qwen" ModelConfig flags; projected image
  embeddings overwrite the placeholder positions between the
  <img>/</img> markers (hidden[a+1:b] = images in the reference; here
  the scatter keyed on config.image_token_id, like the other VL
  families).

Positional embeddings are used at their stored grid (448px/14 = 32x32
patches pooled to 16x16 queries); get_abs_pos interpolation for other
resolutions is asserted away rather than silently mis-scaled.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import layer_norm

# the text side delegates wholesale to the llama family (qwen v1 flags)
init_params = llama.init_params
quantize_params = llama.quantize_params
forward = llama.forward
merge_fused_params = llama.merge_fused_params
unmerge_fused_params = llama.unmerge_fused_params


@dataclasses.dataclass(frozen=True)
class QwenVLVisionConfig:
    image_size: int = 448
    patch_size: int = 14
    width: int = 1664  # tower hidden
    layers: int = 48
    heads: int = 16
    mlp_ratio: float = 4.9231
    output_dim: int = 4096  # resampler/query dim = text hidden
    layer_norm_eps: float = 1e-6

    @classmethod
    def from_hf(cls, visual: dict) -> "QwenVLVisionConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in visual.items() if k in keys})

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def n_queries(self) -> int:
        return (self.grid // 2) ** 2  # resampler pools 2x2

    @property
    def rs_heads(self) -> int:
        # reference Resampler: num_heads = embed_dim // 128; floored at 1
        # so reduced (test) dims stay valid instead of dividing by zero
        return max(1, self.output_dim // 128)

    @property
    def mlp_dim(self) -> int:
        return int(self.mlp_ratio * self.width)

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size ** 2


def vision_params_from_state_dict(
    vcfg: QwenVLVisionConfig, get, prefix="transformer.visual."
) -> dict:
    def g(name):
        return np.asarray(get(prefix + name), np.float32)

    W = vcfg.width
    blocks: dict[str, list] = {}
    names = [
        ("ln1_w", "ln_1.weight"), ("ln1_b", "ln_1.bias"),
        ("ln2_w", "ln_2.weight"), ("ln2_b", "ln_2.bias"),
        ("in_w", "attn.in_proj.weight"), ("in_b", "attn.in_proj.bias"),
        ("out_w", "attn.out_proj.weight"), ("out_b", "attn.out_proj.bias"),
        ("fc_w", "mlp.c_fc.weight"), ("fc_b", "mlp.c_fc.bias"),
        ("proj_w", "mlp.c_proj.weight"), ("proj_b", "mlp.c_proj.bias"),
    ]
    for i in range(vcfg.layers):
        for key, suffix in names:
            blocks.setdefault(key, []).append(
                g(f"transformer.resblocks.{i}.{suffix}")
            )
    params = {
        "conv1": g("conv1.weight").reshape(W, -1),  # [W, 3*ps*ps]
        "pos_embed": g("positional_embedding"),  # [grid^2, W]
        "ln_pre_w": g("ln_pre.weight"), "ln_pre_b": g("ln_pre.bias"),
        "blocks": {k: np.stack(v) for k, v in blocks.items()},
        "ln_post_w": g("ln_post.weight"), "ln_post_b": g("ln_post.bias"),
        "proj": g("proj"),  # [E, E]
        "rs_query": g("attn_pool.query"),  # [Q, E]
        "rs_pos": g("attn_pool.pos_embed"),  # [Q, E] 2D sincos
        "rs_kv_w": g("attn_pool.kv_proj.weight"),  # [E, W]
        "rs_in_w": g("attn_pool.attn.in_proj_weight"),  # [3E, E]
        "rs_in_b": g("attn_pool.attn.in_proj_bias"),
        "rs_out_w": g("attn_pool.attn.out_proj.weight"),
        "rs_out_b": g("attn_pool.attn.out_proj.bias"),
        "rs_lnq_w": g("attn_pool.ln_q.weight"), "rs_lnq_b": g("attn_pool.ln_q.bias"),
        "rs_lnkv_w": g("attn_pool.ln_kv.weight"), "rs_lnkv_b": g("attn_pool.ln_kv.bias"),
    }
    return jax.tree.map(jnp.asarray, params)


def _mha(q, k, v, in_w, in_b, out_w, out_b, heads: int):
    """torch.nn.MultiheadAttention semantics: fused in_proj applies Wq to
    the query stream and Wk/Wv to the key/value streams; softmax over
    keys; out_proj. q [B,Nq,E], k/v [B,Nk,E] -> [B,Nq,E]."""
    E = q.shape[-1]
    wq, wk, wv = in_w[:E], in_w[E:2 * E], in_w[2 * E:]
    bq, bk, bv = in_b[:E], in_b[E:2 * E], in_b[2 * E:]
    qp = jnp.einsum("bne,fe->bnf", q, wq) + bq
    kp = jnp.einsum("bne,fe->bnf", k, wk) + bk
    vp = jnp.einsum("bne,fe->bnf", v, wv) + bv
    B, Nq, _ = qp.shape
    Nk = kp.shape[1]
    D = E // heads
    qh = qp.reshape(B, Nq, heads, D)
    kh = kp.reshape(B, Nk, heads, D)
    vh = vp.reshape(B, Nk, heads, D)
    att = jnp.einsum("bnhd,bmhd->bhnm", qh, kh) * (D ** -0.5)
    att = jax.nn.softmax(att, axis=-1)
    ctx = jnp.einsum("bhnm,bmhd->bnhd", att, vh).reshape(B, Nq, E)
    return jnp.einsum("bne,fe->bnf", ctx, out_w) + out_b


def image_features(
    vcfg: QwenVLVisionConfig,
    vparams: dict,
    patches: jax.Array,  # [B, N, 3*ps*ps] flattened pixel patches
    out_dtype=jnp.float32,
) -> jax.Array:
    """[B, N, patch_dim] -> [B, n_queries, output_dim]: the full
    VisionTransformer.forward (conv -> +pos -> ln_pre -> blocks ->
    resampler -> ln_post -> @proj)."""
    B, N, _ = patches.shape
    assert N == vcfg.grid ** 2, (
        f"qwen_vl vision expects the stored {vcfg.grid}x{vcfg.grid} patch "
        f"grid (got {N} patches); other resolutions need pos-embed "
        "interpolation"
    )
    W, Hh = vcfg.width, vcfg.heads
    eps = vcfg.layer_norm_eps

    h = jnp.einsum(
        "bnd,wd->bnw", patches.astype(jnp.float32), vparams["conv1"]
    )
    h = h + vparams["pos_embed"][None]
    h = layer_norm(h, vparams["ln_pre_w"], vparams["ln_pre_b"], eps)

    def block(h, p):
        x = layer_norm(h, p["ln1_w"], p["ln1_b"], eps)
        h = h + _mha(x, x, x, p["in_w"], p["in_b"], p["out_w"], p["out_b"], Hh)
        x = layer_norm(h, p["ln2_w"], p["ln2_b"], eps)
        x = jnp.einsum("bnw,fw->bnf", x, p["fc_w"]) + p["fc_b"]
        x = jax.nn.gelu(x, approximate=False)
        h = h + (jnp.einsum("bnf,wf->bnw", x, p["proj_w"]) + p["proj_b"])
        return h, None

    h, _ = jax.lax.scan(block, h, vparams["blocks"])

    # resampler: queries cross-attend (positions on q and k, not v)
    E = vcfg.output_dim
    kv = jnp.einsum("bnw,ew->bne", h, vparams["rs_kv_w"])
    kv = layer_norm(kv, vparams["rs_lnkv_w"], vparams["rs_lnkv_b"], eps)
    q = layer_norm(
        vparams["rs_query"], vparams["rs_lnq_w"], vparams["rs_lnq_b"], eps
    )
    # tower grid (32x32) pools onto the query grid (16x16): k positions
    # are the stored pos_embed interpolated by the reference's
    # get_abs_pos; at the native resolution it is a 2x2 nearest
    # average-free bicubic — we require the native grid and build the
    # k-side positions by bilinear pooling of the query grid instead
    kpos = _expand_pos(vparams["rs_pos"], vcfg.grid)
    out = _mha(
        jnp.broadcast_to(q[None] + vparams["rs_pos"][None], (B, q.shape[0], E)),
        kv + kpos[None],
        kv,
        vparams["rs_in_w"], vparams["rs_in_b"],
        vparams["rs_out_w"], vparams["rs_out_b"],
        vcfg.rs_heads,
    )
    out = layer_norm(out, vparams["ln_post_w"], vparams["ln_post_b"], eps)
    out = jnp.einsum("bqe,ef->bqf", out, vparams["proj"])
    return out.astype(out_dtype)


def _expand_pos(pos: jax.Array, tgt_grid: int) -> jax.Array:
    """[q*q, E] query-grid sincos positions -> [tgt*tgt, E] via bicubic
    resize (the reference's get_abs_pos, qwen_vl.py:24-42, which
    F.interpolate(mode='bicubic')s the stored grid to the source size)."""
    q = int(round(float(np.sqrt(pos.shape[0]))))
    if q == tgt_grid:
        return pos
    grid = pos.reshape(q, q, -1)
    out = jax.image.resize(
        grid, (tgt_grid, tgt_grid, grid.shape[-1]), method="bicubic"
    )
    return out.reshape(tgt_grid * tgt_grid, -1)


def multimodal_prefill(
    config: ModelConfig,
    vcfg: QwenVLVisionConfig,
    params: dict,
    vparams: dict,
    input_ids: np.ndarray,  # [B, T] with image_token_id placeholders
    patches: jax.Array,  # [B, N, patch_dim]
    cache,
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = True,
):
    """Projected image features overwrite the placeholder positions
    (the reference writes hidden[a+1:b] between the <img>/</img> ids;
    here the scatter keys on config.image_token_id)."""
    from bigdl_tpu.models._multimodal import scatter_image_features

    img = image_features(vcfg, vparams, patches)  # [B, Q, E]
    h = scatter_image_features(config, params, input_ids, img, compute_dtype)
    return llama.forward(
        config, params, h, cache, mode="prefill", input_is_hidden=True,
        compute_dtype=compute_dtype, last_logits_only=last_logits_only,
    )
