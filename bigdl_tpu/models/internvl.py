"""InternVL (2/2.5/3, HF-converted layout): InternViT vision tower +
pixel-shuffle projector over a qwen2/llama decoder.

TPU-native counterpart of the reference's internvl support
(/root/reference/python/llm/src/ipex_llm/transformers/models/internvl.py;
dispatch at convert.py:1251-2027). Architecture per HF
modeling_internvl:

- vision tower (InternViT): Conv2d patch embed + cls token + learned
  position embeddings; pre-LN blocks whose attention output scales by a
  per-channel LayerScale lambda_1 and MLP by lambda_2; optional
  full-width RMSNorm on q/k (use_qk_norm);
- feature path: drop the cls token, reshape to the patch grid,
  pixel-shuffle downsample (spatial -> channels), then the multimodal
  projector (LayerNorm -> linear -> gelu -> linear) into the text
  hidden size;
- text side: HF-converted InternVL checkpoints carry a standard
  qwen2/llama decoder under `language_model.` — ingest/quantize/TP all
  reuse the llama-family path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import layer_norm, rms_norm

# the text side delegates wholesale to the llama family
init_params = llama.init_params
quantize_params = llama.quantize_params
forward = llama.forward
merge_fused_params = llama.merge_fused_params
unmerge_fused_params = llama.unmerge_fused_params


@dataclasses.dataclass(frozen=True)
class InternVLVisionConfig:
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    image_size: int = 448
    patch_size: int = 14
    num_channels: int = 3
    layer_norm_eps: float = 1e-6
    use_qk_norm: bool = False
    attention_bias: bool = True
    hidden_act: str = "gelu"  # HF default: exact erf gelu
    downsample_ratio: float = 0.5

    @classmethod
    def from_hf(cls, hf: dict) -> "InternVLVisionConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in hf.items() if k in keys}
        img = hf.get("image_size")
        if isinstance(img, (list, tuple)):
            kw["image_size"] = int(img[0])
        patch = hf.get("patch_size")
        if isinstance(patch, (list, tuple)):
            kw["patch_size"] = int(patch[0])
        return cls(**kw)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def patch_dim(self) -> int:
        return self.num_channels * self.patch_size ** 2


def vision_params_from_state_dict(
    vcfg: InternVLVisionConfig, get, prefix="model.vision_tower."
) -> dict:
    def g(name):
        return np.asarray(get(prefix + name), np.float32)

    E = vcfg.hidden_size
    blocks: dict[str, list] = {}
    names = [
        ("ln1_w", "layernorm_before.weight"), ("ln1_b", "layernorm_before.bias"),
        ("ln2_w", "layernorm_after.weight"), ("ln2_b", "layernorm_after.bias"),
        ("wq", "attention.q_proj.weight"), ("wk", "attention.k_proj.weight"),
        ("wv", "attention.v_proj.weight"),
        ("wo", "attention.projection_layer.weight"),
        ("bo", "attention.projection_layer.bias"),
        ("fc1_w", "mlp.fc1.weight"), ("fc1_b", "mlp.fc1.bias"),
        ("fc2_w", "mlp.fc2.weight"), ("fc2_b", "mlp.fc2.bias"),
        ("lambda1", "lambda_1"), ("lambda2", "lambda_2"),
    ]
    if vcfg.attention_bias:
        names += [("bq", "attention.q_proj.bias"),
                  ("bk", "attention.k_proj.bias"),
                  ("bv", "attention.v_proj.bias")]
    if vcfg.use_qk_norm:
        names += [("q_norm", "attention.q_norm.weight"),
                  ("k_norm", "attention.k_norm.weight")]
    for i in range(vcfg.num_hidden_layers):
        for key, suffix in names:
            blocks.setdefault(key, []).append(g(f"encoder.layer.{i}.{suffix}"))
    params = {
        "patch_proj": g("embeddings.patch_embeddings.projection.weight").reshape(E, -1),
        "patch_bias": g("embeddings.patch_embeddings.projection.bias"),
        "cls_token": g("embeddings.cls_token").reshape(1, E),
        "pos_embed": g("embeddings.position_embeddings")[0],  # [N+1, E]
        "blocks": {k: jnp.asarray(np.stack(v)) for k, v in blocks.items()},
    }
    try:  # use_mean_pooling=False variants carry a final layernorm
        params["post_ln_w"] = g("layernorm.weight")
        params["post_ln_b"] = g("layernorm.bias")
    except KeyError:
        pass
    return jax.tree.map(jnp.asarray, params)


def projector_params_from_state_dict(get, prefix="model.multi_modal_projector.") -> dict:
    def g(name):
        return jnp.asarray(np.asarray(get(prefix + name), np.float32))

    return {
        "ln_w": g("layer_norm.weight"), "ln_b": g("layer_norm.bias"),
        "fc1_w": g("linear_1.weight"), "fc1_b": g("linear_1.bias"),
        "fc2_w": g("linear_2.weight"), "fc2_b": g("linear_2.bias"),
    }


def vision_forward(
    vcfg: InternVLVisionConfig,
    vparams: dict,
    patches: jax.Array,  # [B, N, patch_dim] flattened pixel patches
    out_dtype=jnp.float32,
) -> jax.Array:
    """[B, N, patch_dim] -> [B, N+1, E] hidden states (cls token first),
    matching InternVLVisionModel.last_hidden_state."""
    B, N, _ = patches.shape
    E, Hh, D = vcfg.hidden_size, vcfg.num_attention_heads, vcfg.head_dim
    eps = vcfg.layer_norm_eps

    h = (
        jnp.einsum("bnd,ed->bne", patches.astype(jnp.float32),
                   vparams["patch_proj"])
        + vparams["patch_bias"]
    )
    cls = jnp.broadcast_to(vparams["cls_token"][None], (B, 1, E))
    h = jnp.concatenate([cls, h], axis=1)  # [B, N+1, E]
    h = h + vparams["pos_embed"][None, : N + 1]
    S = N + 1
    scale = D ** -0.5

    def block(h, p):
        x = layer_norm(h, p["ln1_w"], p["ln1_b"], eps)
        q = jnp.einsum("bne,fe->bnf", x, p["wq"])
        k = jnp.einsum("bne,fe->bnf", x, p["wk"])
        v = jnp.einsum("bne,fe->bnf", x, p["wv"])
        if "bq" in p:
            q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
        if "q_norm" in p:  # full-width RMSNorm BEFORE the head split
            q = rms_norm(q, p["q_norm"], eps)
            k = rms_norm(k, p["k_norm"], eps)
        q = q.reshape(B, S, Hh, D)
        k = k.reshape(B, S, Hh, D)
        v = v.reshape(B, S, Hh, D)
        att = jnp.einsum("bnhd,bmhd->bhnm", q, k) * scale
        att = jax.nn.softmax(att, axis=-1)
        ctx = jnp.einsum("bhnm,bmhd->bnhd", att, v).reshape(B, S, E)
        out = jnp.einsum("bne,fe->bnf", ctx, p["wo"]) + p["bo"]
        h = h + out * p["lambda1"]

        x = layer_norm(h, p["ln2_w"], p["ln2_b"], eps)
        x = jnp.einsum("bne,fe->bnf", x, p["fc1_w"]) + p["fc1_b"]
        # HF ACT2FN[hidden_act]: "gelu" = exact erf
        x = jax.nn.gelu(x, approximate=vcfg.hidden_act != "gelu")
        x = jnp.einsum("bnf,ef->bne", x, p["fc2_w"]) + p["fc2_b"]
        h = h + x * p["lambda2"]
        return h, None

    h, _ = jax.lax.scan(block, h, vparams["blocks"])
    if "post_ln_w" in vparams:
        h = layer_norm(h, vparams["post_ln_w"], vparams["post_ln_b"], eps)
    return h.astype(out_dtype)


def pixel_shuffle(feats: jax.Array, scale: float = 0.5) -> jax.Array:
    """[B, W, H, C] -> [B, H*s, W*s, C/s^2] (HF InternVLModel.pixel_shuffle
    — note the width/height swap dance is reproduced exactly)."""
    B, W, H, C = feats.shape
    x = feats.reshape(B, W, int(H * scale), int(C / scale))
    x = jnp.transpose(x, (0, 2, 1, 3))
    x = x.reshape(B, int(H * scale), int(W * scale), int(C / (scale * scale)))
    return jnp.transpose(x, (0, 2, 1, 3))


def image_features(
    vcfg: InternVLVisionConfig,
    vparams: dict,
    pparams: dict,
    patches: jax.Array,  # [B, N, patch_dim], N = grid*grid
    out_dtype=jnp.float32,
) -> jax.Array:
    """Full HF get_image_features path: tower -> drop cls -> grid ->
    pixel shuffle -> projector. Returns [B, N*ds^2, text_hidden]."""
    h = vision_forward(vcfg, vparams, patches)[:, 1:]  # drop cls
    B, N, E = h.shape
    g = int(round(float(np.sqrt(N))))
    ds = vcfg.downsample_ratio
    x = pixel_shuffle(h.reshape(B, g, g, E), ds)
    x = x.reshape(B, -1, x.shape[-1])
    x = layer_norm(x, pparams["ln_w"], pparams["ln_b"], 1e-5)
    x = jnp.einsum("bnk,fk->bnf", x, pparams["fc1_w"]) + pparams["fc1_b"]
    x = jax.nn.gelu(x, approximate=False)
    x = jnp.einsum("bnf,ef->bne", x, pparams["fc2_w"]) + pparams["fc2_b"]
    return x.astype(out_dtype)


def multimodal_prefill(
    config: ModelConfig,
    vcfg: InternVLVisionConfig,
    params: dict,
    vparams: dict,
    pparams: dict,
    input_ids: np.ndarray,  # [B, T] with image_token_id placeholders
    patches: jax.Array,  # [B, N, patch_dim]
    cache,
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = True,
):
    """Scatter projected image features over the placeholder tokens
    (per-row indexing, as minicpmv) -> standard prefill."""
    from bigdl_tpu.models._multimodal import scatter_image_features

    img = image_features(vcfg, vparams, pparams, patches)  # [B, Q, E]
    h = scatter_image_features(config, params, input_ids, img, compute_dtype)
    return llama.forward(
        config, params, h, cache, mode="prefill", input_is_hidden=True,
        compute_dtype=compute_dtype, last_logits_only=last_logits_only,
    )
