"""MiniCPM-V: SigLIP vision tower + perceiver resampler over the
minicpm/qwen2 decoder.

TPU-native counterpart of the reference's minicpm-v support
(/root/reference/python/llm/src/ipex_llm/transformers/models/minicpmv.py
patches SiglipAttention/Idefics2VisionAttention and wraps chat/generate;
dispatch at convert.py:1251-2027). Architecture per the OpenBMB
implementation:

- vpm: SigLIP vision transformer — Conv2d patch embed (expressed as one
  linear over the flattened [C * p * p] patch vector), learned position
  embeddings, pre-LN blocks (LN -> MHA -> LN -> tanh-gelu MLP), final
  post_layernorm;
- resampler: one cross-attention block with `query_num` learned queries
  attending to kv-projected vision features + 2-D sincos position
  embeddings on the keys, then LN + out-projection into the LLM hidden;
- llm: MiniCPM-V-2_5 is llama3-shaped, 2_6 is qwen2-shaped — both served
  by the existing llama family (weights under the `llm.` prefix,
  translated in convert/hf._minicpmv_layer).

The language model quantizes; the vision tower and resampler stay dense
bf16/f32 (the reference likewise only low-bits the LLM for multimodal
families, convert.py minicpmv branch).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import layer_norm

# the text side delegates wholesale to the llama family
init_params = llama.init_params
quantize_params = llama.quantize_params
forward = llama.forward
merge_fused_params = llama.merge_fused_params
unmerge_fused_params = llama.unmerge_fused_params


@dataclasses.dataclass(frozen=True)
class SiglipConfig:
    hidden_size: int = 1152
    intermediate_size: int = 4304
    num_hidden_layers: int = 27
    num_attention_heads: int = 16
    image_size: int = 980
    patch_size: int = 14
    num_channels: int = 3
    layer_norm_eps: float = 1e-6

    @classmethod
    def from_hf(cls, hf: dict) -> "SiglipConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in hf.items() if k in keys})

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def patch_dim(self) -> int:
        return self.num_channels * self.patch_size ** 2


@dataclasses.dataclass(frozen=True)
class ResamplerConfig:
    num_queries: int = 64
    embed_dim: int = 3584  # LLM hidden size
    num_heads: int = 28
    kv_dim: int = 1152  # vision hidden size


def vision_params_from_state_dict(vcfg: SiglipConfig, get, prefix="vpm.") -> dict:
    """HF SigLIP checkpoint names -> stacked param tree (blocks stacked
    along a leading depth axis for lax.scan)."""

    def g(name):
        return np.asarray(get(prefix + name), np.float32)

    E = vcfg.hidden_size
    blocks: dict[str, list] = {}
    names = [
        ("ln1_w", "layer_norm1.weight"), ("ln1_b", "layer_norm1.bias"),
        ("ln2_w", "layer_norm2.weight"), ("ln2_b", "layer_norm2.bias"),
        ("wq", "self_attn.q_proj.weight"), ("bq", "self_attn.q_proj.bias"),
        ("wk", "self_attn.k_proj.weight"), ("bk", "self_attn.k_proj.bias"),
        ("wv", "self_attn.v_proj.weight"), ("bv", "self_attn.v_proj.bias"),
        ("wo", "self_attn.out_proj.weight"), ("bo", "self_attn.out_proj.bias"),
        ("fc1_w", "mlp.fc1.weight"), ("fc1_b", "mlp.fc1.bias"),
        ("fc2_w", "mlp.fc2.weight"), ("fc2_b", "mlp.fc2.bias"),
    ]
    for i in range(vcfg.num_hidden_layers):
        for key, suffix in names:
            blocks.setdefault(key, []).append(
                g(f"encoder.layers.{i}.{suffix}")
            )
    params = {
        # Conv2d [E, C, p, p], stride == kernel -> one linear per patch
        "patch_proj": g("embeddings.patch_embedding.weight").reshape(E, -1),
        "patch_bias": g("embeddings.patch_embedding.bias"),
        "pos_embed": g("embeddings.position_embedding.weight"),
        "blocks": {k: jnp.asarray(np.stack(v)) for k, v in blocks.items()},
        "post_ln_w": g("post_layernorm.weight"),
        "post_ln_b": g("post_layernorm.bias"),
    }
    return jax.tree.map(jnp.asarray, params)


def resampler_params_from_state_dict(get, prefix="resampler.") -> dict:
    def g(name):
        return jnp.asarray(np.asarray(get(prefix + name), np.float32))

    return {
        "query": g("query"),
        "kv_proj": g("kv_proj.weight"),
        "in_proj_w": g("attn.in_proj_weight"),
        "in_proj_b": g("attn.in_proj_bias"),
        "out_proj_w": g("attn.out_proj.weight"),
        "out_proj_b": g("attn.out_proj.bias"),
        "ln_q_w": g("ln_q.weight"), "ln_q_b": g("ln_q.bias"),
        "ln_kv_w": g("ln_kv.weight"), "ln_kv_b": g("ln_kv.bias"),
        "ln_post_w": g("ln_post.weight"), "ln_post_b": g("ln_post.bias"),
        "proj": g("proj"),
    }


def siglip_forward(
    vcfg: SiglipConfig,
    vparams: dict,
    patches: jax.Array,  # [B, N, patch_dim] flattened pixel patches
    position_ids: Optional[jax.Array] = None,  # [B, N]; default arange
    out_dtype=jnp.float32,
) -> jax.Array:
    """[B, N, patch_dim] -> [B, N, E] vision features (post_layernorm
    applied). position_ids indexes the learned position table — MiniCPM-V
    passes per-slice grids for adaptive resolution."""
    B, N, _ = patches.shape
    E, Hh, D = vcfg.hidden_size, vcfg.num_attention_heads, vcfg.head_dim
    eps = vcfg.layer_norm_eps

    h = (
        jnp.einsum("bnd,ed->bne", patches.astype(jnp.float32),
                   vparams["patch_proj"])
        + vparams["patch_bias"]
    )
    if position_ids is None:
        position_ids = jnp.broadcast_to(jnp.arange(N)[None], (B, N))
    h = h + vparams["pos_embed"][position_ids]

    scale = D ** -0.5

    def block(h, p):
        x = layer_norm(h, p["ln1_w"], p["ln1_b"], eps)
        q = (jnp.einsum("bne,fe->bnf", x, p["wq"]) + p["bq"]).reshape(B, N, Hh, D)
        k = (jnp.einsum("bne,fe->bnf", x, p["wk"]) + p["bk"]).reshape(B, N, Hh, D)
        v = (jnp.einsum("bne,fe->bnf", x, p["wv"]) + p["bv"]).reshape(B, N, Hh, D)
        att = jnp.einsum("bnhd,bmhd->bhnm", q, k) * scale
        att = jax.nn.softmax(att, axis=-1)
        ctx = jnp.einsum("bhnm,bmhd->bnhd", att, v).reshape(B, N, E)
        h = h + jnp.einsum("bne,fe->bnf", ctx, p["wo"]) + p["bo"]

        x = layer_norm(h, p["ln2_w"], p["ln2_b"], eps)
        x = jnp.einsum("bne,fe->bnf", x, p["fc1_w"]) + p["fc1_b"]
        x = jax.nn.gelu(x, approximate=True)  # gelu_pytorch_tanh
        h = h + jnp.einsum("bnf,ef->bne", x, p["fc2_w"]) + p["fc2_b"]
        return h, None

    h, _ = jax.lax.scan(block, h, vparams["blocks"])
    h = layer_norm(h, vparams["post_ln_w"], vparams["post_ln_b"], eps)
    return h.astype(out_dtype)


def sincos_pos_embed_2d(embed_dim: int, h: int, w: int) -> np.ndarray:
    """[h*w, embed_dim] 2-D sincos table (OpenBMB get_2d_sincos_pos_embed):
    half the channels encode the h coordinate, half the w, each as
    interleaved sin/cos over 10000^(-2i/d_half)."""
    d_half = embed_dim // 2

    def one_dim(pos):
        omega = 1.0 / 10000 ** (np.arange(d_half // 2, dtype=np.float64)
                                / (d_half / 2.0))
        out = np.einsum("m,d->md", pos.reshape(-1).astype(np.float64), omega)
        return np.concatenate([np.sin(out), np.cos(out)], axis=1)

    gh = np.broadcast_to(np.arange(h)[:, None], (h, w))
    gw = np.broadcast_to(np.arange(w)[None, :], (h, w))
    emb = np.concatenate([one_dim(gh), one_dim(gw)], axis=1)
    return emb.astype(np.float32)  # [h*w, embed_dim]


def resampler_forward(
    rcfg: ResamplerConfig,
    rparams: dict,
    feats: jax.Array,  # [B, N, kv_dim] vision features
    tgt_size: tuple[int, int],  # (h, w) patch grid, h*w == N
    out_dtype=jnp.float32,
) -> jax.Array:
    """[B, N, kv_dim] -> [B, num_queries, embed_dim]: `query_num` learned
    queries cross-attend to the features, keys carry a 2-D sincos
    position embedding (OpenBMB Resampler.forward); then LN + proj."""
    B, N, _ = feats.shape
    E, Hh, Q = rcfg.embed_dim, rcfg.num_heads, rcfg.num_queries
    D = E // Hh

    x = jnp.einsum("bnk,ek->bne", feats.astype(jnp.float32), rparams["kv_proj"])
    x = layer_norm(x, rparams["ln_kv_w"], rparams["ln_kv_b"], 1e-5)
    q = layer_norm(rparams["query"], rparams["ln_q_w"], rparams["ln_q_b"], 1e-5)

    pos = jnp.asarray(sincos_pos_embed_2d(E, *tgt_size))  # [N, E]
    k_in = x + pos[None]
    v_in = x

    # torch.nn.MultiheadAttention packed in_proj: rows [q; k; v]
    wq, wk, wv = (rparams["in_proj_w"][i * E:(i + 1) * E] for i in range(3))
    bq, bk, bv = (rparams["in_proj_b"][i * E:(i + 1) * E] for i in range(3))
    qh = (jnp.einsum("qe,fe->qf", q, wq) + bq).reshape(Q, Hh, D)
    kh = (jnp.einsum("bne,fe->bnf", k_in, wk) + bk).reshape(B, N, Hh, D)
    vh = (jnp.einsum("bne,fe->bnf", v_in, wv) + bv).reshape(B, N, Hh, D)

    att = jnp.einsum("qhd,bnhd->bhqn", qh, kh) * (D ** -0.5)
    att = jax.nn.softmax(att, axis=-1)
    ctx = jnp.einsum("bhqn,bnhd->bqhd", att, vh).reshape(B, Q, E)
    out = jnp.einsum("bqe,fe->bqf", ctx, rparams["out_proj_w"]) + rparams["out_proj_b"]

    out = layer_norm(out, rparams["ln_post_w"], rparams["ln_post_b"], 1e-5)
    out = jnp.einsum("bqe,ef->bqf", out, rparams["proj"])
    return out.astype(out_dtype)


def multimodal_prefill(
    config: ModelConfig,
    vcfg: SiglipConfig,
    rcfg: ResamplerConfig,
    params: dict,
    vparams: dict,
    rparams: dict,
    input_ids: np.ndarray,  # [B, T] with image_token_id placeholders
    patches: jax.Array,  # [B, N, patch_dim]
    tgt_size: tuple[int, int],
    cache,
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = True,
):
    """Vision tower -> resampler -> scatter the query embeddings over the
    placeholder tokens -> standard 1-D-rope prefill (minicpm-v's LLM uses
    plain rope — no M-RoPE). Shares the tower/scatter/prefill glue with
    minicpm-o (the image-only case of minicpmo.multimodal_prefill)."""
    from bigdl_tpu.models import minicpmo  # lazy: minicpmo imports us

    return minicpmo.multimodal_prefill(
        config, params, input_ids, cache,
        vcfg=vcfg, rcfg=rcfg, vparams=vparams, rparams=rparams,
        patches=patches, tgt_size=tgt_size,
        compute_dtype=compute_dtype, last_logits_only=last_logits_only,
    )
