"""Model configuration.

One frozen dataclass covers the decoder-family architectures the
reference optimizes per-file in `transformers/models/` (llama, mistral,
qwen2, gemma2, phi3, baichuan, starcoder2, stablelm, glm, minicpm, ...;
SURVEY.md §2.2 "Model zoo"): the differences the reference encodes as
separate patched forwards (qkv bias, tied embeddings, rope scaling,
sliding window, logit softcap, partial rotary, pre/post norms, ALiBi,
MoE routing) are config flags here, resolved once at trace time — dead
branches compile away under jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden // heads
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # qwen2-style qkv bias
    attention_out_bias: bool = False  # starcoder2: o_proj bias too
    mlp_bias: bool = False
    sliding_window: Optional[int] = None  # mistral-style local attention
    # gemma2/gemma3: layer l uses sliding attention iff (l+1) % pattern != 0
    # (None = every layer sliding when sliding_window is set, like mistral)
    sliding_window_pattern: Optional[int] = None
    # explicit per-layer sliding flags (gemma3 layer_types); overrides the
    # pattern when set
    sliding_layers: Optional[tuple] = None
    # gemma3: sliding layers rope with this base instead of rope_theta
    # (and without the global layers' rope_scaling)
    rope_local_theta: Optional[float] = None
    attn_logit_softcap: Optional[float] = None  # gemma2
    final_logit_softcap: Optional[float] = None  # gemma2
    # attention scale override (gemma2 query_pre_attn_scalar**-0.5); None =
    # 1/sqrt(head_dim)
    attn_scale: Optional[float] = None
    hidden_act: str = "silu"
    gated_mlp: bool = True  # False: plain fc->act->proj (starcoder2, gpt2)
    # normalization
    norm_type: str = "rmsnorm"  # "rmsnorm" | "layernorm"
    norm_bias: bool = False  # layernorm bias (starcoder2, stablelm)
    rms_norm_offset: bool = False  # gemma (1+w) rmsnorm weights
    post_attn_norm: bool = False  # gemma2 extra norms after attn/mlp blocks
    qk_norm: bool = False  # per-head RMSNorm on q/k (qwen3-style)
    # gemma-style embedding scale
    scale_embeddings: bool = False  # multiply embed output by sqrt(hidden)
    embedding_scale: Optional[float] = None  # minicpm scale_emb multiplier
    # minicpm residual scaling: hidden += scale_depth/sqrt(L) * block_out
    residual_scale: Optional[float] = None
    logit_scale: Optional[float] = None  # minicpm/cohere: logits *= scale
    lm_head_bias: bool = False  # phi-1/2: the lm head carries a bias
    # positions
    partial_rotary_factor: float = 1.0  # stablelm 0.25, glm 0.5
    rope_interleaved: bool = False  # GPT-NeoX/GLM pair-interleaved rope
    alibi: bool = False  # baichuan-13b/bloom attention-bias positions
    # multiplier on the alibi bias: falcon-rw folds the 1/sqrt(head_dim)
    # score scale into the bias too ((scores + alibi) * inv_norm_factor,
    # HF modeling_falcon eager path); bloom/baichuan/mpt add it unscaled
    alibi_scale: Optional[float] = None
    learned_positions: bool = False  # gpt2 wpe table (rope disabled)
    # qwen v1 logn attention: q *= max(1, log_train_len(pos+1)) for
    # positions beyond the training length (HF modeling_qwen logn_tensor)
    logn_attn: bool = False
    logn_train_len: int = 0
    parallel_residual: bool = False  # gptneox: h += attn(x) + mlp(x)
    embed_layernorm: bool = False  # bloom word_embeddings_layernorm
    # MoE (mixtral / qwen2_moe); 0 experts = dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_intermediate_size: Optional[int] = None
    shared_expert_intermediate_size: Optional[int] = None  # qwen2_moe
    norm_topk_prob: bool = False  # renormalize top-k router weights
    # dispatch formulation: None = auto (dense for E<=8, ragged above),
    # or force "dense" / "ragged" (models/llama.py _moe_mlp)
    moe_dispatch: Optional[str] = None
    moe_capacity_factor: float = 1.25  # ragged: slots per expert vs even load
    # mllama (llama-3.2 vision): indices of the tanh-gated cross-attention
    # layers interleaved into the decoder (models/mllama.py)
    cross_attention_layers: Optional[tuple] = None
    # MLA (deepseek v2/v3, minicpm3 — models/deepseek.py): latent KV
    # compression ranks and split head dims; kv_lora_rank set = MLA
    q_lora_rank: Optional[int] = None
    kv_lora_rank: Optional[int] = None
    qk_nope_head_dim: Optional[int] = None
    qk_rope_head_dim: Optional[int] = None
    v_head_dim: Optional[int] = None
    # DeepSeek-MoE routing (models/deepseek.py _router)
    n_group: Optional[int] = None
    topk_group: Optional[int] = None
    topk_method: Optional[str] = None  # greedy|group_limited_greedy|noaux_tc
    scoring_func: str = "softmax"  # v3: sigmoid
    routed_scaling_factor: float = 1.0
    first_k_dense_replace: int = 0
    n_shared_experts: Optional[int] = None  # ungated, n * moe_intermediate
    # RWKV (v4/v5): attention-free recurrence (models/rwkv.py). head_size
    # set = v5 multi-head matrix state; None = v4 scalar WKV
    attention_hidden_size: Optional[int] = None
    rwkv_head_size: Optional[int] = None
    rwkv_group_norm_eps: Optional[float] = None  # v5 ln_x GroupNorm eps
    # multimodal (qwen2_vl): M-RoPE channel sections for (t, h, w) position
    # components; standard rope when the three components are equal
    mrope_section: Optional[tuple] = None
    image_token_id: Optional[int] = None
    video_token_id: Optional[int] = None
    vision_start_token_id: Optional[int] = None
    audio_token_id: Optional[int] = None  # minicpmo audio placeholders
    audio_pool_step: Optional[int] = None  # minicpmo post-projection pool

    def __post_init__(self):
        if self.moe_dispatch not in (None, "dense", "ragged"):
            raise ValueError(
                f"moe_dispatch must be None, 'dense' or 'ragged'; "
                f"got {self.moe_dispatch!r}"
            )
        # ModelConfig is a static jit argument and must hash; rope_scaling
        # arrives as a dict from HF config.json (or a list-of-pairs after a
        # JSON round-trip through save_low_bit) — normalize to a tuple.
        rs = self.rope_scaling
        if isinstance(rs, dict):
            rs = tuple(sorted((k, _hashable(v)) for k, v in rs.items()))
        elif isinstance(rs, (list, tuple)):
            rs = tuple((k, _hashable(v)) for k, v in rs)
        object.__setattr__(self, "rope_scaling", rs)
        # list-typed fields arrive as lists after a JSON round-trip
        # (save_low_bit -> load_low_bit) and must re-become tuples or the
        # config stops hashing as a static jit argument
        for f in ("sliding_layers", "cross_attention_layers",
                  "mrope_section"):
            v = getattr(self, f)
            if isinstance(v, list):
                object.__setattr__(self, f, tuple(v))

    @property
    def rope_scaling_dict(self) -> Optional[dict]:
        return dict(self.rope_scaling) if self.rope_scaling else None

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @property
    def q_dim(self) -> int:
        return self.num_attention_heads * self.head_dim_

    @property
    def kv_dim(self) -> int:
        return self.num_key_value_heads * self.head_dim_

    @property
    def rotary_dim(self) -> int:
        # keep even (rope rotates dim/2 pairs)
        r = int(self.head_dim_ * self.partial_rotary_factor)
        return r - (r % 2)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def layer_is_sliding(self, layer_idx: int) -> bool:
        """Static per-layer attention kind (gemma2 alternation / gemma3
        explicit layer_types)."""
        if self.sliding_window is None:
            return False
        if self.sliding_layers is not None:
            return bool(self.sliding_layers[layer_idx])
        if self.sliding_window_pattern is None:
            return True
        return (layer_idx + 1) % self.sliding_window_pattern != 0

    @classmethod
    def from_hf_config(cls, hf: dict[str, Any]) -> "ModelConfig":
        """Build from a HuggingFace config.json dict (the ingest path the
        reference drives through transformers AutoConfig, model.py:111)."""
        model_type = hf.get("model_type", "llama")
        if model_type == "chatglm" and isinstance(hf.get("vision_config"),
                                                  dict):
            # THUDM glm-4v-9b ships model_type "chatglm" + a vision_config
            # dict; route to the chatglm4v family (EVA2-CLIP tower over
            # the same chatglm text schema)
            model_type = "chatglm4v"
        if model_type == "Yi":
            # legacy 01-ai remote-code id (reference convert.py:1738);
            # the architecture is llama-shaped — served by the yi entry
            model_type = "yi"
        if model_type == "phi-msft":
            # mlabonne phixtral ships phi-2's legacy remote-code id
            # (reference convert.py:1685-1687 keys on num_local_experts
            # exactly this way to exclude plain phi-2)
            if hf.get("num_local_experts"):
                model_type = "phixtral"
            else:
                raise NotImplementedError(
                    "legacy phi-msft (phi-2 remote-code) checkpoints are "
                    "not supported — use the native model_type='phi' "
                    "release of phi-2"
                )
        if isinstance(hf.get("text_config"), dict):
            # multimodal configs nest the decoder fields (HF >= 4.52
            # qwen2_vl etc.); original checkpoints keep them at top level
            # — merge with the nested values winning
            hf = {**hf, **{k: v for k, v in hf["text_config"].items()
                           if v is not None}}
            hf["model_type"] = model_type
        known = {
            "vocab_size", "hidden_size", "intermediate_size",
            "num_hidden_layers", "num_attention_heads", "num_key_value_heads",
            "head_dim", "rms_norm_eps", "rope_theta", "rope_scaling",
            "max_position_embeddings", "tie_word_embeddings", "sliding_window",
            "hidden_act", "attention_bias", "mlp_bias",
            "partial_rotary_factor",
        }
        kwargs = {k: hf[k] for k in known if k in hf and hf[k] is not None}
        kwargs["model_type"] = model_type
        rs = kwargs.get("rope_scaling")
        if isinstance(rs, dict):
            # longrope/su/dynamic/yarn need the context lengths, which HF
            # stores at the TOP level of config.json (phi3: rope_scaling
            # only carries the factor lists) — inject them.
            rs = dict(rs)
            for src, dst in (
                ("original_max_position_embeddings", "original_max_position_embeddings"),
                ("max_position_embeddings", "max_position_embeddings"),
            ):
                if dst not in rs and hf.get(src) is not None:
                    rs[dst] = hf[src]
            kwargs["rope_scaling"] = rs
        builder = _HF_BUILDERS.get(model_type)
        if builder is not None:
            builder(hf, kwargs)
        if "num_key_value_heads" not in kwargs:
            kwargs["num_key_value_heads"] = kwargs.get(
                "num_attention_heads", cls.num_attention_heads
            )
        return cls(**kwargs)


def _hashable(v):
    if isinstance(v, list):
        return tuple(v)
    return v


# --- per-model_type config translation -------------------------------------
# The reference's per-arch knowledge lives in ~70 `model_type` branches of
# `_optimize_post` (convert.py:1251-2027); here it is a table of small
# config builders (weights-side counterparts live in bigdl_tpu/convert/hf.py).

def _hf_qwen2(hf, kw):
    # qwen2 has qkv bias but no o/mlp bias; HF config lacks the flag
    kw.setdefault("attention_bias", True)


def _hf_gemma(hf, kw):
    kw["scale_embeddings"] = True
    kw["rms_norm_offset"] = True
    kw.setdefault("tie_word_embeddings", True)
    kw.setdefault("hidden_act", hf.get("hidden_activation", "gelu_pytorch_tanh"))


def _hf_gemma2(hf, kw):
    _hf_gemma(hf, kw)
    kw["attn_logit_softcap"] = hf.get("attn_logit_softcapping", 50.0)
    kw["final_logit_softcap"] = hf.get("final_logit_softcapping", 30.0)
    kw["post_attn_norm"] = True
    kw["sliding_window_pattern"] = 2
    if "query_pre_attn_scalar" in hf:
        kw["attn_scale"] = hf["query_pre_attn_scalar"] ** -0.5


def _hf_gemma3(hf, kw):
    """Gemma3 text (HF Gemma3TextConfig): gemma2's norms/scales plus
    per-head q/k RMSNorm and DUAL rope — full-attention layers use
    rope_theta (+rope_scaling), sliding layers rope_local_base_freq
    unscaled. layer_types lists the alternation explicitly."""
    _hf_gemma(hf, kw)
    kw["post_attn_norm"] = True
    kw["qk_norm"] = True
    kw.setdefault("head_dim", hf.get("head_dim", 256))
    kw["rms_norm_eps"] = hf.get("rms_norm_eps", 1e-6)
    if "query_pre_attn_scalar" in hf:
        kw["attn_scale"] = hf["query_pre_attn_scalar"] ** -0.5
    lt = hf.get("layer_types")
    if lt:
        kw["sliding_layers"] = tuple(t == "sliding_attention" for t in lt)
    else:
        kw["sliding_window_pattern"] = hf.get("sliding_window_pattern", 6)
    kw["rope_local_theta"] = hf.get("rope_local_base_freq", 10000.0)


def _hf_phi3(hf, kw):
    # phi3 ships fused qkv/gate_up; split at ingest (convert/hf.py)
    kw.setdefault("tie_word_embeddings", hf.get("tie_word_embeddings", False))


def _hf_stablelm(hf, kw):
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["attention_bias"] = hf.get("use_qkv_bias", False)
    kw.setdefault("partial_rotary_factor", hf.get("partial_rotary_factor", 0.25))
    kw["rms_norm_eps"] = hf.get("layer_norm_eps", 1e-5)


def _hf_starcoder2(hf, kw):
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["attention_bias"] = hf.get("use_bias", True)
    kw["attention_out_bias"] = hf.get("use_bias", True)
    kw["mlp_bias"] = hf.get("use_bias", True)
    kw["gated_mlp"] = False
    kw["rms_norm_eps"] = hf.get("norm_epsilon", 1e-5)
    kw.setdefault("tie_word_embeddings", hf.get("tie_word_embeddings", True))


def _hf_baichuan(hf, kw):
    # 7B is rope llama-shaped; 13B (no rope, 40 heads, alibi) detected by
    # position embeddings absence → model_max_length + alibi
    if hf.get("num_attention_heads", 32) >= 40 and "rope_theta" not in hf:
        kw["alibi"] = True
    kw.setdefault(
        "max_position_embeddings",
        hf.get("model_max_length", hf.get("max_position_embeddings", 4096)),
    )


def _hf_internlm2(hf, kw):
    kw.setdefault("attention_bias", hf.get("bias", False))


def _hf_internlm(hf, kw):
    """internlm v1: llama layout with biased qkv AND o projections."""
    kw["attention_bias"] = bool(hf.get("bias", True))
    kw["attention_out_bias"] = bool(hf.get("bias", True))


def _hf_minicpm(hf, kw):
    L = kw.get("num_hidden_layers", 32)
    kw["residual_scale"] = hf.get("scale_depth", 1.0) / (L ** 0.5)
    # runtime multiplier, NOT folded into weights: with tied embeddings the
    # lm head shares the matrix and must stay unscaled
    kw["embedding_scale"] = hf.get("scale_emb", 1.0)
    if "dim_model_base" in hf and hf.get("hidden_size"):
        kw["logit_scale"] = 1.0 / (hf["hidden_size"] / hf["dim_model_base"])


def _hf_glm(hf, kw):
    kw.setdefault("partial_rotary_factor", hf.get("partial_rotary_factor", 0.5))
    kw["rope_interleaved"] = True
    kw["attention_bias"] = hf.get("attention_bias", True)
    kw.setdefault("head_dim", hf.get("head_dim"))


def _hf_gpt2(hf, kw):
    kw["hidden_size"] = hf.get("n_embd", 768)
    kw["num_hidden_layers"] = hf.get("n_layer", 12)
    kw["num_attention_heads"] = hf.get("n_head", 12)
    kw["num_key_value_heads"] = kw["num_attention_heads"]
    kw["intermediate_size"] = hf.get("n_inner") or 4 * kw["hidden_size"]
    kw["max_position_embeddings"] = hf.get("n_positions", 1024)
    kw["rms_norm_eps"] = hf.get("layer_norm_epsilon", 1e-5)
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["gated_mlp"] = False
    kw["mlp_bias"] = True
    kw["attention_bias"] = True
    kw["attention_out_bias"] = True
    kw["learned_positions"] = True
    kw["hidden_act"] = hf.get("activation_function", "gelu_new")
    kw.setdefault("tie_word_embeddings", True)


def _hf_bloom(hf, kw):
    kw["num_hidden_layers"] = hf.get("n_layer", 24)
    kw["num_attention_heads"] = hf.get("n_head", 16)
    kw["num_key_value_heads"] = kw["num_attention_heads"]
    kw["intermediate_size"] = 4 * kw.get("hidden_size", hf.get("hidden_size", 64))
    kw["rms_norm_eps"] = hf.get("layer_norm_epsilon", 1e-5)
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["gated_mlp"] = False
    kw["mlp_bias"] = True
    kw["attention_bias"] = True
    kw["attention_out_bias"] = True
    kw["alibi"] = True
    kw["embed_layernorm"] = True
    kw["hidden_act"] = "gelu_pytorch_tanh"
    kw.setdefault("tie_word_embeddings", True)


def _hf_gptneox(hf, kw):
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["gated_mlp"] = False
    kw["mlp_bias"] = True
    kw["attention_bias"] = True
    kw["attention_out_bias"] = True
    kw["parallel_residual"] = hf.get("use_parallel_residual", True)
    kw.setdefault("partial_rotary_factor", hf.get("rotary_pct", 0.25))
    kw["rope_theta"] = hf.get("rotary_emb_base", 10000.0)
    kw["rms_norm_eps"] = hf.get("layer_norm_eps", 1e-5)
    kw["hidden_act"] = hf.get("hidden_act", "gelu")


def _hf_mixtral(hf, kw):
    kw["num_experts"] = hf.get("num_local_experts", 8)
    kw["num_experts_per_tok"] = hf.get("num_experts_per_tok", 2)
    kw["norm_topk_prob"] = True


def _hf_qwen2_moe(hf, kw):
    kw.setdefault("attention_bias", True)
    kw["num_experts"] = hf.get("num_experts", 60)
    kw["num_experts_per_tok"] = hf.get("num_experts_per_tok", 4)
    kw["moe_intermediate_size"] = hf.get("moe_intermediate_size", 1408)
    kw["shared_expert_intermediate_size"] = hf.get(
        "shared_expert_intermediate_size", 5632
    )
    kw["norm_topk_prob"] = hf.get("norm_topk_prob", False)


def _hf_chatglm(hf, kw):
    """THUDM chatglm2/3 and glm-4 trust_remote_code config schema
    (reference models/chatglm2.py, chatglm4.py: interleaved rope on the
    first half of kv_channels, MQA via multi_query_group_num, fused
    query_key_value / dense_h_to_4h checkpoints)."""
    kw["num_hidden_layers"] = hf.get("num_layers", 28)
    kw["intermediate_size"] = hf.get("ffn_hidden_size", 13696)
    kw["vocab_size"] = hf.get("padded_vocab_size", hf.get("vocab_size", 65024))
    kw["head_dim"] = hf.get("kv_channels")
    if hf.get("multi_query_attention"):
        kw["num_key_value_heads"] = hf.get("multi_query_group_num", 2)
    kw["rms_norm_eps"] = hf.get("layernorm_epsilon", 1e-5)
    kw["partial_rotary_factor"] = 0.5
    kw["rope_interleaved"] = True
    # chatglm2-32k / glm-4 scale the base by rope_ratio
    # (chatglm2.py:102-109: base = 10000 * rope_ratio)
    kw["rope_theta"] = 10000.0 * hf.get("rope_ratio", 1.0)
    kw["attention_bias"] = bool(hf.get("add_qkv_bias", False))
    kw["max_position_embeddings"] = hf.get("seq_length", 8192)
    kw["tie_word_embeddings"] = bool(hf.get("tie_word_embeddings", False))
    if not hf.get("rmsnorm", True):
        kw["norm_type"] = "layernorm"


def _hf_qwen2_vl(hf, kw):
    """Qwen2-VL text side: qwen2 layout + M-RoPE. The mrope inv_freq is
    the standard one — only the application is sectioned — so
    rope_scaling is consumed here, not by make_inv_freq_scaled."""
    kw.setdefault("attention_bias", True)
    rs = kw.pop("rope_scaling", None) or {}
    if isinstance(rs, (list, tuple)):
        rs = dict(rs)
    sections = rs.get("mrope_section")
    if sections:
        kw["mrope_section"] = tuple(int(s) for s in sections)
    kw["image_token_id"] = hf.get("image_token_id", 151655)
    kw["video_token_id"] = hf.get("video_token_id", 151656)
    kw["vision_start_token_id"] = hf.get("vision_start_token_id", 151652)


def _hf_mpt(hf, kw):
    """MPT (reference models/mpt.py): alibi positions, fused Wqkv,
    non-gated gelu MLP, bias-free layernorm, tied head."""
    kw["hidden_size"] = hf.get("d_model", 4096)
    kw["num_attention_heads"] = hf.get("n_heads", 32)
    kw["num_hidden_layers"] = hf.get("n_layers", 32)
    kw["intermediate_size"] = int(
        hf.get("expansion_ratio", 4) * kw["hidden_size"]
    )
    kw["max_position_embeddings"] = hf.get("max_seq_len", 2048)
    attn = hf.get("attn_config") or {}
    kw["alibi"] = bool(attn.get("alibi", True))
    kw["norm_type"] = "layernorm"
    kw["hidden_act"] = "gelu"
    kw["gated_mlp"] = False
    kw["tie_word_embeddings"] = True
    if not hf.get("no_bias", True):
        # the weight translator (_mpt_layer) loads weights only; silently
        # dropping a biased checkpoint's biases would generate garbage
        raise NotImplementedError(
            "mpt with no_bias=False (biased linears/layernorms) is not "
            "supported; released MPT checkpoints use no_bias=True"
        )


def _mla_fields(hf, kw):
    for f in ("q_lora_rank", "kv_lora_rank", "qk_nope_head_dim",
              "qk_rope_head_dim", "v_head_dim"):
        if hf.get(f) is not None:
            kw[f] = hf[f]
    kw["rope_interleaved"] = True  # DeepSeek complex-pair rope


def _hf_deepseek_v2(hf, kw):
    """DeepSeek-V2 (HF modeling_deepseek_v2; the reference's minicpm3.py
    implements the same MLA): latent-KV attention + DeepSeek-MoE with
    group-limited greedy routing and ungated shared experts."""
    _mla_fields(hf, kw)
    kw["num_experts"] = hf.get("n_routed_experts") or 0
    kw["num_experts_per_tok"] = hf.get("num_experts_per_tok") or 2
    kw["moe_intermediate_size"] = hf.get("moe_intermediate_size")
    kw["n_shared_experts"] = hf.get("n_shared_experts")
    kw["first_k_dense_replace"] = hf.get("first_k_dense_replace", 0)
    kw["topk_method"] = hf.get("topk_method", "greedy")
    kw["n_group"] = hf.get("n_group")
    kw["topk_group"] = hf.get("topk_group")
    kw["routed_scaling_factor"] = hf.get("routed_scaling_factor", 1.0)
    kw["norm_topk_prob"] = hf.get("norm_topk_prob", False)
    kw["scoring_func"] = hf.get("scoring_func", "softmax")
    if hf.get("moe_layer_freq", 1) != 1:
        raise NotImplementedError("deepseek moe_layer_freq != 1")


def _hf_deepseek_v3(hf, kw):
    _hf_deepseek_v2(hf, kw)
    kw["topk_method"] = hf.get("topk_method", "noaux_tc")
    kw["scoring_func"] = hf.get("scoring_func", "sigmoid")
    kw["norm_topk_prob"] = hf.get("norm_topk_prob", True)


def _hf_minicpm3(hf, kw):
    """MiniCPM3 (reference models/minicpm3.py): MLA attention + the
    minicpm residual/embedding/logit scalings, dense MLP."""
    _hf_minicpm(hf, kw)
    _mla_fields(hf, kw)


def _hf_qwen3(hf, kw):
    """Qwen3: qwen2 minus the qkv bias plus per-head q/k RMSNorm."""
    kw["qk_norm"] = True
    kw.setdefault("head_dim", hf.get("head_dim"))


def _hf_qwen3_moe(hf, kw):
    _hf_qwen3(hf, kw)
    kw["num_experts"] = hf.get("num_experts", 128)
    kw["num_experts_per_tok"] = hf.get("num_experts_per_tok", 8)
    kw["moe_intermediate_size"] = hf.get("moe_intermediate_size", 768)
    kw["norm_topk_prob"] = hf.get("norm_topk_prob", False)  # HF default
    if hf.get("mlp_only_layers") or hf.get("decoder_sparse_step", 1) != 1:
        # mixed dense/MoE stacks would hit the translator with dense
        # layers lacking expert weights — fail with a clear message
        raise NotImplementedError(
            "qwen3_moe with mlp_only_layers/decoder_sparse_step != 1"
        )


def _hf_phi(hf, kw):
    """Phi-1/1.5/2 (HF modeling_phi): parallel attn+mlp sharing ONE
    input layernorm (the translator duplicates it, like falcon-7b),
    biased linears everywhere incl. the lm head, partial rotary,
    gelu_new MLP."""
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["parallel_residual"] = True
    kw["gated_mlp"] = False
    kw["mlp_bias"] = True
    kw["attention_bias"] = True
    kw["attention_out_bias"] = True
    kw["lm_head_bias"] = True
    kw["rms_norm_eps"] = hf.get("layer_norm_eps", 1e-5)
    kw.setdefault("partial_rotary_factor", hf.get("partial_rotary_factor", 0.5))
    kw["hidden_act"] = hf.get("hidden_act", "gelu_new")
    if hf.get("qk_layernorm"):
        # the translator would silently drop q/k layernorm weights
        raise NotImplementedError("phi with qk_layernorm=True")


def _hf_baichuan_m1(hf, kw):
    """Baichuan-M1: llama numerics + fused W_pack + kernel-2 K/V conv
    (models/baichuan_m1.py). The reference ignores the config's sliding
    window (baichuan_m1.py:216); so do we."""
    kw.setdefault("attention_bias", False)
    kw.pop("sliding_window", None)


def _hf_qwen(hf, kw):
    """Qwen v1 (Qwen-7B/14B remote code, reference models/qwen.py):
    fused biased c_attn, bias-free c_proj, RMSNorm, MHA, and an MLP
    whose HF intermediate_size is the SUM of the two halves (w1/w2 each
    project to intermediate//2; out = c_proj(w1(x) * silu(w2(x)))).
    Optional logn attention scaling beyond the training length."""
    kw["attention_bias"] = True
    kw["attention_out_bias"] = False
    kw["intermediate_size"] = hf.get("intermediate_size", 22016) // 2
    kw["rms_norm_eps"] = hf.get("layer_norm_epsilon", 1e-6)
    kw["max_position_embeddings"] = hf.get(
        "max_position_embeddings", hf.get("seq_length", 8192))
    if hf.get("use_logn_attn"):
        kw["logn_attn"] = True
        kw["logn_train_len"] = hf.get("seq_length", 8192)
    if "visual" in hf:  # Qwen-VL: <img>pad...pad</img> placeholders
        kw["image_token_id"] = hf["visual"].get("image_start_id", 151857) + 2
    # qwen's dynamic NTK adapts the rope base to the live sequence
    # length; fixed-shape TPU programs pin it at the training length
    # (exact within seq_length; longer contexts need an explicit
    # rope_scaling override)


def _hf_deci(hf, kw):
    """DeciLM: llama with VARIABLE GQA (num_key_value_heads_per_layer).
    Scan-stacked layers need uniform shapes, so ingest replicates each
    layer's kv heads up to the max — numerically exact (repeat_kv
    commutes with GQA grouping; convert/hf._deci_layer)."""
    per_layer = hf.get("num_key_value_heads_per_layer")
    if per_layer:
        kw["num_key_value_heads"] = max(per_layer)
    kw.setdefault("attention_bias", False)


def _hf_gptbigcode(hf, kw):
    """GPT-BigCode (starcoder v1, reference models/gptbigcode.py):
    gpt2-style learned positions + layernorm + non-gated gelu MLP, but
    nn.Linear weights (not Conv1D) and multi-query attention (1 kv
    head) via a [H + 2*head_dim] fused c_attn."""
    kw["hidden_size"] = hf.get("n_embd", 768)
    kw["num_hidden_layers"] = hf.get("n_layer", 12)
    kw["num_attention_heads"] = hf.get("n_head", 12)
    kw["num_key_value_heads"] = 1 if hf.get("multi_query", True) else (
        kw["num_attention_heads"])
    kw["intermediate_size"] = hf.get("n_inner") or 4 * kw["hidden_size"]
    kw["max_position_embeddings"] = hf.get("n_positions", 1024)
    kw["rms_norm_eps"] = hf.get("layer_norm_epsilon", 1e-5)
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["gated_mlp"] = False
    kw["mlp_bias"] = True
    kw["attention_bias"] = True
    kw["attention_out_bias"] = True
    kw["learned_positions"] = True
    kw["hidden_act"] = hf.get("activation_function", "gelu_pytorch_tanh")
    kw.setdefault("tie_word_embeddings", True)


def _hf_phixtral(hf, kw):
    """Phixtral (mlabonne MoE over phi-2 experts, reference
    models/phixtral.py): phi's parallel-residual/biased/partial-rotary
    decoder with mixtral-style top-k routing over NON-GATED fc1/fc2
    experts; routing weights renormalize after top-k. Configs use the
    legacy mixformer schema (n_embd/n_layer/rotary_dim)."""
    _hf_phi(hf, kw)
    kw["hidden_size"] = hf.get("n_embd", 2560)
    kw["num_hidden_layers"] = hf.get("n_layer", 32)
    kw["num_attention_heads"] = hf.get("n_head", 32)
    kw["num_key_value_heads"] = hf.get("n_head_kv") or kw["num_attention_heads"]
    kw["intermediate_size"] = hf.get("n_inner") or 4 * kw["hidden_size"]
    kw["max_position_embeddings"] = hf.get("n_positions", 2048)
    kw["num_experts"] = hf.get("num_local_experts", 4)
    kw["num_experts_per_tok"] = hf.get("num_experts_per_tok", 2)
    kw["norm_topk_prob"] = True
    kw["rms_norm_eps"] = hf.get("layer_norm_epsilon", 1e-5)
    kw["hidden_act"] = hf.get("activation_function", "gelu_new")
    kw["lm_head_bias"] = True
    if "rotary_dim" in hf:
        head_dim = kw["hidden_size"] // kw["num_attention_heads"]
        kw["partial_rotary_factor"] = hf["rotary_dim"] / head_dim


def _hf_cohere(hf, kw):
    """Cohere / Command-R: bias-free LayerNorm, parallel attn+mlp over
    one shared norm, interleaved rope, logits scaled by logit_scale,
    tied embeddings."""
    kw["norm_type"] = "layernorm"
    kw["parallel_residual"] = True
    kw["rope_interleaved"] = True
    kw["rms_norm_eps"] = hf.get("layer_norm_eps", 1e-5)
    kw["logit_scale"] = hf.get("logit_scale", 0.0625)
    kw["attention_bias"] = bool(hf.get("attention_bias", False))
    kw.setdefault("tie_word_embeddings", hf.get("tie_word_embeddings", True))
    if hf.get("use_qk_norm"):
        raise NotImplementedError(
            "cohere use_qk_norm=True (per-head LayerNorm) is not supported"
        )


def _hf_janus(hf, kw):
    """Janus/Janus-Pro understanding path: the merged text_config is
    llama-shaped; keep the image placeholder id for the feature
    scatter (models/janus.py)."""
    kw["image_token_id"] = hf.get("image_token_id", hf.get("image_token_index"))


def _hf_internvl(hf, kw):
    """InternVL (HF-converted layout): the merged text_config is
    qwen2 or llama shaped; apply the text architecture's defaults and
    keep the image token id (models/internvl.py scatters features
    there)."""
    inner = (hf.get("text_config") or {}).get("model_type", "qwen2")
    if inner == "qwen2":
        kw.setdefault("attention_bias", True)
    kw["image_token_id"] = hf.get("image_token_id", hf.get("image_token_index"))


def _hf_mllama(hf, kw):
    """Mllama / Llama-3.2-Vision text side (reference models/mllama.py;
    HF MllamaTextConfig — from_hf_config already merged the nested
    text_config). The embedding table carries 8 extra special-image rows
    beyond vocab_size (handled by the translator); lm_head stays at
    vocab_size."""
    kw["cross_attention_layers"] = tuple(
        int(i) for i in hf.get("cross_attention_layers", ())
    )


def _hf_minicpmv(hf, kw):
    """MiniCPM-V (reference models/minicpmv.py): the LLM half is
    llama3-shaped (2_5) or qwen2-shaped (2_6, version >= 2.6 in
    config.json); vision/resampler configs are consumed separately by
    models/minicpmv.py. The image placeholder id comes from the
    tokenizer's <unk>/<image> id — overridable at generate time."""
    if float(hf.get("version", 2.6)) >= 2.6:
        kw.setdefault("attention_bias", True)  # qwen2 qkv bias
    kw.setdefault("image_token_id", hf.get("image_token_id", 0))


def _hf_minicpmo(hf, kw):
    """MiniCPM-o 2.6 (reference convert.py:1030-1041, 1963-1983): the
    LLM half is qwen2-shaped at the top level of config.json; vision
    (SigLIP + resampler) and audio (Whisper encoder + projection)
    configs are consumed separately by models/minicpmo.py."""
    kw.setdefault("attention_bias", True)  # qwen2 qkv bias
    kw.setdefault("image_token_id", hf.get("image_token_id", 0))
    # no silent default: the published config carries no audio_token_id,
    # and defaulting it to 0 would collide with the image placeholder —
    # callers set it from their tokenizer (models/minicpmo.py docstring)
    if "audio_token_id" in hf:
        kw.setdefault("audio_token_id", hf["audio_token_id"])
    # default (2) lives in one place: models/minicpmo.DEFAULT_AUDIO_POOL_STEP
    if "audio_pool_step" in hf:
        kw.setdefault("audio_pool_step", hf["audio_pool_step"])


def _hf_qwen2_audio(hf, kw):
    """Qwen2-Audio (reference convert.py:969-971, 1655-1656): the text
    half is qwen2 (nested text_config, merged by from_hf_config); the
    <|AUDIO|> placeholder id is the top-level audio_token_index."""
    kw.setdefault("attention_bias", True)  # qwen2 qkv bias
    if hf.get("audio_token_index") is not None:
        kw.setdefault("audio_token_id", hf["audio_token_index"])


def _hf_yuan(hf, kw):
    """Yuan-2 (reference models/yuan.py; original schema in
    gguf/models/model_implement/yuan2/configuration_yuan.py): llama
    fields + LFA conv filter handled by models/yuan.py."""
    kw.setdefault(
        "max_position_embeddings",
        hf.get("model_max_length", hf.get("max_position_embeddings", 8192)),
    )


def _hf_falcon(hf, kw):
    """Falcon (reference gguf/models/falcon.py; HF modeling_falcon.py).
    Three variants: falcon-rw (alibi, sequential residual), falcon-7b
    (multi-query + parallel attn/mlp sharing ONE input layernorm — the
    translator duplicates it into attn_norm/mlp_norm), falcon-40b/180b
    (new_decoder_architecture: GQA + separate ln_attn/ln_mlp)."""
    kw["num_attention_heads"] = hf.get("num_attention_heads", hf.get("n_head", 71))
    kw["num_hidden_layers"] = hf.get("num_hidden_layers", hf.get("n_layer", 32))
    if hf.get("new_decoder_architecture"):
        kw["num_key_value_heads"] = hf.get("num_kv_heads", 8)
    elif hf.get("multi_query", True):
        kw["num_key_value_heads"] = 1
    else:
        kw["num_key_value_heads"] = kw["num_attention_heads"]
    kw["intermediate_size"] = hf.get("ffn_hidden_size") or 4 * hf.get(
        "hidden_size", 4544
    )
    kw["rms_norm_eps"] = hf.get("layer_norm_epsilon", 1e-5)
    kw["norm_type"] = "layernorm"
    kw["norm_bias"] = True
    kw["gated_mlp"] = False
    kw["hidden_act"] = "gelu"
    kw["mlp_bias"] = bool(hf.get("bias", False))
    kw["attention_bias"] = bool(hf.get("bias", False))
    kw["attention_out_bias"] = bool(hf.get("bias", False))
    kw["parallel_residual"] = bool(
        hf.get("parallel_attn", True) or hf.get("new_decoder_architecture")
    )
    if hf.get("alibi"):
        kw["alibi"] = True
        head_dim = hf.get("hidden_size", 4544) // kw["num_attention_heads"]
        kw["alibi_scale"] = head_dim ** -0.5
    kw.setdefault("tie_word_embeddings", hf.get("tie_word_embeddings", True))


def _hf_rwkv(hf, kw):
    """RWKV v4 (HF `rwkv` config schema: modeling_rwkv.py in
    transformers; reference models/rwkv4.py). layer_norm_epsilon feeds
    every LayerNorm; rescale_every is an fp16-overflow trick HF applies
    only in half precision — exact under LN invariance, skipped here
    (we compute the recurrence in f32)."""
    kw["attention_hidden_size"] = hf.get(
        "attention_hidden_size", hf.get("hidden_size", 4096)
    )
    kw["intermediate_size"] = (
        hf.get("intermediate_size") or 4 * hf.get("hidden_size", 4096)
    )
    kw["rms_norm_eps"] = hf.get("layer_norm_epsilon", 1e-5)
    kw["norm_type"] = "layernorm"
    kw["max_position_embeddings"] = hf.get("context_length", 1024)
    kw.setdefault("num_attention_heads", 1)
    kw["num_key_value_heads"] = kw["num_attention_heads"]
    kw["tie_word_embeddings"] = bool(hf.get("tie_word_embeddings", False))


def _hf_rwkv5(hf, kw):
    """RWKV v5 "Eagle" (trust_remote_code schema, e.g. rwkv-5-world;
    reference models/rwkv5.py): multi-head matrix state with head_size
    (64), gate branch, GroupNorm ln_x whose eps scales with
    head_size_divisor."""
    _hf_rwkv(hf, kw)
    kw["rwkv_head_size"] = hf.get("head_size", 64)
    kw["rwkv_group_norm_eps"] = 1e-5 * float(hf.get("head_size_divisor", 8)) ** 2
    kw["num_attention_heads"] = kw["attention_hidden_size"] // kw["rwkv_head_size"]
    kw["num_key_value_heads"] = kw["num_attention_heads"]


_HF_BUILDERS = {
    "qwen2": _hf_qwen2,
    "qwen2_vl": _hf_qwen2_vl,
    "chatglm": _hf_chatglm,
    "mpt": _hf_mpt,
    "gemma": _hf_gemma,
    "gemma2": _hf_gemma2,
    "gemma3": _hf_gemma3,
    "gemma3_text": _hf_gemma3,
    "phi3": _hf_phi3,
    # phi-3-vision: the reference optimizes it as phi3 (convert.py:947,
    # :1829 `in ["phi3", "phi3_v"]`); text fields are phi3's, the CLIP
    # tower weights are simply not loaded on the text path
    "phi3_v": _hf_phi3,
    "stablelm": _hf_stablelm,
    "starcoder2": _hf_starcoder2,
    "baichuan": _hf_baichuan,
    "internlm2": _hf_internlm2,
    # internlm-xcomposer2: internlm2 decoder + per-linear Plora deltas
    # that apply only to image-token rows (reference convert.py:984,
    # :1523); the text path (im_mask=None) is exactly internlm2, and the
    # Plora_A/B checkpoint keys are ignored by the internlm2 translation
    "internlmxcomposer2": _hf_internlm2,
    "internlm": _hf_internlm,
    "minicpm": _hf_minicpm,
    "glm": _hf_glm,
    "gpt2": _hf_gpt2,
    "bloom": _hf_bloom,
    "gpt_neox": _hf_gptneox,
    "mixtral": _hf_mixtral,
    "qwen2_moe": _hf_qwen2_moe,
    "rwkv": _hf_rwkv,
    "rwkv5": _hf_rwkv5,
    "falcon": _hf_falcon,
    "yuan": _hf_yuan,
    "minicpmv": _hf_minicpmv,
    "minicpmo": _hf_minicpmo,
    "qwen2_audio": _hf_qwen2_audio,
    "mllama": _hf_mllama,
    "mllama_text_model": _hf_mllama,
    "deepseek_v2": _hf_deepseek_v2,
    "deepseek_v3": _hf_deepseek_v3,
    "minicpm3": _hf_minicpm3,
    "internvl": _hf_internvl,
    "internvl_chat": _hf_internvl,
    "janus": _hf_janus,
    "multi_modality": _hf_janus,  # janus checkpoints' original model_type
    "qwen3": _hf_qwen3,
    "qwen3_moe": _hf_qwen3_moe,
    "phi": _hf_phi,
    "cohere": _hf_cohere,
    "qwen": _hf_qwen,
    "qwen_vl": _hf_qwen,  # Qwen-VL ships model_type "qwen" + visual dict
    "chatglm4v": _hf_chatglm,  # glm-4v: chatglm text schema + vision_config
    "deci": _hf_deci,
    "gpt_bigcode": _hf_gptbigcode,
    "phixtral": _hf_phixtral,
    "baichuan_m1": _hf_baichuan_m1,
}


# Canonical shapes for tests and benchmarks (no checkpoints needed).
PRESETS: dict[str, ModelConfig] = {
    "tiny-llama": ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    ),
    "llama2-7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=32,
    ),
    "llama3-8b": ModelConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        rope_theta=500000.0, max_position_embeddings=8192,
    ),
    "mistral-7b": ModelConfig(
        model_type="mistral", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_hidden_layers=32,
        num_attention_heads=32, num_key_value_heads=8,
        sliding_window=4096, rope_theta=1000000.0,
    ),
    "qwen2-7b": ModelConfig(
        model_type="qwen2", vocab_size=152064, hidden_size=3584,
        intermediate_size=18944, num_hidden_layers=28,
        num_attention_heads=28, num_key_value_heads=4,
        attention_bias=True, rope_theta=1000000.0,
    ),
    "gemma2-9b": ModelConfig(
        model_type="gemma2", vocab_size=256000, hidden_size=3584,
        intermediate_size=14336, num_hidden_layers=42,
        num_attention_heads=16, num_key_value_heads=8, head_dim=256,
        scale_embeddings=True, rms_norm_offset=True, post_attn_norm=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        sliding_window=4096, sliding_window_pattern=2,
        attn_scale=224.0 ** -0.5, tie_word_embeddings=True,
        hidden_act="gelu_pytorch_tanh",
    ),
    "phi3-mini": ModelConfig(
        model_type="phi3", vocab_size=32064, hidden_size=3072,
        intermediate_size=8192, num_hidden_layers=32,
        num_attention_heads=32, num_key_value_heads=32,
        max_position_embeddings=4096,
    ),
    "mixtral-8x7b": ModelConfig(
        model_type="mixtral", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_hidden_layers=32,
        num_attention_heads=32, num_key_value_heads=8,
        rope_theta=1000000.0, num_experts=8, num_experts_per_tok=2,
        norm_topk_prob=True,
    ),
}
