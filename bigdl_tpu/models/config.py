"""Model configuration.

One frozen dataclass covers the decoder-family architectures the
reference optimizes per-file in `transformers/models/` (llama, mistral,
qwen2, ...; SURVEY.md §2.2 "Model zoo"): the differences the reference
encodes as separate patched forwards (qkv bias, tied embeddings, rope
scaling, sliding window, logit softcap) are config flags here, resolved
once at trace time — dead branches compile away under jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden // heads
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # qwen2-style qkv bias
    mlp_bias: bool = False
    sliding_window: Optional[int] = None  # mistral-style local attention
    attn_logit_softcap: Optional[float] = None  # gemma2
    final_logit_softcap: Optional[float] = None  # gemma2
    hidden_act: str = "silu"
    # gemma-style normalizations
    scale_embeddings: bool = False  # multiply embed output by sqrt(hidden)
    post_attn_norm: bool = False  # gemma2 extra norms around blocks
    rms_norm_offset: bool = False  # gemma (1+w) rmsnorm weights

    def __post_init__(self):
        # ModelConfig is a static jit argument and must hash; rope_scaling
        # arrives as a dict from HF config.json (or a list-of-pairs after a
        # JSON round-trip through save_low_bit) — normalize to a tuple.
        rs = self.rope_scaling
        if isinstance(rs, dict):
            rs = tuple(sorted(rs.items()))
        elif isinstance(rs, (list, tuple)):
            rs = tuple(tuple(kv) for kv in rs)
        object.__setattr__(self, "rope_scaling", rs)

    @property
    def rope_scaling_dict(self) -> Optional[dict]:
        return dict(self.rope_scaling) if self.rope_scaling else None

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @property
    def q_dim(self) -> int:
        return self.num_attention_heads * self.head_dim_

    @property
    def kv_dim(self) -> int:
        return self.num_key_value_heads * self.head_dim_

    @classmethod
    def from_hf_config(cls, hf: dict[str, Any]) -> "ModelConfig":
        """Build from a HuggingFace config.json dict (the ingest path the
        reference drives through transformers AutoConfig, model.py:111)."""
        model_type = hf.get("model_type", "llama")
        known = {
            "vocab_size", "hidden_size", "intermediate_size",
            "num_hidden_layers", "num_attention_heads", "num_key_value_heads",
            "head_dim", "rms_norm_eps", "rope_theta", "rope_scaling",
            "max_position_embeddings", "tie_word_embeddings", "sliding_window",
            "hidden_act", "attention_bias", "mlp_bias",
        }
        kwargs = {k: hf[k] for k in known if k in hf and hf[k] is not None}
        kwargs["model_type"] = model_type
        if model_type == "qwen2":
            # qwen2 has qkv bias but no o/mlp bias; HF config lacks the flag
            kwargs.setdefault("attention_bias", True)
        if "num_key_value_heads" not in kwargs:
            kwargs["num_key_value_heads"] = kwargs.get(
                "num_attention_heads", cls.num_attention_heads
            )
        if model_type == "gemma2":
            kwargs["attn_logit_softcap"] = hf.get("attn_logit_softcapping", 50.0)
            kwargs["final_logit_softcap"] = hf.get("final_logit_softcapping", 30.0)
            kwargs["scale_embeddings"] = True
            kwargs["post_attn_norm"] = True
            kwargs["rms_norm_offset"] = True
            kwargs.setdefault("tie_word_embeddings", True)
        return cls(**kwargs)


# Canonical shapes for tests and benchmarks (no checkpoints needed).
PRESETS: dict[str, ModelConfig] = {
    "tiny-llama": ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    ),
    "llama2-7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=32,
    ),
    "llama3-8b": ModelConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        rope_theta=500000.0, max_position_embeddings=8192,
    ),
    "mistral-7b": ModelConfig(
        model_type="mistral", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_hidden_layers=32,
        num_attention_heads=32, num_key_value_heads=8,
        sliding_window=4096, rope_theta=1000000.0,
    ),
    "qwen2-7b": ModelConfig(
        model_type="qwen2", vocab_size=152064, hidden_size=3584,
        intermediate_size=18944, num_hidden_layers=28,
        num_attention_heads=28, num_key_value_heads=4,
        attention_bias=True, rope_theta=1000000.0,
    ),
}
