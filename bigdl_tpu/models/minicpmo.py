"""MiniCPM-o 2.6: MiniCPM-V's SigLIP tower + resampler, plus a
Whisper-encoder audio tower ("apm") projected into the qwen2-shaped LLM.

Reference support lives in convert.py:1030-1041 (_optimize_pre: vpm
merge_qkv, tts optimized as its own model, llm treated as qwen2) and
convert.py:1963-1983 (_optimize_post: patches the vpm's SiglipAttention
and the apm's WhisperSdpaAttention); the modeling itself is OpenBMB
remote code. The audio path follows the published MiniCPM-o
architecture:

    apm (Whisper encoder over mel chunks)
      -> audio_projection_layer (linear -> relu -> linear, apm hidden ->
         LLM hidden)
      -> AvgPool1d(audio_pool_step) over time
      -> scattered over the prompt's audio placeholder tokens

The vision path is identical to minicpmv (vpm + resampler, re-exported
below). Only the LLM quantizes; towers stay dense, as the reference
does for multimodal families. The TTS head is out of scope — it is a
separate generation model the reference merely re-optimizes, not part
of the language-understanding path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama, whisper
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.models.minicpmv import (  # noqa: F401 — re-exported vision path
    ResamplerConfig,
    SiglipConfig,
    resampler_forward,
    resampler_params_from_state_dict,
    siglip_forward,
    vision_params_from_state_dict,
)
from bigdl_tpu.models.whisper import WhisperConfig

# the text side delegates wholesale to the llama family (qwen2-shaped)
init_params = llama.init_params
quantize_params = llama.quantize_params
forward = llama.forward
merge_fused_params = llama.merge_fused_params
unmerge_fused_params = llama.unmerge_fused_params

DEFAULT_AUDIO_POOL_STEP = 2


def apm_params_from_state_dict(wcfg: WhisperConfig, get, prefix: str = "apm.") -> dict:
    """Translate the checkpoint's WhisperEncoder weights (stored directly
    under `apm.` — conv1/conv2, embed_positions, layers.N.*, layer_norm)
    into the encoder subset of models/whisper.py's param tree, so
    whisper.encode runs the tower unchanged. Delegates to the shared
    translator (whisper.encoder_params_from_state_dict); the tower stays
    dense, like the vision path."""
    return whisper.encoder_params_from_state_dict(wcfg, get, prefix)


def audio_proj_params_from_state_dict(
    get, prefix: str = "audio_projection_layer.",
) -> dict:
    """MultiModalProjector: linear1 -> relu -> linear2."""

    def g(name):
        return jnp.asarray(np.asarray(get(prefix + name), np.float32))

    return {
        "w1": g("linear1.weight"), "b1": g("linear1.bias"),
        "w2": g("linear2.weight"), "b2": g("linear2.bias"),
    }


def audio_embed(
    wcfg: WhisperConfig,
    aparams: dict,
    pparams: dict,
    mel: jax.Array,  # [B, n_mels, T_audio]
    pool_step: int = DEFAULT_AUDIO_POOL_STEP,
    out_dtype=jnp.float32,
) -> jax.Array:
    """mel -> [B, floor(T_audio/2/pool_step), E_llm] audio embeddings:
    Whisper encoder, MultiModalProjector, then non-overlapping mean pool
    over time (AvgPool1d(pool_step, stride=pool_step) semantics — a
    trailing partial window is dropped)."""
    enc = whisper.encode(wcfg, aparams, mel)  # [B, S, H]
    x = jnp.einsum("bsh,eh->bse", enc.astype(jnp.float32), pparams["w1"])
    x = jax.nn.relu(x + pparams["b1"])
    x = jnp.einsum("bse,fe->bsf", x, pparams["w2"]) + pparams["b2"]
    B, S, E = x.shape
    S_out = S // pool_step
    x = x[:, : S_out * pool_step].reshape(B, S_out, pool_step, E).mean(axis=2)
    return x.astype(out_dtype)


def multimodal_prefill(
    config: ModelConfig,
    params: dict,
    input_ids: np.ndarray,  # [B, T] with image/audio placeholder ids
    cache,
    vcfg: Optional[SiglipConfig] = None,
    rcfg: Optional[ResamplerConfig] = None,
    vparams: Optional[dict] = None,
    rparams: Optional[dict] = None,
    patches: Optional[jax.Array] = None,  # [B, N, patch_dim]
    tgt_size: Optional[tuple] = None,
    wcfg: Optional[WhisperConfig] = None,
    aparams: Optional[dict] = None,
    pparams: Optional[dict] = None,
    mel: Optional[jax.Array] = None,  # [B, n_mels, T_audio]
    audio: Optional[jax.Array] = None,  # precomputed audio_embed output
    pool_step: Optional[int] = None,  # default: config.audio_pool_step
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = True,
):
    """Vision and/or audio towers -> scatter over placeholders ->
    standard 1-D-rope prefill (the minicpm-o LLM uses plain rope).
    Pass either `mel` (tower runs here) or precomputed `audio` features
    to skip a second tower pass."""
    from bigdl_tpu.models._multimodal import scatter_image_features

    img = None
    if patches is not None:
        feats = siglip_forward(vcfg, vparams, patches)
        img = resampler_forward(rcfg, rparams, feats, tgt_size)
    if audio is None and mel is not None:
        if pool_step is None:
            pool_step = (
                config.audio_pool_step
                if config.audio_pool_step is not None
                else DEFAULT_AUDIO_POOL_STEP
            )
        audio = audio_embed(wcfg, aparams, pparams, mel, pool_step)
    h = scatter_image_features(
        config, params, input_ids, img, compute_dtype, audio=audio,
    )
    return llama.forward(
        config, params, h, cache, mode="prefill", input_is_hidden=True,
        compute_dtype=compute_dtype, last_logits_only=last_logits_only,
    )
