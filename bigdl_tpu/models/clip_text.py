"""CLIP text encoder (SD's conditioning model), TPU-native.

The reference runs the text encoder inside stock torch diffusers; here
it is jnp so the whole SD pipeline (encode -> denoise -> decode) stays
on-device (models/sd.py). Layout per HF `CLIPTextModel` (SD 1.x uses
openai/clip-vit-large-patch14: 12 layers, width 768, quick_gelu):

- token + learned position embeddings;
- pre-LN transformer blocks with CAUSAL attention (CLIP's text side is
  autoregressive-masked);
- final LayerNorm; SD consumes `last_hidden_state` (not the pooled
  projection), so the projection head is omitted.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.ops import layer_norm
from bigdl_tpu.ops.linear import linear


@dataclasses.dataclass(frozen=True)
class ClipTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 77
    layer_norm_eps: float = 1e-5
    hidden_act: str = "quick_gelu"  # SD1.x; SD2 uses "gelu"

    @classmethod
    def from_hf(cls, hf: dict) -> "ClipTextConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in hf.items() if k in keys})

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def init_params(config: ClipTextConfig, key: jax.Array,
                dtype=jnp.float32) -> dict:
    counter = [0]

    def nxt():
        counter[0] += 1
        return jax.random.fold_in(key, counter[0])

    def w(shape, scale=0.02):
        return (jax.random.normal(nxt(), shape, jnp.float32) * scale
                ).astype(dtype)

    E, I, L = (config.hidden_size, config.intermediate_size,
               config.num_hidden_layers)
    layers = {
        "ln1_w": jnp.ones((L, E), dtype), "ln1_b": jnp.zeros((L, E), dtype),
        "ln2_w": jnp.ones((L, E), dtype), "ln2_b": jnp.zeros((L, E), dtype),
        "wq": w((L, E, E)), "bq": jnp.zeros((L, E), dtype),
        "wk": w((L, E, E)), "bk": jnp.zeros((L, E), dtype),
        "wv": w((L, E, E)), "bv": jnp.zeros((L, E), dtype),
        "wo": w((L, E, E)), "bo": jnp.zeros((L, E), dtype),
        "fc1": w((L, I, E)), "b1": jnp.zeros((L, I), dtype),
        "fc2": w((L, E, I)), "b2": jnp.zeros((L, E), dtype),
    }
    return {
        "tok": w((config.vocab_size, E)),
        "pos": w((config.max_position_embeddings, E)),
        "layers": layers,
        "lnf_w": jnp.ones((E,), dtype), "lnf_b": jnp.zeros((E,), dtype),
    }


def params_from_state_dict(config: ClipTextConfig, get,
                           prefix: str = "text_model.") -> dict:
    """HF CLIPTextModel state_dict -> our stacked-layer tree."""
    def g(name):
        return np.asarray(get(prefix + name), np.float32)

    names = [
        ("ln1_w", "layer_norm1.weight"), ("ln1_b", "layer_norm1.bias"),
        ("ln2_w", "layer_norm2.weight"), ("ln2_b", "layer_norm2.bias"),
        ("wq", "self_attn.q_proj.weight"), ("bq", "self_attn.q_proj.bias"),
        ("wk", "self_attn.k_proj.weight"), ("bk", "self_attn.k_proj.bias"),
        ("wv", "self_attn.v_proj.weight"), ("bv", "self_attn.v_proj.bias"),
        ("wo", "self_attn.out_proj.weight"), ("bo", "self_attn.out_proj.bias"),
        ("fc1", "mlp.fc1.weight"), ("b1", "mlp.fc1.bias"),
        ("fc2", "mlp.fc2.weight"), ("b2", "mlp.fc2.bias"),
    ]
    layers: dict[str, list] = {}
    for i in range(config.num_hidden_layers):
        for key, suffix in names:
            layers.setdefault(key, []).append(
                g(f"encoder.layers.{i}.{suffix}")
            )
    return {
        "tok": jnp.asarray(g("embeddings.token_embedding.weight")),
        "pos": jnp.asarray(g("embeddings.position_embedding.weight")),
        "layers": {k: jnp.asarray(np.stack(v)) for k, v in layers.items()},
        "lnf_w": jnp.asarray(g("final_layer_norm.weight")),
        "lnf_b": jnp.asarray(g("final_layer_norm.bias")),
    }


def forward(
    config: ClipTextConfig,
    params: dict,
    tokens: jax.Array,  # [B, T] int32
    compute_dtype=jnp.float32,
) -> jax.Array:
    """Returns last_hidden_state [B, T, E] (post final LayerNorm) — the
    conditioning tensor SD's cross-attention consumes."""
    B, T = tokens.shape
    E, Hh, D = (config.hidden_size, config.num_attention_heads,
                config.head_dim)
    eps = config.layer_norm_eps

    h = (params["tok"][tokens] + params["pos"][None, :T]).astype(compute_dtype)
    ti = jnp.arange(T)
    mask = (ti[None, :] <= ti[:, None])[None, None]  # causal [1,1,T,T]

    def block(h, p):
        x = layer_norm(h, p["ln1_w"], p["ln1_b"], eps)
        q = (linear(x, p["wq"], p["bq"], compute_dtype)
             .reshape(B, T, Hh, D))
        k = (linear(x, p["wk"], p["bk"], compute_dtype)
             .reshape(B, T, Hh, D))
        v = (linear(x, p["wv"], p["bv"], compute_dtype)
             .reshape(B, T, Hh, D))
        att = jnp.einsum("bthd,bshd->bhts", q, k) * (D ** -0.5)
        att = jnp.where(mask, att, -jnp.inf)
        att = jax.nn.softmax(att.astype(jnp.float32), axis=-1
                             ).astype(compute_dtype)
        ctx = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T, E)
        h = h + linear(ctx, p["wo"], p["bo"], compute_dtype)

        x = layer_norm(h, p["ln2_w"], p["ln2_b"], eps)
        x = linear(x, p["fc1"], p["b1"], compute_dtype)
        if config.hidden_act == "quick_gelu":
            x = x * jax.nn.sigmoid(1.702 * x)
        else:
            x = jax.nn.gelu(x, approximate=False)
        x = linear(x, p["fc2"], p["b2"], compute_dtype)
        return h + x, None

    h, _ = jax.lax.scan(block, h, params["layers"])
    return layer_norm(h, params["lnf_w"], params["lnf_b"], eps)
