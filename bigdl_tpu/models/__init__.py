"""Model zoo registry.

The reference dispatches ~70 `model_type` branches in `_optimize_post`
(convert.py:1251-2027) to per-file patched forwards. Here a family
registry maps HF `model_type` to a (init, quantize, forward) triple; one
decoder-family implementation covers the llama-shaped architectures and
further families register alongside it.
"""

from __future__ import annotations

from bigdl_tpu.models.config import ModelConfig, PRESETS
from bigdl_tpu.models import llama

# model_type -> module implementing init_params / quantize_params / forward.
# One decoder-family implementation covers every llama-shaped architecture
# via ModelConfig flags (bigdl_tpu/models/llama.py docstring lists them).
_FAMILIES = {
    "llama": llama,
    "mistral": llama,
    "qwen2": llama,
    "gemma": llama,
    "gemma2": llama,
    "gemma3": llama,  # dual rope via rope_local_theta + layer_types
    "gemma3_text": llama,
    "phi3": llama,
    "baichuan": llama,
    "internlm2": llama,
    "internlm": llama,  # v1: biased qkv+o
    "aquila": llama,  # llama-shaped (BAAI Aquila/Aquila2)
    "starcoder2": llama,
    "stablelm": llama,
    "minicpm": llama,
    "glm": llama,
    # THUDM chatglm2/3 + glm-4 remote-code schema: interleaved half-dim
    # rope + fused checkpoints, translated in config._hf_chatglm and
    # convert/hf._chatglm_layer
    "chatglm": llama,
    "gpt2": llama,
    "mpt": llama,  # alibi + fused Wqkv, translated in config/_hf_mpt
    "bloom": llama,
    "gpt_neox": llama,
    "mixtral": llama,
    "qwen2_moe": llama,
    "qwen3": llama,  # per-head qk RMSNorm via qk_norm flag
    "qwen3_moe": llama,
    "phi": llama,  # parallel residual + shared norm, biased everything
    "cohere": llama,  # parallel residual, interleaved rope, logit scale
    "yi": llama,
    # parallel attn/mlp + grouped fused qkv, translated in
    # config._hf_falcon and convert/hf._falcon_layer
    "falcon": llama,
    "qwen": llama,  # v1: fused c_attn, halved-ff gate/up, logn scaling
    "deci": llama,  # variable GQA replicated to uniform kv heads at ingest
    "gpt_bigcode": llama,  # starcoder v1: MQA + learned positions
    "phixtral": llama,  # phi decoder + MoE over non-gated fc1/fc2 experts
    # phi-3-vision: optimized as phi3 on the text path (reference
    # convert.py:947,1829 treats phi3/phi3_v identically)
    "phi3_v": llama,
    # internlm-xcomposer2: internlm2 decoder; Plora image-row deltas are
    # a vision-path addition (reference convert.py:984,1523) — text path
    # is exactly internlm2
    "internlmxcomposer2": llama,
    # Megrez-3B-Omni: the llm half is llama (reference convert.py:1044
    # rewrites model.llm.config.model_type = "llama"); towers load
    # separately like minicpmv (same `llm.` checkpoint prefix)
    "megrezo": llama,
}

from bigdl_tpu.models import qwen2_vl  # noqa: E402  (delegates text to llama)

_FAMILIES["qwen2_vl"] = qwen2_vl

from bigdl_tpu.models import qwen_vl  # noqa: E402  (delegates text to llama)

# Qwen-VL checkpoints ship model_type "qwen" + a `visual` dict; the
# text side is the qwen v1 decoder, the tower/resampler live here
_FAMILIES["qwen_vl"] = qwen_vl

from bigdl_tpu.models import minicpmv  # noqa: E402  (delegates text to llama)

_FAMILIES["minicpmv"] = minicpmv

from bigdl_tpu.models import minicpmo  # noqa: E402  (adds whisper-apm audio)

# MiniCPM-o 2.6: minicpmv's vision path + a Whisper-encoder audio tower
# projected into the qwen2-shaped LLM (models/minicpmo.py)
_FAMILIES["minicpmo"] = minicpmo

from bigdl_tpu.models import qwen2_audio  # noqa: E402  (whisper-pool tower)

# Qwen2-Audio: whisper-style encoder with an in-encoder AvgPool1d(2) +
# single-linear projector over the qwen2 decoder (models/qwen2_audio.py)
_FAMILIES["qwen2_audio"] = qwen2_audio

from bigdl_tpu.models import mllama  # noqa: E402  (cross-attn decoder)

_FAMILIES["mllama"] = mllama
_FAMILIES["mllama_text_model"] = mllama  # nested text_config model_type

from bigdl_tpu.models import internvl  # noqa: E402  (delegates text to llama)

_FAMILIES["internvl"] = internvl
_FAMILIES["internvl_chat"] = internvl  # trust_remote_code model_type

from bigdl_tpu.models import janus  # noqa: E402  (delegates text to llama)

_FAMILIES["janus"] = janus
_FAMILIES["multi_modality"] = janus  # original janus checkpoints

from bigdl_tpu.models import chatglm4v  # noqa: E402  (delegates text to llama)

# THUDM glm-4v-9b: chatglm text schema + EVA2-CLIP tower/adapter
_FAMILIES["chatglm4v"] = chatglm4v

from bigdl_tpu.models import deepseek  # noqa: E402  (MLA latent-KV cache)

_FAMILIES["deepseek_v2"] = deepseek
_FAMILIES["deepseek_v3"] = deepseek
_FAMILIES["minicpm3"] = deepseek

from bigdl_tpu.models import yuan  # noqa: E402  (LFA conv-filtered attention)

# yuan's cache composes the KV cache with the conv-filter state, so it
# has its own module + init_cache hook (models/yuan.py)
_FAMILIES["yuan"] = yuan

from bigdl_tpu.models import baichuan_m1  # noqa: E402  (conv-enhanced KV)

# baichuan-m1 convolves K/V over time and carries the pre-conv tail in
# its cache (models/baichuan_m1.py), like yuan's filter state
_FAMILIES["baichuan_m1"] = baichuan_m1

from bigdl_tpu.models import rwkv  # noqa: E402  (attention-free recurrence)

# rwkv replaces the KV cache with a recurrent state: it exposes
# `init_cache` returning an RwkvState, which generate.generate_tokens
# consumes through the family cache_init hook
_FAMILIES["rwkv"] = rwkv
_FAMILIES["rwkv5"] = rwkv

# whisper (models/whisper.py) is an encoder-decoder family with its own
# WhisperConfig and (params, mel, prompt) call shape — deliberately NOT in
# _FAMILIES, whose consumers (optimize_model, TpuModel.generate) assume
# the decoder signature; it is served through the api_server's
# /v1/audio/transcriptions endpoint (whisper= kwarg) instead
#
# sd (models/sd.py) is likewise outside the registry: a diffusion UNet +
# DDIM sampler with (latents, t, context) call shape — pair it with the
# diffusers attention processor in integrations/diffusers.py or drive it
# directly (params_from_state_dict ingests a diffusers UNet checkpoint)


def get_family(model_type: str):
    if model_type not in _FAMILIES:
        raise NotImplementedError(
            f"model_type {model_type!r} not yet supported; have {sorted(_FAMILIES)}"
        )
    return _FAMILIES[model_type]


__all__ = ["ModelConfig", "PRESETS", "get_family", "llama"]
