"""Qwen2-Audio: a Whisper-style audio encoder with an in-encoder
AvgPool1d(2), a single-linear projector, and a qwen2 LLM.

Reference support: convert.py:969-971 (_optimize_pre merges the
language_model's qkv) and :1655-1656 (_optimize_post optimizes the
language_model as plain qwen2); the towers run through transformers'
Qwen2AudioEncoder. Architecture per transformers modeling_qwen2_audio:

    audio_tower (whisper encoder layers; AvgPool1d(2, stride=2) between
      the layer stack and the final layer_norm)
      -> multi_modal_projector (one biased linear, d_model -> hidden)
      -> scattered over the prompt's <|AUDIO|> placeholder tokens
         (config.audio_token_index)

The checkpoint stores the decoder under `language_model.` (qwen2
layout), the encoder under `audio_tower.` (whisper encoder names — the
shared translator whisper.encoder_params_from_state_dict reads it
directly), and the projector under `multi_modal_projector.linear.`.
Only the LLM quantizes; the tower stays dense, as the reference does
for multimodal families.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama, whisper
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.models.whisper import WhisperConfig

# the text side delegates wholesale to the llama family (qwen2-shaped)
init_params = llama.init_params
quantize_params = llama.quantize_params
forward = llama.forward
merge_fused_params = llama.merge_fused_params
unmerge_fused_params = llama.unmerge_fused_params

POOL_STEP = 2  # fixed in transformers Qwen2AudioEncoder (avg_pooler)


def tower_params_from_state_dict(
    wcfg: WhisperConfig, get, prefix: str = "audio_tower.",
) -> dict:
    """Qwen2AudioEncoder uses whisper's encoder key names verbatim."""
    return whisper.encoder_params_from_state_dict(wcfg, get, prefix)


def proj_params_from_state_dict(
    get, prefix: str = "multi_modal_projector.",
) -> dict:
    def g(name):
        return jnp.asarray(np.asarray(get(prefix + name), np.float32))

    return {"w": g("linear.weight"), "b": g("linear.bias")}


def audio_embed(
    wcfg: WhisperConfig,
    aparams: dict,
    pparams: dict,
    mel: jax.Array,  # [B, n_mels, 2 * max_source_positions]
    out_dtype=jnp.float32,
) -> jax.Array:
    """mel -> [B, max_source_positions // 2, E_llm]: encoder (with its
    internal pool-2) then the single-linear projector."""
    enc = whisper.encode(wcfg, aparams, mel, pool_before_ln=POOL_STEP)
    x = jnp.einsum("bsh,eh->bse", enc.astype(jnp.float32), pparams["w"])
    return (x + pparams["b"]).astype(out_dtype)


def multimodal_prefill(
    config: ModelConfig,
    params: dict,
    input_ids: np.ndarray,  # [B, T] with audio_token_id placeholders
    cache,
    wcfg: Optional[WhisperConfig] = None,
    aparams: Optional[dict] = None,
    pparams: Optional[dict] = None,
    mel: Optional[jax.Array] = None,
    audio: Optional[jax.Array] = None,  # precomputed audio_embed output
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = True,
):
    """Audio tower -> projector -> scatter over placeholders -> standard
    qwen2 prefill. Pass either `mel` (tower runs here) or precomputed
    `audio` features (callers that already ran audio_embed — e.g. to
    size the placeholder run — skip a second tower pass)."""
    from bigdl_tpu.models._multimodal import scatter_image_features

    if audio is None and mel is not None:
        audio = audio_embed(wcfg, aparams, pparams, mel)
    h = scatter_image_features(
        config, params, input_ids, None, compute_dtype, audio=audio,
    )
    return llama.forward(
        config, params, h, cache, mode="prefill", input_is_hidden=True,
        compute_dtype=compute_dtype, last_logits_only=last_logits_only,
    )
