"""LLaMA-family decoder (covers llama/llama2/llama3, mistral, qwen2, ...).

TPU-native re-design of the reference's patched forwards
(`models/llama.py:56-200`, `models/mistral.py`, `models/qwen2.py` in
/root/reference): instead of monkey-patching HF modules, the model is a
pure function over a parameter pytree whose linear-layer leaves may be
`QTensor` (packed low-bit). Layers are **stacked along a leading axis and
iterated with `lax.scan`**, which keeps compile time O(1) in depth and
gives the pipeline axis a natural sharding target.

With a cache, attention always runs over the full cache [0, max_len)
under a validity mask derived from (start, pos) — so multi-chunk prefill
and decode share one code path and chunked prefill sees earlier chunks.
The `mode` argument only labels the jit specialization (prefill T>1 vs
decode T=1), mirroring the reference's prefill/decode kernel split
(low_bit_linear.py:606-716); a Pallas flash-attention prefill fast path
will key off it.

Batch rows are left-padded (see bigdl_tpu/kvcache.py).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import kvcache
from bigdl_tpu.kvcache import KVCache
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import apply_rotary_emb, attention, linear, rms_norm, rope_cos_sin
from bigdl_tpu.ops.rope import make_inv_freq
from bigdl_tpu.quant import QTensor, quantize
from bigdl_tpu.quant.qtypes import resolve_qtype

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# init / quantize
# ---------------------------------------------------------------------------

def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> Params:
    """Random dense init (tests/benchmarks run without checkpoints)."""
    L, H, I = config.num_hidden_layers, config.hidden_size, config.intermediate_size
    V, QD, KD = config.vocab_size, config.q_dim, config.kv_dim
    keys = iter(jax.random.split(key, 16))

    def w(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "attn_norm": jnp.ones((L, H), dtype),
        "mlp_norm": jnp.ones((L, H), dtype),
        "wq": w(next(keys), (L, QD, H)),
        "wk": w(next(keys), (L, KD, H)),
        "wv": w(next(keys), (L, KD, H)),
        "wo": w(next(keys), (L, H, QD)),
        "w_gate": w(next(keys), (L, I, H)),
        "w_up": w(next(keys), (L, I, H)),
        "w_down": w(next(keys), (L, H, I)),
    }
    if config.attention_bias:
        layers["bq"] = jnp.zeros((L, QD), dtype)
        layers["bk"] = jnp.zeros((L, KD), dtype)
        layers["bv"] = jnp.zeros((L, KD), dtype)
    params: Params = {
        "embed": w(next(keys), (V, H)),
        "layers": layers,
        "final_norm": jnp.ones((H,), dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = w(next(keys), (V, H))
    return params


_QUANT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params: Params, qtype: str, lm_head_qtype: Optional[str] = None) -> Params:
    """Quantize the linear weights of a dense param tree.

    Equivalent of `ggml_convert_low_bit` walking modules (convert.py:1077):
    norms/biases stay dense; the lm head may use a different (higher) qtype,
    mirroring the reference's mixed-precision lm-head handling
    (convert.py:469-750, IPEX_LLM_LAST_LM_HEAD).
    """
    spec = resolve_qtype(qtype)
    if spec.is_dense:
        return params
    out = dict(params)
    out["layers"] = dict(params["layers"])
    for name in _QUANT_TARGETS:
        w = params["layers"][name]
        if isinstance(w, QTensor):  # idempotent: already low-bit
            continue
        out["layers"][name] = quantize(w, spec.name)
    if "lm_head" in params and not isinstance(params["lm_head"], QTensor):
        lm_spec = resolve_qtype(lm_head_qtype) if lm_head_qtype else spec
        if not lm_spec.is_dense:
            out["lm_head"] = quantize(params["lm_head"], lm_spec.name)
    return out


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _act(name: str, x: jax.Array) -> jax.Array:
    if name == "silu":
        return jax.nn.silu(x)
    if name in ("gelu", "gelu_pytorch_tanh"):
        return jax.nn.gelu(x, approximate=True)
    raise NotImplementedError(f"hidden_act {name}")


def _softcap(x: jax.Array, cap: Optional[float]) -> jax.Array:
    if cap is None:
        return x
    return jnp.tanh(x / cap) * cap


def _lora_delta(x, pair, scale, compute_dtype):
    """x [.., in] through a LoRA pair {'a': [r, in], 'b': [out, r]}."""
    a, b = pair["a"], pair["b"]
    xa = jnp.einsum("...k,rk->...r", x.astype(compute_dtype), a.astype(compute_dtype))
    return jnp.einsum("...r,or->...o", xa, b.astype(compute_dtype)) * scale


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cache: Optional[KVCache],
    mode: str = "prefill",  # static: "prefill" | "decode"
    compute_dtype=jnp.bfloat16,
    lora: Optional[Params] = None,  # LoRA adapter tree (see bigdl_tpu.train)
    start: Optional[jax.Array] = None,  # [B] pad offsets when cache is None
) -> tuple[jax.Array, Optional[KVCache]]:
    """Returns (logits [B, T, V] float32, updated cache with pos advanced).

    cache=None runs the cache-free training/scoring path (full block-causal
    attention, no KV writes) — the path QLoRA finetuning differentiates
    through.
    """
    assert mode in ("prefill", "decode")
    B, T = tokens.shape
    Hq, Hkv, D = config.num_attention_heads, config.num_key_value_heads, config.head_dim_

    if cache is None:
        pos0 = jnp.zeros((), jnp.int32)
        row_start = jnp.zeros((B,), jnp.int32) if start is None else start
    else:
        pos0 = cache.pos
        row_start = cache.start

    h = params["embed"].astype(compute_dtype)[tokens]
    if config.scale_embeddings:
        h = h * jnp.asarray(config.hidden_size**0.5, compute_dtype)

    # Rotary tables: positions are relative to each row's start (left pad).
    slots = pos0 + jnp.arange(T)[None, :]  # [1, T] global cache slots
    positions = jnp.maximum(slots - row_start[:, None], 0)  # [B, T]
    inv_freq = make_inv_freq(D, config.rope_theta, config.rope_scaling_dict)
    cos, sin = rope_cos_sin(positions, inv_freq)

    # Prefill goes through the Pallas flash-attention kernel (no [T,S]
    # score matrix in HBM); decode and the differentiable cache-free
    # training path use the fused XLA attention. Mirrors the reference's
    # sdp_causal vs sdp dispatch (models/common.py:222-258).
    from bigdl_tpu.ops.pallas import use_pallas

    use_flash = cache is not None and mode == "prefill" and T > 1 and use_pallas()

    # Attention masks (shared by all layers, computed once outside the scan).
    if use_flash:
        mask = None
    elif cache is None:
        # cache-free training path: block-local causal
        tj = jnp.arange(T)
        mask = (tj[None, :] <= tj[:, None])[None] & (
            tj[None, None, :] >= row_start[:, None, None]
        )  # [B, T, T]
        if config.sliding_window:
            mask = mask & (tj[None, None, :] > tj[None, :, None] - config.sliding_window)
    else:
        # Both prefill and decode attend over the full cache with a validity
        # mask — chunked prefill (pos > 0) therefore sees earlier chunks.
        S = cache.max_len
        sj = jnp.arange(S)
        q_slot = slots  # [B (broadcast), T]
        mask = (sj[None, None, :] <= q_slot[..., None]) & (
            sj[None, None, :] >= row_start[:, None, None]
        )  # [B, T, S]
        if config.sliding_window:
            mask = mask & (sj[None, None, :] > q_slot[..., None] - config.sliding_window)
    if mask is not None:
        mask = mask[:, None, None]  # [B, 1, 1, T, S'] broadcasts over (Hkv, G)

    lora_scale = lora["scale"] if lora is not None else None

    def proj(x, p, lp, wname, bname=None):
        y = linear(x, p[wname], p.get(bname) if bname else None, compute_dtype)
        if lp is not None and wname in lp:
            y = y + _lora_delta(x, lp[wname], lora_scale, compute_dtype)
        return y

    def body(carry, xs):
        hidden, c, idx = carry
        p, lp = xs if lora is not None else (xs, None)

        x = rms_norm(hidden, p["attn_norm"], config.rms_norm_eps)
        q = proj(x, p, lp, "wq", "bq").reshape(B, T, Hq, D)
        k = proj(x, p, lp, "wk", "bk").reshape(B, T, Hkv, D)
        v = proj(x, p, lp, "wv", "bv").reshape(B, T, Hkv, D)
        q, k = apply_rotary_emb(q, k, cos, sin)

        if c is not None:
            c = kvcache.update_layer(c, idx, k, v)
            k_att, v_att = kvcache.read_layer(c, idx, compute_dtype)
        else:
            k_att = k.astype(compute_dtype)
            v_att = v.astype(compute_dtype)

        if use_flash:
            from bigdl_tpu.ops.pallas import flash_attention

            attn = flash_attention(
                q, k_att, v_att, start=row_start, q_offset=pos0,
                window=config.sliding_window, softcap=config.attn_logit_softcap,
            )
        else:
            attn = attention(q, k_att, v_att, mask, softcap=config.attn_logit_softcap)
        out = proj(attn.reshape(B, T, Hq * D), p, lp, "wo")
        hidden = hidden + out

        x = rms_norm(hidden, p["mlp_norm"], config.rms_norm_eps)
        gate = proj(x, p, lp, "w_gate")
        up = proj(x, p, lp, "w_up")
        down = proj(_act(config.hidden_act, gate) * up, p, lp, "w_down")
        hidden = hidden + down

        return (hidden, c, idx + 1), None

    xs = (params["layers"], lora["layers"]) if lora is not None else params["layers"]
    (h, cache, _), _ = jax.lax.scan(
        body, (h, cache, jnp.zeros((), jnp.int32)), xs
    )

    h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
    lm_head = params.get("lm_head", params["embed"])
    logits = linear(h, lm_head, None, compute_dtype).astype(jnp.float32)
    logits = _softcap(logits, config.final_logit_softcap)
    if cache is not None:
        cache = kvcache.advance(cache, T)
    return logits, cache
