"""Decoder-family model (llama/llama2/llama3, mistral, qwen2, gemma/gemma2,
phi3, baichuan2, starcoder2, stablelm, internlm2, minicpm, glm, and the MoE
variants mixtral/qwen2-moe).

TPU-native re-design of the reference's patched forwards
(`models/llama.py:56-200`, `models/mistral.py`, `models/qwen2.py`,
`models/gemma2.py`, `models/phi3.py`, `models/baichuan.py`,
`models/starcoder2.py`, `models/stablelm.py`, `models/mixtral.py`,
`models/qwen2_moe.py` in /root/reference): instead of monkey-patching HF
modules per architecture, one pure function over a parameter pytree reads
architecture differences from `ModelConfig` flags; dead branches compile
away under jit. Linear-layer leaves may be `QTensor` (packed low-bit).
Layers are **stacked along a leading axis and iterated with `lax.scan`**,
which keeps compile time O(1) in depth and gives the pipeline axis a
natural sharding target.

With a cache, attention always runs over the full cache [0, max_len)
under a validity mask derived from (start, pos) — so multi-chunk prefill
and decode share one code path and chunked prefill sees earlier chunks.
The `mode` argument only labels the jit specialization (prefill T>1 vs
decode T=1), mirroring the reference's prefill/decode kernel split
(low_bit_linear.py:606-716); the Pallas flash-attention prefill fast path
keys off it.

Batch rows are left-padded (see bigdl_tpu/kvcache.py).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import kvcache
from bigdl_tpu.kvcache import KVCache
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import apply_rotary_emb, attention, linear, rms_norm, rope_cos_sin
from bigdl_tpu.ops.norms import layer_norm
from bigdl_tpu.ops.rope import alibi_slopes, make_inv_freq_scaled
from bigdl_tpu.quant import QTensor, quantize
from bigdl_tpu.quant.qtypes import resolve_qtype

Params = dict[str, Any]

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# init / quantize
# ---------------------------------------------------------------------------

def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> Params:
    """Random dense init (tests/benchmarks run without checkpoints)."""
    L, H, I = config.num_hidden_layers, config.hidden_size, config.intermediate_size
    V, QD, KD = config.vocab_size, config.q_dim, config.kv_dim
    keys = iter(jax.random.split(key, 32))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "attn_norm": jnp.ones((L, H), dtype),
        "mlp_norm": jnp.ones((L, H), dtype),
        "wq": w((L, QD, H)),
        "wk": w((L, KD, H)),
        "wv": w((L, KD, H)),
        "wo": w((L, H, QD)),
    }
    if config.is_moe:
        E = config.num_experts
        EI = config.moe_intermediate_size or I
        layers["router"] = w((L, E, H))
        if config.gated_mlp:
            layers["w_gate_e"] = w((L, E, EI, H))
        layers["w_up_e"] = w((L, E, EI, H))
        layers["w_down_e"] = w((L, E, H, EI))
        if not config.gated_mlp and config.mlp_bias:
            layers["b_up_e"] = jnp.zeros((L, E, EI), dtype)
            layers["b_down_e"] = jnp.zeros((L, E, H), dtype)
        if config.shared_expert_intermediate_size:
            S = config.shared_expert_intermediate_size
            layers["w_gate_s"] = w((L, S, H))
            layers["w_up_s"] = w((L, S, H))
            layers["w_down_s"] = w((L, H, S))
            layers["shared_gate"] = w((L, 1, H))
    elif config.gated_mlp:
        layers["w_gate"] = w((L, I, H))
        layers["w_up"] = w((L, I, H))
        layers["w_down"] = w((L, H, I))
    else:
        layers["w_up"] = w((L, I, H))
        layers["w_down"] = w((L, H, I))
    if config.attention_bias:
        layers["bq"] = jnp.zeros((L, QD), dtype)
        layers["bk"] = jnp.zeros((L, KD), dtype)
        layers["bv"] = jnp.zeros((L, KD), dtype)
    if config.attention_out_bias:
        layers["bo"] = jnp.zeros((L, H), dtype)
    if config.mlp_bias:
        if config.gated_mlp:
            layers["b_gate"] = jnp.zeros((L, I), dtype)
        layers["b_up"] = jnp.zeros((L, I), dtype)
        layers["b_down"] = jnp.zeros((L, H), dtype)
    if config.norm_bias:
        layers["attn_norm_b"] = jnp.zeros((L, H), dtype)
        layers["mlp_norm_b"] = jnp.zeros((L, H), dtype)
    if config.post_attn_norm:
        layers["post_attn_norm"] = jnp.ones((L, H), dtype)
        layers["post_mlp_norm"] = jnp.ones((L, H), dtype)
    if config.qk_norm:
        D = config.head_dim_
        layers["q_norm"] = jnp.ones((L, D), dtype)
        layers["k_norm"] = jnp.ones((L, D), dtype)
    params: Params = {
        "embed": w((V, H)),
        "layers": layers,
        "final_norm": jnp.ones((H,), dtype),
    }
    if config.norm_bias:
        params["final_norm_b"] = jnp.zeros((H,), dtype)
    if config.learned_positions:
        params["wpe"] = w((config.max_position_embeddings, H))
    if config.embed_layernorm:
        params["embed_norm"] = jnp.ones((H,), dtype)
        params["embed_norm_b"] = jnp.zeros((H,), dtype)
    if not config.tie_word_embeddings:
        params["lm_head"] = w((V, H))
        if config.lm_head_bias:
            params["lm_head_b"] = jnp.zeros((V,), dtype)
    return params


_QUANT_TARGETS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    "wqkv", "w_gateup",  # fused layout (merge_fused_params)
    "w_gate_e", "w_up_e", "w_down_e", "w_gate_s", "w_up_s", "w_down_s",
)


def quantize_params(params: Params, qtype: str, lm_head_qtype: Optional[str] = None) -> Params:
    """Quantize the linear weights of a dense param tree.

    Equivalent of `ggml_convert_low_bit` walking modules (convert.py:1077):
    norms/biases/router stay dense; the lm head may use a different (higher)
    qtype, mirroring the reference's mixed-precision lm-head handling
    (convert.py:469-750, IPEX_LLM_LAST_LM_HEAD). Mixed aliases (q4_k_m)
    resolve to (body, head) formats here.
    """
    from bigdl_tpu.quant.qtypes import split_mixed_qtype

    qtype, head_default = split_mixed_qtype(qtype)
    lm_head_qtype = lm_head_qtype or head_default
    spec = resolve_qtype(qtype)
    if spec.is_dense:
        return params
    from bigdl_tpu.quant import quantize_or_dense

    out = dict(params)
    out["layers"] = dict(params["layers"])
    for name in _QUANT_TARGETS:
        w = params["layers"].get(name)
        if w is None or isinstance(w, QTensor):  # absent or already low-bit
            continue
        out["layers"][name] = quantize_or_dense(w, spec.name, name)
    if "lm_head" in params and not isinstance(params["lm_head"], QTensor):
        lm_spec = resolve_qtype(lm_head_qtype) if lm_head_qtype else spec
        if not lm_spec.is_dense:
            out["lm_head"] = quantize_or_dense(
                params["lm_head"], lm_spec.name, "lm_head")
    return out


def _concat_weights(ws, axis=-2):
    """Concatenate dense arrays or QTensors along the output axis.
    Returns None when the formats can't merge losslessly (mixed qtypes
    or dense leaves mixed with QTensors)."""
    if all(isinstance(w, jax.Array) for w in ws):
        return jnp.concatenate(ws, axis=axis)
    if not all(isinstance(w, QTensor) for w in ws):
        return None
    q0 = ws[0]
    if any(w.qtype != q0.qtype for w in ws):
        return None
    spec = q0.spec
    if spec.storage not in ("packed_u8", "packed_planes", "int8",
                            "fp8_e4m3", "fp8_e5m2"):
        return None  # every field must be row-leading [O, *]
    from bigdl_tpu.quant.qtensor import map_arrays_multi

    return map_arrays_multi(
        list(ws), lambda arrs: jnp.concatenate(arrs, axis=axis)
    )


def unmerge_fused_params(params: Params, config: ModelConfig) -> Params:
    """Inverse of merge_fused_params: split fused weights back into their
    parts (row slices — lossless). Used before tensor-parallel sharding:
    a column-parallel fused weight would put the q/k/v split boundaries
    off shard boundaries for GQA models, forcing GSPMD resharding
    collectives on every layer."""
    layers = params.get("layers", {})
    if "wqkv" not in layers and "w_gateup" not in layers:
        return params
    out = dict(params)
    lay = dict(layers)

    def rows(w, a, b):
        if isinstance(w, QTensor):
            return w.map_arrays(lambda arr: arr[..., a:b, :])
        return w[..., a:b, :]

    if "wqkv" in lay:
        QD, KD = config.q_dim, config.kv_dim
        w = lay.pop("wqkv")
        lay["wq"] = rows(w, 0, QD)
        lay["wk"] = rows(w, QD, QD + KD)
        lay["wv"] = rows(w, QD + KD, QD + 2 * KD)
        if "bqkv" in lay:
            b = lay.pop("bqkv")
            lay["bq"], lay["bk"], lay["bv"] = (
                b[..., :QD], b[..., QD:QD + KD], b[..., QD + KD:]
            )
    if "w_gateup" in lay:
        w = lay.pop("w_gateup")
        I = (w.shape[-2] if not isinstance(w, QTensor)
             else w.data.shape[-2]) // 2
        lay["w_gate"] = rows(w, 0, I)
        lay["w_up"] = rows(w, I, 2 * I)
        if "b_gateup" in lay:
            b = lay.pop("b_gateup")
            lay["b_gate"], lay["b_up"] = b[..., :I], b[..., I:]
    out["layers"] = lay
    return out


def merge_fused_params(params: Params, config: ModelConfig) -> Params:
    """Fuse qkv and gate/up into single linears (the reference's
    merge_qkv / mlp fusion, models/common.py:22-53 + _optimize_pre
    convert.py:886): one kernel call streams one larger weight — fewer
    per-call fixed costs on the decode hot path. The forward splits the
    fused output, so results are bit-identical to the unmerged layout.
    Falls back silently (returns the tree unchanged) for formats that
    can't concatenate losslessly."""
    layers = params.get("layers", {})
    if "wqkv" in layers or "wq" not in layers:
        return params
    out = dict(params)
    lay = dict(layers)

    wqkv = _concat_weights([lay["wq"], lay["wk"], lay["wv"]])
    if wqkv is not None:
        lay["wqkv"] = wqkv
        for k in ("wq", "wk", "wv"):
            del lay[k]
        if "bq" in lay:
            lay["bqkv"] = jnp.concatenate(
                [lay.pop("bq"), lay.pop("bk"), lay.pop("bv")], axis=-1
            )
    if config.gated_mlp and not config.is_moe and "w_gate" in lay:
        gu = _concat_weights([lay["w_gate"], lay["w_up"]])
        if gu is not None:
            lay["w_gateup"] = gu
            del lay["w_gate"], lay["w_up"]
            if "b_gate" in lay:
                lay["b_gateup"] = jnp.concatenate(
                    [lay.pop("b_gate"), lay.pop("b_up")], axis=-1
                )
    out["layers"] = lay
    return out


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _act(name: str, x: jax.Array) -> jax.Array:
    if name == "silu":
        return jax.nn.silu(x)
    if name == "gelu":  # HF get_activation("gelu") = exact erf gelu
        return jax.nn.gelu(x, approximate=False)
    if name in ("gelu_new", "gelu_pytorch_tanh", "gelu_tanh"):
        return jax.nn.gelu(x, approximate=True)
    if name == "relu":
        return jax.nn.relu(x)
    raise NotImplementedError(f"hidden_act {name}")


def _softcap(x: jax.Array, cap: Optional[float]) -> jax.Array:
    if cap is None:
        return x
    return jnp.tanh(x / cap) * cap


def embed_tokens(config: ModelConfig, params: Params, tokens: jax.Array,
                 compute_dtype=jnp.bfloat16) -> jax.Array:
    """Token embedding incl. the gemma/minicpm scaling knobs — shared by
    forward() and the pipeline stage program (parallel/pipeline.py).
    The table may be dense, a QTensor (LowBitEmbedding), or a
    HostEmbedding (CPU/disk offload) — see bigdl_tpu/embedding.py."""
    from bigdl_tpu.embedding import embed_lookup

    h = embed_lookup(params["embed"], tokens, compute_dtype)
    if config.scale_embeddings:
        h = h * jnp.asarray(config.hidden_size**0.5, compute_dtype)
    if config.embedding_scale:
        h = h * jnp.asarray(config.embedding_scale, compute_dtype)
    return h


def lm_head_logits(config: ModelConfig, params: Params, h: jax.Array,
                   compute_dtype=jnp.bfloat16) -> jax.Array:
    """Final norm + lm head + logit scaling/softcap — shared by forward()
    and the pipeline stage program."""
    if config.norm_type == "layernorm":
        h = layer_norm(h, params["final_norm"], params.get("final_norm_b"),
                       config.rms_norm_eps)
    else:
        h = rms_norm(h, params["final_norm"], config.rms_norm_eps,
                     offset=config.rms_norm_offset)
    lm_head = params.get("lm_head", params["embed"])
    logits = linear(
        h, lm_head, params.get("lm_head_b"), compute_dtype
    ).astype(jnp.float32)
    if config.logit_scale:
        logits = logits * config.logit_scale
    return _softcap(logits, config.final_logit_softcap)


def _lora_delta(x, pair, scale, compute_dtype):
    """x [.., in] through a LoRA pair {'a': [r, in], 'b': [out, r]}.
    Batched per-row pairs ({'a': [B, r, in], 'b': [B, out, r]}, scale
    [B]) apply slot i's adapter to row i — the serving engine's
    heterogeneous multi-tenant decode batch (ops/linear.lora_epilogue;
    docs/serving.md §7)."""
    from bigdl_tpu.ops.linear import lora_epilogue

    return lora_epilogue(x, pair["a"], pair["b"], scale, compute_dtype)


def _deq(w, compute_dtype):
    return w.dequantize(compute_dtype) if isinstance(w, QTensor) else w.astype(compute_dtype)


def resolve_moe_dispatch(config: ModelConfig) -> str:
    """Auto policy: dense combine is cheaper below ~8 experts (all-matmul,
    no gather/scatter); capacity dispatch above (FLOPs ∝ k/E)."""
    if config.moe_dispatch is not None:
        return config.moe_dispatch
    return "ragged" if config.num_experts > 8 else "dense"


def _moe_router(config: ModelConfig, xc: jax.Array, p: Params):
    """Top-k routing with softmax weights. Returns (topv [B,T,k] f32,
    topi [B,T,k] i32). Mixtral renormalizes the top-k weights
    (norm_topk_prob=True via config), qwen2_moe per its flag."""
    router_logits = jnp.einsum(
        "bth,eh->bte", xc, p["router"].astype(xc.dtype),
        preferred_element_type=jnp.float32,
    ).astype(jnp.float32)
    probs_all = jax.nn.softmax(router_logits, axis=-1)
    topv, topi = jax.lax.top_k(probs_all, config.num_experts_per_tok)
    if config.norm_topk_prob:
        topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-20)
    return topv, topi


def _expert_ffn(config: ModelConfig, xe: jax.Array, p: Params, compute_dtype):
    """Per-expert FFN on already-grouped tokens: [E, C, H] -> [E, C, H].
    Gated (mixtral/qwen2-moe) or plain fc->act->proj with biases
    (phixtral's phi-2 experts, gated_mlp=False)."""
    wu = _deq(p["w_up_e"], compute_dtype)  # [E, I, H]
    wd = _deq(p["w_down_e"], compute_dtype)  # [E, H, I]
    u = jnp.einsum("ech,eih->eci", xe, wu, preferred_element_type=compute_dtype)
    if config.gated_mlp:
        wg = _deq(p["w_gate_e"], compute_dtype)  # [E, I, H]
        g = jnp.einsum("ech,eih->eci", xe, wg,
                       preferred_element_type=compute_dtype)
        z = _act(config.hidden_act, g) * u
    else:
        if "b_up_e" in p:
            u = u + p["b_up_e"].astype(compute_dtype)[:, None, :]
        z = _act(config.hidden_act, u)
    out = jnp.einsum("eci,ehi->ech", z, wd, preferred_element_type=compute_dtype)
    if not config.gated_mlp and "b_down_e" in p:
        out = out + p["b_down_e"].astype(compute_dtype)[:, None, :]
    return out


def _moe_dispatch_ragged(
    config: ModelConfig, xc: jax.Array, p: Params, compute_dtype,
    topv: jax.Array, topi: jax.Array,
) -> jax.Array:
    """Capacity-based ragged dispatch (GShard/Switch style): each expert
    computes only its routed tokens, so FLOPs scale with k/E instead of
    1 — the difference between mixtral (E=8, k=2: dense costs 4x) and
    qwen2-moe (E=60, k=4: dense would cost 15x).

    Static-shape formulation for XLA: per-expert slot positions come from
    a cumulative sum over the one-hot assignment matrix; tokens beyond
    expert capacity C = ceil(N*k/E * capacity_factor) are dropped (their
    combine weight is zeroed — router softmax mass simply doesn't arrive,
    matching GShard overflow semantics). Gather/scatter both
    differentiate cleanly for MoE training.
    """
    B, T, H = xc.shape
    E, k = config.num_experts, config.num_experts_per_tok
    N = B * T
    cf = config.moe_capacity_factor
    C = max(1, min(N, int(-(-N * k * cf // E))))

    x_flat = xc.reshape(N, H)
    e_flat = topi.reshape(N * k)  # assignment order: token-major
    w_flat = topv.reshape(N * k).astype(compute_dtype)

    onehot = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)  # [N*k, E]
    pos = jnp.cumsum(onehot, axis=0) - onehot  # prior same-expert count
    pos = jnp.sum(pos * onehot, axis=-1)  # [N*k] slot within expert
    keep = pos < C
    slot = jnp.where(keep, e_flat * C + pos, E * C)  # E*C = overflow bin

    tok = jnp.repeat(jnp.arange(N), k)  # token of each assignment
    x_disp = jnp.zeros((E * C + 1, H), compute_dtype).at[slot].add(
        x_flat[tok], mode="drop"
    )
    y = _expert_ffn(
        config, x_disp[:-1].reshape(E, C, H), p, compute_dtype
    ).reshape(E * C, H)
    y = jnp.concatenate([y, jnp.zeros((1, H), compute_dtype)], axis=0)

    contrib = y[slot] * w_flat[:, None]  # overflow slots read zeros
    out = jnp.zeros((N, H), compute_dtype).at[tok].add(contrib)
    return out.reshape(B, T, H)


def _moe_dispatch_dense(
    config: ModelConfig, xc: jax.Array, p: Params, compute_dtype,
    topv: jax.Array, topi: jax.Array,
) -> jax.Array:
    """Dense combine: every expert computes every token, top-k weights
    (zero for unrouted) scatter into a [B,T,E] combine matrix —
    all-matmul, MXU-friendly, exactly differentiable. Best at small E.
    Shared by the llama-family router and the DeepSeek router
    (models/deepseek.py)."""
    onehot = jax.nn.one_hot(topi, config.num_experts, dtype=jnp.float32)
    combine = jnp.einsum("btk,btke->bte", topv, onehot)
    wu = _deq(p["w_up_e"], compute_dtype)  # [E, I, H]
    wd = _deq(p["w_down_e"], compute_dtype)  # [E, H, I]
    u = jnp.einsum("bth,eih->btei", xc, wu, preferred_element_type=compute_dtype)
    if config.gated_mlp:
        wg = _deq(p["w_gate_e"], compute_dtype)  # [E, I, H]
        g = jnp.einsum("bth,eih->btei", xc, wg,
                       preferred_element_type=compute_dtype)
        z = _act(config.hidden_act, g) * u
    else:  # phixtral: plain biased fc1 -> act; biases ride inside each
        # expert's weighted term, exactly like HF's per-expert MLP call
        if "b_up_e" in p:
            u = u + p["b_up_e"].astype(compute_dtype)[None, None]
        z = _act(config.hidden_act, u)
    d = jnp.einsum("btei,ehi->bteh", z, wd, preferred_element_type=compute_dtype)
    if not config.gated_mlp and "b_down_e" in p:
        d = d + p["b_down_e"].astype(compute_dtype)[None, None]
    return jnp.einsum("bteh,bte->bth", d, combine.astype(compute_dtype))


def _moe_mlp(config: ModelConfig, x: jax.Array, p: Params, compute_dtype) -> jax.Array:
    """Mixture-of-experts MLP (reference models/mixtral.py, qwen2_moe.py +
    `xe_linear.get_moe_indexes`): top-k routing with softmax weights.

    Two formulations, chosen by `config.moe_dispatch` (auto = by expert
    count):
    - "dense": every expert computes every token, router weights (zero
      for unrouted) combine them — all-matmul, no gather/scatter,
      MXU-friendly, exactly differentiable. Best at mixtral scale (E=8).
    - "ragged": capacity-based dispatch, FLOPs ∝ k/E — required for
      qwen2-moe scale (E=60, k=4). See _moe_dispatch_ragged.
    """
    B, T, H = x.shape
    xc = x.astype(compute_dtype)
    topv, topi = _moe_router(config, xc, p)

    if resolve_moe_dispatch(config) == "ragged":
        out = _moe_dispatch_ragged(config, xc, p, compute_dtype, topv, topi)
    else:
        out = _moe_dispatch_dense(config, xc, p, compute_dtype, topv, topi)

    if config.shared_expert_intermediate_size:
        # qwen2_moe shared expert, sigmoid-gated (models/qwen2_moe.py)
        sg = jnp.einsum("bth,ih->bti", xc, _deq(p["w_gate_s"], compute_dtype))
        su = jnp.einsum("bth,ih->bti", xc, _deq(p["w_up_s"], compute_dtype))
        sd = jnp.einsum(
            "bti,hi->bth", _act(config.hidden_act, sg) * su,
            _deq(p["w_down_s"], compute_dtype),
        )
        gate = jax.nn.sigmoid(
            jnp.einsum("bth,oh->bto", xc, p["shared_gate"].astype(compute_dtype))
        )
        out = out + sd * gate
    return out


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cache: Optional[KVCache],
    mode: str = "prefill",  # static: "prefill" | "decode"
    compute_dtype=jnp.bfloat16,
    lora: Optional[Params] = None,  # LoRA adapter tree (see bigdl_tpu.train)
    start: Optional[jax.Array] = None,  # [B] pad offsets when cache is None
    collect_obs: int = 0,  # static: stash the last-N rotated queries per layer
    attention_override=None,  # static: fn(q, k, v, start) for the cache-free
    # path — e.g. sequence-parallel ring attention (parallel/ring.py)
    input_is_hidden: bool = False,  # static: tokens is [B,T,H] hidden states
    return_hidden: bool = False,  # static: skip final norm/head, return h
    layer_offset=0,  # global index of params['layers'][0] (pipeline stages)
    position_grid=None,  # [3, B, T] M-RoPE (t, h, w) positions — multimodal
    # prefill only (models/qwen2_vl.py); None = standard 1-D positions
    positions=None,  # [B, T] explicit 1-D rope/learned positions — remote-
    # code schemes where slot != position (chatglm4v repeats the image
    # span's position across all patches); pair with cache.rope_base so
    # decode continues from the true last position
    last_logits_only: bool = False,  # static: lm head on the last position
    # only — prefill skips the [B,T,V] logits (reference
    # reshape_lm_head_input / IPEX_LLM_LAST_LM_HEAD,
    # low_bit_linear.py:262-270)
    remat: bool = False,  # static: jax.checkpoint each scan layer —
    # backward recomputes the layer instead of saving its activations
    # (long-context training memory lever; make_train_step(remat=True))
    comm=None,  # static: parallel/qcollectives.CommConfig — routes the
    # row-parallel epilogues (wo, w_down) through the explicit
    # block-quantized ring all-reduce instead of GSPMD's implicit fp32
    # psum. None or comm_qtype="none" keeps today's path bit-identical.
) -> tuple[jax.Array, Optional[KVCache]]:
    """Returns (logits [B, T, V] float32, updated cache with pos advanced).

    cache=None runs the cache-free training/scoring path (full block-causal
    attention, no KV writes) — the path QLoRA finetuning differentiates
    through.

    collect_obs=W > 0 (prefill only) additionally returns the observation
    window queries [L, B, W, Hq, D] for SnapKV compression
    (kvcache.compress) as a third element.

    input_is_hidden/return_hidden let a pipeline stage run only its slice
    of the layer stack (parallel/pipeline.py): embedding happens before
    the first stage, final norm + lm head after the last.
    """
    assert mode in ("prefill", "decode")
    B, T = tokens.shape[:2]
    Hq, Hkv, D = config.num_attention_heads, config.num_key_value_heads, config.head_dim_
    eps = config.rms_norm_eps

    def norm(x, w, b=None):
        if config.norm_type == "layernorm":
            return layer_norm(x, w, b, eps)
        return rms_norm(x, w, eps, offset=config.rms_norm_offset)

    if cache is None:
        pos0 = jnp.zeros((), jnp.int32)
        row_start = jnp.zeros((B,), jnp.int32) if start is None else start
    else:
        pos0 = cache.pos
        row_start = cache.start

    # Positions are relative to each row's start (left pad); after SnapKV
    # compression slots ≠ positions and the cache carries the true next
    # position in rope_base. pos may be per-row (serving engine).
    pos_col = pos0[:, None] if pos0.ndim == 1 else pos0
    slots = pos_col + jnp.arange(T)[None, :]  # [B|1, T] global cache slots
    if positions is not None:
        positions = positions.astype(jnp.int32)  # caller-supplied override
    elif cache is not None:
        positions = cache.next_positions(T)  # [B, T]
    else:
        positions = jnp.maximum(slots - row_start[:, None], 0)  # [B, T]

    if input_is_hidden:
        h = tokens.astype(compute_dtype)
    else:
        h = embed_tokens(config, params, tokens, compute_dtype)
        if config.learned_positions:  # gpt2 wpe table
            h = h + params["wpe"].astype(compute_dtype)[positions]
        if config.embed_layernorm:  # bloom word_embeddings_layernorm
            h = layer_norm(
                h, params["embed_norm"], params.get("embed_norm_b"),
                config.rms_norm_eps,
            )

    use_rope = not (config.alibi or config.learned_positions)
    cos_local = sin_local = None
    if use_rope:
        inv_freq, att_scale = make_inv_freq_scaled(
            config.rotary_dim, config.rope_theta, config.rope_scaling_dict,
            seq_len=(cache.max_len if cache is not None else T),
        )
        if position_grid is not None and config.mrope_section:
            from bigdl_tpu.ops.rope import mrope_cos_sin

            cos, sin = mrope_cos_sin(
                position_grid, inv_freq, config.mrope_section,
                scale=att_scale,
            )
        else:
            cos, sin = rope_cos_sin(
                positions, inv_freq, interleaved=config.rope_interleaved,
                scale=att_scale,
            )
        if config.rope_local_theta is not None:
            # gemma3 dual rope: sliding layers use the local base,
            # UNscaled (HF applies rope_scaling to global layers only)
            inv_local, _ = make_inv_freq_scaled(
                config.rotary_dim, config.rope_local_theta, None
            )
            cos_local, sin_local = rope_cos_sin(
                positions, inv_local, interleaved=config.rope_interleaved
            )
    else:
        cos = sin = None

    # qwen v1 logn attention (HF modeling_qwen logn_tensor; reference
    # models/qwen.py): queries beyond the training length scale by
    # log_train_len(pos+1) so attention entropy stays flat as the
    # context grows. max(1, .) keeps in-distribution positions exact.
    logn_col = None
    if config.logn_attn and config.logn_train_len:
        i = positions.astype(jnp.float32) + 1.0
        logn = jnp.maximum(
            jnp.log(i) / jnp.log(jnp.float32(config.logn_train_len)), 1.0
        )
        logn_col = logn[:, :, None, None].astype(compute_dtype)

    # Prefill goes through the Pallas flash-attention kernel (no [T,S]
    # score matrix in HBM); decode and the differentiable cache-free
    # training path use the fused XLA attention. Mirrors the reference's
    # sdp_causal vs sdp dispatch (models/common.py:222-258).
    from bigdl_tpu.ops.pallas import use_pallas

    uniform_window = (config.sliding_window_pattern is None
                      and config.sliding_layers is None)
    use_flash = (
        cache is not None and mode == "prefill" and T > 1 and use_pallas()
        and uniform_window and not config.alibi
        and cache.pos.ndim == 0  # kernel takes a scalar q_offset
    )
    # training (cache=None): the differentiable flash kernel
    # (ops/pallas/flash_backward.py) — the backward recomputes attention
    # blockwise instead of saving the [T, T] probabilities, which is
    # what lets long-context single-chip finetuning fit in HBM
    use_flash_train = (
        cache is None and T > 1 and use_pallas()
        and uniform_window and not config.alibi
        and attention_override is None
        and config.attn_logit_softcap is None
    )

    # Attention masks (shared by all layers, computed once outside the scan).
    # With sliding-window alternation (gemma2) both the global and the
    # sliding mask are built; the scan body selects per layer index.
    def build_masks():
        if cache is None:
            tj = jnp.arange(T)
            base = (tj[None, :] <= tj[:, None])[None] & (
                tj[None, None, :] >= row_start[:, None, None]
            )  # [B, T, T]
            k_slot = tj[None, None, :]
            q_slot = tj[None, :, None]
        else:
            S = cache.max_len
            sj = jnp.arange(S)
            base = (sj[None, None, :] <= slots[..., None]) & (
                sj[None, None, :] >= row_start[:, None, None]
            )  # [B, T, S]
            k_slot = sj[None, None, :]
            q_slot = slots[..., None]
        if config.sliding_window:
            sliding = base & (k_slot > q_slot - config.sliding_window)
        else:
            sliding = base
        return base, sliding, k_slot, q_slot

    # Paged decode reads KV pages in place via the Pallas paged-attention
    # kernel — the XLA path would gather every page into a dense [B, S]
    # copy per step (3x the HBM traffic; kvpaged.py docstring).
    from bigdl_tpu.kvpaged import PagedKVCache

    use_paged_kernel = (
        isinstance(cache, PagedKVCache) and mode == "decode" and T == 1
        and use_pallas() and not config.alibi
        and attention_override is None
    )

    if use_flash or use_paged_kernel or use_flash_train:
        mask_global = mask_sliding = None
        alibi_bias = None
    else:
        mask_global, mask_sliding, k_slot, q_slot = build_masks()
        if config.alibi:
            # additive float bias: slope_h * (k_pos - q_pos), 0 on diagonal
            # (start offsets cancel in the difference)
            slopes = alibi_slopes(Hq).reshape(Hkv, Hq // Hkv)
            if config.alibi_scale:  # falcon-rw: bias shares the score scale
                slopes = slopes * config.alibi_scale
            dist = (k_slot - q_slot).astype(jnp.float32)  # [B, T, S]
            alibi_bias = (
                slopes[None, :, :, None, None] * dist[:, None, None]
            )  # [B, Hkv, G, T, S]
        else:
            alibi_bias = None
        mask_global = mask_global[:, None, None]  # [B,1,1,T,S]
        mask_sliding = mask_sliding[:, None, None]

    lora_scale = lora["scale"] if lora is not None else None

    quantize_comm = comm is not None and comm.enabled

    def proj(x, p, lp, wname, bname=None):
        b = p.get(bname) if bname else None
        pair = lp[wname] if lp is not None and wname in lp else None
        if quantize_comm and wname in ("wo", "w_down"):
            # the two per-layer row-parallel epilogues whose implicit TP
            # psum the quantized ring replaces (the lm_head's single
            # vocab-shard reduce and MoE experts stay on GSPMD's); the
            # LoRA delta below still reduces implicitly — rank-r traffic
            # is negligible next to the hidden-size epilogue
            from bigdl_tpu.ops.linear import row_parallel_linear

            y = row_parallel_linear(x, p[wname], comm, b, compute_dtype)
            if pair is not None:
                y = y + _lora_delta(x, pair, lora_scale, compute_dtype)
        else:
            # the adapter delta rides INTO linear: eligible quantized
            # shapes fold it into the Pallas dequant-GEMM's writeback
            # (zero extra activation HBM round trips); every other path
            # applies the same lora_epilogue einsums as before
            lo = ((pair["a"], pair["b"], lora_scale)
                  if pair is not None else None)
            y = linear(x, p[wname], b, compute_dtype, lora=lo)
        return y

    # per-layer static sliding flags, as a traced vector for the scan body
    sliding_flags = jnp.asarray(
        [config.layer_is_sliding(l) for l in range(config.num_hidden_layers)],
        jnp.bool_,
    )

    def body(carry, xs):
        hidden, c, idx = carry
        p, lp = xs if lora is not None else (xs, None)

        x = norm(hidden, p["attn_norm"], p.get("attn_norm_b"))
        if "wqkv" in p:  # merged layout (merge_fused_params)
            QD, KD = Hq * D, Hkv * D
            qkv = linear(x, p["wqkv"], p.get("bqkv"), compute_dtype)
            q, k, v = (qkv[..., :QD], qkv[..., QD:QD + KD],
                       qkv[..., QD + KD:])
            if lp is not None:  # lora stays keyed by the unmerged names
                if "wq" in lp:
                    q = q + _lora_delta(x, lp["wq"], lora_scale, compute_dtype)
                if "wk" in lp:
                    k = k + _lora_delta(x, lp["wk"], lora_scale, compute_dtype)
                if "wv" in lp:
                    v = v + _lora_delta(x, lp["wv"], lora_scale, compute_dtype)
            q = q.reshape(B, T, Hq, D)
            k = k.reshape(B, T, Hkv, D)
            v = v.reshape(B, T, Hkv, D)
        else:
            q = proj(x, p, lp, "wq", "bq").reshape(B, T, Hq, D)
            k = proj(x, p, lp, "wk", "bk").reshape(B, T, Hkv, D)
            v = proj(x, p, lp, "wv", "bv").reshape(B, T, Hkv, D)
        if config.qk_norm:
            q = rms_norm(q, p["q_norm"], eps, offset=config.rms_norm_offset)
            k = rms_norm(k, p["k_norm"], eps, offset=config.rms_norm_offset)
        if use_rope:
            if cos_local is not None:
                is_sliding_l = sliding_flags[layer_offset + idx]
                cos_l = jnp.where(is_sliding_l, cos_local, cos)
                sin_l = jnp.where(is_sliding_l, sin_local, sin)
            else:
                cos_l, sin_l = cos, sin
            q, k = apply_rotary_emb(q, k, cos_l, sin_l, config.rope_interleaved)
        if logn_col is not None:
            q = q * logn_col

        k_scale_att = v_scale_att = None
        if c is not None:
            c = kvcache.update_layer(c, idx, k, v)
            if use_flash and c.quantized:
                # fp8 codes + scales go straight to the flash kernel,
                # which dequantizes per block in-kernel — never a dense
                # bf16 copy of the cache in HBM (kvcache.read_layer_raw)
                k_att, v_att, k_scale_att, v_scale_att = \
                    kvcache.read_layer_raw(c, idx)
            elif not use_paged_kernel:
                k_att, v_att = kvcache.read_layer(c, idx, compute_dtype)
        else:
            k_att = k.astype(compute_dtype)
            v_att = v.astype(compute_dtype)

        if use_paged_kernel:
            from bigdl_tpu.ops.pallas import paged_decode_attention

            if config.sliding_window is None:
                win_l = None
            else:  # traced: sliding layers alternate within the scan
                win_l = jnp.where(
                    sliding_flags[layer_offset + idx],
                    config.sliding_window, 2 ** 30,
                ).astype(jnp.int32)
            attn = paged_decode_attention(
                q[:, 0], c.k, c.v, c.block_tables, idx, c.pos, c.start,
                k_scale=c.k_scale, v_scale=c.v_scale,
                scale=config.attn_scale,
                softcap=config.attn_logit_softcap, window=win_l,
            )[:, None]
        elif attention_override is not None and c is None:
            attn = attention_override(q, k_att, v_att, row_start)
        elif use_flash_train:
            from bigdl_tpu.ops.pallas import flash_attention_trainable

            attn = flash_attention_trainable(
                q, k_att, v_att, row_start,
                window=config.sliding_window, scale=config.attn_scale,
            )
        elif use_flash:
            from bigdl_tpu.ops.pallas import flash_attention

            attn = flash_attention(
                q, k_att, v_att, start=row_start, q_offset=pos0,
                window=config.sliding_window, softcap=config.attn_logit_softcap,
                scale=config.attn_scale,
                k_scale=k_scale_att, v_scale=v_scale_att,
            )
        else:
            is_sliding = sliding_flags[layer_offset + idx]
            mask = jnp.where(is_sliding, mask_sliding, mask_global)
            if alibi_bias is not None:
                mask = jnp.where(mask, alibi_bias, _NEG_INF)
            attn = attention(
                q, k_att, v_att, mask,
                scale=config.attn_scale, softcap=config.attn_logit_softcap,
            )
        out = proj(attn.reshape(B, T, Hq * D), p, lp, "wo", "bo")
        if config.post_attn_norm:
            out = norm(out, p["post_attn_norm"])
        rs = config.residual_scale
        if config.parallel_residual:
            # gptneox: attention and MLP both read the SAME layer input;
            # residual adds both at once
            mlp_in = norm(hidden, p["mlp_norm"], p.get("mlp_norm_b"))
        else:
            hidden = hidden + (out * rs if rs else out)
            mlp_in = norm(hidden, p["mlp_norm"], p.get("mlp_norm_b"))

        x = mlp_in
        if config.is_moe:
            down = _moe_mlp(config, x, p, compute_dtype)
        elif "w_gateup" in p:  # merged layout (merge_fused_params)
            gu = linear(x, p["w_gateup"], p.get("b_gateup"), compute_dtype)
            I2 = gu.shape[-1] // 2
            gate, up = gu[..., :I2], gu[..., I2:]
            if lp is not None:
                if "w_gate" in lp:
                    gate = gate + _lora_delta(x, lp["w_gate"], lora_scale,
                                              compute_dtype)
                if "w_up" in lp:
                    up = up + _lora_delta(x, lp["w_up"], lora_scale,
                                          compute_dtype)
            down = proj(_act(config.hidden_act, gate) * up, p, lp, "w_down", "b_down")
        elif config.gated_mlp:
            gate = proj(x, p, lp, "w_gate", "b_gate")
            up = proj(x, p, lp, "w_up", "b_up")
            down = proj(_act(config.hidden_act, gate) * up, p, lp, "w_down", "b_down")
        else:
            up = proj(x, p, lp, "w_up", "b_up")
            down = proj(_act(config.hidden_act, up), p, lp, "w_down", "b_down")
        if config.post_attn_norm:
            down = norm(down, p["post_mlp_norm"])
        if config.parallel_residual:
            hidden = hidden + out + down
        else:
            hidden = hidden + (down * rs if rs else down)

        ys = q[:, T - collect_obs:] if collect_obs else None
        return (hidden, c, idx + 1), ys

    xs = (params["layers"], lora["layers"]) if lora is not None else params["layers"]
    scan_body = body
    if remat:
        # recompute the layer in the backward instead of saving its
        # activations; prevent_cse is the documented setting for remat
        # inside scan (jax.checkpoint docs)
        scan_body = jax.checkpoint(body, prevent_cse=False)
    (h, cache, _), obs = jax.lax.scan(
        scan_body, (h, cache, jnp.zeros((), jnp.int32)), xs
    )

    if return_hidden:
        logits = h
    else:
        if last_logits_only:
            h = h[:, -1:]
        logits = lm_head_logits(config, params, h, compute_dtype)
    if cache is not None:
        cache = kvcache.advance(cache, T)
    if collect_obs:
        return logits, cache, obs
    return logits, cache
