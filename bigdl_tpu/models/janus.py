"""Janus / Janus-Pro — understanding path: SigLIP-style vision encoder +
aligner MLP over the llama decoder.

TPU-native counterpart of the reference's janus support
(/root/reference/python/llm/src/ipex_llm/transformers/models/janus.py —
it, too, optimizes only the vision attention; dispatch at
convert.py:1251-2027). Architecture per HF modeling_janus:

- vision: Conv2d patch embed + learned position embeddings (no cls
  token), pre-LN blocks (LN -> MHA -> LN -> gelu MLP), final
  post_layernorm;
- aligner: fc1 to projection_dim then (depth-1) x (act -> linear);
- text: llama-shaped decoder; image features scatter over the
  placeholder tokens like the other multimodal families.

The image-GENERATION path (JanusVQVAE decoding image tokens) is out of
scope — the reference likewise leaves the VQVAE untouched and only
accelerates the understanding stack.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import layer_norm

# the text side delegates wholesale to the llama family
init_params = llama.init_params
quantize_params = llama.quantize_params
forward = llama.forward
merge_fused_params = llama.merge_fused_params
unmerge_fused_params = llama.unmerge_fused_params


@dataclasses.dataclass(frozen=True)
class JanusVisionConfig:
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    image_size: int = 384
    patch_size: int = 16
    num_channels: int = 3
    layer_norm_eps: float = 1e-6
    attention_bias: bool = True
    hidden_act: str = "gelu"  # HF JanusVisionConfig default: exact erf
    projection_dim: int = 2048  # aligner output (text hidden)
    depth: int = 2  # aligner layers

    @classmethod
    def from_hf(cls, hf: dict) -> "JanusVisionConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in hf.items() if k in keys})

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def patch_dim(self) -> int:
        return self.num_channels * self.patch_size ** 2


def vision_params_from_state_dict(
    vcfg: JanusVisionConfig, get, prefix="model.vision_model."
) -> dict:
    def g(name):
        return np.asarray(get(prefix + name), np.float32)

    E = vcfg.hidden_size
    blocks: dict[str, list] = {}
    names = [
        ("ln1_w", "layer_norm1.weight"), ("ln1_b", "layer_norm1.bias"),
        ("ln2_w", "layer_norm2.weight"), ("ln2_b", "layer_norm2.bias"),
        ("wq", "self_attn.q_proj.weight"), ("wk", "self_attn.k_proj.weight"),
        ("wv", "self_attn.v_proj.weight"),
        ("wo", "self_attn.projection_layer.weight"),
        ("bo", "self_attn.projection_layer.bias"),
        ("fc1_w", "mlp.fc1.weight"), ("fc1_b", "mlp.fc1.bias"),
        ("fc2_w", "mlp.fc2.weight"), ("fc2_b", "mlp.fc2.bias"),
    ]
    if vcfg.attention_bias:
        names += [("bq", "self_attn.q_proj.bias"),
                  ("bk", "self_attn.k_proj.bias"),
                  ("bv", "self_attn.v_proj.bias")]
    for i in range(vcfg.num_hidden_layers):
        for key, suffix in names:
            blocks.setdefault(key, []).append(g(f"encoder.layers.{i}.{suffix}"))
    params = {
        "patch_proj": g("embeddings.patch_embedding.weight").reshape(E, -1),
        "patch_bias": g("embeddings.patch_embedding.bias"),
        "pos_embed": g("embeddings.position_embedding.weight"),  # [N, E]
        "blocks": {k: jnp.asarray(np.stack(v)) for k, v in blocks.items()},
        "post_ln_w": g("post_layernorm.weight"),
        "post_ln_b": g("post_layernorm.bias"),
    }
    return jax.tree.map(jnp.asarray, params)


def aligner_params_from_state_dict(vcfg: JanusVisionConfig, get,
                                   prefix="model.aligner.") -> dict:
    def g(name):
        return jnp.asarray(np.asarray(get(prefix + name), np.float32))

    out = {"fc1_w": g("fc1.weight"), "fc1_b": g("fc1.bias"), "hidden": []}
    for i in range(vcfg.depth - 1):
        out["hidden"].append(
            (g(f"hidden_layers.{i}.weight"), g(f"hidden_layers.{i}.bias"))
        )
    return out


def _act(vcfg: JanusVisionConfig, x):
    # HF ACT2FN[config.hidden_act]: "gelu" = exact erf, tanh variants approx
    exact = vcfg.hidden_act == "gelu"
    return jax.nn.gelu(x, approximate=not exact)


def vision_forward(
    vcfg: JanusVisionConfig,
    vparams: dict,
    patches: jax.Array,  # [B, N, patch_dim]
    out_dtype=jnp.float32,
) -> jax.Array:
    """[B, N, patch_dim] -> [B, N, E] (post_layernorm applied), matching
    JanusVisionModel.last_hidden_state."""
    B, N, _ = patches.shape
    E, Hh, D = vcfg.hidden_size, vcfg.num_attention_heads, vcfg.head_dim
    eps = vcfg.layer_norm_eps

    h = (
        jnp.einsum("bnd,ed->bne", patches.astype(jnp.float32),
                   vparams["patch_proj"])
        + vparams["patch_bias"]
    )
    h = h + vparams["pos_embed"][None, :N]
    scale = D ** -0.5

    def block(h, p):
        x = layer_norm(h, p["ln1_w"], p["ln1_b"], eps)
        q = jnp.einsum("bne,fe->bnf", x, p["wq"])
        k = jnp.einsum("bne,fe->bnf", x, p["wk"])
        v = jnp.einsum("bne,fe->bnf", x, p["wv"])
        if "bq" in p:
            q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
        q = q.reshape(B, N, Hh, D)
        k = k.reshape(B, N, Hh, D)
        v = v.reshape(B, N, Hh, D)
        att = jnp.einsum("bnhd,bmhd->bhnm", q, k) * scale
        att = jax.nn.softmax(att, axis=-1)
        ctx = jnp.einsum("bhnm,bmhd->bnhd", att, v).reshape(B, N, E)
        h = h + jnp.einsum("bne,fe->bnf", ctx, p["wo"]) + p["bo"]

        x = layer_norm(h, p["ln2_w"], p["ln2_b"], eps)
        x = jnp.einsum("bne,fe->bnf", x, p["fc1_w"]) + p["fc1_b"]
        x = _act(vcfg, x)
        h = h + jnp.einsum("bnf,ef->bne", x, p["fc2_w"]) + p["fc2_b"]
        return h, None

    h, _ = jax.lax.scan(block, h, vparams["blocks"])
    h = layer_norm(h, vparams["post_ln_w"], vparams["post_ln_b"], eps)
    return h.astype(out_dtype)


def image_features(
    vcfg: JanusVisionConfig,
    vparams: dict,
    aparams: dict,
    patches: jax.Array,
    out_dtype=jnp.float32,
) -> jax.Array:
    """Tower + aligner MLP = HF JanusModel.get_image_features."""
    h = vision_forward(vcfg, vparams, patches)
    h = jnp.einsum("bne,pe->bnp", h, aparams["fc1_w"]) + aparams["fc1_b"]
    for w, b in aparams["hidden"]:
        h = _act(vcfg, h)
        h = jnp.einsum("bnp,qp->bnq", h, w) + b
    return h.astype(out_dtype)


def multimodal_prefill(
    config: ModelConfig,
    vcfg: JanusVisionConfig,
    params: dict,
    vparams: dict,
    aparams: dict,
    input_ids: np.ndarray,  # [B, T] with image_token_id placeholders
    patches: jax.Array,  # [B, N, patch_dim]
    cache,
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = True,
):
    from bigdl_tpu.models._multimodal import scatter_image_features

    img = image_features(vcfg, vparams, aparams, patches)  # [B, Q, E]
    h = scatter_image_features(config, params, input_ids, img, compute_dtype)
    return llama.forward(
        config, params, h, cache, mode="prefill", input_is_hidden=True,
        compute_dtype=compute_dtype, last_logits_only=last_logits_only,
    )
