"""Yuan-2 family — llama-shaped decoder with Localized Filtering-based
Attention (LFA).

TPU-native re-design of the reference's patched forward
(/root/reference/python/llm/src/ipex_llm/transformers/models/yuan.py and
the bundled original at transformers/gguf/models/model_implement/yuan2/
yuan_hf_model.py:46-130): before the q/k projections, the post-norm
hidden passes a two-stage causal conv filter (kernel 2 over time) with a
residual RMSNorm — Mega-style EMA smoothing; v projects from the
unfiltered hidden. A kernel-2 conv over time is just `shift + matmul`,
so the whole filter is two pairs of MXU matmuls here, no conv op.

Decode needs the last TWO post-norm hiddens per layer to recompute the
filter for the next token (the reference appends them as a third element
of past_key_value, yuan.py:120-128). `YuanCache` composes the standard
KVCache with that [L, B, 2, C] conv state and satisfies the
`generate_tokens` family-cache contract (`start` field + `init_cache`
hook), like RWKV's recurrent state.

Left-padding: pad positions zero their post-norm hidden and their
first-stage conv outputs, reproducing the reference's zero conv padding
at the true sequence start.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import kvcache
from bigdl_tpu.kvcache import KVCache
from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import apply_rotary_emb, attention, linear, rms_norm, rope_cos_sin
from bigdl_tpu.ops.rope import make_inv_freq_scaled

Params = dict[str, Any]

_NEG_INF = -1e30


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class YuanCache:
    kv: KVCache
    lf: jax.Array  # [L, B, 2, C] f32: last two post-norm hiddens
    start: jax.Array  # [B] int32 (mirrored into kv at forward entry)

    @property
    def pos(self):
        return self.kv.pos


def init_cache(
    config: ModelConfig,
    batch: int,
    cache_len: int,
    quantize_kv: bool = False,
    dtype=jnp.bfloat16,
) -> YuanCache:
    kv = kvcache.init_cache(
        config.num_hidden_layers, batch, cache_len,
        config.num_key_value_heads, config.head_dim_,
        quantize_kv=quantize_kv, dtype=dtype,
    )
    lf = jnp.zeros(
        (config.num_hidden_layers, batch, 2, config.hidden_size), jnp.float32
    )
    return YuanCache(kv=kv, lf=lf, start=kv.start)


# --- serving-engine adapter (serving/engine.py custom-cache protocol):
# the nested KV pool inserts like the generic path; the localized-filter
# hiddens lf are per-row state copied alongside.

def engine_pool(config: ModelConfig, n_slots: int, max_len: int):
    cache = init_cache(config, n_slots, max_len)
    kv = dataclasses.replace(cache.kv, pos=jnp.zeros((n_slots,), jnp.int32))
    return dataclasses.replace(cache, kv=kv)


def engine_insert(cache, pcache, slot, pad):
    kv = kvcache.insert_row(cache.kv, pcache.kv, slot, pad)
    return dataclasses.replace(
        cache, kv=kv,
        lf=cache.lf.at[:, slot].set(pcache.lf[:, 0]),
        start=kv.start,
    )


def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> Params:
    """Random dense init (tests/benchmarks run without checkpoints)."""
    L, H, I = config.num_hidden_layers, config.hidden_size, config.intermediate_size
    V, QD, KD = config.vocab_size, config.q_dim, config.kv_dim
    Hh = H // 2
    keys = iter(jax.random.split(key, 24))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "attn_norm": jnp.ones((L, H), dtype),
        "mlp_norm": jnp.ones((L, H), dtype),
        "wq": w((L, QD, H)),
        "wk": w((L, KD, H)),
        "wv": w((L, KD, H)),
        "wo": w((L, H, QD)),
        "w_gate": w((L, I, H)),
        "w_up": w((L, I, H)),
        "w_down": w((L, H, I)),
        "lf_w1a": w((L, Hh, H)), "lf_w1b": w((L, Hh, H)),
        "lf_b1": jnp.zeros((L, Hh), dtype),
        "lf_w2a": w((L, H, Hh)), "lf_w2b": w((L, H, Hh)),
        "lf_b2": jnp.zeros((L, H), dtype),
        "lf_norm": jnp.ones((L, H), dtype),
    }
    return {
        "embed": w((V, H)),
        "layers": layers,
        "final_norm": jnp.ones((H,), dtype),
        "lm_head": w((V, H)),
    }


# llama's quantizer covers yuan's tree: the shared wq/wk/wv/wo and
# gate/up/down names quantize, the lf_* conv weights (absent from its
# _QUANT_TARGETS) stay dense — they are [C/2, C] (tiny next to the
# attention/MLP linears) and feed the f32 filter path
quantize_params = llama.quantize_params


def lfa_filter(x, lf_state, real, ent0_real, p, eps, compute_dtype):
    """Localized filtering: two causal kernel-2 convs + residual RMSNorm.

    x: [B, T, C] post-norm hidden, already zeroed at pad positions;
    lf_state: [B, 2, C] the two hiddens before this chunk; real: [B, T]
    1.0 at non-pad positions; ent0_real: [B, 1] whether slot pos-1 (the
    first-stage entry recomputed from the state) is a real position.
    Returns (filtered [B, T, C], new state [B, 2, C]).

    conv(k=2)[t] = Wa·x[t-1] + Wb·x[t] + b — shift + two matmuls. The
    first-stage outputs at pre-start positions are zeroed to reproduce
    the reference's zero conv padding at the sequence start
    (yuan_hf_model.py:99-105: `output1[:, :, :seq_len]` after pad=1) —
    the conv BIAS would otherwise leak through zeroed inputs.
    """
    xf = x.astype(jnp.float32)
    ext = jnp.concatenate([lf_state.astype(jnp.float32), xf], axis=1)  # [B,T+2,C]

    def mm(v, wname):
        return jnp.einsum("btc,oc->bto", v, p[wname].astype(jnp.float32))

    # c1 entries j=0..T at positions (slot pos-1, x_0..x_{T-1})
    c1 = mm(ext[:, :-1], "lf_w1a") + mm(ext[:, 1:], "lf_w1b")
    c1 = c1 + p["lf_b1"].astype(jnp.float32)
    c1_mask = jnp.concatenate([ent0_real, real], axis=1)[:, :, None]
    c1 = c1 * c1_mask
    # c2[t] = W2a·c1[t-1 pos] + W2b·c1[t pos], positions x_0..x_{T-1}
    c2 = mm(c1[:, :-1], "lf_w2a") + mm(c1[:, 1:], "lf_w2b")
    c2 = c2 + p["lf_b2"].astype(jnp.float32)
    c2 = c2 * real[:, :, None]
    out = rms_norm((c2 + xf).astype(compute_dtype), p["lf_norm"], eps)
    out = out * real[:, :, None].astype(out.dtype)
    return out, ext[:, -2:]


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cache: Optional[YuanCache],
    mode: str = "prefill",
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = False,
) -> tuple[jax.Array, Optional[YuanCache]]:
    """Returns (logits [B, T, V] float32, advanced cache)."""
    assert mode in ("prefill", "decode")
    B, T = tokens.shape
    Hq, Hkv, D = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim_)
    eps = config.rms_norm_eps

    fresh = cache is None
    if fresh:
        cache = init_cache(config, B, T)
    kv = dataclasses.replace(cache.kv, start=cache.start)

    pos_col = kv.pos[:, None] if kv.pos.ndim == 1 else kv.pos
    slots = pos_col + jnp.arange(T)[None, :]  # [B|1, T]
    positions = kv.next_positions(T)  # [B, T]
    real = (slots >= cache.start[:, None]).astype(jnp.float32)
    if real.shape[0] != B:
        real = jnp.broadcast_to(real, (B, T))
    ent0_real = (
        (slots[:, :1] - 1) >= cache.start[:, None]
    ).astype(jnp.float32)
    if ent0_real.shape[0] != B:
        ent0_real = jnp.broadcast_to(ent0_real, (B, 1))

    from bigdl_tpu.embedding import embed_lookup

    h = embed_lookup(params["embed"], tokens, compute_dtype)

    inv_freq, att_scale = make_inv_freq_scaled(
        config.rotary_dim, config.rope_theta, config.rope_scaling_dict,
        seq_len=kv.max_len,
    )
    cos, sin = rope_cos_sin(positions, inv_freq, scale=att_scale)

    S = kv.max_len
    sj = jnp.arange(S)
    mask = (sj[None, None, :] <= slots[..., None]) & (
        sj[None, None, :] >= cache.start[:, None, None]
    )  # [B, T, S]
    mask = mask[:, None, None]  # [B,1,1,T,S]

    def proj(x, p, wname):
        return linear(x, p[wname], None, compute_dtype)

    def body(carry, xs):
        hidden, c, idx = carry
        p, lf_st = xs

        x = rms_norm(hidden, p["attn_norm"], eps)
        x = x * real[:, :, None].astype(x.dtype)  # zero pads for the filter
        filtered, new_lf = lfa_filter(
            x, lf_st, real, ent0_real, p, eps, compute_dtype
        )

        q = proj(filtered, p, "wq").reshape(B, T, Hq, D)
        k = proj(filtered, p, "wk").reshape(B, T, Hkv, D)
        v = proj(x, p, "wv").reshape(B, T, Hkv, D)
        q, k = apply_rotary_emb(q, k, cos, sin, False)

        c = kvcache.update_layer(c, idx, k, v)
        k_att, v_att = kvcache.read_layer(c, idx, compute_dtype)
        attn = attention(q, k_att, v_att, mask)
        out = proj(attn.reshape(B, T, Hq * D), p, "wo")
        hidden = hidden + out

        x2 = rms_norm(hidden, p["mlp_norm"], eps)
        gate = proj(x2, p, "w_gate")
        up = proj(x2, p, "w_up")
        hidden = hidden + proj(jax.nn.silu(gate) * up, p, "w_down")
        return (hidden, c, idx + 1), new_lf

    (h, kv, _), new_lf = jax.lax.scan(
        body, (h, kv, jnp.zeros((), jnp.int32)), (params["layers"], cache.lf)
    )

    if last_logits_only:
        h = h[:, -1:]
    hN = rms_norm(h, params["final_norm"], eps)
    logits = linear(hN, params["lm_head"], None, compute_dtype).astype(jnp.float32)

    if fresh:
        return logits, None
    kv = kvcache.advance(kv, T)
    return logits, YuanCache(kv=kv, lf=new_lf, start=cache.start)
