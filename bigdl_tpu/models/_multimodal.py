"""Shared multimodal glue: scatter projected image features over
placeholder tokens — used by minicpmv, internvl, and janus (qwen2_vl
needs its own path: its features are globally concatenated across
images, not per-row)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig


def scatter_image_features(
    config: ModelConfig,
    params: dict,
    input_ids: np.ndarray,  # [B, T]
    img: jnp.ndarray,  # [B, Q, E] per-row projected image features
    compute_dtype,
    allow_text_rows: bool = True,
) -> jnp.ndarray:
    """Token embeddings with row b's Q features scattered over its
    image_token_id placeholders (per-row indexing — a global cumsum
    would misassign in mixed batches). Rows must carry exactly Q
    placeholders (or zero, when allow_text_rows — their patches are
    ignored); anything else raises like HF's masked_scatter path."""
    h = llama.embed_tokens(config, params, jnp.asarray(input_ids), compute_dtype)
    mask = jnp.asarray(input_ids == config.image_token_id)
    B = input_ids.shape[0]
    Q = img.shape[1]
    counts = np.asarray(input_ids == config.image_token_id).sum(axis=1)
    ok = (counts == Q) | ((counts == 0) if allow_text_rows else False)
    if not np.all(ok):
        raise ValueError(
            f"image placeholder count per row {counts.tolist()} must be "
            f"{'0 or ' if allow_text_rows else ''}exactly {Q} "
            "(the projected feature count)"
        )
    row_cum = jnp.cumsum(mask, axis=1) - 1
    idx = jnp.arange(B)[:, None] * Q + jnp.clip(row_cum, 0, Q - 1)
    flat = img.reshape(-1, img.shape[-1])
    return jnp.where(mask[..., None], flat[idx].astype(compute_dtype), h)
