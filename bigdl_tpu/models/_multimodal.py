"""Shared multimodal glue: scatter projected image/audio features over
placeholder tokens — used by minicpmv, internvl, janus, and minicpmo
(qwen2_vl needs its own path: its features are globally concatenated
across images, not per-row)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig


def scatter_features(
    h: jnp.ndarray,  # [B, T, E] token embeddings (already computed)
    input_ids: np.ndarray,  # [B, T]
    feats: jnp.ndarray,  # [B, Q, E] per-row projected features
    token_id: int,
    compute_dtype,
    allow_text_rows: bool = True,
    what: str = "image",
) -> jnp.ndarray:
    """Replace row b's `token_id` placeholder embeddings with its Q
    features (per-row indexing — a global cumsum would misassign in
    mixed batches). Rows must carry exactly Q placeholders (or zero,
    when allow_text_rows — their features are ignored); anything else
    raises like HF's masked_scatter path."""
    mask = jnp.asarray(input_ids == token_id)
    B = input_ids.shape[0]
    Q = feats.shape[1]
    counts = np.asarray(input_ids == token_id).sum(axis=1)
    ok = (counts == Q) | ((counts == 0) if allow_text_rows else False)
    if not np.all(ok):
        raise ValueError(
            f"{what} placeholder count per row {counts.tolist()} must be "
            f"{'0 or ' if allow_text_rows else ''}exactly {Q} "
            "(the projected feature count)"
        )
    row_cum = jnp.cumsum(mask, axis=1) - 1
    idx = jnp.arange(B)[:, None] * Q + jnp.clip(row_cum, 0, Q - 1)
    flat = feats.reshape(-1, feats.shape[-1])
    return jnp.where(mask[..., None], flat[idx].astype(compute_dtype), h)


def scatter_image_features(
    config: ModelConfig,
    params: dict,
    input_ids: np.ndarray,  # [B, T]
    img: Optional[jnp.ndarray],  # [B, Q, E] per-row projected image features
    compute_dtype,
    allow_text_rows: bool = True,
    audio: Optional[jnp.ndarray] = None,  # [B, Qa, E] audio features
) -> jnp.ndarray:
    """Token embeddings with image (and optionally audio) features
    scattered over their placeholder ids."""
    h = llama.embed_tokens(config, params, jnp.asarray(input_ids), compute_dtype)
    if (
        img is not None
        and audio is not None
        and config.image_token_id == config.audio_token_id
    ):
        raise ValueError(
            f"image_token_id == audio_token_id == {config.image_token_id}: "
            "set distinct placeholder ids (from the tokenizer) before "
            "passing both modalities"
        )
    if img is not None:
        h = scatter_features(
            h, input_ids, img, config.image_token_id, compute_dtype,
            allow_text_rows, what="image",
        )
    if audio is not None:
        if config.audio_token_id is None:
            raise ValueError("audio features given but audio_token_id unset")
        h = scatter_features(
            h, input_ids, audio, config.audio_token_id, compute_dtype,
            allow_text_rows, what="audio",
        )
    return h
