"""Mllama (Llama-3.2-Vision) text model — llama decoder with interleaved
tanh-gated cross-attention layers.

TPU-native counterpart of the reference's mllama support
(/root/reference/python/llm/src/ipex_llm/transformers/models/mllama.py
patches MllamaTextCrossAttention/self-attention; dispatch at
convert.py:1251-2027). Architecture per HF modeling_mllama:

- self-attention layers: plain llama3 GQA + rope (every index NOT in
  config.cross_attention_layers);
- cross-attention layers: q from the hidden state with per-head RMSNorm,
  k/v from the vision states with per-head RMSNorm on k, NO rope; the
  attention and MLP branches re-enter the residual through
  `tanh(gate)` scalars, and the MLP branch is zeroed for tokens whose
  cross-attention row is fully masked (HF full_text_row_masked_out_mask;
  those rows' attention runs UNMASKED — _prepare_cross_attention_mask
  zeroes their -inf row, yielding uniform attention — reproduced here);
- embed table has 8 extra special-image rows past vocab_size; lm_head
  stays at vocab_size.

Layer heterogeneity vs the scan-stacked llama family: self layers stack
into contiguous segments separated by cross layers (positions are
static config), so the forward runs `lax.scan` per segment with a
layer-index offset and applies one cross layer between segments —
compile time stays O(segments), not O(layers).

`MllamaCache` composes the self-attention KVCache with the per-layer
cross K/V (computed once from the vision states at multimodal prefill;
`ck is None` = text-only, cross layers skip entirely — matching HF's
layer skip when no image is present).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import kvcache
from bigdl_tpu.kvcache import KVCache
from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import apply_rotary_emb, attention, linear, rms_norm, rope_cos_sin
from bigdl_tpu.ops.rope import make_inv_freq_scaled

Params = dict[str, Any]


def _segments(config: ModelConfig) -> list[int]:
    """Self-layer run lengths between cross layers. cross layer s sits
    after segment s; a trailing segment may have no cross layer."""
    cross = list(config.cross_attention_layers or ())
    sizes, prev = [], 0
    for c in cross:
        sizes.append(c - prev)
        prev = c + 1
    sizes.append(config.num_hidden_layers - prev)
    return sizes


def num_self_layers(config: ModelConfig) -> int:
    return config.num_hidden_layers - len(config.cross_attention_layers or ())


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MllamaCache:
    kv: KVCache  # self-attention layers only
    ck: Optional[jax.Array]  # [S, B, N, Hkv, D] normed cross keys, or None
    cv: Optional[jax.Array]  # [S, B, N, Hkv, D]
    # decode-time cross state, carried from the last prefill token (HF
    # extends the final cross_attention_mask column over generated
    # tokens): additive mask over vision tokens + row liveness for the
    # gated-MLP zeroing
    cross_amask: Optional[jax.Array]  # [B, N] additive (0 = attend)
    cross_live: Optional[jax.Array]  # [B] f32: row's cross row not dead
    start: jax.Array  # [B]

    @property
    def pos(self):
        return self.kv.pos


def init_cache(
    config: ModelConfig,
    batch: int,
    cache_len: int,
    quantize_kv: bool = False,
    dtype=jnp.bfloat16,
) -> MllamaCache:
    """Text-only cache (ck=None): cross layers skip, decoder == llama3."""
    kv = kvcache.init_cache(
        num_self_layers(config), batch, cache_len,
        config.num_key_value_heads, config.head_dim_,
        quantize_kv=quantize_kv, dtype=dtype,
    )
    return MllamaCache(kv=kv, ck=None, cv=None, cross_amask=None,
                       cross_live=None, start=kv.start)


# --- serving-engine adapter (serving/engine.py custom-cache protocol):
# text-only serving — the pool has no cross state (ck=None) and the
# engine's prefill builds text-only caches, so cross layers skip and the
# decoder is llama3. Image requests go through TpuModel.generate.

def engine_pool(config: ModelConfig, n_slots: int, max_len: int):
    cache = init_cache(config, n_slots, max_len)
    kv = dataclasses.replace(cache.kv, pos=jnp.zeros((n_slots,), jnp.int32))
    return dataclasses.replace(cache, kv=kv)


def engine_insert(cache, pcache, slot, pad):
    assert pcache.ck is None, (
        "engine serving is text-only for mllama; use generate() for images"
    )
    kv = kvcache.insert_row(cache.kv, pcache.kv, slot, pad)
    return dataclasses.replace(cache, kv=kv, start=kv.start)


def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> Params:
    """Random init: llama tree for the self layers (num_self_layers deep)
    + a stacked cross-layer tree."""
    S = len(config.cross_attention_layers or ())
    base_cfg = dataclasses.replace(
        config, num_hidden_layers=num_self_layers(config),
        cross_attention_layers=None,
    )
    params = llama.init_params(base_cfg, key, dtype, scale)
    H, I = config.hidden_size, config.intermediate_size
    QD, KD, D = config.q_dim, config.kv_dim, config.head_dim_
    keys = iter(jax.random.split(jax.random.fold_in(key, 7), 16))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    if S:
        params["cross"] = {
            "attn_norm": jnp.ones((S, H), dtype),
            "mlp_norm": jnp.ones((S, H), dtype),
            "wq": w((S, QD, H)), "wk": w((S, KD, H)),
            "wv": w((S, KD, H)), "wo": w((S, H, QD)),
            "q_norm": jnp.ones((S, D), dtype),
            "k_norm": jnp.ones((S, D), dtype),
            "attn_gate": jnp.zeros((S,), dtype),
            "mlp_gate": jnp.zeros((S,), dtype),
            "w_gate": w((S, I, H)), "w_up": w((S, I, H)),
            "w_down": w((S, H, I)),
        }
    # embed carries 8 extra special-image rows (HF vocab_size + 8)
    V, _ = params["embed"].shape
    extra = (jax.random.normal(next(keys), (8, H), jnp.float32) * scale).astype(dtype)
    params["embed"] = jnp.concatenate([params["embed"], extra], axis=0)
    return params


def quantize_params(params: Params, qtype: str, lm_head_qtype: Optional[str] = None) -> Params:
    """Self layers + lm head via the llama quantizer; the cross-layer
    projections quantize with the same body qtype."""
    from bigdl_tpu.quant import QTensor, quantize
    from bigdl_tpu.quant.qtypes import resolve_qtype, split_mixed_qtype

    out = llama.quantize_params(params, qtype, lm_head_qtype)
    body_qtype, _ = split_mixed_qtype(qtype)
    spec = resolve_qtype(body_qtype)
    if spec.is_dense or "cross" not in params:
        return out
    cross = dict(params["cross"])
    for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        wv_ = cross.get(name)
        if wv_ is not None and not isinstance(wv_, QTensor):
            cross[name] = quantize(wv_, spec.name)
    out = dict(out)
    out["cross"] = cross
    return out


def encode_cross_kv(
    config: ModelConfig,
    params: Params,
    cross_states: jax.Array,  # [B, N, H] vision features (projected)
    compute_dtype=jnp.bfloat16,
) -> tuple[jax.Array, jax.Array]:
    """Per-cross-layer K/V from the vision states, k per-head-normed —
    computed once at prefill, reused every decode step (the reference
    caches them the same way through HF's cache plumbing)."""
    B, N, _ = cross_states.shape
    Hkv, D = config.num_key_value_heads, config.head_dim_
    cp = params["cross"]
    S = len(config.cross_attention_layers or ())
    ks, vs = [], []
    for s in range(S):
        k = linear(cross_states, _slice(cp["wk"], s), None, compute_dtype)
        v = linear(cross_states, _slice(cp["wv"], s), None, compute_dtype)
        k = rms_norm(
            k.reshape(B, N, Hkv, D), _slice(cp["k_norm"], s),
            config.rms_norm_eps,
        )
        ks.append(k)
        vs.append(v.reshape(B, N, Hkv, D))
    return jnp.stack(ks), jnp.stack(vs)


def _slice(w, s):
    from bigdl_tpu.quant import QTensor

    if isinstance(w, QTensor):
        return w.map_arrays(lambda a: a[s])
    return w[s]


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32 (or [B, T, H] with input_is_hidden)
    cache: Optional[MllamaCache],
    mode: str = "prefill",
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = False,
    input_is_hidden: bool = False,
    # prefill-only: [B, T, N] bool, True where token t may attend vision
    # token n. Tokens with an all-False row are "dead" (HF
    # full_text_row_masked_out_mask): their additive mask becomes all
    # zeros (uniform attention — exactly what HF's
    # _prepare_cross_attention_mask produces) and their gated MLP branch
    # is zeroed. None = every token attends everything.
    cross_mask: Optional[jax.Array] = None,
) -> tuple[jax.Array, Optional[MllamaCache]]:
    assert mode in ("prefill", "decode")
    B, T = tokens.shape[:2]
    Hq, Hkv, D = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim_)
    eps = config.rms_norm_eps

    fresh = cache is None
    if fresh:
        cache = init_cache(config, B, T, dtype=jnp.float32)
    kv = dataclasses.replace(cache.kv, start=cache.start)

    pos_col = kv.pos[:, None] if kv.pos.ndim == 1 else kv.pos
    slots = pos_col + jnp.arange(T)[None, :]
    positions = kv.next_positions(T)

    if input_is_hidden:
        h = tokens.astype(compute_dtype)
    else:
        h = llama.embed_tokens(config, params, tokens, compute_dtype)

    inv_freq, att_scale = make_inv_freq_scaled(
        config.rotary_dim, config.rope_theta, config.rope_scaling_dict,
        seq_len=kv.max_len,
    )
    cos, sin = rope_cos_sin(positions, inv_freq, scale=att_scale)

    Smax = kv.max_len
    sj = jnp.arange(Smax)
    self_mask = (sj[None, None, :] <= slots[..., None]) & (
        sj[None, None, :] >= cache.start[:, None, None]
    )
    self_mask = self_mask[:, None, None]

    # cross-attention additive mask + per-token row liveness, HF
    # _prepare_cross_attention_mask semantics: dead rows' -inf collapses
    # to all-zero (uniform attention) and their MLP branch is zeroed
    if cache.ck is not None:
        N = cache.ck.shape[2]
        if cross_mask is not None:
            live = jnp.any(cross_mask, axis=-1).astype(jnp.float32)  # [B, T]
            amask = jnp.where(cross_mask, 0.0, -1e30) * live[..., None]
        elif mode == "decode" and cache.cross_amask is not None:
            live = jnp.broadcast_to(cache.cross_live[:, None], (B, T))
            amask = jnp.broadcast_to(
                cache.cross_amask[:, None, :], (B, T, N)
            )
        else:
            live = jnp.ones((B, T), jnp.float32)
            amask = jnp.zeros((B, T, N), jnp.float32)
        amask5 = amask[:, None, None]  # [B, 1, 1, T, N]
    else:
        live = amask5 = amask = None

    def self_body(carry, p):
        hidden, c, idx = carry
        x = rms_norm(hidden, p["attn_norm"], eps)
        q = linear(x, p["wq"], None, compute_dtype).reshape(B, T, Hq, D)
        k = linear(x, p["wk"], None, compute_dtype).reshape(B, T, Hkv, D)
        v = linear(x, p["wv"], None, compute_dtype).reshape(B, T, Hkv, D)
        q, k = apply_rotary_emb(q, k, cos, sin, False)
        c = kvcache.update_layer(c, idx, k, v)
        k_att, v_att = kvcache.read_layer(c, idx, compute_dtype)
        attn = attention(q, k_att, v_att, self_mask)
        hidden = hidden + linear(
            attn.reshape(B, T, Hq * D), p["wo"], None, compute_dtype
        )
        x = rms_norm(hidden, p["mlp_norm"], eps)
        gate = linear(x, p["w_gate"], None, compute_dtype)
        up = linear(x, p["w_up"], None, compute_dtype)
        hidden = hidden + linear(
            jax.nn.silu(gate) * up, p["w_down"], None, compute_dtype
        )
        return (hidden, c, idx + 1), None

    def cross_body(hidden, s):
        cp = params["cross"]
        x = rms_norm(hidden, _slice(cp["attn_norm"], s), eps)
        q = linear(x, _slice(cp["wq"], s), None, compute_dtype).reshape(B, T, Hq, D)
        q = rms_norm(q, _slice(cp["q_norm"], s), eps)
        attn = attention(q, cache.ck[s].astype(compute_dtype),
                         cache.cv[s].astype(compute_dtype), amask5)
        out = linear(attn.reshape(B, T, Hq * D), _slice(cp["wo"], s), None,
                     compute_dtype)
        g_attn = jnp.tanh(cp["attn_gate"][s].astype(jnp.float32)).astype(compute_dtype)
        hidden = hidden + g_attn * out

        x = rms_norm(hidden, _slice(cp["mlp_norm"], s), eps)
        gate = linear(x, _slice(cp["w_gate"], s), None, compute_dtype)
        up = linear(x, _slice(cp["w_up"], s), None, compute_dtype)
        mlp = linear(jax.nn.silu(gate) * up, _slice(cp["w_down"], s), None,
                     compute_dtype)
        g_mlp = jnp.tanh(cp["mlp_gate"][s].astype(jnp.float32)).astype(compute_dtype)
        return hidden + g_mlp * mlp * live[..., None].astype(compute_dtype)

    sizes = _segments(config)
    off = 0
    idx = jnp.asarray(0, jnp.int32)
    for si, size in enumerate(sizes):
        if size:
            # QTensor is a pytree node, so the map slices data/scales too
            seg = jax.tree.map(lambda a: a[off:off + size], params["layers"])
            (h, kv, idx), _ = jax.lax.scan(self_body, (h, kv, idx), seg)
            off += size
        if si < len(sizes) - 1 and cache.ck is not None:
            h = cross_body(h, si)

    if last_logits_only:
        h = h[:, -1:]
    logits = llama.lm_head_logits(config, params, h, compute_dtype)

    if fresh:
        return logits, None
    kv = kvcache.advance(kv, T)
    cache = dataclasses.replace(cache, kv=kv)
    if cache.ck is not None and mode == "prefill":
        # generated tokens inherit the last prompt token's cross row
        # (HF extends the final cross_attention_mask column)
        cache = dataclasses.replace(
            cache, cross_amask=amask[:, -1], cross_live=live[:, -1]
        )
    return logits, cache


def multimodal_prefill(
    config: ModelConfig,
    params: Params,
    input_ids,  # [B, T]
    cross_states: jax.Array,  # [B, N, H] projected vision features
    cache_len: int,
    cross_mask: Optional[jax.Array] = None,  # [B, T, N] bool
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = True,
):
    """Encode the cross K/V once, then prefill. Returns (logits, cache)
    ready for plain decode steps (cross K/V and the last token's cross
    row ride in the cache)."""
    B, T = input_ids.shape
    base = init_cache(config, B, cache_len, dtype=compute_dtype)
    ck, cv = encode_cross_kv(config, params, cross_states, compute_dtype)
    cache = dataclasses.replace(base, ck=ck, cv=cv)
    return forward(
        config, params, jnp.asarray(input_ids), cache, mode="prefill",
        compute_dtype=compute_dtype, last_logits_only=last_logits_only,
        cross_mask=cross_mask,
    )
