"""BERT-family encoder (embeddings / retrieval serving).

Counterpart of the reference's bert support (models/bert.py in
/root/reference, patched into its conversion engine; downstream it backs
the LangChain embeddings path, langchain/embeddings/). Architecture per
HF BertModel: learned word+position+token-type embeddings with LayerNorm,
post-norm encoder blocks (self-attention with biases -> residual+LN ->
gelu intermediate -> residual+LN), optional tanh pooler over [CLS].

Like whisper, this family has its own config and call shape (encoder,
bidirectional mask) so it is NOT in models._FAMILIES; use it directly or
through integrations.langchain's embedding class.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.ops import layer_norm, linear
from bigdl_tpu.quant import QTensor, quantize

Params = dict


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @classmethod
    def from_hf_config(cls, hf: dict[str, Any]) -> "BertConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in hf.items() if k in keys and v is not None})

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def params_from_hf(config: BertConfig, get, qtype: str = "bf16") -> Params:
    """HF BertModel state dict -> stacked param tree; linear weights
    quantized to `qtype` (dense for bf16/fp16)."""
    H = config.hidden_size

    def g(name):
        return np.asarray(get(name), np.float32)

    def maybe_q(w: np.ndarray):
        if qtype in ("bf16", "fp16"):
            return jnp.asarray(w, jnp.bfloat16 if qtype == "bf16" else jnp.float16)
        return quantize(jnp.asarray(w), qtype)

    names = [
        ("wq", "attention.self.query.weight"), ("bq", "attention.self.query.bias"),
        ("wk", "attention.self.key.weight"), ("bk", "attention.self.key.bias"),
        ("wv", "attention.self.value.weight"), ("bv", "attention.self.value.bias"),
        ("wo", "attention.output.dense.weight"), ("bo", "attention.output.dense.bias"),
        ("attn_ln_w", "attention.output.LayerNorm.weight"),
        ("attn_ln_b", "attention.output.LayerNorm.bias"),
        ("w_mid", "intermediate.dense.weight"), ("b_mid", "intermediate.dense.bias"),
        ("w_out", "output.dense.weight"), ("b_out", "output.dense.bias"),
        ("out_ln_w", "output.LayerNorm.weight"), ("out_ln_b", "output.LayerNorm.bias"),
    ]
    stacks: dict[str, list] = {k: [] for k, _ in names}
    for i in range(config.num_hidden_layers):
        p = f"encoder.layer.{i}."
        for key, suffix in names:
            stacks[key].append(g(p + suffix))
    layers = {}
    for key, _ in names:
        arr = np.stack(stacks[key])
        if key.startswith("w"):
            layers[key] = maybe_q(arr)
        else:
            layers[key] = jnp.asarray(arr, jnp.float32)

    params = {
        "word_embed": jnp.asarray(g("embeddings.word_embeddings.weight"),
                                  jnp.float32),
        "pos_embed": jnp.asarray(g("embeddings.position_embeddings.weight"),
                                 jnp.float32),
        "type_embed": jnp.asarray(g("embeddings.token_type_embeddings.weight"),
                                  jnp.float32),
        "embed_ln_w": jnp.asarray(g("embeddings.LayerNorm.weight"), jnp.float32),
        "embed_ln_b": jnp.asarray(g("embeddings.LayerNorm.bias"), jnp.float32),
        "layers": layers,
    }
    try:
        params["pooler_w"] = maybe_q(g("pooler.dense.weight"))
        params["pooler_b"] = jnp.asarray(g("pooler.dense.bias"), jnp.float32)
    except KeyError:
        pass  # sentence-transformer exports often drop the pooler
    return params


def forward(
    config: BertConfig,
    params: Params,
    input_ids: jax.Array,  # [B, T] int32
    attention_mask: Optional[jax.Array] = None,  # [B, T] 1 = real token
    token_type_ids: Optional[jax.Array] = None,
    compute_dtype=jnp.float32,
) -> tuple[jax.Array, Optional[jax.Array]]:
    """Returns (last_hidden [B, T, H], pooled [B, H] | None)."""
    B, T = input_ids.shape
    Hh, D = config.num_attention_heads, config.head_dim
    eps = config.layer_norm_eps
    if attention_mask is None:
        attention_mask = jnp.ones((B, T), jnp.int32)
    if token_type_ids is None:
        token_type_ids = jnp.zeros((B, T), jnp.int32)

    h = (
        params["word_embed"][input_ids]
        + params["pos_embed"][jnp.arange(T)][None]
        + params["type_embed"][token_type_ids]
    ).astype(compute_dtype)
    h = layer_norm(h, params["embed_ln_w"], params["embed_ln_b"], eps)

    # bidirectional mask: attend to every real token
    mask = attention_mask[:, None, None, :].astype(jnp.bool_)  # [B,1,1,T]
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)

    def block(h, p):
        q = linear(h, p["wq"], p["bq"], compute_dtype).reshape(B, T, Hh, D)
        k = linear(h, p["wk"], p["bk"], compute_dtype).reshape(B, T, Hh, D)
        v = linear(h, p["wv"], p["bv"], compute_dtype).reshape(B, T, Hh, D)
        att = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
        att = att / np.sqrt(D) + jnp.where(mask, 0.0, neg)
        att = jax.nn.softmax(att, axis=-1).astype(compute_dtype)
        ctx = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T, Hh * D)
        attn_out = linear(ctx, p["wo"], p["bo"], compute_dtype)
        h = layer_norm(h + attn_out, p["attn_ln_w"], p["attn_ln_b"], eps)

        mid = jax.nn.gelu(
            linear(h, p["w_mid"], p["b_mid"], compute_dtype), approximate=False
        )
        out = linear(mid, p["w_out"], p["b_out"], compute_dtype)
        return layer_norm(h + out, p["out_ln_w"], p["out_ln_b"], eps), None

    h, _ = jax.lax.scan(block, h, params["layers"])

    pooled = None
    if "pooler_w" in params:
        pooled = jnp.tanh(
            linear(h[:, 0], params["pooler_w"], params["pooler_b"],
                   compute_dtype)
        )
    return h, pooled


def mean_pool(last_hidden: jax.Array, attention_mask: jax.Array) -> jax.Array:
    """Masked mean over tokens — the sentence-transformers default."""
    m = attention_mask[..., None].astype(last_hidden.dtype)
    return (last_hidden * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1e-9)


def embed_texts(
    config: BertConfig,
    params: Params,
    tokenizer,
    texts: list[str],
    max_length: int = 256,
    normalize: bool = True,
    return_usage: bool = False,
):
    """[n, H] sentence embeddings (mean-pooled, optionally L2-normalized)
    — the LangChain embeddings entry point. return_usage=True also
    returns the POST-truncation token count (what was actually encoded —
    the serving usage field must not re-tokenize or overreport)."""
    enc = [tokenizer.encode(t)[:max_length] for t in texts]
    T = max(len(e) for e in enc)
    ids = np.zeros((len(enc), T), np.int32)
    mask = np.zeros((len(enc), T), np.int32)
    for i, e in enumerate(enc):
        ids[i, : len(e)] = e
        mask[i, : len(e)] = 1
    h, _ = forward(config, params, jnp.asarray(ids), jnp.asarray(mask))
    emb = mean_pool(h, jnp.asarray(mask))
    if normalize:
        emb = emb / jnp.maximum(
            jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-9
        )
    if return_usage:
        return np.asarray(emb), sum(len(e) for e in enc)
    return np.asarray(emb)
