"""RWKV v4 / v5 families — attention-free recurrent language models.

TPU-native re-design of the reference's patched RWKV forwards
(/root/reference/python/llm/src/ipex_llm/transformers/models/rwkv4.py,
rwkv5.py, backed by the native `xe_linear.rwkv_linear_attention_v4/v5`
and `rwkv_time_shift` SYCL kernels, SURVEY.md §2.1): instead of an eager
per-op kernel sequence, the whole block is one jitted program in which
the FLOP-heavy projections run as batched [B,T] matmuls on the MXU and
only the strictly-sequential WKV recurrence runs in a `lax.scan` over
time — elementwise [B,C] (v4) / [B,H,D,D] (v5) work per step, in
float32 for the exp-based v4 numerics.

The recurrent state replaces the KV cache: `RwkvState` carries the
per-layer time-shift vectors and WKV accumulators and satisfies the same
structural contract as `kvcache.KVCache` (`start` field, `pos` counter),
so `generate.generate_tokens` drives RWKV through the family `init_cache`
hook with no RWKV-specific branches. State size is O(L*C) — independent
of sequence length, RWKV's raison d'être for long contexts.

Left-padding: positions with slot < start[b] zero their ln-ed x (so the
first real token's time-shift reads zeros = the initial state, matching
HF) and mask their state updates in the scan.

Layer params are stacked along a leading L axis and iterated with
`lax.scan`, like every other family (models/llama.py docstring).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import linear
from bigdl_tpu.ops.norms import layer_norm
from bigdl_tpu.quant import QTensor, quantize, quantize_or_dense
from bigdl_tpu.quant.qtypes import resolve_qtype

Params = dict[str, Any]


def _is_v5(config: ModelConfig) -> bool:
    return config.rwkv_head_size is not None


def _dims(config: ModelConfig):
    C = config.hidden_size
    A = config.attention_hidden_size or C
    if _is_v5(config):
        D = config.rwkv_head_size
        H = A // D
    else:
        D, H = A, 1
    return C, A, H, D


# ---------------------------------------------------------------------------
# state ("cache")
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class RwkvState:
    """Recurrent state, pytree-registered (donate/jit/shard-safe).

    v4 wkv: [L, B, 3, C] float32 — (num, den, max) accumulators of the
    numerically-stable WKV form. v5 wkv: [L, B, H, D, D] float32 — the
    per-head outer-product state matrix.
    """

    shift_att: jax.Array  # [L, B, C] f32: x_{t-1} entering time-mix
    shift_ffn: jax.Array  # [L, B, C] f32: x_{t-1} entering channel-mix
    wkv: jax.Array
    pos: jax.Array  # scalar int32: tokens consumed so far
    start: jax.Array  # [B] int32: left-pad offsets


def init_cache(
    config: ModelConfig,
    batch: int,
    cache_len: int = 0,  # unused: state size is sequence-independent
    quantize_kv: bool = False,  # unused: nothing grows with context
    dtype=jnp.float32,
) -> RwkvState:
    L = config.num_hidden_layers
    C, A, H, D = _dims(config)
    if _is_v5(config):
        wkv = jnp.zeros((L, batch, H, D, D), dtype)
    else:
        # (num, den, max): max starts hugely negative so the first real
        # token overwrites it (HF inits max_state to -1e38)
        wkv = jnp.zeros((L, batch, 3, C), dtype)
        wkv = wkv.at[:, :, 2].set(-1e30)
    return RwkvState(
        shift_att=jnp.zeros((L, batch, C), dtype),
        shift_ffn=jnp.zeros((L, batch, C), dtype),
        wkv=wkv,
        pos=jnp.zeros((), jnp.int32),
        start=jnp.zeros((batch,), jnp.int32),
    )


# --- serving-engine adapter (serving/engine.py custom-cache protocol) ---
# RWKV state is sequence-independent: a "slot" is just a batch row of
# each state tensor, so insert copies row 0 of the prefill state into
# the slot row. pos becomes per-row (forward broadcasts either way).

def engine_pool(config: ModelConfig, n_slots: int, max_len: int):
    cache = init_cache(config, n_slots)
    return dataclasses.replace(cache, pos=jnp.zeros((n_slots,), jnp.int32))


def engine_insert(cache, pcache, slot, pad):
    return dataclasses.replace(
        cache,
        shift_att=cache.shift_att.at[:, slot].set(pcache.shift_att[:, 0]),
        shift_ffn=cache.shift_ffn.at[:, slot].set(pcache.shift_ffn[:, 0]),
        wkv=cache.wkv.at[:, slot].set(pcache.wkv[:, 0]),
        pos=cache.pos.at[slot].set(pcache.pos),
        start=cache.start.at[slot].set(pad),
    )


# ---------------------------------------------------------------------------
# init / quantize
# ---------------------------------------------------------------------------

def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> Params:
    """Random init (tests/benchmarks run without checkpoints)."""
    C, A, H, D = _dims(config)
    L, V, I = config.num_hidden_layers, config.vocab_size, config.intermediate_size
    keys = iter(jax.random.split(key, 24))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "ln1_w": jnp.ones((L, C), dtype), "ln1_b": jnp.zeros((L, C), dtype),
        "ln2_w": jnp.ones((L, C), dtype), "ln2_b": jnp.zeros((L, C), dtype),
        "att_mix_k": jnp.full((L, C), 0.5, dtype),
        "att_mix_v": jnp.full((L, C), 0.5, dtype),
        "att_mix_r": jnp.full((L, C), 0.5, dtype),
        "att_decay": w((L, H, D) if _is_v5(config) else (L, A)),
        "att_first": w((L, H, D) if _is_v5(config) else (L, A)),
        "att_k": w((L, A, C)), "att_v": w((L, A, C)), "att_r": w((L, A, C)),
        "att_o": w((L, C, A)),
        "ffn_mix_k": jnp.full((L, C), 0.5, dtype),
        "ffn_mix_r": jnp.full((L, C), 0.5, dtype),
        "ffn_k": w((L, I, C)), "ffn_r": w((L, C, C)), "ffn_v": w((L, C, I)),
    }
    if _is_v5(config):
        layers["att_mix_g"] = jnp.full((L, C), 0.5, dtype)
        layers["att_g"] = w((L, A, C))
        layers["ln_x_w"] = jnp.ones((L, A), dtype)
        layers["ln_x_b"] = jnp.zeros((L, A), dtype)
    return {
        "embed": w((V, C)),
        "ln0_w": jnp.ones((C,), dtype), "ln0_b": jnp.zeros((C,), dtype),
        "layers": layers,
        "final_norm": jnp.ones((C,), dtype),
        "final_norm_b": jnp.zeros((C,), dtype),
        "lm_head": w((V, C)),
    }


_QUANT_TARGETS = ("att_k", "att_v", "att_r", "att_g", "att_o",
                  "ffn_k", "ffn_r", "ffn_v")


def quantize_params(params: Params, qtype: str, lm_head_qtype: Optional[str] = None) -> Params:
    """Quantize the projection weights; time-mix/decay vectors and norms
    stay dense (they are tiny and feed the f32 recurrence)."""
    from bigdl_tpu.quant.qtypes import split_mixed_qtype

    qtype, head_default = split_mixed_qtype(qtype)
    lm_head_qtype = lm_head_qtype or head_default
    spec = resolve_qtype(qtype)
    if spec.is_dense:
        return params
    out = dict(params)
    out["layers"] = dict(params["layers"])
    for name in _QUANT_TARGETS:
        w = params["layers"].get(name)
        if w is None or isinstance(w, QTensor):
            continue
        out["layers"][name] = quantize_or_dense(w, spec.name, name)
    if "lm_head" in params and not isinstance(params["lm_head"], QTensor):
        lm_spec = resolve_qtype(lm_head_qtype) if lm_head_qtype else spec
        if not lm_spec.is_dense:
            out["lm_head"] = quantize_or_dense(
                params["lm_head"], lm_spec.name, "lm_head")
    return out


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _shift(x: jax.Array, prev: jax.Array) -> jax.Array:
    """x_{t-1} along time: [B,T,C] with prev [B,C] filling t=0 (the
    reference's xe_linear.rwkv_time_shift)."""
    return jnp.concatenate([prev[:, None].astype(x.dtype), x[:, :-1]], axis=1)


def _wkv4(k, v, real, st, w, u):
    """v4 scalar WKV recurrence, numerically-stable log-space form
    (matches HF rwkv_linear_attention_cpu; the reference fuses it as
    xe_linear.rwkv_linear_attention_v4).

    k, v: [T, B, A] f32 time-major; real: [T, B, 1] f32 mask;
    st: [B, 3, A] (num, den, max); w = -exp(time_decay), u = time_first.
    Returns (out [T, B, A], new st).
    """

    def step(carry, inp):
        num, den, mx = carry
        kt, vt, m = inp
        ww = u + kt
        q = jnp.maximum(mx, ww)
        e1 = jnp.exp(mx - q)
        e2 = jnp.exp(ww - q)
        out = (e1 * num + e2 * vt) / (e1 * den + e2)
        ww = mx + w
        q2 = jnp.maximum(ww, kt)
        e1 = jnp.exp(ww - q2)
        e2 = jnp.exp(kt - q2)
        num = jnp.where(m > 0, e1 * num + e2 * vt, num)
        den = jnp.where(m > 0, e1 * den + e2, den)
        mx = jnp.where(m > 0, q2, mx)
        return (num, den, mx), out

    carry = (st[:, 0], st[:, 1], st[:, 2])
    (num, den, mx), out = jax.lax.scan(step, carry, (k, v, real))
    return out, jnp.stack([num, den, mx], axis=1)


def _wkv5(r, k, v, real, S, w, u):
    """v5 multi-head matrix-state linear attention (Eagle; the reference
    fuses it as xe_linear.rwkv_linear_attention_v5).

    r, k, v: [T, B, H, D] f32 time-major; real: [T, B, 1, 1] f32;
    S: [B, H, D, D]; w = exp(-exp(decay)) [H, D], u = time_first [H, D]
    (both indexed by the k-dim of the state: out_t = r_t·(u⊙k_t v_tᵀ + S),
    S ← k_t v_tᵀ + w⊙S).
    Returns (out [T, B, H, D], new S).
    """
    wk = w[None, :, :, None]  # decay the k rows of the state
    uk = u[None, :, :, None]

    def step(S, inp):
        rt, kt, vt, m = inp
        at = kt[..., :, None] * vt[..., None, :]  # [B, H, D, D]
        out = jnp.einsum("bhk,bhkv->bhv", rt, uk * at + S)
        S = jnp.where(m[..., None] > 0, at + wk * S, S)
        return S, out

    S, out = jax.lax.scan(step, S, (r, k, v, real))
    return out, S


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cache: Optional[RwkvState],
    mode: str = "prefill",  # static: labels the jit specialization only
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = False,
) -> tuple[jax.Array, Optional[RwkvState]]:
    """Returns (logits [B, T, V] float32, advanced state).

    cache=None runs a stateless scoring pass (fresh zero state, no state
    out) — the training/perplexity path.
    """
    assert mode in ("prefill", "decode")
    B, T = tokens.shape
    C, A, H, D = _dims(config)
    eps = config.rms_norm_eps
    v5 = _is_v5(config)

    state = cache if cache is not None else init_cache(config, B)
    # pos may be scalar (generate path) or [B] (serving engine slots)
    pos_col = state.pos[:, None] if state.pos.ndim == 1 else state.pos[None, None]
    slots = pos_col + jnp.arange(T)[None, :]  # [B|1, T] global positions
    # start is always [B], so >= broadcasts to [B, T] either way
    real = (slots >= state.start[:, None]).astype(jnp.float32)  # [B,T]
    maskf = real[..., None]  # [B, T, 1]
    real_tm = jnp.transpose(real, (1, 0))[..., None]  # [T, B, 1]

    from bigdl_tpu.embedding import embed_lookup

    h = embed_lookup(params["embed"], tokens, compute_dtype)
    h = layer_norm(h, params["ln0_w"], params["ln0_b"], eps)

    def body(hidden, xs):
        p, st = xs

        # ---- time mix ----
        x = layer_norm(hidden, p["ln1_w"], p["ln1_b"], eps)
        x = x * maskf.astype(x.dtype)  # zeroed pads = HF zero initial shift
        xprev = _shift(x, st["shift_att"])

        def mixed(name):
            m = p[name].astype(x.dtype)
            return x * m + xprev * (1 - m)

        kx = linear(mixed("att_mix_k"), p["att_k"], None, compute_dtype)
        vx = linear(mixed("att_mix_v"), p["att_v"], None, compute_dtype)
        rx = linear(mixed("att_mix_r"), p["att_r"], None, compute_dtype)

        k_tm = jnp.transpose(kx.astype(jnp.float32), (1, 0, 2))
        v_tm = jnp.transpose(vx.astype(jnp.float32), (1, 0, 2))

        if v5:
            w = jnp.exp(-jnp.exp(p["att_decay"].astype(jnp.float32)))
            u = p["att_first"].astype(jnp.float32)
            gx = linear(mixed("att_mix_g"), p["att_g"], None, compute_dtype)
            r_tm = jnp.transpose(rx.astype(jnp.float32), (1, 0, 2))
            out_tm, S = _wkv5(
                r_tm.reshape(T, B, H, D),
                k_tm.reshape(T, B, H, D),
                v_tm.reshape(T, B, H, D),
                real_tm[..., None],
                st["wkv"], w, u,
            )
            out = jnp.transpose(out_tm, (1, 0, 2, 3)).reshape(B, T, A)
            # ln_x: GroupNorm over heads, per (b, t)
            g = out.reshape(B, T, H, D)
            mu = jnp.mean(g, axis=-1, keepdims=True)
            var = jnp.var(g, axis=-1, keepdims=True)
            gn_eps = config.rwkv_group_norm_eps or 1e-5
            g = (g - mu) * jax.lax.rsqrt(var + gn_eps)
            out = (
                g.reshape(B, T, A) * p["ln_x_w"].astype(jnp.float32)
                + p["ln_x_b"].astype(jnp.float32)
            )
            out = out.astype(compute_dtype) * jax.nn.silu(gx)
            new_wkv = S
        else:
            w = -jnp.exp(p["att_decay"].astype(jnp.float32))
            u = p["att_first"].astype(jnp.float32)
            wkv_tm, new_wkv = _wkv4(k_tm, v_tm, real_tm, st["wkv"], w, u)
            wkv = jnp.transpose(wkv_tm, (1, 0, 2))
            out = jax.nn.sigmoid(rx) * wkv.astype(compute_dtype)

        att_out = linear(out, p["att_o"], None, compute_dtype)
        hidden = hidden + att_out * maskf.astype(hidden.dtype)
        new_shift_att = x[:, -1].astype(jnp.float32)

        # ---- channel mix ----
        x = layer_norm(hidden, p["ln2_w"], p["ln2_b"], eps)
        x = x * maskf.astype(x.dtype)
        xprev = _shift(x, st["shift_ffn"])

        def mixed2(name):
            m = p[name].astype(x.dtype)
            return x * m + xprev * (1 - m)

        kf = linear(mixed2("ffn_mix_k"), p["ffn_k"], None, compute_dtype)
        rf = linear(mixed2("ffn_mix_r"), p["ffn_r"], None, compute_dtype)
        kf = jnp.square(jax.nn.relu(kf))
        ffn_out = jax.nn.sigmoid(rf) * linear(kf, p["ffn_v"], None, compute_dtype)
        hidden = hidden + ffn_out * maskf.astype(hidden.dtype)
        new_shift_ffn = x[:, -1].astype(jnp.float32)

        return hidden, {
            "shift_att": new_shift_att,
            "shift_ffn": new_shift_ffn,
            "wkv": new_wkv,
        }

    st_tree = {
        "shift_att": state.shift_att,
        "shift_ffn": state.shift_ffn,
        "wkv": state.wkv,
    }
    h, new_st = jax.lax.scan(body, h, (params["layers"], st_tree))

    if last_logits_only:
        h = h[:, -1:]
    h = layer_norm(h, params["final_norm"], params["final_norm_b"], eps)
    logits = linear(h, params["lm_head"], None, compute_dtype).astype(jnp.float32)

    if cache is None:
        return logits, None
    new_state = RwkvState(
        shift_att=new_st["shift_att"],
        shift_ffn=new_st["shift_ffn"],
        wkv=new_st["wkv"],
        pos=state.pos + T,
        start=state.start,
    )
    return logits, new_state
