"""DeepSeek-V2/V3 and MiniCPM3 — Multi-head Latent Attention (MLA)
decoders with DeepSeek-MoE.

TPU-native counterpart of the reference's minicpm3 support
(/root/reference/python/llm/src/ipex_llm/transformers/models/minicpm3.py,
dispatch at convert.py:1010-1025, 1899 — the same MLA attention DeepSeek
V2/V3 use; HF modeling_deepseek_v2/v3 are the behavioral spec).

MLA caches a per-token LATENT instead of full K/V: c_kv [r] (the
compressed kv, r = kv_lora_rank) plus one shared rope key k_pe [dr].
The decode math here is the ABSORBED formulation — the up-projections
W_uk/W_uv fold into the query/output sides, so attention runs directly
against the latent cache:

    q_eff[h]  = W_uk[h]^T q_nope[h]            # [r] per head
    score     = (q_eff · c_kv[s] + q_pe · k_pe[s]) * scale
    ctx[h]    = Σ_s softmax(score)[s] c_kv[s]  # [r]
    out[h]    = W_uv[h] ctx[h]                 # [dv]

— algebraically identical to expanding K/V per head (the HF formulation)
but the cache stays [S, r + dr] per layer: ~576 floats/token for
DeepSeek-V2 vs ~8k for an equivalent MHA, and decode reads latents once
for all heads. Rope on the pe channels is DeepSeek's pair-interleaved
(complex) convention = our rope_interleaved path.

DeepSeek-MoE: softmax (v2) or sigmoid (v3) router scores,
group-limited expert selection (`group_limited_greedy` max-per-group /
`noaux_tc` top2-sum with e_score_correction_bias), routed_scaling_factor
on the combine weights, ungated shared experts, and the first
`first_k_dense_replace` layers dense — realized as two homogeneous scan
segments (dense-MLP layers, then MoE layers), like mllama's segmented
stack. Expert compute reuses the llama family's dense/ragged dispatch.

MiniCPM3 = MLA + dense MLP + the minicpm residual/embedding/logit
scalings (config builder _hf_minicpm3).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.kvcache import _scatter_rows
from bigdl_tpu.models import llama
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import linear, rms_norm
from bigdl_tpu.ops.rope import make_inv_freq_scaled, rope_cos_sin

Params = dict[str, Any]

_NEG_INF = -1e30


def _dims(config: ModelConfig):
    H = config.num_attention_heads
    dn = config.qk_nope_head_dim or 128
    dr = config.qk_rope_head_dim or 64
    dv = config.v_head_dim or 128
    r = config.kv_lora_rank or 512
    return H, dn, dr, dv, r


def mla_softmax_scale(config: ModelConfig) -> float:
    """(dn+dr)^-0.5, times the yarn temperature mscale^2 when the checkpoint
    ships `rope_scaling.mscale_all_dim` (all real DeepSeek-V2/V3 and MiniCPM3
    configs do). Official DeepSeek modeling and HF DeepseekV3Attention
    (modeling_deepseek_v3.py:373-377, transformers 4.57) fold
    yarn_get_mscale(factor, mscale_all_dim)^2 into the softmax scale; the
    rope-level attention_factor on cos/sin is the mscale/mscale_all_dim
    ratio (1.0 for these checkpoints), so without this term the attention
    temperature would be dropped entirely (~1.6-1.9x under-scaled scores).
    Note transformers 4.57's *integrated* DeepseekV2Attention omits the
    term — a known fidelity gap vs the official remote code; we follow the
    official checkpoints (and HF V3)."""
    from bigdl_tpu.ops.rope import get_mscale

    _, dn, dr, _, _ = _dims(config)
    scale = (dn + dr) ** -0.5
    rs = config.rope_scaling_dict
    if rs and rs.get("mscale_all_dim"):
        m = get_mscale(rs.get("factor", 1.0), rs["mscale_all_dim"])
        scale = scale * m * m
    return scale


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MLACache:
    """Latent KV cache: compressed kv + shared rope key per token."""

    ckv: jax.Array  # [L, B, S, r]
    kpe: jax.Array  # [L, B, S, dr]
    pos: jax.Array  # scalar or [B]
    start: jax.Array  # [B]

    @property
    def max_len(self) -> int:
        return self.ckv.shape[2]

    def next_positions(self, t: int) -> jax.Array:
        step = jnp.arange(t, dtype=jnp.int32)[None, :]
        pos = self.pos[:, None] if self.pos.ndim == 1 else self.pos
        return jnp.maximum(pos + step - self.start[:, None], 0)


def init_cache(
    config: ModelConfig,
    batch: int,
    cache_len: int,
    quantize_kv: bool = False,  # latent is already ~14x smaller than MHA KV
    dtype=jnp.bfloat16,
) -> MLACache:
    _, _, dr, _, r = _dims(config)
    L = config.num_hidden_layers
    return MLACache(
        ckv=jnp.zeros((L, batch, cache_len, r), dtype),
        kpe=jnp.zeros((L, batch, cache_len, dr), dtype),
        pos=jnp.zeros((), jnp.int32),
        start=jnp.zeros((batch,), jnp.int32),
    )


# the serving engine's generic dataclass insert/pool path supports this
# family's cache (flat [L, B, S, ...] array fields + real pos/start
# fields) — see serving/engine.py; rwkv/yuan/mllama caches need
# dedicated handling and must NOT set this
SERVABLE_CACHE = True


def _layer_is_moe(config: ModelConfig, idx: int) -> bool:
    return config.is_moe and idx >= config.first_k_dense_replace


def num_dense_layers(config: ModelConfig) -> int:
    if not config.is_moe:
        return config.num_hidden_layers
    return min(config.first_k_dense_replace, config.num_hidden_layers)


def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
) -> Params:
    """Random init (tests/benchmarks run without checkpoints)."""
    H, dn, dr, dv, r = _dims(config)
    hid = config.hidden_size
    V, I = config.vocab_size, config.intermediate_size
    rq = config.q_lora_rank
    keys = iter(jax.random.split(key, 48))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    def attn_block(n):
        out = {
            "attn_norm": jnp.ones((n, hid), dtype),
            "mlp_norm": jnp.ones((n, hid), dtype),
            "w_dkv": w((n, r + dr, hid)),
            "kv_norm": jnp.ones((n, r), dtype),
            "w_uk": w((n, H, dn, r)),
            "w_uv": w((n, H, dv, r)),
            "wo": w((n, hid, H * dv)),
        }
        if rq:
            out["w_dq"] = w((n, rq, hid))
            out["q_norm"] = jnp.ones((n, rq), dtype)
            out["w_uq"] = w((n, H * (dn + dr), rq))
        else:
            out["wq"] = w((n, H * (dn + dr), hid))
        return out

    K = num_dense_layers(config)
    layers = attn_block(K)
    layers["w_gate"] = w((K, I, hid))
    layers["w_up"] = w((K, I, hid))
    layers["w_down"] = w((K, hid, I))

    params: Params = {
        "embed": w((V, hid)),
        "layers": layers,
        "final_norm": jnp.ones((hid,), dtype),
    }
    M = config.num_hidden_layers - K
    if M:
        E = config.num_experts
        Im = config.moe_intermediate_size or I
        moe = attn_block(M)
        moe["router"] = w((M, E, hid))
        if (config.topk_method or "") == "noaux_tc":
            moe["e_bias"] = jnp.zeros((M, E), jnp.float32)
        moe["w_gate_e"] = w((M, E, Im, hid))
        moe["w_up_e"] = w((M, E, Im, hid))
        moe["w_down_e"] = w((M, E, hid, Im))
        if config.n_shared_experts:
            S = config.n_shared_experts * Im
            moe["w_gate_s"] = w((M, S, hid))
            moe["w_up_s"] = w((M, S, hid))
            moe["w_down_s"] = w((M, hid, S))
        params["moe_layers"] = moe
    if not config.tie_word_embeddings:
        params["lm_head"] = w((V, hid))
    return params


_QUANT_TARGETS = ("wq", "w_dq", "w_uq", "w_dkv", "w_uk", "w_uv", "wo",
                  "w_gate", "w_up", "w_down",
                  "w_gate_e", "w_up_e", "w_down_e",
                  "w_gate_s", "w_up_s", "w_down_s")

# per-head absorbed factors that must stay dense under quantization —
# the single source of truth for BOTH the random-init path below and
# the checkpoint path (convert/hf.py leaves them out of its
# _QUANT_TARGETS include-list for the same reason)
MLA_DENSE_FACTORS = ("w_uk", "w_uv")


def quantize_params(params: Params, qtype: str, lm_head_qtype: Optional[str] = None) -> Params:
    from bigdl_tpu.quant import QTensor, quantize, quantize_or_dense
    from bigdl_tpu.quant.qtypes import resolve_qtype, split_mixed_qtype

    qtype, head_default = split_mixed_qtype(qtype)
    lm_head_qtype = lm_head_qtype or head_default
    spec = resolve_qtype(qtype)
    if spec.is_dense:
        return params
    out = dict(params)
    for group in ("layers", "moe_layers"):
        if group not in params:
            continue
        g = dict(params[group])
        for name in _QUANT_TARGETS:
            wv = g.get(name)
            if wv is None or isinstance(wv, QTensor):
                continue
            if name in MLA_DENSE_FACTORS:
                continue  # 4-D per-head factors stay dense (tiny, f32 math)
            g[name] = quantize_or_dense(wv, spec.name, name)
        out[group] = g
    if "lm_head" in params and not isinstance(params["lm_head"], QTensor):
        lm_spec = resolve_qtype(lm_head_qtype) if lm_head_qtype else spec
        if not lm_spec.is_dense:
            out["lm_head"] = quantize_or_dense(
                params["lm_head"], lm_spec.name, "lm_head")
    return out


def _router(config: ModelConfig, xc, p):
    """DeepSeek routing: (topv [N,k] f32, topi [N,k] i32) over flattened
    tokens. Mirrors DeepseekV2MoEGate / DeepseekV3TopkRouter exactly."""
    E, k = config.num_experts, config.num_experts_per_tok
    logits = jnp.einsum(
        "nh,eh->ne", xc.astype(jnp.float32),
        p["router"].astype(jnp.float32),
    )
    if config.scoring_func == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    else:
        scores = jax.nn.softmax(logits, axis=-1)

    method = config.topk_method or "greedy"
    if method == "greedy":
        topv, topi = jax.lax.top_k(scores, k)
    else:
        G = config.n_group
        per = E // G
        grouped = scores.reshape(-1, G, per)
        if method == "noaux_tc":
            biased = grouped + p["e_bias"].reshape(G, per)[None]
            group_scores = jnp.sum(jax.lax.top_k(biased, 2)[0], axis=-1)
            choice = biased.reshape(-1, E)
        else:  # group_limited_greedy
            group_scores = jnp.max(grouped, axis=-1)
            choice = scores
        gsel = jax.lax.top_k(group_scores, config.topk_group)[1]
        gmask = jnp.zeros((scores.shape[0], G), jnp.float32)
        gmask = gmask.at[jnp.arange(scores.shape[0])[:, None], gsel].set(1.0)
        emask = jnp.repeat(gmask, per, axis=-1)
        masked = jnp.where(emask > 0, choice.reshape(-1, E), 0.0)
        _, topi = jax.lax.top_k(masked, k)
        # weights come from the UNBIASED scores (v3: bias selects only)
        topv = jnp.take_along_axis(scores, topi, axis=-1)
    # norm_topk_prob: only the v3 router honors it (HF DeepseekV2MoEGate
    # ignores the flag entirely — our oracle; the official v2 remote code
    # normalizes INSTEAD of scaling, a known upstream divergence)
    if config.norm_topk_prob and method == "noaux_tc":
        topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-20)
    return topv * config.routed_scaling_factor, topi


def _moe_mlp(config: ModelConfig, x, p, compute_dtype):
    """Routed experts (llama's dense/ragged dispatch over our router) +
    ungated shared experts (DeepseekV2MoE.forward)."""
    B, T, hid = x.shape
    xc = x.astype(compute_dtype)
    topv, topi = _router(config, xc.reshape(-1, hid), p)
    topv = topv.reshape(B, T, -1)
    topi = topi.reshape(B, T, -1)

    if llama.resolve_moe_dispatch(config) == "ragged":
        rcfg = config
        if (config.topk_method or "greedy") != "greedy" and config.n_group:
            # group-limited routing concentrates every token's k experts
            # into topk_group of n_group groups, so per-expert load can
            # exceed the uniform-load capacity by G/topk_group — scale
            # the capacity factor accordingly or hot experts silently
            # drop tokens (GShard overflow) where HF computes the full sum
            rcfg = dataclasses.replace(
                config,
                moe_capacity_factor=config.moe_capacity_factor
                * config.n_group / max(config.topk_group or 1, 1),
            )
        out = llama._moe_dispatch_ragged(rcfg, xc, p, compute_dtype, topv, topi)
    else:
        out = llama._moe_dispatch_dense(config, xc, p, compute_dtype, topv, topi)

    if config.n_shared_experts:
        g = linear(xc, p["w_gate_s"], None, compute_dtype)
        u = linear(xc, p["w_up_s"], None, compute_dtype)
        out = out + linear(jax.nn.silu(g) * u, p["w_down_s"], None, compute_dtype)
    return out


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cache: Optional[MLACache],
    mode: str = "prefill",
    compute_dtype=jnp.bfloat16,
    last_logits_only: bool = False,
) -> tuple[jax.Array, Optional[MLACache]]:
    assert mode in ("prefill", "decode")
    B, T = tokens.shape
    H, dn, dr, dv, r = _dims(config)
    eps = config.rms_norm_eps
    scale = mla_softmax_scale(config)

    fresh = cache is None
    if fresh:
        cache = init_cache(config, B, T, dtype=jnp.float32)

    pos_col = cache.pos[:, None] if cache.pos.ndim == 1 else cache.pos
    slots = pos_col + jnp.arange(T)[None, :]
    positions = cache.next_positions(T)

    h = llama.embed_tokens(config, params, tokens, compute_dtype)

    inv_freq, att_scale = make_inv_freq_scaled(
        dr, config.rope_theta, config.rope_scaling_dict,
        seq_len=cache.max_len,
    )
    cos, sin = rope_cos_sin(positions, inv_freq, interleaved=True,
                            scale=att_scale)

    S = cache.max_len
    sj = jnp.arange(S)
    mask = (sj[None, None, :] <= slots[..., None]) & (
        sj[None, None, :] >= cache.start[:, None, None]
    )  # [B, T, S]
    mask = mask[:, None]  # [B, 1, T, S]

    per_row = cache.pos.ndim == 1

    def attn(x, p, ckv_l, kpe_l):
        """MLA with absorbed up-projections over the latent cache.
        Returns (attn_out [B,T,hid], new ckv_l, new kpe_l)."""
        from bigdl_tpu.ops.rope import apply_rotary_emb

        if "w_dq" in p:
            qa = linear(x, p["w_dq"], None, compute_dtype)
            q = linear(rms_norm(qa, p["q_norm"], eps), p["w_uq"], None,
                       compute_dtype)
        else:
            q = linear(x, p["wq"], None, compute_dtype)
        q = q.reshape(B, T, H, dn + dr)
        q_nope, q_pe = q[..., :dn], q[..., dn:]

        ckv_pe = linear(x, p["w_dkv"], None, compute_dtype)  # [B,T,r+dr]
        ckv = rms_norm(ckv_pe[..., :r], p["kv_norm"], eps)
        kpe = ckv_pe[..., None, r:]  # [B,T,1,dr] single shared rope head

        q_pe, kpe = apply_rotary_emb(q_pe, kpe, cos, sin, True)
        kpe = kpe[..., 0, :]  # [B,T,dr]

        # write latents into the cache at this layer's rows
        if per_row:
            ckv_l = _scatter_rows(ckv_l[None], jnp.zeros((), jnp.int32),
                                  cache.pos, ckv)[0]
            kpe_l = _scatter_rows(kpe_l[None], jnp.zeros((), jnp.int32),
                                  cache.pos, kpe)[0]
        else:
            ckv_l = jax.lax.dynamic_update_slice(
                ckv_l, ckv.astype(ckv_l.dtype), (0, cache.pos, 0)
            )
            kpe_l = jax.lax.dynamic_update_slice(
                kpe_l, kpe.astype(kpe_l.dtype), (0, cache.pos, 0)
            )

        CKV = ckv_l.astype(compute_dtype)  # [B,S,r]
        KPE = kpe_l.astype(compute_dtype)  # [B,S,dr]

        # absorbed scores: q_eff = W_uk^T q_nope, dotted with the latent
        q_eff = jnp.einsum("bthd,hdr->bthr", q_nope,
                           p["w_uk"].astype(compute_dtype))
        s_nope = jnp.einsum("bthr,bsr->bhts", q_eff, CKV,
                            preferred_element_type=jnp.float32)
        s_pe = jnp.einsum("bthd,bsd->bhts", q_pe, KPE,
                          preferred_element_type=jnp.float32)
        scores = (s_nope + s_pe).astype(jnp.float32) * scale
        scores = jnp.where(mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)

        ctx = jnp.einsum("bhts,bsr->bthr", probs.astype(compute_dtype), CKV)
        out = jnp.einsum("bthr,hdr->bthd", ctx,
                         p["w_uv"].astype(compute_dtype))
        return (
            linear(out.reshape(B, T, H * dv), p["wo"], None, compute_dtype),
            ckv_l, kpe_l,
        )

    rs = config.residual_scale

    def make_body(moe: bool):
        def body(hidden, xs):
            p, ckv_l, kpe_l = xs
            x = rms_norm(hidden, p["attn_norm"], eps)
            out, ckv_l, kpe_l = attn(x, p, ckv_l, kpe_l)
            hidden = hidden + (out * rs if rs else out)
            x = rms_norm(hidden, p["mlp_norm"], eps)
            if moe:
                d = _moe_mlp(config, x, p, compute_dtype)
            else:
                g = linear(x, p["w_gate"], None, compute_dtype)
                u = linear(x, p["w_up"], None, compute_dtype)
                d = linear(jax.nn.silu(g) * u, p["w_down"], None, compute_dtype)
            hidden = hidden + (d * rs if rs else d)
            return hidden, (ckv_l, kpe_l)

        return body

    K = num_dense_layers(config)
    new_ckv, new_kpe = [], []
    if K:
        h, (c0, k0) = jax.lax.scan(
            make_body(False), h,
            (params["layers"], cache.ckv[:K], cache.kpe[:K]),
        )
        new_ckv.append(c0)
        new_kpe.append(k0)
    if config.num_hidden_layers - K:
        h, (c1, k1) = jax.lax.scan(
            make_body(True), h,
            (params["moe_layers"], cache.ckv[K:], cache.kpe[K:]),
        )
        new_ckv.append(c1)
        new_kpe.append(k1)

    if last_logits_only:
        h = h[:, -1:]
    logits = llama.lm_head_logits(config, params, h, compute_dtype)

    if fresh:
        return logits, None
    cache = dataclasses.replace(
        cache,
        ckv=jnp.concatenate(new_ckv, axis=0),
        kpe=jnp.concatenate(new_kpe, axis=0),
        pos=cache.pos + T,
    )
    return logits, cache
