"""Whisper encoder-decoder family (speech-to-text).

TPU-native counterpart of the reference's whisper support
(`transformers/models/whisper.py` in /root/reference, which patches HF
WhisperAttention; the WER eval harness lives in
`dev/benchmark/whisper/`). Instead of patching, the whole model is a
pair of pure functions over one param pytree:

- `encode`: conv1 → gelu → conv2(stride 2) → gelu → +learned positions →
  pre-norm bidirectional transformer stack → final layernorm. One
  `lax.scan` over stacked encoder layers (compile time O(1) in depth).
- `forward`: the decoder — causal self-attention with the shared
  `bigdl_tpu.kvcache` slot cache, cross-attention over encoder states
  whose K/V are projected ONCE per utterance (`cross_kv`, the standard
  encoder-decoder cache trick; the reference gets it for free from HF's
  EncoderDecoderCache), pre-norm MLP, tied lm head.

Quantization covers every linear projection (q/k/v/o, cross q/o, fc1/2)
through the same QTensor machinery as the decoder-only zoo; the conv
frontend and layernorms stay dense, mirroring the reference's policy of
quantizing only nn.Linear (convert.py:469-750).

HF weight-name translation lives in `params_from_hf` (layout identical
to transformers WhisperForConditionalGeneration: k_proj carries no bias,
q/v/out do; decoder positions are learned and offset by the cache
position during decode).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache
from bigdl_tpu.kvcache import KVCache
from bigdl_tpu.ops import attention, linear
from bigdl_tpu.ops.norms import layer_norm
from bigdl_tpu.quant import QTensor, quantize
from bigdl_tpu.quant.qtypes import resolve_qtype

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    model_type: str = "whisper"
    vocab_size: int = 51865
    num_mel_bins: int = 80
    hidden_size: int = 384  # d_model
    encoder_layers: int = 4
    decoder_layers: int = 4
    num_heads: int = 6
    ffn_dim: int = 1536
    max_source_positions: int = 1500
    max_target_positions: int = 448
    layer_norm_eps: float = 1e-5
    scale_embedding: bool = False
    activation: str = "gelu"
    decoder_start_token_id: int = 50258
    eos_token_id: int = 50257
    pad_token_id: int = 50257

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def from_hf_config(cls, hf: dict) -> "WhisperConfig":
        # all released whisper sizes use symmetric encoder/decoder heads
        # and ffn; this implementation shares one num_heads/ffn_dim, so
        # reject (rather than silently mistranslate) asymmetric configs
        for enc, dec in (
            ("encoder_attention_heads", "decoder_attention_heads"),
            ("encoder_ffn_dim", "decoder_ffn_dim"),
        ):
            if dec in hf and enc in hf and hf[dec] != hf[enc]:
                raise NotImplementedError(
                    f"asymmetric whisper config: {enc}={hf[enc]} vs "
                    f"{dec}={hf[dec]}"
                )
        return cls(
            # vocab/decoder fields are absent from encoder-only configs
            # (Qwen2AudioEncoderConfig) — default them
            vocab_size=hf.get("vocab_size", 51865),
            num_mel_bins=hf.get("num_mel_bins", 80),
            hidden_size=hf["d_model"],
            encoder_layers=hf["encoder_layers"],
            decoder_layers=hf.get("decoder_layers", 0),
            num_heads=hf["encoder_attention_heads"],
            ffn_dim=hf.get("encoder_ffn_dim", 4 * hf["d_model"]),
            max_source_positions=hf.get("max_source_positions", 1500),
            max_target_positions=hf.get("max_target_positions", 448),
            scale_embedding=hf.get("scale_embedding", False),
            activation=hf.get("activation_function", "gelu"),
            decoder_start_token_id=hf.get("decoder_start_token_id", 50258),
            eos_token_id=hf.get("eos_token_id", 50257),
            pad_token_id=hf.get("pad_token_id", 50257),
        )


def transcribe_waveform(
    config: WhisperConfig,
    params: Params,
    wave,  # [T] float32 @ 16 kHz (numpy)
    prompt_ids: "Optional[list[int]]" = None,
    max_new_tokens: int = 128,
) -> list[int]:
    """Waveform -> token ids over 30-second windows: the ONE transcription
    pipeline (mel slice, per-chunk generate, EOS/pad filtering) shared by
    the serving endpoint (/v1/audio/transcriptions) and the WER harness
    (eval/wer.py), so the metric always scores exactly what serving
    produces."""
    import jax.numpy as jnp

    from bigdl_tpu import audio as A

    prompt = prompt_ids or default_prompt_ids(config)
    ids: list[int] = []
    for off in range(0, max(len(wave), 1), A.N_SAMPLES):
        mel = A.log_mel_spectrogram(
            wave[off:off + A.N_SAMPLES], n_mels=config.num_mel_bins
        )[:, : 2 * config.max_source_positions]
        toks = generate(
            config, params, jnp.asarray(mel[None]),
            jnp.asarray([prompt], jnp.int32), max_new_tokens=max_new_tokens,
        )
        ids.extend(
            int(t) for t in toks[0]
            if t not in (config.eos_token_id, config.pad_token_id)
        )
    return ids


def default_prompt_ids(config: WhisperConfig) -> list[int]:
    """Minimal forced decoder prefix: <|startoftranscript|>. Callers with
    a tokenizer prepend language/task tokens (<|en|><|transcribe|>...)
    the way the HF processor does."""
    return [config.decoder_start_token_id]


def _act(config: WhisperConfig, x: jax.Array) -> jax.Array:
    if config.activation == "gelu":
        return jax.nn.gelu(x, approximate=False)
    return jax.nn.gelu(x, approximate=True)


# ---------------------------------------------------------------------------
# init / HF ingest / quantize
# ---------------------------------------------------------------------------

_ENC_KEYS = ("wq", "wk", "wv", "wo", "fc1", "fc2")
_DEC_KEYS = _ENC_KEYS + ("xwq", "xwk", "xwv", "xwo")


def init_params(config: WhisperConfig, key: jax.Array, dtype=jnp.float32,
                scale: float = 0.02) -> Params:
    """Random init (tests/benchmarks run without checkpoints)."""
    H, F, V = config.hidden_size, config.ffn_dim, config.vocab_size
    keys = iter(jax.random.split(key, 64))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    def enc_layer(L):
        return {
            "ln1_w": jnp.ones((L, H), dtype), "ln1_b": jnp.zeros((L, H), dtype),
            "wq": w((L, H, H)), "bq": jnp.zeros((L, H), dtype),
            "wk": w((L, H, H)),
            "wv": w((L, H, H)), "bv": jnp.zeros((L, H), dtype),
            "wo": w((L, H, H)), "bo": jnp.zeros((L, H), dtype),
            "ln2_w": jnp.ones((L, H), dtype), "ln2_b": jnp.zeros((L, H), dtype),
            "fc1": w((L, F, H)), "b1": jnp.zeros((L, F), dtype),
            "fc2": w((L, H, F)), "b2": jnp.zeros((L, H), dtype),
        }

    Le, Ld = config.encoder_layers, config.decoder_layers
    dec = enc_layer(Ld)
    dec.update({
        "lnx_w": jnp.ones((Ld, H), dtype), "lnx_b": jnp.zeros((Ld, H), dtype),
        "xwq": w((Ld, H, H)), "xbq": jnp.zeros((Ld, H), dtype),
        "xwk": w((Ld, H, H)),
        "xwv": w((Ld, H, H)), "xbv": jnp.zeros((Ld, H), dtype),
        "xwo": w((Ld, H, H)), "xbo": jnp.zeros((Ld, H), dtype),
    })
    return {
        "conv1_w": w((H, config.num_mel_bins, 3)), "conv1_b": jnp.zeros((H,), dtype),
        "conv2_w": w((H, H, 3)), "conv2_b": jnp.zeros((H,), dtype),
        "enc_pos": w((config.max_source_positions, H)),
        "enc": enc_layer(Le),
        "enc_ln_w": jnp.ones((H,), dtype), "enc_ln_b": jnp.zeros((H,), dtype),
        "embed": w((V, H)),
        "dec_pos": w((config.max_target_positions, H)),
        "dec": dec,
        "dec_ln_w": jnp.ones((H,), dtype), "dec_ln_b": jnp.zeros((H,), dtype),
    }


def _attn_names(p, pre, q, d):
    return {
        f"{pre}wq": [p + "q_proj.weight", q],
        f"{pre}bq": [p + "q_proj.bias", d],
        f"{pre}wk": [p + "k_proj.weight", q],  # k_proj: no bias in HF
        f"{pre}wv": [p + "v_proj.weight", q],
        f"{pre}bv": [p + "v_proj.bias", d],
        f"{pre}wo": [p + "out_proj.weight", q],
        f"{pre}bo": [p + "out_proj.bias", d],
    }


def _stack_layers(per: list[dict]) -> dict:
    out = {}
    for k in per[0]:
        vals = [layer[k] for layer in per]
        if isinstance(vals[0], QTensor):
            from bigdl_tpu.quant.qtensor import map_arrays_multi

            out[k] = map_arrays_multi(vals, jnp.stack)
        else:
            out[k] = jnp.stack(vals)
    return out


def encoder_params_from_state_dict(
    config: WhisperConfig, get, prefix: str = "model.encoder.",
    q=None, d=None,
) -> Params:
    """Translate a transformers WhisperEncoder state dict (accessor
    `get(name) -> np.ndarray`, names relative to `prefix`) into the
    encoder subset of this module's param tree, runnable by `encode`.
    Used by `params_from_hf` and by MiniCPM-o's apm tower
    (models/minicpmo.py), whose checkpoint stores a bare WhisperEncoder
    under `apm.`. `q`/`d` transform linear / non-linear weights
    (default: dense float32)."""
    q = q or (lambda arr: jnp.asarray(arr, jnp.float32))
    d = d or (lambda arr: jnp.asarray(arr, jnp.float32))
    per = []
    for i in range(config.encoder_layers):
        p = f"{prefix}layers.{i}."
        m = {
            "ln1_w": [p + "self_attn_layer_norm.weight", d],
            "ln1_b": [p + "self_attn_layer_norm.bias", d],
            **_attn_names(p + "self_attn.", "", q, d),
            "ln2_w": [p + "final_layer_norm.weight", d],
            "ln2_b": [p + "final_layer_norm.bias", d],
            "fc1": [p + "fc1.weight", q], "b1": [p + "fc1.bias", d],
            "fc2": [p + "fc2.weight", q], "b2": [p + "fc2.bias", d],
        }
        per.append({k: fn(get(name)) for k, (name, fn) in m.items()})
    return {
        "conv1_w": d(get(prefix + "conv1.weight")),
        "conv1_b": d(get(prefix + "conv1.bias")),
        "conv2_w": d(get(prefix + "conv2.weight")),
        "conv2_b": d(get(prefix + "conv2.bias")),
        "enc_pos": d(get(prefix + "embed_positions.weight")),
        "enc": _stack_layers(per),
        "enc_ln_w": d(get(prefix + "layer_norm.weight")),
        "enc_ln_b": d(get(prefix + "layer_norm.bias")),
    }


def params_from_hf(config: WhisperConfig, get, qtype: str = "bf16",
                   dtype=jnp.float32) -> Params:
    """Translate a transformers WhisperForConditionalGeneration state dict
    (accessor `get(name) -> np.ndarray`) into our pytree, quantizing the
    linear projections."""
    spec = resolve_qtype(qtype)

    def q(arr):
        if spec.is_dense:
            return jnp.asarray(arr, dtype)
        return quantize(jnp.asarray(arr, jnp.float32), spec.name)

    def d(arr):
        return jnp.asarray(arr, dtype)

    dec_per = []
    for i in range(config.decoder_layers):
        p = f"model.decoder.layers.{i}."
        m = {
            "ln1_w": [p + "self_attn_layer_norm.weight", d],
            "ln1_b": [p + "self_attn_layer_norm.bias", d],
            **_attn_names(p + "self_attn.", "", q, d),
            "ln2_w": [p + "final_layer_norm.weight", d],
            "ln2_b": [p + "final_layer_norm.bias", d],
            "fc1": [p + "fc1.weight", q], "b1": [p + "fc1.bias", d],
            "fc2": [p + "fc2.weight", q], "b2": [p + "fc2.bias", d],
            "lnx_w": [p + "encoder_attn_layer_norm.weight", d],
            "lnx_b": [p + "encoder_attn_layer_norm.bias", d],
            **_attn_names(p + "encoder_attn.", "x", q, d),
        }
        dec_per.append({k: fn(get(name)) for k, (name, fn) in m.items()})

    return {
        **encoder_params_from_state_dict(config, get, "model.encoder.", q, d),
        "embed": d(get("model.decoder.embed_tokens.weight")),
        "dec_pos": d(get("model.decoder.embed_positions.weight")),
        "dec": _stack_layers(dec_per),
        "dec_ln_w": d(get("model.decoder.layer_norm.weight")),
        "dec_ln_b": d(get("model.decoder.layer_norm.bias")),
    }


def quantize_params(params: Params, qtype: str) -> Params:
    """Quantize the linear projections of a dense whisper tree."""
    spec = resolve_qtype(qtype)
    if spec.is_dense:
        return params
    out = dict(params)
    for side, keys in (("enc", _ENC_KEYS), ("dec", _DEC_KEYS)):
        blk = dict(params[side])
        for k in keys:
            if not isinstance(blk[k], QTensor):
                blk[k] = quantize(blk[k], spec.name)
        out[side] = blk
    return out


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _mha(config, x_q, k, v, mask, compute_dtype):
    B, T = x_q.shape[:2]
    Hd, D = config.num_heads, config.head_dim
    return attention(
        x_q.reshape(B, T, Hd, D), k, v, mask
    ).reshape(B, T, Hd * D)


def encode(config: WhisperConfig, params: Params, mel: jax.Array,
           compute_dtype=jnp.float32, pool_before_ln: int = 1) -> jax.Array:
    """mel [B, n_mels, T_audio] → encoder states [B, T_audio//2, H].

    T_audio must be 2 * max_source_positions (whisper's fixed 30 s
    window; shorter audio is zero-padded upstream, as in HF).
    pool_before_ln > 1 applies Qwen2Audio's in-encoder AvgPool1d
    (kernel == stride == pool_before_ln) between the layer stack and the
    final layer_norm (transformers Qwen2AudioEncoder.forward)."""
    H = config.hidden_size
    Hd, D = config.num_heads, config.head_dim
    eps = config.layer_norm_eps
    x = mel.astype(compute_dtype)

    dn = ("NCH", "OIH", "NCH")
    x = jax.lax.conv_general_dilated(
        x, params["conv1_w"].astype(compute_dtype), (1,), [(1, 1)],
        dimension_numbers=dn,
    ) + params["conv1_b"].astype(compute_dtype)[None, :, None]
    x = jax.nn.gelu(x, approximate=False)
    x = jax.lax.conv_general_dilated(
        x, params["conv2_w"].astype(compute_dtype), (2,), [(1, 1)],
        dimension_numbers=dn,
    ) + params["conv2_b"].astype(compute_dtype)[None, :, None]
    x = jax.nn.gelu(x, approximate=False)

    h = x.transpose(0, 2, 1)  # [B, S, H]
    B, S, _ = h.shape
    h = h + params["enc_pos"].astype(compute_dtype)[:S]

    def body(hidden, p):
        x = layer_norm(hidden, p["ln1_w"], p["ln1_b"], eps)
        q = linear(x, p["wq"], p["bq"], compute_dtype)
        k = linear(x, p["wk"], None, compute_dtype).reshape(B, S, Hd, D)
        v = linear(x, p["wv"], p["bv"], compute_dtype).reshape(B, S, Hd, D)
        a = _mha(config, q, k, v, None, compute_dtype)
        hidden = hidden + linear(a, p["wo"], p["bo"], compute_dtype)
        x = layer_norm(hidden, p["ln2_w"], p["ln2_b"], eps)
        x = _act(config, linear(x, p["fc1"], p["b1"], compute_dtype))
        hidden = hidden + linear(x, p["fc2"], p["b2"], compute_dtype)
        return hidden, None

    h, _ = jax.lax.scan(body, h, params["enc"])
    if pool_before_ln > 1:
        S_out = h.shape[1] // pool_before_ln
        h = h[:, : S_out * pool_before_ln].reshape(
            B, S_out, pool_before_ln, H
        ).mean(axis=2)
    return layer_norm(h, params["enc_ln_w"], params["enc_ln_b"], eps)


def cross_kv(config: WhisperConfig, params: Params, enc: jax.Array,
             compute_dtype=jnp.float32) -> tuple[jax.Array, jax.Array]:
    """Project encoder states to per-decoder-layer cross-attention K/V
    ONCE per utterance: [Ld, B, S, Hd, D] each."""
    B, S, _ = enc.shape
    Hd, D = config.num_heads, config.head_dim

    def body(_, p):
        k = linear(enc, p["xwk"], None, compute_dtype).reshape(B, S, Hd, D)
        v = linear(enc, p["xwv"], p["xbv"], compute_dtype).reshape(B, S, Hd, D)
        return None, (k, v)

    _, (xk, xv) = jax.lax.scan(body, None, params["dec"])
    return xk, xv


def forward(
    config: WhisperConfig,
    params: Params,
    tokens: jax.Array,  # [B, T] int32 decoder ids
    cache: Optional[KVCache],
    xk: jax.Array,  # [Ld, B, S, Hd, D] from cross_kv
    xv: jax.Array,
    mode: str = "prefill",
    compute_dtype=jnp.float32,
) -> tuple[jax.Array, Optional[KVCache]]:
    """Decoder step. Returns (logits [B, T, V] f32, advanced cache)."""
    assert mode in ("prefill", "decode")
    B, T = tokens.shape
    Hd, D = config.num_heads, config.head_dim
    eps = config.layer_norm_eps

    if cache is None:
        pos0 = jnp.zeros((), jnp.int32)
    else:
        pos0 = cache.pos
    positions = pos0 + jnp.arange(T)

    h = params["embed"].astype(compute_dtype)[tokens]
    if config.scale_embedding:
        h = h * jnp.asarray(config.hidden_size ** 0.5, compute_dtype)
    h = h + params["dec_pos"].astype(compute_dtype)[positions]

    if cache is None:
        tj = jnp.arange(T)
        mask = (tj[None, :] <= tj[:, None])[None, None, None]  # [1,1,1,T,T]
    else:
        sj = jnp.arange(cache.max_len)
        slots = pos0 + jnp.arange(T)
        mask = (sj[None, :] <= slots[:, None])[None, None, None]

    def body(carry, xs):
        hidden, c, idx = carry
        p, xk_l, xv_l = xs

        x = layer_norm(hidden, p["ln1_w"], p["ln1_b"], eps)
        q = linear(x, p["wq"], p["bq"], compute_dtype)
        k = linear(x, p["wk"], None, compute_dtype).reshape(B, T, Hd, D)
        v = linear(x, p["wv"], p["bv"], compute_dtype).reshape(B, T, Hd, D)
        if c is not None:
            c = kvcache.update_layer(c, idx, k, v)
            k_att, v_att = kvcache.read_layer(c, idx, compute_dtype)
        else:
            k_att, v_att = k, v
        a = _mha(config, q, k_att, v_att, mask, compute_dtype)
        hidden = hidden + linear(a, p["wo"], p["bo"], compute_dtype)

        x = layer_norm(hidden, p["lnx_w"], p["lnx_b"], eps)
        qx = linear(x, p["xwq"], p["xbq"], compute_dtype)
        ax = _mha(config, qx, xk_l, xv_l, None, compute_dtype)
        hidden = hidden + linear(ax, p["xwo"], p["xbo"], compute_dtype)

        x = layer_norm(hidden, p["ln2_w"], p["ln2_b"], eps)
        x = _act(config, linear(x, p["fc1"], p["b1"], compute_dtype))
        hidden = hidden + linear(x, p["fc2"], p["b2"], compute_dtype)
        return (hidden, c, idx + 1), None

    (h, cache, _), _ = jax.lax.scan(
        body, (h, cache, jnp.zeros((), jnp.int32)), (params["dec"], xk, xv)
    )

    h = layer_norm(h, params["dec_ln_w"], params["dec_ln_b"], eps)
    logits = jnp.einsum(
        "bth,vh->btv", h, params["embed"].astype(compute_dtype),
        preferred_element_type=jnp.float32,
    ).astype(jnp.float32)
    if cache is not None:
        cache = kvcache.advance(cache, T)
    return logits, cache


# ---------------------------------------------------------------------------
# generation (greedy transcription loop, one compiled program)
# ---------------------------------------------------------------------------

def generate(
    config: WhisperConfig,
    params: Params,
    mel: jax.Array,  # [B, n_mels, T_audio]
    prompt_ids: jax.Array,  # [B, P] forced decoder prefix
    max_new_tokens: int = 64,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """Greedy seq2seq decode: encode once, prefill the forced prefix,
    then a lax.while_loop emits tokens until EOS or budget (the
    transcription path behind the server's /v1/audio/transcriptions —
    reference serving/fastapi/api_server.py)."""
    run = _generate_jit(config, max_new_tokens, jnp.dtype(compute_dtype))
    return run(params, mel, prompt_ids)


@functools.lru_cache(maxsize=32)
def _generate_jit(config: WhisperConfig, max_new_tokens: int, compute_dtype):
    """Compiled-program cache: generate() is called per HTTP request by
    the transcription endpoint — a closure-level @jax.jit would retrace
    and recompile every call."""

    @jax.jit
    def run(params, mel, prompt_ids):
        enc = encode(config, params, mel, compute_dtype)
        xk, xv = cross_kv(config, params, enc, compute_dtype)
        B, P = prompt_ids.shape
        cache = kvcache.init_cache(
            config.decoder_layers, B, P + max_new_tokens + 1,
            config.num_heads, config.head_dim, dtype=compute_dtype,
        )
        logits, cache = forward(
            config, params, prompt_ids, cache, xk, xv, mode="prefill",
            compute_dtype=compute_dtype,
        )
        first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out = jnp.full((B, max_new_tokens), config.pad_token_id, jnp.int32)
        out = out.at[:, 0].set(first)
        done = first == config.eos_token_id

        def cond(state):
            i, _, _, done, _ = state
            return (i < max_new_tokens) & ~jnp.all(done)

        def step(state):
            i, cur, cache, done, out = state
            logits, cache = forward(
                config, params, cur[:, None], cache, xk, xv, mode="decode",
                compute_dtype=compute_dtype,
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            nxt = jnp.where(done, config.pad_token_id, nxt)
            done = done | (nxt == config.eos_token_id)
            out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
            return (i + 1, nxt, cache, done, out)

        state = (jnp.ones((), jnp.int32), first, cache, done, out)
        return jax.lax.while_loop(cond, step, state)[4]

    return run
