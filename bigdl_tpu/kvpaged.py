"""Paged KV cache: block tables over a shared physical page pool.

The reference reaches paged attention through its vLLM fork
(vllm/xpu/, 3,992 LoC in /root/reference); our engine's dense
[slots, max_len] pool wastes HBM per idle slot and cannot share prompt
prefixes. Here KV lives in pages of `page_size` tokens:

- `k`/`v` [L, n_pages, page_size, Hkv, D] — one physical pool;
- `block_tables` [B, max_pages] int32 map each row's logical page to a
  physical page (unallocated entries may hold anything: reads beyond
  `pos` are masked by attention, and the engine allocates before
  writes);
- writes scatter through the table; reads gather the row's pages back
  into the dense [B, S, Hkv, D] view the attention ops consume (the
  gather moves the same bytes attention reads — a dedicated Pallas
  paged-attention kernel that indexes pages in place is the follow-up).

Pages are allocated on demand and refcounted (`PagePool`), so identical
prompt prefixes share both storage and prefill compute — the serving
engine's radix-tree prefix cache (serving/radix.py) holds one reference
per cached page and matches prompts at any token split point.

The class mirrors the KVCache interface surface the model forward uses
(pos/start/max_len/next_positions + update/read/advance dispatched via
bigdl_tpu.kvcache), so llama.forward runs on either cache unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    k: jax.Array  # [L, n_pages, page_size, Hkv, D] bf16 or fp8_e5m2
    v: jax.Array
    block_tables: jax.Array  # [B, max_pages] int32 physical page ids
    pos: jax.Array  # [B] int32 next logical slot per row
    start: jax.Array  # [B] int32 first valid slot (left padding)
    rope_base: Optional[jax.Array] = None  # [B] (see kvcache.KVCache)
    # fp8 pages: per-vector absmax scales, f32 (3% of the fp8 codes at
    # D=128 — the fp8 page halves KV HBM traffic AND capacity, the same
    # lever as the dense pool's quantize_kv)
    k_scale: Optional[jax.Array] = None  # [L, n_pages, page_size, Hkv]
    v_scale: Optional[jax.Array] = None

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def max_len(self) -> int:  # logical capacity per row
        return self.block_tables.shape[1] * self.page_size

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def next_positions(self, t: int) -> jax.Array:
        step = jnp.arange(t, dtype=jnp.int32)[None, :]
        if self.rope_base is not None:
            return self.rope_base[:, None] + step
        pos = self.pos[:, None]
        return jnp.maximum(pos + step - self.start[:, None], 0)


def init_paged(
    n_layers: int,
    n_pages: int,
    page_size: int,
    n_kv_heads: int,
    head_dim: int,
    batch: int,
    max_pages_per_row: int,
    dtype=jnp.bfloat16,
    quantize_kv: bool = False,
) -> PagedKVCache:
    shape = (n_layers, n_pages, page_size, n_kv_heads, head_dim)
    if quantize_kv:
        k = jnp.zeros(shape, jnp.float8_e5m2)
        v = jnp.zeros(shape, jnp.float8_e5m2)
        ks = jnp.zeros(shape[:-1], jnp.float32)
        vs = jnp.zeros(shape[:-1], jnp.float32)
    else:
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        ks = vs = None
    return PagedKVCache(
        k=k, v=v, k_scale=ks, v_scale=vs,
        block_tables=jnp.zeros((batch, max_pages_per_row), jnp.int32),
        pos=jnp.zeros((batch,), jnp.int32),
        start=jnp.zeros((batch,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# Host-side page accounting (serving/engine.py + serving/radix.py)
# ---------------------------------------------------------------------------


class PagePool:
    """Refcounted free-list accounting for the physical pages of a
    PagedKVCache. Physical page 0 is the reserved scratch sink (idle
    decode slots' masked garbage writes land there) and is never
    allocatable.

    Ownership discipline: every holder of a page carries exactly one
    reference — each slot block-table entry is one hold, and the radix
    prefix cache (serving/radix.py) takes its OWN hold per cached node.
    A page returns to the free list exactly when its count reaches 0,
    so there is no "cached but refcount 0" special case to reconcile at
    release time (the flat prefix cache's `_page_key` membership test)."""

    def __init__(self, n_pages: int):
        self.n_pages = n_pages
        self.free = list(range(1, n_pages))  # page 0 = scratch
        self.ref = [0] * n_pages

    def alloc(self) -> Optional[int]:
        """A free page with its first reference, or None when dry (the
        caller escalates: radix eviction, then preemption)."""
        if not self.free:
            return None
        pg = self.free.pop()
        self.ref[pg] = 1
        return pg

    def incref(self, pg: int) -> None:
        self.ref[pg] += 1

    def decref(self, pg: int) -> int:
        """Drop one hold; a count reaching 0 returns the page to the
        free list. Returns the new count (callers assert-friendly)."""
        n = self.ref[pg] = self.ref[pg] - 1
        if n < 0:  # a double-release corrupts the pool silently later;
            # fail at the exact site instead
            raise AssertionError(f"page {pg} refcount went negative")
        if n == 0:
            self.free.append(pg)
        return n

    @property
    def n_free(self) -> int:
        return len(self.free)


def kv_page_nbytes(cache: PagedKVCache) -> int:
    """Bytes of ONE physical page across every layer (K + V + fp8
    scales) — the unit the unified KV/adapter device budget is
    denominated in (serving/adapters.AdapterPager)."""
    L, _, page, Hkv, D = cache.k.shape
    n = 2 * L * page * Hkv * D * cache.k.dtype.itemsize
    if cache.quantized:
        n += 2 * L * page * Hkv * cache.k_scale.dtype.itemsize
    return n


class AdapterPageStore:
    """Device residency for LoRA adapter weights, page-framed so it
    draws from the SAME :class:`PagePool` as KV.

    One flat bf16 buffer ``buf [n_pages, page_elems]`` where
    ``page_elems`` is the element count whose byte size matches one KV
    page (``kv_page_nbytes``). The store is a typed VIEW of the page
    frame, not a second allocation pool: page ids come from the shared
    PagePool, so every adapter page resident here is one KV page the
    radix cache / slots cannot hold — a single HBM budget, the S-LoRA
    unified-paging model (docs/serving.md §7).

    The store itself does no accounting; ownership (refcounts, LRU,
    eviction order) lives in ``serving/adapters.AdapterPager``."""

    def __init__(self, n_pages: int, page_nbytes: int):
        self.page_elems = max(page_nbytes // 2, 1)  # bf16 elements/page
        self.buf = jnp.zeros((n_pages, self.page_elems), jnp.bfloat16)

    def n_for(self, n_elems: int) -> int:
        """Pages needed to hold ``n_elems`` bf16 elements."""
        return -(-int(n_elems) // self.page_elems)

    def write(self, pages, flat) -> None:
        """Scatter a flat bf16 host/device vector into physical pages
        `pages` (zero-padded to the page frame)."""
        import numpy as np

        n = len(pages) * self.page_elems
        v = np.zeros((n,), np.float32)
        v[: flat.size] = np.asarray(flat, np.float32).ravel()
        self.buf = self.buf.at[jnp.asarray(list(pages), jnp.int32)].set(
            jnp.asarray(v.reshape(len(pages), self.page_elems),
                        jnp.bfloat16)
        )

    def read(self, pages, n_elems: int) -> jax.Array:
        """Gather pages back into the leading ``n_elems`` of the flat
        vector (device-side — no host round trip)."""
        ids = jnp.asarray(list(pages), jnp.int32)
        return self.buf[ids].reshape(-1)[:n_elems]


# ---------------------------------------------------------------------------
# Host-RAM page swap (serving preemption)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class HostKVPages:
    """A preempted request's KV pages parked in host RAM (all layers,
    page-granular). The serving engine swaps a victim out here, releases
    its device pages, and swaps back into freshly allocated (possibly
    different) physical pages on resume — contents are byte-preserved, so
    decode after swap-in is bit-exact with the uninterrupted run. On a
    real TPU runtime `jax.device_get` stages through the runtime's host
    transfer buffers; the arrays below are plain (pageable) numpy — a
    pinned-allocation fast path is a perf follow-up, not a correctness
    one."""

    k: "object"  # np.ndarray [L, n, page, Hkv, D] in the pool dtype
    v: "object"
    k_scale: Optional[object] = None  # [L, n, page, Hkv] when quantized
    v_scale: Optional[object] = None

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def nbytes(self) -> int:
        n = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n


def swap_out_pages(cache: PagedKVCache, pages) -> HostKVPages:
    """Copy the listed physical pages' KV (every layer) to host RAM.
    `pages` is a host-side list/array of physical page ids; the gather +
    device→host transfer is one fused program per distinct page count."""
    import numpy as np

    ids = jnp.asarray(list(pages), jnp.int32)
    k = np.asarray(jax.device_get(cache.k[:, ids]))
    v = np.asarray(jax.device_get(cache.v[:, ids]))
    ks = vs = None
    if cache.quantized:
        ks = np.asarray(jax.device_get(cache.k_scale[:, ids]))
        vs = np.asarray(jax.device_get(cache.v_scale[:, ids]))
    return HostKVPages(k=k, v=v, k_scale=ks, v_scale=vs)


def swap_in_pages(cache: PagedKVCache, k, v, k_scale, v_scale,
                  pages: jax.Array) -> PagedKVCache:
    """Write a host blob's pages back into physical pages `pages` (a [n]
    int32 array; need not be the pages the blob came from). jit-friendly:
    the engine wraps it with donated cache buffers so the scatter happens
    in place; one compiled program per distinct page count."""
    upd = {"k": cache.k.at[:, pages].set(jnp.asarray(k, cache.k.dtype)),
           "v": cache.v.at[:, pages].set(jnp.asarray(v, cache.v.dtype))}
    if cache.quantized:
        upd["k_scale"] = cache.k_scale.at[:, pages].set(
            jnp.asarray(k_scale, cache.k_scale.dtype))
        upd["v_scale"] = cache.v_scale.at[:, pages].set(
            jnp.asarray(v_scale, cache.v_scale.dtype))
    return dataclasses.replace(cache, **upd)


def update_layer(
    cache: PagedKVCache, layer: jax.Array, k_new: jax.Array, v_new: jax.Array
) -> PagedKVCache:
    """Write k_new/v_new [B,T,Hkv,D] at each row's pos through the block
    table. Does NOT advance pos (the model advances once per forward)."""
    B, T = k_new.shape[:2]
    page = cache.page_size
    s = cache.pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B,T]
    pg = s // page
    off = s % page
    phys = jnp.take_along_axis(cache.block_tables, pg, axis=1)  # [B,T]
    upd = {}
    if cache.quantized:
        from bigdl_tpu.kvcache import _quantize_heads

        kq, ks = _quantize_heads(k_new, scale_dtype=jnp.float32)
        vq, vs = _quantize_heads(v_new, scale_dtype=jnp.float32)
        upd["k"] = cache.k.at[layer, phys, off].set(kq)
        upd["v"] = cache.v.at[layer, phys, off].set(vq)
        upd["k_scale"] = cache.k_scale.at[layer, phys, off].set(ks)
        upd["v_scale"] = cache.v_scale.at[layer, phys, off].set(vs)
    else:
        upd["k"] = cache.k.at[layer, phys, off].set(k_new.astype(cache.k.dtype))
        upd["v"] = cache.v.at[layer, phys, off].set(v_new.astype(cache.v.dtype))
    return dataclasses.replace(cache, **upd)


def read_layer(
    cache: PagedKVCache, layer: jax.Array, dtype=jnp.bfloat16
) -> tuple[jax.Array, jax.Array]:
    """Gather one layer's pages into the dense [B, S, Hkv, D] view
    (dequantizing fp8 pages in-graph)."""
    k_l = jax.lax.dynamic_index_in_dim(cache.k, layer, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(cache.v, layer, 0, keepdims=False)
    B, mp = cache.block_tables.shape
    page = cache.page_size
    k = k_l[cache.block_tables]  # [B, max_pages, page, Hkv, D]
    v = v_l[cache.block_tables]
    if cache.quantized:
        ks = jax.lax.dynamic_index_in_dim(
            cache.k_scale, layer, 0, keepdims=False)[cache.block_tables]
        vs = jax.lax.dynamic_index_in_dim(
            cache.v_scale, layer, 0, keepdims=False)[cache.block_tables]
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
    k = k.reshape(B, mp * page, *k.shape[3:])
    v = v.reshape(B, mp * page, *v.shape[3:])
    return k.astype(dtype), v.astype(dtype)
