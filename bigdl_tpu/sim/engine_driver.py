"""Discrete-event driver: the REAL `serving/engine.py` under a
`SimClock` and a roofline cost model (docs/benchmarking.md).

What is real: the scheduler, admission bounds, queue/request deadlines,
preemption + host-RAM swap, the paged prefix cache (full-page and
sub-page sharing), fault injection, finish-reason accounting, /metrics
histograms and the tracer — every host-side code path a production
engine runs. What is fake: **time** (the engine's injectable ``clock=``
reads a `SimClock` that only the event loop advances) and **per-call
latency** (each jitted model call still executes — a tiny CPU model
provides token/cache dynamics — but its simulated duration comes from
`sim/cost.py`, charged by wrappers installed over the engine's jitted
entry points). The result: engine-level TTFT/p99/shed/preemption
numbers with zero devices, byte-identical across runs of the same
seeded trace.

Event loop: time advances only at discrete events — trace arrivals,
modeled phase completions (decode step, prefill chunk, KV copy, swap),
injected ``slow_step`` stalls, and a small host-step epsilon for
engine iterations that dispatch no device work (so queue sweeps and
deadline reaps always make progress).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

import numpy as np

from bigdl_tpu.serving.metrics import Histogram
from bigdl_tpu.sim.clock import SimClock
from bigdl_tpu.sim.cost import CostModel
from bigdl_tpu.sim.traces import Trace, named_trace

REPORT_FORMAT = "bigdl-tpu-sim-report"
REPORT_VERSION = 1


class RecordingHistogram(Histogram):
    """The engine's Histogram plus the raw sample list, so the report
    computes EXACT percentiles while /metrics renders the same
    observations through the same buckets — the fidelity tests compare
    the two views of one stream."""

    def __init__(self, buckets):
        super().__init__(buckets=buckets)
        self.samples: list = []

    def observe(self, x: float) -> None:
        self.samples.append(float(x))
        super().observe(x)


def _summary(samples: list) -> dict:
    """Deterministic percentile summary (nearest-rank on the sorted
    sample list; no interpolation, no float-order sensitivity)."""
    if not samples:
        return {"n": 0}
    s = sorted(samples)
    n = len(s)

    def pct(q: float) -> float:
        return round(s[min(max(int(np.ceil(q * n)) - 1, 0), n - 1)], 6)

    return {
        "n": n, "mean": round(float(np.sum(s)) / n, 6),
        "p50": pct(0.50), "p90": pct(0.90), "p99": pct(0.99),
        "max": round(s[-1], 6),
    }


_MODEL_CACHE: dict = {}


def tiny_model(qtype: str = "sym_int4", seed: int = 7):
    """The CPU token-dynamics model (tiny-llama): shared per process —
    its compiled programs are the dominant sim start-up cost."""
    key = (qtype, seed)
    if key not in _MODEL_CACHE:
        import jax

        from bigdl_tpu import optimize_model
        from bigdl_tpu.api import TpuModel
        from bigdl_tpu.models import llama
        from bigdl_tpu.models.config import PRESETS

        cfg = PRESETS["tiny-llama"]
        params = optimize_model(
            llama.init_params(cfg, jax.random.PRNGKey(seed)), cfg, qtype
        )
        _MODEL_CACHE[key] = TpuModel(cfg, params, qtype)
    return _MODEL_CACHE[key]


def default_cost_model(hbm_gbps: Optional[float] = None,
                       quantize_kv: bool = False,
                       ici_gbps: Optional[float] = None,
                       tp: Optional[int] = None,
                       comm_qtype: Optional[str] = None) -> CostModel:
    """The modeled target: llama2-7b sym_int4 on a v5e-class HBM (the
    BASELINE.json headline pair). `hbm_gbps` is the calibration knob;
    `ici_gbps`/`tp`/`comm_qtype` are its collective-side twins (simserve
    --ici-gbps): tp > 1 prices the per-layer TP all-reduce into every
    step, at fp32 or quantized wire format."""
    from bigdl_tpu.models.config import PRESETS

    kw: dict = {"label": "llama2-7b"}
    if hbm_gbps is not None:
        kw["hbm_gbps"] = float(hbm_gbps)
    if ici_gbps is not None:
        kw["ici_gbps"] = float(ici_gbps)
    if tp is not None:
        kw["tp"] = int(tp)
    if comm_qtype is not None:
        kw["comm_qtype"] = comm_qtype
    return CostModel(config=PRESETS["llama2-7b"], qtype="sym_int4",
                     quantize_kv=quantize_kv, **kw)


@dataclasses.dataclass
class SimConfig:
    """Engine shape for a simulated deployment (tiny-llama scaled:
    max_len 128 is the preset's position ceiling)."""

    n_slots: int = 4
    max_len: int = 128
    paged: bool = True
    page_size: int = 16
    n_pages: Optional[int] = None  # None = full coverage (no pressure)
    max_queue: Optional[int] = None
    queue_deadline_s: Optional[float] = None
    deadline_s: Optional[float] = None
    preemption: bool = True
    # chunked prefill (docs/serving.md §6): None = monolithic
    prefill_chunk_tokens: Optional[int] = None
    # multi-tenant LoRA (serving/adapters.py §7): when the trace's
    # arrivals carry adapter ids, the driver mints one synthetic
    # rank-4 adapter artifact per tenant and serves through a real
    # AdapterRegistry whose budget holds this many adapters (None =
    # unbounded — no eviction churn)
    adapter_budget: Optional[int] = None
    # in-engine speculative decoding (serving/engine.py §spec): the
    # engine runs REAL draft+verify rounds on the tiny model (which
    # must be dense — bf16/fp16 — for the sym_int4 self-draft) while
    # cost.spec_round_s prices each round as draft_k draft steps + one
    # batched verify. Composes with adapter traces (base draft,
    # adapter-applied verify); chunked prefill the engine still refuses.
    speculative: bool = False
    draft_k: int = 4
    seed: int = 0


class SimDriver:
    """One simulation run: a Trace through a fresh engine."""

    def __init__(self, trace: Trace, model=None,
                 sim: Optional[SimConfig] = None,
                 cost: Optional[CostModel] = None,
                 faults: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 host_step_s: float = 5e-5,
                 max_steps: int = 200_000):
        from bigdl_tpu.serving.engine import InferenceEngine

        self.trace = trace
        self.sim = sim or SimConfig()
        self.cost = cost or default_cost_model()
        self.clock = SimClock()
        self.host_step_s = host_step_s
        self.max_steps = max_steps
        s = self.sim
        if model is not None:
            self.model = model
        elif s.speculative:
            # the self-draft needs a dense target (api.self_draft_params
            # re-quantizes to sym_int4); token dynamics stay tiny-llama
            self.model = tiny_model("bf16")
        else:
            self.model = tiny_model()
        self._adapter_dir = None
        self.adapters = self._make_adapters()
        self.engine = InferenceEngine(
            self.model, n_slots=s.n_slots, max_len=s.max_len,
            paged=s.paged, page_size=s.page_size, n_pages=s.n_pages,
            max_queue=s.max_queue, queue_deadline_s=s.queue_deadline_s,
            deadline_s=s.deadline_s, preemption=s.preemption,
            prefill_chunk_tokens=s.prefill_chunk_tokens,
            seed=s.seed, faults=faults, tracer=tracer, clock=self.clock,
            adapters=self.adapters,
            speculative=s.speculative, draft_k=s.draft_k,
        )
        self._install_recorders()
        self._install_cost_wrappers()
        if faults is not None:
            self._wrap_faults(faults)

    # -- multi-tenant adapters (serving/adapters.py §7) ----------------------

    def _make_adapters(self):
        """When the trace's arrivals name adapters, mint one synthetic
        rank-4 LoRA artifact per tenant (seeded, B=0 identity init —
        token dynamics stay those of the tiny model while the engine
        runs the REAL batched-epilogue decode program and the cost
        model prices its extra bytes/FLOPs) and serve through a real
        AdapterRegistry: verify-on-load, LRU, refcounts, and — under
        `SimConfig.adapter_budget` — genuine eviction/reload churn, on
        the same SimClock as everything else."""
        names = sorted({a.adapter for a in self.trace.arrivals
                        if a.adapter})
        if not names:
            return None
        import os
        import tempfile

        import jax

        from bigdl_tpu.serving.adapters import (
            AdapterRegistry, lora_nbytes, save_adapter,
        )
        from bigdl_tpu.train.qlora import init_lora

        self._adapter_dir = tempfile.TemporaryDirectory(
            prefix="bigdl-tpu-sim-adapters-"
        )
        cfg = self.model.config
        nbytes = 0
        for i, name in enumerate(names):
            lora = init_lora(
                cfg, jax.random.PRNGKey(self.sim.seed * 1009 + i),
                rank=4, alpha=8.0, targets=("wq", "wv"),
            )
            nbytes = lora_nbytes(lora)
            save_adapter(
                os.path.join(self._adapter_dir.name, f"{name}.npz"), lora
            )
        budget = (None if self.sim.adapter_budget is None
                  else self.sim.adapter_budget * nbytes)
        return AdapterRegistry(dir=self._adapter_dir.name,
                               budget_bytes=budget, clock=self.clock)

    def _active_adapter_ranks(self) -> list:
        """(rank, targets) per ACTIVE adapter-carrying slot — the
        decode-step epilogue cost's input, priced over each adapter's
        ACTUAL target set (a wq/wv-only adapter must not charge all
        seven projections)."""
        eng = self.engine
        out = []
        for i in np.nonzero(eng.active)[0]:
            e = eng._slot_adapter[int(i)]
            if e is not None:
                out.append((e.rank, e.targets))
        return out

    # -- instrumentation ----------------------------------------------------

    def _install_recorders(self) -> None:
        eng = self.engine
        for name in ("ttft", "itl", "queue_wait", "prefill_seconds",
                     "decode_step_seconds", "resume_wait"):
            h = getattr(eng, name)
            setattr(eng, name, RecordingHistogram(h.buckets))

    def _active_positions(self) -> list:
        """Written tokens per ACTIVE slot — the decode-attention cost's
        per-row context. Paged keeps a host mirror; dense is estimated
        from request progress (cache.pos is donated away mid-step)."""
        eng = self.engine
        out = []
        for i in np.nonzero(eng.active)[0]:
            s = eng._slots[int(i)]
            if eng.paged:
                out.append(int(eng._slot_pos[int(i)]))
            elif s.req is not None:
                out.append(len(s.req.prompt) + len(s.req.out_tokens))
        return out

    def _install_cost_wrappers(self) -> None:
        """Replace each jitted engine entry point with itself + a
        simulated-latency charge. The charge lands INSIDE the engine's
        own t0/t1 clock reads, so decode_step_seconds / prefill_seconds
        / TTFT all measure modeled device time, not host wall time."""
        eng, cost, clock = self.engine, self.cost, self.clock
        page = self.sim.page_size

        decode0 = eng._decode

        def decode(*a, **k):
            rows = self._active_positions()
            ranks = self._active_adapter_ranks()
            out = decode0(*a, **k)
            clock.advance(cost.decode_step_s(
                rows, page, paged=eng.paged, max_len=eng.max_len,
                adapter_ranks=ranks))
            return out

        eng._decode = decode

        prefill0 = eng._prefill

        def prefill(*a, **k):
            out = prefill0(*a, **k)
            chunk = int(a[1].shape[1])
            self._last_prefill_tokens = chunk
            clock.advance(cost.prefill_s(
                chunk, prior_tokens=0,
                adapter_rank=(eng._last_prefill_rank,
                              eng._last_prefill_targets)))
            return out

        eng._prefill = prefill
        self._last_prefill_tokens = 0

        insert0 = eng._insert

        def insert(*a, **k):
            out = insert0(*a, **k)
            clock.advance(cost.kv_copy_s(self._last_prefill_tokens))
            return out

        eng._insert = insert

        paged_prefill0 = eng._paged_prefill

        def paged_prefill(*a, **k):
            out = paged_prefill0(*a, **k)
            chunk = int(a[7].shape[1])  # bucketed tail tokens
            prior = int(np.asarray(a[6])[0])  # prefix-cache coverage
            clock.advance(cost.prefill_s(
                chunk, prior_tokens=prior,
                adapter_rank=(eng._last_prefill_rank,
                              eng._last_prefill_targets)))
            return out

        eng._paged_prefill = paged_prefill

        copy_page0 = eng._copy_page

        def copy_page(*a, **k):
            out = copy_page0(*a, **k)
            clock.advance(cost.kv_copy_s(page))
            return out

        eng._copy_page = copy_page

        # speculative rounds: the engine's real draft+verify program
        # runs on the tiny model; the charge is K draft steps + one
        # batched verify at the modeled config (cost.spec_round_s)
        if getattr(eng, "_spec_decode", None) is not None:
            spec0 = eng._spec_decode

            def spec_decode(k_draft, *a, **kw):
                rows = self._active_positions()
                ranks = self._active_adapter_ranks()
                out = spec0(k_draft, *a, **kw)
                clock.advance(cost.spec_round_s(
                    rows, page, int(k_draft), paged=eng.paged,
                    max_len=eng.max_len, adapter_ranks=ranks))
                return out

            eng._spec_decode = spec_decode

        # preemption swap traffic (round trip charged at swap-in; the
        # swap-out device_get has no jitted hook)
        if getattr(eng, "_swap_in", None) is not None:
            swap_in0 = eng._swap_in

            def swap_in(*a, **k):
                out = swap_in0(*a, **k)
                clock.advance(cost.swap_s(int(a[5].shape[0]) * page))
                return out

            eng._swap_in = swap_in
        if getattr(eng, "_dense_swap_in", None) is not None:
            dswap0 = eng._dense_swap_in

            def dense_swap_in(*a, **k):
                out = dswap0(*a, **k)
                clock.advance(cost.swap_s(int(a[1].shape[1])))
                return out

            eng._dense_swap_in = dense_swap_in

    def _wrap_faults(self, inj) -> None:
        """Compose serving/faults.py with the SimClock: an injected
        slow_step stall advances SIMULATED time by its payload (the
        engine's real sleep is wall time the sim never sees), so chaos
        runs shift TTFT/ITL exactly as a stalled device would."""
        clock = self.clock
        fire0 = inj.fire

        def fire(point: str):
            p = fire0(point)
            if p is not None and point == "slow_step":
                clock.advance(float(p.get("seconds", 0.05)))
            return p

        inj.fire = fire

    # -- the event loop -----------------------------------------------------

    def run(self) -> dict:
        eng = self.engine
        arrivals = self.trace.arrivals
        n = len(arrivals)
        i = 0
        requests = []
        steps = 0
        # (sim-time weight, occupancy, kv utilization) per iteration:
        # means must be TIME-weighted, or the thousands of cheap
        # host-epsilon iterations of a blocked stretch would swamp the
        # few hundred decode steps that carry almost all simulated time
        samples: list = []
        while True:
            while i < n and arrivals[i].t <= self.clock.now:
                requests.append(eng.submit(
                    arrivals[i].prompt,
                    max_new_tokens=arrivals[i].max_new_tokens,
                    adapter=arrivals[i].adapter,
                ))
                i += 1
            t_before = self.clock.now
            busy = eng.step()
            steps += 1
            if self.clock.now <= t_before:
                # pure host iteration (admission blocked, sweeps only):
                # charge the host epsilon so deadline machinery always
                # sees time move and the loop cannot spin at one instant
                self.clock.advance(self.host_step_s)
            samples.append((self.clock.now - t_before,
                            int(eng.active.sum()),
                            float(eng.kv_utilization())))
            if not busy:
                if i < n:
                    self.clock.advance_to(arrivals[i].t)
                    continue
                if eng.idle():
                    break
            if steps >= self.max_steps:
                raise RuntimeError(
                    f"sim exceeded max_steps={self.max_steps} "
                    f"(t={self.clock.now:.3f}s, {i}/{n} arrivals)"
                )
        return self._report(requests, steps, samples)

    # -- reporting ----------------------------------------------------------

    def _report(self, requests: list, steps: int,
                samples: list) -> dict:
        eng = self.engine
        tr = self.trace
        sim_s = self.clock.now
        wsum = sum(w for w, _, _ in samples) or 1.0
        occ_mean = sum(w * o for w, o, _ in samples) / wsum
        kvu_mean = sum(w * u for w, _, u in samples) / wsum
        occ_peak = max((o for _, o, _ in samples), default=0)
        kvu_peak = max((u for _, _, u in samples), default=0.0)
        done = [r for r in requests if r.done]
        completed = [r for r in done if r.finish_reason in ("stop", "length")]
        out_tokens = sum(len(r.out_tokens) for r in requests)
        offered_s = max(tr.duration_s, 1e-9)
        reasons = {k: v for k, v in sorted(eng.finish_reasons.items())}
        n_req = max(len(requests), 1)
        page_leak = 0
        kv_extra: dict = {}
        if eng.paged:
            # refcount-vs-holders reconciliation (slot tables + radix
            # nodes), not a bare ref>0 scan: cached pages legitimately
            # hold the radix's own reference at drain
            page_leak = eng.page_leaks()
            kv_extra = {
                "free_pages_at_drain": len(eng._free_pages),
                "cached_prefix_pages": eng.radix.n_nodes,
                "prefix_hits": eng.prefix_hits,
                "prefix_partial_hits": eng.prefix_partial_hits,
                "prefix_tokens_reused": eng.prefix_tokens_reused,
                "prefix_evictions": eng.prefix_evictions,
            }
        adapter_extra: dict = {}
        if self.adapters is not None:
            # registry churn counters (adapter hit/evict — the
            # scheduler-level cost of multi-tenant adapter traffic,
            # gated on CPU like everything else)
            st = self.adapters.stats()
            pager = getattr(eng, "_pager", None)
            adapter_extra["adapters"] = {
                "n_tenants": len({a.adapter for a in tr.arrivals
                                  if a.adapter}),
                "budget": self.sim.adapter_budget,
                "loads": st["loads"],
                "hits": st["hits"],
                "evictions": st["evictions"],
                "load_failures": st["load_failures"],
                "resident_at_drain": st["resident"],
                # unified HBM paging churn (serving/adapters.AdapterPager):
                # device pages in the SHARED KV pool; 0s when the engine
                # runs dense (no pager)
                "page_ins": pager.page_ins if pager is not None else 0,
                "page_outs": pager.page_outs if pager is not None else 0,
                "pages_resident_at_drain": (
                    pager.pages_resident if pager is not None else 0),
            }
        spec_extra: dict = {}
        if getattr(eng, "speculative", False):
            rounds = eng.spec_rounds
            spec_extra["speculative"] = {
                "draft_k": self.sim.draft_k,
                "rounds": rounds,
                "emitted": eng.spec_emitted,
                # tokens per verify round (1.0 = nothing accepted,
                # draft_k = every draft accepted + the bonus token)
                "tokens_per_round": round(
                    eng.spec_emitted / rounds, 4) if rounds else 0.0,
            }
        s = self.sim
        return {
            "format": REPORT_FORMAT, "version": REPORT_VERSION,
            **adapter_extra,
            **spec_extra,
            "trace": {
                "name": tr.name, "seed": tr.seed, "n_requests": len(tr.arrivals),
                "duration_s": round(tr.duration_s, 6),
                "offered_rps": round(len(tr.arrivals) / offered_s, 3),
                "offered_tokens": tr.offered_tokens(),
            },
            "engine": {
                "n_slots": s.n_slots, "max_len": s.max_len,
                "paged": s.paged, "page_size": s.page_size,
                "n_pages": eng.n_pages if eng.paged else None,
                "max_queue": s.max_queue,
                "queue_deadline_s": s.queue_deadline_s,
                "deadline_s": s.deadline_s,
                "prefill_chunk_tokens": s.prefill_chunk_tokens,
            },
            "cost_model": self.cost.describe(),
            "sim": {"steps": steps, "sim_seconds": round(sim_s, 6)},
            "throughput": {
                "achieved_rps": round(len(completed) / max(sim_s, 1e-9), 3),
                "offered_rps": round(len(tr.arrivals) / offered_s, 3),
                "completed": len(completed),
                "output_tokens": out_tokens,
                "output_tokens_per_s": round(out_tokens / max(sim_s, 1e-9), 2),
            },
            "latency": {
                "ttft_s": _summary(eng.ttft.samples),
                "itl_s": _summary(eng.itl.samples),
                "queue_wait_s": _summary(eng.queue_wait.samples),
                "prefill_s": _summary(eng.prefill_seconds.samples),
                "decode_step_s": _summary(eng.decode_step_seconds.samples),
                "resume_wait_s": _summary(eng.resume_wait.samples),
            },
            "counters": {
                "finish_reasons": reasons,
                "preemptions": eng.preemptions,
                "preemption_resumes": eng.preemption_resumes,
                "requests_shed": eng.requests_shed,
                "request_timeouts": eng.request_timeouts,
                "requests_completed": eng.requests_completed,
                "prefill_chunks": eng.prefill_chunks,
            },
            "rates": {
                "shed_rate": round(eng.requests_shed / n_req, 4),
                "timeout_rate": round(eng.request_timeouts / n_req, 4),
                "preemption_rate": round(eng.preemptions / n_req, 4),
            },
            "kv": {
                "utilization_mean": round(kvu_mean, 4),
                "utilization_peak": round(kvu_peak, 4),
                "page_leak_at_drain": page_leak,
                **kv_extra,
            },
            "occupancy": {
                "mean": round(occ_mean, 3),
                "peak": occ_peak,
            },
        }


# ---------------------------------------------------------------------------
# scenario registry: trace mix + the engine shape that makes it tell its
# story. "overload" pairs ~4x-capacity offered load with a small page
# pool and bounded admission so preemption AND shed AND deadline kills
# all fire — the acceptance workload for every future scheduler PR.
# ---------------------------------------------------------------------------

SCENARIOS: dict = {
    "poisson": SimConfig(),
    "bursty": SimConfig(),
    # bounded pool: the radix cache runs under genuine eviction
    # pressure (leaf-first LRU vs a working set larger than the pool).
    # Chunking stays OFF here — this mix is the TTFT acceptance number
    # and chunked prefill deliberately trades admission latency for
    # decode smoothness (the overload mix's ITL tells that story)
    "prefix-heavy": SimConfig(n_pages=24),
    "overload": SimConfig(
        n_pages=18, max_queue=6, queue_deadline_s=0.75, deadline_s=3.0,
        prefill_chunk_tokens=32,
    ),
    # 4 Zipf-popular tenants over a 2-adapter host-RAM budget: the
    # hot tenants stay resident, the tail churns — loads, hits AND
    # evictions all fire (serving/adapters.py §7)
    "adapter-zipf": SimConfig(adapter_budget=2),
    # real self-draft + verify rounds on a dense tiny model, each round
    # priced as draft_k decode steps + one batched verify
    # (cost.spec_round_s) — the ROADMAP sim-calibration remainder that
    # previously made SimDriver refuse speculative engines
    "speculative": SimConfig(speculative=True, draft_k=4),
    # S-LoRA completion: Zipf adapter traffic THROUGH speculative
    # decoding (base draft, adapter-applied verify) over a page pool
    # tight enough that adapter pages and KV fight for the same budget
    # — acceptance, adapter page churn AND zero-leak drain all gate on
    # this mix (scripts/ci.sh --core). Host-RAM budget covers all 4
    # tenants (host churn is adapter-zipf's story); the pressure here
    # is DEVICE pages: 16 shared pages force holder-free adapter
    # page-outs when concurrent KV demand spikes
    "adapter-spec": SimConfig(adapter_budget=4, speculative=True,
                              draft_k=4, n_pages=16),
}


def run_scenario(name: str, seed: int = 0, model=None,
                 hbm_gbps: Optional[float] = None,
                 sim: Optional[SimConfig] = None,
                 trace: Optional[Trace] = None,
                 faults: Optional[Any] = None,
                 tracer: Optional[Any] = None) -> dict:
    """One named mix end to end: generate (or take) the trace, drive a
    fresh engine, return the report dict (json.dumps(sort_keys=True)
    of it is the banked artifact)."""
    if sim is None:
        if name not in SCENARIOS:
            raise ValueError(
                f"unknown scenario {name!r}; known: {sorted(SCENARIOS)}"
            )
        sim = SCENARIOS[name]
    trace = trace if trace is not None else named_trace(name, seed=seed)
    driver = SimDriver(trace, model=model, sim=sim,
                       cost=default_cost_model(hbm_gbps=hbm_gbps),
                       faults=faults, tracer=tracer)
    return driver.run()


def report_json(report: dict) -> str:
    """The canonical serialized form — sorted keys, no whitespace
    variance, so identical runs are byte-identical."""
    return json.dumps(report, sort_keys=True, separators=(",", ":"))
