"""Per-step latency model for the serving simulator, fed by
`benchmark/roofline.py`'s analytic bytes/FLOPs at the kernels' real
tile shapes (docs/benchmarking.md).

The modeled hardware/model pair is INDEPENDENT of the tiny model that
produces token dynamics on CPU: the engine executes tiny-llama to keep
every cache/scheduler path real, while each jitted call's duration is
priced as if it were `config` (default llama2-7b) at `qtype` on an
HBM with `hbm_gbps` — the calibration knob the next live-TPU window
tunes against measured GB/s (BENCH_NOTES r03 discipline).

Pricing follows the roofline: a phase costs
``max(bytes / HBM_BW, flops / peak)`` plus a fixed per-dispatch host
overhead. Decode is bytes-bound (weight streaming + KV touched ∝ batch
occupancy and positions); prefill cost is ∝ chunk tokens through the
same qmatmul model at M=chunk plus the flash-prefill attention cost at
the kernel's real (block_q, block_k).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from bigdl_tpu.benchmark.roofline import (
    all_reduce_cost, bwd_dw_cost, bwd_dx_cost, decode_attention_cost,
    flash_prefill_cost, lora_epilogue_cost, qmatmul_cost,
)
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant.qtypes import resolve_qtype


@dataclasses.dataclass
class CostModel:
    config: ModelConfig
    qtype: Optional[str] = "sym_int4"  # None = dense bf16 weights
    #: the calibration knob (docs/benchmarking.md): achievable HBM GB/s
    #: of the modeled chip; default is v5e-class. The next live-TPU
    #: window sets this from measured kernel GB/s (bench.py gemv_timed).
    hbm_gbps: float = 819.0
    #: bf16 MXU peak — the compute-bound floor of every phase
    peak_tflops: float = 197.0
    #: host dispatch + engine bookkeeping per jitted call (the sim's
    #: step() host work happens between modeled device calls)
    step_overhead_s: float = 5e-4
    #: host<->HBM link for preemption swap traffic (PCIe/ICI class)
    swap_gbps: float = 32.0
    #: modeled KV page (the engine's real page_size is passed per call;
    #: this is only the default for standalone queries)
    page_size: int = 64
    quantize_kv: bool = False
    label: str = ""
    #: tensor-parallel degree of the MODELED deployment. tp > 1 adds the
    #: per-layer TP all-reduce epilogues (wo + w_down, M x hidden each)
    #: over the ICI ring to every decode step / prefill chunk. The charge
    #: is purely ADDITIVE — compute is deliberately NOT divided by tp, so
    #: this knob prices the communication OVERHEAD of going multi-chip
    #: (decode_step_s rises with tp at fp32; quantized comms claw it
    #: back), not the compute speedup. tp=1 (default) charges nothing and
    #: keeps every banked report byte-identical.
    tp: int = 1
    #: achievable per-chip ICI GB/s — the collective calibration knob
    #: twin of hbm_gbps (benchmark/roofline.py collective cost model);
    #: default is a v5e-class 45 GB/s per link direction
    ici_gbps: float = 45.0
    #: wire format of the TP all-reduce ("none"|"int8"|"fp8_e4m3") —
    #: parallel/qcollectives.py's comm_qtype knob, priced here
    comm_qtype: str = "none"
    #: whether the LoRA epilogue is priced as the fused Pallas writeback
    #: (qmatmul_lora: zero activation HBM round trips) or the XLA einsum
    #: fallback (two round trips — re-read x, round-trip the delta).
    #: True matches the serving engine's dispatch on eligible shapes;
    #: False reproduces the pre-fusion path for before/after comparisons
    #: (docs/benchmarking.md §3 banks the seed-0 pair)
    fused_lora: bool = True
    #: whether the train-step backward is priced at the fused Pallas dx
    #: kernel (ops/pallas/qbackward.py: packed weights re-decoded
    #: per-chunk in VMEM) or the XLA remat path (a full bf16 dequant of
    #: W written to + read back from HBM per projection per step) —
    #: train/qlora.make_train_step's fused_backward knob, priced here so
    #: the supervisor path is sim-gateable like serving
    fused_backward: bool = True

    # -- pieces --------------------------------------------------------------

    def _supported_qtype(self) -> Optional[str]:
        """The matmul-model qtype, or None when the modeled config's
        contractions don't align to the format's scale blocks (tiny
        configs) — then weights price as dense bf16."""
        if self.qtype is None:
            return None
        spec = resolve_qtype(self.qtype)
        blk = spec.superblock or spec.block_size
        cfg = self.config
        for k in (cfg.hidden_size, cfg.q_dim, cfg.intermediate_size):
            if k % blk:
                return None
        return self.qtype

    def linear_cost(self, M: int) -> dict:
        """bytes/flops of every projection of one full forward at M
        rows: L x (merged qkv, o, gate_up, down) + the lm_head."""
        cfg = self.config
        shapes = [
            (cfg.hidden_size, cfg.q_dim + 2 * cfg.kv_dim),  # qkv
            (cfg.q_dim, cfg.hidden_size),                   # o
            (cfg.hidden_size, 2 * cfg.intermediate_size),   # gate_up
            (cfg.intermediate_size, cfg.hidden_size),       # down
        ]
        qt = self._supported_qtype()
        total_b = total_f = 0
        for K, O in shapes:
            if qt is not None:
                c = qmatmul_cost(qt, M, K, O)
                total_b += c["fused_bytes"]
                total_f += c["flops"]
            else:
                total_b += K * O * 2 + M * (K + O) * 2
                total_f += 2 * M * K * O
        total_b *= cfg.num_hidden_layers
        total_f *= cfg.num_hidden_layers
        # lm_head stays bf16 (the stack's convention: output head is
        # not quantized)
        K, O = cfg.hidden_size, cfg.vocab_size
        total_b += K * O * 2 + M * (K + O) * 2
        total_f += 2 * M * K * O
        return {"bytes": total_b, "flops": total_f}

    def _seconds(self, nbytes: float, flops: float) -> float:
        bw = self.hbm_gbps * 1e9
        peak = self.peak_tflops * 1e12
        return max(nbytes / bw, flops / peak)

    def _lora_target_dims(self, targets=None):
        """(in, out) per target of the adapter's target set (None = all
        seven) — the per-layer shapes of its A [r, in] / B [out, r]
        pairs."""
        cfg = self.config
        H, I = cfg.hidden_size, cfg.intermediate_size
        dims = {
            "wq": (H, cfg.q_dim),
            "wk": (H, cfg.kv_dim),
            "wv": (H, cfg.kv_dim),
            "wo": (cfg.q_dim, H),
            "w_gate": (H, I),
            "w_up": (H, I),
            "w_down": (I, H),
        }
        names = dims.keys() if targets is None else targets
        return [dims[t] for t in names if t in dims]

    def lora_cost(self, ranks, M: int = 1, fused=None) -> dict:
        """The multi-tenant LoRA epilogue's extra traffic per forward,
        priced by `roofline.lora_epilogue_cost` per target per layer at
        the dequant-GEMM's real M tiles. `ranks` = one entry per
        adapter-carrying row — a bare rank (priced over all seven
        targets) or a (rank, targets) pair priced over the adapter's
        ACTUAL target set; adapter-less rows cost nothing (their
        zero-padded rows still move with the batch's bucket, but the
        dominant term — distinct adapters' weights — is what's priced).

        ``fused`` (default: the model's `fused_lora` field) switches
        between the fused-writeback pricing (adapter stream only, zero
        activation round trips) and the XLA fallback's two extra
        activation HBM round trips per target — the ISSUE 18 perf delta
        the adapter-zipf before/after banks."""
        if fused is None:
            fused = self.fused_lora
        nbytes = flops = 0
        for r in ranks:
            rank, targets = r if isinstance(r, tuple) else (r, None)
            if not rank:
                continue
            for K, O in self._lora_target_dims(targets):
                c = lora_epilogue_cost(M, K, O, rank, fused=fused)
                nbytes += c["bytes"]
                flops += c["flops"]
        L = self.config.num_hidden_layers
        return {"bytes": nbytes * L, "flops": flops * L}

    def tp_comm_s(self, M: int) -> float:
        """Seconds of per-forward TP collective traffic at M rows: two
        ring all-reduces per layer (the wo and w_down row-parallel
        epilogues parallel/qcollectives.py makes explicit), each over
        [M, hidden] at `comm_qtype`'s wire format, serialized on the
        ICI ring at `ici_gbps`. Zero at tp=1."""
        if self.tp <= 1 or M <= 0:
            return 0.0
        c = all_reduce_cost(M * self.config.hidden_size, self.tp,
                            self.comm_qtype, ici_gbps=self.ici_gbps)
        return 2 * self.config.num_hidden_layers * c["ring_time_s"]

    def kv_token_bytes(self) -> int:
        """HBM bytes one token's K+V occupies across all layers."""
        cfg = self.config
        bpe = 1 if self.quantize_kv else 2
        scale = 4 if self.quantize_kv else 0
        return 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * (
            cfg.head_dim_ * bpe + scale
        )

    # -- phases (what the driver's wrappers charge) --------------------------

    def decode_step_s(self, positions, page: int,
                      paged: bool = True, max_len: int = 0,
                      adapter_ranks=()) -> float:
        """One batched decode step: M=occupancy through every
        projection + the decode-attention KV sweep at the rows' actual
        positions. `adapter_ranks` (one LoRA rank per adapter-carrying
        row) adds the multi-tenant epilogue's weight stream + einsum
        FLOPs (serving/adapters.py)."""
        rows = list(positions)
        if not rows:
            return self.step_overhead_s
        cfg = self.config
        lin = self.linear_cost(len(rows))
        att = decode_attention_cost(
            rows, page, cfg.num_attention_heads, cfg.num_key_value_heads,
            cfg.head_dim_, layers=cfg.num_hidden_layers, paged=paged,
            quantize_kv=self.quantize_kv, max_len=max_len,
        )
        lo = self.lora_cost(adapter_ranks, M=1)
        return self._seconds(lin["bytes"] + att["bytes"] + lo["bytes"],
                             lin["flops"] + att["flops"] + lo["flops"]) \
            + self.tp_comm_s(len(rows)) + self.step_overhead_s

    def spec_round_s(self, positions, page: int, draft_k: int,
                     paged: bool = True, max_len: int = 0,
                     adapter_ranks=()) -> float:
        """One speculative round (serving/engine.py `_spec_decode`):
        `draft_k` sequential per-token draft steps at advancing
        positions, then ONE batched verify forward over each row's
        draft_k+1 candidate tokens through the target. Monotonically
        increasing in draft_k (each extra draft adds a full decode-step
        charge plus a wider verify).

        Approximation (documented in docs/benchmarking.md): the draft
        model is priced at this CostModel's own qtype/config — the
        engine's self-draft shares the target's architecture, and the
        sym_int4 default IS the self-draft's format; a separately-sized
        draft model would need its own CostModel."""
        rows = list(positions)
        if not rows:
            return self.step_overhead_s
        if draft_k < 1:
            raise ValueError(f"draft_k must be >= 1, got {draft_k}")
        cfg = self.config
        total = 0.0
        for i in range(draft_k):
            total += self.decode_step_s(
                [p + i for p in rows], page, paged=paged,
                max_len=max_len, adapter_ranks=adapter_ranks,
            )
        # verify: M = rows * (K+1) candidate tokens through every
        # projection; each candidate's attention sweeps its row's KV at
        # the post-draft depth (the verify writes K drafts first, so
        # every query sees the full speculated context)
        M = len(rows) * (draft_k + 1)
        lin = self.linear_cost(M)
        vrows = [p + draft_k for p in rows for _ in range(draft_k + 1)]
        att = decode_attention_cost(
            vrows, page, cfg.num_attention_heads,
            cfg.num_key_value_heads, cfg.head_dim_,
            layers=cfg.num_hidden_layers, paged=paged,
            quantize_kv=self.quantize_kv, max_len=max_len,
        )
        lo = self.lora_cost(adapter_ranks, M=draft_k + 1)
        total += self._seconds(
            lin["bytes"] + att["bytes"] + lo["bytes"],
            lin["flops"] + att["flops"] + lo["flops"],
        ) + self.tp_comm_s(M) + self.step_overhead_s
        return total

    def prefill_s(self, chunk_tokens: int, prior_tokens: int = 0,
                  adapter_rank=0) -> float:
        """A prefill chunk of `chunk_tokens` attending `prior_tokens`
        of existing context (prefix-cache hits shrink the chunk, which
        is exactly how the cache saves simulated time). `adapter_rank`
        (a rank or a (rank, targets) pair) prices the request's LoRA
        epilogue over the chunk."""
        cfg = self.config
        lin = self.linear_cost(chunk_tokens)
        att = flash_prefill_cost(
            chunk_tokens, prior_tokens + chunk_tokens,
            cfg.num_attention_heads, cfg.num_key_value_heads,
            cfg.head_dim_, layers=cfg.num_hidden_layers,
            quantize_kv=self.quantize_kv, q_offset=prior_tokens,
        )
        lo = self.lora_cost([adapter_rank], M=chunk_tokens)
        return self._seconds(lin["bytes"] + att["bytes"] + lo["bytes"],
                             lin["flops"] + att["flops"] + lo["flops"]) \
            + self.tp_comm_s(chunk_tokens) + self.step_overhead_s

    def suggest_prefill_chunk(self, occupancy: int = 4,
                              context_tokens: int = 1024,
                              decode_steps: float = 4.0,
                              page: Optional[int] = None) -> int:
        """The roofline-derived `prefill_chunk_tokens` default
        (docs/serving.md §6): the largest page-multiple chunk whose
        prefill charge stays within ~`decode_steps` decode steps of a
        batch at `occupancy` rows around `context_tokens` of context —
        so an arriving long prompt stalls the running batch's streams
        by a few tokens' worth of time per chunk, never by the whole
        prompt."""
        page = page or self.page_size
        rows = [context_tokens] * max(occupancy, 1)
        target = decode_steps * self.decode_step_s(rows, page)
        chunk = page
        while (self.prefill_s(chunk * 2, prior_tokens=context_tokens)
               <= target):
            chunk *= 2
        return chunk

    def train_step_s(self, tokens: int, adapter_rank: int = 8) -> float:
        """Price one QLoRA train step over a `tokens`-row batch —
        forward + backward — so the supervisor path is sim-gateable
        like serving (train/qlora.make_train_step is the real thing).

        Forward: the serving prefill charge (fused dequant GEMMs +
        flash attention + the LoRA epilogue). Backward, per projection:
        the dx term at `roofline.bwd_dx_cost`'s real tile shapes —
        fused (qbackward kernel) or the XLA remat that writes a bf16
        copy of W to HBM and reads it back, per the `fused_backward`
        field; dense (unquantized) configs charge dx plus the fused dW
        accumulation instead. Flash backward is priced at 2x the
        forward attention bytes and 2.5x its FLOPs (the dq and dkv
        passes each re-sweep KV, and the kernel recomputes the
        probabilities from the saved LSE rather than loading a [T, S]
        matrix); adapter grads (da/db) double the LoRA epilogue stream.
        The lm_head (dense bf16 by convention) charges a same-shape dx."""
        cfg = self.config
        M = int(tokens)
        if M <= 0:
            return self.step_overhead_s
        lin = self.linear_cost(M)
        att = flash_prefill_cost(
            M, M, cfg.num_attention_heads, cfg.num_key_value_heads,
            cfg.head_dim_, layers=cfg.num_hidden_layers,
            quantize_kv=False,
        )
        lo = self.lora_cost([adapter_rank], M=M)

        qt = self._supported_qtype()
        shapes = [
            (cfg.hidden_size, cfg.q_dim + 2 * cfg.kv_dim),
            (cfg.q_dim, cfg.hidden_size),
            (cfg.hidden_size, 2 * cfg.intermediate_size),
            (cfg.intermediate_size, cfg.hidden_size),
        ]
        bwd_b = bwd_f = 0
        for K, O in shapes:
            if qt is not None:  # frozen low-bit base: dx only
                c = bwd_dx_cost(qt, M, K, O)
                bwd_b += (c["fused_bytes"] if self.fused_backward
                          else c["xla_remat_bytes"])
                bwd_f += c["flops"]
            else:  # dense trainable weights: dx + the dW accumulation
                dw = bwd_dw_cost(M, K, O)
                bwd_b += K * O * 2 + M * (K + O) * 2 + dw["fused_bytes"]
                bwd_f += 2 * M * K * O + dw["flops"]
        bwd_b *= cfg.num_hidden_layers
        bwd_f *= cfg.num_hidden_layers
        K, O = cfg.hidden_size, cfg.vocab_size  # lm_head dx, dense bf16
        bwd_b += K * O * 2 + M * (K + O) * 2
        bwd_f += 2 * M * K * O
        bwd_b += 2 * att["bytes"]
        bwd_f += int(2.5 * att["flops"])
        bwd_b += 2 * lo["bytes"]
        bwd_f += 2 * lo["flops"]

        total_b = lin["bytes"] + att["bytes"] + lo["bytes"] + bwd_b
        total_f = lin["flops"] + att["flops"] + lo["flops"] + bwd_f
        return (self._seconds(total_b, total_f)
                + 2 * self.tp_comm_s(M) + self.step_overhead_s)

    def kv_copy_s(self, tokens: int) -> float:
        """HBM->HBM KV move (prefill-insert, sub-page prefix copy)."""
        nbytes = 2 * tokens * self.kv_token_bytes()  # read + write
        return nbytes / (self.hbm_gbps * 1e9)

    def swap_s(self, tokens: int) -> float:
        """Preemption swap round trip (out at preempt + in at resume,
        charged together at resume) over the host link."""
        nbytes = 2 * tokens * self.kv_token_bytes()
        return nbytes / (self.swap_gbps * 1e9)

    def describe(self) -> dict:
        return {
            "model": self.label or self.config.model_type,
            "hidden": self.config.hidden_size,
            "layers": self.config.num_hidden_layers,
            "qtype": self.qtype,
            "effective_qtype": self._supported_qtype(),
            "quantize_kv": self.quantize_kv,
            "hbm_gbps": self.hbm_gbps,
            "peak_tflops": self.peak_tflops,
            "step_overhead_s": self.step_overhead_s,
            "swap_gbps": self.swap_gbps,
            "tp": self.tp,
            "ici_gbps": self.ici_gbps,
            "comm_qtype": self.comm_qtype,
            "fused_lora": self.fused_lora,
            "fused_backward": self.fused_backward,
        }
