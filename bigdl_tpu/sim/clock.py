"""Virtual clock for discrete-event serving simulation.

A `SimClock` instance IS the engine's ``clock=`` callable: calling it
reads the current simulated time, and only the simulator's event loop
(`sim/engine_driver.py`) moves it — at arrivals, modeled step
completions, and deadline boundaries. Nothing in this package may touch
wall time (graftlint WCT001 covers bigdl_tpu/sim/), so two runs of the
same seeded trace produce byte-identical reports on any machine.
"""

from __future__ import annotations


class SimClock:
    """Monotonic simulated time in seconds, starting at `start`.

    The engine calls the instance (``clock()``); the driver advances it
    with `advance` (relative, e.g. a modeled decode-step latency) or
    `advance_to` (absolute, e.g. the next trace arrival). Backward
    moves are rejected — a clock that rewinds would corrupt every
    histogram and deadline downstream.
    """

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"SimClock cannot move backward (dt={dt})")
        self._now += float(dt)
        return self._now

    def advance_to(self, t: float) -> float:
        """Jump to absolute time `t` (no-op when `t` is in the past —
        the idle-until-next-arrival jump must not rewind past work the
        engine already stamped)."""
        if t > self._now:
            self._now = float(t)
        return self._now
