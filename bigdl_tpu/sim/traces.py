"""Seeded synthetic arrival-trace generators + the replayable JSONL
trace format (docs/benchmarking.md).

Three workload shapes, mirroring how continuous-batching serving is
characterized by request-level TTFT/TPOT (arxiv 2311.00502) and the
radix-cache workload the ROADMAP scheduler item targets:

* `poisson_trace` — memoryless arrivals at a constant offered rate;
* `bursty_trace` — on/off modulated Poisson (exponential on/off
  periods), the queue-depth stressor;
* `prefix_heavy_trace` — a pool of shared system-prompt prefixes with
  divergence at configurable split points, the prefix-cache workload.

Every generator is a pure function of its seed (numpy Generator,
PCG64): the same call produces a byte-identical trace, and the trace
file round-trips byte-identically through `Trace.save`/`Trace.load`.
Lines carry the journal's crc suffix (serving/journal.crc_line) so
interior rot in a banked trace is detectable, and writes commit
atomically (utils/durability.atomic_write).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

import numpy as np

from bigdl_tpu.serving.journal import crc_line, split_crc_line

FORMAT = "bigdl-tpu-sim-trace"
VERSION = 1


@dataclasses.dataclass
class Arrival:
    """One request of the offered load: submit at simulated second `t`.
    `adapter` names the LoRA fine-tune this tenant decodes with (None =
    the shared base; serving/adapters.py)."""

    t: float
    prompt: list
    max_new_tokens: int
    adapter: Optional[str] = None

    def tokens_offered(self) -> int:
        return len(self.prompt) + self.max_new_tokens


@dataclasses.dataclass
class Trace:
    """An ordered offered-load trace plus the header that regenerates
    it (name/seed/params — the report embeds it so a banked number is
    traceable to its workload)."""

    name: str
    seed: int
    arrivals: list
    params: dict = dataclasses.field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return self.arrivals[-1].t if self.arrivals else 0.0

    def offered_tokens(self) -> int:
        return sum(a.tokens_offered() for a in self.arrivals)

    # -- JSONL serialization ------------------------------------------------

    def to_lines(self) -> list:
        head = {"format": FORMAT, "version": VERSION, "name": self.name,
                "seed": self.seed, "n": len(self.arrivals),
                "params": self.params}
        lines = [crc_line(json.dumps(head, sort_keys=True))]
        for a in self.arrivals:
            rec = {"t": round(a.t, 6), "prompt": a.prompt,
                   "max_new_tokens": a.max_new_tokens}
            if a.adapter is not None:
                rec["adapter"] = a.adapter
            lines.append(crc_line(json.dumps(rec, sort_keys=True)))
        return lines

    def save(self, path: str) -> None:
        from bigdl_tpu.utils.durability import atomic_write

        payload = ("\n".join(self.to_lines()) + "\n").encode("utf-8")
        atomic_write(path, lambda f: f.write(payload))

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, encoding="utf-8") as f:
            raw = [ln for ln in f.read().splitlines() if ln]
        if not raw:
            raise ValueError(f"{path}: empty trace file")
        bodies = []
        for i, line in enumerate(raw):
            body, ok = split_crc_line(line)
            if ok is not True:
                # a trace is a generated artifact, not an append-under-
                # crash journal: ANY bad line means the workload is not
                # the one the header claims — refuse, don't salvage
                raise ValueError(
                    f"{path}:{i + 1}: corrupt trace line (crc "
                    f"{'mismatch' if ok is False else 'missing'})"
                )
            bodies.append(json.loads(body))
        head = bodies[0]
        if head.get("format") != FORMAT:
            raise ValueError(f"{path}: not a {FORMAT} file")
        arrivals = [Arrival(t=b["t"], prompt=list(b["prompt"]),
                            max_new_tokens=b["max_new_tokens"],
                            adapter=b.get("adapter"))
                    for b in bodies[1:]]
        if head.get("n") != len(arrivals):
            raise ValueError(
                f"{path}: header claims {head.get('n')} arrivals, file "
                f"holds {len(arrivals)} — truncated trace"
            )
        return cls(name=head["name"], seed=head["seed"],
                   arrivals=arrivals, params=head.get("params", {}))


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------


def _lengths(rng, n: int, lo: int, hi: int) -> np.ndarray:
    return rng.integers(lo, hi + 1, size=n)


def _prompt(rng, length: int, vocab: int) -> list:
    # token ids in [1, vocab): id 0 is the conventional pad id and a
    # pad-leading prompt would left-pad differently than intended
    return rng.integers(1, vocab, size=int(length)).tolist()


def poisson_trace(rate_rps: float, n_requests: int, seed: int = 0,
                  vocab: int = 256, prompt_len=(8, 48),
                  out_tokens=(4, 24), name: str = "poisson",
                  t0: float = 0.0, params: Optional[dict] = None) -> Trace:
    """Memoryless arrivals: exponential inter-arrival gaps at
    `rate_rps`, uniform prompt/output-length marginals."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)
    ts = t0 + np.cumsum(gaps)
    plens = _lengths(rng, n_requests, *prompt_len)
    olens = _lengths(rng, n_requests, *out_tokens)
    arrivals = [
        Arrival(t=round(float(ts[i]), 6),
                prompt=_prompt(rng, plens[i], vocab),
                max_new_tokens=int(olens[i]))
        for i in range(n_requests)
    ]
    p = {"rate_rps": rate_rps, "vocab": vocab,
         "prompt_len": list(prompt_len), "out_tokens": list(out_tokens)}
    p.update(params or {})
    return Trace(name=name, seed=seed, arrivals=arrivals, params=p)


def bursty_trace(rate_on_rps: float, n_requests: int, seed: int = 0,
                 mean_on_s: float = 1.0, mean_off_s: float = 2.0,
                 vocab: int = 256, prompt_len=(8, 48),
                 out_tokens=(4, 24), name: str = "bursty") -> Trace:
    """On/off modulated Poisson: exponential ON windows at
    `rate_on_rps` separated by exponential OFF gaps with no arrivals —
    the queue fills in bursts and drains in the silences, the shape
    that separates a p99 story from a mean-throughput story."""
    rng = np.random.default_rng(seed)
    arrivals = []
    t = 0.0
    while len(arrivals) < n_requests:
        on_end = t + float(rng.exponential(mean_on_s))
        while len(arrivals) < n_requests:
            t += float(rng.exponential(1.0 / rate_on_rps))
            if t > on_end:
                break
            arrivals.append(Arrival(
                t=round(t, 6),
                prompt=_prompt(rng, int(_lengths(rng, 1, *prompt_len)[0]),
                               vocab),
                max_new_tokens=int(_lengths(rng, 1, *out_tokens)[0]),
            ))
        t = on_end + float(rng.exponential(mean_off_s))
    return Trace(name=name, seed=seed, arrivals=arrivals, params={
        "rate_on_rps": rate_on_rps, "mean_on_s": mean_on_s,
        "mean_off_s": mean_off_s, "vocab": vocab,
        "prompt_len": list(prompt_len), "out_tokens": list(out_tokens),
    })


def prefix_heavy_trace(rate_rps: float, n_requests: int, seed: int = 0,
                       n_prefixes: int = 3, split_points=(16, 32, 48),
                       share_p: float = 0.85, vocab: int = 256,
                       tail_len=(4, 16), out_tokens=(4, 16),
                       name: str = "prefix-heavy") -> Trace:
    """The radix-cache workload: a pool of `n_prefixes` shared system
    prompts; each arrival reuses one with probability `share_p`,
    cutting it at a seeded choice of `split_points` and appending a
    unique tail — so shared prefixes hit the paged prefix cache at
    page-aligned AND mid-page split points (the sub-page copy path)."""
    rng = np.random.default_rng(seed)
    prefixes = [_prompt(rng, max(split_points), vocab)
                for _ in range(n_prefixes)]
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)
    ts = np.cumsum(gaps)
    arrivals = []
    for i in range(n_requests):
        tail = _prompt(rng, int(_lengths(rng, 1, *tail_len)[0]), vocab)
        if rng.random() < share_p:
            pre = prefixes[int(rng.integers(0, n_prefixes))]
            cut = int(split_points[int(rng.integers(0, len(split_points)))])
            prompt = pre[:cut] + tail
        else:
            prompt = tail
        arrivals.append(Arrival(
            t=round(float(ts[i]), 6), prompt=prompt,
            max_new_tokens=int(_lengths(rng, 1, *out_tokens)[0]),
        ))
    return Trace(name=name, seed=seed, arrivals=arrivals, params={
        "rate_rps": rate_rps, "n_prefixes": n_prefixes,
        "split_points": list(split_points), "share_p": share_p,
        "vocab": vocab, "tail_len": list(tail_len),
        "out_tokens": list(out_tokens),
    })


def assign_adapters(trace: Trace, n_adapters: int, seed: int = 0,
                    zipf_a: float = 1.3,
                    name_fmt: str = "tenant-{:02d}") -> Trace:
    """Stamp every arrival with an adapter id drawn from a seeded,
    truncated Zipf over `n_adapters` tenants — the multi-tenant
    popularity law (a few hot fine-tunes, a long cold tail) that makes
    the registry's LRU/eviction behavior measurable: a budget below
    n_adapters forces churn exactly on the tail. Deterministic in
    `seed`; mutates + returns `trace` (its params record the draw)."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_adapters + 1, dtype=np.float64)
    p = ranks ** -zipf_a
    p /= p.sum()
    draws = rng.choice(n_adapters, size=len(trace.arrivals), p=p)
    for a, k in zip(trace.arrivals, draws):
        a.adapter = name_fmt.format(int(k))
    trace.params["n_adapters"] = n_adapters
    trace.params["zipf_a"] = zipf_a
    return trace


# ---------------------------------------------------------------------------
# named mixes: the CLI / bench.py vocabulary. Sizes are chosen so every
# mix completes on CPU (tiny-llama token dynamics) in seconds while
# still exercising its target path; "overload" offers ~4x the modeled
# capacity so admission bounds, queue deadlines, preemption and shed
# all fire (sim/engine_driver.py pairs it with a small page pool).
# ---------------------------------------------------------------------------

TRACE_NAMES = ("poisson", "bursty", "prefix-heavy", "overload",
               "adapter-zipf", "speculative", "adapter-spec")


def named_trace(name: str, seed: int = 0) -> Trace:
    if name == "poisson":
        return poisson_trace(rate_rps=6.0, n_requests=40, seed=seed)
    if name == "bursty":
        return bursty_trace(rate_on_rps=20.0, n_requests=40, seed=seed)
    if name == "prefix-heavy":
        # long shared system prompts (up to 6 pages at the sim's
        # page_size 16) cut at page-aligned AND mid-page points: the
        # radix workload — full-page descent, sub-page copy, and (with
        # the scenario's bounded pool) leaf eviction all fire
        return prefix_heavy_trace(
            rate_rps=12.0, n_requests=40, seed=seed, n_prefixes=4,
            split_points=(24, 48, 72, 96), tail_len=(4, 16),
            out_tokens=(4, 16),
        )
    if name == "overload":
        return poisson_trace(
            rate_rps=40.0, n_requests=48, seed=seed, name="overload",
            prompt_len=(24, 56), out_tokens=(16, 32),
        )
    if name == "adapter-zipf":
        # the multi-tenant workload (serving/adapters.py §7): Poisson
        # arrivals, each naming one of 4 tenants' LoRA adapters under a
        # Zipf popularity law — the scenario pairs it with a 2-adapter
        # registry budget so LRU eviction + reload churn genuinely fire
        return assign_adapters(
            poisson_trace(rate_rps=8.0, n_requests=40, seed=seed,
                          name="adapter-zipf"),
            n_adapters=4, seed=seed,
        )
    if name == "speculative":
        # greedy long-ish generations — the acceptance-friendly regime
        # where draft+verify rounds dominate (sim prices each round via
        # cost.spec_round_s; the engine's rollback machinery is real)
        return poisson_trace(
            rate_rps=6.0, n_requests=24, seed=seed, name="speculative",
            prompt_len=(8, 24), out_tokens=(16, 48),
        )
    if name == "adapter-spec":
        # S-LoRA completion: Zipf adapter traffic THROUGH speculative
        # decoding — base-model draft, adapter-applied verify. The
        # scenario's tight shared page pool makes adapter pages and KV
        # fight for one budget, so unified-paging churn fires alongside
        # acceptance (engine_driver SCENARIOS["adapter-spec"])
        return assign_adapters(
            poisson_trace(rate_rps=16.0, n_requests=24, seed=seed,
                          name="adapter-spec", prompt_len=(8, 24),
                          out_tokens=(16, 48)),
            n_adapters=4, seed=seed,
        )
    raise ValueError(f"unknown trace mix {name!r}; known: {TRACE_NAMES}")
