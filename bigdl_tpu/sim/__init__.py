"""Simulated-clock serving simulator — the zero-device perf gate
(docs/benchmarking.md; ROADMAP "simulated-clock serving benchmark").

Drives the REAL `serving/engine.py` — real scheduler, admission,
deadlines, preemption, prefix cache, journal, metrics, tracing — under
a virtual clock (`sim/clock.py`) and seeded synthetic arrival traces
(`sim/traces.py`). Only two things are fake: time (every engine
timestamp flows through the injectable ``clock=``, enforced statically
by graftlint WCT001) and the per-step latency, which comes from
`sim/cost.py`'s analytic roofline model instead of the host's wall
clock. A dead-TPU-tunnel day still emits engine-level TTFT/p99/shed
numbers: `bigdl-tpu simserve` / `bench.py --sim`.
"""

from bigdl_tpu.sim.clock import SimClock
from bigdl_tpu.sim.cost import CostModel
from bigdl_tpu.sim.traces import (
    Arrival, Trace, bursty_trace, named_trace, poisson_trace,
    prefix_heavy_trace,
)

__all__ = [
    "Arrival", "CostModel", "SimClock", "Trace", "bursty_trace",
    "named_trace", "poisson_trace", "prefix_heavy_trace",
]
