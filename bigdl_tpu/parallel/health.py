"""Multi-host health layer: init retry, heartbeats, anomaly consensus.

`multihost.init_multihost` makes joining a pod job one call, but a
production JobSet adds three failure modes the bare call ignores
(EQuARX, arxiv 2506.17615, catalogs the collective-path partial
failures; the reference's MPI jobs simply hang on all of them):

1. **Flaky coordinator at pod start** — the process-0 coordinator pod
   may come up seconds after its peers; a one-shot
   `jax.distributed.initialize` on a peer then dies and the whole
   JobSet crash-loops. :func:`init_multihost_with_retry` wraps the join
   in bounded exponential backoff.
2. **A lagging or desynced peer mid-run** — :class:`HealthMonitor`
   heartbeats (rank, step, timestamp) across hosts and raises a
   structured :class:`RankDropError` naming the stale peer
   (`max_step_lag`) or, under the injected ``rank_drop`` fault, the
   missing one. Honest limit: a peer that is fully DEAD wedges the
   heartbeat allgather exactly like any other collective, so the
   *detection* of that case stays with `train/watchdog.py`'s timeout
   (exit 42) — this layer diagnoses the partial-failure modes a
   collective can actually survive, and gives tests an injectable
   seam for the rest.
3. **Rank-local anomaly decisions desyncing SPMD** — if rank 3 skips an
   optimizer update that rank 5 applies, every later collective runs on
   diverged state (silent corruption, not a crash).
   :func:`anomaly_consensus` reduces the skip/continue flag across
   processes so all ranks take the same branch by construction.

Everything degrades to a no-op-ish identity on a single process, so the
training supervisor (`train/supervisor.py`) calls these unconditionally
and the whole layer is CPU-testable: each function takes an injectable
`allgather` so tests simulate N hosts (and dropped ranks) in-process.
"""

from __future__ import annotations

import dataclasses
import sys
import time
import warnings
from typing import Callable, Optional, Sequence

import numpy as np

from bigdl_tpu.parallel.multihost import init_multihost


def _default_allgather(row: np.ndarray) -> np.ndarray:
    """Gather one fixed-shape float row per process -> [nproc, ...].
    Single-process: identity (no collective, no device traffic)."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(row)[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.asarray(row)))


def init_multihost_with_retry(
    attempts: int = 5,
    backoff_s: float = 1.0,
    max_backoff_s: float = 30.0,
    init_fn: Optional[Callable] = None,
    **kwargs,
) -> int:
    """`init_multihost` under bounded exponential backoff — the
    coordinator pod of a fresh JobSet routinely comes up after its
    peers, and the bare `jax.distributed.initialize` fails fast on a
    connection refusal. Returns the number of attempts used; re-raises
    the last error once `attempts` are exhausted (a partial-config
    ValueError is NOT retried: a wrong process identity never becomes
    right by waiting)."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    fn = init_fn or init_multihost
    delay = backoff_s
    for attempt in range(1, attempts + 1):
        try:
            fn(**kwargs)
            return attempt
        except ValueError:
            raise  # config error, not a flaky coordinator
        except Exception as e:  # noqa: BLE001 - RuntimeError/XlaRuntimeError
            if attempt == attempts:
                raise
            print(
                f"[bigdl-tpu health] distributed init attempt "
                f"{attempt}/{attempts} failed ({type(e).__name__}: {e}); "
                f"retrying in {delay:.1f}s",
                file=sys.stderr, flush=True,
            )
            time.sleep(delay)
            delay = min(delay * 2, max_backoff_s)
    raise AssertionError("unreachable")  # pragma: no cover


@dataclasses.dataclass
class RankStatus:
    rank: int
    step: int
    ts: float  # sender's wall clock at heartbeat


class RankDropError(RuntimeError):
    """A heartbeat round is missing (or has stale entries for) one or
    more ranks. Structured so the supervisor's abort diagnostic can
    name the peer instead of 'collective hung'."""

    def __init__(self, missing: Sequence[int], present: Sequence[int],
                 step: int, detail: str = ""):
        self.missing = sorted(missing)
        self.present = sorted(present)
        self.step = step
        self.detail = detail
        super().__init__(
            f"rank(s) {self.missing} missing from the step-{step} "
            f"heartbeat (present: {self.present})"
            + (f" — {detail}" if detail else "")
        )


class HealthMonitor:
    """Cross-host heartbeat: every process contributes
    (rank, step, timestamp); :meth:`check` raises :class:`RankDropError`
    when a rank is absent or its step lags by more than `max_step_lag`.

    `allgather` is injectable for CPU tests (simulate N hosts from one
    process); `faults` threads a TrainFaultInjector — an armed
    ``rank_drop`` point deletes the victim rank's row from the gathered
    heartbeat, driving the exact code path a dead peer would."""

    def __init__(
        self,
        *,
        num_processes: Optional[int] = None,
        process_index: Optional[int] = None,
        max_step_lag: Optional[int] = None,
        allgather: Optional[Callable] = None,
        faults=None,
        clock: Callable[[], float] = time.time,  # heartbeat timestamps
        # (injectable so multi-host health tests run under the simulated
        # clock like everything else; graftlint WCT001)
    ):
        import jax

        self.num_processes = (num_processes if num_processes is not None
                              else jax.process_count())
        self.process_index = (process_index if process_index is not None
                              else jax.process_index())
        self.max_step_lag = max_step_lag
        self._allgather = allgather or _default_allgather
        self._faults = faults
        self._clock = clock

    def snapshot(self, step: int) -> list:
        """One heartbeat round -> [RankStatus] actually heard from."""
        row = np.asarray(
            [float(self.process_index), float(step), self._clock()],
            np.float64,
        )
        gathered = np.atleast_2d(np.asarray(self._allgather(row)))
        statuses = [
            RankStatus(rank=int(r[0]), step=int(r[1]), ts=float(r[2]))
            for r in gathered
        ]
        if self._faults is not None:
            f = self._faults.fire("rank_drop")
            if f is not None:
                victim = int(f.get("rank", self.num_processes - 1))
                statuses = [s for s in statuses if s.rank != victim]
        return statuses

    def check(self, step: int) -> list:
        """Heartbeat + verdict: returns the statuses when every rank is
        present and fresh, raises :class:`RankDropError` otherwise."""
        statuses = self.snapshot(step)
        seen = {s.rank for s in statuses}
        missing = set(range(self.num_processes)) - seen
        if missing:
            raise RankDropError(missing, seen, step)
        if self.max_step_lag is not None:
            stale = [s for s in statuses
                     if step - s.step > self.max_step_lag]
            if stale:
                raise RankDropError(
                    [s.rank for s in stale], seen, step,
                    detail=f"stale: {[(s.rank, s.step) for s in stale]} "
                           f"lag > {self.max_step_lag} steps",
                )
        return statuses


def consensus_any(flags: Sequence[bool],
                  allgather: Optional[Callable] = None) -> list:
    """Element-wise all-ranks OR of a vector of rank-local boolean
    verdicts in ONE collective. Every rank MUST call this at the same
    step boundary; all ranks then act on identical verdicts, so a
    rank-local decision (NaN skip, preemption exit) can never fork the
    SPMD program state. Single process: identity."""
    gather = allgather or _default_allgather
    row = np.asarray([1.0 if f else 0.0 for f in flags], np.float32)
    return [bool(v) for v in np.asarray(gather(row)).max(axis=0) > 0]


def anomaly_consensus(local_flag: bool,
                      allgather: Optional[Callable] = None) -> bool:
    """All-ranks OR of a rank-local anomaly verdict (one-flag
    :func:`consensus_any`)."""
    return consensus_any([local_flag], allgather=allgather)[0]


def warn_if_unhealthy(monitor: HealthMonitor, step: int) -> Optional[str]:
    """Non-fatal heartbeat probe: returns (and warns with) the
    diagnostic instead of raising — for callers that want visibility
    without an abort (e.g. the final pre-shutdown beat)."""
    try:
        monitor.check(step)
        return None
    except RankDropError as e:
        warnings.warn(str(e))
        return str(e)
