"""Multi-host initialization and host-aware mesh layout.

The reference scales past one machine with per-feature process backends
— Intel MPI k8s jobs for training, oneCCL process groups for pipeline
parallelism, Ray actors for vLLM TP (SURVEY.md §2.3). The TPU-native
replacement is ONE call per process (`jax.distributed.initialize`) after
which `jax.devices()` is the global device set and every jitted program
in this framework — generate, the serving engine, the (dp, sp, tp, pp)
train steps — runs SPMD across hosts with zero further changes: XLA
lays collectives on ICI within a slice and DCN across slices.

The one thing that DOES need care across hosts is the MESH LAYOUT:
axes that carry heavy collectives (tp's per-layer psum, sp's per-step
ppermute ring) must stay inside a host/slice so they ride ICI, while
light axes (dp's once-per-step gradient reduce, pp's once-per-
microbatch boundary transfer) absorb the slow DCN hops.
`host_aware_mesh` builds exactly that layout from `jax.local_device_count()`.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host job (the reference's mpirun/Ray launch step).

    On TPU pods with standard launchers (GKE, queued resources) all
    arguments auto-detect and this is `jax.distributed.initialize()`
    verbatim. Explicit args (or BIGDL_TPU_COORDINATOR / _NUM_PROCS /
    _PROC_ID env fallbacks) cover bare-metal launches. Safe to call on
    a single host: with no coordinator configured it is a no-op.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "BIGDL_TPU_COORDINATOR"
    )
    if num_processes is None and os.environ.get("BIGDL_TPU_NUM_PROCS"):
        num_processes = int(os.environ["BIGDL_TPU_NUM_PROCS"])
    if process_id is None and os.environ.get("BIGDL_TPU_PROC_ID"):
        process_id = int(os.environ["BIGDL_TPU_PROC_ID"])
    explicit = (coordinator_address, num_processes, process_id)
    if any(v is not None for v in explicit):
        if any(v is None for v in explicit):
            # a partial config silently auto-joining would give the
            # process a wrong identity — fail loudly instead
            raise ValueError(
                "init_multihost needs coordinator_address, num_processes "
                f"AND process_id together; got {explicit}"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return
    # auto-detect ONLY when a distributed launcher left its markers —
    # and then let failures propagate: swallowing them would silently
    # degrade a pod job to one host (other processes would hang in
    # cross-host collectives waiting for this one)
    markers = ("COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
               "MEGASCALE_COORDINATOR_ADDRESS", "CLOUD_TPU_TASK_ID")
    if any(m in os.environ for m in markers):
        jax.distributed.initialize()


def host_aware_mesh(
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    dp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    local_devices: Optional[int] = None,
    axes: Sequence[str] = ("dp", "pp", "sp", "tp"),
) -> Mesh:
    """A (dp, pp, sp, tp) mesh whose heavy axes stay intra-host.

    Devices order host-major (jax.devices() already groups by process);
    the mesh reshapes so tp (fastest-varying) and sp tile WITHIN one
    host's devices whenever tp*sp <= local_device_count — their
    per-layer/per-step collectives then never cross DCN — and dp/pp
    span hosts. Raises if tp*sp cannot fit in one host, with the
    cross-DCN implication spelled out, unless BIGDL_TPU_ALLOW_DCN_TP=1.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    local = local_devices or jax.local_device_count()
    if dp is None:
        dp = n // (tp * sp * pp)
    if dp * pp * sp * tp != n:
        raise ValueError(
            f"dp*pp*sp*tp = {dp}*{pp}*{sp}*{tp} != {n} devices"
        )
    # contiguity of a tp row within one host requires tp*sp to DIVIDE the
    # local device count, not merely fit in it (tp=6 on local=8 would
    # straddle the host boundary at device 8)
    if (tp * sp > local or local % (tp * sp) != 0) \
            and os.environ.get("BIGDL_TPU_ALLOW_DCN_TP") != "1":
        raise ValueError(
            f"tp*sp = {tp * sp} does not tile the {local} devices of one "
            "host: per-layer tensor-parallel psums would cross DCN and "
            "dominate step time. Pick tp*sp dividing the local device "
            "count and shard the rest over pp/dp across hosts, or set "
            "BIGDL_TPU_ALLOW_DCN_TP=1 to accept the slow layout."
        )
    from bigdl_tpu.parallel.mesh import make_mesh

    return make_mesh((dp, pp, sp, tp), devices=devices, axes=axes)
