"""Pipeline parallelism over a `pp` mesh axis.

TPU-native re-design of the reference's pipeline-parallel inference
(`transformers/pipeline_parallel.py:166-234` stage slicing with
Dummy layers, `:300-446` p2p send/recv token loop over oneCCL in
/root/reference): stages are shards of the **stacked layer axis** (the
same leading-L layout `lax.scan` iterates), microbatches flow stage to
stage via `ppermute` inside one jitted SPMD program — no process groups,
no explicit send/recv, and the whole GPipe schedule (fill, steady state,
drain: n_micro + n_stages - 1 ticks) compiles into a single XLA loop
with compute/ICI overlap.

Two entry points:

- `make_pipeline_forward`: microbatched GPipe forward for the cache-free
  scoring/training path (fill, steady state, drain ticks).
- `make_pipeline_step`: prefill/decode with **per-stage KV caches** —
  the cache's layer axis is sharded over `pp` exactly like the params,
  each stage's rows update at its tick, and the same step signature as
  the family forward lets `TpuModel.generate()` and the serving engine
  run unchanged over a (pp, tp) mesh (the reference's serving-grade
  `PPModelWorker`, pipeline_parallel.py:482-929, reaches this with
  explicit p2p + a Python scheduler; here it is one SPMD program).

On TPU slices tensor parallelism over ICI usually dominates PP; PP's
niche is multi-slice/DCN topologies and models bigger than one slice's
HBM.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor
from bigdl_tpu.parallel._compat import shard_map as _shard_map


def pipeline_param_specs(params: dict, axis: str = "pp") -> dict:
    """PartitionSpec tree: layer-stack leaves sharded on their leading L
    axis over `axis`; embed/head/final norm replicated (they run on the
    edge stages). QTensor nodes expand field-wise."""
    from bigdl_tpu.parallel.sharding import expand_specs_for_params

    is_node = lambda x: isinstance(x, (QTensor, jax.Array))
    specs = {
        k: jax.tree.map(
            lambda _: P(axis) if k == "layers" else P(), v, is_leaf=is_node
        )
        for k, v in params.items()
    }
    return expand_specs_for_params(specs, params)


def shard_for_pipeline(params: dict, mesh: Mesh, axis: str = "pp") -> dict:
    """Place a param tree with the layer stack split across pp stages."""
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pipeline_param_specs(params, axis),
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(params, shardings)


def pp_param_specs(config: ModelConfig, base_specs: dict, axis: str = "pp") -> dict:
    """Compose PP with TP: take sharding.param_specs (tp dims) and put
    `axis` on the leading layer-stack dimension of every layers leaf."""

    def relayer(spec):
        if not isinstance(spec, P):
            return spec
        rest = tuple(spec)[1:] if len(spec) else ()
        return P(axis, *rest)

    out = dict(base_specs)
    out["layers"] = jax.tree.map(
        relayer, base_specs["layers"], is_leaf=lambda x: isinstance(x, P)
    )
    return out


def pp_cache_specs(cache, axis: str = "pp"):
    """PartitionSpec tree for a KVCache: per-layer arrays (k/v and their
    scales) sharded on the leading layer axis; positions replicated."""
    import dataclasses

    fields = {}
    for f in dataclasses.fields(cache):
        val = getattr(cache, f.name)
        if val is None:
            fields[f.name] = None
        elif f.name in ("k", "v", "k_scale", "v_scale"):
            fields[f.name] = P(axis)
        else:
            fields[f.name] = P()
    return type(cache)(**fields)


def _tree_where(pred, new, old):
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), new, old)


def make_pipeline_step(
    config: ModelConfig,
    forward_fn: Callable,
    mesh: Mesh,
    axis: str = "pp",
    compute_dtype=jnp.bfloat16,
):
    """Returns step(params, tokens, cache, mode=..., last_logits_only=...)
    -> (logits, cache): the family-forward signature, run as a pipeline
    over `axis` with per-stage KV caches.

    Params and cache carry their layer stacks sharded over `axis`
    (pp_param_specs / pp_cache_specs); any 'tp'/'dp' axes in the mesh
    stay automatic (GSPMD) — shard_map is manual over `axis` only. The
    token's hidden state flows stage to stage via ppermute across
    n_stages ticks; stage s commits its KV-cache rows only at tick s
    (a jnp.where select per tick — the price of one SPMD program).
    """
    n_stages = mesh.shape[axis]
    L = config.num_hidden_layers
    assert L % n_stages == 0, f"layers {L} not divisible by {n_stages} stages"
    L_local = L // n_stages
    perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]

    from bigdl_tpu.models.llama import embed_tokens, lm_head_logits

    def step(params, tokens, cache, mode="decode", last_logits_only=False,
             collect_obs: int = 0):
        def stage_step(params, tokens, cache):
            # NOTE pinned-jax limitation: when the mesh composes pp with a
            # real (size>1) tp/dp axis, 0.4.37's partial-manual shard_map
            # cannot lower this program (axis_index -> PartitionId
            # UNIMPLEMENTED; feeding the stage id as a pp-sharded operand
            # instead trades that for a partitioner CHECK-fail crash, so
            # the clean exception is the better failure). pp-only meshes
            # (every axis but pp size 1) run fully manual and work.
            s = jax.lax.axis_index(axis)
            h0 = embed_tokens(config, params, tokens, compute_dtype)
            B, T = tokens.shape
            # per-stage SnapKV observation queries, committed (like the
            # cache) only on the stage's active tick
            obs0 = jnp.zeros(
                (L_local, B, collect_obs, config.num_attention_heads,
                 config.head_dim_), compute_dtype,
            ) if collect_obs else None

            def tick(carry, t):
                recv, cache, out, obs = carry
                res = forward_fn(
                    config, params, recv, cache, mode=mode,
                    compute_dtype=compute_dtype, input_is_hidden=True,
                    return_hidden=True, layer_offset=s * L_local,
                    collect_obs=collect_obs,
                )
                if collect_obs:
                    h_out, cache_new, obs_new = res
                else:
                    (h_out, cache_new), obs_new = res, None
                active = s == t
                cache = _tree_where(active, cache_new, cache)
                if collect_obs:
                    obs = jnp.where(active, obs_new, obs)
                out = jnp.where(active & (s == n_stages - 1), h_out, out)
                recv = jax.lax.ppermute(h_out, axis, perm_fwd)
                return (recv, cache, out, obs), None

            (_, cache, out, obs), _ = jax.lax.scan(
                tick, (h0, cache, jnp.zeros_like(h0), obs0),
                jnp.arange(n_stages)
            )
            # psum: only the last stage holds the real hidden (V/H times
            # less ICI traffic than psumming logits). f32: XLA CPU's
            # AllReducePromotion pass check-fails cloning a bf16
            # all-reduce inside the generate while_loop (found round 3);
            # f32 sidesteps it at negligible cost for a [B,T,H] tensor.
            h_final = jax.lax.psum(
                jnp.where(s == n_stages - 1, out, 0.0).astype(jnp.float32),
                axis,
            ).astype(compute_dtype)
            if last_logits_only:
                h_final = h_final[:, -1:]
            logits = lm_head_logits(config, params, h_final, compute_dtype)
            if collect_obs:
                return logits, cache, obs
            return logits, cache

        from bigdl_tpu.parallel.sharding import param_specs

        pspecs = pp_param_specs(config, param_specs(config), axis)
        # drop non-pp axis names from the manual specs: shard_map is
        # manual over `axis` only; tp placement stays automatic
        def only_pp(spec):
            if not isinstance(spec, P):
                return spec
            return P(*(a if a == axis else None for a in tuple(spec)))

        pspecs = jax.tree.map(only_pp, pspecs, is_leaf=lambda x: isinstance(x, P))
        from bigdl_tpu.parallel.sharding import expand_specs_for_params

        pspecs = expand_specs_for_params(pspecs, params)
        out_specs = (P(), pp_cache_specs(cache, axis))
        if collect_obs:
            # obs stacks stage-local layer blocks -> global [L, B, W, Hq, D]
            out_specs = out_specs + (P(axis),)
        return _shard_map(
            stage_step,
            mesh=mesh,
            in_specs=(pspecs, P(), pp_cache_specs(cache, axis)),
            out_specs=out_specs,
            axis_names={axis},
            check_vma=False,
        )(params, tokens, cache)

    return step


def make_pipeline_forward(
    config: ModelConfig,
    forward_fn: Callable,  # family forward (models.llama.forward)
    mesh: Mesh,
    n_micro: int,
    axis: str = "pp",
    compute_dtype=jnp.bfloat16,
):
    """Returns fn(params, tokens [B,T], start [B]|None) -> logits
    [B,T,V] float32, with params layer-sharded over `axis`
    (shard_for_pipeline) and B divisible by n_micro.
    """
    n_stages = mesh.shape[axis]
    L = config.num_hidden_layers
    assert L % n_stages == 0, f"layers {L} not divisible by {n_stages} stages"
    L_local = L // n_stages
    perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]

    from bigdl_tpu.models.llama import embed_tokens, lm_head_logits

    def stage_fn(params, tokens, start):
        s = jax.lax.axis_index(axis)
        B, T = tokens.shape
        Bm = B // n_micro
        toks_mb = tokens.reshape(n_micro, Bm, T)
        start_mb = start.reshape(n_micro, Bm)
        H = config.hidden_size

        n_ticks = n_micro + n_stages - 1
        outs0 = jnp.zeros((n_micro, Bm, T, H), compute_dtype)
        recv0 = jnp.zeros((Bm, T, H), compute_dtype)

        def tick(carry, t):
            recv, outs = carry
            m = t - s  # microbatch index at this stage this tick
            active = (m >= 0) & (m < n_micro)
            mi = jnp.clip(m, 0, n_micro - 1)
            toks_m = toks_mb[mi]
            start_m = start_mb[mi]
            # stage 0 embeds; later stages consume the ppermuted hidden
            h_in = jnp.where(
                s == 0, embed_tokens(config, params, toks_m, compute_dtype), recv
            )
            h_out, _ = forward_fn(
                config, params, h_in, None, compute_dtype=compute_dtype,
                start=start_m, input_is_hidden=True, return_hidden=True,
                layer_offset=s * L_local,
            )
            outs = jnp.where(
                active & (s == n_stages - 1),
                outs.at[mi].set(h_out),
                outs,
            )
            send = jax.lax.ppermute(h_out, axis, perm_fwd)
            return (send, outs), None

        (recv, outs), _ = jax.lax.scan(tick, (recv0, outs0), jnp.arange(n_ticks))
        # only the last stage holds real hiddens (zeros elsewhere): psum the
        # [B,T,H] hidden — V/H times less ICI traffic than psumming logits —
        # then run the replicated head locally on the identical summed value.
        h_final = jax.lax.psum(
            jnp.where(s == n_stages - 1, outs.reshape(B, T, H), 0.0), axis
        )
        return lm_head_logits(config, params, h_final, compute_dtype)

    def fn(params, tokens, start=None):
        if start is None:
            start = jnp.zeros((tokens.shape[0],), jnp.int32)
        sharded = _shard_map(
            stage_fn,
            mesh=mesh,
            in_specs=(pipeline_param_specs(params, axis), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return sharded(params, tokens, start)

    return fn
