"""Pipeline parallelism over a `pp` mesh axis.

TPU-native re-design of the reference's pipeline-parallel inference
(`transformers/pipeline_parallel.py:166-234` stage slicing with
Dummy layers, `:300-446` p2p send/recv token loop over oneCCL in
/root/reference): stages are shards of the **stacked layer axis** (the
same leading-L layout `lax.scan` iterates), microbatches flow stage to
stage via `ppermute` inside one jitted SPMD program — no process groups,
no explicit send/recv, and the whole GPipe schedule (fill, steady state,
drain: n_micro + n_stages - 1 ticks) compiles into a single XLA loop
with compute/ICI overlap.

This covers the scoring/training forward (cache-free path). For decode,
tensor parallelism over ICI dominates PP on TPU slices — PP's niche is
multi-slice/DCN topologies, where the same ppermute schedule applies to
the decode step with per-stage KV caches (planned).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor


def pipeline_param_specs(params: dict, axis: str = "pp") -> dict:
    """PartitionSpec tree: layer-stack leaves sharded on their leading L
    axis over `axis`; embed/head/final norm replicated (they run on the
    edge stages). QTensor nodes expand field-wise."""
    is_node = lambda x: isinstance(x, (QTensor, jax.Array))

    def expand(spec, param):
        if isinstance(param, QTensor):
            return QTensor(
                data=spec, scales=spec,
                mins=None if param.mins is None else spec, qtype=param.qtype,
            )
        return spec

    specs = {
        k: jax.tree.map(
            lambda _: P(axis) if k == "layers" else P(), v, is_leaf=is_node
        )
        for k, v in params.items()
    }
    return jax.tree.map(expand, specs, params, is_leaf=lambda x: isinstance(x, P))


def shard_for_pipeline(params: dict, mesh: Mesh, axis: str = "pp") -> dict:
    """Place a param tree with the layer stack split across pp stages."""
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pipeline_param_specs(params, axis),
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(params, shardings)


def make_pipeline_forward(
    config: ModelConfig,
    forward_fn: Callable,  # family forward (models.llama.forward)
    mesh: Mesh,
    n_micro: int,
    axis: str = "pp",
    compute_dtype=jnp.bfloat16,
):
    """Returns fn(params, tokens [B,T], start [B]|None) -> logits
    [B,T,V] float32, with params layer-sharded over `axis`
    (shard_for_pipeline) and B divisible by n_micro.
    """
    n_stages = mesh.shape[axis]
    L = config.num_hidden_layers
    assert L % n_stages == 0, f"layers {L} not divisible by {n_stages} stages"
    L_local = L // n_stages
    perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]

    from bigdl_tpu.models.llama import embed_tokens, lm_head_logits

    def stage_fn(params, tokens, start):
        s = jax.lax.axis_index(axis)
        B, T = tokens.shape
        Bm = B // n_micro
        toks_mb = tokens.reshape(n_micro, Bm, T)
        start_mb = start.reshape(n_micro, Bm)
        H = config.hidden_size

        n_ticks = n_micro + n_stages - 1
        outs0 = jnp.zeros((n_micro, Bm, T, H), compute_dtype)
        recv0 = jnp.zeros((Bm, T, H), compute_dtype)

        def tick(carry, t):
            recv, outs = carry
            m = t - s  # microbatch index at this stage this tick
            active = (m >= 0) & (m < n_micro)
            mi = jnp.clip(m, 0, n_micro - 1)
            toks_m = toks_mb[mi]
            start_m = start_mb[mi]
            # stage 0 embeds; later stages consume the ppermuted hidden
            h_in = jnp.where(
                s == 0, embed_tokens(config, params, toks_m, compute_dtype), recv
            )
            h_out, _ = forward_fn(
                config, params, h_in, None, compute_dtype=compute_dtype,
                start=start_m, input_is_hidden=True, return_hidden=True,
                layer_offset=s * L_local,
            )
            outs = jnp.where(
                active & (s == n_stages - 1),
                outs.at[mi].set(h_out),
                outs,
            )
            send = jax.lax.ppermute(h_out, axis, perm_fwd)
            return (send, outs), None

        (recv, outs), _ = jax.lax.scan(tick, (recv0, outs0), jnp.arange(n_ticks))
        # only the last stage holds real hiddens (zeros elsewhere): psum the
        # [B,T,H] hidden — V/H times less ICI traffic than psumming logits —
        # then run the replicated head locally on the identical summed value.
        h_final = jax.lax.psum(
            jnp.where(s == n_stages - 1, outs.reshape(B, T, H), 0.0), axis
        )
        return lm_head_logits(config, params, h_final, compute_dtype)

    def fn(params, tokens, start=None):
        if start is None:
            start = jnp.zeros((tokens.shape[0],), jnp.int32)
        sharded = jax.shard_map(
            stage_fn,
            mesh=mesh,
            in_specs=(pipeline_param_specs(params, axis), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return sharded(params, tokens, start)

    return fn
