"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

The reference has **no** sequence parallelism (SURVEY.md §2.3: its
long-context levers are single-device KV compression/quantization); this
is the TPU-native upgrade that makes long context a first-class scaling
axis: shard the sequence over `sp`, keep every device's attention
working set at 1/n of the sequence, and rotate KV shards around the ring
with `ppermute` so each hop overlaps compute with neighbor ICI traffic
(blockwise/ring attention; PAPERS.md "Ring Attention with Blockwise
Transformers").

`ring_attention` is the device-local function — call it INSIDE
`shard_map` with q/k/v already sharded along the sequence axis. Online
softmax (m, l, acc) accumulates across ring steps exactly like the
Pallas flash kernel accumulates across K blocks, so the result is
bit-comparable to dense attention up to fp32 reduction order.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from bigdl_tpu.parallel._compat import shard_map as _shard_map

_NEG_INF = -1e30


def ring_attention(
    q: jax.Array,  # [B, Tl, Hq, D] local query chunk
    k: jax.Array,  # [B, Sl, Hkv, D] local key chunk
    v: jax.Array,  # [B, Sl, Hkv, D]
    axis_name: str = "sp",
    axis_size: Optional[int] = None,  # ring length (static); None = axis size
    causal: bool = True,
    scale: Optional[float] = None,
    start: Optional[jax.Array] = None,  # [B] global left-pad offsets
    comm_qtype: str = "none",  # quantize the rotating k/v payloads
    comm_block_size: int = 256,
) -> jax.Array:
    """Device-local ring attention step (use inside shard_map).

    Chunk layout: device i holds global positions [i*Tl, (i+1)*Tl) of q
    and [i*Sl, (i+1)*Sl) of k/v. Returns the local output chunk
    [B, Tl, Hq, D] in q.dtype.

    `comm_qtype` ("int8"|"fp8_e4m3"; parallel/qcollectives.py) encodes
    each k/v chunk ONCE at entry and rotates the block-quantized
    payload (codes + f16 scales) around the ring instead of the raw
    fp32/bf16 chunks — n-1 hops of ~quarter traffic, one quantization
    event total (no per-hop requantization, so no error feedback is
    needed on this path). Every device decodes the same bytes, so all
    shards attend over identical dequantized k/v.
    """
    B, Tl, Hq, D = q.shape
    _, Sl, Hkv, _ = k.shape
    G = Hq // Hkv
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if axis_size is None:
        axis_size = jax.lax.psum(1, axis_name)  # concrete under shard_map
    n = int(axis_size)
    me = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32).reshape(B, Tl, Hkv, G, D)
    qf = jnp.moveaxis(qf, 1, 3)  # [B, Hkv, G, Tl, D]
    qpos = me * Tl + jnp.arange(Tl)  # [Tl] global q positions

    m0 = jnp.full((B, Hkv, G, Tl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Tl, 1), jnp.float32)
    acc0 = jnp.zeros((B, Hkv, G, Tl, D), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    from bigdl_tpu.parallel import qcollectives as qc

    if qc.resolve_comm_qtype(comm_qtype) != "none":
        payload0 = (qc.encode_array(k, comm_qtype, comm_block_size)
                    + qc.encode_array(v, comm_qtype, comm_block_size))

        def materialize(pl):
            kd, ks, vd, vs = pl
            return (
                qc.decode_array(kd, ks, k.shape, jnp.float32,
                                comm_block_size),
                qc.decode_array(vd, vs, v.shape, jnp.float32,
                                comm_block_size),
            )
    else:
        payload0 = (k, v)

        def materialize(pl):
            return pl

    def rotate(pl):
        return tuple(jax.lax.ppermute(a, axis_name, perm) for a in pl)

    def step(carry, i):
        m, l, acc, pl = carry
        # rotate at the TOP of every step after the first — the final
        # step's kv then stays put, saving one k+v ICI hop per call
        pl = jax.lax.cond(i > 0, rotate, lambda p: p, pl)
        kc, vc = materialize(pl)
        src = (me - i) % n  # origin shard of the kv chunk we hold now
        kpos = src * Sl + jnp.arange(Sl)  # [Sl] global k positions

        s = jnp.einsum(
            "bhgtd,bshd->bhgts", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        valid = jnp.ones((B, 1, 1, Tl, Sl), jnp.bool_)
        if causal:
            valid = valid & (kpos[None, :] <= qpos[:, None])[None, None, None]
        if start is not None:
            valid = valid & (kpos[None, None, None, None, :] >= start[:, None, None, None, None])
        s = jnp.where(valid, s, _NEG_INF)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhgts,bshd->bhgtd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * alpha + pv
        return (m_new, l_new, acc_new, pl), None

    (m, l, acc, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, payload0), jnp.arange(n)
    )
    out = acc / jnp.where(l == 0.0, 1.0, l)  # [B, Hkv, G, Tl, D]
    out = jnp.moveaxis(out, 3, 1).reshape(B, Tl, Hq, D)
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp", causal: bool = True,
                        comm_qtype: str = "none"):
    """Whole-array convenience wrapper: shard q/k/v over `axis_name`
    (sequence dim), run ring attention, return the full output. Other mesh
    axes are ignored (inputs replicated over them). `comm_qtype` rotates
    block-quantized k/v payloads (see `ring_attention`)."""
    n = mesh.shape[axis_name]
    seq_spec = P(None, axis_name, None, None)

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    )
    def sharded(q, k, v):
        return ring_attention(
            q, k, v, axis_name=axis_name, axis_size=n, causal=causal,
            comm_qtype=comm_qtype,
        )

    def fn(q, k, v):
        sh = NamedSharding(mesh, seq_spec)
        return sharded(
            jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
        )

    return fn
