"""Quantized ICI collectives: block-scaled int8 / fp8_e4m3 ring
all-reduce and all-gather with per-rank error feedback.

TP decode is latency-bound on the per-layer all-reduce (one per o-proj
and one per down-proj epilogue), and PP/multihost weight distribution is
bandwidth-bound on the all-gather. EQuARX (arxiv 2506.17615) shows a
block-scaled quantized all-reduce recovers most of that ICI bandwidth at
negligible quality cost, and arxiv 2301.12017 gives the composability
argument for stacking low-bit comms on top of already-quantized weights
— exactly this stack, where every TP epilogue sits downstream of a fused
dequant-GEMM.

Codec (docs/parallelism.md): the payload of every ring hop is the
partial sum flattened, zero-padded to a multiple of ``block_size``, and
encoded as per-block absmax-scaled int8 (d = absmax/127) or fp8_e4m3
(d = absmax/448) with float16 scales — the same per-block symmetric
format as `quant/numerics.py` (whose primitives this reuses), at a
comm-tuned block size (default 256: scale overhead 2/256 bytes/elem).

Algorithm — reduce-scatter ring + all-gather ring, both on
``jax.lax.ppermute`` with the neighbor permutation `ring.py` uses:

* reduce-scatter (n-1 hops): chunk ``c`` starts as rank ``c+1``'s local
  slice and travels the ring accumulating each stop's local slice, so
  after n-1 hops rank ``r`` owns the fully-reduced chunk ``r``. Every
  hop's payload is quantized; **error feedback** keeps the residual of
  hop *k*'s quantization on the sender and adds it back before
  quantizing hop *k+1*, so codec error does not compound around the
  ring (the property `tests/test_qcollectives.py` checks).
* all-gather (n-1 hops): each owner quantizes its reduced chunk ONCE
  and the encoded payload is forwarded unchanged; the owner itself uses
  the decoded version of its own chunk, so all ranks reconstruct
  bit-identical output.

``qtype="none"`` bypasses all of this and calls ``jax.lax.psum`` /
``jax.lax.all_gather`` — bit-identical to the unquantized path.

Everything here is device-local (runs inside `_compat.shard_map`, the
jax-0.4.37-portable shim) and CPU-testable on the dryrun meshes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel._compat import shard_map as _shard_map
# the per-block symmetric codec primitives (quant/numerics.py): blocked
# views, safe reciprocal, fp8 format ranges/dtypes
from bigdl_tpu.quant.numerics import _FP8_DTYPE, _FP8_MAX, _safe_inv

COMM_QTYPES = ("none", "int8", "fp8_e4m3")

#: comm-tuned block: 2 scale bytes per 256 payload elems (~0.8% overhead)
DEFAULT_BLOCK = 256

#: declared exactness tolerance per comm qtype: max abs error of the
#: quantized all-reduce relative to max|fp32 result|, on any dryrun
#: mesh / ring size (error feedback keeps it hop-count independent).
TOLERANCE = {"int8": 2e-2, "fp8_e4m3": 8e-2}


def resolve_comm_qtype(name: Optional[str]) -> str:
    qt = "none" if name is None else str(name)
    if qt not in COMM_QTYPES:
        raise ValueError(
            f"unknown comm_qtype {name!r}; expected one of {COMM_QTYPES}"
        )
    return qt


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """The `comm_qtype` knob as a hashable (jit-static) bundle: which
    mesh axis the TP epilogues reduce over, the payload format, and the
    declared tolerance the parity tests/gates hold the codec to."""

    mesh: Mesh
    axis_name: str = "tp"
    qtype: str = "none"
    block_size: int = DEFAULT_BLOCK
    #: None = the format's declared default (`TOLERANCE`)
    tolerance: Optional[float] = None
    error_feedback: bool = True

    def __post_init__(self):
        resolve_comm_qtype(self.qtype)

    @property
    def axis_size(self) -> int:
        return int(self.mesh.shape.get(self.axis_name, 1))

    @property
    def enabled(self) -> bool:
        """Quantized routing only engages with a real ring; "none" (or
        a 1-wide axis) keeps the model on today's implicit-psum path,
        bit-identical."""
        return self.qtype != "none" and self.axis_size > 1

    def tol(self) -> float:
        if self.tolerance is not None:
            return float(self.tolerance)
        return TOLERANCE[self.qtype]


# ---------------------------------------------------------------------------
# codec: per-block absmax scales over a flat padded payload
# ---------------------------------------------------------------------------


def _encode(x: jax.Array, qtype: str, block_size: int):
    """Block-quantize a flat fp32 payload (length % block_size == 0).

    Returns (data, scales): int8 or fp8_e4m3 data of x's shape plus one
    float16 absmax scale per block — `quant/numerics.py`'s symmetric
    per-block format at a comm-tuned block size."""
    xb = x.reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(xb), axis=-1)
    if qtype == "int8":
        d = absmax / 127.0
        data = jnp.clip(
            jnp.round(xb * _safe_inv(d)[:, None]), -127, 127
        ).astype(jnp.int8)
    elif qtype == "fp8_e4m3":
        d = absmax / _FP8_MAX["fp8_e4m3"]
        data = (xb * _safe_inv(d)[:, None]).astype(_FP8_DTYPE["fp8_e4m3"])
    else:
        raise ValueError(f"not a quantized comm format: {qtype!r}")
    return data.reshape(x.shape), d.astype(jnp.float16)


def _decode(data: jax.Array, scales: jax.Array, block_size: int) -> jax.Array:
    xb = data.astype(jnp.float32).reshape(-1, block_size)
    out = xb * scales.astype(jnp.float32)[:, None]
    return out.reshape(data.shape)


def _flatten_pad(x: jax.Array, multiple: int):
    """Flatten to fp32 and zero-pad to a length multiple (ragged last
    block: numerics._blocked refuses ragged dims, comms must not)."""
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % multiple
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat, n


def encode_array(x: jax.Array, qtype: str, block_size: int = DEFAULT_BLOCK):
    """Codec over an arbitrary-shape array (ring-attention k/v payloads,
    weight shards): flatten, pad, block-quantize once."""
    flat, _ = _flatten_pad(x, block_size)
    return _encode(flat, qtype, block_size)


def decode_array(data: jax.Array, scales: jax.Array, shape, dtype,
                 block_size: int = DEFAULT_BLOCK) -> jax.Array:
    n = 1
    for s in shape:
        n *= s
    flat = _decode(data, scales, block_size)[:n]
    return flat.reshape(shape).astype(dtype)


# ---------------------------------------------------------------------------
# device-local collectives (call inside _compat.shard_map)
# ---------------------------------------------------------------------------


def quantized_reduce_scatter(x: jax.Array, axis_name: str = "tp",
                             qtype: str = "int8",
                             axis_size: Optional[int] = None,
                             block_size: int = DEFAULT_BLOCK,
                             error_feedback: bool = True) -> jax.Array:
    """The reduce-scatter half of the ring: rank ``r`` returns the
    fully-reduced chunk ``r`` of `x` flattened and zero-padded to
    ``n * ceil(size / (n*block))`` — fp32, [padded_size / n].

    At hop h (1..n-1) rank r forwards the quantized partial for chunk
    (r-h) mod n and receives + accumulates chunk (r-h-1) mod n. With
    `error_feedback` the residual of rank r's hop-h encode rides into
    its hop-h+1 payload, telescoping the injected error around the ring
    so the AGGREGATE codec error stays at ~n dropped residuals instead
    of the n*(n-1) quantization events of the feedback-free ring — the
    sense in which error "does not compound with hop count"
    (tests/test_qcollectives.py measures exactly this)."""
    qt = resolve_comm_qtype(qtype)
    n = int(axis_size if axis_size is not None
            else jax.lax.psum(1, axis_name))
    me = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    flat, _ = _flatten_pad(x, n * block_size)
    chunks = flat.reshape(n, flat.shape[0] // n)
    if qt == "none":
        red = jax.lax.psum(chunks, axis_name)
        return jax.lax.dynamic_index_in_dim(red, me, 0, keepdims=False)

    def rs_step(carry, k):
        partial, err = carry
        v = partial + err if error_feedback else partial
        data, scales = _encode(v, qt, block_size)
        if error_feedback:
            err = v - _decode(data, scales, block_size)
        data = jax.lax.ppermute(data, axis_name, perm)
        scales = jax.lax.ppermute(scales, axis_name, perm)
        recv = _decode(data, scales, block_size)
        local = jax.lax.dynamic_index_in_dim(
            chunks, (me - k - 2) % n, axis=0, keepdims=False
        )
        return (recv + local, err), None

    p0 = jax.lax.dynamic_index_in_dim(
        chunks, (me - 1) % n, axis=0, keepdims=False
    )
    (own, _), _ = jax.lax.scan(
        rs_step, (p0, jnp.zeros_like(p0)), jnp.arange(n - 1)
    )
    return own


def quantized_psum(x: jax.Array, axis_name: str = "tp",
                   qtype: str = "int8", axis_size: Optional[int] = None,
                   block_size: int = DEFAULT_BLOCK,
                   error_feedback: bool = True) -> jax.Array:
    """All-reduce `x` over `axis_name` through the quantized ring.

    Reduce-scatter with per-rank error feedback, then a single-encode
    all-gather (module docstring has the hop math). ``qtype="none"``
    is exactly ``jax.lax.psum``. `error_feedback=False` exists for the
    property test that shows feedback is what keeps the ring's
    aggregate error hop-count independent — production paths leave it
    on."""
    qt = resolve_comm_qtype(qtype)
    if qt == "none":
        return jax.lax.psum(x, axis_name)
    n = int(axis_size if axis_size is not None
            else jax.lax.psum(1, axis_name))
    if n == 1:
        return x
    me = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    flat, nelem = _flatten_pad(x, n * block_size)
    chunks = flat.reshape(n, flat.shape[0] // n)
    own = quantized_reduce_scatter(
        x, axis_name, qtype=qt, axis_size=n, block_size=block_size,
        error_feedback=error_feedback,
    )

    # all-gather: encode the owned chunk ONCE and forward the payload;
    # every rank (owner included) uses the decoded version, so outputs
    # are bit-identical across the ring.
    data, scales = _encode(own, qt, block_size)
    out = jnp.zeros_like(chunks)
    out = out.at[me].set(_decode(data, scales, block_size))

    def ag_step(carry, g):
        acc, d, s = carry
        d = jax.lax.ppermute(d, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        # after g+1 forwards we hold the chunk owned by rank me-g-1
        acc = acc.at[(me - g - 1) % n].set(_decode(d, s, block_size))
        return (acc, d, s), None

    (out, _, _), _ = jax.lax.scan(
        ag_step, (out, data, scales), jnp.arange(n - 1)
    )
    return out.reshape(-1)[:nelem].reshape(x.shape).astype(x.dtype)


def quantized_all_gather(x: jax.Array, axis_name: str = "tp",
                         qtype: str = "int8",
                         axis_size: Optional[int] = None,
                         block_size: int = DEFAULT_BLOCK,
                         tiled: bool = False) -> jax.Array:
    """All-gather `x` over `axis_name` with block-quantized payloads
    (PP/multihost weight and KV-page distribution). Each shard encodes
    ONCE; payloads ride the ring n-1 hops unchanged, so every rank
    decodes identical bytes. ``qtype="none"`` is ``jax.lax.all_gather``."""
    qt = resolve_comm_qtype(qtype)
    if qt == "none":
        return jax.lax.all_gather(x, axis_name, tiled=tiled)
    n = int(axis_size if axis_size is not None
            else jax.lax.psum(1, axis_name))
    me = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    flat, _ = _flatten_pad(x, block_size)
    data, scales = _encode(flat, qt, block_size)

    def as_x(d, s):
        return decode_array(d, s, x.shape, x.dtype, block_size)

    out = jnp.zeros((n,) + tuple(x.shape), x.dtype)
    out = out.at[me].set(as_x(data, scales))

    def step(carry, g):
        acc, d, s = carry
        d = jax.lax.ppermute(d, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        acc = acc.at[(me - g - 1) % n].set(as_x(d, s))
        return (acc, d, s), None

    (out, _, _), _ = jax.lax.scan(
        step, (out, data, scales), jnp.arange(n - 1)
    )
    if tiled:
        out = out.reshape((n * x.shape[0],) + tuple(x.shape[1:]))
    return out


# ---------------------------------------------------------------------------
# whole-array wrappers (parity tests, dryrun harness)
# ---------------------------------------------------------------------------


def mesh_all_reduce(xs: jax.Array, mesh: Mesh, axis_name: str = "tp",
                    qtype: str = "int8",
                    block_size: int = DEFAULT_BLOCK,
                    error_feedback: bool = True) -> jax.Array:
    """Reduce stacked per-rank partials ``xs[i]`` (leading axis =
    ``mesh.shape[axis_name]``) through the quantized ring; returns the
    same stacked shape with every row holding the reduced result — the
    parity-test harness for `quantized_psum` on dp×sp×tp meshes."""
    n = int(mesh.shape[axis_name])
    if xs.shape[0] != n:
        raise ValueError(
            f"xs leading axis {xs.shape[0]} != mesh {axis_name}={n}"
        )
    spec = P(axis_name, *([None] * (xs.ndim - 1)))

    def body(local):
        red = quantized_psum(
            local[0], axis_name, qtype=qtype, axis_size=n,
            block_size=block_size, error_feedback=error_feedback,
        )
        return red[None]

    f = _shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec,
                   check_vma=False)
    return f(xs)


def mesh_reduce_scatter(xs: jax.Array, mesh: Mesh, axis_name: str = "tp",
                        qtype: str = "int8",
                        block_size: int = DEFAULT_BLOCK,
                        error_feedback: bool = True) -> jax.Array:
    """Reduce stacked per-rank partials ``xs[i]`` and return the
    reassembled flat reduced vector (chunk r from rank r, concatenated;
    zero-padding included) — the error-feedback property test's view of
    the reduce-scatter half in isolation."""
    n = int(mesh.shape[axis_name])
    if xs.shape[0] != n:
        raise ValueError(
            f"xs leading axis {xs.shape[0]} != mesh {axis_name}={n}"
        )
    spec = P(axis_name, *([None] * (xs.ndim - 1)))

    def body(local):
        own = quantized_reduce_scatter(
            local[0], axis_name, qtype=qtype, axis_size=n,
            block_size=block_size, error_feedback=error_feedback,
        )
        return own[None]

    f = _shard_map(body, mesh=mesh, in_specs=(spec,),
                   out_specs=P(axis_name, None), check_vma=False)
    return f(xs).reshape(-1)


def mesh_all_gather(x: jax.Array, mesh: Mesh, axis_name: str = "tp",
                    qtype: str = "none",
                    block_size: int = DEFAULT_BLOCK) -> jax.Array:
    """Replicate an axis-0-sharded array (a weight shard table, a KV
    page pool) via the quantized ring all-gather: every device ends up
    holding the full array, paying quantized instead of fp32 bytes on
    the wire."""
    n = int(mesh.shape[axis_name])
    if x.shape[0] % n:
        raise ValueError(
            f"axis 0 ({x.shape[0]}) not divisible by {axis_name}={n}"
        )
    spec = P(axis_name, *([None] * (x.ndim - 1)))

    def body(local):
        return quantized_all_gather(
            local, axis_name, qtype=qtype, axis_size=n,
            block_size=block_size, tiled=True,
        )

    f = _shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=P(),
                   check_vma=False)
    return f(x)
