"""jax API-spelling compat for the pinned jax (same role as
ops/pallas/_compat.py, for the sharding layer).

Newer jax promoted `shard_map` to `jax.shard_map` and renamed its
kwargs (`check_rep` -> `check_vma`, manual axes declared via
`axis_names`); jax 0.4.37 ships it at
`jax.experimental.shard_map.shard_map` with the old spelling. The
pipeline-parallel forward, ring attention, and the QLoRA ring-mesh
train path were all failing with AttributeError on the pinned jax (11
tier-1 tests). One translating wrapper here so every call site can use
the NEW spelling and keep working when jax is upgraded.
"""

from __future__ import annotations

import jax

if hasattr(jax, "set_mesh"):
    set_mesh = jax.set_mesh
else:
    def set_mesh(mesh):
        """0.4.37 spelling of the ambient-mesh context: `jax.sharding.
        Mesh` IS a context manager (`with mesh:`), which is what resolves
        bare PartitionSpecs in with_sharding_constraint / shard_map on
        the pinned jax. Returning the mesh keeps `with set_mesh(m):`
        call sites working under both spellings."""
        return mesh


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        """New-API facade over the 0.4.37 experimental shard_map:
        check_vma -> check_rep; axis_names (manual axes) -> auto (the
        complement over the mesh). Usable positionally or as a
        functools.partial-style decorator, like the new jax.shard_map."""
        if f is None:
            return lambda g: shard_map(
                g, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma, axis_names=axis_names,
            )
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
        if axis_names is not None:
            # only axes with real extent go to `auto`: treating size-1
            # axes as (trivially) manual is semantically identical and
            # keeps the common pp-only mesh on the plain shard_map path —
            # 0.4.37's auto-mode lowers axis_index to a PartitionId
            # instruction its SPMD partitioner then rejects
            auto = frozenset(n for n in mesh.axis_names
                             if n not in axis_names and mesh.shape[n] > 1)
            if auto:
                kw["auto"] = auto
        return _shard_map(f, **kw)
