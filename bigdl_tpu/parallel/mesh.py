"""Mesh construction.

The reference picks a process backend per feature (oneCCL for PP, Ray
for vLLM TP, MPI for k8s training — SURVEY.md §2.3). Here every feature
shares one `jax.sharding.Mesh`; choosing a parallelism strategy is
choosing a mesh shape.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "sp", "tp")


def mesh_shape_for(
    n_devices: int,
    tp: Optional[int] = None,
    sp: int = 1,
    dp: Optional[int] = None,
) -> tuple[int, int, int]:
    """Resolve a (dp, sp, tp) shape for n_devices.

    Default policy: everything tensor-parallel (inference-friendly on one
    slice — weights shard, activations replicate), dp=sp=1.
    """
    if tp is None:
        if dp is None:
            tp, dp = n_devices // sp, 1
        else:
            tp = n_devices // (dp * sp)
    if dp is None:
        dp = n_devices // (tp * sp)
    if dp * sp * tp != n_devices:
        raise ValueError(f"dp*sp*tp = {dp}*{sp}*{tp} != {n_devices} devices")
    return dp, sp, tp


def make_mesh(
    shape: Optional[tuple[int, ...]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axes: Sequence[str] = AXES,
) -> Mesh:
    """Create a mesh (default axes (dp, sp, tp); pass axes=("pp", ...) etc.
    for pipeline topologies). The last axis is fastest-varying so that
    tensor-parallel collectives ride neighboring ICI links."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = mesh_shape_for(len(devices))
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axes))
