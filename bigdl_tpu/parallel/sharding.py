"""Sharding rules for model parameter trees.

Megatron-style tensor parallelism, expressed as PartitionSpecs instead of
the reference's sharded-module detection + explicit all-reduce
(convert.py:152-234, low_bit_linear.py:675-682):

- q/k/v/gate/up projections: column-parallel (output features on `tp`)
- o/down projections: row-parallel (input features on `tp`; XLA inserts
  the psum the reference calls `mp_group.all_reduce`)
- embedding + lm head: vocab on `tp` (logit psum likewise automatic)
- norms, biases of row-parallel layers: replicated

A QTensor shards with the SAME spec for codes/scales/mins because all
three carry the block structure along the same axes; quantization blocks
(32/64 elems) always divide per-shard contraction dims for real model
sizes, so no cross-shard block ever straddles a boundary.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor

# Layer weights have a leading stacked-layer axis (dim 0).
_COL = P(None, "tp", None)  # [L, out/tp, in]
_ROW = P(None, None, "tp")  # [L, out, in/tp]
_REP = P()


def layer_specs(config: ModelConfig) -> dict:
    specs = {
        "attn_norm": _REP,
        "mlp_norm": _REP,
        "wq": _COL,
        "wk": _COL,
        "wv": _COL,
        # merged layout (models/llama.merge_fused_params): still
        # column-parallel — GSPMD reshards the post-split slices as needed
        "wqkv": _COL,
        "bqkv": P(None, "tp"),
        "w_gateup": _COL,
        "b_gateup": P(None, "tp"),
        "wo": _ROW,
    }
    if config.is_moe:
        # experts sharded over 'tp' (expert parallelism: each shard holds
        # E/tp full experts; the combine einsum psums over the axis)
        specs.update({
            "router": _REP,
            "w_gate_e": P(None, "tp", None, None),
            "w_up_e": P(None, "tp", None, None),
            "w_down_e": P(None, "tp", None, None),
            # phixtral non-gated expert biases ride the expert axis
            "b_up_e": P(None, "tp", None),
            "b_down_e": P(None, "tp", None),
        })
        if config.shared_expert_intermediate_size:
            specs.update({
                "w_gate_s": _COL, "w_up_s": _COL, "w_down_s": _ROW,
                "shared_gate": _REP,
            })
    elif config.gated_mlp:
        specs.update({"w_gate": _COL, "w_up": _COL, "w_down": _ROW})
    else:
        specs.update({"w_up": _COL, "w_down": _ROW})
    if config.attention_bias:
        specs.update({"bq": P(None, "tp"), "bk": P(None, "tp"), "bv": P(None, "tp")})
    if config.attention_out_bias:
        specs["bo"] = _REP
    if config.mlp_bias:
        if config.gated_mlp:
            specs["b_gate"] = P(None, "tp")
        specs["b_up"] = P(None, "tp")
        specs["b_down"] = _REP
    if config.norm_bias:
        specs.update({"attn_norm_b": _REP, "mlp_norm_b": _REP})
    if config.post_attn_norm:
        specs.update({"post_attn_norm": _REP, "post_mlp_norm": _REP})
    if config.qk_norm:
        specs.update({"q_norm": _REP, "k_norm": _REP})
    return specs


def _mla_attn_specs() -> dict:
    """MLA (models/deepseek.py): heads shard over tp on the q side and
    the absorbed per-head factors; the kv LATENT is shared across heads
    (MQA-like) so its down-projection replicates."""
    return {
        "attn_norm": _REP, "mlp_norm": _REP,
        "wq": _COL, "w_uq": _COL,  # [L, H*(dn+dr), ·] — heads on tp
        "w_dq": _REP, "q_norm": _REP,
        "w_dkv": _REP, "kv_norm": _REP,
        "w_uk": P(None, "tp", None, None),  # [L, H, dn, r]
        "w_uv": P(None, "tp", None, None),
        "wo": _ROW,  # [L, hid, H*dv]
    }


def _deepseek_specs(config: ModelConfig) -> dict:
    dense = dict(_mla_attn_specs())
    dense.update({"w_gate": _COL, "w_up": _COL, "w_down": _ROW})
    specs = {"layers": dense}
    if config.is_moe:
        moe = dict(_mla_attn_specs())
        moe.update({
            "router": _REP, "e_bias": _REP,
            "w_gate_e": P(None, "tp", None, None),
            "w_up_e": P(None, "tp", None, None),
            "w_down_e": P(None, "tp", None, None),
            "w_gate_s": _COL, "w_up_s": _COL, "w_down_s": _ROW,
        })
        specs["moe_layers"] = moe
    return specs


def _rwkv_specs(config: ModelConfig) -> dict:
    """RWKV (models/rwkv.py): the channel axis A shards over tp — the
    WKV recurrence is elementwise over A, so decay/first shard with it;
    mix vectors and norms (over the residual C) replicate."""
    v5 = config.rwkv_head_size is not None
    layers = {
        "ln1_w": _REP, "ln1_b": _REP, "ln2_w": _REP, "ln2_b": _REP,
        "att_mix_k": _REP, "att_mix_v": _REP, "att_mix_r": _REP,
        "att_k": _COL, "att_v": _COL, "att_r": _COL,
        "att_o": _ROW,
        "att_decay": P(None, "tp", None) if v5 else P(None, "tp"),
        "att_first": P(None, "tp", None) if v5 else P(None, "tp"),
        "ffn_mix_k": _REP, "ffn_mix_r": _REP,
        "ffn_k": _COL, "ffn_r": _COL, "ffn_v": _ROW,
    }
    if v5:
        layers.update({"att_mix_g": _REP, "att_g": _COL,
                       "ln_x_w": _REP, "ln_x_b": _REP})
    return {"layers": layers}


def _yuan_extra_specs() -> dict:
    """Yuan LFA filter (models/yuan.py): conv stage 1 column-parallel,
    stage 2 row-parallel; the filter norm replicates."""
    return {
        "lf_w1a": _COL, "lf_w1b": _COL, "lf_b1": P(None, "tp"),
        "lf_w2a": _ROW, "lf_w2b": _ROW, "lf_b2": _REP,
        "lf_norm": _REP,
    }


def param_specs(config: ModelConfig, tie_word_embeddings: bool | None = None) -> dict:
    tie = config.tie_word_embeddings if tie_word_embeddings is None else tie_word_embeddings
    specs = {
        "embed": P("tp", None),
        "final_norm": _REP,
    }
    mt = config.model_type
    if mt in ("deepseek_v2", "deepseek_v3", "minicpm3"):
        specs.update(_deepseek_specs(config))
    elif mt in ("rwkv", "rwkv5"):
        specs.update(_rwkv_specs(config))
        specs.update({"ln0_w": _REP, "ln0_b": _REP, "final_norm_b": _REP})
    else:
        specs["layers"] = layer_specs(config)
        if mt == "yuan":
            specs["layers"].update(_yuan_extra_specs())
        if mt in ("mllama", "mllama_text_model"):
            specs["cross"] = {
                "attn_norm": _REP, "mlp_norm": _REP,
                "wq": _COL, "wk": _COL, "wv": _COL, "wo": _ROW,
                "q_norm": _REP, "k_norm": _REP,
                "attn_gate": _REP, "mlp_gate": _REP,
                "w_gate": _COL, "w_up": _COL, "w_down": _ROW,
            }
    if config.norm_bias:
        specs["final_norm_b"] = _REP
    if config.learned_positions:
        specs["wpe"] = _REP
    if config.embed_layernorm:
        specs.update({"embed_norm": _REP, "embed_norm_b": _REP})
    if not tie:
        specs["lm_head"] = P("tp", None)
        # phi: the lm head bias shards with its output (vocab) axis
        specs["lm_head_b"] = P("tp") if config.lm_head_bias else _REP
    return specs


def lora_specs(config: ModelConfig, targets: tuple[str, ...]) -> dict:
    """LoRA A is row-sharded like the base weight's contraction axis only
    when the base is row-parallel; keep both factors replicated except the
    dimension that matches the base weight's tp axis."""
    col_targets = {"wq", "wk", "wv", "w_gate", "w_up"}
    layers = {}
    for t in targets:
        if t in col_targets:
            layers[t] = {"a": _REP, "b": P(None, "tp", None)}  # b: [L, out/tp, r]
        else:  # row-parallel base: shard A's input dim
            layers[t] = {"a": P(None, None, "tp"), "b": _REP}
    return {"layers": layers, "scale": _REP}


def expand_specs_for_params(specs, params, wrap=lambda spec: spec):
    """Match a per-leaf spec tree against `params`' exact structure:
    QTensor pytree nodes expand field-wise (data/scales share the spec,
    mins only when present), and spec dicts are pruned to the keys the
    params actually carry (layer_specs lists both the split and merged
    qkv/gate-up layouts; a tree holds one or the other). `wrap` maps each
    spec to its final leaf (e.g. NamedSharding). The ONE place this
    QTensor trick lives — used by sharding_tree and both pipeline spec
    builders."""

    def replicate_like(p):
        if isinstance(p, dict):
            return {k: replicate_like(v) for k, v in p.items()}
        return _REP

    def prune(s, p):
        if isinstance(s, dict) and isinstance(p, dict):
            # params keys without a spec REPLICATE (correct for any
            # family; a dedicated spec is a performance upgrade, its
            # absence must never be a crash)
            return {
                k: prune(s[k], p[k]) if k in s else replicate_like(p[k])
                for k in p.keys()
            }
        return s

    specs = prune(specs, params)

    def expand(spec, param):
        if isinstance(param, QTensor):
            w = wrap(spec)
            return param.map_arrays(lambda _: w)
        return wrap(spec)

    return jax.tree.map(
        expand, specs, params, is_leaf=lambda x: isinstance(x, P)
    )


def sharding_tree(specs: dict, mesh: Mesh, params) -> dict:
    """Expand a PartitionSpec tree into a NamedSharding tree exactly
    matching `params` structure (QTensor nodes expand field-wise)."""
    return expand_specs_for_params(
        specs, params, wrap=lambda spec: NamedSharding(mesh, spec)
    )


def shard_params(params, specs: dict, mesh: Mesh):
    """Place a param tree onto the mesh (host → sharded device buffers)."""
    return jax.device_put(params, sharding_tree(specs, mesh, params))


def gather_array(x, mesh: Mesh, axis_name: str = "tp",
                 comm_qtype: str = "none"):
    """Replicate an axis-0-sharded array to every device along
    `axis_name` — PP/multihost weight distribution and KV-page handout.

    With a quantized `comm_qtype` ("int8"|"fp8_e4m3") the wire format
    is the block-scaled ring all-gather of parallel/qcollectives.py
    (each shard encodes once, payloads forward unchanged, every rank
    decodes identical bytes) instead of GSPMD's fp32/bf16 all-gather —
    the bandwidth-bound half of the multi-chip story, priced by
    `benchmark/roofline.all_gather_cost`."""
    from bigdl_tpu.parallel.qcollectives import mesh_all_gather

    return mesh_all_gather(x, mesh, axis_name=axis_name, qtype=comm_qtype)
