"""Sharding rules for model parameter trees.

Megatron-style tensor parallelism, expressed as PartitionSpecs instead of
the reference's sharded-module detection + explicit all-reduce
(convert.py:152-234, low_bit_linear.py:675-682):

- q/k/v/gate/up projections: column-parallel (output features on `tp`)
- o/down projections: row-parallel (input features on `tp`; XLA inserts
  the psum the reference calls `mp_group.all_reduce`)
- embedding + lm head: vocab on `tp` (logit psum likewise automatic)
- norms, biases of row-parallel layers: replicated

A QTensor shards with the SAME spec for codes/scales/mins because all
three carry the block structure along the same axes; quantization blocks
(32/64 elems) always divide per-shard contraction dims for real model
sizes, so no cross-shard block ever straddles a boundary.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor

# Layer weights have a leading stacked-layer axis (dim 0).
_COL = P(None, "tp", None)  # [L, out/tp, in]
_ROW = P(None, None, "tp")  # [L, out, in/tp]
_REP = P()


def layer_specs(config: ModelConfig) -> dict:
    specs = {
        "attn_norm": _REP,
        "mlp_norm": _REP,
        "wq": _COL,
        "wk": _COL,
        "wv": _COL,
        "wo": _ROW,
        "w_gate": _COL,
        "w_up": _COL,
        "w_down": _ROW,
    }
    if config.attention_bias:
        specs.update({"bq": P(None, "tp"), "bk": P(None, "tp"), "bv": P(None, "tp")})
    return specs


def param_specs(config: ModelConfig, tie_word_embeddings: bool | None = None) -> dict:
    tie = config.tie_word_embeddings if tie_word_embeddings is None else tie_word_embeddings
    specs = {
        "embed": P("tp", None),
        "layers": layer_specs(config),
        "final_norm": _REP,
    }
    if not tie:
        specs["lm_head"] = P("tp", None)
    return specs


def lora_specs(config: ModelConfig, targets: tuple[str, ...]) -> dict:
    """LoRA A is row-sharded like the base weight's contraction axis only
    when the base is row-parallel; keep both factors replicated except the
    dimension that matches the base weight's tp axis."""
    col_targets = {"wq", "wk", "wv", "w_gate", "w_up"}
    layers = {}
    for t in targets:
        if t in col_targets:
            layers[t] = {"a": _REP, "b": P(None, "tp", None)}  # b: [L, out/tp, r]
        else:  # row-parallel base: shard A's input dim
            layers[t] = {"a": P(None, None, "tp"), "b": _REP}
    return {"layers": layers, "scale": _REP}


def sharding_tree(specs: dict, mesh: Mesh, params) -> dict:
    """Expand a PartitionSpec tree into a NamedSharding tree exactly
    matching `params` structure (QTensor nodes expand field-wise)."""

    def expand(spec, param):
        if isinstance(param, QTensor):
            ns = NamedSharding(mesh, spec)
            return QTensor(
                data=ns,
                scales=ns,
                mins=None if param.mins is None else ns,
                qtype=param.qtype,
            )
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        expand, specs, params, is_leaf=lambda x: isinstance(x, P)
    )


def shard_params(params, specs: dict, mesh: Mesh):
    """Place a param tree onto the mesh (host → sharded device buffers)."""
    return jax.device_put(params, sharding_tree(specs, mesh, params))
