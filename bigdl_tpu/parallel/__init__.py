"""Distributed execution over a device mesh.

Replaces the reference's entire parallelism stack — DeepSpeed-AutoTP
tensor parallel (convert.py:152-234 + all-reduce in
low_bit_linear.py:675-682), its own pipeline-parallel token loop
(pipeline_parallel.py:300-446), and the oneCCL/MPI/Ray process backends
(SURVEY.md §2.3) — with **one GSPMD mesh**: parameters and activations
carry `NamedSharding`s, XLA inserts the collectives (psum over ICI for
row-parallel matmuls, all-gathers for sequence shards), and multi-host
launch is `jax.distributed.initialize` instead of MPI.

Axes:
    dp — data parallel (batch)
    tp — tensor parallel (megatron-style column/row sharded linears)
    sp — sequence parallel (activation sequence dim; ring attention later)
"""

from bigdl_tpu.parallel.health import (
    HealthMonitor,
    RankDropError,
    anomaly_consensus,
    consensus_any,
    init_multihost_with_retry,
)
from bigdl_tpu.parallel.mesh import make_mesh, mesh_shape_for
from bigdl_tpu.parallel.multihost import host_aware_mesh, init_multihost
from bigdl_tpu.parallel.qcollectives import (
    COMM_QTYPES,
    CommConfig,
    quantized_all_gather,
    quantized_psum,
    quantized_reduce_scatter,
    resolve_comm_qtype,
)
from bigdl_tpu.parallel.sharding import (
    layer_specs,
    param_specs,
    shard_params,
    sharding_tree,
)

__all__ = [
    "COMM_QTYPES",
    "CommConfig",
    "HealthMonitor",
    "RankDropError",
    "anomaly_consensus",
    "consensus_any",
    "host_aware_mesh",
    "init_multihost",
    "init_multihost_with_retry",
    "make_mesh",
    "mesh_shape_for",
    "param_specs",
    "layer_specs",
    "quantized_all_gather",
    "quantized_psum",
    "quantized_reduce_scatter",
    "resolve_comm_qtype",
    "shard_params",
    "sharding_tree",
]
