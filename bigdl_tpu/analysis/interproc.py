"""graftlint v2 rule families: interprocedural PAGE / LCK / DSP checks.

These checks consume :mod:`bigdl_tpu.analysis.flow`'s project-wide
symbol table, call graph, and summaries instead of a single file's AST.
Each still implements the plain :class:`~bigdl_tpu.analysis.core.Check`
protocol — ``run(ctx)`` emits findings for *ctx*'s file only — so the
suppression/baseline/CLI machinery from PR 12 applies unchanged.  The
project analysis is computed once per root and cached (flow.py), so the
per-file cost is a dictionary lookup plus this file's share of results.

Rule map (details + examples in docs/static-analysis.md):

- PAGE001  page ref leaks on a normal exit (return / fall-off)
- PAGE002  page refs live across a may-raise call with no enclosing try
- LCK101   lock-order cycle (two witness call paths reported)
- LCK102   blocking call (fsync/flush/sleep/host transfer) under a hot
           lock (``_stat_lock`` / ``_admission_lock``)
- DSP001   registered qtype missing from the GEMV dispatch table (or a
           dispatch key naming an unregistered qtype); table entry with
           neither a fused backward kernel nor a stated bwd_exempt
- DSP002   ``from bigdl_tpu.ops.pallas import X`` where X is not
           exported by the kernel package
- DSP003   dispatch k_multiple (forward or bwd_k_multiple) incompatible
           with the qtype's block/superblock geometry; DecodeSpec
           storage not covered
- DSP004   VMEM-budget magic number drifted from tiling.py's constants
- DSP005   tiling.py budget invariants (caps, lane alignment) violated
- DSP006   attention epilogue decodes K/V tiles inline instead of
           through the shared qdecode.decode_kv body
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Check, FileContext, Finding
from . import flow

# ---------------------------------------------------------------------------
# PAGE family.


class PageLeakOnExit(Check):
    rule = "PAGE001"
    description = (
        "page ref acquired (PagePool.alloc/incref) but not released or "
        "ownership-transferred on every normal exit path"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if "alloc(" not in ctx.src and ".incref(" not in ctx.src:
            return
        project = flow.project_for(ctx)
        for fi, leak in flow.page_leaks_for_module(project, ctx.rel):
            if leak.rule != self.rule:
                continue
            yield Finding(
                rule=self.rule, path=ctx.rel, line=leak.line,
                message="in %s: %s" % (fi.node.name, leak.detail),
                hint="decref on this path, append into the owning "
                     "table/list before exiting, or return the ref "
                     "to the caller",
            )


class PageLeakOnRaise(Check):
    rule = "PAGE002"
    description = (
        "page refs live across a may-raise call (storage write, host "
        "transfer, raising callee) with no enclosing try to roll back"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if "alloc(" not in ctx.src and ".incref(" not in ctx.src:
            return
        project = flow.project_for(ctx)
        for fi, leak in flow.page_leaks_for_module(project, ctx.rel):
            if leak.rule != self.rule:
                continue
            yield Finding(
                rule=self.rule, path=ctx.rel, line=leak.line,
                message="in %s: %s" % (fi.node.name, leak.detail),
                hint="wrap the faultable call in try/except, decref "
                     "the held refs in the handler, and re-raise",
            )


# ---------------------------------------------------------------------------
# LCK family.
#
# The lock analysis is whole-project; each check filters the shared
# report down to sites in ctx's file so findings stay file-anchored
# (and suppressions / baseline entries work per-site as usual).


class LockOrderCycle(Check):
    rule = "LCK101"
    description = (
        "lock-order cycle: two call paths acquire the same locks in "
        "opposite order (deadlock when the threads interleave)"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if "Lock(" not in ctx.src and "RLock(" not in ctx.src \
                and "with self." not in ctx.src:
            return
        project = flow.project_for(ctx)
        report = flow.lock_report(project)
        for site in report.self_deadlocks:
            if site.rel != ctx.rel:
                continue
            yield Finding(
                rule=self.rule, path=ctx.rel, line=site.line,
                message="re-acquisition of non-reentrant lock %s in %s "
                        "(already held on this call path) deadlocks"
                        % (site.lock, site.func),
                hint="make the inner call a _locked variant, or declare "
                     "the lock RLock if re-entry is intended",
            )
        for edges in report.cycles:
            # Anchor the cycle at each in-file witness edge (usually
            # one); the message carries every witness path.
            witnesses = "; ".join(e.witness for e in edges)
            order = " -> ".join([edges[0].held] +
                                [e.acquired for e in edges])
            for e in edges:
                rel, line = _witness_site(e)
                if rel != ctx.rel:
                    continue
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=line,
                    message="lock-order cycle %s; witnesses: %s"
                            % (order, witnesses),
                    hint="pick one global order for these locks and "
                         "restructure the call path that violates it "
                         "(move work outside the outer lock)",
                )


def _witness_site(edge: "flow.LockEdge") -> Tuple[str, int]:
    # witness text ends with "... at rel:line (holding X)"
    try:
        at = edge.witness.rsplit(" at ", 1)[1]
        loc = at.split(" ", 1)[0]
        rel, line = loc.rsplit(":", 1)
        return rel, int(line)
    except (IndexError, ValueError):  # pragma: no cover - defensive
        return "", 0


class BlockingUnderHotLock(Check):
    rule = "LCK102"
    description = (
        "blocking call (fsync/flush/sleep/host transfer, or a callee "
        "that transitively blocks) made while holding a hot serving "
        "lock (_stat_lock/_admission_lock)"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if "_stat_lock" not in ctx.src and "_admission_lock" not in ctx.src:
            return
        project = flow.project_for(ctx)
        report = flow.lock_report(project)
        seen: Set[Tuple[int, str]] = set()
        for site, desc in report.blocking_under_hot:
            if site.rel != ctx.rel or (site.line, desc) in seen:
                continue
            seen.add((site.line, desc))
            yield Finding(
                rule=self.rule, path=ctx.rel, line=site.line,
                message="blocking call '%s' under hot lock %s (in %s): "
                        "every scrape/submit convoys behind it"
                        % (desc, site.lock, site.func),
                hint="snapshot state under the lock, do the blocking "
                     "work after releasing it",
            )


# ---------------------------------------------------------------------------
# DSP family.

_QTYPES_REL = "bigdl_tpu/quant/qtypes.py"
_LINEAR_REL = "bigdl_tpu/ops/linear.py"
_TILING_REL = "bigdl_tpu/ops/pallas/tiling.py"
_QDECODE_REL = "bigdl_tpu/ops/pallas/qdecode.py"
_PALLAS_INIT_REL = "bigdl_tpu/ops/pallas/__init__.py"
_QMATMUL_REL = "bigdl_tpu/ops/pallas/qmatmul.py"


def _registry_specs(project: "flow.Project") -> Dict[str, Dict[str, object]]:
    """qtype name -> literal QTypeSpec kwargs, from qtypes.py's
    ``_register(QTypeSpec(...))`` calls."""
    mod = project.modules.get(_QTYPES_REL)
    out: Dict[str, Dict[str, object]] = {}
    if mod is None:
        return out
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "_register" and node.args):
            continue
        spec = node.args[0]
        if not (isinstance(spec, ast.Call)
                and isinstance(spec.func, ast.Name)
                and spec.func.id == "QTypeSpec"):
            continue
        kwargs: Dict[str, object] = {
            "bits": None, "block_size": None, "storage": "packed_u8",
            "planes": (), "superblock": 0, "line": spec.lineno,
        }
        pos_names = ("name", "bits", "block_size")
        for i, arg in enumerate(spec.args[:3]):
            try:
                kwargs[pos_names[i]] = flow.eval_const(arg)
            except ValueError:
                pass
        for kw in spec.keywords:
            if kw.arg is None:
                continue
            try:
                kwargs[kw.arg] = flow.eval_const(kw.value)
            except ValueError:
                pass
        name = kwargs.get("name")
        if isinstance(name, str):
            out[name] = kwargs
    return out


def _gemv_table(tree: ast.Module) -> Tuple[Optional[int],
                                           Dict[str, Tuple[int, int]]]:
    """(dict lineno, {qtype: (k_multiple, entry lineno)}) from the
    ``_QGEMV_QTYPES = {...}`` literal in linear.py."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_QGEMV_QTYPES"
                and isinstance(node.value, ast.Dict)):
            table: Dict[str, Tuple[int, int]] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                k_multiple = -1
                if isinstance(v, ast.Call) and v.args:
                    try:
                        k_multiple = int(flow.eval_const(v.args[0]))
                    except (ValueError, TypeError):
                        k_multiple = -1
                table[k.value] = (k_multiple, k.lineno)
            return node.lineno, table
    return None, {}


#: _GemvEntry field order (positional-arg mapping for the resolvers
#: below); kept in sync by test_dsp001_field_order_matches_linear.
_GEMV_FIELDS = ("k_multiple", "run", "gemm", "gemm_exempt",
                "bwd", "bwd_exempt", "bwd_k_multiple")


def _gemv_entries(tree: ast.Module):
    """(qtype, key lineno, value ast.Call) per _QGEMV_QTYPES entry."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_QGEMV_QTYPES"
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Call)):
                    yield k.value, k.lineno, v


def _entry_factories(tree: ast.Module) -> Dict[str, tuple]:
    """name -> (param names, {param: default expr}, return-call field
    exprs) for every module-level helper whose body returns a
    ``_GemvEntry(...)`` — linear.py's ``_entry`` and any sibling a new
    format family adds."""
    out: Dict[str, tuple] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        ret = None
        for stmt in ast.walk(node):
            if (isinstance(stmt, ast.Return)
                    and isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Name)
                    and stmt.value.func.id == "_GemvEntry"):
                ret = stmt.value
        if ret is None:
            continue
        a = node.args
        params = [p.arg for p in a.args]
        defaults = dict(zip(params[len(params) - len(a.defaults):],
                            a.defaults))
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            params.append(p.arg)
            if d is not None:
                defaults[p.arg] = d
        fields = dict(zip(_GEMV_FIELDS, ret.args))
        for kw in ret.keywords:
            if kw.arg:
                fields[kw.arg] = kw.value
        out[node.name] = (params, defaults, fields)
    return out


def _entry_fields(call: ast.Call,
                  factories: Dict[str, tuple]) -> Optional[Dict[str, object]]:
    """Resolve one table entry's _GemvEntry field exprs, following one
    level of factory indirection (``_entry(64, f)`` substitutes the
    caller's arguments into the factory's ``_GemvEntry(...)`` return).
    None when the callee cannot be analyzed statically."""
    fname = call.func.id if isinstance(call.func, ast.Name) else None
    if fname == "_GemvEntry":
        fields: Dict[str, object] = dict(zip(_GEMV_FIELDS, call.args))
        for kw in call.keywords:
            if kw.arg:
                fields[kw.arg] = kw.value
        return fields
    fac = factories.get(fname or "")
    if fac is None:
        return None
    params, defaults, ret_fields = fac
    bind: Dict[str, object] = dict(zip(params, call.args))
    for kw in call.keywords:
        if kw.arg:
            bind[kw.arg] = kw.value
    fields = {}
    for field, expr in ret_fields.items():
        if isinstance(expr, ast.Name) and expr.id in params:
            expr = bind.get(expr.id, defaults.get(expr.id))
        fields[field] = expr
    return fields


def _expr_is_none(expr: object) -> bool:
    """Absent (NamedTuple default None) or a literal ``None``."""
    return expr is None or (isinstance(expr, ast.Constant)
                            and expr.value is None)


class DispatchCoverage(Check):
    rule = "DSP001"
    description = (
        "every non-dense registered qtype needs a _QGEMV_QTYPES entry "
        "(or the table names a qtype that is not registered); every "
        "entry needs a fused backward kernel or an explicit bwd_exempt"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel != _LINEAR_REL:
            return
        project = flow.project_for(ctx)
        specs = _registry_specs(project)
        if not specs:
            return
        lineno, table = _gemv_table(ctx.tree)
        if lineno is None:
            return
        for name, spec in sorted(specs.items()):
            if spec.get("storage") == "dense":
                continue  # bf16/fp16 pass-through: no kernel needed
            if name not in table:
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=lineno,
                    message="registered qtype '%s' (qtypes.py:%s) has no "
                            "_QGEMV_QTYPES entry — it would silently fall "
                            "back to dequant-matmul on the decode path"
                            % (name, spec.get("line")),
                    hint="add a _QGEMV_QTYPES entry (kernel or explicit "
                         "gemm-path _entry with gemm_exempt)",
                )
        for name, (_, line) in sorted(table.items()):
            if name not in specs:
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=line,
                    message="_QGEMV_QTYPES entry '%s' names a qtype that "
                            "is not registered in quant/qtypes.py" % name,
                    hint="remove the stale entry or register the qtype",
                )
        # the backward column: the import-time assert catches this at
        # runtime, but only on a path that imports linear.py — the lint
        # catches it on the diff. A silent bwd=None entry falls back to
        # XLA-remat dx, which writes a full bf16 dequant of W to HBM
        # every train step (the backward twin of the forward cliff).
        factories = _entry_factories(ctx.tree)
        for name, line, call in _gemv_entries(ctx.tree):
            fields = _entry_fields(call, factories)
            if fields is None:
                continue  # opaque callee: runtime assert still guards
            if _expr_is_none(fields.get("bwd")) \
                    and _expr_is_none(fields.get("bwd_exempt")):
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=line,
                    message="'%s' declares neither a fused backward "
                            "kernel (bwd=) nor a bwd_exempt reason — dx "
                            "would silently fall back to XLA-remat "
                            "dequant every train step" % name,
                    hint="route bwd through ops/pallas/qbackward.py's "
                         "table-driven dx kernel, or state why the "
                         "format cannot decode in the transposed access "
                         "pattern",
                )


class KernelExportConsistency(Check):
    rule = "DSP002"
    description = (
        "`from bigdl_tpu.ops.pallas import X` where X is not exported "
        "by the kernel package (lazy imports fail only at dispatch time)"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if "bigdl_tpu.ops.pallas" not in ctx.src:
            return
        project = flow.project_for(ctx)
        exported = _pallas_exports(project)
        if not exported:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.ImportFrom)
                    and node.module == "bigdl_tpu.ops.pallas"):
                continue
            for alias in node.names:
                if alias.name not in exported:
                    yield Finding(
                        rule=self.rule, path=ctx.rel, line=node.lineno,
                        message="'%s' is not exported by "
                                "bigdl_tpu.ops.pallas — this lazy import "
                                "raises at first dispatch, not at "
                                "module import" % alias.name,
                        hint="export it from ops/pallas/__init__.py or "
                             "fix the symbol name",
                    )


def _pallas_exports(project: "flow.Project") -> Set[str]:
    mod = project.modules.get(_PALLAS_INIT_REL)
    if mod is None:
        return set()
    names: Set[str] = set(mod.functions) | set(mod.classes)
    names |= set(mod.imports)  # from .qmatmul import qmatmul_int4, ...
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
        elif isinstance(node, ast.ImportFrom):
            # relative `from .qmatmul import X` bindings land in the
            # package namespace too (ModuleInfo.imports only records
            # absolute-module froms).
            for alias in node.names:
                names.add(alias.asname or alias.name)
    names.discard("__all__")
    # submodules are importable from the package too (qmatmul.py does
    # `from bigdl_tpu.ops.pallas import qdecode`)
    pkg = _PALLAS_INIT_REL.rsplit("/", 1)[0] + "/"
    for rel in project.modules:
        if rel.startswith(pkg):
            names.add(rel[len(pkg):-len(".py")])
    return names


class DispatchGeometry(Check):
    rule = "DSP003"
    description = (
        "dispatch k_multiple (forward or backward) must be divisible by "
        "the qtype's block (and superblock) size — and bwd_k_multiple "
        "may only coarsen the forward alignment; DecodeSpec storage "
        "dispatch must cover every registered storage or have an "
        "explicit default"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel == _LINEAR_REL:
            yield from self._check_k_multiples(ctx)
        elif ctx.rel == _QDECODE_REL:
            yield from self._check_storage_coverage(ctx)

    def _check_k_multiples(self, ctx: FileContext) -> Iterable[Finding]:
        project = flow.project_for(ctx)
        specs = _registry_specs(project)
        _, table = _gemv_table(ctx.tree)
        for name, (k_multiple, line) in sorted(table.items()):
            spec = specs.get(name)
            if spec is None or k_multiple <= 0:
                continue
            block = spec.get("block_size")
            if isinstance(block, int) and block > 0 \
                    and k_multiple % block != 0:
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=line,
                    message="'%s' k_multiple %d is not a multiple of its "
                            "quant block_size %d — the kernel's K grid "
                            "would split blocks" % (name, k_multiple, block),
                    hint="round k_multiple up to lcm(block_size, lane "
                         "tiling)",
                )
            sb = spec.get("superblock")
            if isinstance(sb, int) and sb > 0 and k_multiple % sb != 0:
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=line,
                    message="'%s' k_multiple %d is not a multiple of its "
                            "superblock %d (k-quant scale hierarchy "
                            "would straddle tiles)" % (name, k_multiple, sb),
                    hint="use a k_multiple that is a multiple of the "
                         "superblock",
                )
            if spec.get("storage") == "packed_planes" \
                    and not spec.get("planes"):
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=line,
                    message="'%s' uses packed_planes storage but declares "
                            "no planes tuple" % name,
                    hint="declare the per-plane bit widths in QTypeSpec",
                )
        # backward tile geometry: a declared bwd_k_multiple must satisfy
        # the same block/superblock divisibility as the forward's, and
        # may only COARSEN it (the dx kernel's chunk walk has the same
        # plane-split period as the forward's — a finer backward
        # alignment would admit shapes the decode loop cannot tile)
        factories = _entry_factories(ctx.tree)
        for name, line, call in _gemv_entries(ctx.tree):
            fields = _entry_fields(call, factories)
            if fields is None:
                continue
            expr = fields.get("bwd_k_multiple")
            if _expr_is_none(expr):
                continue  # inherits k_multiple, already checked above
            try:
                bkm = int(flow.eval_const(expr))
            except (ValueError, TypeError):
                continue
            spec = specs.get(name)
            if spec is None or bkm <= 0:
                continue
            for field in ("block_size", "superblock"):
                unit = spec.get(field)
                if isinstance(unit, int) and unit > 0 and bkm % unit != 0:
                    yield Finding(
                        rule=self.rule, path=ctx.rel, line=line,
                        message="'%s' bwd_k_multiple %d is not a multiple "
                                "of its %s %d — the dx kernel's K walk "
                                "would straddle quant groups"
                                % (name, bkm, field, unit),
                        hint="backward alignment must keep whole quant "
                             "blocks per decoded chunk",
                    )
            fwd = table.get(name, (-1, 0))[0]
            if fwd > 0 and bkm % fwd != 0:
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=line,
                    message="'%s' bwd_k_multiple %d is not a multiple of "
                            "its forward k_multiple %d — it may only "
                            "coarsen the contraction alignment, never "
                            "refine it" % (name, bkm, fwd),
                    hint="use a multiple of k_multiple (or None to "
                         "inherit it)",
                )

    def _check_storage_coverage(self, ctx: FileContext) -> Iterable[Finding]:
        project = flow.project_for(ctx)
        specs = _registry_specs(project)
        storages = {s.get("storage") for s in specs.values()}
        storages.discard("dense")  # dense never reaches DecodeSpec
        fn = None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) and node.name == "spec_for":
                fn = node
                break
        if fn is None:
            return
        covered, has_default = _storage_branches(fn)
        if has_default:
            return
        for storage in sorted(s for s in storages
                              if isinstance(s, str) and s not in covered):
            yield Finding(
                rule=self.rule, path=ctx.rel, line=fn.lineno,
                message="spec_for() has no branch for storage '%s' and "
                        "no default — decode dispatch would fall through"
                        % storage,
                hint="add an explicit branch or a default return",
            )


def _storage_branches(fn: ast.FunctionDef) -> Tuple[Set[str], bool]:
    """Storage string literals compared in *fn*, and whether the
    function has an unconditional (default) exit."""
    covered: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for comp in node.comparators:
                if isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, str):
                    covered.add(comp.value)
    # Default exit: a top-level return/raise, or an if/elif chain whose
    # final `else:` exists (every storage falls somewhere).
    has_default = False
    for stmt in fn.body:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            has_default = True
        elif isinstance(stmt, ast.If):
            tail = stmt
            while tail.orelse and len(tail.orelse) == 1 \
                    and isinstance(tail.orelse[0], ast.If):
                tail = tail.orelse[0]
            if tail.orelse:
                has_default = True
    return covered, has_default


#: VMEM-budget names in tiling.py whose values (and half-values) other
#: ops/ files must derive, not restate as literals.
_BUDGET_NAMES = ("VMEM_BUDGET", "LORA_VMEM_CAP", "_X_SLAB_BYTES")


class VmemLiteralDrift(Check):
    rule = "DSP004"
    description = (
        "MiB-scale literal in ops/ equal to a tiling.py VMEM budget "
        "constant (or half of one) — derive it, don't restate it"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.rel.startswith("bigdl_tpu/ops/") \
                or ctx.rel == _TILING_REL:
            return
        project = flow.project_for(ctx)
        tiling = project.modules.get(_TILING_REL)
        if tiling is None:
            return
        env = flow.module_consts(tiling.tree)
        budget_values: Dict[int, str] = {}
        for name in _BUDGET_NAMES:
            v = env.get(name)
            if isinstance(v, int):
                budget_values.setdefault(v, name)
                budget_values.setdefault(v // 2, name + " // 2")
        if not budget_values:
            return
        for node, value in _toplevel_literal_ints(ctx.tree):
            if value < (1 << 20):
                continue
            name = budget_values.get(value)
            if name is None:
                continue
            yield Finding(
                rule=self.rule, path=ctx.rel, line=node.lineno,
                message="literal %d restates tiling.py's %s — when the "
                        "budget moves, this site silently diverges"
                        % (value, name),
                hint="import the constant from ops/pallas/tiling.py "
                     "(lazily, next to the kernel import) and derive it",
            )


def _toplevel_literal_ints(tree: ast.Module):
    """(node, value) for maximal pure-literal int expressions."""
    out = []

    def visit(node: ast.AST) -> None:
        try:
            value = flow.eval_const(node)
        except ValueError:
            for child in ast.iter_child_nodes(node):
                visit(child)
            return
        if isinstance(value, int) and not isinstance(value, bool):
            out.append((node, value))

    visit(tree)
    return out


class TilingBudgetInvariants(Check):
    rule = "DSP005"
    description = (
        "tiling.py budget invariants: slabs fit the VMEM budget, the "
        "LoRA cap leaves headroom, flash blocks are lane-aligned"
    )

    #: (required names, predicate over env, message, hint)
    INVARIANTS = (
        (("LORA_VMEM_CAP", "VMEM_BUDGET"),
         lambda e: e["LORA_VMEM_CAP"] <= e["VMEM_BUDGET"] // 2,
         "LORA_VMEM_CAP exceeds half the VMEM budget — the fused LoRA "
         "epilogue would starve the base-kernel slabs",
         "keep the LoRA operand cap <= VMEM_BUDGET // 2"),
        (("_X_SLAB_BYTES", "VMEM_BUDGET"),
         lambda e: e["_X_SLAB_BYTES"] < e["VMEM_BUDGET"],
         "_X_SLAB_BYTES does not fit inside VMEM_BUDGET",
         "shrink the activation slab or raise the budget"),
        (("FLASH_BLOCK_Q", "MOSAIC_LANES"),
         lambda e: e["FLASH_BLOCK_Q"] % e["MOSAIC_LANES"] == 0,
         "FLASH_BLOCK_Q is not a multiple of MOSAIC_LANES",
         "flash attention block shapes must be lane-aligned"),
        (("FLASH_BLOCK_K", "MOSAIC_LANES"),
         lambda e: e["FLASH_BLOCK_K"] % e["MOSAIC_LANES"] == 0,
         "FLASH_BLOCK_K is not a multiple of MOSAIC_LANES",
         "flash attention block shapes must be lane-aligned"),
        (("VMEM_BUDGET",),
         lambda e: e["VMEM_BUDGET"] <= 16 * 1024 * 1024,
         "VMEM_BUDGET exceeds the 16 MiB per-core scoped-vmem ceiling",
         "the budget must leave room for Mosaic's own scratch"),
        (("_DX_SLAB_BYTES", "VMEM_BUDGET"),
         lambda e: e["_DX_SLAB_BYTES"] < e["VMEM_BUDGET"],
         "_DX_SLAB_BYTES does not fit inside VMEM_BUDGET — the dx "
         "accumulator slab would leave no room for the chunk loop",
         "shrink the backward accumulator slab or raise the budget"),
        (("DX_ACC_BPE",),
         lambda e: e["DX_ACC_BPE"] >= 6,
         "DX_ACC_BPE under-prices the dx row tile (f32 accumulator + "
         "bf16 output block is 6 B/element minimum)",
         "keep DX_ACC_BPE >= 6 so pick_block_m_dx cannot overcommit "
         "VMEM"),
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel != _TILING_REL:
            return
        env = flow.module_consts(ctx.tree)
        lines = {name: line for name, line in _const_lines(ctx.tree)}
        for names, pred, message, hint in self.INVARIANTS:
            if not all(isinstance(env.get(n), int) for n in names):
                continue
            if pred(env):
                continue
            yield Finding(
                rule=self.rule, path=ctx.rel,
                line=lines.get(names[0], 1),
                message=message, hint=hint,
            )


def _const_lines(tree: ast.Module):
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            yield stmt.targets[0].id, stmt.lineno


#: the attention kernel files whose K/V loads must decode through the
#: one shared body in qdecode.decode_kv (the fp8-KV epilogues)
_ATTN_EPILOGUE_RELS = (
    "bigdl_tpu/ops/pallas/flash_attention.py",
    "bigdl_tpu/ops/pallas/paged_attention.py",
    "bigdl_tpu/ops/pallas/flash_backward.py",
)


class AttentionDecoderUnification(Check):
    rule = "DSP006"
    description = (
        "attention epilogues must decode K/V tiles through "
        "qdecode.decode_kv — an inlined astype/bit-decode is the "
        "three-copies-of-the-decoder drift this family exists to stop"
    )

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel not in _ATTN_EPILOGUE_RELS:
            return
        uses_decode_kv = False
        touches_kv = False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Name) and node.id in ("k_ref", "v_ref"):
                touches_kv = True
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            callee = (f.attr if isinstance(f, ast.Attribute)
                      else f.id if isinstance(f, ast.Name) else None)
            if callee == "decode_kv":
                uses_decode_kv = True
            elif callee == "decode_values":
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=node.lineno,
                    message="decode_values called directly — the bit "
                            "decoder's body belongs to qdecode; the "
                            "attention epilogues call the decode_kv "
                            "wrapper so fp8-KV and the GEMM weights "
                            "cannot drift apart",
                    hint="use qdecode.decode_kv",
                )
            elif (callee == "astype"
                    and isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Subscript)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id in ("k_ref", "v_ref")):
                yield Finding(
                    rule=self.rule, path=ctx.rel, line=node.lineno,
                    message="K/V tile decoded inline (%s[...].astype) — "
                            "this is the duplicated-decoder pattern "
                            "decode_kv replaced" % f.value.value.id,
                    hint="load through qdecode.decode_kv (scale=None "
                         "for the bf16 passthrough arm)",
                )
        if touches_kv and not uses_decode_kv:
            yield Finding(
                rule=self.rule, path=ctx.rel, line=1,
                message="file reads k_ref/v_ref but never calls "
                        "qdecode.decode_kv — the shared-decoder "
                        "unification has regressed",
                hint="route every K/V tile load through "
                     "qdecode.decode_kv",
            )


INTERPROC_CHECKS = (
    PageLeakOnExit, PageLeakOnRaise,
    LockOrderCycle, BlockingUnderHotLock,
    DispatchCoverage, KernelExportConsistency, DispatchGeometry,
    VmemLiteralDrift, TilingBudgetInvariants,
    AttentionDecoderUnification,
)
