"""Interprocedural flow analysis for graftlint v2 (docs/static-analysis.md).

This module turns the per-file AST walker of PR 12 into a project-wide
engine: a symbol table over every module in the package, a call graph
with enough receiver-type inference to resolve ``self.method(...)`` and
``self.attr.method(...)`` calls, per-function summaries computed to a
fixpoint (may-raise, returns-a-page-ref, captures-param, blocking), a
path-sensitive liveness interpreter for PagePool reference obligations,
and a held-lock-set propagation pass that builds the lock-order graph.

Everything here is plain ``ast`` — no jax, no imports of the analyzed
code.  The whole-project pass parses ~120 files in well under a second;
results are cached per root so the N file-level checks that consume a
:class:`Project` pay for it once.

Fixture support: ``bigdl_tpu.analysis.core.lint_text`` feeds synthetic
sources whose ``rel`` may shadow a real file.  :func:`project_for`
detects that (source text differs from the file on disk) and analyzes
the fixture as a single-file overlay on top of the cached real project,
so unit tests get interprocedural context without re-parsing the tree.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# Name heuristics shared by the summaries.
#
# Attribute calls we cannot resolve to a function in the project are
# normally assumed pure (neither raising nor blocking): the engine is
# full of jitted callables and numpy ops, and treating every unknown
# call as a potential raise would flag half the codebase.  Two curated
# lists carve out the exceptions.

#: Unresolvable attribute calls with these names are treated as
#: may-raise: durable-storage writes and host<->device transfers are the
#: fault points the injection framework (faults.py) arms, so a page ref
#: live across one of them is live across a real-world failure.
KNOWN_RAISERS = frozenset({
    "write", "flush", "fsync", "load", "save", "open",
    "device_get", "device_put", "block_until_ready",
})

#: Unresolvable attribute calls with these names are treated as
#: blocking (for LCK102: no blocking work under a hot lock).
KNOWN_BLOCKERS = frozenset({
    "flush", "fsync", "sleep", "join", "wait",
    "device_get", "device_put", "block_until_ready",
    "recv", "send", "connect", "accept",
})

#: PagePool refcount primitives: a raise inside these is already a
#: double-release assertion, so calls to them never create exception
#: edges in the liveness interpreter (otherwise every rollback loop
#: would flag itself).
_REFCOUNT_NAMES = frozenset({"alloc", "incref", "decref"})

#: Attribute names that smell like a lock guarding serving hot paths.
#: LCK102 only fires for blocking calls under these (the journal's own
#: lock intentionally serializes its fsync; that is its job).
HOT_LOCK_ATTRS = frozenset({"_stat_lock", "_admission_lock"})

_MAX_STATES = 32        # path explosion cap per function (then we merge)
_MAX_HELD = 4           # held-lock set size cap during propagation
_MAX_CHAIN = 6          # witness call-chain length cap


def _call_attr(node: ast.AST) -> Optional[str]:
    """Attribute name of a Call like ``<expr>.name(...)``, else None."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _is_self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_alloc_name(attr: Optional[str]) -> bool:
    """Page-allocator naming convention: ``pool.alloc()`` and the
    ``self._alloc_page*`` / injected ``self._alloc`` wrappers around it.
    Name-based so callable attributes (AdapterPager's ``_alloc`` is a
    constructor-injected closure) count even when unresolvable."""
    return attr is not None and (attr == "alloc" or attr.startswith("_alloc"))


# ---------------------------------------------------------------------------
# Constant evaluation (DSP checks).


def eval_const(node: ast.AST, env: Optional[Dict[str, object]] = None):
    """Evaluate a literal/constant-arithmetic expression, else raise.

    Supports int/float/str/bool constants, tuples, names bound in *env*,
    unary minus, and + - * // % ** << binary ops.  Deliberately no
    attribute access, calls, or true division (float creep).
    """
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Tuple):
        return tuple(eval_const(e, env) for e in node.elts)
    if isinstance(node, ast.Name):
        if env is not None and node.id in env:
            return env[node.id]
        raise ValueError("unbound name %s" % node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -eval_const(node.operand, env)
    if isinstance(node, ast.BinOp):
        left = eval_const(node.left, env)
        right = eval_const(node.right, env)
        op = node.op
        if isinstance(op, ast.Add):
            return left + right
        if isinstance(op, ast.Sub):
            return left - right
        if isinstance(op, ast.Mult):
            return left * right
        if isinstance(op, ast.FloorDiv):
            return left // right
        if isinstance(op, ast.Mod):
            return left % right
        if isinstance(op, ast.Pow):
            return left ** right
        if isinstance(op, ast.LShift):
            return left << right
        raise ValueError("unsupported binop")
    raise ValueError("not a constant expression")


def module_consts(tree: ast.Module) -> Dict[str, object]:
    """Top-level ``NAME = <const expr>`` bindings of a module."""
    env: Dict[str, object] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            try:
                env[stmt.targets[0].id] = eval_const(stmt.value, env)
            except ValueError:
                pass
    return env


# ---------------------------------------------------------------------------
# Symbol table.


class FuncInfo:
    """One function or method, with its resolution context."""

    __slots__ = ("qualname", "rel", "node", "cls", "module")

    def __init__(self, qualname, rel, node, cls, module):
        self.qualname = qualname          # "rel::Class.meth" or "rel::fn"
        self.rel = rel
        self.node = node                  # ast.FunctionDef
        self.cls = cls                    # ClassInfo or None
        self.module = module              # ModuleInfo


class ClassInfo:
    __slots__ = ("name", "rel", "node", "methods", "attr_types", "lock_attrs",
                 "module")

    def __init__(self, name, rel, node, module):
        self.name = name
        self.rel = rel
        self.node = node
        self.module = module
        self.methods: Dict[str, FuncInfo] = {}
        # attr -> set of class names this attr may hold (from
        # ``self.x = ClassName(...)`` in any method, incl. inside
        # BoolOp/IfExp operands, and from annotations).
        self.attr_types: Dict[str, Set[str]] = {}
        # attr -> "Lock" | "RLock" for ``self.x = threading.Lock()``.
        self.lock_attrs: Dict[str, str] = {}


class ModuleInfo:
    __slots__ = ("rel", "src", "tree", "classes", "functions", "imports")

    def __init__(self, rel, src, tree):
        self.rel = rel
        self.src = src
        self.tree = tree
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        # local name -> dotted module path it refers to ("from X import
        # Y" maps Y -> "X.Y"; "import X.Y as Z" maps Z -> "X.Y").
        self.imports: Dict[str, str] = {}


def _scan_attr_types(cls: ClassInfo) -> None:
    """Infer ``self.attr`` class types from constructor-call assignments."""

    def record(attr: str, value: ast.AST) -> None:
        # Unwrap conditional forms: ``a if c else b``, ``a or b``.
        candidates: List[ast.AST] = [value]
        if isinstance(value, ast.IfExp):
            candidates = [value.body, value.orelse]
        elif isinstance(value, ast.BoolOp):
            candidates = list(value.values)
        for v in candidates:
            if isinstance(v, ast.Call):
                fn = v.func
                name = None
                if isinstance(fn, ast.Name):
                    name = fn.id
                elif isinstance(fn, ast.Attribute):
                    name = fn.attr
                if name:
                    if name in ("Lock", "RLock"):
                        cls.lock_attrs.setdefault(attr, name)
                    elif name[:1].isupper():
                        cls.attr_types.setdefault(attr, set()).add(name)

    for node in ast.walk(cls.node):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                attr = _is_self_attr(tgt)
                if attr:
                    record(attr, node.value)
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            attr = _is_self_attr(node.target)
            if attr and isinstance(node.annotation, ast.Name):
                ann = node.annotation.id
                if ann[:1].isupper():
                    cls.attr_types.setdefault(attr, set()).add(ann)
            if attr and node.value is not None:
                record(attr, node.value)


def _build_module(rel: str, src: str, tree: ast.Module) -> ModuleInfo:
    mod = ModuleInfo(rel, src, tree)
    for stmt in tree.body:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                mod.imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name
        elif isinstance(stmt, ast.ImportFrom) and stmt.module:
            for alias in stmt.names:
                mod.imports[alias.asname or alias.name] = \
                    stmt.module + "." + alias.name
        elif isinstance(stmt, ast.ClassDef):
            cls = ClassInfo(stmt.name, rel, stmt, mod)
            mod.classes[stmt.name] = cls
            for item in stmt.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = "%s::%s.%s" % (rel, stmt.name, item.name)
                    cls.methods[item.name] = FuncInfo(qn, rel, item, cls, mod)
            _scan_attr_types(cls)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = "%s::%s" % (rel, stmt.name)
            mod.functions[stmt.name] = FuncInfo(qn, rel, stmt, None, mod)
    return mod


class Project:
    """Symbol table + call resolution + memoized summaries for one tree."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}
        # class name -> [ClassInfo] (names are unique enough in practice;
        # resolution fans out over all same-named classes).
        self.class_index: Dict[str, List[ClassInfo]] = {}
        # method/function simple name -> [FuncInfo] for last-resort
        # unique-name resolution.
        self._summaries: Dict[Tuple[str, str], object] = {}

    # -- construction -------------------------------------------------------

    @classmethod
    def load(cls, root: str) -> "Project":
        proj = cls(root)
        pkg = os.path.join(root, "bigdl_tpu")
        if os.path.isdir(pkg):
            for dirpath, dirnames, filenames in os.walk(pkg):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, root).replace(os.sep, "/")
                    try:
                        with open(path, "r", encoding="utf-8") as f:
                            src = f.read()
                        tree = ast.parse(src)
                    except (OSError, SyntaxError):
                        continue
                    proj._add_module(rel, src, tree)
        proj._reindex()
        return proj

    def _add_module(self, rel: str, src: str, tree: ast.Module) -> None:
        self.modules[rel] = _build_module(rel, src, tree)

    def _reindex(self) -> None:
        self.class_index = {}
        for mod in self.modules.values():
            for cls in mod.classes.values():
                self.class_index.setdefault(cls.name, []).append(cls)

    def overlay(self, rel: str, src: str, tree: ast.Module) -> "Project":
        """A copy of this project with *rel* replaced by fixture source."""
        proj = Project(self.root)
        proj.modules = dict(self.modules)
        proj._add_module(rel, src, tree)
        proj._reindex()
        return proj

    def src_of(self, rel: str) -> Optional[str]:
        mod = self.modules.get(rel)
        return mod.src if mod is not None else None

    # -- call resolution ----------------------------------------------------

    def _classes_named(self, name: str) -> List[ClassInfo]:
        return self.class_index.get(name, [])

    def resolve_call(self, call: ast.Call, scope: FuncInfo) -> List[FuncInfo]:
        """Possible callees of *call* evaluated inside *scope*.

        Best-effort: an empty list means "unknown receiver", not "no
        callee".  Checks treat unknown calls per the KNOWN_* heuristics.
        """
        fn = call.func
        out: List[FuncInfo] = []
        if isinstance(fn, ast.Name):
            name = fn.id
            mod = scope.module
            if name in mod.functions:
                return [mod.functions[name]]
            # Constructor: Class(...) resolves to Class.__init__.
            for cls in self._classes_named(name):
                init = cls.methods.get("__init__")
                if init is not None:
                    out.append(init)
            if out:
                return out
            # from X import f
            dotted = mod.imports.get(name)
            if dotted:
                return self._resolve_dotted(dotted)
            return []
        if isinstance(fn, ast.Attribute):
            meth = fn.attr
            recv = fn.value
            # self.meth(...)
            if isinstance(recv, ast.Name) and recv.id == "self" and scope.cls:
                m = scope.cls.methods.get(meth)
                if m is not None:
                    return [m]
                return []
            # self.attr.meth(...) via inferred attr types
            attr = _is_self_attr(recv)
            if attr and scope.cls:
                for tname in sorted(scope.cls.attr_types.get(attr, ())):
                    for cls in self._classes_named(tname):
                        m = cls.methods.get(meth)
                        if m is not None:
                            out.append(m)
                return out
            # module.f(...)
            if isinstance(recv, ast.Name):
                dotted = scope.module.imports.get(recv.id)
                if dotted:
                    return self._resolve_dotted(dotted + "." + meth)
                # local var with inferred class type
                for tname in sorted(
                        self._local_types(scope).get(recv.id, ())):
                    for cls in self._classes_named(tname):
                        m = cls.methods.get(meth)
                        if m is not None:
                            out.append(m)
                return out
        return out

    def _resolve_dotted(self, dotted: str) -> List[FuncInfo]:
        """Resolve "pkg.mod.fn" / "pkg.mod.Class" to FuncInfos."""
        parts = dotted.split(".")
        for split in range(len(parts) - 1, 0, -1):
            rel = "/".join(parts[:split]) + ".py"
            mod = self.modules.get(rel)
            if mod is None:
                continue
            tail = parts[split:]
            if len(tail) == 1:
                f = mod.functions.get(tail[0])
                if f is not None:
                    return [f]
                cls = mod.classes.get(tail[0])
                if cls is not None and "__init__" in cls.methods:
                    return [cls.methods["__init__"]]
            elif len(tail) == 2:
                cls = mod.classes.get(tail[0])
                if cls is not None:
                    m = cls.methods.get(tail[1])
                    if m is not None:
                        return [m]
        return []

    def _local_types(self, scope: FuncInfo) -> Dict[str, Set[str]]:
        """``x = ClassName(...)`` local bindings inside *scope*."""
        key = ("localtypes", scope.qualname)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached  # type: ignore[return-value]
        out: Dict[str, Set[str]] = {}
        for node in ast.walk(scope.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and node.value.func.id[:1].isupper()):
                out.setdefault(node.targets[0].id, set()).add(
                    node.value.func.id)
        self._summaries[key] = out
        return out

    def all_functions(self) -> List[FuncInfo]:
        out = []
        for mod in self.modules.values():
            out.extend(mod.functions.values())
            for cls in mod.classes.values():
                out.extend(cls.methods.values())
        return out

    # -- summaries ----------------------------------------------------------

    def may_raise(self, fi: FuncInfo, _depth: int = 0) -> bool:
        """Whether calling *fi* can plausibly raise on a real fault path.

        Explicit ``raise`` in the body counts unless it sits inside a
        ``try`` of the same function (assumed handled).  Transitively,
        resolved callees are consulted up to depth 2; unresolved
        attribute calls count only when named like I/O (KNOWN_RAISERS).
        Refcount primitives never count (their raise is a double-release
        assertion, itself a bug this checker exists to prevent).
        """
        key = ("may_raise", fi.qualname)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached  # type: ignore[return-value]
        self._summaries[key] = False        # recursion guard: optimistic
        result = self._may_raise_uncached(fi, _depth)
        self._summaries[key] = result
        return result

    def _may_raise_uncached(self, fi: FuncInfo, depth: int) -> bool:
        guarded = _try_guarded_lines(fi.node)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Raise) and node.lineno not in guarded:
                return True
            if depth >= 2 or not isinstance(node, ast.Call):
                continue
            attr = _call_attr(node)
            if attr in _REFCOUNT_NAMES:
                continue
            callees = self.resolve_call(node, fi)
            if callees:
                if any(self.may_raise(c, depth + 1) for c in callees):
                    return True
            elif attr in KNOWN_RAISERS and node.lineno not in guarded:
                return True
        return False

    def is_blocking(self, fi: FuncInfo, _depth: int = 0) -> bool:
        """Whether *fi* transitively performs blocking I/O (for LCK102)."""
        key = ("blocking", fi.qualname)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached  # type: ignore[return-value]
        self._summaries[key] = False
        result = False
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            attr = _call_attr(node)
            if attr in KNOWN_BLOCKERS:
                result = True
                break
            if isinstance(node.func, ast.Name) and node.func.id == "sleep":
                result = True
                break
            if _depth < 3:
                callees = self.resolve_call(node, fi)
                if any(self.is_blocking(c, _depth + 1) for c in callees):
                    result = True
                    break
        self._summaries[key] = result
        return result

    def returns_ref(self, fi: FuncInfo) -> bool:
        """Whether *fi* returns a freshly-acquired page ref to its caller.

        Fixpoint over "returns a var assigned from ``.alloc()`` or from
        a returns_ref callee" (covers Engine._alloc_page and the
        preempting wrapper around it without hand-listing either).
        """
        self._compute_returns_ref()
        return bool(self._summaries.get(("returns_ref", fi.qualname)))

    def _compute_returns_ref(self) -> None:
        if self._summaries.get(("returns_ref_done", "")):
            return
        funcs = self.all_functions()
        flagged: Set[str] = set()
        changed = True
        rounds = 0
        while changed and rounds < 5:
            changed = False
            rounds += 1
            for fi in funcs:
                if fi.qualname in flagged:
                    continue
                if self._returns_ref_once(fi, flagged):
                    flagged.add(fi.qualname)
                    changed = True
        for qn in flagged:
            self._summaries[("returns_ref", qn)] = True
        self._summaries[("returns_ref_done", "")] = True

    def _returns_ref_once(self, fi: FuncInfo, flagged: Set[str]) -> bool:
        ref_vars: Set[str] = set()
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                val = node.value
                if isinstance(val, ast.Call) and \
                        _is_alloc_name(_call_attr(val)):
                    ref_vars.add(node.targets[0].id)
                elif isinstance(val, ast.Call):
                    callees = self.resolve_call(val, fi)
                    if any(c.qualname in flagged for c in callees):
                        ref_vars.add(node.targets[0].id)
        if not ref_vars:
            return False
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Return) and isinstance(node.value, ast.Name)
                    and node.value.id in ref_vars):
                return True
        return False

    def captured_params(self, fi: FuncInfo) -> Set[str]:
        """Params of *fi* stored into ``self`` (ownership transferred in).

        ``def __init__(self, pages): self.pages = pages`` captures
        "pages": a caller passing a live ref there has handed it over.
        Also covers ``self.x.append(p)`` and ``self.x[k] = p``.
        """
        key = ("captures", fi.qualname)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached  # type: ignore[return-value]
        params = {a.arg for a in fi.node.args.args if a.arg != "self"}
        out: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                names = {v.id for v in ast.walk(node.value)
                         if isinstance(v, ast.Name)} & params
                if not names:
                    continue
                for tgt in node.targets:
                    base = tgt
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute):
                        out |= names
            elif (isinstance(node, ast.Call)
                    and _call_attr(node) == "append"
                    and isinstance(node.func, ast.Attribute)  # noqa: SIM102
                    and isinstance(node.func.value, (ast.Attribute,
                                                     ast.Subscript))):
                out |= {a.id for a in node.args
                        if isinstance(a, ast.Name)} & params
        self._summaries[key] = out
        return out


def _try_guarded_lines(fn: ast.AST) -> FrozenSet[int]:
    """Line numbers inside any ``try`` body of *fn* (handlers excluded)."""
    lines: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Try) and (node.handlers or node.finalbody):
            for stmt in node.body:
                end = getattr(stmt, "end_lineno", stmt.lineno)
                lines.update(range(stmt.lineno, end + 1))
    return frozenset(lines)


# ---------------------------------------------------------------------------
# Project cache / fixture overlay.

_PROJECT_CACHE: Dict[str, Project] = {}
_OVERLAY_CACHE: Dict[Tuple[str, str, int], Project] = {}


def project_for(ctx) -> Project:
    """The Project for a FileContext — cached, fixture-aware.

    If *ctx*'s source matches the file on disk (normal tree lint) the
    shared per-root project is returned.  Otherwise the source is a
    synthetic fixture (lint_text in tests): a single-file overlay is
    built on top of the cached project so interprocedural context (the
    real qtype registry, lock declarations, ...) stays available.
    """
    base = _PROJECT_CACHE.get(ctx.root)
    if base is None:
        base = Project.load(ctx.root)
        _PROJECT_CACHE[ctx.root] = base
    if base.src_of(ctx.rel) == ctx.src:
        return base
    key = (ctx.root, ctx.rel, hash(ctx.src))
    proj = _OVERLAY_CACHE.get(key)
    if proj is None:
        if len(_OVERLAY_CACHE) > 64:
            _OVERLAY_CACHE.clear()
        proj = base.overlay(ctx.rel, ctx.src, ctx.tree)
        _OVERLAY_CACHE[key] = proj
    return proj


def invalidate_cache() -> None:
    """Drop cached projects (tests that rewrite tree files call this)."""
    _PROJECT_CACHE.clear()
    _OVERLAY_CACHE.clear()


# ---------------------------------------------------------------------------
# PAGE liveness interpreter.


class PageLeak:
    __slots__ = ("rule", "line", "var", "acquired_line", "detail")

    def __init__(self, rule, line, var, acquired_line, detail):
        self.rule = rule
        self.line = line
        self.var = var
        self.acquired_line = acquired_line
        self.detail = detail


class _State:
    """One abstract execution path: live refs + escaped names."""

    __slots__ = ("live", "escaped")

    def __init__(self, live=None, escaped=None):
        self.live: Dict[str, int] = dict(live or {})
        self.escaped: Set[str] = set(escaped or ())

    def copy(self) -> "_State":
        return _State(self.live, self.escaped)

    def key(self):
        return (frozenset(self.live.items()), frozenset(self.escaped))


def _merge_states(states: List[_State]) -> List[_State]:
    seen = {}
    for s in states:
        seen.setdefault(s.key(), s)
    out = list(seen.values())
    if len(out) <= _MAX_STATES:
        return out
    # Path explosion: collapse to one may-be-live union state.
    union = _State()
    for s in out:
        for v, ln in s.live.items():
            union.live.setdefault(v, ln)
        union.escaped |= s.escaped
    return [union]


class _PageInterp:
    """Path-sensitive page-ref liveness over one function body.

    Acquire events: ``x = <e>.alloc()``, ``<e>.incref(x)`` (unless x
    already escaped to a container/object), ``x = f(...)`` where f's
    summary says returns_ref, and ``for p in xs: <e>.incref(p)`` which
    acquires the iterable as a unit.  Release/transfer events: decref
    (incl. the loop form), append into a local list (moves the ref),
    assignment into self/attrs/subscripts (ownership transfer), return
    of the live name (transfer to caller), and passing the name to a
    callee whose summary captures that parameter.

    ``x is None`` tests refine paths: on the branch where x is None the
    obligation dies (alloc returned None — nothing was acquired).
    """

    def __init__(self, project: Project, fi: FuncInfo):
        self.project = project
        self.fi = fi
        self.leaks: List[PageLeak] = []
        self.guarded = _try_guarded_lines(fi.node)
        self._reported: Set[Tuple[str, int]] = set()
        # loop-var substitution: {loopvar: iterable_name}
        self.subst: Dict[str, str] = {}

    # -- entry --------------------------------------------------------------

    def run(self) -> List[PageLeak]:
        states = self._exec_block(self.fi.node.body, [_State()])
        end = getattr(self.fi.node, "end_lineno", self.fi.node.lineno)
        for s in states:
            self._report_exit(s, end, "falls off the end of the function")
        return self.leaks

    # -- helpers ------------------------------------------------------------

    def _name(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.subst.get(node.id, node.id)
        return None

    def _report_exit(self, s: _State, line: int, how: str) -> None:
        for var, acq in sorted(s.live.items()):
            if (var, acq) in self._reported:
                continue
            self._reported.add((var, acq))
            self.leaks.append(PageLeak(
                "PAGE001", line, var, acq,
                "page ref held by '%s' (acquired line %d) %s without "
                "decref or ownership transfer" % (var, acq, how)))

    def _kill_live_in_expr(self, s: _State, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            nm = self._name(node)
            if nm and nm in s.live:
                del s.live[nm]
                s.escaped.add(nm)

    # -- statement dispatch --------------------------------------------------

    def _exec_block(self, body: Sequence[ast.stmt],
                    states: List[_State]) -> List[_State]:
        for stmt in body:
            if not states:
                return states
            states = self._exec_stmt(stmt, states)
            states = _merge_states(states)
        return states

    def _exec_stmt(self, stmt: ast.stmt,
                   states: List[_State]) -> List[_State]:
        if isinstance(stmt, ast.Assign):
            return [self._do_assign(stmt, s) for s in states]
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            fake = ast.Assign(targets=[stmt.target], value=stmt.value)
            ast.copy_location(fake, stmt)
            return [self._do_assign(fake, s) for s in states]
        if isinstance(stmt, ast.AugAssign):
            for s in states:
                self._scan_calls(stmt, s)
            return states
        if isinstance(stmt, ast.Expr):
            for s in states:
                self._do_call_effects(stmt.value, s)
                self._check_may_raise(stmt, s)
            return states
        if isinstance(stmt, ast.Return):
            out: List[_State] = []
            for s in states:
                if stmt.value is not None:
                    self._do_call_effects(stmt.value, s)
                    self._kill_live_in_expr(s, stmt.value)
                self._report_exit(s, stmt.lineno, "leaks on this return")
            return out
        if isinstance(stmt, ast.Raise):
            for s in states:
                if stmt.lineno not in self.guarded:
                    self._report_exit(s, stmt.lineno, "leaks on this raise")
            return []
        if isinstance(stmt, ast.If):
            return self._do_if(stmt, states)
        if isinstance(stmt, (ast.While,)):
            return self._do_while(stmt, states)
        if isinstance(stmt, ast.For):
            return self._do_for(stmt, states)
        if isinstance(stmt, ast.With):
            for s in states:
                self._check_may_raise(stmt, s, items_only=True)
            return self._exec_block(stmt.body, states)
        if isinstance(stmt, ast.Try):
            return self._do_try(stmt, states)
        if isinstance(stmt, (ast.Break, ast.Continue)):
            # Approximate: carry the state through to after the loop.
            return states
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Import, ast.ImportFrom,
                             ast.Global, ast.Nonlocal, ast.Pass)):
            return states
        if isinstance(stmt, ast.Assert):
            return states
        if isinstance(stmt, ast.Delete):
            for s in states:
                for tgt in stmt.targets:
                    nm = self._name(tgt)
                    if nm:
                        s.live.pop(nm, None)
            return states
        # Anything else: conservatively scan for call effects.
        for s in states:
            self._scan_calls(stmt, s)
        return states

    # -- assignment ----------------------------------------------------------

    def _do_assign(self, stmt: ast.Assign, s: _State) -> _State:
        s = s.copy()
        val = stmt.value
        self._do_call_effects(val, s)
        self._check_may_raise(stmt, s)
        tgt = stmt.targets[0] if len(stmt.targets) == 1 else None
        tname = self._name(tgt) if tgt is not None else None

        acquires = False
        if isinstance(val, ast.Call):
            if _is_alloc_name(_call_attr(val)):
                acquires = True
            else:
                callees = self.project.resolve_call(val, self.fi)
                if callees and any(self.project.returns_ref(c)
                                   for c in callees):
                    acquires = True

        if tname is not None and isinstance(tgt, ast.Name):
            # Rebinding a name drops its old obligation only if moved.
            if acquires:
                s.live[tname] = stmt.lineno
            else:
                # x = y / x = a + b: obligation moves to x.
                moved = False
                for node in ast.walk(val):
                    nm = self._name(node)
                    if nm and nm in s.live:
                        acq = s.live.pop(nm)
                        s.live[tname] = min(acq, s.live.get(tname, acq))
                        moved = True
                if not moved:
                    s.live.pop(tname, None)
        else:
            # Store into self.x / obj[k] / tuple target: ownership
            # transfers out of the frame for every live name used.
            self._kill_live_in_expr(s, val)
        return s

    # -- calls ---------------------------------------------------------------

    def _do_call_effects(self, expr: ast.AST, s: _State) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            attr = _call_attr(node)
            if attr == "incref" and len(node.args) == 1:
                nm = self._name(node.args[0])
                if nm and nm not in s.escaped and nm not in s.live:
                    s.live[nm] = node.lineno
            elif attr == "decref" and len(node.args) == 1:
                nm = self._name(node.args[0])
                if nm:
                    s.live.pop(nm, None)
            elif attr == "append" and len(node.args) == 1:
                nm = self._name(node.args[0])
                if nm and nm in s.live:
                    recv = node.func.value  # type: ignore[union-attr]
                    rname = self._name(recv)
                    acq = s.live.pop(nm)
                    if rname is not None:
                        # Moves into a local list: list now owns it.
                        s.live[rname] = min(acq, s.live.get(rname, acq))
                    else:
                        # self._slot_pages[slot].append(pg): transferred.
                        s.escaped.add(nm)
            else:
                # Passing a name to a callee that captures it transfers
                # ownership (if live) and marks it escaped either way —
                # a later incref on an escaped name is the *container's*
                # hold (e.g. RadixNode stores the page, then insert
                # increfs on the node's behalf), not a new obligation
                # of this frame.
                named_args = [(i, self._name(a)) for i, a in
                              enumerate(node.args)]
                named_args = [(i, nm) for i, nm in named_args if nm]
                if not named_args:
                    continue
                for callee in self.project.resolve_call(node, self.fi):
                    captured = self.project.captured_params(callee)
                    if not captured:
                        continue
                    params = [a.arg for a in callee.node.args.args]
                    if params and params[0] == "self":
                        params = params[1:]
                    for i, nm in named_args:
                        if i < len(params) and params[i] in captured:
                            s.live.pop(nm, None)
                            s.escaped.add(nm)

    def _scan_calls(self, stmt: ast.stmt, s: _State) -> None:
        self._do_call_effects(stmt, s)
        self._check_may_raise(stmt, s)

    def _check_may_raise(self, stmt: ast.stmt, s: _State,
                         items_only: bool = False) -> None:
        """PAGE002: a may-raise call with refs live and no enclosing try."""
        if not s.live or stmt.lineno in self.guarded:
            return
        nodes = stmt.items if items_only and isinstance(stmt, ast.With) \
            else [stmt]
        for top in nodes:
            for node in ast.walk(top):
                if not isinstance(node, ast.Call):
                    continue
                attr = _call_attr(node)
                if attr in _REFCOUNT_NAMES or attr == "append":
                    continue
                raises = False
                callees = self.project.resolve_call(node, self.fi)
                if callees:
                    raises = any(self.project.may_raise(c) for c in callees)
                elif attr in KNOWN_RAISERS:
                    raises = True
                if not raises:
                    continue
                key = ("PAGE002", node.lineno)
                if key in self._reported:
                    continue
                self._reported.add(key)
                held = ", ".join(
                    "'%s' (line %d)" % (v, ln)
                    for v, ln in sorted(s.live.items()))
                self.leaks.append(PageLeak(
                    "PAGE002", node.lineno, next(iter(sorted(s.live))),
                    min(s.live.values()),
                    "call may raise while page refs %s are held with no "
                    "enclosing try to roll them back" % held))

    # -- control flow --------------------------------------------------------

    def _refine(self, test: ast.AST, s: _State, branch: bool) -> _State:
        """Kill obligations proven None on this branch of *test*."""
        s = s.copy()

        def none_vars(t: ast.AST, when: bool) -> Set[str]:
            # Vars known None when `t` evaluates to `when`.
            if isinstance(t, ast.Compare) and len(t.ops) == 1 \
                    and isinstance(t.comparators[0], ast.Constant) \
                    and t.comparators[0].value is None:
                nm = self._name(t.left)
                if nm:
                    if isinstance(t.ops[0], ast.Is) and when:
                        return {nm}
                    if isinstance(t.ops[0], ast.IsNot) and not when:
                        return {nm}
                return set()
            if isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not):
                return none_vars(t.operand, not when)
            if isinstance(t, ast.BoolOp):
                if isinstance(t.op, ast.Or) and not when:
                    # (a or b) false => every operand false.
                    out: Set[str] = set()
                    for v in t.values:
                        out |= none_vars(v, False)
                    return out
                if isinstance(t.op, ast.And) and when:
                    out = set()
                    for v in t.values:
                        out |= none_vars(v, True)
                    return out
            return set()

        for nm in none_vars(test, branch):
            s.live.pop(nm, None)
        return s

    def _do_if(self, stmt: ast.If, states: List[_State]) -> List[_State]:
        for s in states:
            self._do_call_effects(stmt.test, s)
            self._check_may_raise(ast.Expr(value=stmt.test, lineno=stmt.lineno,
                                           col_offset=0), s)
        then_in = [self._refine(stmt.test, s, True) for s in states]
        else_in = [self._refine(stmt.test, s, False) for s in states]
        out = self._exec_block(stmt.body, then_in)
        out += self._exec_block(stmt.orelse, else_in)
        return out

    def _do_while(self, stmt: ast.While,
                  states: List[_State]) -> List[_State]:
        # Abstract: body runs 0 or 1 times; obligations created in the
        # body must resolve within it (merge catches carried liveness).
        body_in = [self._refine(stmt.test, s, True) for s in states]
        after_body = self._exec_block(stmt.body, body_in)
        exits = states + after_body
        return [self._refine(stmt.test, s, False) for s in exits]

    def _do_for(self, stmt: ast.For, states: List[_State]) -> List[_State]:
        # Loop-var substitution: incref/decref/append on the loop var
        # apply to the iterable as a unit ("for p in pages: decref(p)"
        # releases `pages`).
        loopvar = stmt.target.id if isinstance(stmt.target, ast.Name) else None
        itername = self._name(stmt.iter)
        pushed = False
        if loopvar and itername:
            self.subst[loopvar] = itername
            pushed = True
        try:
            after_body = self._exec_block(stmt.body, [s.copy() for s in states])
            if pushed:
                # Acquire/release loops over a tracked container run
                # "exactly once" abstractly: a zero-iteration release
                # loop only happens when the container is empty, i.e.
                # the obligation was vacuous to begin with.
                return after_body
            zero_iter = self._exec_block(stmt.orelse, states) \
                if stmt.orelse else states
            return zero_iter + after_body
        finally:
            if pushed:
                del self.subst[loopvar]

    def _do_try(self, stmt: ast.Try, states: List[_State]) -> List[_State]:
        body_out = self._exec_block(stmt.body, [s.copy() for s in states])
        # Handlers see the union of entry and post-body states (a raise
        # can interrupt anywhere; entry state is the conservative floor).
        handler_in = _merge_states(
            [s.copy() for s in states] + [s.copy() for s in body_out])
        out = list(body_out)
        for handler in stmt.handlers:
            out += self._exec_block(handler.body, [s.copy()
                                                   for s in handler_in])
        if stmt.orelse:
            out = self._exec_block(stmt.orelse, out)
        if stmt.finalbody:
            out = self._exec_block(stmt.finalbody, out)
        return out


def _has_page_ops(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        attr = _call_attr(node)
        if attr == "incref" or _is_alloc_name(attr):
            return True
    return False


def page_leaks_in(project: Project, fi: FuncInfo) -> List[PageLeak]:
    """PAGE findings for one function (empty unless it acquires refs)."""
    if fi.node.name == "__init__":
        # Constructors store what they're given; captured params are the
        # caller's transfer, not an acquisition here.
        return []
    if not _has_page_ops(fi.node):
        return []
    interp = _PageInterp(project, fi)
    return interp.run()


def page_leaks_for_module(project: Project,
                          rel: str) -> List[Tuple[FuncInfo, PageLeak]]:
    """All PAGE findings in one module — cached (PAGE001 and PAGE002
    share one interpreter run per file)."""
    key = ("page_leaks", rel)
    cached = project._summaries.get(key)
    if cached is not None:
        return cached  # type: ignore[return-value]
    out: List[Tuple[FuncInfo, PageLeak]] = []
    mod = project.modules.get(rel)
    if mod is not None:
        funcs = list(mod.functions.values())
        for cls in mod.classes.values():
            funcs.extend(cls.methods.values())
        for fi in funcs:
            for leak in page_leaks_in(project, fi):
                out.append((fi, leak))
    project._summaries[key] = out
    return out


# ---------------------------------------------------------------------------
# Lock analysis.


class LockSite:
    __slots__ = ("lock", "rel", "line", "func")

    def __init__(self, lock, rel, line, func):
        self.lock = lock
        self.rel = rel
        self.line = line
        self.func = func


class LockEdge:
    __slots__ = ("held", "acquired", "witness")

    def __init__(self, held, acquired, witness):
        self.held = held              # lock id
        self.acquired = acquired      # lock id
        self.witness = witness        # "f -> g -> h acquires X at rel:line"


class LockReport:
    def __init__(self):
        self.locks: Dict[str, str] = {}          # lock id -> kind
        self.edges: Dict[Tuple[str, str], LockEdge] = {}
        self.self_deadlocks: List[LockSite] = []  # plain Lock re-acquired
        self.blocking_under_hot: List[Tuple[LockSite, str]] = []
        self.cycles: List[List[LockEdge]] = []


def _lock_attr_index(project: Project) -> Dict[str, List[str]]:
    """attr name -> [lock ids] across every class (for unique-name use)."""
    idx: Dict[str, List[str]] = {}
    for mod in project.modules.values():
        for cls in mod.classes.values():
            for attr, kind in cls.lock_attrs.items():
                idx.setdefault(attr, []).append("%s.%s" % (cls.name, attr))
    return idx


class _LockWalker:
    """Propagates held-lock sets through the call graph."""

    def __init__(self, project: Project):
        self.project = project
        self.report = LockReport()
        self.attr_index = _lock_attr_index(project)
        for mod in project.modules.values():
            for cls in mod.classes.values():
                for attr, kind in cls.lock_attrs.items():
                    self.report.locks["%s.%s" % (cls.name, attr)] = kind
        self._seen: Set[Tuple[str, FrozenSet[str]]] = set()

    def resolve_lock(self, expr: ast.AST, scope: FuncInfo) -> Optional[str]:
        """``with <expr>:`` -> lock id, or None if not a known lock."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            # self.X
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and scope.cls and attr in scope.cls.lock_attrs:
                return "%s.%s" % (scope.cls.name, attr)
            # self.a.X / obj.X: attr-type inference, else unique name.
            base_attr = _is_self_attr(expr.value)
            if base_attr and scope.cls:
                for tname in sorted(scope.cls.attr_types.get(base_attr, ())):
                    for cls in self.project._classes_named(tname):
                        if attr in cls.lock_attrs:
                            return "%s.%s" % (cls.name, attr)
            ids = self.attr_index.get(attr, [])
            if len(ids) == 1:
                return ids[0]
        return None

    def run(self) -> LockReport:
        for fi in self.project.all_functions():
            self._visit_func(fi, frozenset(), ())
        self._find_cycles()
        return self.report

    def _visit_func(self, fi: FuncInfo, held: FrozenSet[str],
                    chain: Tuple[str, ...]) -> None:
        key = (fi.qualname, held)
        if key in self._seen or len(held) > _MAX_HELD \
                or len(chain) > _MAX_CHAIN:
            return
        self._seen.add(key)
        # `local` = locks acquired lexically in THIS function: LCK102
        # findings anchor there (the frame that took the lock owns the
        # fix); inherited holds still propagate for ordering edges.
        self._visit_body(fi.node.body, fi, held, frozenset(), chain)

    def _visit_body(self, body, fi: FuncInfo, held: FrozenSet[str],
                    local: FrozenSet[str], chain: Tuple[str, ...]) -> None:
        for stmt in body:
            self._visit_stmt(stmt, fi, held, local, chain)

    def _visit_stmt(self, stmt, fi: FuncInfo, held: FrozenSet[str],
                    local: FrozenSet[str], chain: Tuple[str, ...]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run later, with nothing held
        if isinstance(stmt, ast.With):
            acquired: List[Tuple[str, int]] = []
            for item in stmt.items:
                lock = self.resolve_lock(item.context_expr, fi)
                if lock is None:
                    continue
                site = LockSite(lock, fi.rel, stmt.lineno, fi.qualname)
                kind = self.report.locks.get(lock, "Lock")
                if lock in held:
                    if kind != "RLock":
                        self.report.self_deadlocks.append(site)
                    continue  # re-entry adds no ordering edge
                for h in sorted(held):
                    ekey = (h, lock)
                    if ekey not in self.report.edges:
                        witness = " -> ".join(chain + (fi.qualname,)) + \
                            " acquires %s at %s:%d (holding %s)" % (
                                lock, fi.rel, stmt.lineno, h)
                        self.report.edges[ekey] = LockEdge(h, lock, witness)
                acquired.append((lock, stmt.lineno))
            news = {l for l, _ in acquired}
            self._visit_body(stmt.body, fi, held | news, local | news, chain)
            return
        # Compound statements: recurse into bodies (held set unchanged),
        # visiting calls only in the header expression here so nested
        # With blocks are not double-walked.
        if isinstance(stmt, ast.If):
            for n in ast.walk(stmt.test):
                self._visit_call(n, fi, held, local, chain)
            self._visit_body(stmt.body, fi, held, local, chain)
            self._visit_body(stmt.orelse, fi, held, local, chain)
            return
        if isinstance(stmt, (ast.For, ast.While)):
            header = stmt.iter if isinstance(stmt, ast.For) else stmt.test
            for n in ast.walk(header):
                self._visit_call(n, fi, held, local, chain)
            self._visit_body(stmt.body, fi, held, local, chain)
            self._visit_body(stmt.orelse, fi, held, local, chain)
            return
        if isinstance(stmt, ast.Try):
            self._visit_body(stmt.body, fi, held, local, chain)
            for handler in stmt.handlers:
                self._visit_body(handler.body, fi, held, local, chain)
            self._visit_body(stmt.orelse, fi, held, local, chain)
            self._visit_body(stmt.finalbody, fi, held, local, chain)
            return
        # Simple statement: every call in it runs with `held` held.
        for node in ast.walk(stmt):
            self._visit_call(node, fi, held, local, chain)

    def _visit_call(self, node, fi: FuncInfo, held: FrozenSet[str],
                    local: FrozenSet[str], chain: Tuple[str, ...]) -> None:
        if not isinstance(node, ast.Call):
            return
        callees = self.project.resolve_call(node, fi)
        if local:
            hot = sorted(h for h in local
                         if h.split(".", 1)[-1] in HOT_LOCK_ATTRS)
            if hot:
                attr = _call_attr(node)
                blocking = attr in KNOWN_BLOCKERS or (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "sleep")
                if not blocking and callees:
                    blocking = any(self.project.is_blocking(c)
                                   for c in callees)
                if blocking:
                    site = LockSite(hot[0], fi.rel, node.lineno, fi.qualname)
                    desc = attr or (node.func.id if isinstance(
                        node.func, ast.Name) else "<call>")
                    self.report.blocking_under_hot.append((site, desc))
        for callee in callees:
            self._visit_func(callee, held, chain + (fi.qualname,))

    def _find_cycles(self) -> None:
        graph: Dict[str, List[str]] = {}
        for (h, a) in self.report.edges:
            graph.setdefault(h, []).append(a)
        seen_cycles: Set[FrozenSet[str]] = set()
        # For each node, BFS for the shortest path back to itself; a
        # cycle is recorded once, keyed by its node set.
        for start in sorted(graph):
            parent: Dict[str, str] = {}
            queue = [start]
            found = None
            while queue and found is None:
                cur = queue.pop(0)
                for nxt in sorted(graph.get(cur, ())):
                    if nxt == start:
                        found = cur
                        break
                    if nxt not in parent:
                        parent[nxt] = cur
                        queue.append(nxt)
            if found is None:
                continue
            path = [found]
            while path[-1] != start:
                path.append(parent[path[-1]])
            path.reverse()            # start .. found
            cyc = path + [start]      # start .. found -> start
            key = frozenset(path)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            edges = [self.report.edges[(cyc[i], cyc[i + 1])]
                     for i in range(len(cyc) - 1)]
            self.report.cycles.append(edges)
        # Deterministic order for stable output.
        self.report.cycles.sort(
            key=lambda es: tuple(e.acquired for e in es))


def lock_report(project: Project) -> LockReport:
    """The (cached) whole-project lock analysis."""
    cached = project._summaries.get(("lock_report", ""))
    if cached is None:
        cached = _LockWalker(project).run()
        project._summaries[("lock_report", "")] = cached
    return cached  # type: ignore[return-value]
