"""graftlint — AST-based invariant checks for the bigdl_tpu codebase.

Entry points: ``bigdl-tpu lint`` (cli.py), ``scripts/ci.sh --lint``,
and programmatically::

    from bigdl_tpu.analysis import run
    rc = run()          # 0 clean, 1 findings, 2 config error

IMPORTANT: this package (and everything it imports) must never import
jax — the lint gate runs on any machine in seconds and ci.sh --lint
asserts jax stayed out of sys.modules. See docs/static-analysis.md.
"""

from bigdl_tpu.analysis.core import (  # noqa: F401
    DEFAULT_BASELINE,
    Check,
    FileContext,
    Finding,
    apply_baseline,
    lint_paths,
    lint_text,
    load_baseline,
    run,
    stale_baseline_entries,
    write_baseline,
)
