"""graftlint core: a small AST-walking lint framework that machine-
enforces this codebase's cross-cutting invariants.

PRs 6-11 each earned a convention — every engine timestamp flows
through an injectable ``clock=`` (PR 11), every on-disk artifact
commits via ``durability.atomic_write`` (PR 7), every fault point is
declared in its injector's ``points`` registry (PR 6/7/10),
``_stat_lock``-guarded engine state is never read bare (PR 11's
scrape-500 race), metric families cannot drift from
``expected_families`` (PR 11), journal/event-log lines carry a crc
suffix (PR 7/10) — but until this module each was enforced only by
reviewer memory. The INT4 composability study (arxiv 2301.12017) shows
the failure mode precisely: individually-correct changes composing
into silent breakage. graftlint turns the conventions into CI-gated,
file:line-reported checks (docs/static-analysis.md).

Design constraints:

- **No jax import, ever.** The lint gate runs per-PR on any machine in
  seconds; ``scripts/ci.sh --lint`` asserts ``jax`` never entered
  ``sys.modules``. Checks therefore work purely on ``ast`` trees and
  source text.
- **One parse per file.** Every check receives the same
  :class:`FileContext`; a full-tree run stays well under the 10 s
  budget.
- **Suppressable, with receipts.** An inline
  ``# graftlint: disable=RULE`` on the offending line (or the line
  above it) silences a finding at the site, visible in review. The
  checked-in baseline (``bigdl_tpu/analysis/baseline.json``) grandfathers
  accepted findings — each entry carries a one-line justification —
  so new violations fail CI while the baseline shrinks over time.

Checks live in :mod:`bigdl_tpu.analysis.checks`; the CLI entry is
``bigdl-tpu lint`` (cli.py) and the CI gate is ``scripts/ci.sh --lint``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Optional, Sequence

#: inline suppression: ``# graftlint: disable=WCT001`` or
#: ``disable=WCT001,ATW001`` or ``disable=all`` — honored on the
#: finding's own line and on the line immediately above it.
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([\w,]+)")

#: default scan root: the installed bigdl_tpu package directory
PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: default baseline location (ships with the package, checked in)
DEFAULT_BASELINE = os.path.join(
    PACKAGE_DIR, "analysis", "baseline.json"
)


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored to a file:line.

    ``code`` (the stripped source line) is the line-number-insensitive
    fingerprint component: baseline entries match on
    ``(rule, path, code)`` so unrelated edits shifting line numbers
    don't invalidate the baseline."""

    rule: str
    path: str  # scan-root-relative, '/'-separated (e.g. bigdl_tpu/serving/engine.py)
    line: int
    message: str
    hint: str = ""
    severity: str = "error"
    code: str = ""

    def key(self) -> tuple:
        return (self.rule, self.path, self.code)

    def format(self) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            s += f"\n    fix: {self.hint}"
        return s


@dataclasses.dataclass
class FileContext:
    """Everything a check needs about one file — parsed exactly once."""

    path: str  # absolute
    rel: str  # scan-root-relative, '/'-separated
    src: str
    lines: list  # src.splitlines()
    tree: ast.Module
    root: str  # absolute scan root (the bigdl_tpu package's parent)


class Check:
    """Protocol for a rule: subclass, set ``rule``/``description``,
    implement :meth:`run` yielding findings (``line``/``message`` set;
    the runner fills ``code`` and applies suppressions/baseline)."""

    rule: str = "XXX000"
    description: str = ""

    def run(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


# ---------------------------------------------------------------------------
# AST helpers shared by the checks
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``time.time`` / ``datetime.datetime.now`` / ``open`` for a Call's
    func expression; None when the callee isn't a plain dotted name."""
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def docstring_nodes(tree: ast.Module) -> set:
    """id()s of every docstring Constant (module/class/function) so
    string-scanning checks can skip prose."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                out.add(id(body[0].value))
    return out


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------

def _suppressed_rules(line_text: str) -> set:
    m = _SUPPRESS_RE.search(line_text)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def is_suppressed(f: Finding, lines: Sequence[str]) -> bool:
    """Inline suppression on the finding's line or the line above."""
    for ln in (f.line, f.line - 1):
        if 1 <= ln <= len(lines):
            rules = _suppressed_rules(lines[ln - 1])
            if "all" in rules or f.rule in rules:
                return True
    return False


def load_baseline(path: str) -> list:
    """Baseline entries: ``{rule, path, code, justification}`` dicts.
    A missing file is an empty baseline (the desired end state)."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("findings", data) if isinstance(data, dict) else data
    for e in entries:
        if not e.get("justification"):
            raise ValueError(
                f"baseline entry {e.get('rule')}:{e.get('path')} lacks a "
                "justification — every grandfathered finding must say why"
            )
    return list(entries)


def apply_baseline(findings: Sequence[Finding], baseline: Sequence[dict]
                   ) -> tuple:
    """(new, grandfathered): findings not covered by the baseline, and
    the ones it absorbs. Matching is on (rule, path, code) — immune to
    line-number drift, invalidated the moment the offending line's text
    changes."""
    keys = {(e["rule"], e["path"], e["code"]) for e in baseline}
    new, old = [], []
    for f in findings:
        (old if f.key() in keys else new).append(f)
    return new, old


def write_baseline(findings: Sequence[Finding], path: str,
                   justification: str = "TODO: justify or fix",
                   previous: Sequence[dict] = ()) -> None:
    """Serialize current findings as the new baseline, carrying over
    the justification of any entry that survives from `previous`.
    Deliberately NOT atomic-write: this is a dev-workstation
    convenience writing a file that git tracks, not a runtime
    artifact."""
    carried = {(e["rule"], e["path"], e["code"]): e.get("justification")
               for e in previous}
    entries = [
        {"rule": f.rule, "path": f.path, "code": f.code,
         "line": f.line,
         "justification": carried.get(f.key()) or justification}
        for f in findings
    ]
    with open(path, "w", encoding="utf-8") as fh:  # graftlint: disable=ATW001
        json.dump({"findings": entries}, fh, indent=2, sort_keys=False)
        fh.write("\n")


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def default_checks() -> list:
    from bigdl_tpu.analysis.checks import ALL_CHECKS

    return [c() for c in ALL_CHECKS]


def iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _rel(path: str, root: str) -> str:
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    if not rel.startswith(".."):
        return rel
    # an explicit path argument outside the scan root: anchor at the
    # deepest bigdl_tpu component so the path-scoped rules (WCT001,
    # FLT001) still see "bigdl_tpu/serving/..." instead of "../.."
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "bigdl_tpu" in parts:
        i = len(parts) - 1 - parts[::-1].index("bigdl_tpu")
        return "/".join(parts[i:])
    return rel


def lint_text(src: str, rel: str, root: Optional[str] = None,
              checks: Optional[Sequence[Check]] = None) -> list:
    """Lint one in-memory source blob as if it lived at ``rel`` under
    ``root`` — the fixture-test entry point. Suppressions apply;
    baseline does not."""
    root = root or os.path.dirname(PACKAGE_DIR)
    checks = list(checks) if checks is not None else default_checks()
    lines = src.splitlines()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("PARSE", rel, e.lineno or 1,
                        f"syntax error: {e.msg}", severity="error")]
    ctx = FileContext(path=os.path.join(root, rel), rel=rel, src=src,
                      lines=lines, tree=tree, root=root)
    out = []
    for chk in checks:
        for f in chk.run(ctx):
            if not f.code and 1 <= f.line <= len(lines):
                f.code = lines[f.line - 1].strip()
            if not is_suppressed(f, lines):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_paths(paths: Optional[Sequence[str]] = None,
               root: Optional[str] = None,
               checks: Optional[Sequence[Check]] = None) -> list:
    """Lint files/directories (default: the whole bigdl_tpu package).
    Returns all unsuppressed findings; baseline filtering is the
    caller's second step (see :func:`apply_baseline`)."""
    root = os.path.abspath(root) if root else os.path.dirname(PACKAGE_DIR)
    checks = list(checks) if checks is not None else default_checks()
    targets: list = []
    if not paths:
        targets = list(iter_py_files(PACKAGE_DIR))
    else:
        for p in paths:
            p = os.path.abspath(p)
            if os.path.isdir(p):
                targets.extend(iter_py_files(p))
            else:
                targets.append(p)
    findings: list = []
    for path in targets:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            findings.append(Finding("IO", _rel(path, root), 1, str(e)))
            continue
        findings.extend(lint_text(src, _rel(path, root), root=root,
                                  checks=checks))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# CLI body (bigdl-tpu lint delegates here; returns the exit code)
# ---------------------------------------------------------------------------

def stale_baseline_entries(baseline: Sequence[dict],
                           findings: Sequence[Finding]) -> list:
    """Baseline entries matching no current finding — each is itself an
    error (the violation was fixed; the entry must go), reported as a
    BASE001 finding so the baseline monotonically shrinks."""
    live = {f.key() for f in findings}
    out = []
    for e in baseline:
        if (e.get("rule"), e.get("path"), e.get("code")) not in live:
            out.append(Finding(
                rule="BASE001", path=e.get("path", "?"),
                line=int(e.get("line", 0) or 0),
                message=(f"stale baseline entry for {e.get('rule')} — no "
                         "current finding matches its code line; the "
                         "violation was fixed, so the entry must be "
                         "removed"),
                hint="run `bigdl-tpu lint --update-baseline` (drops "
                     "stale entries, keeps surviving justifications)",
                code=e.get("code", ""),
            ))
    return out


def _emit(new: Sequence[Finding], grandfathered: Sequence[Finding],
          fmt: str, out) -> None:
    if fmt == "json":
        doc = {
            "findings": [dataclasses.asdict(f) for f in new],
            "baselined": len(grandfathered),
        }
        print(json.dumps(doc, indent=2), file=out)
        return
    if fmt == "github":
        # GitHub workflow-command annotations: one line per finding,
        # surfaced inline on the PR diff by the Actions runner.
        for f in new:
            msg = f.message + (f" (fix: {f.hint})" if f.hint else "")
            # newlines/`::` would terminate the workflow command early
            msg = msg.replace("%", "%25").replace("\n", "%0A")
            print(f"::error file={f.path},line={f.line},"
                  f"title=graftlint {f.rule}::{msg}", file=out)
        print(f"graftlint: {len(new)} finding(s), "
              f"{len(grandfathered)} baselined", file=out)
        return
    for f in new:
        print(f.format(), file=out)
    tail = (f"graftlint: {len(new)} finding(s)"
            + (f" ({len(grandfathered)} baselined)" if grandfathered else "")
            + f" across {len({f.path for f in new}) if new else 0} file(s)")
    print(tail, file=out)


def run(paths: Optional[Sequence[str]] = None,
        baseline_path: Optional[str] = None,
        rules: Optional[Sequence[str]] = None,
        write_baseline_path: Optional[str] = None,
        out=None, fmt: str = "human",
        update_baseline: bool = False) -> int:
    """Full lint run: scan, subtract baseline, print, exit code.
    0 = clean; 1 = non-baselined findings (or stale baseline entries);
    2 = usage/config error.

    ``fmt`` selects the output: "human" (default), "json" (one document
    with every finding), or "github" (``::error`` annotation lines).
    ``update_baseline`` regenerates the baseline in place from the
    current findings — justifications of surviving entries carry over,
    stale entries drop."""
    import sys

    out = out or sys.stdout
    if fmt not in ("human", "json", "github"):
        print(f"graftlint: unknown format {fmt!r} "
              "(choose human, json, github)", file=out)
        return 2
    if (write_baseline_path or update_baseline) and (paths or rules):
        # a filtered scan sees only a slice of the findings; writing it
        # as THE baseline would silently drop every grandfathered entry
        # outside the slice, and the next full run would fail on them
        print("graftlint: --write-baseline/--update-baseline require a "
              "full, unfiltered scan (no paths, no --rules)", file=out)
        return 2
    checks = default_checks()
    if rules:
        want = {r.strip().upper() for r in rules}
        known = {c.rule for c in checks}
        unknown = want - known
        if unknown:
            print(f"graftlint: unknown rule(s) {sorted(unknown)}; "
                  f"known: {sorted(known)}", file=out)
            return 2
        checks = [c for c in checks if c.rule in want]
    findings = lint_paths(paths, checks=checks)
    bl_path = baseline_path or DEFAULT_BASELINE
    try:
        baseline = load_baseline(bl_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"graftlint: bad baseline {bl_path}: {e}", file=out)
        return 2
    new, grandfathered = apply_baseline(findings, baseline)
    if update_baseline:
        write_baseline(findings, bl_path, previous=baseline)
        live = {f.key() for f in findings}
        surviving = sum(
            1 for e in baseline
            if (e.get("rule"), e.get("path"), e.get("code")) in live)
        print(f"graftlint: baseline {bl_path} now carries "
              f"{len(findings)} entry(ies) "
              f"({len(baseline) - surviving} stale dropped, "
              f"{surviving} justification(s) preserved); "
              "new entries need their TODO justifications filled in",
              file=out)
        return 0
    if write_baseline_path:
        write_baseline(findings, write_baseline_path, previous=baseline)
        print(f"graftlint: wrote {len(findings)} finding(s) to "
              f"{write_baseline_path}", file=out)
        return 0
    # baseline hygiene: on a full scan, an entry absorbing nothing is
    # itself an error (partial scans can't judge staleness — a filtered
    # run legitimately misses findings the entry still matches)
    if not paths and not rules:
        new = list(new) + stale_baseline_entries(baseline, findings)
    _emit(new, grandfathered, fmt, out)
    return 1 if new else 0
