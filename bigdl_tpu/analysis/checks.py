"""graftlint rules: the codebase's serving/training contracts as AST
checks (rule table + rationale in docs/static-analysis.md).

==========  ===============================================================
rule        invariant
==========  ===============================================================
``WCT001``  no wall-clock *calls* in serving/, obs/, sim/,
            train/supervisor.py, parallel/health.py — timestamps flow
            through the injectable ``clock=`` (PR 11; sim/ added by
            ISSUE 13: the simulator must be wall-clock-free or its
            reports stop being reproducible); referencing ``time.time``
            as a default clock implementation is fine, *calling* it is
            not
``ATW001``  no bare ``open(..., "w"/"wb")`` anywhere in bigdl_tpu/ —
            artifacts commit via ``utils/durability.atomic_write`` (PR 7);
            append-mode logs are exempt (append-only is its own protocol)
``FLT001``  every ``.fire("p")`` / ``.arm("p")`` names a point declared in
            the scoped injector registry (serving/faults.POINTS,
            train/supervisor.POINTS, utils/diskfaults.DISK_POINTS)
``LCK001``  attributes carrying a ``# guarded-by: <lock>`` annotation are
            only touched inside ``with self.<lock>:`` (outside the
            constructor) — the kv_pool_utilization scrape-500 bug class
``MET001``  serving/metrics.py family names reconciled two-way against the
            ``expected_families`` registry tuples, statically (no jax)
``DON001``  a variable passed at a donating jit call site
            (``donate_argnums``/``donate_argnames``) is not read again
            afterwards in the same function without rebinding
``CRC001``  JSONL journal/event-log lines (``.write`` of a ``json.dumps``)
            go through ``serving/journal.crc_line``
==========  ===============================================================
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from bigdl_tpu.analysis.core import (
    Check, FileContext, Finding, const_str, docstring_nodes, dotted_name,
)


# ---------------------------------------------------------------------------
# WCT001 — wall-clock ban
# ---------------------------------------------------------------------------

class WallClockBan(Check):
    rule = "WCT001"
    description = (
        "wall-clock calls in clock-injected subsystems (serving/, obs/, "
        "train/supervisor.py, parallel/health.py, "
        "parallel/qcollectives.py)"
    )

    SCOPES = (
        "bigdl_tpu/serving/",
        "bigdl_tpu/obs/",
        "bigdl_tpu/sim/",  # the simulator IS the fake-clock domain: one
        # wall-clock call would silently re-couple reports to the host
        "bigdl_tpu/train/supervisor.py",
        "bigdl_tpu/parallel/health.py",
        # collectives run inside jit traces priced by roofline/sim
        # models — any host-clock call there is a trace-time landmine
        "bigdl_tpu/parallel/qcollectives.py",
    )
    BANNED = {
        "time.time", "time.time_ns", "time.monotonic",
        "time.monotonic_ns", "time.perf_counter", "time.perf_counter_ns",
        "datetime.now", "datetime.utcnow", "datetime.datetime.now",
        "datetime.datetime.utcnow", "datetime.date.today",
    }

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if not any(ctx.rel.startswith(s) or ctx.rel == s.rstrip("/")
                   for s in self.SCOPES):
            return
        # `from time import monotonic [as m]` / `from datetime import
        # datetime as dt` would otherwise bypass the dotted-name match:
        # map the local alias back to its fully-qualified spelling
        aliased: dict = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module in (
                    "time", "datetime"):
                for a in node.names:
                    aliased[a.asname or a.name] = f"{node.module}.{a.name}"
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name:
                head, _, rest = name.partition(".")
                if head in aliased:
                    name = aliased[head] + (f".{rest}" if rest else "")
            if name in self.BANNED:
                yield Finding(
                    self.rule, ctx.rel, node.lineno,
                    f"wall-clock call {name}() in a clock-injected "
                    "subsystem",
                    hint="route the timestamp through the injectable "
                         "clock= (engine/ApiServer/TraceRecorder ctor "
                         "arg); keep wall-clock references only as "
                         "default clock implementations",
                )


# ---------------------------------------------------------------------------
# ATW001 — non-atomic writes
# ---------------------------------------------------------------------------

class AtomicWriteBan(Check):
    rule = "ATW001"
    description = (
        "bare write-mode open() outside utils/durability.py's atomic "
        "protocol"
    )

    EXEMPT_FILES = ("bigdl_tpu/utils/durability.py",)

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel in self.EXEMPT_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in ("open", "io.open"):
                continue
            mode = None
            if len(node.args) >= 2:
                mode = const_str(node.args[1])
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = const_str(kw.value)
            if mode is None:
                continue  # default "r", or dynamic (can't tell statically)
            if "w" in mode or "x" in mode:
                yield Finding(
                    self.rule, ctx.rel, node.lineno,
                    f"non-atomic write-mode open(..., {mode!r}) — a kill "
                    "mid-write leaves a torn artifact",
                    hint="commit through utils/durability.atomic_write"
                         "(path, writer) (tmp + fsync + rename); append-"
                         "mode journals are exempt by design",
                )


# ---------------------------------------------------------------------------
# FLT001 — fault-point validity
# ---------------------------------------------------------------------------

class FaultPointValidity(Check):
    rule = "FLT001"
    description = (
        ".fire()/.arm() strings must be declared injector points "
        "(serving/faults, train/supervisor, utils/diskfaults registries)"
    )

    #: registry source file -> module-level tuple constant holding the
    #: declared points
    REGISTRIES = (
        ("serving", "bigdl_tpu/serving/faults.py", "POINTS"),
        ("train", "bigdl_tpu/train/supervisor.py", "POINTS"),
        ("disk", "bigdl_tpu/utils/diskfaults.py", "DISK_POINTS"),
    )

    def __init__(self):
        # one registry parse per scan root, not per linted file — the
        # three source files would otherwise be re-parsed ~100x per run
        self._reg_cache: dict = {}

    def _load_registries(self, root: str) -> dict:
        if root in self._reg_cache:
            return self._reg_cache[root]
        regs: dict = {}
        for key, rel, const in self.REGISTRIES:
            path = os.path.join(root, rel.replace("/", os.sep))
            if not os.path.exists(path):
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            for node in tree.body:
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == const):
                    try:
                        val = ast.literal_eval(node.value)
                    except ValueError:
                        continue
                    if isinstance(val, (tuple, list)) and all(
                            isinstance(v, str) for v in val):
                        regs[key] = set(val)
        self._reg_cache[root] = regs
        return regs

    def _scope(self, rel: str, regs: dict) -> tuple:
        """(scope label, allowed point set) for a file. parallel/ rides
        the train registry: health.py fires the supervisor's rank_drop."""
        if (rel.startswith("bigdl_tpu/serving/")
                or rel.startswith("bigdl_tpu/sim/")):
            # sim/ composes the SERVING injector (chaos traces arm
            # slow_step/alloc_page against the simulated engine), so its
            # fault points are checked against the serving registry
            return "serving", regs.get("serving", set())
        if (rel.startswith("bigdl_tpu/train/")
                or rel.startswith("bigdl_tpu/parallel/")):
            return "train", regs.get("train", set())
        if rel.startswith("bigdl_tpu/utils/"):
            return "disk", regs.get("disk", set())
        union: set = set()
        for s in regs.values():
            union |= s
        return "any", union

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        regs = self._load_registries(ctx.root)
        if not regs:
            return
        scope, allowed = self._scope(ctx.rel, regs)
        if not allowed:
            return
        for node in ast.walk(ctx.tree):
            if (not isinstance(node, ast.Call)
                    or not isinstance(node.func, ast.Attribute)
                    or node.func.attr not in ("fire", "arm")
                    or not node.args):
                continue
            point = const_str(node.args[0])
            if point is None or point in allowed:
                continue
            yield Finding(
                self.rule, ctx.rel, node.lineno,
                f".{node.func.attr}({point!r}) names no declared "
                f"injection point of the {scope} registry",
                hint=f"declare it in the injector's points tuple or use "
                     f"one of: {', '.join(sorted(allowed))}",
            )


# ---------------------------------------------------------------------------
# LCK001 — lock discipline
# ---------------------------------------------------------------------------

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")


class LockDiscipline(Check):
    rule = "LCK001"
    description = (
        "# guarded-by: <lock> annotated attributes accessed outside "
        "`with self.<lock>:` (outside the constructor)"
    )

    @staticmethod
    def _guarded_attrs(ctx: FileContext, cls: ast.ClassDef) -> dict:
        """{attr: lock} from guarded-by comments attached to self.attr
        assignments in this class (trailing comment on the assignment
        line, or a comment on the line directly above it)."""
        assigns: list = []  # (lineno, end_lineno, attr, fn_name)
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        assigns.append((node.lineno,
                                        node.end_lineno or node.lineno,
                                        t.attr, fn.name))
        guarded: dict = {}
        for i, text in enumerate(ctx.lines, start=1):
            m = _GUARD_RE.search(text)
            if not m:
                continue
            lock = m.group(1)
            # trailing comment on the assignment's own line(s) wins; the
            # comment-above form applies only when the annotation line
            # holds no assignment itself (else a trailing annotation
            # would also leak onto the NEXT attribute)
            on_line = [(a, f) for lo, hi, a, f in assigns if lo <= i <= hi]
            if on_line:
                for attr, fn_name in on_line:
                    guarded[attr] = (lock, fn_name)
                continue
            for lo, _hi, attr, fn_name in assigns:
                if lo == i + 1:
                    guarded[attr] = (lock, fn_name)
        return guarded

    def _visit(self, node, guarded: dict, ctx: FileContext,
               held: frozenset, out: list) -> None:
        """Recursive walk tracking which `self.<lock>`s are held."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def may run long after the enclosing with exits:
            # its body is scanned as holding nothing
            for child in ast.iter_child_nodes(node):
                self._visit(child, guarded, ctx, frozenset(), out)
            return
        if isinstance(node, ast.With):
            locks = set()
            for item in node.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"):
                    locks.add(ce.attr)
                # the header expressions themselves evaluate unlocked
                self._visit(ce, guarded, ctx, held, out)
            for stmt in node.body:
                self._visit(stmt, guarded, ctx, held | frozenset(locks),
                            out)
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr in guarded):
            lock, _ = guarded[node.attr]
            if lock not in held:
                out.append(Finding(
                    self.rule, ctx.rel, node.lineno,
                    f"self.{node.attr} is annotated guarded-by {lock} "
                    f"but accessed outside `with self.{lock}:`",
                    hint=f"take `with self.{lock}:` around the access "
                         "(or move it into the guarded helper)",
                ))
        for child in ast.iter_child_nodes(node):
            self._visit(child, guarded, ctx, held, out)

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if "guarded-by:" not in ctx.src:
            return
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = self._guarded_attrs(ctx, cls)
            if not guarded:
                continue
            init_fns = {fn for (_, fn) in guarded.values()}
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name in init_fns:
                    # the annotating constructor runs before any other
                    # thread can exist — bare init writes are the point
                    continue
                out: list = []
                for stmt in fn.body:
                    self._visit(stmt, guarded, ctx, frozenset(), out)
                yield from out


# ---------------------------------------------------------------------------
# MET001 — static metrics drift
# ---------------------------------------------------------------------------

class MetricsDrift(Check):
    rule = "MET001"
    description = (
        "serving/metrics.py family names reconciled against the "
        "expected_families registry tuples, two-way, without importing jax"
    )

    TARGET = "bigdl_tpu/serving/metrics.py"
    REGISTRY_NAMES = ("_PROCESS_FAMILIES", "_ENGINE_FAMILIES",
                      "_PAGED_FAMILIES", "_SPEC_FAMILIES",
                      "_ADAPTER_FAMILIES")
    _TYPE_RE = re.compile(r"# TYPE (bigdl_tpu_\w+) ")
    _FAMILY_RE = re.compile(r"^(bigdl_tpu_\w+)(?:$|[\s{])")

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel != self.TARGET:
            return
        registry: dict = {}  # family -> lineno
        registry_spans: list = []
        for node in ctx.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id in self.REGISTRY_NAMES):
                registry_spans.append(
                    (node.lineno, node.end_lineno or node.lineno))
                try:
                    for fam in ast.literal_eval(node.value):
                        registry.setdefault(fam, node.lineno)
                except ValueError:
                    yield Finding(
                        self.rule, ctx.rel, node.lineno,
                        f"{node.targets[0].id} is not a literal tuple of "
                        "strings — the registry must be statically "
                        "readable",
                    )
        docstrings = docstring_nodes(ctx.tree)
        rendered: dict = {}  # family -> lineno
        for node in ast.walk(ctx.tree):
            if (not isinstance(node, ast.Constant)
                    or not isinstance(node.value, str)
                    or id(node) in docstrings):
                continue
            if any(lo <= node.lineno <= hi for lo, hi in registry_spans):
                continue
            for fam in self._TYPE_RE.findall(node.value):
                rendered.setdefault(fam, node.lineno)
            m = self._FAMILY_RE.match(node.value)
            if m:
                rendered.setdefault(m.group(1), node.lineno)
        for fam in sorted(set(rendered) - set(registry)):
            yield Finding(
                self.rule, ctx.rel, rendered[fam],
                f"family {fam} is rendered but absent from the "
                "expected_families registry",
                hint="add it to the matching _*_FAMILIES tuple (the "
                     "runtime drift gate in ci --core enforces the same "
                     "invariant dynamically)",
            )
        for fam in sorted(set(registry) - set(rendered)):
            yield Finding(
                self.rule, ctx.rel, registry[fam],
                f"family {fam} is registered in expected_families but "
                "never constructed by render()",
                hint="render it or drop the registry entry",
            )


# ---------------------------------------------------------------------------
# DON001 — donation hazard
# ---------------------------------------------------------------------------

class DonationHazard(Check):
    rule = "DON001"
    description = (
        "a variable passed at a donating jit call site is read again in "
        "the same function without rebinding (its buffer is gone)"
    )

    @staticmethod
    def _donation(call: ast.Call) -> Optional[tuple]:
        """(argnums, argnames) when ``call`` is a jax.jit/pjit with
        donation; None otherwise."""
        name = dotted_name(call.func)
        if name not in ("jax.jit", "jit", "jax.pjit", "pjit"):
            return None
        nums: list = []
        names: list = []
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                try:
                    v = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                nums = [v] if isinstance(v, int) else list(v)
            elif kw.arg == "donate_argnames":
                try:
                    v = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                names = [v] if isinstance(v, str) else list(v)
        if not nums and not names:
            return None
        return nums, names

    @staticmethod
    def _walk_local(fn) -> Iterable[ast.AST]:
        """fn's own nodes only — nested defs/lambdas have their own
        scopes (and their own _scan_function pass), so a same-named
        parameter or local inside one is a different variable."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._scan_function(fn, ctx)

    def _scan_function(self, fn, ctx: FileContext) -> Iterable[Finding]:
        # 1. locals bound to a donating jit
        jitted: dict = {}  # local name -> (argnums, argnames)
        calls: list = []  # (call node, argnums, argnames)
        local_nodes = list(self._walk_local(fn))
        for node in local_nodes:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                don = self._donation(node.value)
                if don and len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name):
                    jitted[node.targets[0].id] = don
        for node in local_nodes:
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) and \
                        node.func.id in jitted:
                    calls.append((node, *jitted[node.func.id]))
                elif isinstance(node.func, ast.Call):
                    # direct jax.jit(f, donate_*=...)(args)
                    don = self._donation(node.func)
                    if don:
                        calls.append((node, *don))
        if not calls:
            return
        # 2. per call: donated plain-Name arguments
        events: list = []  # (lineno, col, kind, name) kind: load|store
        for node in self._walk_local(fn):
            if isinstance(node, ast.Name):
                kind = ("store" if isinstance(node.ctx, (ast.Store,
                                                         ast.Del))
                        else "load")
                events.append((node.lineno, node.col_offset, kind,
                               node.id))
        events.sort()
        for call, nums, names in calls:
            donated: list = []  # (var, spelled)
            for i in nums:
                if 0 <= i < len(call.args) and isinstance(
                        call.args[i], ast.Name):
                    donated.append((call.args[i].id, f"argnum {i}"))
            for kw in call.keywords:
                if kw.arg in names and isinstance(kw.value, ast.Name):
                    donated.append((kw.value.id, f"argname {kw.arg!r}"))
            end = call.end_lineno or call.lineno
            for var, spelled in donated:
                for lineno, _col, kind, name in events:
                    if name != var or lineno < call.lineno:
                        continue
                    if kind == "store":
                        # rebound — including the canonical
                        # `x = g(x)` pattern, whose Store target sorts
                        # before the call's own argument Load — so the
                        # stale buffer is unreachable from here on
                        break
                    if lineno <= end:
                        continue  # the donated argument itself
                    yield Finding(
                        self.rule, ctx.rel, lineno,
                        f"{var!r} was donated at the jit call on line "
                        f"{call.lineno} ({spelled}) and read again here "
                        "— its buffer is deleted after the call",
                        hint="rebind the result over the donated name "
                             f"({var} = f({var}, ...)) or drop the "
                             "donation",
                    )
                    break  # one finding per donated var is enough


# ---------------------------------------------------------------------------
# CRC001 — journal-line discipline
# ---------------------------------------------------------------------------

class JournalLineDiscipline(Check):
    rule = "CRC001"
    description = (
        "JSONL journal/event-log writes (.write of a json.dumps line) "
        "must go through serving/journal.crc_line"
    )

    @classmethod
    def _trailing_const(cls, node):
        """Rightmost constant of a concat chain / f-string — the line
        terminator a JSONL write appends. None = not statically
        determinable (or no trailing literal at all)."""
        while True:
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
                node = node.right
                continue
            if isinstance(node, ast.JoinedStr) and node.values:
                node = node.values[-1]
                continue
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "encode"):
                node = node.func.value
                continue
            break
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bytes):
                v = v.decode("latin-1")
            if isinstance(v, str):
                return v
        return None

    def run(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if (not isinstance(node, ast.Call)
                    or not isinstance(node.func, ast.Attribute)
                    or node.func.attr != "write" or not node.args):
                continue
            arg = node.args[0]
            has_dumps = any(
                isinstance(s, ast.Call)
                and (dotted_name(s.func) or "").endswith("dumps")
                for s in ast.walk(arg)
            )
            if not has_dumps:
                continue
            # only JSONL *lines* are in scope: the payload must end with
            # exactly one newline. Whole-document JSON (config files,
            # trace exports) and wire protocols (SSE "data: ...\n\n",
            # FastChat's NUL-delimited stream) are different contracts.
            tail = self._trailing_const(arg)
            if tail is None or not tail.endswith("\n") \
                    or tail.endswith("\n\n"):
                continue
            has_crc = any(
                isinstance(s, ast.Call)
                and (dotted_name(s.func) or "").endswith("crc_line")
                for s in ast.walk(arg)
            )
            if has_crc:
                continue
            yield Finding(
                self.rule, ctx.rel, node.lineno,
                "JSONL record written without the crc-suffix line "
                "discipline — interior rot in this log would be "
                "undetectable",
                hint="wrap the body: f.write(journal.crc_line("
                     "json.dumps(rec)) + '\\n') (serving/journal.py)",
            )


from .interproc import INTERPROC_CHECKS  # noqa: E402 (checks need the
# Check/Finding definitions above via core; interproc imports from core
# directly so this late import only avoids a cosmetic cycle)

ALL_CHECKS = (
    WallClockBan,
    AtomicWriteBan,
    FaultPointValidity,
    LockDiscipline,
    MetricsDrift,
    DonationHazard,
    JournalLineDiscipline,
) + INTERPROC_CHECKS
