"""QTensor — a quantized tensor as a JAX pytree node.

The TPU-native counterpart of the reference's `FP4Params`
(/root/reference python/llm/src/ipex_llm/transformers/low_bit_linear.py:312):
instead of a torch.nn.Parameter subclass holding a ggml byte blob, a QTensor
is a registered dataclass whose array fields (packed codes, scales, mins)
are ordinary JAX arrays. That makes quantized weights first-class citizens
of every JAX transform: they can be donated, sharded with
`jax.sharding.NamedSharding`, carried through `lax.scan` over stacked
layers, and saved/restored as pytree leaves.

The logical shape is derived from the storage shape, so a QTensor sliced
along a leading (layer-stacking) axis by `lax.scan` remains self-consistent
without any static-metadata surgery.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.quant.numerics import dequantize_blockwise, quantize_blockwise
from bigdl_tpu.quant.qtypes import QTypeSpec, resolve_qtype


# array fields of a QTensor, in declaration order; sub_scales/sub_mins
# carry the integer sub-block scales of two-level (k-quant) formats
ARRAY_FIELDS = ("data", "scales", "mins", "sub_scales", "sub_mins")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QTensor:
    data: jax.Array
    scales: jax.Array
    mins: Optional[jax.Array] = None
    qtype: str = dataclasses.field(metadata=dict(static=True), kw_only=True)
    sub_scales: Optional[jax.Array] = None
    sub_mins: Optional[jax.Array] = None

    @property
    def spec(self) -> QTypeSpec:
        return resolve_qtype(self.qtype)

    @property
    def shape(self) -> tuple[int, ...]:
        spec = self.spec
        if spec.storage == "packed_u8":
            return (*self.data.shape[:-1], self.data.shape[-1] * 2)
        if spec.storage == "packed_planes":
            # planes store sum(planes) == spec.bits bits per element
            return (*self.data.shape[:-1], self.data.shape[-1] * 8 // spec.bits)
        return tuple(self.data.shape)

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return dequantize_blockwise(
            self.data, self.scales, self.mins, self.spec, dtype,
            sub_scales=self.sub_scales, sub_mins=self.sub_mins,
        )

    def map_arrays(self, fn) -> "QTensor":
        """New QTensor with `fn` applied to every non-None array field —
        the one place slice/stack/concat/shard rebuilds go through, so
        field additions don't scatter across call sites."""
        kw = {
            f: (None if getattr(self, f) is None else fn(getattr(self, f)))
            for f in ARRAY_FIELDS
        }
        return QTensor(qtype=self.qtype, **kw)

    def nbytes(self) -> int:
        n = 0
        for f in ARRAY_FIELDS:
            v = getattr(self, f)
            if v is not None:
                n += v.size * v.dtype.itemsize
        return n


def map_arrays_multi(ws: list["QTensor"], fn) -> "QTensor":
    """Combine several same-qtype QTensors field-wise (stack/concat):
    `fn` receives the list of arrays for each non-None field."""
    kw = {
        f: (None if getattr(ws[0], f) is None
            else fn([getattr(w, f) for w in ws]))
        for f in ARRAY_FIELDS
    }
    return QTensor(qtype=ws[0].qtype, **kw)


# k-quant fallbacks for tensors whose contraction dim is not a multiple
# of the 256-element super-block — same policy as llama.cpp, which drops
# incompatible tensors to a 32-block format of comparable width.
_KQUANT_FALLBACK = {
    "q2_k": "sym_int4", "q3_k": "sym_int4", "q4_k": "sym_int4",
    "q5_k": "sym_int5", "q6_k": "sym_int8",
}


def _effective_spec(last_dim: int, qtype: str):
    """The spec quantize() will actually use for a given last dim —
    including the k-quant superblock fallback."""
    spec = resolve_qtype(qtype)
    if (spec.superblock and last_dim % spec.superblock
            and spec.name in _KQUANT_FALLBACK):
        spec = resolve_qtype(_KQUANT_FALLBACK[spec.name])
    return spec


def quantize(x: jax.Array, qtype: str) -> QTensor:
    """Quantize `x` along its last axis into a QTensor.

    Equivalent of the reference's `FP4Params.quantize`
    (low_bit_linear.py:348): blockwise along the contraction axis.
    """
    spec = resolve_qtype(qtype)
    if spec.is_dense:
        raise ValueError(f"qtype {qtype} is dense; keep the array as-is")
    spec = _effective_spec(x.shape[-1], qtype)
    fields = quantize_blockwise(x, spec)
    return QTensor(qtype=spec.name, **fields)


def quantize_or_dense(x: jax.Array, qtype: str, what: str = "weight"):
    """quantize(), but weights whose last dim cannot take the format
    (not divisible by the effective block size, after the k-quant
    fallback) stay dense with a warning instead of failing the whole
    model — the reference's per-module gating behavior (convert.py's
    is_linear_module checks). Shared by every family's quantize_params."""
    spec = _effective_spec(x.shape[-1], qtype)
    if x.shape[-1] % spec.block_size:
        import warnings

        warnings.warn(
            f"{what}: last dim {x.shape[-1]} not divisible by "
            f"{spec.name}'s block size {spec.block_size}; keeping this "
            "weight dense"
        )
        return x
    return quantize(x, qtype)


def dequantize(qt: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    return qt.dequantize(dtype)
