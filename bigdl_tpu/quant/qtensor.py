"""QTensor — a quantized tensor as a JAX pytree node.

The TPU-native counterpart of the reference's `FP4Params`
(/root/reference python/llm/src/ipex_llm/transformers/low_bit_linear.py:312):
instead of a torch.nn.Parameter subclass holding a ggml byte blob, a QTensor
is a registered dataclass whose array fields (packed codes, scales, mins)
are ordinary JAX arrays. That makes quantized weights first-class citizens
of every JAX transform: they can be donated, sharded with
`jax.sharding.NamedSharding`, carried through `lax.scan` over stacked
layers, and saved/restored as pytree leaves.

The logical shape is derived from the storage shape, so a QTensor sliced
along a leading (layer-stacking) axis by `lax.scan` remains self-consistent
without any static-metadata surgery.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.quant.numerics import dequantize_blockwise, quantize_blockwise
from bigdl_tpu.quant.qtypes import QTypeSpec, resolve_qtype


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QTensor:
    data: jax.Array
    scales: jax.Array
    mins: Optional[jax.Array]
    qtype: str = dataclasses.field(metadata=dict(static=True))

    @property
    def spec(self) -> QTypeSpec:
        return resolve_qtype(self.qtype)

    @property
    def shape(self) -> tuple[int, ...]:
        spec = self.spec
        if spec.storage == "packed_u8":
            return (*self.data.shape[:-1], self.data.shape[-1] * 2)
        if spec.storage == "ggml_block":
            # data [..., n_superblocks, block_bytes]
            return (*self.data.shape[:-2], self.data.shape[-2] * spec.block_size)
        return tuple(self.data.shape)

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return dequantize_blockwise(self.data, self.scales, self.mins, self.spec, dtype)

    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize
        n += self.scales.size * self.scales.dtype.itemsize
        if self.mins is not None:
            n += self.mins.size * self.mins.dtype.itemsize
        return n


# k-quant fallbacks for tensors whose contraction dim is not a multiple
# of the 256-element super-block — same policy as llama.cpp, which drops
# incompatible tensors to a 32-block format of comparable width.
_KQUANT_FALLBACK = {
    "q2_k": "sym_int4", "q3_k": "sym_int4", "q4_k": "sym_int4",
    "q5_k": "sym_int5", "q6_k": "sym_int8",
}


def quantize(x: jax.Array, qtype: str) -> QTensor:
    """Quantize `x` along its last axis into a QTensor.

    Equivalent of the reference's `FP4Params.quantize`
    (low_bit_linear.py:348): blockwise along the contraction axis.
    """
    spec = resolve_qtype(qtype)
    if spec.is_dense:
        raise ValueError(f"qtype {qtype} is dense; keep the array as-is")
    if (spec.storage == "ggml_block" and x.shape[-1] % spec.block_size
            and spec.name in _KQUANT_FALLBACK):
        spec = resolve_qtype(_KQUANT_FALLBACK[spec.name])
    data, scales, mins = quantize_blockwise(x, spec)
    return QTensor(data=data, scales=scales, mins=mins, qtype=spec.name)


def dequantize(qt: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    return qt.dequantize(dtype)
