"""Quantization type registry.

Mirrors the reference's qtype enumeration (`ggml/quantize.py:28-57` in
/root/reference: sym_int4, asym_int4, sym_int8, nf4, fp4, fp8_e4m3,
fp8_e5m2, fp16, bf16, k-quants, ...), re-designed for TPU storage:

- 4-bit codes are nibble-packed two-per-uint8 along the contraction axis
  (XLA/Pallas unpack with shifts; HBM footprint = 0.5 byte/weight + scales).
- int8 codes are stored as int8.
- fp8 codes are stored as native XLA float8 dtypes (TPU v5 supports them).
- Scales (and mins for asymmetric types) are float16 per block, matching
  the reference's ggml half-precision `d`/`m` fields.

Each qtype is described by a `QTypeSpec`; numerics live in
`bigdl_tpu.quant.numerics`.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# 16-entry NormalFloat4 codebook (QLoRA paper / bitsandbytes); the reference
# consumes the same table inside its native kernels for qtype "nf4".
NF4_CODEBOOK = np.array(
    [
        -1.0,
        -0.6961928009986877,
        -0.5250730514526367,
        -0.39491748809814453,
        -0.28444138169288635,
        -0.18477343022823334,
        -0.09105003625154495,
        0.0,
        0.07958029955625534,
        0.16093020141124725,
        0.24611230194568634,
        0.33791524171829224,
        0.44070982933044434,
        0.5626170039176941,
        0.7229568362236023,
        1.0,
    ],
    dtype=np.float32,
)

# 8-entry NormalFloat3 codebook: quantiles of N(0,1) normalized to [-1, 1],
# with 0 included (same construction as nf4 with 3 bits).
NF3_CODEBOOK = np.array(
    [-1.0, -0.5350227355957031, -0.2469314038753510, 0.0,
     0.1833375245332718, 0.3819939494132996, 0.6229856610298157, 1.0],
    dtype=np.float32,
)

# FP4 (e2m1) magnitudes; sign bit is the top bit of the 4-bit code.
FP4_MAGNITUDES = np.array([0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0], dtype=np.float32)

# Signed 16-entry fp4 codebook indexed by the raw 4-bit code.
FP4_CODEBOOK = np.concatenate([FP4_MAGNITUDES, -FP4_MAGNITUDES]).astype(np.float32)

# FP6 (e2m3) magnitudes: 1 sign bit, 2 exponent bits, 3 mantissa bits.
# Values: for exp e in {0 (subnormal),1,2,3}: subnormals m/8*0.25? We use the
# standard e2m3 value set with bias 1: subnormal = m * 2**-3 * 2**0? To keep a
# simple monotone codebook we enumerate all 32 magnitudes below.
def _fp6_e2m3_magnitudes() -> np.ndarray:
    vals = []
    for e in range(4):
        for m in range(8):
            if e == 0:
                vals.append(m / 8.0 * 0.5)  # subnormals, scale 2**(1-bias)=0.5
            else:
                vals.append((1.0 + m / 8.0) * (2.0 ** (e - 1)) * 0.5)
    return np.array(vals, dtype=np.float32)


FP6_MAGNITUDES = _fp6_e2m3_magnitudes()
FP6_CODEBOOK = np.concatenate([FP6_MAGNITUDES, -FP6_MAGNITUDES]).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class QTypeSpec:
    name: str
    bits: int
    block_size: int  # elements sharing one scale along the contraction axis
    asymmetric: bool = False  # stores per-block mins in addition to scales
    codebook: np.ndarray | None = None  # LUT types (nf4/nf3/fp4/fp6)
    storage: str = "packed_u8"  # packed_u8 | packed_planes | int8 |
    # fp8_e4m3 | fp8_e5m2 | dense. packed_u8 = nibble pairs (half-split);
    # packed_planes = the multi-split generalization (see `planes`);
    # dense == not quantized (fp16/bf16 passthrough kept as plain arrays)
    block_bytes: int = 0  # ggml import/export codec: bytes per super-block
    # packed_planes: bit widths of the stored planes, low bits first
    # (e.g. fp6 = (4, 2): a half-split nibble plane + a quarter-split
    # 2-bit plane). A b-bit plane over K elements is K*b/8 bytes where
    # byte j carries elements j + m*(K*b/8) at bit offset b*m — the
    # multi-split generalization of pack_nibbles' half-split trick, so
    # both XLA and the Pallas GEMV unpack it with static shifts of
    # contiguous slices. Planes are concatenated along the last axis of
    # `data` in declaration order.
    planes: tuple = ()
    # two-level (super-block) scale factorization: the contraction axis
    # must be a multiple of this at encode time, and QTensor carries
    # per-super-block f16 scales (d, dmin) in scales/mins plus integer
    # sub-scales in sub_scales/sub_mins. 0 = single-level scales.
    superblock: int = 0

    @property
    def is_dense(self) -> bool:
        return self.storage == "dense"


_REGISTRY: dict[str, QTypeSpec] = {}


def _register(spec: QTypeSpec) -> QTypeSpec:
    _REGISTRY[spec.name] = spec
    return spec


# ggml Q4_0-compatible: block 32, signed scale from the max-|x| element.
SYM_INT4 = _register(QTypeSpec("sym_int4", bits=4, block_size=32))
# ggml Q4_1-compatible: block 32, scale + min.
ASYM_INT4 = _register(QTypeSpec("asym_int4", bits=4, block_size=32, asymmetric=True))
# ggml Q5_0-compatible numerics; codes 0..31 stored as a half-split
# nibble plane + an eighth-split 1-bit plane (5 bits/weight in HBM — the
# fused GEMV reads both planes in-kernel; was int8 codes until round 6).
SYM_INT5 = _register(QTypeSpec(
    "sym_int5", bits=5, block_size=32, storage="packed_planes", planes=(4, 1)
))
ASYM_INT5 = _register(
    QTypeSpec("asym_int5", bits=5, block_size=32, asymmetric=True, storage="int8")
)
# ggml Q8_0-compatible: block 32, absmax/127.
SYM_INT8 = _register(QTypeSpec("sym_int8", bits=8, block_size=32, storage="int8"))
NF4 = _register(QTypeSpec("nf4", bits=4, block_size=64, codebook=NF4_CODEBOOK))
NF3 = _register(QTypeSpec(
    "nf3", bits=3, block_size=64, codebook=NF3_CODEBOOK,
    storage="packed_planes", planes=(2, 1),
))
FP4 = _register(QTypeSpec("fp4", bits=4, block_size=64, codebook=FP4_CODEBOOK))
FP6 = _register(QTypeSpec(
    "fp6", bits=6, block_size=64, codebook=FP6_CODEBOOK,
    storage="packed_planes", planes=(4, 2),
))
FP8_E4M3 = _register(QTypeSpec("fp8_e4m3", bits=8, block_size=128, storage="fp8_e4m3"))
FP8_E5M2 = _register(QTypeSpec("fp8_e5m2", bits=8, block_size=128, storage="fp8_e5m2"))
# k-quants: 256-element super-blocks with two-level scales (ggml q4_K =
# 4.5 bit/weight, q6_K = 6.5625). llama.cpp's interleaved byte layout is
# a CPU-SIMD artifact; on TPU, EVERY k-quant lives in a PLANAR layout
# the Pallas fused GEMV can read (packed code planes + factored
# super-scales — see quant/kq_planar.py), with the exact byte-level
# repack done once at the GGUF / encoder boundary:
#   q2_k — quarter-split 2-bit plane, 4-bit sc/mn per 16 elements;
#   q3_k — int8 centered codes + int8 sc per 16 (exactly q6_k's planar
#          structure, so it shares the q6_k fused kernel);
#   q4_k/q5_k — half-split nibbles (+ eighth-split 1-bit plane for
#          q5_k), 6-bit sc/mn per 32;
#   q6_k — int8 centered codes + int8 sc per 16.
# KQUANT_LAYOUT is the single source of truth for the on-disk byte
# layouts: name -> (block_bytes, byte offset of the fp16 super-scale d).
# Consumed by quant/kquants.py (codecs), quant/kq_planar.py (repack),
# quant/numerics.py (encode) and convert/gguf.py (_BLOCK sizes).
KQUANT_LAYOUT = {
    "q2_k": (84, 80),
    "q3_k": (110, 108),
    "q4_k": (144, 0),
    "q5_k": (176, 0),
    "q6_k": (210, 208),
}
# q2_k planar: data = quarter-split packed 2-bit codes [.., K/4]
# (codes 0..3), scales/mins = d/dmin f16 [.., K/256], sub_scales/
# sub_mins = 4-bit sc/mn u8 [.., K/16];
# w = (d*sc)*q - (dmin*mn) per 16-element sub-block. 2.625 bit/weight.
Q2_K = _register(QTypeSpec(
    "q2_k", bits=2, block_size=16, storage="packed_planes", planes=(2,),
    block_bytes=84, asymmetric=True, superblock=256,
))
# q3_k planar: data = int8 centered codes (q-4 in [-4,3]) [.., K],
# scales = d f16 [.., K/256], sub_scales = int8 sc [.., K/16];
# w = (d*sc)*q per 16-element sub-block — structurally IDENTICAL to
# planar q6_k, so it shares q6_k's fused GEMV kernel. int8 code planes
# trade 3.35 -> 8.56 bit/weight for Mosaic lane alignment at every K
# (same tradeoff as q6_k below).
Q3_K = _register(QTypeSpec(
    "q3_k", bits=3, block_size=16, storage="int8", block_bytes=110,
    superblock=256,
))
# q4_k planar: data = half-split packed nibbles [.., K/2] (codes 0..15),
# scales = d f16 [.., K/256], mins = dmin f16 [.., K/256], sub_scales =
# 6-bit sc u8 [.., K/32], sub_mins = 6-bit mn u8 [.., K/32];
# w = (d*sc)*q - (dmin*mn), per 32-element sub-block. 4.625 bit/weight.
Q4_K = _register(QTypeSpec(
    "q4_k", bits=4, block_size=32, storage="packed_u8", block_bytes=144,
    asymmetric=True, superblock=256,
))
# q5_k planar: data = half-split packed nibbles [.., K/2] ++ eighth-
# split 1-bit plane [.., K/8] (codes 0..31), scales/mins = d/dmin f16
# [.., K/256], sub_scales/sub_mins = 6-bit sc/mn u8 [.., K/32];
# w = (d*sc)*q - (dmin*mn) per 32-element sub-block. 5.625 bit/weight.
Q5_K = _register(QTypeSpec(
    "q5_k", bits=5, block_size=32, storage="packed_planes", planes=(4, 1),
    block_bytes=176, asymmetric=True, superblock=256,
))
# q6_k planar: data = int8 codes (q-32) [.., K], scales = d f16
# [.., K/256], sub_scales = int8 sc [.., K/16]; w = (d*sc)*q per
# 16-element sub-block. 8.56 bit/weight (vs ggml's packed 6.56 — int8
# code planes keep Mosaic lane alignment for every K; a 4+2-bit packed
# plane needs K%1024 alignment llama2's 11008 lacks).
Q6_K = _register(QTypeSpec(
    "q6_k", bits=6, block_size=16, storage="int8", block_bytes=210,
    superblock=256,
))
FP16 = _register(QTypeSpec("fp16", bits=16, block_size=1, storage="dense"))
BF16 = _register(QTypeSpec("bf16", bits=16, block_size=1, storage="dense"))

for _name, (_bb, _d_off) in KQUANT_LAYOUT.items():
    assert _REGISTRY[_name].block_bytes == _bb, (
        f"{_name}: QTypeSpec.block_bytes != KQUANT_LAYOUT"
    )

# Aliases matching the reference's user-facing spellings
# (transformers/model.py: load_in_low_bit values).
_ALIASES = {
    "int4": "sym_int4",
    "q4_0": "sym_int4",
    "q4_1": "asym_int4",
    "q5_0": "sym_int5",
    "q5_1": "asym_int5",
    "int8": "sym_int8",
    "q8_0": "sym_int8",
    "fp8": "fp8_e5m2",  # reference maps plain "fp8" to e5m2 on most devices
    # the reference's *_rtn variants (ggml/quantize.py:53-55) skip its
    # MSE scale search; our blockwise quantizer IS round-to-nearest, so
    # they resolve to the base formats (the searched variant is
    # quant/imatrix.quantize_with_weights)
    "sym_int4_rtn": "sym_int4",
    "asym_int4_rtn": "asym_int4",
    "sym_int8_rtn": "sym_int8",
    "woq_int4": "sym_int4",
}


# mixed qtypes: body format + higher-precision lm head (reference
# gguf_mixed_qtype, ggml/quantize.py:60-61: *_s/*_m variants keep the
# output layer at q6_k)
MIXED_QTYPES = {
    "q2_k_s": ("q2_k", "q4_k"),
    "q3_k_s": ("q3_k", "q6_k"),
    "q3_k_m": ("q3_k", "q6_k"),
    "q4_k_s": ("q4_k", "q6_k"),
    "q4_k_m": ("q4_k", "q6_k"),
    "q5_k_s": ("q5_k", "q6_k"),
    "q5_k_m": ("q5_k", "q6_k"),
}


def split_mixed_qtype(name: str) -> tuple[str, "str | None"]:
    """(body_qtype, lm_head_qtype|None) — resolves the mixed aliases so
    every quantization entry point (optimize_model, quantize_params,
    from_gguf, from_pretrained) accepts them uniformly."""
    key = name.lower()
    if key in MIXED_QTYPES:
        return MIXED_QTYPES[key]
    return name, None


def qtype_registry() -> dict[str, QTypeSpec]:
    return dict(_REGISTRY)


def resolve_qtype(name: str) -> QTypeSpec:
    key = name.lower()
    key = _ALIASES.get(key, key)
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown qtype {name!r}; known: {sorted(_REGISTRY) + sorted(_ALIASES)}"
        )
    return _REGISTRY[key]
