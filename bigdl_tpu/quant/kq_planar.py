"""Exact repack between llama.cpp k-quant super-block bytes and the
TPU planar layout (numpy, host-side).

llama.cpp's q4_K/q6_K byte layouts interleave codes, packed 6-bit
sub-scales and fp16 super-scales inside 144/210-byte super-blocks — a
CPU-SIMD artifact. A Pallas kernel cannot slice those byte offsets
(Mosaic lane alignment), and XLA's in-graph byte decode materializes
bf16 weights in HBM, measured 2.7x slower end-to-end (BENCH_NOTES r03).
So on TPU a k-quant QTensor stores PLANES:

  q4_k: data      [.., K/2]   uint8  half-split packed 4-bit codes
        scales    [.., K/256] f16    super-scale d
        mins      [.., K/256] f16    super-scale dmin
        sub_scales[.., K/32]  uint8  6-bit sc (element-order sub-blocks)
        sub_mins  [.., K/32]  uint8  6-bit mn
        w[e] = (d*sc[e/32]) * q[e] - (dmin*mn[e/32])
  q6_k: data      [.., K]     int8   codes (q-32, element order)
        scales    [.., K/256] f16    super-scale d
        sub_scales[.., K/16]  int8   sc
        w[e] = (d*sc[e/16]) * q[e]

The repack is pure integer/f16-view work — bit-exact both ways — and
runs once at the GGUF import / encoder boundary (reference counterpart:
the verbatim ggml byte carry in transformers/gguf/models/*.py of
/root/reference, which XPU kernels can consume directly; TPU cannot).
Dequantized values are identical to quant/kquants.dequant_* because
f32(d)*f32(sc) is exact (11-bit x 6-bit mantissa) and evaluation order
matches.
"""

from __future__ import annotations

import numpy as np

QK_K = 256


def _f16_at(blocks: np.ndarray, off: int) -> np.ndarray:
    """fp16 scalar at byte offset `off` of each super-block."""
    return (
        blocks[..., off:off + 2].copy().view(np.float16)[..., 0]
    )


def _unpack_q4k_scales_np(sc_raw: np.ndarray):
    """12 packed bytes -> (sc [., 8], mn [., 8]) uint8 6-bit values
    (llama.cpp get_scale_min_k4; numpy mirror of kquants jnp version)."""
    sc = np.empty((*sc_raw.shape[:-1], 8), np.uint8)
    mn = np.empty_like(sc)
    for j in range(8):
        if j < 4:
            sc[..., j] = sc_raw[..., j] & 63
            mn[..., j] = sc_raw[..., j + 4] & 63
        else:
            sc[..., j] = (sc_raw[..., j + 4] & 0xF) | (
                (sc_raw[..., j - 4] >> 6) << 4
            )
            mn[..., j] = (sc_raw[..., j + 4] >> 4) | (
                (sc_raw[..., j] >> 6) << 4
            )
    return sc, mn


def q4k_codes(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 144] -> element-order codes [.., n_sb, 256] uint8."""
    qs = blocks[..., 16:144]
    out = np.empty((*blocks.shape[:-1], QK_K), np.uint8)
    for pair in range(4):
        grp = qs[..., 32 * pair:32 * (pair + 1)]
        out[..., 64 * pair:64 * pair + 32] = grp & 0xF
        out[..., 64 * pair + 32:64 * pair + 64] = grp >> 4
    return out


def from_q4k_blocks(blocks: np.ndarray) -> dict:
    """[.., n_sb, 144] super-block bytes -> planar QTensor fields."""
    d = _f16_at(blocks, 0)  # [.., n_sb]
    dmin = _f16_at(blocks, 2)
    sc, mn = _unpack_q4k_scales_np(blocks[..., 4:16])  # [.., n_sb, 8]
    codes = q4k_codes(blocks)

    lead = blocks.shape[:-2]
    k = blocks.shape[-2] * QK_K
    codes = codes.reshape(*lead, k)
    half = k // 2
    data = codes[..., :half] | (codes[..., half:] << 4)
    return dict(
        data=data,
        scales=d,
        mins=dmin,
        sub_scales=sc.reshape(*lead, k // 32),
        sub_mins=mn.reshape(*lead, k // 32),
    )


def q6k_codes(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 210] -> element-order centered codes [.., n_sb, 256]
    int8 (q - 32 in [-32, 31])."""
    ql = blocks[..., 0:128]
    qh = blocks[..., 128:192]
    out = np.empty((*blocks.shape[:-1], QK_K), np.int8)
    for half in range(2):
        l1 = ql[..., 64 * half:64 * half + 32]
        l2 = ql[..., 64 * half + 32:64 * half + 64]
        h = qh[..., 32 * half:32 * half + 32]
        base = 128 * half
        out[..., base:base + 32] = (
            ((l1 & 0xF) | ((h & 3) << 4)).astype(np.int8) - 32
        )
        out[..., base + 32:base + 64] = (
            ((l2 & 0xF) | (((h >> 2) & 3) << 4)).astype(np.int8) - 32
        )
        out[..., base + 64:base + 96] = (
            ((l1 >> 4) | (((h >> 4) & 3) << 4)).astype(np.int8) - 32
        )
        out[..., base + 96:base + 128] = (
            ((l2 >> 4) | (((h >> 6) & 3) << 4)).astype(np.int8) - 32
        )
    return out


def from_q6k_blocks(blocks: np.ndarray) -> dict:
    """[.., n_sb, 210] super-block bytes -> planar QTensor fields."""
    d = _f16_at(blocks, 208)
    sc = blocks[..., 192:208].view(np.int8)  # [.., n_sb, 16]
    codes = q6k_codes(blocks)

    lead = blocks.shape[:-2]
    k = blocks.shape[-2] * QK_K
    return dict(
        data=codes.reshape(*lead, k),
        scales=d,
        sub_scales=np.ascontiguousarray(sc).reshape(*lead, k // 16),
    )
