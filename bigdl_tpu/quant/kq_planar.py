"""Exact repack between llama.cpp k-quant super-block bytes and the
TPU planar layout (numpy, host-side).

llama.cpp's k-quant byte layouts interleave codes, packed sub-scales
and fp16 super-scales inside 84..210-byte super-blocks — a CPU-SIMD
artifact. A Pallas kernel cannot slice those byte offsets (Mosaic lane
alignment), and XLA's in-graph byte decode materializes bf16 weights in
HBM, measured 2.7x slower end-to-end (BENCH_NOTES r03). So on TPU a
k-quant QTensor stores PLANES:

  q2_k: data      [.., K/4]   uint8  quarter-split packed 2-bit codes
        scales    [.., K/256] f16    super-scale d
        mins      [.., K/256] f16    super-scale dmin
        sub_scales[.., K/16]  uint8  4-bit sc
        sub_mins  [.., K/16]  uint8  4-bit mn
        w[e] = (d*sc[e/16]) * q[e] - (dmin*mn[e/16])
  q3_k: data      [.., K]     int8   codes (q-4, element order)
        scales    [.., K/256] f16    super-scale d
        sub_scales[.., K/16]  int8   sc (6-bit, bias 32 removed)
        w[e] = (d*sc[e/16]) * q[e]      (== q6_k's structure)
  q4_k: data      [.., K/2]   uint8  half-split packed 4-bit codes
        scales    [.., K/256] f16    super-scale d
        mins      [.., K/256] f16    super-scale dmin
        sub_scales[.., K/32]  uint8  6-bit sc (element-order sub-blocks)
        sub_mins  [.., K/32]  uint8  6-bit mn
        w[e] = (d*sc[e/32]) * q[e] - (dmin*mn[e/32])
  q5_k: data      [.., 5K/8]  uint8  half-split nibbles ++ eighth-split
                                     1-bit plane (codes 0..31)
        (scales/mins/sub_scales/sub_mins as q4_k)
        w[e] = (d*sc[e/32]) * q[e] - (dmin*mn[e/32])
  q6_k: data      [.., K]     int8   codes (q-32, element order)
        scales    [.., K/256] f16    super-scale d
        sub_scales[.., K/16]  int8   sc
        w[e] = (d*sc[e/16]) * q[e]

The repack is pure integer/f16-view work — bit-exact both ways — and
runs once at the GGUF import / encoder boundary (reference counterpart:
the verbatim ggml byte carry in transformers/gguf/models/*.py of
/root/reference, which XPU kernels can consume directly; TPU cannot).
Dequantized values are identical to quant/kquants.dequant_* because
f32(d)*f32(sc) is exact (11-bit x 6-bit mantissa) and evaluation order
matches.
"""

from __future__ import annotations

import numpy as np

QK_K = 256


def pack_planes_np(codes: np.ndarray, planes: tuple) -> np.ndarray:
    """numpy mirror of quant/numerics.pack_planes ([.., K] codes ->
    concatenated multi-split bit planes, low bits first)."""
    k = codes.shape[-1]
    shift = 0
    outs = []
    for bits in planes:
        s = 8 // bits
        q = k // s
        sub = (codes >> shift) & ((1 << bits) - 1)
        acc = sub[..., :q].astype(np.uint8)
        for m in range(1, s):
            acc = acc | (sub[..., m * q:(m + 1) * q] << (bits * m)).astype(
                np.uint8)
        outs.append(acc)
        shift += bits
    return np.concatenate(outs, axis=-1)


def _f16_at(blocks: np.ndarray, off: int) -> np.ndarray:
    """fp16 scalar at byte offset `off` of each super-block."""
    return (
        blocks[..., off:off + 2].copy().view(np.float16)[..., 0]
    )


def _unpack_q4k_scales_np(sc_raw: np.ndarray):
    """12 packed bytes -> (sc [., 8], mn [., 8]) uint8 6-bit values
    (llama.cpp get_scale_min_k4; numpy mirror of kquants jnp version)."""
    sc = np.empty((*sc_raw.shape[:-1], 8), np.uint8)
    mn = np.empty_like(sc)
    for j in range(8):
        if j < 4:
            sc[..., j] = sc_raw[..., j] & 63
            mn[..., j] = sc_raw[..., j + 4] & 63
        else:
            sc[..., j] = (sc_raw[..., j + 4] & 0xF) | (
                (sc_raw[..., j - 4] >> 6) << 4
            )
            mn[..., j] = (sc_raw[..., j + 4] >> 4) | (
                (sc_raw[..., j] >> 6) << 4
            )
    return sc, mn


def q4k_codes(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 144] -> element-order codes [.., n_sb, 256] uint8."""
    qs = blocks[..., 16:144]
    out = np.empty((*blocks.shape[:-1], QK_K), np.uint8)
    for pair in range(4):
        grp = qs[..., 32 * pair:32 * (pair + 1)]
        out[..., 64 * pair:64 * pair + 32] = grp & 0xF
        out[..., 64 * pair + 32:64 * pair + 64] = grp >> 4
    return out


def from_q4k_blocks(blocks: np.ndarray) -> dict:
    """[.., n_sb, 144] super-block bytes -> planar QTensor fields."""
    d = _f16_at(blocks, 0)  # [.., n_sb]
    dmin = _f16_at(blocks, 2)
    sc, mn = _unpack_q4k_scales_np(blocks[..., 4:16])  # [.., n_sb, 8]
    codes = q4k_codes(blocks)

    lead = blocks.shape[:-2]
    k = blocks.shape[-2] * QK_K
    codes = codes.reshape(*lead, k)
    half = k // 2
    data = codes[..., :half] | (codes[..., half:] << 4)
    return dict(
        data=data,
        scales=d,
        mins=dmin,
        sub_scales=sc.reshape(*lead, k // 32),
        sub_mins=mn.reshape(*lead, k // 32),
    )


def q6k_codes(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 210] -> element-order centered codes [.., n_sb, 256]
    int8 (q - 32 in [-32, 31])."""
    ql = blocks[..., 0:128]
    qh = blocks[..., 128:192]
    out = np.empty((*blocks.shape[:-1], QK_K), np.int8)
    for half in range(2):
        l1 = ql[..., 64 * half:64 * half + 32]
        l2 = ql[..., 64 * half + 32:64 * half + 64]
        h = qh[..., 32 * half:32 * half + 32]
        base = 128 * half
        out[..., base:base + 32] = (
            ((l1 & 0xF) | ((h & 3) << 4)).astype(np.int8) - 32
        )
        out[..., base + 32:base + 64] = (
            ((l2 & 0xF) | (((h >> 2) & 3) << 4)).astype(np.int8) - 32
        )
        out[..., base + 64:base + 96] = (
            ((l1 >> 4) | (((h >> 4) & 3) << 4)).astype(np.int8) - 32
        )
        out[..., base + 96:base + 128] = (
            ((l2 >> 4) | (((h >> 6) & 3) << 4)).astype(np.int8) - 32
        )
    return out


def from_q6k_blocks(blocks: np.ndarray) -> dict:
    """[.., n_sb, 210] super-block bytes -> planar QTensor fields."""
    d = _f16_at(blocks, 208)
    sc = blocks[..., 192:208].view(np.int8)  # [.., n_sb, 16]
    codes = q6k_codes(blocks)

    lead = blocks.shape[:-2]
    k = blocks.shape[-2] * QK_K
    return dict(
        data=codes.reshape(*lead, k),
        scales=d,
        sub_scales=np.ascontiguousarray(sc).reshape(*lead, k // 16),
    )


def q2k_codes(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 84] -> element-order codes [.., n_sb, 256] uint8
    (0..3). Element 128h + 32j + l comes from bits 2j of qs[32h + l]."""
    qs = blocks[..., 16:80]
    out = np.empty((*blocks.shape[:-1], QK_K), np.uint8)
    for h in range(2):
        grp = qs[..., 32 * h:32 * (h + 1)]
        for j in range(4):
            e0 = 128 * h + 32 * j
            out[..., e0:e0 + 32] = (grp >> (2 * j)) & 3
    return out


def from_q2k_blocks(blocks: np.ndarray) -> dict:
    """[.., n_sb, 84] super-block bytes -> planar QTensor fields."""
    d = _f16_at(blocks, 80)
    dmin = _f16_at(blocks, 82)
    sc_raw = blocks[..., 0:16]  # [.., n_sb, 16]: sc | mn << 4 per sub
    codes = q2k_codes(blocks)

    lead = blocks.shape[:-2]
    k = blocks.shape[-2] * QK_K
    return dict(
        data=pack_planes_np(codes.reshape(*lead, k), (2,)),
        scales=d,
        mins=dmin,
        sub_scales=(sc_raw & 0xF).reshape(*lead, k // 16),
        sub_mins=(sc_raw >> 4).reshape(*lead, k // 16),
    )


def _unpack_q3k_scales_np(sc_raw: np.ndarray) -> np.ndarray:
    """12 bytes -> 16 6-bit scales, still biased by +32 (numpy mirror of
    kquants._unpack_q3k_scales)."""
    sc = np.empty((*sc_raw.shape[:-1], 16), np.uint8)
    for i in range(16):
        j, grp = i & 3, i >> 2
        if grp == 0:
            lo4 = sc_raw[..., j] & 0xF
        elif grp == 1:
            lo4 = sc_raw[..., 4 + j] & 0xF
        elif grp == 2:
            lo4 = sc_raw[..., j] >> 4
        else:
            lo4 = sc_raw[..., 4 + j] >> 4
        hi2 = (sc_raw[..., 8 + j] >> (2 * grp)) & 3
        sc[..., i] = lo4 | (hi2 << 4)
    return sc


def q3k_codes(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 110] -> element-order centered codes [.., n_sb, 256]
    int8 (q - 4 in [-4, 3]). Element 128h + 32j + l = (qs[32h+l] >> 2j
    & 3) - (hmask[l] bit (4h+j) ? 0 : 4)."""
    hmask = blocks[..., 0:32]
    qs = blocks[..., 32:96]
    out = np.empty((*blocks.shape[:-1], QK_K), np.int8)
    for h in range(2):
        grp = qs[..., 32 * h:32 * (h + 1)]
        for j in range(4):
            q2 = ((grp >> (2 * j)) & 3).astype(np.int8)
            hb = ((hmask >> (4 * h + j)) & 1).astype(np.int8)
            e0 = 128 * h + 32 * j
            out[..., e0:e0 + 32] = q2 + 4 * hb - 4
    return out


def from_q3k_blocks(blocks: np.ndarray) -> dict:
    """[.., n_sb, 110] super-block bytes -> planar QTensor fields
    (q6_k's structure: int8 centered codes + int8 sub-scales per 16)."""
    d = _f16_at(blocks, 108)
    sc = (_unpack_q3k_scales_np(blocks[..., 96:108]).astype(np.int16)
          - 32).astype(np.int8)
    codes = q3k_codes(blocks)

    lead = blocks.shape[:-2]
    k = blocks.shape[-2] * QK_K
    return dict(
        data=codes.reshape(*lead, k),
        scales=d,
        sub_scales=sc.reshape(*lead, k // 16),
    )


def q5k_codes(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 176] -> element-order codes [.., n_sb, 256] uint8
    (0..31): q4_K nibble groups + the qh 5th-bit plane."""
    qh = blocks[..., 16:48]
    qs = blocks[..., 48:176]
    out = np.empty((*blocks.shape[:-1], QK_K), np.uint8)
    for pair in range(4):
        grp = qs[..., 32 * pair:32 * (pair + 1)]
        out[..., 64 * pair:64 * pair + 32] = (
            (grp & 0xF) | (((qh >> (2 * pair)) & 1) << 4)
        )
        out[..., 64 * pair + 32:64 * pair + 64] = (
            (grp >> 4) | (((qh >> (2 * pair + 1)) & 1) << 4)
        )
    return out


def from_q5k_blocks(blocks: np.ndarray) -> dict:
    """[.., n_sb, 176] super-block bytes -> planar QTensor fields
    (q4_k's fields, with the 5th code bit as an extra packed plane)."""
    d = _f16_at(blocks, 0)
    dmin = _f16_at(blocks, 2)
    sc, mn = _unpack_q4k_scales_np(blocks[..., 4:16])  # [.., n_sb, 8]
    codes = q5k_codes(blocks)

    lead = blocks.shape[:-2]
    k = blocks.shape[-2] * QK_K
    return dict(
        data=pack_planes_np(codes.reshape(*lead, k), (4, 1)),
        scales=d,
        mins=dmin,
        sub_scales=sc.reshape(*lead, k // 32),
        sub_mins=mn.reshape(*lead, k // 32),
    )
