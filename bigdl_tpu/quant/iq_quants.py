"""IQ ("importance-quant") GGUF decoders: iq2_xxs, iq2_xs, iq1_s.

The reference exposes these formats through its native wheels
(gguf_iq2_xxs/xs, gguf_iq1_s/m enum ids in ggml/quantize.py:43-47 of
/root/reference); files in them were rejected here (VERDICT r03 missing
#5). This module implements the super-block byte layouts so such
checkpoints dequantize on load (then re-quantize to a runtime format,
convert/gguf.py's non-repackable path).

The formats index CODEBOOK GRIDS — empirical E8-lattice point sets
published as data tables in llama.cpp's ggml-common.h (iq2xxs_grid[256],
iq2xs_grid[512], iq1s_grid[2048] — thousands of constants that cannot be
derived algorithmically). This environment ships neither llama.cpp nor
the `gguf` package, so the tables load at runtime:

- `BIGDL_TPU_IQ_TABLES=/path/to/tables.npz` with int8 arrays
  `iq2xxs_grid [256,8]`, `iq2xs_grid [512,8]`, `iq1s_grid [2048,8]`; or
- `BIGDL_TPU_IQ_TABLES=/path/to/ggml-common.h` — the llama.cpp header is
  parsed directly (the uint64 entries unpack little-endian into 8 int8
  codes each).

`ksigns` IS algorithmic (7 stored sign bits + an 8th chosen for even
total parity) and is generated here. Without tables, decoding raises
with these instructions instead of silently producing garbage.
iq1_m additionally packs its f16 super-scale into the scale words'
high nibbles; it remains NotImplemented until its layout can be
validated against a real decoder.
"""

from __future__ import annotations

import os
import re
from typing import Optional

import numpy as np

QK_K = 256

IQ1S_DELTA = 0.125

# 7-bit sign index -> 8 sign bits, the 8th making total parity even
KSIGNS = np.asarray(
    [i | ((bin(i).count("1") & 1) << 7) for i in range(128)], np.uint8
)

_TABLES: Optional[dict] = None
_REQUIRED = {"iq2xxs_grid": 256, "iq2xs_grid": 512, "iq1s_grid": 2048}


def _parse_ggml_common_text(text: str) -> dict:
    """Extract the grid tables from llama.cpp's ggml-common.h source.
    Handles both declaration styles: the macro form used since the
    tables moved into ggml-common.h (GGML_TABLE_BEGIN(uint64_t,
    iq2xxs_grid, 256) ... GGML_TABLE_END()) and the older plain C array
    (possibly with a symbolic size like iq1s_grid[NGRID_IQ1S])."""
    out = {}
    for name, n in _REQUIRED.items():
        m = re.search(
            r"GGML_TABLE_BEGIN\(\s*\w+\s*,\s*" + name
            + r"\s*,\s*\w+\s*\)(.*?)GGML_TABLE_END\(\)",
            text, re.S,
        ) or re.search(
            name + r"\s*\[[^\]]*\]\s*=\s*\{(.*?)\}", text, re.S
        )
        if not m:
            continue
        vals = [int(v, 0) for v in re.findall(r"0x[0-9a-fA-F]+|\d+", m.group(1))]
        if len(vals) != n:
            raise ValueError(f"{name}: expected {n} entries, got {len(vals)}")
        u64 = np.asarray(vals, np.uint64)
        out[name] = u64.view(np.uint8).reshape(n, 8).astype(np.int8)
    return out


def set_iq_tables(tables: dict) -> None:
    """Install grid tables programmatically (tests inject synthetic
    grids; deployments may load them from their llama.cpp checkout)."""
    global _TABLES
    for name, n in _REQUIRED.items():
        t = np.asarray(tables[name], np.int8)
        if t.shape != (n, 8):
            raise ValueError(f"{name}: expected shape ({n}, 8), got {t.shape}")
    _TABLES = {k: np.asarray(tables[k], np.int8) for k in _REQUIRED}


def _cache_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "bigdl_tpu", "iq_tables.npz")


# llama.cpp publishes the grids in ggml/src/ggml-common.h; any mirror of
# that file works (the parser handles both declaration styles)
DEFAULT_TABLES_URL = (
    "https://raw.githubusercontent.com/ggml-org/llama.cpp/master/"
    "ggml/src/ggml-common.h"
)


def _load_path(path: str) -> None:
    if path.endswith(".npz"):
        npz = np.load(path)
        set_iq_tables({k: npz[k] for k in _REQUIRED})
        return
    _load_text(open(path).read(), origin=path)


def _load_text(text: str, origin: str) -> None:
    parsed = _parse_ggml_common_text(text)
    missing = set(_REQUIRED) - set(parsed)
    if missing:
        raise ValueError(f"{origin}: could not find tables {sorted(missing)}")
    set_iq_tables(parsed)


def fetch_tables(url: str = DEFAULT_TABLES_URL, cache: bool = True,
                 timeout: float = 30.0) -> dict:
    """Download + parse ggml-common.h, cache the parsed grids as an npz
    so every later `from_gguf` on an IQ file is turnkey (VERDICT r04
    missing #5's fetch-and-cache step). Returns the installed tables."""
    from urllib import request as urlrequest

    with urlrequest.urlopen(url, timeout=timeout) as resp:
        text = resp.read().decode("utf-8", errors="replace")
    _load_text(text, origin=url)
    if cache:
        path = _cache_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp_npz = f"{path}.tmp-{os.getpid()}.npz"  # .npz: savez appends
        np.savez(tmp_npz, **_TABLES)               # otherwise
        os.replace(tmp_npz, path)
    return _TABLES


def iq_tables(autofetch: Optional[bool] = None) -> dict:
    """Resolve the grids: installed > $BIGDL_TPU_IQ_TABLES > the
    fetch cache > network autofetch (disable with
    BIGDL_TPU_IQ_AUTOFETCH=0)."""
    global _TABLES
    if _TABLES is not None:
        return _TABLES
    path = os.environ.get("BIGDL_TPU_IQ_TABLES")
    if path:
        _load_path(path)
        return _TABLES
    cached = _cache_path()
    cache_err = ""
    if os.path.exists(cached):
        try:
            _load_path(cached)
            return _TABLES
        except Exception as e:  # noqa: BLE001 — corrupt/stale cache:
            # fall through to autofetch (self-heals by rewriting it)
            cache_err = f" (cache {cached} unreadable: {e!r})"
    if autofetch is None:
        autofetch = os.environ.get("BIGDL_TPU_IQ_AUTOFETCH", "1") != "0"
    if autofetch:
        try:
            return fetch_tables()
        except Exception as e:  # noqa: BLE001 — no network: explain below
            fetch_err = f" (autofetch failed: {e!r})"
    else:
        fetch_err = " (autofetch disabled)"
    raise RuntimeError(
        "IQ-quant decoding needs the llama.cpp codebook grids "
        "(iq2xxs_grid/iq2xs_grid/iq1s_grid — empirical tables this "
        "package cannot synthesize). Run `bigdl-tpu fetch-iq-tables` "
        "on a machine with network access (caches to "
        f"{_cache_path()}), or set BIGDL_TPU_IQ_TABLES to a "
        "ggml-common.h from a llama.cpp checkout or an .npz with "
        "int8 arrays iq2xxs_grid[256,8], iq2xs_grid[512,8], "
        f"iq1s_grid[2048,8].{fetch_err}{cache_err}"
    )


def _signs(idx: np.ndarray) -> np.ndarray:
    """[..] 7-bit sign indices -> [.., 8] +-1.0 factors."""
    bits = KSIGNS[idx]  # [..]
    j = np.arange(8, dtype=np.uint8)
    neg = (bits[..., None] >> j) & 1
    return np.where(neg == 1, -1.0, 1.0).astype(np.float32)


def _f16_at(blocks: np.ndarray, off: int) -> np.ndarray:
    return blocks[..., off:off + 2].copy().view(np.float16)[..., 0]


def dequant_iq2_xxs(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 66] -> [.., n_sb*256] f32. Layout (block_iq2_xxs):
    f16 d + 32 u16 qs; per 32-element group, 4 grid bytes then a u32 of
    4x7-bit sign indices + a 4-bit scale in the top bits."""
    grid = iq_tables()["iq2xxs_grid"].astype(np.float32)  # [256, 8]
    d = _f16_at(blocks, 0).astype(np.float32)  # [.., n_sb]
    qs = blocks[..., 2:66].copy().view(np.uint16)  # [.., n_sb, 32]

    lead = blocks.shape[:-1]
    out = np.empty((*lead, QK_K), np.float32)
    for ib in range(8):  # 32-element groups
        q4 = qs[..., 4 * ib:4 * ib + 4].astype(np.uint32)
        aux8 = np.stack(
            [q4[..., 0] & 0xFF, q4[..., 0] >> 8,
             q4[..., 1] & 0xFF, q4[..., 1] >> 8], axis=-1
        )  # [.., 4] grid indices
        aux32 = q4[..., 2] | (q4[..., 3] << 16)
        db = d * (0.5 + (aux32 >> 28).astype(np.float32)) * 0.25
        for l in range(4):
            g = grid[aux8[..., l]]  # [.., 8]
            sg = _signs(((aux32 >> (7 * l)) & 127).astype(np.int64))
            out[..., 32 * ib + 8 * l:32 * ib + 8 * l + 8] = (
                db[..., None] * g * sg
            )
    return out.reshape(*blocks.shape[:-2], -1)


def dequant_iq2_xs(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 74] -> values. Layout (block_iq2_xs): f16 d + 32 u16
    qs (9-bit grid index | 7-bit sign index) + 8 scale bytes (two 4-bit
    scales per 32-element group, one per 16)."""
    grid = iq_tables()["iq2xs_grid"].astype(np.float32)  # [512, 8]
    d = _f16_at(blocks, 0).astype(np.float32)
    qs = blocks[..., 2:66].copy().view(np.uint16)
    scales = blocks[..., 66:74]  # [.., n_sb, 8]

    lead = blocks.shape[:-1]
    out = np.empty((*lead, QK_K), np.float32)
    for ib in range(8):
        ls = scales[..., ib]
        db = np.stack([
            d * (0.5 + (ls & 0xF).astype(np.float32)) * 0.25,
            d * (0.5 + (ls >> 4).astype(np.float32)) * 0.25,
        ], axis=-1)  # [.., 2]
        for l in range(4):
            q = qs[..., 4 * ib + l]
            g = grid[(q & 511).astype(np.int64)]
            sg = _signs((q >> 9).astype(np.int64))
            out[..., 32 * ib + 8 * l:32 * ib + 8 * l + 8] = (
                db[..., l // 2, None] * g * sg
            )
    return out.reshape(*blocks.shape[:-2], -1)


def dequant_iq1_s(blocks: np.ndarray) -> np.ndarray:
    """[.., n_sb, 50] -> values. Layout (block_iq1_s): f16 d + 32 u8 qs
    + 8 u16 qh. Per 32-element group: 3-bit scale (qh bits 12-14),
    shared +-IQ1S_DELTA offset (qh bit 15), grid index = qs byte |
    3 high bits from qh."""
    grid = iq_tables()["iq1s_grid"].astype(np.float32)  # [2048, 8]
    d = _f16_at(blocks, 0).astype(np.float32)
    qs = blocks[..., 2:34]  # [.., n_sb, 32]
    qh = blocks[..., 34:50].copy().view(np.uint16)  # [.., n_sb, 8]

    lead = blocks.shape[:-1]
    out = np.empty((*lead, QK_K), np.float32)
    for ib in range(8):
        h = qh[..., ib].astype(np.uint32)
        dl = d * (2.0 * ((h >> 12) & 7).astype(np.float32) + 1.0)
        delta = np.where(h & 0x8000, -IQ1S_DELTA, IQ1S_DELTA).astype(np.float32)
        for l in range(4):
            idx = (qs[..., 4 * ib + l].astype(np.int64)
                   | (((h >> (3 * l)) & 7) << 8).astype(np.int64))
            g = grid[idx]
            out[..., 32 * ib + 8 * l:32 * ib + 8 * l + 8] = (
                dl[..., None] * (g + delta[..., None])
            )
    return out.reshape(*blocks.shape[:-2], -1)
