"""Low-bit quantization core (TPU-native equivalent of the reference's
ggml/ + low_bit_linear.py layer, see SURVEY.md §2.1)."""

from bigdl_tpu.quant.qtypes import (
    QTypeSpec,
    qtype_registry,
    resolve_qtype,
)
from bigdl_tpu.quant.numerics import (
    dequantize_blockwise,
    pack_nibbles,
    quantize_blockwise,
    unpack_nibbles,
)
from bigdl_tpu.quant.qtensor import (QTensor, dequantize, quantize,
                                     quantize_or_dense)

__all__ = [
    "QTensor",
    "QTypeSpec",
    "quantize",
    "quantize_or_dense",
    "dequantize",
    "quantize_blockwise",
    "dequantize_blockwise",
    "pack_nibbles",
    "unpack_nibbles",
    "qtype_registry",
    "resolve_qtype",
]
