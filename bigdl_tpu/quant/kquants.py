"""K-quant (super-block) codecs: q4_K and q6_K.

The reference reaches these formats through its native quantizers
(`ggml_quantize_tensor` with q4_k/q6_k qtypes, ggml/quantize.py:28-57 +
gguf_mixed_qtype :60-61 in /root/reference). Here:

- storage is the llama.cpp super-block byte layout (256 elements; q4_K:
  fp16 d/dmin + 12B packed 6-bit sub-scales/mins + 128B nibbles = 144B;
  q6_K: 128B low nibbles + 64B high bits + 16 int8 sub-scales + fp16 d =
  210B) so GGUF k-quant tensors repack into QTensor **without**
  dequantization (convert/gguf.py);
- `dequant_q4_k` / `dequant_q6_k` are jnp (jit-safe) — they run in-graph
  on TPU, fused by XLA into the consuming matmul like the other formats;
- the encoders are host-side numpy (RTN two-level scales — the
  non-imatrix ggml path) used at checkpoint ingest.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

QK_K = 256


# ---------------------------------------------------------------------------
# jnp decoders (device-side, jit-safe)
# ---------------------------------------------------------------------------

def _read_f16(blocks: jnp.ndarray, off: int) -> jnp.ndarray:
    """fp16 scalar stored little-endian at byte offset `off`."""
    lo = blocks[..., off].astype(jnp.uint16)
    hi = blocks[..., off + 1].astype(jnp.uint16)
    bits = lo | (hi << 8)
    return jax.lax.bitcast_convert_type(bits, jnp.float16).astype(jnp.float32)


def dequant_q6_k(blocks: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """blocks [..., n_sb, 210] uint8 -> [..., n_sb*256]."""
    ql = blocks[..., 0:128]
    qh = blocks[..., 128:192]
    scales = blocks[..., 192:208].astype(jnp.int8).astype(jnp.float32)
    d = _read_f16(blocks, 208)

    outs = []
    for half in range(2):
        l1 = ql[..., 64 * half:64 * half + 32]
        l2 = ql[..., 64 * half + 32:64 * half + 64]
        h = qh[..., 32 * half:32 * half + 32]
        q1 = ((l1 & 0xF) | ((h & 3) << 4)).astype(jnp.float32) - 32.0
        q2 = ((l2 & 0xF) | (((h >> 2) & 3) << 4)).astype(jnp.float32) - 32.0
        q3 = ((l1 >> 4) | (((h >> 4) & 3) << 4)).astype(jnp.float32) - 32.0
        q4 = ((l2 >> 4) | (((h >> 6) & 3) << 4)).astype(jnp.float32) - 32.0
        outs.extend([q1, q2, q3, q4])
    q = jnp.concatenate(outs, axis=-1)  # [..., 256] element order
    sub_scale = jnp.repeat(scales, 16, axis=-1)
    vals = q * sub_scale * d[..., None]
    return vals.reshape(*blocks.shape[:-2], -1).astype(dtype)


def _unpack_q4k_scales(sc_raw: jnp.ndarray):
    """12 packed bytes -> (sc [., 8], mn [., 8]) floats (get_scale_min_k4)."""
    sc = []
    mn = []
    for j in range(8):
        if j < 4:
            sc.append((sc_raw[..., j] & 63).astype(jnp.float32))
            mn.append((sc_raw[..., j + 4] & 63).astype(jnp.float32))
        else:
            sc.append(
                ((sc_raw[..., j + 4] & 0xF) | ((sc_raw[..., j - 4] >> 6) << 4)
                 ).astype(jnp.float32)
            )
            mn.append(
                ((sc_raw[..., j + 4] >> 4) | ((sc_raw[..., j] >> 6) << 4)
                 ).astype(jnp.float32)
            )
    return jnp.stack(sc, axis=-1), jnp.stack(mn, axis=-1)


def dequant_q4_k(blocks: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """blocks [..., n_sb, 144] uint8 -> [..., n_sb*256]."""
    d = _read_f16(blocks, 0)
    dmin = _read_f16(blocks, 2)
    sc, mn = _unpack_q4k_scales(blocks[..., 4:16])
    qs = blocks[..., 16:144]

    outs = []
    for pair in range(4):
        grp = qs[..., 32 * pair:32 * (pair + 1)]
        lo = (grp & 0xF).astype(jnp.float32)
        hi = (grp >> 4).astype(jnp.float32)
        j0, j1 = 2 * pair, 2 * pair + 1
        outs.append(
            d[..., None] * sc[..., j0:j0 + 1] * lo
            - dmin[..., None] * mn[..., j0:j0 + 1]
        )
        outs.append(
            d[..., None] * sc[..., j1:j1 + 1] * hi
            - dmin[..., None] * mn[..., j1:j1 + 1]
        )
    vals = jnp.concatenate(outs, axis=-1)
    return vals.reshape(*blocks.shape[:-2], -1).astype(dtype)


# ---------------------------------------------------------------------------
# numpy encoders (host-side ingest; RTN two-level scales)
# ---------------------------------------------------------------------------

def quantize_q6_k(x: np.ndarray) -> np.ndarray:
    """x [..., K] (K % 256 == 0) -> blocks [..., K/256, 210] uint8."""
    x = np.asarray(x, np.float32)
    lead = x.shape[:-1]
    xb = x.reshape(-1, QK_K // 16, 16)  # [n_sb_total, 16 subblocks, 16]
    n = xb.shape[0]

    # per-sub-block signed-absmax scale, super scale d = max|s|/127
    idx = np.argmax(np.abs(xb), axis=-1)
    smax = np.take_along_axis(xb, idx[..., None], axis=-1)[..., 0]  # [n, 16]
    s = smax / -32.0
    d = np.max(np.abs(s), axis=-1) / 127.0  # [n]
    inv_d = np.where(d == 0, 0.0, 1.0 / np.where(d == 0, 1, d))
    sc = np.clip(np.round(s * inv_d[:, None]), -128, 127).astype(np.int8)

    eff = d[:, None] * sc.astype(np.float32)  # effective sub scales
    inv_eff = np.where(eff == 0, 0.0, 1.0 / np.where(eff == 0, 1, eff))
    q = np.clip(np.round(xb * inv_eff[..., None]), -32, 31).astype(np.int32) + 32
    q = q.reshape(n, QK_K)  # element order

    blocks = np.zeros((n, 210), np.uint8)
    for half in range(2):
        base = 128 * half
        q1 = q[:, base:base + 32]
        q2 = q[:, base + 32:base + 64]
        q3 = q[:, base + 64:base + 96]
        q4 = q[:, base + 96:base + 128]
        blocks[:, 64 * half:64 * half + 32] = (q1 & 0xF) | ((q3 & 0xF) << 4)
        blocks[:, 64 * half + 32:64 * half + 64] = (q2 & 0xF) | ((q4 & 0xF) << 4)
        blocks[:, 128 + 32 * half:128 + 32 * half + 32] = (
            (q1 >> 4) | ((q2 >> 4) << 2) | ((q3 >> 4) << 4) | ((q4 >> 4) << 6)
        )
    blocks[:, 192:208] = sc.view(np.uint8)
    blocks[:, 208:210] = (
        d.astype(np.float16).view(np.uint8).reshape(n, 2)
    )
    return blocks.reshape(*lead, x.shape[-1] // QK_K, 210)


def quantize_q4_k(x: np.ndarray) -> np.ndarray:
    """x [..., K] (K % 256 == 0) -> blocks [..., K/256, 144] uint8."""
    x = np.asarray(x, np.float32)
    lead = x.shape[:-1]
    xb = x.reshape(-1, 8, 32)  # 8 sub-blocks of 32
    n = xb.shape[0]

    mins = np.minimum(xb.min(axis=-1), 0.0)  # [n, 8] (m >= 0 convention)
    maxs = xb.max(axis=-1)
    scales = (maxs - mins) / 15.0
    d = scales.max(axis=-1) / 63.0
    dmin = (-mins).max(axis=-1) / 63.0
    inv_d = np.where(d == 0, 0.0, 1.0 / np.where(d == 0, 1, d))
    inv_dm = np.where(dmin == 0, 0.0, 1.0 / np.where(dmin == 0, 1, dmin))
    sc = np.clip(np.round(scales * inv_d[:, None]), 0, 63).astype(np.uint8)
    mn = np.clip(np.round(-mins * inv_dm[:, None]), 0, 63).astype(np.uint8)

    eff_s = d[:, None] * sc.astype(np.float32)
    eff_m = dmin[:, None] * mn.astype(np.float32)
    inv_eff = np.where(eff_s == 0, 0.0, 1.0 / np.where(eff_s == 0, 1, eff_s))
    q = np.clip(
        np.round((xb + eff_m[..., None]) * inv_eff[..., None]), 0, 15
    ).astype(np.uint8)

    blocks = np.zeros((n, 144), np.uint8)
    blocks[:, 0:2] = d.astype(np.float16).view(np.uint8).reshape(n, 2)
    blocks[:, 2:4] = dmin.astype(np.float16).view(np.uint8).reshape(n, 2)
    # pack 6-bit scales/mins (inverse of get_scale_min_k4)
    packed = np.zeros((n, 12), np.uint8)
    for j in range(4):
        packed[:, j] = sc[:, j] | ((sc[:, j + 4] >> 4) << 6)
        packed[:, j + 4] = mn[:, j] | ((mn[:, j + 4] >> 4) << 6)
        packed[:, j + 8] = (sc[:, j + 4] & 0xF) | ((mn[:, j + 4] & 0xF) << 4)
    blocks[:, 4:16] = packed
    for pair in range(4):
        lo = q[:, 2 * pair]
        hi = q[:, 2 * pair + 1]
        blocks[:, 16 + 32 * pair:16 + 32 * (pair + 1)] = lo | (hi << 4)
    return blocks.reshape(*lead, x.shape[-1] // QK_K, 144)
