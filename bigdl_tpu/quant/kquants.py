"""K-quant (super-block) codecs: q2_K, q3_K, q4_K, q5_K and q6_K.

The reference reaches these formats through its native quantizers
(`ggml_quantize_tensor` with q4_k/q6_k qtypes, ggml/quantize.py:28-57 +
gguf_mixed_qtype :60-61 in /root/reference). Here:

This module speaks the llama.cpp super-block BYTE layout (256 elements;
q2_K: 16B 4-bit sub-scale/min pairs + 64B 2-bit quants + fp16 d/dmin =
84B; q3_K: 32B high-bit mask + 64B 2-bit quants + 12B 6-bit scales +
fp16 d = 110B; q4_K: fp16 d/dmin + 12B packed 6-bit sub-scales/mins +
128B nibbles = 144B; q5_K: q4_K's header + 32B high bits + 128B nibbles
= 176B; q6_K: 128B low nibbles + 64B high bits + 16 int8 sub-scales +
fp16 d = 210B):

- the encoders are host-side numpy (RTN two-level scales — the
  non-imatrix ggml path) used at checkpoint ingest and GGUF export;
- the `dequant_*` jnp decoders are the byte-layout oracle the planar
  repack (quant/kq_planar.py) is verified against bit-for-bit, and the
  numpy import path's decode backend (convert/gguf.py).

RUNTIME storage is NOT these bytes: every k-quant QTensor holds the
planar fields of quant/kq_planar.py, which both XLA dequant and the
fused Pallas GEMV kernels read directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

QK_K = 256

# re-export: the layout table lives in qtypes (numpy-only module) so
# convert/gguf.py can consume it without pulling in jax
from bigdl_tpu.quant.qtypes import KQUANT_LAYOUT  # noqa: E402,F401


# ---------------------------------------------------------------------------
# jnp decoders (device-side, jit-safe)
# ---------------------------------------------------------------------------

def _read_f16(blocks: jnp.ndarray, off: int) -> jnp.ndarray:
    """fp16 scalar stored little-endian at byte offset `off`."""
    lo = blocks[..., off].astype(jnp.uint16)
    hi = blocks[..., off + 1].astype(jnp.uint16)
    bits = lo | (hi << 8)
    return jax.lax.bitcast_convert_type(bits, jnp.float16).astype(jnp.float32)


def dequant_q6_k(blocks: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """blocks [..., n_sb, 210] uint8 -> [..., n_sb*256]."""
    ql = blocks[..., 0:128]
    qh = blocks[..., 128:192]
    scales = blocks[..., 192:208].astype(jnp.int8).astype(jnp.float32)
    d = _read_f16(blocks, 208)

    outs = []
    for half in range(2):
        l1 = ql[..., 64 * half:64 * half + 32]
        l2 = ql[..., 64 * half + 32:64 * half + 64]
        h = qh[..., 32 * half:32 * half + 32]
        q1 = ((l1 & 0xF) | ((h & 3) << 4)).astype(jnp.float32) - 32.0
        q2 = ((l2 & 0xF) | (((h >> 2) & 3) << 4)).astype(jnp.float32) - 32.0
        q3 = ((l1 >> 4) | (((h >> 4) & 3) << 4)).astype(jnp.float32) - 32.0
        q4 = ((l2 >> 4) | (((h >> 6) & 3) << 4)).astype(jnp.float32) - 32.0
        outs.extend([q1, q2, q3, q4])
    q = jnp.concatenate(outs, axis=-1)  # [..., 256] element order
    sub_scale = jnp.repeat(scales, 16, axis=-1)
    vals = q * sub_scale * d[..., None]
    return vals.reshape(*blocks.shape[:-2], -1).astype(dtype)


def _unpack_q4k_scales(sc_raw: jnp.ndarray):
    """12 packed bytes -> (sc [., 8], mn [., 8]) floats (get_scale_min_k4)."""
    sc = []
    mn = []
    for j in range(8):
        if j < 4:
            sc.append((sc_raw[..., j] & 63).astype(jnp.float32))
            mn.append((sc_raw[..., j + 4] & 63).astype(jnp.float32))
        else:
            sc.append(
                ((sc_raw[..., j + 4] & 0xF) | ((sc_raw[..., j - 4] >> 6) << 4)
                 ).astype(jnp.float32)
            )
            mn.append(
                ((sc_raw[..., j + 4] >> 4) | ((sc_raw[..., j] >> 6) << 4)
                 ).astype(jnp.float32)
            )
    return jnp.stack(sc, axis=-1), jnp.stack(mn, axis=-1)


def dequant_q4_k(blocks: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """blocks [..., n_sb, 144] uint8 -> [..., n_sb*256]."""
    d = _read_f16(blocks, 0)
    dmin = _read_f16(blocks, 2)
    sc, mn = _unpack_q4k_scales(blocks[..., 4:16])
    qs = blocks[..., 16:144]

    outs = []
    for pair in range(4):
        grp = qs[..., 32 * pair:32 * (pair + 1)]
        lo = (grp & 0xF).astype(jnp.float32)
        hi = (grp >> 4).astype(jnp.float32)
        j0, j1 = 2 * pair, 2 * pair + 1
        outs.append(
            d[..., None] * sc[..., j0:j0 + 1] * lo
            - dmin[..., None] * mn[..., j0:j0 + 1]
        )
        outs.append(
            d[..., None] * sc[..., j1:j1 + 1] * hi
            - dmin[..., None] * mn[..., j1:j1 + 1]
        )
    vals = jnp.concatenate(outs, axis=-1)
    return vals.reshape(*blocks.shape[:-2], -1).astype(dtype)


def dequant_q2_k(blocks: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """blocks [..., n_sb, 84] uint8 -> [..., n_sb*256].

    Layout (llama.cpp block_q2_K): scales[16] (4-bit scale | 4-bit min
    per 16-element sub-block), qs[64] (2-bit quants), fp16 d, fp16 dmin.
    Element 128h + 32j + 16g + l comes from bits 2j of qs[32h + 16g + l],
    sub-block index 8h + 2j + g."""
    sc_raw = blocks[..., 0:16]
    qs = blocks[..., 16:80]
    d = _read_f16(blocks, 80)
    dmin = _read_f16(blocks, 82)

    dl = d[..., None] * (sc_raw & 0xF).astype(jnp.float32)  # [..., 16]
    ml = dmin[..., None] * (sc_raw >> 4).astype(jnp.float32)

    outs = []
    for h in range(2):
        qh_bytes = qs[..., 32 * h:32 * (h + 1)]
        for j in range(4):
            q2 = ((qh_bytes >> (2 * j)) & 3).astype(jnp.float32)  # [..., 32]
            for g in range(2):
                i_s = 8 * h + 2 * j + g
                outs.append(
                    dl[..., i_s:i_s + 1] * q2[..., 16 * g:16 * (g + 1)]
                    - ml[..., i_s:i_s + 1]
                )
    vals = jnp.concatenate(outs, axis=-1)
    return vals.reshape(*blocks.shape[:-2], -1).astype(dtype)


def _unpack_q3k_scales(sc_raw: jnp.ndarray) -> jnp.ndarray:
    """12 bytes -> 16 6-bit scales (still biased by +32). Scale i: low 4
    bits from bytes[0..7] nibbles, high 2 bits from bytes[8..11]."""
    sc = []
    for i in range(16):
        j, grp = i & 3, i >> 2
        if grp == 0:
            lo4 = sc_raw[..., j] & 0xF
        elif grp == 1:
            lo4 = sc_raw[..., 4 + j] & 0xF
        elif grp == 2:
            lo4 = sc_raw[..., j] >> 4
        else:
            lo4 = sc_raw[..., 4 + j] >> 4
        hi2 = (sc_raw[..., 8 + j] >> (2 * grp)) & 3
        sc.append((lo4 | (hi2 << 4)).astype(jnp.float32))
    return jnp.stack(sc, axis=-1)  # [..., 16]


def dequant_q3_k(blocks: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """blocks [..., n_sb, 110] uint8 -> [..., n_sb*256].

    Layout (block_q3_K): hmask[32], qs[64] (2-bit), scales[12] (6-bit,
    bias 32), fp16 d. Element 128h + 32j + 16g + l = (qs[32h+16g+l] >>
    2j & 3) - (hmask[16g+l] bit (4h+j) ? 0 : 4), scaled by
    d * (scale[8h+2j+g] - 32)."""
    hmask = blocks[..., 0:32]
    qs = blocks[..., 32:96]
    sc = _unpack_q3k_scales(blocks[..., 96:108]) - 32.0  # [..., 16]
    d = _read_f16(blocks, 108)

    dl = d[..., None] * sc  # [..., 16]
    outs = []
    for h in range(2):
        q_bytes = qs[..., 32 * h:32 * (h + 1)]
        for j in range(4):
            bit = 4 * h + j
            q2 = ((q_bytes >> (2 * j)) & 3).astype(jnp.int32)
            hb = ((hmask >> bit) & 1).astype(jnp.int32)  # [..., 32]
            qv = (q2 - jnp.where(hb == 1, 0, 4)).astype(jnp.float32)
            for g in range(2):
                i_s = 8 * h + 2 * j + g
                outs.append(dl[..., i_s:i_s + 1] * qv[..., 16 * g:16 * (g + 1)])
    vals = jnp.concatenate(outs, axis=-1)
    return vals.reshape(*blocks.shape[:-2], -1).astype(dtype)


def dequant_q5_k(blocks: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """blocks [..., n_sb, 176] uint8 -> [..., n_sb*256].

    Layout (block_q5_K): fp16 d/dmin, scales[12] (q4_K packing), qh[32]
    (5th bits), qs[128] (nibbles). 64-element pair p: lo-nibble group
    uses qh bit 2p, hi-nibble group bit 2p+1."""
    d = _read_f16(blocks, 0)
    dmin = _read_f16(blocks, 2)
    sc, mn = _unpack_q4k_scales(blocks[..., 4:16])
    qh = blocks[..., 16:48]
    qs = blocks[..., 48:176]

    outs = []
    for pair in range(4):
        grp = qs[..., 32 * pair:32 * (pair + 1)]
        lo = (grp & 0xF).astype(jnp.float32) + (
            ((qh >> (2 * pair)) & 1) << 4
        ).astype(jnp.float32)
        hi = (grp >> 4).astype(jnp.float32) + (
            ((qh >> (2 * pair + 1)) & 1) << 4
        ).astype(jnp.float32)
        j0, j1 = 2 * pair, 2 * pair + 1
        outs.append(
            d[..., None] * sc[..., j0:j0 + 1] * lo
            - dmin[..., None] * mn[..., j0:j0 + 1]
        )
        outs.append(
            d[..., None] * sc[..., j1:j1 + 1] * hi
            - dmin[..., None] * mn[..., j1:j1 + 1]
        )
    vals = jnp.concatenate(outs, axis=-1)
    return vals.reshape(*blocks.shape[:-2], -1).astype(dtype)


# ---------------------------------------------------------------------------
# numpy encoders (host-side ingest; RTN two-level scales)
# ---------------------------------------------------------------------------

def quantize_q6_k(x: np.ndarray) -> np.ndarray:
    """x [..., K] (K % 256 == 0) -> blocks [..., K/256, 210] uint8."""
    x = np.asarray(x, np.float32)
    lead = x.shape[:-1]
    xb = x.reshape(-1, QK_K // 16, 16)  # [n_sb_total, 16 subblocks, 16]
    n = xb.shape[0]

    # per-sub-block signed-absmax scale, super scale d = max|s|/127
    idx = np.argmax(np.abs(xb), axis=-1)
    smax = np.take_along_axis(xb, idx[..., None], axis=-1)[..., 0]  # [n, 16]
    s = smax / -32.0
    d = np.max(np.abs(s), axis=-1) / 127.0  # [n]
    inv_d = np.where(d == 0, 0.0, 1.0 / np.where(d == 0, 1, d))
    sc = np.clip(np.round(s * inv_d[:, None]), -128, 127).astype(np.int8)

    eff = d[:, None] * sc.astype(np.float32)  # effective sub scales
    inv_eff = np.where(eff == 0, 0.0, 1.0 / np.where(eff == 0, 1, eff))
    q = np.clip(np.round(xb * inv_eff[..., None]), -32, 31).astype(np.int32) + 32
    q = q.reshape(n, QK_K)  # element order

    blocks = np.zeros((n, 210), np.uint8)
    for half in range(2):
        base = 128 * half
        q1 = q[:, base:base + 32]
        q2 = q[:, base + 32:base + 64]
        q3 = q[:, base + 64:base + 96]
        q4 = q[:, base + 96:base + 128]
        blocks[:, 64 * half:64 * half + 32] = (q1 & 0xF) | ((q3 & 0xF) << 4)
        blocks[:, 64 * half + 32:64 * half + 64] = (q2 & 0xF) | ((q4 & 0xF) << 4)
        blocks[:, 128 + 32 * half:128 + 32 * half + 32] = (
            (q1 >> 4) | ((q2 >> 4) << 2) | ((q3 >> 4) << 4) | ((q4 >> 4) << 6)
        )
    blocks[:, 192:208] = sc.view(np.uint8)
    blocks[:, 208:210] = (
        d.astype(np.float16).view(np.uint8).reshape(n, 2)
    )
    return blocks.reshape(*lead, x.shape[-1] // QK_K, 210)


def quantize_q2_k(x: np.ndarray) -> np.ndarray:
    """x [..., K] (K % 256 == 0) -> blocks [..., K/256, 84] uint8."""
    x = np.asarray(x, np.float32)
    lead = x.shape[:-1]
    xb = x.reshape(-1, 16, 16)  # 16 sub-blocks of 16
    n = xb.shape[0]

    d, dmin, sc, mn, q = _two_level_asym_scales(xb, qmax=3, super_max=15)
    q = q.reshape(n, QK_K)

    blocks = np.zeros((n, 84), np.uint8)
    blocks[:, 0:16] = sc | (mn << 4)
    for h in range(2):
        acc = np.zeros((n, 32), np.uint8)
        for j in range(4):
            e0 = 128 * h + 32 * j
            acc |= (q[:, e0:e0 + 32] << (2 * j)).astype(np.uint8)
        blocks[:, 16 + 32 * h:16 + 32 * (h + 1)] = acc
    blocks[:, 80:82] = d.astype(np.float16).view(np.uint8).reshape(n, 2)
    blocks[:, 82:84] = dmin.astype(np.float16).view(np.uint8).reshape(n, 2)
    return blocks.reshape(*lead, x.shape[-1] // QK_K, 84)


def quantize_q3_k(x: np.ndarray) -> np.ndarray:
    """x [..., K] (K % 256 == 0) -> blocks [..., K/256, 110] uint8."""
    x = np.asarray(x, np.float32)
    lead = x.shape[:-1]
    xb = x.reshape(-1, 16, 16)
    n = xb.shape[0]

    idx = np.argmax(np.abs(xb), axis=-1)
    smax = np.take_along_axis(xb, idx[..., None], axis=-1)[..., 0]
    s = smax / -4.0
    d = np.max(np.abs(s), axis=-1) / 31.0
    inv_d = np.where(d == 0, 0.0, 1.0 / np.where(d == 0, 1, d))
    sc = np.clip(np.round(s * inv_d[:, None]), -32, 31).astype(np.int32)

    eff = d[:, None] * sc.astype(np.float32)
    inv_eff = np.where(eff == 0, 0.0, 1.0 / np.where(eff == 0, 1, eff))
    q = np.clip(np.round(xb * inv_eff[..., None]), -4, 3).astype(np.int32)
    qp = (q + 4).astype(np.uint8).reshape(n, QK_K)  # 0..7

    blocks = np.zeros((n, 110), np.uint8)
    hmask = np.zeros((n, 32), np.uint8)
    for h in range(2):
        acc = np.zeros((n, 32), np.uint8)
        for j in range(4):
            e0 = 128 * h + 32 * j
            grp = qp[:, e0:e0 + 32]
            acc |= ((grp & 3) << (2 * j)).astype(np.uint8)
            hmask |= ((grp >> 2) << (4 * h + j)).astype(np.uint8)
        blocks[:, 32 + 32 * h:32 + 32 * (h + 1)] = acc
    blocks[:, 0:32] = hmask
    # 6-bit scale pack (inverse of _unpack_q3k_scales), bias +32
    st = (sc + 32).astype(np.uint8)  # [n, 16]
    sp = np.zeros((n, 12), np.uint8)
    for i in range(16):
        j, grp = i & 3, i >> 2
        lo4, hi2 = st[:, i] & 0xF, st[:, i] >> 4
        if grp == 0:
            sp[:, j] |= lo4
        elif grp == 1:
            sp[:, 4 + j] |= lo4
        elif grp == 2:
            sp[:, j] |= lo4 << 4
        else:
            sp[:, 4 + j] |= lo4 << 4
        sp[:, 8 + j] |= hi2 << (2 * grp)
    blocks[:, 96:108] = sp
    blocks[:, 108:110] = d.astype(np.float16).view(np.uint8).reshape(n, 2)
    return blocks.reshape(*lead, x.shape[-1] // QK_K, 110)


def _two_level_asym_scales(xb: np.ndarray, qmax: int, super_max: int = 63):
    """Shared q2_K/q4_K/q5_K RTN scale search over [n, n_sub, sub] blocks:
    per-sub-block (scale, min) quantized to `super_max`-code integers
    under fp16 super-scales. Returns (d, dmin, sc, mn, q) with q the
    codes in [0, qmax]."""
    mins = np.minimum(xb.min(axis=-1), 0.0)  # (m >= 0 convention)
    maxs = xb.max(axis=-1)
    scales = (maxs - mins) / qmax
    d = scales.max(axis=-1) / super_max
    dmin = (-mins).max(axis=-1) / super_max
    inv_d = np.where(d == 0, 0.0, 1.0 / np.where(d == 0, 1, d))
    inv_dm = np.where(dmin == 0, 0.0, 1.0 / np.where(dmin == 0, 1, dmin))
    sc = np.clip(np.round(scales * inv_d[:, None]), 0, super_max).astype(np.uint8)
    mn = np.clip(np.round(-mins * inv_dm[:, None]), 0, super_max).astype(np.uint8)

    eff_s = d[:, None] * sc.astype(np.float32)
    eff_m = dmin[:, None] * mn.astype(np.float32)
    inv_eff = np.where(eff_s == 0, 0.0, 1.0 / np.where(eff_s == 0, 1, eff_s))
    q = np.clip(
        np.round((xb + eff_m[..., None]) * inv_eff[..., None]), 0, qmax
    ).astype(np.uint8)
    return d, dmin, sc, mn, q


def _pack_q4k_scales(sc: np.ndarray, mn: np.ndarray) -> np.ndarray:
    """[n, 8] 6-bit scales/mins -> 12 packed bytes (inverse of
    _unpack_q4k_scales); shared by q4_K and q5_K."""
    n = sc.shape[0]
    packed = np.zeros((n, 12), np.uint8)
    for j in range(4):
        packed[:, j] = sc[:, j] | ((sc[:, j + 4] >> 4) << 6)
        packed[:, j + 4] = mn[:, j] | ((mn[:, j + 4] >> 4) << 6)
        packed[:, j + 8] = (sc[:, j + 4] & 0xF) | ((mn[:, j + 4] & 0xF) << 4)
    return packed


def quantize_q5_k(x: np.ndarray) -> np.ndarray:
    """x [..., K] (K % 256 == 0) -> blocks [..., K/256, 176] uint8."""
    x = np.asarray(x, np.float32)
    lead = x.shape[:-1]
    xb = x.reshape(-1, 8, 32)
    n = xb.shape[0]

    d, dmin, sc, mn, q = _two_level_asym_scales(xb, qmax=31)

    blocks = np.zeros((n, 176), np.uint8)
    blocks[:, 0:2] = d.astype(np.float16).view(np.uint8).reshape(n, 2)
    blocks[:, 2:4] = dmin.astype(np.float16).view(np.uint8).reshape(n, 2)
    blocks[:, 4:16] = _pack_q4k_scales(sc, mn)
    qh = np.zeros((n, 32), np.uint8)
    for pair in range(4):
        lo, hi = q[:, 2 * pair], q[:, 2 * pair + 1]
        blocks[:, 48 + 32 * pair:48 + 32 * (pair + 1)] = (
            (lo & 0xF) | ((hi & 0xF) << 4)
        )
        qh |= ((lo >> 4) << (2 * pair)) | ((hi >> 4) << (2 * pair + 1))
    blocks[:, 16:48] = qh
    return blocks.reshape(*lead, x.shape[-1] // QK_K, 176)


def quantize_q4_k(x: np.ndarray) -> np.ndarray:
    """x [..., K] (K % 256 == 0) -> blocks [..., K/256, 144] uint8."""
    x = np.asarray(x, np.float32)
    lead = x.shape[:-1]
    xb = x.reshape(-1, 8, 32)  # 8 sub-blocks of 32
    n = xb.shape[0]

    d, dmin, sc, mn, q = _two_level_asym_scales(xb, qmax=15)

    blocks = np.zeros((n, 144), np.uint8)
    blocks[:, 0:2] = d.astype(np.float16).view(np.uint8).reshape(n, 2)
    blocks[:, 2:4] = dmin.astype(np.float16).view(np.uint8).reshape(n, 2)
    blocks[:, 4:16] = _pack_q4k_scales(sc, mn)
    for pair in range(4):
        lo = q[:, 2 * pair]
        hi = q[:, 2 * pair + 1]
        blocks[:, 16 + 32 * pair:16 + 32 * (pair + 1)] = lo | (hi << 4)
    return blocks.reshape(*lead, x.shape[-1] // QK_K, 144)
