"""Synthetic QTensor fields for kernel smokes and benchmarks.

The fused-GEMV kernels only see packed fields; running the real
host-side quantizer at benchmark shapes costs minutes (the k-quant
numpy pass on a 4096x14336 weight measured ~90 s on the bench host,
r05) while random-but-valid fields cost milliseconds and exercise the
identical compiled program. Used by bench.py's compile-smoke stage and
scripts/tpu_smoke.py."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.quant.qtensor import QTensor
from bigdl_tpu.quant.qtypes import resolve_qtype


def synth_qtensor(qtype: str, O: int, K: int,
                  rng: np.random.Generator | None = None) -> QTensor:
    """Random-but-valid QTensor host-side fields (not device-put)."""
    rng = rng or np.random.default_rng(0)
    spec = resolve_qtype(qtype)
    f16 = jnp.float16
    if qtype == "sym_int8":
        fields = dict(
            data=jnp.asarray(rng.integers(-127, 128, (O, K), np.int8)),
            scales=jnp.asarray(rng.random((O, K // 32), np.float32) * 0.01,
                               f16),
        )
    elif qtype == "q6_k":
        fields = dict(
            data=jnp.asarray(rng.integers(-32, 32, (O, K), np.int8)),
            scales=jnp.asarray(rng.random((O, K // 256), np.float32) * 0.01,
                               f16),
            sub_scales=jnp.asarray(
                rng.integers(-64, 64, (O, K // 16), np.int8)),
        )
    elif qtype == "q4_k":
        fields = dict(
            data=jnp.asarray(rng.integers(0, 256, (O, K // 2), np.uint8)),
            scales=jnp.asarray(rng.random((O, K // 256), np.float32) * 0.01,
                               f16),
            mins=jnp.asarray(rng.random((O, K // 256), np.float32) * 0.01,
                             f16),
            sub_scales=jnp.asarray(rng.integers(0, 64, (O, K // 32),
                                                np.uint8)),
            sub_mins=jnp.asarray(rng.integers(0, 64, (O, K // 32),
                                              np.uint8)),
        )
    elif qtype == "asym_int4":
        fields = dict(
            data=jnp.asarray(rng.integers(0, 256, (O, K // 2), np.uint8)),
            scales=jnp.asarray(rng.random((O, K // 32), np.float32) * 0.01,
                               f16),
            mins=jnp.asarray(rng.random((O, K // 32), np.float32) * -0.08,
                             f16),
        )
    else:  # sym_int4 / nf4 / fp4: packed nibbles + one scale per block
        nb = K // spec.block_size
        fields = dict(
            data=jnp.asarray(rng.integers(0, 256, (O, K // 2), np.uint8)),
            scales=jnp.asarray(rng.random((O, nb), np.float32) * 0.01, f16),
        )
    return QTensor(qtype=qtype, **fields)
