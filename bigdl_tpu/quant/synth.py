"""Synthetic QTensor fields for kernel smokes and benchmarks.

The fused-GEMV kernels only see packed fields; running the real
host-side quantizer at benchmark shapes costs minutes (the k-quant
numpy pass on a 4096x14336 weight measured ~90 s on the bench host,
r05) while random-but-valid fields cost milliseconds and exercise the
identical compiled program. Used by bench.py's compile-smoke stage and
scripts/tpu_smoke.py."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.quant.qtensor import QTensor
from bigdl_tpu.quant.qtypes import resolve_qtype


def synth_qtensor(qtype: str, O: int, K: int,
                  rng: np.random.Generator | None = None) -> QTensor:
    """Random-but-valid QTensor host-side fields (not device-put)."""
    rng = rng or np.random.default_rng(0)
    spec = resolve_qtype(qtype)
    f16 = jnp.float16

    def scales(nb, mag=0.01):
        return jnp.asarray(rng.random((O, nb), np.float32) * mag, f16)

    if qtype in ("sym_int8", "q3_k"):
        sub = spec.block_size if spec.superblock else None
        fields = dict(
            data=jnp.asarray(rng.integers(-127, 128, (O, K), np.int8)
                             if qtype == "sym_int8"
                             else rng.integers(-4, 4, (O, K), np.int8)),
            scales=scales(K // (spec.superblock or spec.block_size)),
        )
        if sub:
            fields["sub_scales"] = jnp.asarray(
                rng.integers(-32, 32, (O, K // sub), np.int8))
    elif qtype == "asym_int5":
        fields = dict(
            data=jnp.asarray(rng.integers(0, 32, (O, K), np.int8)),
            scales=scales(K // 32),
            mins=scales(K // 32, mag=-0.08),
        )
    elif qtype in ("fp8_e4m3", "fp8_e5m2"):
        dt = jnp.float8_e4m3fn if qtype == "fp8_e4m3" else jnp.float8_e5m2
        fields = dict(
            data=jnp.asarray(rng.normal(size=(O, K)), np.float32).astype(dt),
            scales=scales(K // 128),
        )
    elif qtype == "q6_k":
        fields = dict(
            data=jnp.asarray(rng.integers(-32, 32, (O, K), np.int8)),
            scales=scales(K // 256),
            sub_scales=jnp.asarray(
                rng.integers(-64, 64, (O, K // 16), np.int8)),
        )
    elif qtype in ("q4_k", "q5_k", "q2_k"):
        sub = spec.block_size  # 32 / 32 / 16
        nbytes = K * spec.bits // 8 if spec.storage == "packed_planes" \
            else K // 2
        smax = 16 if qtype == "q2_k" else 64
        fields = dict(
            data=jnp.asarray(rng.integers(0, 256, (O, nbytes), np.uint8)),
            scales=scales(K // 256),
            mins=scales(K // 256),
            sub_scales=jnp.asarray(rng.integers(0, smax, (O, K // sub),
                                                np.uint8)),
            sub_mins=jnp.asarray(rng.integers(0, smax, (O, K // sub),
                                              np.uint8)),
        )
    elif qtype == "asym_int4":
        fields = dict(
            data=jnp.asarray(rng.integers(0, 256, (O, K // 2), np.uint8)),
            scales=scales(K // 32),
            mins=scales(K // 32, mag=-0.08),
        )
    elif spec.storage == "packed_planes":  # sym_int5 / fp6 / nf3
        fields = dict(
            data=jnp.asarray(rng.integers(0, 256, (O, K * spec.bits // 8),
                                          np.uint8)),
            scales=scales(K // spec.block_size),
        )
    else:  # sym_int4 / nf4 / fp4: packed nibbles + one scale per block
        nb = K // spec.block_size
        fields = dict(
            data=jnp.asarray(rng.integers(0, 256, (O, K // 2), np.uint8)),
            scales=scales(nb),
        )
    return QTensor(qtype=qtype, **fields)
