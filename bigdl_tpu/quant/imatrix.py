"""Importance-matrix (weighted) quantization.

Equivalent of the reference's `ggml_quantize_tensor_with_weights` /
`ggml_quantize_tensor_rtn_with_weights` entry points
(ggml/model/llama/llama_cpp.py:955-1047 in /root/reference, driven from
low_bit_linear.py's imatrix path): per-channel importance weights
(activation second moments collected on a calibration set) steer the
block scale search, so frequently-activated channels round more
accurately.

Default (un-weighted) quantization in this framework is plain RTN — the
reference's `*_rtn` variants; `quantize_with_weights` is the upgrade:
for each block it searches candidate scales minimizing the weighted MSE
    sum_i w_i * (x_i - d * q_i(d))^2
over a grid around the RTN scale (the same shape of search as ggml's
make_qx_quants).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigdl_tpu.quant.qtypes import resolve_qtype


def _search_scales(
    xb: np.ndarray,  # [n_blocks, bs]
    wb: np.ndarray,  # [n_blocks, bs] importance weights
    qmin: int,
    qmax: int,
    anchor: np.ndarray,  # [n_blocks] RTN scale (signed)
    n_steps: int = 16,
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (d [n_blocks], q [n_blocks, bs] int codes)."""
    best_d = anchor.copy()
    inv = np.where(anchor == 0, 0.0, 1.0 / np.where(anchor == 0, 1, anchor))
    q = np.clip(np.round(xb * inv[:, None]), qmin, qmax)
    best_err = np.sum(wb * (xb - best_d[:, None] * q) ** 2, axis=-1)

    # candidates: scale the anchor by factors around 1 (ggml tries
    # nmax-1+is*0.1 style perturbations of the divisor)
    for f in np.linspace(0.75, 1.25, n_steps):
        d = anchor * f
        inv = np.where(d == 0, 0.0, 1.0 / np.where(d == 0, 1, d))
        q = np.clip(np.round(xb * inv[:, None]), qmin, qmax)
        # given the rounding, the OPTIMAL scale for these codes is the
        # weighted least-squares fit  d* = sum(w x q) / sum(w q^2)
        num = np.sum(wb * xb * q, axis=-1)
        den = np.sum(wb * q * q, axis=-1)
        d_opt = np.where(den > 0, num / np.maximum(den, 1e-30), d)
        err = np.sum(wb * (xb - d_opt[:, None] * q) ** 2, axis=-1)
        better = err < best_err
        best_d = np.where(better, d_opt, best_d)
        best_err = np.where(better, err, best_err)

    inv = np.where(best_d == 0, 0.0, 1.0 / np.where(best_d == 0, 1, best_d))
    q = np.clip(np.round(xb * inv[:, None]), qmin, qmax)
    return best_d, q


def quantize_with_weights(
    x: np.ndarray,  # [..., K]
    qtype: str,
    weights: Optional[np.ndarray] = None,  # [K] or broadcastable to x
):
    """Weighted-search quantization for sym_int4/sym_int8. Returns a
    QTensor. weights=None degrades to (searched, unweighted) quantization
    — still better than plain RTN."""
    import jax.numpy as jnp

    from bigdl_tpu.quant import QTensor
    from bigdl_tpu.quant.numerics import pack_nibbles

    spec = resolve_qtype(qtype)
    if spec.name not in ("sym_int4", "sym_int8"):
        raise NotImplementedError(f"imatrix search for {qtype}")
    x = np.asarray(x, np.float32)
    k = x.shape[-1]
    bs = spec.block_size
    assert k % bs == 0
    w = np.ones_like(x) if weights is None else np.broadcast_to(
        np.asarray(weights, np.float32), x.shape
    )
    lead = x.shape[:-1]
    xb = x.reshape(-1, bs)
    wb = w.reshape(-1, bs)

    if spec.name == "sym_int4":
        qmin, qmax, offset = -8, 7, 8
        idx = np.argmax(np.abs(xb), axis=-1)
        anchor = xb[np.arange(len(xb)), idx] / -8.0
    else:
        qmin, qmax, offset = -127, 127, 0
        anchor = np.abs(xb).max(axis=-1) / 127.0

    d, q = _search_scales(xb, wb, qmin, qmax, anchor)
    scales = d.astype(np.float16).reshape(*lead, k // bs)
    codes = (q + offset).reshape(*lead, k)
    if spec.name == "sym_int4":
        data = np.asarray(pack_nibbles(jnp.asarray(codes.astype(np.uint8))))
    else:
        data = codes.astype(np.int8)
    return QTensor(
        data=jnp.asarray(data), scales=jnp.asarray(scales), mins=None,
        qtype=spec.name,
    )
