"""Blockwise quantize/dequantize numerics, in pure jnp.

These are the TPU-native equivalents of the reference's native entry points
`ggml_quantize_tensor` / `ggml_dequantize_*` (ctypes surface enumerated in
/root/reference python/llm/src/ipex_llm/ggml/model/llama/llama_cpp.py:955-1065,
used from transformers/low_bit_linear.py:104-258). Numerics follow the ggml
block formats (Q4_0/Q4_1/Q5_0/Q5_1/Q8_0) and the bitsandbytes NF4/FP4
codebook scheme so that quantized-model quality lands in the same perplexity
band as the reference's README table.

Everything here is shape-polymorphic jnp and jit-safe: it runs on host CPU
during checkpoint conversion and on TPU when re-quantizing (e.g. FP8 KV
cache). Packing layout: 4-bit codes are packed two-per-uint8 along the last
(contraction) axis in half-split order — element j in the low nibble of
byte j, element j + K/2 in its high nibble (see pack_nibbles).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.quant.qtypes import QTypeSpec, resolve_qtype

_FP8_MAX = {"fp8_e4m3": 448.0, "fp8_e5m2": 57344.0}
_FP8_DTYPE = {"fp8_e4m3": jnp.float8_e4m3fn, "fp8_e5m2": jnp.float8_e5m2}


def _blocked(x: jax.Array, block_size: int) -> jax.Array:
    k = x.shape[-1]
    if k % block_size != 0:
        raise ValueError(
            f"last dim {k} not divisible by block_size {block_size}; "
            "pad the weight before quantizing"
        )
    return x.reshape(*x.shape[:-1], k // block_size, block_size)


def pack_nibbles(codes: jax.Array) -> jax.Array:
    """[..., K] uint8 codes in [0,16) -> [..., K//2] packed uint8.

    Half-split layout: byte j carries element j (low nibble) and element
    j + K/2 (high nibble). Chosen for the TPU hot path: the fused GEMV
    kernel (ops/pallas/qmatmul.py) then reads the activations for the two
    nibble planes as two *contiguous* halves of x — an interleaved layout
    (2i, 2i+1 per byte) would need a strided lane deinterleave per call,
    which Mosaic can't express and XLA charges ~40us/call for.
    """
    k = codes.shape[-1]
    lo = codes[..., : k // 2]
    hi = codes[..., k // 2:]
    return (lo | (hi << 4)).astype(jnp.uint8)


def unpack_nibbles(packed: jax.Array) -> jax.Array:
    """[..., K//2] packed uint8 -> [..., K] uint8 codes (element order).

    Written as broadcast-shift + reshape rather than
    ``concatenate([lo, hi], -1)``: the pinned jaxlib's SPMD partitioner
    miscompiles concatenate along a sharded axis whenever the mesh has a
    second non-trivial axis (partial replication), which silently
    corrupted every packed-weight dequant on dp>1 inference meshes.
    The two spellings are bit-identical on unsharded inputs.
    """
    shifts = jnp.asarray([0, 4], jnp.uint8)[:, None]
    out = (packed[..., None, :] >> shifts) & 0xF
    return out.reshape(*packed.shape[:-1], 2 * packed.shape[-1])


def pack_planes(codes: jax.Array, planes: tuple) -> jax.Array:
    """[..., K] uint8 codes -> concatenated bit planes (uint8).

    The multi-split generalization of pack_nibbles: a b-bit plane over K
    elements is K*b/8 bytes where byte j carries elements j + m*(K*b/8)
    at bit offset b*m (m = 0 .. 8/b - 1). `planes` lists each plane's
    bit width, LOW bits of the code first (fp6 = (4, 2); sym_int5 =
    (4, 1); nf3 = (2, 1)); plane arrays concatenate along the last axis.
    Every unpack — XLA or the Pallas fused GEMV — is static shifts of
    contiguous slices, never a strided deinterleave.
    """
    k = codes.shape[-1]
    shift = 0
    outs = []
    for bits in planes:
        s = 8 // bits
        q = k // s
        sub = (codes >> shift) & ((1 << bits) - 1)
        acc = sub[..., :q].astype(jnp.uint8)
        for m in range(1, s):
            acc = acc | (sub[..., m * q:(m + 1) * q] << (bits * m)).astype(
                jnp.uint8)
        outs.append(acc)
        shift += bits
    return jnp.concatenate(outs, axis=-1)


def unpack_planes(data: jax.Array, planes: tuple, k: int) -> jax.Array:
    """Inverse of pack_planes: concatenated planes -> [..., K] uint8.

    Same broadcast-shift + reshape spelling as unpack_nibbles (instead of
    a concatenate over the per-byte sub-element splits) — see the
    sharded-concatenate note there.
    """
    off = 0
    shift = 0
    code = None
    for bits in planes:
        s = 8 // bits
        q = k // s
        plane = data[..., off:off + q]
        shifts = (bits * jnp.arange(s, dtype=jnp.uint8))[:, None]
        vals = (plane[..., None, :] >> shifts) & ((1 << bits) - 1)
        vals = vals.reshape(*plane.shape[:-1], s * q)
        part = (vals.astype(jnp.uint8) << shift).astype(jnp.uint8)
        code = part if code is None else code | part
        off += q
        shift += bits
    return code


def _signed_absmax(xb: jax.Array) -> jax.Array:
    """Per-block value with the largest magnitude, keeping its sign (ggml Q4_0)."""
    idx = jnp.argmax(jnp.abs(xb), axis=-1, keepdims=True)
    return jnp.take_along_axis(xb, idx, axis=-1)[..., 0]


def _safe_inv(d: jax.Array) -> jax.Array:
    return jnp.where(d == 0, 0.0, 1.0 / jnp.where(d == 0, 1.0, d))


@functools.lru_cache(maxsize=None)
def _codebook_tables(qtype_name: str):
    """(codebook, sorted-order permutation, decision boundaries) as numpy."""
    spec = resolve_qtype(qtype_name)
    cb = spec.codebook
    order = np.argsort(cb)
    sorted_cb = cb[order]
    boundaries = (sorted_cb[1:] + sorted_cb[:-1]) / 2.0
    return cb, order.astype(np.int32), boundaries


def quantize_blockwise(x: jax.Array, spec: QTypeSpec) -> dict:
    """Quantize x along its last axis. Returns a dict of QTensor array
    fields: always data/scales (+ mins for asymmetric types, +
    sub_scales/sub_mins for two-level k-quants).

    Single-level scales/mins are float16 with shape [..., K //
    block_size], matching the reference's half-precision block headers.
    K-quants encode on host (numpy) through the llama.cpp codec
    (quant/kquants.py) and repack into the TPU planar layout
    (quant/kq_planar.py) that the fused Pallas GEMV reads.
    """
    x = x.astype(jnp.float32)
    name = spec.name

    if spec.superblock:  # k-quants: host codec + planar repack
        from bigdl_tpu.quant import kq_planar, kquants

        xh = np.asarray(x)  # host-side encode (ingest path)
        enc = getattr(kquants, f"quantize_{name}")
        repack = getattr(kq_planar, f"from_{name.replace('_', '')}_blocks")
        fields = repack(enc(xh))
        return {k: jnp.asarray(v) for k, v in fields.items()}

    if spec.storage.startswith("fp8"):
        xb = _blocked(x, spec.block_size)
        absmax = jnp.max(jnp.abs(xb), axis=-1)
        scale = absmax / _FP8_MAX[name]
        q = (xb * _safe_inv(scale)[..., None]).astype(_FP8_DTYPE[name])
        return dict(data=q.reshape(x.shape), scales=scale.astype(jnp.float16))

    xb = _blocked(x, spec.block_size)

    if spec.codebook is not None:
        cb, order, boundaries = _codebook_tables(name)
        cb_max = float(np.max(np.abs(cb)))
        absmax = jnp.max(jnp.abs(xb), axis=-1)
        scale = absmax / cb_max
        xn = xb * _safe_inv(scale)[..., None]
        idx_sorted = jnp.searchsorted(jnp.asarray(boundaries), xn)
        codes = jnp.asarray(order)[idx_sorted]
        codes = codes.reshape(x.shape)
        if spec.storage == "packed_u8":
            data = pack_nibbles(codes.astype(jnp.uint8))
        elif spec.storage == "packed_planes":
            data = pack_planes(codes.astype(jnp.uint8), spec.planes)
        else:
            data = codes.astype(jnp.int8)
        return dict(data=data, scales=scale.astype(jnp.float16))

    if name == "sym_int4":
        smax = _signed_absmax(xb)
        d = smax / -8.0
        q = jnp.clip(jnp.round(xb * _safe_inv(d)[..., None]) + 8.0, 0, 15)
        data = pack_nibbles(q.reshape(x.shape).astype(jnp.uint8))
        return dict(data=data, scales=d.astype(jnp.float16))

    if name == "asym_int4":
        mins = jnp.min(xb, axis=-1)
        d = (jnp.max(xb, axis=-1) - mins) / 15.0
        q = jnp.clip(jnp.round((xb - mins[..., None]) * _safe_inv(d)[..., None]), 0, 15)
        data = pack_nibbles(q.reshape(x.shape).astype(jnp.uint8))
        return dict(data=data, scales=d.astype(jnp.float16),
                    mins=mins.astype(jnp.float16))

    if name == "sym_int5":
        smax = _signed_absmax(xb)
        d = smax / -16.0
        q = jnp.clip(jnp.round(xb * _safe_inv(d)[..., None]) + 16.0, 0, 31)
        data = pack_planes(q.reshape(x.shape).astype(jnp.uint8), spec.planes)
        return dict(data=data, scales=d.astype(jnp.float16))

    if name == "asym_int5":
        mins = jnp.min(xb, axis=-1)
        d = (jnp.max(xb, axis=-1) - mins) / 31.0
        q = jnp.clip(jnp.round((xb - mins[..., None]) * _safe_inv(d)[..., None]), 0, 31)
        return dict(data=q.reshape(x.shape).astype(jnp.int8),
                    scales=d.astype(jnp.float16), mins=mins.astype(jnp.float16))

    if name == "sym_int8":
        d = jnp.max(jnp.abs(xb), axis=-1) / 127.0
        q = jnp.clip(jnp.round(xb * _safe_inv(d)[..., None]), -127, 127)
        return dict(data=q.reshape(x.shape).astype(jnp.int8),
                    scales=d.astype(jnp.float16))

    raise NotImplementedError(f"quantize: qtype {name}")


def kq_effective_scales(
    scales: jax.Array,  # f16 super-scales d [..., K/superblock]
    sub_scales: jax.Array,  # integer sub-scales [..., K/block_size]
) -> jax.Array:
    """Per-sub-block f32 effective scale d*sc of a planar k-quant.
    Exact: f16 (11-bit mantissa) x <=8-bit integer fits f32."""
    reps = sub_scales.shape[-1] // scales.shape[-1]
    return (
        jnp.repeat(scales.astype(jnp.float32), reps, axis=-1)
        * sub_scales.astype(jnp.float32)
    )


def dequantize_blockwise(
    data: jax.Array,
    scales: jax.Array,
    mins: jax.Array | None,
    spec: QTypeSpec,
    dtype=jnp.float32,
    sub_scales: jax.Array | None = None,
    sub_mins: jax.Array | None = None,
) -> jax.Array:
    """Inverse of quantize_blockwise; returns [..., K] in `dtype`."""
    name = spec.name

    if name in ("q4_k", "q2_k", "q5_k"):
        # planar two-level asym: w = (d*sc)*q - (dmin*mn); matches the
        # kquants.dequant_* byte decoders bit-for-bit (f32, same grouping)
        if spec.storage == "packed_u8":
            codes = unpack_nibbles(data)
        else:
            k = data.shape[-1] * 8 // spec.bits
            codes = unpack_planes(data, spec.planes, k)
        codes = codes.astype(jnp.float32)
        s = kq_effective_scales(scales, sub_scales)
        m = kq_effective_scales(mins, sub_mins)
        vb = _blocked(codes, spec.block_size)
        y = vb * s[..., None] - m[..., None]
        return y.reshape(codes.shape).astype(dtype)

    if name in ("q6_k", "q3_k"):
        # planar two-level sym: w = (d*sc)*q, codes already centered
        s = kq_effective_scales(scales, sub_scales)
        vb = _blocked(data.astype(jnp.float32), spec.block_size)
        y = vb * s[..., None]
        return y.reshape(data.shape).astype(dtype)

    if spec.storage.startswith("fp8"):
        xb = _blocked(data.astype(jnp.float32), spec.block_size)
        y = xb * scales.astype(jnp.float32)[..., None]
        return y.reshape(data.shape).astype(dtype)

    if spec.storage == "packed_u8":
        codes = unpack_nibbles(data)
    elif spec.storage == "packed_planes":
        codes = unpack_planes(data, spec.planes,
                              data.shape[-1] * 8 // spec.bits)
    else:
        codes = data

    if spec.codebook is not None:
        cb = jnp.asarray(spec.codebook)
        vals = cb[codes.astype(jnp.int32) & ((1 << max(spec.bits, 4)) - 1)]
    elif name == "sym_int4":
        vals = codes.astype(jnp.float32) - 8.0
    elif name == "asym_int4":
        vals = codes.astype(jnp.float32)
    elif name == "sym_int5":
        vals = codes.astype(jnp.float32) - 16.0
    elif name == "asym_int5":
        vals = codes.astype(jnp.float32)
    elif name == "sym_int8":
        vals = codes.astype(jnp.float32)
    else:
        raise NotImplementedError(f"dequantize: qtype {name}")

    vb = _blocked(vals, spec.block_size)
    y = vb * scales.astype(jnp.float32)[..., None]
    if mins is not None:
        y = y + mins.astype(jnp.float32)[..., None]
    return y.reshape(vals.shape).astype(dtype)
