"""bigdl_tpu — a TPU-native LLM acceleration framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of IPEX-LLM
(reference: /root/reference, qiuxin2012/BigDL): low-bit weight quantization
(INT4/INT8/NF4/FP4/FP8/...), an optimized model zoo, KV-cache management,
decode-time algorithms (speculative decoding, prompt lookup), QLoRA-style
finetuning, and distributed inference/training over a `jax.sharding.Mesh`.

Where the reference patches PyTorch/HuggingFace modules in place
(ipex_llm/transformers/convert.py), this framework owns its model
definitions: models are pure functions over parameter pytrees whose leaves
may be `QTensor` (packed low-bit weights + scales), and everything runs
under `jax.jit` on a device mesh.

Public API (mirrors the reference's user surface, optimize.py:197 and
transformers/model.py:111):

    from bigdl_tpu import AutoModelForCausalLM
    model = AutoModelForCausalLM.from_pretrained(path, load_in_low_bit="sym_int4")
    out = model.generate(token_ids, max_new_tokens=64)
"""

__version__ = "0.1.0"

__all__ = [
    "QTensor",
    "quantize",
    "dequantize",
    "qtype_registry",
    "AutoModelForCausalLM",
    "optimize_model",
    "ChatSession",
    "__version__",
]

# every public name -> providing submodule; ALL resolved lazily (PEP 562).
# `import bigdl_tpu` must stay jax-free: the quant exports drag jax in,
# and jax-free importability is a hard contract of `bigdl-tpu lint` /
# scripts/ci.sh --lint (the gate asserts jax never enters sys.modules).
_LAZY = {
    "QTensor": "bigdl_tpu.quant",
    "quantize": "bigdl_tpu.quant",
    "dequantize": "bigdl_tpu.quant",
    "qtype_registry": "bigdl_tpu.quant",
    "AutoModelForCausalLM": "bigdl_tpu.api",
    "optimize_model": "bigdl_tpu.api",
    "ChatSession": "bigdl_tpu.chat",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module 'bigdl_tpu' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
