"""Generation: jitted prefill + on-device decode loop.

The reference rides HF `GenerationMixin.generate` — a host-side Python
token loop launching one eager kernel per op (SURVEY.md §3.2). The
TPU-native design compiles the whole decode loop into one XLA program:
`lax.while_loop` carrying the KV cache, with on-device sampling
(greedy / temperature / top-k / top-p) and early exit when every row hit
EOS. Host↔device traffic is two transfers total (prompt in, tokens out).

Prompt lengths are bucketed (powers of two) so at most O(log S) prefill
programs are ever compiled per model.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache
from bigdl_tpu.models.config import ModelConfig


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    repetition_penalty: float = 1.0  # HF semantics: >1 discourages repeats
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0


def apply_repetition_penalty(
    logits: jax.Array,  # [B, V]
    seen: jax.Array,  # [B, V] bool: token appeared in prompt or output
    penalty,  # float or [B] traced
) -> jax.Array:
    """HF RepetitionPenaltyLogitsProcessor semantics (the reference fuses
    this as xe_addons.repetition_penalty_logits_process_inplaced): seen
    tokens' scores divide by the penalty when positive, multiply when
    negative."""
    p = jnp.asarray(penalty, logits.dtype)
    if p.ndim == 1:
        p = p[:, None]
    penalized = jnp.where(logits < 0, logits * p, logits / p)
    return jnp.where(seen, penalized, logits)


def seen_from_prompt(tokens: jax.Array, start: jax.Array, vocab: int) -> jax.Array:
    """[B, V] bool presence mask over the real (non-pad) prompt tokens."""
    B, T = tokens.shape
    real = jnp.arange(T)[None, :] >= start[:, None]
    idx = jnp.where(real, tokens, vocab)  # pads land in the overflow bin
    return (
        jnp.zeros((B, vocab + 1), jnp.bool_)
        .at[jnp.arange(B)[:, None], idx].set(True)[:, :vocab]
    )


def sample_token(
    logits: jax.Array,  # [B, V] float32
    key: jax.Array,
    gen: GenerationConfig,
) -> jax.Array:
    """On-device sampling; gen is static so dead branches compile away."""
    if not gen.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / max(gen.temperature, 1e-5)
    if gen.top_k is not None:
        kth = jax.lax.top_k(logits, gen.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if gen.top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        cutoff_idx = jnp.sum(cum < gen.top_p, axis=-1, keepdims=True) - 1
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def filter_logits_per_row(
    logits: jax.Array,  # [B, ..., V] float32
    temperature: jax.Array,  # [B] float32
    top_k: jax.Array,  # [B] int32, <=0 disables
    top_p: jax.Array,  # [B] float32, >=1 disables
) -> jax.Array:
    """Temperature + top-k + top-p filtering with traced per-row params;
    returns masked/scaled logits whose softmax is the exact sampling
    distribution (shared by sample_token_per_row and the speculative
    rejection-acceptance path, which needs the DISTRIBUTION, not just a
    sample). Extra middle axes broadcast (verify rounds pass [B, K, V])."""
    V = logits.shape[-1]
    exp = (slice(None),) + (None,) * (logits.ndim - 1)
    lt = logits / jnp.maximum(temperature, 1e-5)[exp]
    sorted_desc = jnp.sort(lt, axis=-1)[..., ::-1]
    # top-k first: threshold at the k-th largest value per row
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(top_k - 1, 0, V - 1)[exp], axis=-1
    )
    lt_k = jnp.where((top_k > 0)[exp] & (lt < kth), -jnp.inf, lt)
    # top-p (nucleus) over the top-k-FILTERED, renormalized
    # distribution (HF order; matches sample_token): -inf survivors
    # sort last and carry zero probability
    sorted_k = jnp.sort(lt_k, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_k, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs
    cutoff_idx = jnp.sum(cum < top_p[exp], axis=-1, keepdims=True) - 1
    cutoff = jnp.take_along_axis(
        sorted_k, jnp.clip(cutoff_idx, 0, V - 1), axis=-1
    )
    return jnp.where((top_p < 1.0)[exp] & (lt_k < cutoff), -jnp.inf, lt_k)


def sample_token_per_row(
    logits: jax.Array,  # [B, V] float32
    key: jax.Array,
    temperature: jax.Array,  # [B] float32
    top_k: jax.Array,  # [B] int32, <=0 disables
    top_p: jax.Array,  # [B] float32, >=1 disables
    do_sample: jax.Array,  # [B] bool
) -> jax.Array:
    """Per-row sampling with TRACED parameters — every row of a batch can
    carry its own temperature/top-k/top-p (the serving engine's
    per-request sampling; the reference serves one sampling config per
    worker, model_worker.py:28-200, so this exceeds it). Rows with
    do_sample=False take the plain argmax.
    """
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def run_sampling(_):
        masked = filter_logits_per_row(logits, temperature, top_k, top_p)
        return jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)

    # all-greedy batches (the serving engine's common case) skip the
    # full-vocab sort/softmax entirely
    sampled = jax.lax.cond(
        jnp.any(do_sample), run_sampling, lambda _: greedy, operand=None
    )
    return jnp.where(do_sample, sampled, greedy)


def pad_prompts(
    prompts: Sequence[Sequence[int]], pad_id: int, bucket: Optional[int] = None
) -> tuple[np.ndarray, np.ndarray]:
    """Left-pad a ragged batch to a power-of-two bucket.

    Returns (tokens [B, T], start [B]) — `start[b]` = number of pad slots,
    feeding KVCache's validity mask. Left-padding keeps every row's last
    prompt token at index T-1, so prefill logits need no gather.
    """
    maxlen = max(len(p) for p in prompts)
    if bucket is None:
        bucket = 16
        while bucket < maxlen:
            bucket *= 2
    assert bucket >= maxlen
    b = len(prompts)
    tokens = np.full((b, bucket), pad_id, np.int32)
    start = np.zeros((b,), np.int32)
    for i, p in enumerate(prompts):
        tokens[i, bucket - len(p):] = np.asarray(p, np.int32)
        start[i] = bucket - len(p)
    return tokens, start


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "gen", "model_forward", "cache_len", "quantize_kv",
        "compress_budget", "compress_window", "compress_kernel",
        "last_logits", "cache_init", "streaming",
    ),
    donate_argnames=(),
)
def generate_tokens(
    config: ModelConfig,
    params,
    tokens: jax.Array,  # [B, T] left-padded prompt
    start: jax.Array,  # [B]
    key: jax.Array,
    gen: GenerationConfig,
    model_forward,  # static: the family forward fn (models.llama.forward)
    cache_len: int,
    quantize_kv: bool = False,
    compress_budget: int = 0,  # SnapKV: compress prompt KV to this many slots
    compress_window: int = 32,
    compress_kernel: int = 7,
    # lm head on the last prefill position only (BIGDL_TPU_LAST_LM_HEAD;
    # reference IPEX_LLM_LAST_LM_HEAD) — saves the [B,T,V] prefill logits
    last_logits: bool = True,
    # family cache-init hook: fn(config, B, cache_len, quantize_kv) for
    # architectures whose state is not a KV cache (rwkv's RwkvState);
    # None = standard kvcache.init_cache
    cache_init=None,
    # (sink, window) or (sink, window, chunk) attention-sink streaming:
    # the cache is `window` slots and the oldest `chunk` non-sink slots
    # are evicted together once full (bigdl_tpu/streaming.py) —
    # generation length becomes unbounded
    streaming=None,
) -> jax.Array:
    """One compiled program: prefill + full decode loop.

    With compress_budget > 0 the prompt KV is SnapKV-compressed after
    prefill (reference DynamicCompressCache, kv.py:246-375) and the decode
    loop runs on the compact cache — less HBM traffic per token and a
    cache whose size is independent of prompt length.

    Returns [B, max_new_tokens] generated ids (pad_token_id after EOS).
    """
    from bigdl_tpu.utils import cache_len_for

    B, T = tokens.shape
    shift = None
    if streaming is not None:
        from bigdl_tpu.streaming import default_chunk, make_sink_shift

        sink, window = streaming[:2]
        chunk = streaming[2] if len(streaming) > 2 else default_chunk(window, sink)
        assert cache_len == window and cache_len > T
        assert not quantize_kv and compress_budget == 0 and cache_init is None
        shift = make_sink_shift(config, window, sink, chunk)
    else:
        assert cache_len >= T + gen.max_new_tokens
    if cache_init is not None:
        cache = cache_init(config, B, cache_len, quantize_kv)
        assert compress_budget == 0, "SnapKV needs a KV cache"
    else:
        cache = kvcache.init_cache(
            config.num_hidden_layers, B, cache_len, config.num_key_value_heads,
            config.head_dim_, quantize_kv=quantize_kv,
        )
    cache = dataclasses.replace(cache, start=start)

    if compress_budget:
        assert compress_budget > compress_window
        logits, cache, obs = model_forward(
            config, params, tokens, cache, mode="prefill",
            collect_obs=compress_window, last_logits_only=last_logits,
        )
        out_len = cache_len_for(compress_budget, gen.max_new_tokens)
        cache = kvcache.compress(
            cache, obs, compress_budget, out_len,
            window=compress_window, kernel=compress_kernel,
        )
    else:
        logits, cache = model_forward(
            config, params, tokens, cache, mode="prefill",
            last_logits_only=last_logits,
        )
    use_rep = gen.repetition_penalty != 1.0  # static: compiles away
    seen = (
        seen_from_prompt(tokens, start, config.vocab_size)
        if use_rep else jnp.zeros((B, 1), jnp.bool_)
    )

    key, k0 = jax.random.split(key)
    first_logits = logits[:, -1]
    if use_rep:
        first_logits = apply_repetition_penalty(
            first_logits, seen, gen.repetition_penalty
        )
    first = sample_token(first_logits, k0, gen)
    if use_rep:
        seen = seen.at[jnp.arange(B), first].set(True)

    out = jnp.full((B, gen.max_new_tokens), gen.pad_token_id, jnp.int32)
    out = out.at[:, 0].set(first)
    eos = gen.eos_token_id
    done = (
        first == eos if eos is not None else jnp.zeros((B,), jnp.bool_)
    )

    def cond(state):
        i, _, _, done, _, _, _ = state
        return (i < gen.max_new_tokens) & ~jnp.all(done)

    def step(state):
        i, cur, cache, done, out, key, seen = state
        if shift is not None:
            cache = shift(cache)  # evict the oldest non-sink slot if full
        logits, cache = model_forward(
            config, params, cur[:, None], cache, mode="decode"
        )
        key, k = jax.random.split(key)
        step_logits = logits[:, -1]
        if use_rep:
            step_logits = apply_repetition_penalty(
                step_logits, seen, gen.repetition_penalty
            )
        nxt = sample_token(step_logits, k, gen)
        if eos is not None:
            nxt = jnp.where(done, gen.pad_token_id, nxt)
            done = done | (nxt == eos)
        if use_rep:
            seen = seen.at[jnp.arange(B), nxt].set(True)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
        return (i + 1, nxt, cache, done, out, key, seen)

    state = (jnp.ones((), jnp.int32), first, cache, done, out, key, seen)
    _, _, _, _, out, _, _ = jax.lax.while_loop(cond, step, state)
    return out
