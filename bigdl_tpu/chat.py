"""Incremental multi-turn chat sessions.

The reference's `llm-chat` (cli/llm-cli dispatch) and our own one-shot
`TpuModel.generate` re-prefill the WHOLE conversation every turn — turn
N pays O(history) prefill again. A ChatSession keeps the KV cache alive
across turns: each send() prefills only the new tokens (bucketed for
compile reuse; stale padded slots are masked out by the causal mask and
overwritten later), then decodes token by token.

With `streaming=(sink, window)` the cache is a fixed attention-sink
window (bigdl_tpu/streaming.py): before each prefill the session evicts
enough chunks to make room, and during decode the standard full-cache
shift applies — the conversation length becomes unbounded in constant
memory, the original StreamingLLM use case.

Math note: incremental prefill is exactly equivalent to re-prefilling
the concatenated history (same cache contents, same rope positions), so
within the window a session's replies are byte-identical to one-shot
`generate` on the full transcript — tested in tests/test_chat.py.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache

_MIN_BUCKET = 16


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


class ChatSession:
    def __init__(
        self,
        model,
        max_len: int = 2048,
        streaming: Optional[tuple] = None,  # (sink, window[, chunk])
        compute_dtype=jnp.bfloat16,
    ):
        from bigdl_tpu.models import get_family

        self.model = model
        self.config = model.config
        fam = get_family(self.config.model_type)
        if getattr(fam, "init_cache", None) is not None:
            raise NotImplementedError(
                f"ChatSession supports the standard KV cache; "
                f"{self.config.model_type} uses a family cache adapter"
            )
        self._forward = model.forward_fn
        self._dtype = compute_dtype
        self._evict = None
        self._shift = None
        self._sink = self._chunk = 0
        if streaming is not None:
            from bigdl_tpu.streaming import default_chunk, make_sink_shift

            sink, window = streaming[:2]
            chunk = (streaming[2] if len(streaming) > 2
                     else default_chunk(window, sink))
            max_len = window
            self._sink, self._chunk = sink, chunk
            self._evicts: dict[int, object] = {}  # shift-amount -> jit
            self._evict = self._evict_by  # marker: streaming enabled
            self._shift = jax.jit(make_sink_shift(
                self.config, window, sink, chunk))
        self.max_len = max_len
        self.cache = kvcache.init_cache(
            self.config.num_hidden_layers, 1, max_len,
            self.config.num_key_value_heads, self.config.head_dim_,
        )
        self._prefill_jits: dict[int, object] = {}
        self._decode_jit = jax.jit(
            lambda p, t, c: self._forward(
                self.config, p, t, c, mode="decode",
                compute_dtype=self._dtype,
            )
        )

    @property
    def pos(self) -> int:
        return int(self.cache.pos)

    def reset(self) -> None:
        """Drop the conversation but keep every compiled program."""
        self.cache = kvcache.init_cache(
            self.config.num_hidden_layers, 1, self.max_len,
            self.config.num_key_value_heads, self.config.head_dim_,
        )

    def _evict_by(self, m: int):
        """Jitted m-slot evict, cached per distinct m (the common case is
        the standard chunk; exact-tail amounts < chunk appear when a
        whole-chunk evict would cut into the sinks)."""
        if m not in self._evicts:
            from bigdl_tpu.streaming import make_evict

            self._evicts[m] = jax.jit(make_evict(
                self.config, self.max_len, self._sink, m))
        return self._evicts[m]

    def _make_room(self, n: int) -> None:
        if self.pos + n <= self.max_len:
            return
        if self._evict is None:
            raise ValueError(
                f"conversation ({self.pos} + {n} new tokens) exceeds "
                f"max_len={self.max_len}; start the session with "
                "streaming=(sink, window) for unbounded chats"
            )
        if self._sink + n > self.max_len:
            raise ValueError(
                f"a single turn of {n} tokens cannot fit the streaming "
                f"window ({self.max_len}, sink {self._sink})"
            )
        while self.pos + n > self.max_len:
            avail = self.pos - self._sink  # evictable non-sink tokens
            need = self.pos + n - self.max_len
            m = min(self._chunk if need >= self._chunk else need, avail)
            self.cache = self._evict_by(m)(self.cache)

    def _prefill(self, ids: Sequence[int]) -> jax.Array:
        """Append `ids` to the cache; returns the last real token's
        logits [V]. Bucketed right-padding: the padded queries' KV lands
        in slots the causal mask hides and later writes overwrite."""
        n = len(ids)
        # make room for the whole BUCKET so only power-of-two prefill
        # shapes ever compile; fall back to the exact length when the
        # bucket itself cannot fit (window tail / oversized turn)
        b = _bucket(n)
        if self._evict is None:  # bounded session: no eviction possible
            self._make_room(n)
        else:
            self._make_room(b if self._sink + b <= self.max_len else n)
        if self.pos + b > self.max_len:
            b = n
        if b not in self._prefill_jits:
            self._prefill_jits[b] = jax.jit(
                lambda p, t, c: self._forward(
                    self.config, p, t, c, mode="prefill",
                    compute_dtype=self._dtype, last_logits_only=False,
                )
            )
        padded = np.zeros((1, b), np.int32)
        padded[0, :n] = np.asarray(ids, np.int32)
        pos0 = self.pos
        logits, cache = self._prefill_jits[b](
            self.model.params, jnp.asarray(padded), self.cache
        )
        # roll pos back from the bucket end to the last REAL token + 1
        self.cache = dataclasses.replace(
            cache, pos=jnp.asarray(pos0 + n, jnp.int32)
        )
        return logits[0, n - 1]

    def send_stream(
        self,
        ids: Sequence[int],
        max_new_tokens: int = 128,
        eos_token_id: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        seed: int = 0,
    ) -> Iterator[int]:
        """Prefill this turn's new tokens, then yield generated ids one
        by one (greedy when temperature == 0, else sampled). The yielded
        reply tokens enter the cache, so the next send() only needs the
        next user message."""
        from bigdl_tpu.generate import GenerationConfig, sample_token

        if len(ids) == 0:
            raise ValueError("empty turn")
        bad = next((t for t in ids
                    if not 0 <= t < self.config.vocab_size), None)
        if bad is not None:
            raise ValueError(
                f"token id {bad} outside [0, {self.config.vocab_size}) — "
                "wrong tokenizer for this model?"
            )
        gen = GenerationConfig(
            do_sample=temperature > 0, temperature=max(temperature, 1e-5),
            top_k=top_k, top_p=top_p,
        )
        key = jax.random.PRNGKey(seed + self.pos)  # per-turn stream

        def pick(lg):
            nonlocal key
            key, k = jax.random.split(key)
            return int(sample_token(lg[None].astype(jnp.float32), k, gen)[0])

        logits = self._prefill(ids)
        tok = pick(logits)
        for _ in range(max_new_tokens):
            if self._shift is not None:
                self.cache = self._shift(self.cache)
            elif self.pos >= self.max_len:
                raise ValueError(
                    f"conversation exceeds max_len={self.max_len}; use "
                    "streaming=(sink, window) for unbounded chats"
                )
            yield tok
            # the decode step below also COMMITS tok's KV to the cache —
            # it must run even when stopping at EOS, or the next turn's
            # context would silently miss the transcript's final token
            lg, self.cache = self._decode_jit(
                self.model.params, jnp.asarray([[tok]]), self.cache
            )
            if eos_token_id is not None and tok == eos_token_id:
                return
            tok = pick(lg[0, -1])

    def send(
        self,
        ids: Sequence[int],
        max_new_tokens: int = 128,
        eos_token_id: Optional[int] = None,
        **kw,
    ) -> list[int]:
        return list(self.send_stream(ids, max_new_tokens, eos_token_id, **kw))
