"""Shared utilities (reference: `ipex_llm/utils/` — here kept minimal;
logging/error helpers live in bigdl_tpu.utils.common, env flags in
bigdl_tpu.utils.flags)."""

from __future__ import annotations


def round_up(x: int, m: int) -> int:
    """Smallest multiple of m that is >= x."""
    return (x + m - 1) // m * m


# KV caches are sized to quantum multiples so only a few distinct XLA
# programs are ever compiled per model — the TPU-shaped replacement for the
# reference's KV_CACHE_ALLOC_BLOCK_LENGTH growth policy (models/utils.py:39).
# Overridable via BIGDL_TPU_KV_CACHE_QUANTUM (utils/flags.py).
CACHE_SLOT_QUANTUM = 64


def cache_len_for(prompt_len: int, max_new_tokens: int) -> int:
    from bigdl_tpu.utils.flags import cache_slot_quantum

    return round_up(prompt_len + max_new_tokens, cache_slot_quantum())
