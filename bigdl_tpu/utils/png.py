"""Minimal dependency-free PNG writer (the environment has no PIL):
8-bit RGB, zlib-deflated, one IDAT chunk — enough for `bigdl-tpu
txt2img` to save its output."""

from __future__ import annotations

import struct
import zlib

import numpy as np


def _chunk(tag: bytes, data: bytes) -> bytes:
    return (struct.pack(">I", len(data)) + tag + data
            + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF))


def write_png(path: str, image: np.ndarray) -> None:
    """image: [H, W, 3] uint8."""
    img = np.asarray(image)
    if img.ndim != 3 or img.shape[-1] != 3 or img.dtype != np.uint8:
        raise ValueError(f"expected [H, W, 3] uint8, got "
                         f"{img.shape} {img.dtype}")
    h, w = img.shape[:2]
    # each scanline prefixed with filter byte 0 (None)
    raw = b"".join(b"\x00" + img[y].tobytes() for y in range(h))
    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)  # 8-bit RGB

    def _write(f):
        f.write(b"\x89PNG\r\n\x1a\n")
        f.write(_chunk(b"IHDR", ihdr))
        f.write(_chunk(b"IDAT", zlib.compress(raw, 6)))
        f.write(_chunk(b"IEND", b""))

    # atomic commit (utils/durability, graftlint ATW001): a killed
    # txt2img run must not leave a truncated, viewer-rejected PNG
    from bigdl_tpu.utils.durability import atomic_write

    atomic_write(path, _write)
