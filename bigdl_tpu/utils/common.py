"""Logging + error helpers.

Equivalent of the reference's `utils/common/log4Error.py`
(`invalidInputError` / `invalidOperationError` / log4Error) — the
error-reporting idiom used across its codebase — plus a namespaced
logger factory.
"""

from __future__ import annotations

import logging
from typing import Any, NoReturn, Optional


def get_logger(name: str = "bigdl_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(levelname)s: %(message)s")
        )
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
    return logger


class InvalidInputError(ValueError):
    """Caller mistakes (bad request shapes, contradictory options) — the
    serving layer maps this to HTTP 400."""


def invalid_input_error(condition: Any, msg: str, fix: Optional[str] = None) -> None:
    """Raise InvalidInputError with an actionable message unless
    `condition` (reference invalidInputError: logs then raises)."""
    if not condition:
        full = msg if fix is None else f"{msg}. {fix}"
        get_logger().error(full)
        raise InvalidInputError(full)


def invalid_operation_error(condition: Any, msg: str) -> None:
    if not condition:
        get_logger().error(msg)
        raise RuntimeError(msg)


def log_warning_once(msg: str, _seen: set = set()) -> None:  # noqa: B006
    if msg not in _seen:
        _seen.add(msg)
        get_logger().warning(msg)
