"""Environment-flag configuration.

The reference's config surface is kwargs plus ~40 env vars (SURVEY.md §5:
IPEX_LLM_QUANTIZE_KV_CACHE, IPEX_LLM_COMPRESS_KV_CACHE, IPEX_LLM_LOW_MEM,
IPEX_LLM_PERFORMANCE_MODE, IPEX_LLM_LAST_LM_HEAD,
KV_CACHE_ALLOC_BLOCK_LENGTH, BIGDL_LLM_LINEAR_THRESHOLD, ...). The TPU
build keeps the same shape — explicit kwargs win; env flags set defaults —
under the BIGDL_TPU_* namespace. All flags are read lazily so tests can
monkeypatch os.environ.
"""

from __future__ import annotations

import os
from typing import Optional


def _bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "no", "off")


def _int(name: str, default: Optional[int] = None) -> Optional[int]:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    return int(v)


def quantize_kv_default() -> bool:
    """FP8 KV cache (reference IPEX_LLM_QUANTIZE_KV_CACHE)."""
    return _bool("BIGDL_TPU_QUANTIZE_KV_CACHE")


def compress_kv_budget() -> Optional[int]:
    """SnapKV budget in slots; unset disables (reference
    IPEX_LLM_COMPRESS_KV_CACHE enables at a built-in threshold)."""
    if _bool("BIGDL_TPU_COMPRESS_KV_CACHE"):
        return _int("BIGDL_TPU_COMPRESS_KV_BUDGET", 1024)
    return None


def performance_mode() -> bool:
    """Auto prompt-lookup decoding for long prompts (reference
    IPEX_LLM_PERFORMANCE_MODE=1 auto-enables lookahead, lookup.py:63-83)."""
    return _bool("BIGDL_TPU_PERFORMANCE_MODE")


def last_lm_head_default() -> bool:
    """Compute lm-head on the last position only during prefill
    (reference IPEX_LLM_LAST_LM_HEAD / reshape_lm_head_input,
    low_bit_linear.py:262-270). Default ON: generate() never reads
    earlier prefill logits."""
    return _bool("BIGDL_TPU_LAST_LM_HEAD", True)


def cache_slot_quantum() -> int:
    """KV cache size rounding (reference KV_CACHE_ALLOC_BLOCK_LENGTH)."""
    return _int("BIGDL_TPU_KV_CACHE_QUANTUM", 64)


def native_disabled() -> bool:
    return _bool("BIGDL_TPU_DISABLE_NATIVE")


def pallas_disabled() -> bool:
    return _bool("BIGDL_TPU_DISABLE_PALLAS")
