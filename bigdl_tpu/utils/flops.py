"""Model FLOP / byte accounting for MFU and bandwidth-utilization reporting.

The reference's benchmark harness records latency only
(/root/reference/python/llm/src/ipex_llm/utils/benchmark_util_4_29.py:489-519);
BASELINE.md's north star additionally demands >=50% MFU for QLoRA
finetuning, which requires knowing the model FLOPs per token and the
chip's peak. Conventions:

* MFU counts *model* FLOPs (the PaLM convention), not hardware FLOPs —
  rematerialized forwards don't inflate it.
* Decode at batch=1 is HBM-bound, so we also report MBU (memory-bandwidth
  utilization): bytes of weights + KV that must stream per token divided
  by (bandwidth * latency).
"""

from __future__ import annotations

from typing import Optional

# device_kind prefix -> (peak bf16 FLOP/s, HBM bytes/s). Public specs:
# v4 275 TF / 1.2 TB/s, v5e 197 TF / 819 GB/s, v5p 459 TF / 2.8 TB/s,
# v6e (Trillium) 918 TF / 1.6 TB/s.
_CHIPS = {
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
}


def chip_specs(device=None) -> Optional[tuple[float, float]]:
    """(peak_flops, hbm_bytes_per_s) for the given (default: first) device,
    or None when unknown (CPU test runs)."""
    import jax

    if device is None:
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    kind = getattr(device, "device_kind", "") or ""
    for prefix, specs in _CHIPS.items():
        if kind.startswith(prefix):
            return specs
    return None


def matmul_params(config) -> dict:
    """Per-component matmul parameter counts (what streams from HBM and
    what the MXU multiplies). Embedding gather is excluded (one row).

    For MoE configs `active` counts only the top-k routed experts (+ the
    always-on shared expert) — the FLOPs actually spent per token — while
    `total` counts every expert resident in HBM.
    """
    L, H = config.num_hidden_layers, config.hidden_size
    attn = L * (config.q_dim * H + 2 * config.kv_dim * H + H * config.q_dim)
    if config.is_moe:
        I = config.moe_intermediate_size or config.intermediate_size
        expert = 3 * H * I
        mlp_active = L * (config.num_experts_per_tok * expert
                          + config.num_experts * H)  # + router
        mlp_total = L * (config.num_experts * expert + config.num_experts * H)
        shared = config.shared_expert_intermediate_size
        if shared:
            mlp_active += L * (3 * H * shared + H)
            mlp_total += L * (3 * H * shared + H)
    else:
        mlp_active = mlp_total = L * 3 * H * config.intermediate_size
    head = config.vocab_size * H
    return {
        "attn": attn,
        "mlp_active": mlp_active,
        "mlp_total": mlp_total,
        "lm_head": head,
        "active": attn + mlp_active + head,
        "total": attn + mlp_total + head,
    }


def decode_flops_per_token(config, context_len: int = 0, batch: int = 1) -> float:
    """Matmul FLOPs for one decode step per sequence: 2 * active params
    + attention score/value FLOPs against `context_len` cached tokens."""
    p = matmul_params(config)
    attn_ctx = 2 * 2 * config.num_attention_heads * config.head_dim_ * context_len
    return 2 * p["active"] + attn_ctx


def train_flops_per_token(config, full_finetune: bool = False) -> float:
    """QLoRA convention: forward 2P + backward-through-activations 2P; the
    frozen base contributes no weight-gradient matmuls. Full finetune adds
    the 2P weight-gradient term (the standard 6P)."""
    p = matmul_params(config)
    return (6 if full_finetune else 4) * p["active"]


def decode_bytes_per_token(
    config, context_len: int = 0, batch: int = 1,
    weight_bits: float = 4.5, kv_bytes: int = 2,
) -> float:
    """HBM bytes that must stream for one decode step: every weight once
    (shared across the batch) + each sequence's KV read/write.

    weight_bits: effective bits/param incl. scales — sym_int4 with one
    fp16 scale per 32-block is 4 + 16/32 = 4.5.
    """
    p = matmul_params(config)
    weight_bytes = p["total"] * weight_bits / 8
    kv = (config.num_hidden_layers * 2 * config.kv_dim
          * context_len * kv_bytes) * batch
    return weight_bytes + kv


def mfu(flops_per_token: float, tokens_per_s: float, device=None) -> Optional[float]:
    specs = chip_specs(device)
    if specs is None:
        return None
    return flops_per_token * tokens_per_s / specs[0]


def mbu(bytes_per_token: float, tokens_per_s: float, device=None) -> Optional[float]:
    specs = chip_specs(device)
    if specs is None:
        return None
    return bytes_per_token * tokens_per_s / specs[1]
