"""Deterministic storage fault injection for on-disk artifacts.

The disk twin of `serving/faults.py`: every detection and recovery path
in the artifact-durability layer (utils/durability.py, convert/low_bit,
train/checkpoint, convert/gguf_export, serving/journal) runs on CPU
under *injected* storage faults, so the corruption suite is an ordinary
fast pytest module instead of a story about cosmic rays. The injector
shares FaultInjector's arm/disarm/fire discipline — counted, optionally
probabilistic from a seeded RNG, replayable exactly.

Injection points (fired by `durability.atomic_write`):

==============  ===========================================================
point           effect when armed
==============  ===========================================================
``torn_rename``  the save crashes (``DiskFaultError``) after the tmp file
                 is fully written + fsynced but BEFORE the rename — the
                 SIGKILL-mid-save window. The tmp sibling is left on disk
                 (a killed process cleans nothing up); the prior artifact
                 must remain bit-identical and loadable.
``drop_file``    the rename never happens and the tmp is deleted — the
                 artifact silently never appears (lost write / dropped
                 dirent), driving the missing-file detection path.
``bit_flip``     one byte of the committed file is XOR-flipped after the
                 rename (storage rot). payload: ``offset=int`` pins the
                 position; default draws from the injector's seeded RNG.
``truncate``     the committed file is truncated after the rename (torn
                 storage). payload: ``keep=float`` fraction kept
                 (default 0.5) or ``keep_bytes=int``.
==============  ===========================================================

The post-commit corruptions (`bit_flip`/`truncate`) are also exposed as
plain helpers (:func:`flip_byte`, :func:`truncate_file`) so tests can
corrupt existing artifacts — e.g. journal lines — at exact offsets.
"""

from __future__ import annotations

import os
from typing import Optional

from bigdl_tpu.serving.faults import FaultInjector

DISK_POINTS = ("bit_flip", "truncate", "torn_rename", "drop_file")


class DiskFaultError(RuntimeError):
    """Raised by an injected storage crash point (never by real code)."""


class DiskFaultInjector(FaultInjector):
    """Seedable storage-fault hook table (see module docstring)."""

    points = DISK_POINTS


class NullDiskFaultInjector(DiskFaultInjector):
    """Default for every save path: inert, arming forbidden (the shared
    module-level instance must stay a no-op)."""

    def arm(self, *a, **k):  # pragma: no cover - guard rail
        raise RuntimeError(
            "this is the shared no-op disk injector; construct your own "
            "DiskFaultInjector and pass it via faults="
        )

    def fire(self, point: str) -> Optional[dict]:
        return None


NULL_DISK_INJECTOR = NullDiskFaultInjector()


# ---------------------------------------------------------------------------
# corruption primitives (used by the injector AND directly by tests)
# ---------------------------------------------------------------------------

def flip_byte(path: str, offset: Optional[int] = None, *, bit: int = 0,
              rng=None) -> int:
    """XOR-flip one bit of one byte of `path` in place; returns the
    offset actually flipped. offset=None draws uniformly from `rng`
    (random.Random) — pass a seeded one for replayable corruption."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path}: empty file, nothing to flip")
    if offset is None:
        if rng is None:
            raise ValueError("flip_byte needs offset= or a seeded rng=")
        offset = rng.randrange(size)
    if not 0 <= offset < size:
        raise ValueError(f"offset {offset} outside file of {size} bytes")
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)[0]
        f.seek(offset)
        f.write(bytes([b ^ (1 << (bit & 7))]))
    return offset


def truncate_file(path: str, keep: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
    """Truncate `path` in place to `keep_bytes` (or a `keep` fraction of
    its current size); returns the new size."""
    size = os.path.getsize(path)
    new = keep_bytes if keep_bytes is not None else int(size * keep)
    new = max(0, min(new, size))
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def apply_post_commit(path: str, inj: DiskFaultInjector) -> None:
    """Fire the storage-rot points (`bit_flip`, `truncate`) against a
    just-committed file. Called by durability.atomic_write after the
    rename; corruption after the commit point models media decay, which
    the *load*-side verification must catch."""
    p = inj.fire("bit_flip")
    if p is not None:
        flip_byte(path, p.get("offset"), bit=p.get("bit", 0), rng=inj._rng)
    p = inj.fire("truncate")
    if p is not None:
        truncate_file(path, keep=p.get("keep", 0.5),
                      keep_bytes=p.get("keep_bytes"))
