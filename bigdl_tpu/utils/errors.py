"""Error contract + structured logging.

Counterpart of the reference's `invalidInputError` helper
(utils/common/log4Error.py in /root/reference): user-facing entry points
raise a typed, logged error instead of letting raw assertion tracebacks
surface through the HTTP layer, and log lines are structured (single-line
key=value) so serving logs stay grep/ingest-friendly.

The error class and assert-style guard live in utils/common.py (the
original home); this module adds the structured-event and request-timing
pieces and re-exports the contract for one import site.
"""

from __future__ import annotations

import time
from typing import Any

from bigdl_tpu.utils.common import (  # noqa: F401  (re-exports)
    InvalidInputError,
    get_logger,
    invalid_input_error,
)
from bigdl_tpu.utils.durability import IntegrityError  # noqa: F401  (re-export)


def log_event(event: str, **fields: Any) -> None:
    """One structured line: `event key=value ...` at INFO."""
    parts = [event]
    for k, v in fields.items():
        if isinstance(v, float):
            v = f"{v:.4f}"
        parts.append(f"{k}={v}")
    get_logger().info(" ".join(parts))


class request_timer:
    """Context manager stamping wall-clock duration into log_event +
    a metrics histogram."""

    def __init__(self, metrics, endpoint: str):
        self.metrics = metrics
        self.endpoint = endpoint
        self.status = 200

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self.t0
        status = 500 if exc_type is not None else self.status
        if self.metrics is not None:
            self.metrics.observe_request(self.endpoint, status, dt)
        log_event(
            "http_request", endpoint=self.endpoint, status=status,
            seconds=dt,
        )
        return False
