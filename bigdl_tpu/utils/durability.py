"""Artifact durability: integrity manifests, atomic writes, numerical
validation, and the structured IntegrityError shared by every on-disk
artifact (low-bit checkpoints, train checkpoints, GGUF exports).

Low-bit checkpoints are the silent-scramble failure class in person: a
flipped byte in packed codes or scales doesn't crash, it *dequantizes
garbage* (the exact hazard convert/low_bit.py's FORMAT_VERSION gate
documents for layout drift — bit rot produces it without any version
change). So durability is layered:

1. **Integrity manifest** — per-tensor content digests (crc32 fast path,
   sha256 full mode), byte sizes, shapes and storage dtypes recorded at
   save time; load verifies in modes ``off | fast | full`` and raises a
   structured :class:`IntegrityError` naming every corrupted / missing /
   extra tensor instead of KeyError-ing deep in the loader.
2. **Atomic write protocol** — :func:`atomic_write`: write a
   ``tmp-<pid>`` sibling, flush + fsync, ``os.replace`` into place,
   fsync the directory, and sweep stale tmps from earlier killed saves.
   A kill at any instant leaves the previous artifact bit-identical.
3. **Numerical validation** — NaN/inf scan of float tensors and scales
   plus per-qtype scale-range sanity (:func:`validate_numerics`),
   producing a quarantine report; loaders offer a salvage mode that
   loads the valid subset.
4. **Fault injection** — every save path threads a
   `utils/diskfaults.DiskFaultInjector` through :func:`atomic_write`,
   so tests drive all of the above deterministically on CPU.

`VERIFY_FAILURES` counts every integrity-verification failure process-
wide; serving/metrics.py exports it as
``bigdl_tpu_checkpoint_verify_failures_total``.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import io
import os
import threading
import zipfile
import zlib
from typing import Callable, Optional

import numpy as np

VERIFY_MODES = ("off", "fast", "full")


def check_verify_mode(mode: str) -> str:
    if mode not in VERIFY_MODES:
        raise ValueError(
            f"verify mode {mode!r} not in {VERIFY_MODES}"
        )
    return mode


class _Counter:
    """Process-wide thread-safe counter (metrics exposition)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


# every IntegrityError raised (or salvaged past) by a loader bumps this
VERIFY_FAILURES = _Counter()


class IntegrityError(ValueError):
    """A checkpoint failed integrity verification. Structured: names
    every offending tensor so the operator (and the salvage path) can
    act per-tensor instead of guessing from a KeyError traceback.

    - ``corrupted``: {tensor_name: reason} — digest/shape/size mismatch,
      unreadable member, or a numerics finding (full mode)
    - ``missing``: tensors the manifest lists but the file lacks
    - ``extra``: arrays present in the file but absent from the manifest
    - ``detail``: artifact-level problem (file gone, unreadable zip, …)

    Subclasses ValueError so pre-existing ``except ValueError`` load
    guards keep working.
    """

    def __init__(self, path: str, *, corrupted: Optional[dict] = None,
                 missing=(), extra=(), detail: Optional[str] = None):
        self.path = path
        self.corrupted = dict(corrupted or {})
        self.missing = sorted(missing)
        self.extra = sorted(extra)
        self.detail = detail
        parts = []
        if detail:
            parts.append(detail)
        if self.corrupted:
            parts.append("corrupted: " + "; ".join(
                f"{k} ({v})" for k, v in sorted(self.corrupted.items())
            ))
        if self.missing:
            parts.append(f"missing: {', '.join(self.missing)}")
        if self.extra:
            parts.append(f"extra: {', '.join(self.extra)}")
        super().__init__(
            f"{path}: integrity check failed — " + " | ".join(parts)
        )

    @property
    def bad_tensors(self) -> set:
        return set(self.corrupted) | set(self.missing)


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------

def crc32_hex(data) -> str:
    return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def add_npz_member(zf: "zipfile.ZipFile", key: str, a) -> dict:
    """Serialize one array into an open (uncompressed) npz zip and
    return its integrity entry — digests and zip member share ONE
    serialization pass (the .npy bytes are encoded exactly once).

    Digests cover the serialized .npy MEMBER bytes — exactly what the
    zip stores — not the raw array bytes. That choice makes `fast`
    verification nearly free at load: the zip central directory already
    records each member's crc32, so a metadata-only compare against the
    manifest plus the zip layer's own payload-crc check during the
    (unavoidable) read proves payload == manifest transitively, with
    zero extra bandwidth."""
    b = np.asanyarray(a)
    buf = io.BytesIO()
    np.lib.format.write_array(buf, b, allow_pickle=False)
    raw = buf.getvalue()
    zf.writestr(key + ".npy", raw)
    return {
        "crc32": crc32_hex(raw),
        "sha256": hashlib.sha256(raw).hexdigest(),
        "nbytes": len(raw),
        "shape": list(b.shape),
        "dtype": b.dtype.name,
    }


def write_npz(f, arrays: dict) -> dict:
    """Write `arrays` as an uncompressed .npz (np.load-compatible) to
    the open file object `f`, returning the integrity `tensors` map.
    One tensor is serialized, digested, written, and dropped at a time —
    peak extra memory is one member's bytes, and nothing is serialized
    twice (np.savez + separate digesting would double the encode cost)."""
    tensors = {}
    with zipfile.ZipFile(f, "w", zipfile.ZIP_STORED) as zf:
        for k in sorted(arrays):
            tensors[k] = add_npz_member(zf, k, arrays[k])
    return tensors


def integrity_section(tensors: dict) -> dict:
    """The `integrity` section saved into a checkpoint's metadata."""
    return {
        "version": 1,
        "scheme": "npy-member",  # digests cover the .npy member bytes
        "tensors": tensors,
    }


def verify_npz_members(
    path: str,
    integrity: Optional[dict],
    mode: str,
    expected,
    ignore=frozenset(),
):
    """Read + verify every expected member of an .npz. Returns
    (arrays, corrupted, missing, extra); raises IntegrityError only for
    artifact-level failures (file unreadable as a zip archive).

    Detection layers by mode:
    - every mode: structural (missing/extra members) and the zip layer's
      own payload-vs-member-crc check, which fires during the read —
      even ``off`` cannot hand silently-rotted bytes onward;
    - ``fast``: + zip-directory crc32/size vs the manifest (metadata
      compare, no extra payload pass) and shape/dtype of the decoded
      array;
    - ``full``: + an independent sha256 over the member bytes (distrusts
      the zip metadata entirely).

    `integrity` is the saved `{name: digest_entry}` map (None for
    pre-durability checkpoints: digest checks skip); `ignore` names
    members exempt from expected/extra accounting (e.g. the train
    checkpoint's self-describing "meta").
    """
    expected = set(expected)
    try:
        zf = zipfile.ZipFile(path)
    except Exception as e:
        VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail=f"unreadable archive: {type(e).__name__}: {e}",
        ) from e
    corrupted: dict = {}
    arrays: dict = {}
    with zf:
        infos = {}
        for i in zf.infolist():
            nm = i.filename
            if nm.endswith(".npy"):
                nm = nm[:-4]
            infos[nm] = i
        missing = sorted(expected - infos.keys())
        extra = sorted(infos.keys() - expected - set(ignore))
        for key in sorted(expected & infos.keys()):
            info = infos[key]
            entry = integrity.get(key) if integrity else None
            if mode != "off" and integrity is not None:
                if entry is None:
                    corrupted[key] = "not in integrity manifest"
                    continue
                if info.file_size != entry["nbytes"]:
                    corrupted[key] = (
                        f"{info.file_size} bytes != recorded "
                        f"{entry['nbytes']}"
                    )
                    continue
                if f"{info.CRC & 0xFFFFFFFF:08x}" != entry["crc32"]:
                    corrupted[key] = "crc32 mismatch (zip directory vs " \
                                     "manifest)"
                    continue
            try:
                # zipfile verifies the payload against the member crc
                # during this read — a flipped payload byte fails here
                raw = zf.read(info)
            except Exception as e:
                corrupted[key] = f"unreadable ({type(e).__name__}: {e})"
                continue
            if mode == "full" and entry is not None:
                if hashlib.sha256(raw).hexdigest() != entry["sha256"]:
                    corrupted[key] = "sha256 mismatch"
                    continue
            try:
                a = np.lib.format.read_array(
                    io.BytesIO(raw), allow_pickle=False,
                )
            except Exception as e:
                corrupted[key] = f"undecodable npy ({type(e).__name__}: {e})"
                continue
            if mode != "off" and entry is not None:
                if list(a.shape) != list(entry["shape"]):
                    corrupted[key] = (
                        f"shape {list(a.shape)} != recorded {entry['shape']}"
                    )
                    continue
                if a.dtype.name != entry["dtype"]:
                    corrupted[key] = (
                        f"dtype {a.dtype.name} != recorded {entry['dtype']}"
                    )
                    continue
            arrays[key] = a
    return arrays, corrupted, missing, extra


# ---------------------------------------------------------------------------
# numerical validation (quarantine report)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Finding:
    tensor: str
    issue: str  # "non_finite" | "scale_range"
    detail: str


# storage dtypes worth a non-finite scan (manifest `dtype` names).
# uint views of bf16/fp8 decode through low_bit._decode first.
FLOAT_DTYPES = (
    "float16", "float32", "float64", "bfloat16",
    "float8_e4m3fn", "float8_e5m2",
)

# per-qtype plausibility ceiling for |scale|: block scales derive from
# weight absmax over qmax (quant/numerics.quantize_blockwise), so for
# the formats WE quantize a magnitude in the tens of thousands means
# the fp16 bytes were scrambled, not that the model is big — trained
# transformer weights sit orders of magnitude below 1e4. Unlisted
# qtypes (gguf-imported trees with foreign scale conventions, future
# formats) get a conservative default instead of a false positive.
_SCALE_MAX_DEFAULT = 1e6
_SCALE_MAX = {q: 1e4 for q in (
    "sym_int4", "asym_int4", "sym_int5", "asym_int5", "sym_int8",
    "nf4", "nf3", "fp4", "fp6", "fp8_e4m3", "fp8_e5m2",
    "q2_k", "q3_k", "q4_k", "q5_k", "q6_k",
)}


def scale_bound(qtype: Optional[str]) -> float:
    return _SCALE_MAX.get(qtype, _SCALE_MAX_DEFAULT)


def _stored_to_f32(a: np.ndarray, dtype_name: str) -> np.ndarray:
    """Stored array -> float32, entirely on the numpy side (ml_dtypes
    handles the bf16/fp8 integer views) — the validation scans must not
    round-trip every tensor through jnp device transfers the real load
    is about to pay anyway."""
    if a.dtype.kind in "ui" and dtype_name not in (
        "float16", "float32", "float64",
    ):
        import ml_dtypes

        dt = {
            "bfloat16": ml_dtypes.bfloat16,
            "float8_e4m3fn": ml_dtypes.float8_e4m3fn,
            "float8_e5m2": ml_dtypes.float8_e5m2,
        }[dtype_name]
        a = a.view(dt)
    return a.astype(np.float32)


def scan_non_finite(a: np.ndarray, dtype_name: str) -> Optional[str]:
    """NaN/inf scan of one stored array (bf16/fp8 integer views counted
    correctly). Returns a detail string like '3 NaN / 0 inf of 4096
    values', or None when clean or the dtype is not a float storage
    dtype."""
    if dtype_name not in FLOAT_DTYPES:
        return None
    x = _stored_to_f32(a, dtype_name)
    n_nan = int(np.isnan(x).sum())
    n_inf = int(np.isinf(x).sum())
    if n_nan or n_inf:
        return f"{n_nan} NaN / {n_inf} inf of {x.size} values"
    return None


def validate_numerics(arrays: dict, manifest: dict) -> list:
    """NaN/inf scan of float tensors (dense leaves, scales, mins) plus
    scale-range sanity per qtype. `manifest` is the low-bit manifest
    (path -> {kind, dtype[, qtype]}); `arrays` the stored np arrays
    keyed the same way. Returns a list of Findings (empty = healthy)."""
    findings: list[Finding] = []
    for key in sorted(arrays):
        info = manifest.get(key)
        if info is None or info.get("kind") != "array":
            continue
        dt = info["dtype"]
        if dt not in FLOAT_DTYPES:
            continue
        detail = scan_non_finite(arrays[key], dt)
        if detail is not None:
            findings.append(Finding(key, "non_finite", detail))
            continue
        if key.endswith("@scales"):
            parent = key[: -len("@scales")]
            qtype = (manifest.get(parent) or {}).get("qtype")
            x = _stored_to_f32(arrays[key], dt)
            amax = float(np.abs(x).max()) if x.size else 0.0
            bound = scale_bound(qtype)
            if amax > bound:
                findings.append(Finding(
                    key, "scale_range",
                    f"|scale| max {amax:.3g} exceeds {bound:.0e} "
                    f"for qtype {qtype}",
                ))
    return findings


# ---------------------------------------------------------------------------
# atomic write protocol
# ---------------------------------------------------------------------------

def clean_stale_tmps(path: str) -> list:
    """Remove `path`.tmp-* siblings left by earlier killed saves. Called
    before each save: two live writers racing one target path is already
    undefined, so any surviving tmp is garbage by construction."""
    removed = []
    for tmp in glob.glob(glob.escape(path) + ".tmp-*"):
        try:
            os.unlink(tmp)
            removed.append(tmp)
        except OSError:  # pragma: no cover - racing cleanup is fine
            pass
    return removed


def _fsync_dir(path: str) -> None:
    """fsync the containing directory so the rename itself is durable
    (POSIX: a crashed machine may otherwise forget the dirent)."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:  # pragma: no cover - e.g. exotic filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, writer: Callable, *, faults=None) -> None:
    """Crash-safe file replacement: `writer(f)` streams the payload into
    a ``tmp-<pid>`` sibling, which is flushed, fsynced, and renamed over
    `path`. A kill at ANY instant leaves either the old file (possibly
    plus a stale tmp the next save sweeps) or the complete new file —
    never a torn or missing artifact.

    `faults` (utils/diskfaults.DiskFaultInjector) drives the injected
    failure modes: ``torn_rename`` raises DiskFaultError pre-rename with
    the tmp left behind (simulated SIGKILL — deliberately NOT cleaned
    up), ``drop_file`` discards the write, ``bit_flip``/``truncate``
    corrupt the committed file post-rename (storage rot).
    """
    from bigdl_tpu.utils.diskfaults import (
        NULL_DISK_INJECTOR, DiskFaultError, apply_post_commit,
    )

    inj = faults if faults is not None else NULL_DISK_INJECTOR
    clean_stale_tmps(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        if inj.fire("torn_rename") is not None:
            # simulated kill between fsync and rename: the tmp stays on
            # disk exactly as a real SIGKILL would leave it
            raise DiskFaultError(f"torn_rename injected before {path}")
        if inj.fire("drop_file") is not None:
            os.unlink(tmp)
            return
        os.replace(tmp, path)
    except DiskFaultError:
        raise
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _fsync_dir(path)
    apply_post_commit(path, inj)


# ---------------------------------------------------------------------------
# per-tensor verification report (CLI `bigdl-tpu verify`)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TensorReport:
    name: str
    status: str  # "ok" | "corrupt" | "missing" | "extra" | "numerics"
    detail: str = ""


@dataclasses.dataclass
class VerifyReport:
    path: str
    kind: str  # "low_bit" | "train"
    rows: list
    detail: Optional[str] = None  # artifact-level failure

    @property
    def ok(self) -> bool:
        return self.detail is None and all(
            r.status == "ok" for r in self.rows
        )

    def format(self) -> str:
        lines = [f"{self.path} [{self.kind}]"]
        if self.detail:
            lines.append(f"  ARTIFACT {self.detail}")
        width = max((len(r.name) for r in self.rows), default=0)
        n_bad = 0
        for r in sorted(self.rows, key=lambda r: (r.status == "ok", r.name)):
            if r.status == "ok":
                continue
            n_bad += 1
            lines.append(
                f"  {r.status.upper():8s} {r.name:<{width}s}  {r.detail}"
            )
        lines.append(
            f"  {len(self.rows) - n_bad}/{len(self.rows)} tensors ok"
            + ("" if self.ok else f", {n_bad} findings")
        )
        return "\n".join(lines)


def rows_from_error(err: IntegrityError) -> list:
    rows = [TensorReport(k, "corrupt", v) for k, v in err.corrupted.items()]
    rows += [TensorReport(k, "missing", "listed in manifest, absent "
                          "from file") for k in err.missing]
    rows += [TensorReport(k, "extra", "present in file, absent from "
                          "manifest") for k in err.extra]
    return rows
