"""Latency instrumentation.

Role-equivalent of the reference's `BenchmarkWrapper` — six pinned forks
of HF `generate` instrumented to record `first_cost` / `rest_cost_mean` /
peak memory (utils/benchmark_util_4_29.py:489-519,2467-2476 + version
dispatch utils/__init__.py:23-36 in /root/reference). Here no fork is
needed: prefill and decode are separate jitted programs, so the wrapper
times them directly and the numbers mean exactly what they claim.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache
from bigdl_tpu.generate import GenerationConfig, pad_prompts, sample_token
from bigdl_tpu.utils import cache_len_for


@dataclasses.dataclass
class BenchResult:
    first_cost_ms: float  # prefill (1st token) latency
    rest_cost_mean_ms: float  # mean 2+ token latency
    rest_cost_p90_ms: float
    tokens_per_s: float
    peak_memory_bytes: Optional[int]  # device peak (None off-TPU)
    prompt_len: int
    new_tokens: int

    def row(self) -> dict:
        return dataclasses.asdict(self)


class BenchmarkedModel:
    """Wraps a TpuModel: same generate() surface, but timed step by step
    (the reference's `model = BenchmarkWrapper(model)` pattern)."""

    def __init__(self, model):
        self.model = model
        self.results: list[BenchResult] = []

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int = 32,
        **gen_kw,
    ) -> np.ndarray:
        model = self.model
        config, params = model.config, model.params
        gen = GenerationConfig(max_new_tokens=max_new_tokens, **gen_kw)
        tokens_np, start = pad_prompts(list(prompts), gen.pad_token_id)
        B, T = tokens_np.shape
        cache_len = cache_len_for(T, max_new_tokens)

        fwd = getattr(model, "forward_fn", None) or model.family.forward

        def prefill(params, tokens, cache):
            return fwd(config, params, tokens, cache, mode="prefill")

        def decode(params, cur, cache):
            return fwd(config, params, cur, cache, mode="decode")

        prefill_j = jax.jit(prefill, donate_argnames=("cache",))
        decode_j = jax.jit(decode, donate_argnames=("cache",))

        def fresh_cache():
            c = kvcache.init_cache(
                config.num_hidden_layers, B, cache_len,
                config.num_key_value_heads, config.head_dim_,
            )
            return dataclasses.replace(c, start=jnp.asarray(start))

        # compile outside the timed region (the reference's wrapper also
        # reports post-warmup numbers)
        logits, cache = prefill_j(params, jnp.asarray(tokens_np), fresh_cache())
        logits.block_until_ready()

        key = jax.random.PRNGKey(0)
        t0 = time.perf_counter()
        logits, cache = prefill_j(params, jnp.asarray(tokens_np), fresh_cache())
        cur = sample_token(logits[:, -1], key, gen)
        cur.block_until_ready()
        first_ms = (time.perf_counter() - t0) * 1000

        out = [np.asarray(cur)]
        rest: list[float] = []
        for _ in range(max_new_tokens - 1):
            key, k = jax.random.split(key)
            t0 = time.perf_counter()
            logits, cache = decode_j(params, cur[:, None], cache)
            cur = sample_token(logits[:, -1], k, gen)
            cur.block_until_ready()
            rest.append((time.perf_counter() - t0) * 1000)
            out.append(np.asarray(cur))

        mem = None
        try:
            stats = jax.local_devices()[0].memory_stats()
            if stats:
                mem = stats.get("peak_bytes_in_use")
        except Exception:
            pass

        rest_arr = np.asarray(rest) if rest else np.asarray([first_ms])
        total_s = (first_ms + rest_arr.sum()) / 1000
        self.results.append(
            BenchResult(
                first_cost_ms=round(first_ms, 3),
                rest_cost_mean_ms=round(float(rest_arr.mean()), 3),
                rest_cost_p90_ms=round(float(np.percentile(rest_arr, 90)), 3),
                tokens_per_s=round(B * max_new_tokens / total_s, 2),
                peak_memory_bytes=mem,
                prompt_len=T,
                new_tokens=max_new_tokens,
            )
        )
        return np.stack(out, axis=1)

    @property
    def last(self) -> BenchResult:
        return self.results[-1]
