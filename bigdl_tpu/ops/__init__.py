"""Core compute ops.

TPU-native equivalents of the reference's fused native ops surface
(`xe_linear.forward_new` / `xe_batch.batch_forward` / `xe_addons.{sdp*,
rms_norm, rotary_*}`, see SURVEY.md §2.1): each op is a jnp function that
XLA fuses into the surrounding jit graph. Pallas kernel fast paths for
the hot ops (quantized matmul, flash attention) are planned under
bigdl_tpu/ops/ and will dispatch by backend once present.
"""

from bigdl_tpu.ops.linear import linear, lora_epilogue
from bigdl_tpu.ops.norms import rms_norm, layer_norm
from bigdl_tpu.ops.rope import apply_rotary_emb, rope_cos_sin
from bigdl_tpu.ops.attention import attention

__all__ = [
    "linear",
    "lora_epilogue",
    "rms_norm",
    "layer_norm",
    "apply_rotary_emb",
    "rope_cos_sin",
    "attention",
]
