"""Rotary position embeddings.

Equivalent of the reference's fused `xe_addons.rotary_half_inplaced` /
`rotary_two_inplaced` kernels (models/llama.py:154-167 and ~30 other call
sites). "half" is the HF-LLaMA rotate-half convention (contiguous halves),
"two" is the GPT-NeoX/GLM interleaved-pairs convention; both are provided
(`interleaved=True`). Partial rotary (stablelm/phi/glm) rotates only the
leading `rotary_dim` lanes of each head.

Supports the HF `rope_scaling` schemes used by the reference model zoo:
linear, dynamic-NTK, llama3 frequency smoothing, yarn, and
longrope/su (phi3).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def get_mscale(scale: float, m: float = 1.0) -> float:
    """HF yarn_get_mscale: the yarn attention temperature. Used both for
    the rope-level attention_factor (as a ratio) and, squared, for the
    MLA softmax scale (models.deepseek.mla_softmax_scale)."""
    if scale <= 1.0 or m == 0:
        return 1.0
    return 0.1 * m * math.log(scale) + 1.0


def default_inv_freq(head_dim: int, theta: float) -> jax.Array:
    return 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )


def llama3_scaled_inv_freq(
    inv_freq: jax.Array,
    factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position: int = 8192,
) -> jax.Array:
    """Llama-3.1 rope scaling: smooth interpolation between scaled and
    unscaled frequencies (HF modeling_rope_utils _compute_llama3_parameters)."""
    low_freq_wavelen = original_max_position / low_freq_factor
    high_freq_wavelen = original_max_position / high_freq_factor
    wavelen = 2 * math.pi / inv_freq
    scaled = inv_freq / factor
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    smoothed = (1 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_freq_wavelen, scaled, inv_freq)
    mid = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return jnp.where(mid, smoothed, out)


def yarn_scaled_inv_freq(
    inv_freq: jax.Array,
    head_dim: int,
    theta: float,
    factor: float = 1.0,
    beta_fast: float = 32.0,
    beta_slow: float = 1.0,
    original_max_position: int = 4096,
    attention_factor: Optional[float] = None,
    mscale: Optional[float] = None,
    mscale_all_dim: Optional[float] = None,
) -> tuple[jax.Array, float]:
    """YaRN (deepseek/qwen long-context): NTK-by-parts interpolation plus an
    attention temperature (returned as mscale; multiply cos/sin by it).

    The temperature follows HF _compute_yarn_parameters exactly:
    explicit `attention_factor` wins; else deepseek-style
    mscale/mscale_all_dim give get_mscale(f, m)/get_mscale(f, m_all);
    else the standard 0.1*ln(f)+1. DeepSeek checkpoints ship
    mscale == mscale_all_dim, so their ratio (applied to cos/sin) is
    1.0 — HF splits the yarn temperature between this rope-level
    attention_factor and the attention module's own mscale^2 softmax
    scaling (DeepseekV3Attention); both are needed for parity. The
    mscale^2 half lives in models.deepseek.mla_softmax_scale."""

    def find_dim(num_rot):
        return (
            head_dim
            * math.log(original_max_position / (num_rot * 2 * math.pi))
        ) / (2 * math.log(theta))

    low = max(math.floor(find_dim(beta_fast)), 0)
    high = min(math.ceil(find_dim(beta_slow)), head_dim // 2 - 1)
    ramp = jnp.clip(
        (jnp.arange(head_dim // 2, dtype=jnp.float32) - low) / max(high - low, 1),
        0.0,
        1.0,
    )
    interp = inv_freq / factor  # fully interpolated (long range)
    inv = interp * ramp + inv_freq * (1 - ramp)

    if attention_factor is not None:
        att = float(attention_factor)
    elif mscale and mscale_all_dim:  # BOTH truthy — HF's exact condition
        att = get_mscale(factor, mscale) / get_mscale(factor, mscale_all_dim)
    else:
        att = get_mscale(factor)
    return inv, att


def make_inv_freq(
    head_dim: int, theta: float, rope_scaling: Optional[dict]
) -> jax.Array:
    inv, _ = make_inv_freq_scaled(head_dim, theta, rope_scaling, seq_len=None)
    return inv


def make_inv_freq_scaled(
    head_dim: int,
    theta: float,
    rope_scaling: Optional[dict],
    seq_len: Optional[int] = None,
) -> tuple[jax.Array, float]:
    """Returns (inv_freq [head_dim//2], attention_scale) where cos/sin must be
    multiplied by attention_scale (yarn mscale / longrope factor)."""
    inv_freq = default_inv_freq(head_dim, theta)
    if not rope_scaling:
        return inv_freq, 1.0
    rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
    if rope_type in ("default", None):
        return inv_freq, 1.0
    if rope_type == "linear":
        return inv_freq / rope_scaling.get("factor", 1.0), 1.0
    if rope_type == "dynamic":
        # dynamic NTK: theta grows with the in-use seq len; at trace time we
        # pin to the configured max (the conservative long-context setting).
        factor = rope_scaling.get("factor", 1.0)
        orig = rope_scaling.get("original_max_position_embeddings") or rope_scaling.get(
            "max_position_embeddings", 4096
        )
        use_len = seq_len or int(orig * factor)
        if use_len > orig:
            adj = theta * (
                (factor * use_len / orig) - (factor - 1)
            ) ** (head_dim / (head_dim - 2))
            return default_inv_freq(head_dim, adj), 1.0
        return inv_freq, 1.0
    if rope_type == "llama3":
        return (
            llama3_scaled_inv_freq(
                inv_freq,
                factor=rope_scaling.get("factor", 8.0),
                low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
                high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
                original_max_position=rope_scaling.get(
                    "original_max_position_embeddings", 8192
                ),
            ),
            1.0,
        )
    if rope_type == "yarn":
        return yarn_scaled_inv_freq(
            inv_freq,
            head_dim,
            theta,
            factor=rope_scaling.get("factor", 1.0),
            beta_fast=rope_scaling.get("beta_fast", 32.0),
            beta_slow=rope_scaling.get("beta_slow", 1.0),
            original_max_position=rope_scaling.get(
                "original_max_position_embeddings", 4096
            ),
            attention_factor=rope_scaling.get("attention_factor"),
            mscale=rope_scaling.get("mscale"),
            mscale_all_dim=rope_scaling.get("mscale_all_dim"),
        )
    if rope_type in ("longrope", "su"):
        # phi3 long/short per-frequency factors
        # (HF _compute_longrope_parameters)
        orig = rope_scaling.get("original_max_position_embeddings", 4096)
        maxp = rope_scaling.get("max_position_embeddings", orig)
        long_ctx = (seq_len or maxp) > orig
        key = "long_factor" if long_ctx else "short_factor"
        ext = jnp.asarray(rope_scaling[key], jnp.float32)
        scale = maxp / orig
        if scale <= 1.0:
            att = 1.0
        else:
            att = math.sqrt(1 + math.log(scale) / math.log(orig))
        return inv_freq / ext, att
    raise NotImplementedError(f"rope_scaling type {rope_type!r}")


def rope_cos_sin(
    positions: jax.Array,
    inv_freq: jax.Array,
    dtype=jnp.float32,
    interleaved: bool = False,
    scale: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """positions [..., T] int -> cos/sin [..., T, rotary_dim].

    Layout matches the convention `apply_rotary_emb` consumes: halves
    duplicated (HF) or pairs repeated (interleaved/neox)."""
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, R/2]
    if interleaved:
        angles = jnp.repeat(angles, 2, axis=-1)
    else:
        angles = jnp.concatenate([angles, angles], axis=-1)
    return (
        (jnp.cos(angles) * scale).astype(dtype),
        (jnp.sin(angles) * scale).astype(dtype),
    )


def mrope_cos_sin(
    position_grid: jax.Array,  # [3, B, T] int: (t, h, w) components
    inv_freq: jax.Array,  # [R/2]
    sections,  # e.g. (16, 24, 24); sum == R/2
    dtype=jnp.float32,
    scale: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Qwen2-VL multimodal rope (M-RoPE): the frequency channels are split
    into (t, h, w) sections, each rotated by its own position component
    (HF apply_multimodal_rotary_pos_emb). When all three components are
    equal this reduces exactly to rope_cos_sin. Half-duplicated (llama)
    layout."""
    angles = position_grid.astype(jnp.float32)[..., None] * inv_freq  # [3,B,T,R/2]
    parts = []
    off = 0
    for i, sec in enumerate(sections):
        parts.append(angles[i, ..., off:off + sec])
        off += sec
    half = jnp.concatenate(parts, axis=-1)  # [B, T, R/2]
    full = jnp.concatenate([half, half], axis=-1)
    return (
        (jnp.cos(full) * scale).astype(dtype),
        (jnp.sin(full) * scale).astype(dtype),
    )


def _rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _rotate_pairs(x: jax.Array) -> jax.Array:
    """Even/odd pair rotation — HF modeling_glm redefines rotate_half this
    way (x[0::2]/x[1::2] stacked), unlike the llama contiguous-halves
    convention."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)


def _apply_one(x, cos, sin, interleaved):
    rot = _rotate_pairs(x) if interleaved else _rotate_half(x)
    return x * cos + rot * sin


def apply_rotary_emb(
    q: jax.Array,
    k: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    interleaved: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """q [B,T,Hq,D], k [B,T,Hk,D], cos/sin [B,T,R] with R <= D -> rotated.

    R < D is partial rotary (stablelm/phi/glm): only the first R lanes of
    each head rotate. interleaved=True is the GLM/ChatGLM convention:
    angles repeated pairwise (`rope_cos_sin(interleaved=True)`) and lanes
    rotated as even/odd pairs. Computed in fp32 and cast back (the
    reference kernel also computes the rotation at full precision
    in-register).
    """
    R = cos.shape[-1]
    D = q.shape[-1]
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
    if R < D:
        q_rot = _apply_one(qf[..., :R], cos, sin, interleaved)
        k_rot = _apply_one(kf[..., :R], cos, sin, interleaved)
        q_out = jnp.concatenate([q_rot, qf[..., R:]], axis=-1)
        k_out = jnp.concatenate([k_rot, kf[..., R:]], axis=-1)
    else:
        q_out = _apply_one(qf, cos, sin, interleaved)
        k_out = _apply_one(kf, cos, sin, interleaved)
    return q_out.astype(q.dtype), k_out.astype(k.dtype)


def alibi_slopes(num_heads: int) -> jax.Array:
    """ALiBi per-head slopes (baichuan-13b/bloom; reference
    models/baichuan.py `baichuan_13b_get_alibi_mask`). Standard construction:
    powers of 2^(-8/n) for the nearest power-of-two head count, interpolated
    for the rest."""
    import numpy as np

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    n = 2 ** math.floor(math.log2(num_heads))
    slopes = pow2_slopes(n)
    if n < num_heads:
        extra = pow2_slopes(2 * n)
        slopes += extra[0::2][: num_heads - n]
    return jnp.asarray(np.asarray(slopes, np.float32))
