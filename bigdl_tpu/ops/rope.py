"""Rotary position embeddings.

Equivalent of the reference's fused `xe_addons.rotary_half_inplaced` /
`rotary_two_inplaced` kernels (models/llama.py:154-167 and ~30 other call
sites). "half" is the HF-LLaMA rotate-half convention (contiguous halves),
"two" is the GPT-NeoX interleaved-pairs convention; both are provided.

Supports the HF `rope_scaling` schemes used by the reference model zoo:
linear, dynamic-NTK, and llama3 frequency smoothing.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def default_inv_freq(head_dim: int, theta: float) -> jax.Array:
    return 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )


def llama3_scaled_inv_freq(
    inv_freq: jax.Array,
    factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position: int = 8192,
) -> jax.Array:
    """Llama-3.1 rope scaling: smooth interpolation between scaled and
    unscaled frequencies (HF modeling_rope_utils _compute_llama3_parameters)."""
    low_freq_wavelen = original_max_position / low_freq_factor
    high_freq_wavelen = original_max_position / high_freq_factor
    wavelen = 2 * math.pi / inv_freq
    scaled = inv_freq / factor
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    smoothed = (1 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_freq_wavelen, scaled, inv_freq)
    mid = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return jnp.where(mid, smoothed, out)


def make_inv_freq(head_dim: int, theta: float, rope_scaling: Optional[dict]) -> jax.Array:
    inv_freq = default_inv_freq(head_dim, theta)
    if not rope_scaling:
        return inv_freq
    rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
    if rope_type in ("default", None):
        return inv_freq
    if rope_type == "linear":
        return inv_freq / rope_scaling.get("factor", 1.0)
    if rope_type == "llama3":
        return llama3_scaled_inv_freq(
            inv_freq,
            factor=rope_scaling.get("factor", 8.0),
            low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
            high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
            original_max_position=rope_scaling.get(
                "original_max_position_embeddings", 8192
            ),
        )
    raise NotImplementedError(f"rope_scaling type {rope_type!r}")


def rope_cos_sin(
    positions: jax.Array, inv_freq: jax.Array, dtype=jnp.float32
) -> tuple[jax.Array, jax.Array]:
    """positions [..., T] int -> cos/sin [..., T, head_dim] (halves duplicated,
    HF convention)."""
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, D/2]
    angles = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def _rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary_emb(
    q: jax.Array,
    k: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """q [B,T,Hq,D], k [B,T,Hk,D], cos/sin [B,T,D] -> rotated (q, k).

    rotate-half convention, computed in fp32 and cast back (the reference
    kernel also computes the rotation at full precision in-register).
    """
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
    q_out = qf * cos + _rotate_half(qf) * sin
    k_out = kf * cos + _rotate_half(kf) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)
