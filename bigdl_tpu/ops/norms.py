"""Normalization ops (reference: `xe_addons.rms_norm` / `layer_norm`,
models/common.py:166-182). Computed in float32 regardless of input dtype,
matching the reference kernels' accumulate-in-fp32 behavior."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float = 1e-6, offset: bool = False
) -> jax.Array:
    """offset=True is the gemma convention: scale by (1 + w)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if offset:
        w = 1.0 + w
    return (y * w).astype(x.dtype)


def layer_norm(
    x: jax.Array, weight: jax.Array, bias: jax.Array | None = None, eps: float = 1e-5
) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)
