"""Pallas fused dequant-matmul for packed int4 weights.

TPU-native counterpart of the reference's low-bit GEMM/GEMV kernels
(`xe_linear.forward_new` for prefill, `xe_batch.batch_forward` for
decode; dispatch in low_bit_linear.py:606-716 of /root/reference).

The decode step is HBM-bandwidth-bound: y = x @ W^T with x [M, K],
M <= ~32. The win over the XLA fallback (dequantize to bf16, then
matmul) is that W crosses HBM as packed nibbles — 0.5 byte/weight + one
f16 scale per 32 — i.e. ~4x less weight traffic than bf16, which is the
entire cost of a GEMV.

Layout contract (quant/numerics.py pack_nibbles): byte j of a row packs
element j in its low nibble and element j + K/2 in its high nibble. The
kernel therefore needs x's first and second halves — two *contiguous*
blocks of the same array, delivered by two BlockSpecs over x with no
data movement. (The previous interleaved layout needed a strided
even/odd deinterleave of x per call: ~40us of XLA prologue x 224 calls
per decode step — measured on v5e, round 3 — which dominated the kernel
itself.)

Mosaic constraints found on real TPU (the CPU interpreter accepts all of
these, silently): no f16 vector type -> scales cross as uint16 bits and
are decoded to f32 with integer ops in-kernel; no lane-collapsing
reshape -> per-block scales expand to per-element via a one-hot matmul
(iota compare + MXU dot), not broadcast+reshape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.utils import round_up

BLOCK = 32  # quant block (elements per scale) for sym_int4; nf4/fp4 use 64


def _f16_bits_to_f32(bits):
    """uint16 float16 bit pattern -> f32, integer ops only (Mosaic has no
    f16 vectors). Subnormal f16 decodes exactly as sign * mant * 2^-24 —
    NOT flushed: k-quant super-scales d = max|sub_scale|/127 routinely
    land below 6.1e-5 for real checkpoint magnitudes (caught by the q6_k
    kernel equivalence test: flushing zeroed whole super-blocks)."""
    b = bits.astype(jnp.int32)
    sign = (b >> 15) & 1
    exp = (b >> 10) & 0x1F
    mant = b & 0x3FF
    f32_bits = (sign << 31) | ((exp + 127 - 15) << 23) | (mant << 13)
    val = jax.lax.bitcast_convert_type(f32_bits, jnp.float32)
    sub = (1.0 - 2.0 * sign.astype(jnp.float32)) * (
        mant.astype(jnp.float32) * jnp.float32(2.0 ** -24)
    )
    return jnp.where(exp == 0, sub, val)


def _expand_scales(s, kh: int, base_block: int, block: int = BLOCK):
    """[block_o, nb] per-block scales -> [block_o, kh] per-element, where
    element j of this nibble plane belongs to quant block
    (j + base_block * kh) // block. One-hot matmul: iota/compare/dot only."""
    nb = s.shape[-1]
    sel = (
        jax.lax.broadcasted_iota(jnp.int32, (nb, kh), 1) // block
        + base_block * (kh // block)
        == jax.lax.broadcasted_iota(jnp.int32, (nb, kh), 0)
    ).astype(jnp.float32)
    return jax.lax.dot_general(
        s, sel, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _decode_nibbles(w, codebook):
    """Packed bytes -> (lo, hi) f32 code values. codebook=None is the
    arithmetic sym_int4 map (v - 8); otherwise a static 16-entry LUT
    realized as a compare/select tree (Mosaic has no vector gather)."""
    lo_c = w & 0xF
    hi_c = w >> 4
    if codebook is None:
        return (lo_c - 8).astype(jnp.float32), (hi_c - 8).astype(jnp.float32)

    def lut(c):
        v = jnp.zeros(c.shape, jnp.float32)
        for i, ci in enumerate(codebook):
            if ci != 0.0:
                v = jnp.where(c == i, jnp.float32(ci), v)
        return v

    return lut(lo_c), lut(hi_c)


def _kernel(xl_ref, xh_ref, w_ref, s_ref, o_ref, *, kh: int,
            block: int = BLOCK, codebook=None):
    """One O-tile: o = x_lo @ dq(lo)^T + x_hi @ dq(hi)^T."""
    w = w_ref[:].astype(jnp.int32)  # [block_o, kh] packed bytes
    lo, hi = _decode_nibbles(w, codebook)

    s = _f16_bits_to_f32(s_ref[:])  # [block_o, nb]
    wl = (lo * _expand_scales(s, kh, 0, block)).astype(jnp.bfloat16)
    wh = (hi * _expand_scales(s, kh, 1, block)).astype(jnp.bfloat16)

    xl = xl_ref[:].astype(jnp.bfloat16)  # [M, kh] first half of x
    xh = xh_ref[:].astype(jnp.bfloat16)  # [M, kh] second half
    acc = jax.lax.dot_general(
        xl, wl, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    acc += jax.lax.dot_general(
        xh, wh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    o_ref[:] = acc.astype(o_ref.dtype)


def _kernel_i8(x_ref, w_ref, s_ref, o_ref, *, block: int):
    """One (O, K) tile of the int8 GEMV, accumulating over the K grid
    axis: o += x_k @ (w_k * scale_k)^T. Unlike the nibble kernel there
    is no packing — w is [block_o, block_k] int8; the per-block scales
    expand with the same one-hot matmul, whose sel matrix is
    [block_k/32, block_k] and thus bounded by the K tile (a full-K sel
    at llama3's K=14336 would alone be ~26 MB — over the scoped-VMEM
    limit the int4 path already hit on real v5e)."""
    w = w_ref[:].astype(jnp.float32)  # [block_o, block_k]
    s = _f16_bits_to_f32(s_ref[:])  # [block_o, nb_k]
    wd = (w * _expand_scales(s, w.shape[-1], 0, block)).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        x_ref[:].astype(jnp.bfloat16), wd, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(1) == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "block_k", "interpret",
                              "block")
)
def _qmm_i8(x2, w, s_bits, out_dtype, block_o: int, block_k: int,
            interpret: bool, block: int):
    M, K = x2.shape
    O = w.shape[0]
    return pl.pallas_call(
        functools.partial(_kernel_i8, block=block),
        grid=(O // block_o, K // block_k),
        in_specs=[
            pl.BlockSpec((M, block_k), lambda o, k: (0, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, block_k), lambda o, k: (o, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, block_k // block), lambda o, k: (o, k),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o, k: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x2, w, s_bits).astype(out_dtype)


def qmatmul_int8(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] int8 (sym_int8 / imported q8_0)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int8 QTensor's fields:
    weights cross HBM as int8 — half the traffic of bf16, which is the
    whole cost of a decode GEMV."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    O, Kw = data.shape
    assert Kw == K and K % BLOCK == 0

    M = 1
    for d in lead:
        M *= d
    Mp = round_up(max(M, 1), 8)
    x2 = x.reshape(M, K)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))

    block_o = min(block_o, O)
    # K tile: sel matrix (block_k/32 x block_k f32) + w expansion fit
    # comfortably at 4096
    block_k = K
    while block_k > 4096 and K % (block_k // 2) == 0 and block_k % 2 == 0:
        block_k //= 2
    # VMEM model: w i8 + f32 expansion + bf16 copy ≈ 7 B per element,
    # plus the one-hot sel at ~block_k^2/8 B
    VMEM_BUDGET = 10 * 1024 * 1024
    while block_o > 8 and (
        block_o * block_k * 7 + block_k * block_k // 8 > VMEM_BUDGET
        or O % block_o
    ):
        block_o //= 2
    assert O % block_o == 0, f"O={O} not divisible by block_o={block_o}"
    assert K % block_k == 0

    if scales.dtype == jnp.float16:
        s_bits = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    else:
        s_bits = jax.lax.bitcast_convert_type(
            scales.astype(jnp.float16), jnp.uint16
        )
    y = _qmm_i8(x2, data, s_bits, jnp.dtype(out_dtype), block_o, block_k,
                interpret, BLOCK)
    return y[:M].reshape(*lead, O)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "interpret", "two_view",
                              "block", "codebook")
)
def _qmm(x2, w, s_bits, out_dtype, block_o: int, interpret: bool,
         two_view: bool, block: int = BLOCK, codebook=None):
    """two_view=True: x2 is [M, K] and the kernel's two x operands are
    delivered as half-lane views of the same array by BlockSpec index
    maps — zero data movement. Requires kh % 128 == 0 (Mosaic lane
    rule); small-K callers pre-slice instead (still contiguous)."""
    if two_view:
        M, K = x2.shape
        kh = K // 2
        x_args = (x2, x2)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 1), memory_space=pltpu.VMEM),
        ]
    else:
        xl, xh = x2
        M, kh = xl.shape
        x_args = (xl, xh)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
        ]
    O = w.shape[0]
    grid = (O // block_o,)
    return pl.pallas_call(
        functools.partial(_kernel, kh=kh, block=block, codebook=codebook),
        grid=grid,
        in_specs=x_specs + [
            pl.BlockSpec((block_o, kh), lambda o: (o, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (block_o, kh // (block // 2)), lambda o: (o, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(*x_args, w, s_bits)


def qmatmul_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (sym_int4, half-split)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int4 QTensor's fields."""
    return _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                           block=BLOCK, codebook=None)


def _expand_super(d, n_sub: int, offset_sub: int, per_super: int):
    """[bo, nb_super] f32 super-scales -> [bo, n_sub] per-sub-block:
    sub-block s (global index s + offset_sub) belongs to super-block
    (s + offset_sub) // per_super. One-hot matmul (iota/compare/dot),
    same Mosaic-safe expansion idiom as _expand_scales; the offset form
    handles nibble planes that start mid-super-block (odd super-block
    counts, e.g. llama2's K=11008 -> 43 blocks per row)."""
    nb = d.shape[-1]
    sel = (
        (jax.lax.broadcasted_iota(jnp.int32, (nb, n_sub), 1) + offset_sub)
        // per_super
        == jax.lax.broadcasted_iota(jnp.int32, (nb, n_sub), 0)
    ).astype(jnp.float32)
    return jax.lax.dot_general(
        d, sel, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _kernel_asym(xl_ref, xh_ref, w_ref, sl_ref, sh_ref, ml_ref, mh_ref,
                 o_ref, *, kh: int, block: int):
    """asym_int4 O-tile: w = q*d + m (q in 0..15, per-block f16 d/m,
    mins stored as the raw block minimum — the `+ m` convention of
    quant/numerics). Scales arrive pre-sliced per nibble plane, so the
    one-hot expansion sel is (kh/block, kh) — half the full-row sel.
    The four expansions (s/m x lo/hi) share that one sel via a single
    stacked dot, keeping one sel materialization live."""
    w = w_ref[:].astype(jnp.int32)
    lo = (w & 0xF).astype(jnp.float32)
    hi = (w >> 4).astype(jnp.float32)

    stacked = jnp.concatenate(
        [_f16_bits_to_f32(r[:]) for r in (sl_ref, ml_ref, sh_ref, mh_ref)],
        axis=0,
    )  # [4*bo, kh/block]
    exp = _expand_scales(stacked, kh, 0, block)  # [4*bo, kh]
    bo = w.shape[0]
    s_lo, m_lo = exp[:bo], exp[bo:2 * bo]
    s_hi, m_hi = exp[2 * bo:3 * bo], exp[3 * bo:]

    wl = (lo * s_lo + m_lo).astype(jnp.bfloat16)
    wh = (hi * s_hi + m_hi).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        xl_ref[:].astype(jnp.bfloat16), wl, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc += jax.lax.dot_general(
        xh_ref[:].astype(jnp.bfloat16), wh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = acc.astype(o_ref.dtype)


def _kernel_q4k(xl_ref, xh_ref, w_ref, d_ref, dmin_ref, scl_ref, sch_ref,
                mnl_ref, mnh_ref, o_ref, *, kh: int):
    """q4_k O-tile: w = (d*sc)*q - (dmin*mn) per 32-element sub-block.
    d/dmin are FULL per-super-block rows [bo, nb] (f16 bits) expanded
    in-kernel with an offset one-hot — BlockSpec slicing them per plane
    would need fractional offsets when nb is odd. sc/mn arrive pre-
    sliced per plane ([bo, kh/32] uint8). All four per-element
    expansions share one (kh/32, kh) sel via a stacked dot."""
    w = w_ref[:].astype(jnp.int32)
    lo = (w & 0xF).astype(jnp.float32)
    hi = (w >> 4).astype(jnp.float32)

    d32 = _f16_bits_to_f32(d_ref[:])  # [bo, nb]
    dmin32 = _f16_bits_to_f32(dmin_ref[:])
    n_sub = kh // 32  # sub-blocks per plane
    per_super = 8  # 256-element super-block = 8 sub-blocks of 32
    s_lo = _expand_super(d32, n_sub, 0, per_super) * (
        scl_ref[:].astype(jnp.float32))
    s_hi = _expand_super(d32, n_sub, n_sub, per_super) * (
        sch_ref[:].astype(jnp.float32))
    m_lo = _expand_super(dmin32, n_sub, 0, per_super) * (
        mnl_ref[:].astype(jnp.float32))
    m_hi = _expand_super(dmin32, n_sub, n_sub, per_super) * (
        mnh_ref[:].astype(jnp.float32))

    stacked = jnp.concatenate([s_lo, m_lo, s_hi, m_hi], axis=0)
    exp = _expand_scales(stacked, kh, 0, 32)  # [4*bo, kh]
    bo = w.shape[0]

    wl = (lo * exp[:bo] - exp[bo:2 * bo]).astype(jnp.bfloat16)
    wh = (hi * exp[2 * bo:3 * bo] - exp[3 * bo:]).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        xl_ref[:].astype(jnp.bfloat16), wl, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc += jax.lax.dot_general(
        xh_ref[:].astype(jnp.bfloat16), wh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = acc.astype(o_ref.dtype)


def _kernel_q6k(x_ref, w_ref, d_ref, sc_ref, o_ref, *, block_k: int):
    """One (O, K) tile of the q6_k GEMV, accumulating over the K grid
    axis: w = (d*sc)*q per 16-element sub-block, codes already centered
    int8. K tiles align to 256-element super-blocks so d needs no
    offset; sel is (block_k/16, block_k), bounded by the K tile."""
    w = w_ref[:].astype(jnp.float32)  # [bo, bk] int8 codes
    d32 = _f16_bits_to_f32(d_ref[:])  # [bo, bk/256]
    n_sub = block_k // 16
    s_sub = _expand_super(d32, n_sub, 0, 16) * sc_ref[:].astype(jnp.float32)
    wd = (w * _expand_scales(s_sub, block_k, 0, 16)).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        x_ref[:].astype(jnp.bfloat16), wd, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(1) == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += acc.astype(o_ref.dtype)


def qmatmul_codebook(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split nibbles)
    scales: jax.Array,  # [O, K // block] f16
    codebook,  # 16 static floats: value = codebook[code] * scale
    block: int = 64,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for LUT nibble formats (nf4 / fp4).

    Same HBM story as qmatmul_int4 (weights cross as packed nibbles,
    ~4x less traffic than bf16); the in-kernel decode is a 16-way
    compare/select tree over the static codebook instead of (v - 8) —
    Mosaic has no vector gather, and at GEMV arithmetic intensity the
    extra VPU selects stay under the HBM bound. Without this, nf4/fp4
    decode fell back to dequantize-then-matmul, giving up the entire
    bandwidth win (VERDICT r02 weak #5).
    """
    return _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                           block=block, codebook=tuple(float(c) for c in codebook))


def _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                    block, codebook):
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    O, kh = data.shape
    # K % (2*block): with half-split packing each nibble plane must cover
    # whole quant blocks, or _expand_scales' j//block math is wrong
    assert kh * 2 == K and K % (2 * block) == 0

    M = 1
    for d in lead:
        M *= d
    Mp = round_up(max(M, 1), 8)
    x2 = x.reshape(M, K)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))

    block_o = min(block_o, O)
    # Mosaic scoped-VMEM budget: the kernel materializes lo/hi f32 and
    # wl/wh bf16 expansions of the weight tile — ~12 bytes per packed
    # element on the stack. At block_o=256, K=14336 (llama3-8b down_proj)
    # that overflows the 16 MiB scoped limit on real v5e ("Ran out of
    # memory in memory space vmem", BENCH r03) — a failure interpret-mode
    # CPU tests cannot see. Shrink the O tile until the model fits in
    # ~10 MiB, leaving headroom for x views and the scale one-hot.
    VMEM_BUDGET = 10 * 1024 * 1024
    # block_o-dependent tile (~12 B/packed element) + the block_o-
    # INDEPENDENT one-hot sel matrix ((kh/32) x kh f32 = kh^2/8 B);
    # shrinking the O tile cannot shrink the sel — if a future shape
    # overflows even at block_o=8, the fix is K-tiling like _qmm_i8
    sel_bytes = kh * kh // 8
    while block_o > 8 and (
        block_o * kh * 12 + sel_bytes > VMEM_BUDGET or O % block_o
    ):
        block_o //= 2
    assert O % block_o == 0, f"O={O} not divisible by block_o={block_o}"

    if scales.dtype == jnp.float16:
        s_bits = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    else:  # bf16/f32 scales: round-trip through f16 bits (test paths)
        s_bits = jax.lax.bitcast_convert_type(
            scales.astype(jnp.float16), jnp.uint16
        )
    two_view = kh % 128 == 0
    xa = x2 if two_view else (x2[:, :kh], x2[:, kh:])
    y = _qmm(xa, data, s_bits, jnp.dtype(out_dtype), block_o, interpret,
             two_view, block, codebook)
    return y[:M].reshape(*lead, O)


# ---------------------------------------------------------------------------
# asym_int4 / q4_k / q6_k fused GEMV (two-level scales, min terms)
# ---------------------------------------------------------------------------

def _gemv_prep(x, block_o: int, O: int, interpret):
    """Shared wrapper plumbing: flatten/pad x rows, resolve interpret."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    M = 1
    for d in lead:
        M *= d
    Mp = round_up(max(M, 1), 8)
    x2 = x.reshape(M, K)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))
    return x2, lead, M, K, min(block_o, O), interpret


def _shrink_block_o(block_o: int, O: int, bytes_per_row: int,
                    fixed_bytes: int, budget: int = 10 * 1024 * 1024) -> int:
    """Largest power-of-two O tile whose VMEM model fits the scoped
    budget (round-3 lesson: model VMEM explicitly — Mosaic overflows at
    shapes the CPU interpreter happily accepts)."""
    while block_o > 8 and (
        block_o * bytes_per_row + fixed_bytes > budget or O % block_o
    ):
        block_o //= 2
    assert O % block_o == 0, f"O={O} not divisible by block_o={block_o}"
    return block_o


def _f16_bits(a: jax.Array) -> jax.Array:
    if a.dtype != jnp.float16:
        a = a.astype(jnp.float16)
    return jax.lax.bitcast_convert_type(a, jnp.uint16)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "interpret",
                              "two_view", "block")
)
def _qmm_asym(x2, w, s_bits, m_bits, out_dtype, block_o: int,
              interpret: bool, two_view: bool, block: int):
    if two_view:
        M, K = x2.shape
        kh = K // 2
        x_args = (x2, x2)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 1), memory_space=pltpu.VMEM),
        ]
    else:
        xl, xh = x2
        M, kh = xl.shape
        x_args = (xl, xh)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
        ]
    O = w.shape[0]
    nbp = kh // block  # scale blocks per nibble plane
    sm_specs = [
        pl.BlockSpec((block_o, nbp), lambda o: (o, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((block_o, nbp), lambda o: (o, 1), memory_space=pltpu.VMEM),
    ]
    return pl.pallas_call(
        functools.partial(_kernel_asym, kh=kh, block=block),
        grid=(O // block_o,),
        in_specs=x_specs + [
            pl.BlockSpec((block_o, kh), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),
            sm_specs[0], sm_specs[1],  # s lo/hi plane
            pl.BlockSpec((block_o, nbp), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nbp), lambda o: (o, 1),
                         memory_space=pltpu.VMEM),  # m lo/hi plane
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(*x_args, w, s_bits, s_bits, m_bits, m_bits)


def qmatmul_asym_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split)
    scales: jax.Array,  # [O, K // 32] f16
    mins: jax.Array,  # [O, K // 32] f16 (raw block minimum; w = q*d + m)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for asym_int4: the per-block min adds one
    rank-1-per-block term, folded into the bf16 weight expansion before
    the dot (same HBM story as sym_int4 + 0.5 bit/weight for mins)."""
    O, kh = data.shape
    x2, lead, M, K, block_o, interpret = _gemv_prep(x, block_o, O, interpret)
    assert kh * 2 == K and K % (2 * BLOCK) == 0 and (K // BLOCK) % 2 == 0
    sel_bytes = kh * kh // 8
    block_o = _shrink_block_o(block_o, O, kh * 30, sel_bytes)
    two_view = kh % 128 == 0
    xa = x2 if two_view else (x2[:, :kh], x2[:, kh:])
    y = _qmm_asym(xa, data, _f16_bits(scales), _f16_bits(mins),
                  jnp.dtype(out_dtype), block_o, interpret, two_view, BLOCK)
    return y[:M].reshape(*lead, O)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "interpret", "two_view")
)
def _qmm_q4k(x2, w, d_bits, dmin_bits, sc, mn, out_dtype, block_o: int,
             interpret: bool, two_view: bool):
    if two_view:
        M, K = x2.shape
        kh = K // 2
        x_args = (x2, x2)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 1), memory_space=pltpu.VMEM),
        ]
    else:
        xl, xh = x2
        M, kh = xl.shape
        x_args = (xl, xh)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
        ]
    O, nb = d_bits.shape  # nb = K/256 super-blocks
    nsp = kh // 32  # sub-blocks per plane
    return pl.pallas_call(
        functools.partial(_kernel_q4k, kh=kh),
        grid=(O // block_o,),
        in_specs=x_specs + [
            pl.BlockSpec((block_o, kh), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),  # d (full row)
            pl.BlockSpec((block_o, nb), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),  # dmin
            pl.BlockSpec((block_o, nsp), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),  # sc lo plane
            pl.BlockSpec((block_o, nsp), lambda o: (o, 1),
                         memory_space=pltpu.VMEM),  # sc hi plane
            pl.BlockSpec((block_o, nsp), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),  # mn lo
            pl.BlockSpec((block_o, nsp), lambda o: (o, 1),
                         memory_space=pltpu.VMEM),  # mn hi
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(*x_args, w, d_bits, dmin_bits, sc, sc, mn, mn)


def qmatmul_q4k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split)
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    mins: jax.Array,  # [O, K // 256] f16 super-scale dmin
    sub_scales: jax.Array,  # [O, K // 32] uint8 6-bit sc
    sub_mins: jax.Array,  # [O, K // 32] uint8 6-bit mn
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for planar q4_k (quant/kq_planar.py):
    w = (d*sc)*q - (dmin*mn). Weights cross HBM at 4.625 bits/weight —
    the reference's recommended quality format (README ppl table) served
    at sym_int4-class bandwidth instead of the 2.7x dequant fallback."""
    O, kh = data.shape
    x2, lead, M, K, block_o, interpret = _gemv_prep(x, block_o, O, interpret)
    # whole super-blocks per row and whole 32-element sub-blocks per
    # nibble plane; odd super-block counts are fine (offset expansion)
    assert kh * 2 == K and K % 256 == 0
    sel_bytes = kh * kh // 8
    block_o = _shrink_block_o(block_o, O, kh * 30, sel_bytes)
    two_view = kh % 128 == 0
    xa = x2 if two_view else (x2[:, :kh], x2[:, kh:])
    y = _qmm_q4k(xa, data, _f16_bits(scales), _f16_bits(mins),
                 sub_scales, sub_mins, jnp.dtype(out_dtype), block_o,
                 interpret, two_view)
    return y[:M].reshape(*lead, O)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "block_k", "interpret")
)
def _qmm_q6k(x2, w, d_bits, sc, out_dtype, block_o: int, block_k: int,
             interpret: bool):
    M, K = x2.shape
    O = w.shape[0]
    return pl.pallas_call(
        functools.partial(_kernel_q6k, block_k=block_k),
        grid=(O // block_o, K // block_k),
        in_specs=[
            pl.BlockSpec((M, block_k), lambda o, k: (0, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, block_k), lambda o, k: (o, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, block_k // 256), lambda o, k: (o, k),
                         memory_space=pltpu.VMEM),  # d
            pl.BlockSpec((block_o, block_k // 16), lambda o, k: (o, k),
                         memory_space=pltpu.VMEM),  # sc
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o, k: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x2, w, d_bits, sc).astype(out_dtype)


def qmatmul_q6k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] int8 centered codes
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    sub_scales: jax.Array,  # [O, K // 16] int8 sc
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused GEMV for planar q6_k: w = (d*sc)*q per 16-element
    sub-block, K-tiled accumulation (K tiles align to super-blocks so
    the super-scale expansion needs no offset)."""
    O, Kw = data.shape
    x2, lead, M, K, block_o, interpret = _gemv_prep(x, block_o, O, interpret)
    assert Kw == K and K % 256 == 0

    # K tile: largest multiple-of-256 divisor of K that keeps the
    # (bk/16, bk) one-hot sel within budget (<= 4096); prime super-block
    # counts (llama2's 11008 = 43 blocks) degrade to 256-wide tiles
    block_k = 256
    nb = K // 256
    for t in range(nb, 0, -1):
        if nb % t == 0 and t * 256 <= 4096:
            block_k = t * 256
            break
    sel_bytes = block_k * block_k // 4
    block_o = _shrink_block_o(block_o, O, block_k * 11, sel_bytes)
    y = _qmm_q6k(x2, data, _f16_bits(scales), sub_scales,
                 jnp.dtype(out_dtype), block_o, block_k, interpret)
    return y[:M].reshape(*lead, O)
