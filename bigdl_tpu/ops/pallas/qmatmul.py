"""Pallas fused dequant-matmul for packed int4 weights.

TPU-native counterpart of the reference's low-bit GEMM/GEMV kernels
(`xe_linear.forward_new` for prefill, `xe_batch.batch_forward` for
decode; dispatch in low_bit_linear.py:606-716 of /root/reference).

The decode step is HBM-bandwidth-bound: y = x @ W^T with x [M, K],
M <= ~32. The win over the XLA fallback (dequantize to bf16, then
matmul) is that W crosses HBM as packed nibbles — 0.5 byte/weight + one
f16 scale per 32 — i.e. ~4x less weight traffic than bf16, which is the
entire cost of a GEMV.

Layout contract (quant/numerics.py pack_nibbles): byte j of a row packs
element j in its low nibble and element j + K/2 in its high nibble. The
kernel therefore needs x's first and second halves — two *contiguous*
blocks of the same array, delivered by two BlockSpecs over x with no
data movement. (The previous interleaved layout needed a strided
even/odd deinterleave of x per call: ~40us of XLA prologue x 224 calls
per decode step — measured on v5e, round 3 — which dominated the kernel
itself.)

Mosaic constraints found on real TPU (the CPU interpreter accepts all of
these, silently): no f16 vector type -> scales cross as uint16 bits and
are decoded to f32 with integer ops in-kernel; no lane-collapsing
reshape -> per-block scales expand to per-element via a one-hot matmul
(iota compare + MXU dot), not broadcast+reshape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.utils import round_up

BLOCK = 32  # quant block (elements per scale) for sym_int4; nf4/fp4 use 64


def _f16_bits_to_f32(bits):
    """uint16 float16 bit pattern -> f32, integer ops only (Mosaic has no
    f16 vectors). Subnormal f16 scales flush to zero — a scale below
    6.1e-5 only occurs for an all-zero weight block."""
    b = bits.astype(jnp.int32)
    sign = (b >> 15) & 1
    exp = (b >> 10) & 0x1F
    mant = b & 0x3FF
    f32_bits = (sign << 31) | ((exp + 127 - 15) << 23) | (mant << 13)
    val = jax.lax.bitcast_convert_type(f32_bits, jnp.float32)
    return jnp.where(exp == 0, 0.0, val)


def _expand_scales(s, kh: int, base_block: int, block: int = BLOCK):
    """[block_o, nb] per-block scales -> [block_o, kh] per-element, where
    element j of this nibble plane belongs to quant block
    (j + base_block * kh) // block. One-hot matmul: iota/compare/dot only."""
    nb = s.shape[-1]
    sel = (
        jax.lax.broadcasted_iota(jnp.int32, (nb, kh), 1) // block
        + base_block * (kh // block)
        == jax.lax.broadcasted_iota(jnp.int32, (nb, kh), 0)
    ).astype(jnp.float32)
    return jax.lax.dot_general(
        s, sel, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _decode_nibbles(w, codebook):
    """Packed bytes -> (lo, hi) f32 code values. codebook=None is the
    arithmetic sym_int4 map (v - 8); otherwise a static 16-entry LUT
    realized as a compare/select tree (Mosaic has no vector gather)."""
    lo_c = w & 0xF
    hi_c = w >> 4
    if codebook is None:
        return (lo_c - 8).astype(jnp.float32), (hi_c - 8).astype(jnp.float32)

    def lut(c):
        v = jnp.zeros(c.shape, jnp.float32)
        for i, ci in enumerate(codebook):
            if ci != 0.0:
                v = jnp.where(c == i, jnp.float32(ci), v)
        return v

    return lut(lo_c), lut(hi_c)


def _kernel(xl_ref, xh_ref, w_ref, s_ref, o_ref, *, kh: int,
            block: int = BLOCK, codebook=None):
    """One O-tile: o = x_lo @ dq(lo)^T + x_hi @ dq(hi)^T."""
    w = w_ref[:].astype(jnp.int32)  # [block_o, kh] packed bytes
    lo, hi = _decode_nibbles(w, codebook)

    s = _f16_bits_to_f32(s_ref[:])  # [block_o, nb]
    wl = (lo * _expand_scales(s, kh, 0, block)).astype(jnp.bfloat16)
    wh = (hi * _expand_scales(s, kh, 1, block)).astype(jnp.bfloat16)

    xl = xl_ref[:].astype(jnp.bfloat16)  # [M, kh] first half of x
    xh = xh_ref[:].astype(jnp.bfloat16)  # [M, kh] second half
    acc = jax.lax.dot_general(
        xl, wl, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    acc += jax.lax.dot_general(
        xh, wh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    o_ref[:] = acc.astype(o_ref.dtype)


def _kernel_i8(x_ref, w_ref, s_ref, o_ref, *, block: int):
    """One (O, K) tile of the int8 GEMV, accumulating over the K grid
    axis: o += x_k @ (w_k * scale_k)^T. Unlike the nibble kernel there
    is no packing — w is [block_o, block_k] int8; the per-block scales
    expand with the same one-hot matmul, whose sel matrix is
    [block_k/32, block_k] and thus bounded by the K tile (a full-K sel
    at llama3's K=14336 would alone be ~26 MB — over the scoped-VMEM
    limit the int4 path already hit on real v5e)."""
    w = w_ref[:].astype(jnp.float32)  # [block_o, block_k]
    s = _f16_bits_to_f32(s_ref[:])  # [block_o, nb_k]
    wd = (w * _expand_scales(s, w.shape[-1], 0, block)).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        x_ref[:].astype(jnp.bfloat16), wd, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(1) == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "block_k", "interpret",
                              "block")
)
def _qmm_i8(x2, w, s_bits, out_dtype, block_o: int, block_k: int,
            interpret: bool, block: int):
    M, K = x2.shape
    O = w.shape[0]
    return pl.pallas_call(
        functools.partial(_kernel_i8, block=block),
        grid=(O // block_o, K // block_k),
        in_specs=[
            pl.BlockSpec((M, block_k), lambda o, k: (0, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, block_k), lambda o, k: (o, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, block_k // block), lambda o, k: (o, k),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o, k: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x2, w, s_bits).astype(out_dtype)


def qmatmul_int8(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] int8 (sym_int8 / imported q8_0)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int8 QTensor's fields:
    weights cross HBM as int8 — half the traffic of bf16, which is the
    whole cost of a decode GEMV."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    O, Kw = data.shape
    assert Kw == K and K % BLOCK == 0

    M = 1
    for d in lead:
        M *= d
    Mp = round_up(max(M, 1), 8)
    x2 = x.reshape(M, K)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))

    block_o = min(block_o, O)
    # K tile: sel matrix (block_k/32 x block_k f32) + w expansion fit
    # comfortably at 4096
    block_k = K
    while block_k > 4096 and K % (block_k // 2) == 0 and block_k % 2 == 0:
        block_k //= 2
    # VMEM model: w i8 + f32 expansion + bf16 copy ≈ 7 B per element,
    # plus the one-hot sel at ~block_k^2/8 B
    VMEM_BUDGET = 10 * 1024 * 1024
    while block_o > 8 and (
        block_o * block_k * 7 + block_k * block_k // 8 > VMEM_BUDGET
        or O % block_o
    ):
        block_o //= 2
    assert O % block_o == 0, f"O={O} not divisible by block_o={block_o}"
    assert K % block_k == 0

    if scales.dtype == jnp.float16:
        s_bits = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    else:
        s_bits = jax.lax.bitcast_convert_type(
            scales.astype(jnp.float16), jnp.uint16
        )
    y = _qmm_i8(x2, data, s_bits, jnp.dtype(out_dtype), block_o, block_k,
                interpret, BLOCK)
    return y[:M].reshape(*lead, O)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "interpret", "two_view",
                              "block", "codebook")
)
def _qmm(x2, w, s_bits, out_dtype, block_o: int, interpret: bool,
         two_view: bool, block: int = BLOCK, codebook=None):
    """two_view=True: x2 is [M, K] and the kernel's two x operands are
    delivered as half-lane views of the same array by BlockSpec index
    maps — zero data movement. Requires kh % 128 == 0 (Mosaic lane
    rule); small-K callers pre-slice instead (still contiguous)."""
    if two_view:
        M, K = x2.shape
        kh = K // 2
        x_args = (x2, x2)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 1), memory_space=pltpu.VMEM),
        ]
    else:
        xl, xh = x2
        M, kh = xl.shape
        x_args = (xl, xh)
        x_specs = [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
        ]
    O = w.shape[0]
    grid = (O // block_o,)
    return pl.pallas_call(
        functools.partial(_kernel, kh=kh, block=block, codebook=codebook),
        grid=grid,
        in_specs=x_specs + [
            pl.BlockSpec((block_o, kh), lambda o: (o, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (block_o, kh // (block // 2)), lambda o: (o, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(*x_args, w, s_bits)


def qmatmul_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (sym_int4, half-split)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int4 QTensor's fields."""
    return _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                           block=BLOCK, codebook=None)


def qmatmul_codebook(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split nibbles)
    scales: jax.Array,  # [O, K // block] f16
    codebook,  # 16 static floats: value = codebook[code] * scale
    block: int = 64,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for LUT nibble formats (nf4 / fp4).

    Same HBM story as qmatmul_int4 (weights cross as packed nibbles,
    ~4x less traffic than bf16); the in-kernel decode is a 16-way
    compare/select tree over the static codebook instead of (v - 8) —
    Mosaic has no vector gather, and at GEMV arithmetic intensity the
    extra VPU selects stay under the HBM bound. Without this, nf4/fp4
    decode fell back to dequantize-then-matmul, giving up the entire
    bandwidth win (VERDICT r02 weak #5).
    """
    return _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                           block=block, codebook=tuple(float(c) for c in codebook))


def _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                    block, codebook):
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    O, kh = data.shape
    # K % (2*block): with half-split packing each nibble plane must cover
    # whole quant blocks, or _expand_scales' j//block math is wrong
    assert kh * 2 == K and K % (2 * block) == 0

    M = 1
    for d in lead:
        M *= d
    Mp = round_up(max(M, 1), 8)
    x2 = x.reshape(M, K)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))

    block_o = min(block_o, O)
    # Mosaic scoped-VMEM budget: the kernel materializes lo/hi f32 and
    # wl/wh bf16 expansions of the weight tile — ~12 bytes per packed
    # element on the stack. At block_o=256, K=14336 (llama3-8b down_proj)
    # that overflows the 16 MiB scoped limit on real v5e ("Ran out of
    # memory in memory space vmem", BENCH r03) — a failure interpret-mode
    # CPU tests cannot see. Shrink the O tile until the model fits in
    # ~10 MiB, leaving headroom for x views and the scale one-hot.
    VMEM_BUDGET = 10 * 1024 * 1024
    # block_o-dependent tile (~12 B/packed element) + the block_o-
    # INDEPENDENT one-hot sel matrix ((kh/32) x kh f32 = kh^2/8 B);
    # shrinking the O tile cannot shrink the sel — if a future shape
    # overflows even at block_o=8, the fix is K-tiling like _qmm_i8
    sel_bytes = kh * kh // 8
    while block_o > 8 and (
        block_o * kh * 12 + sel_bytes > VMEM_BUDGET or O % block_o
    ):
        block_o //= 2
    assert O % block_o == 0, f"O={O} not divisible by block_o={block_o}"

    if scales.dtype == jnp.float16:
        s_bits = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    else:  # bf16/f32 scales: round-trip through f16 bits (test paths)
        s_bits = jax.lax.bitcast_convert_type(
            scales.astype(jnp.float16), jnp.uint16
        )
    two_view = kh % 128 == 0
    xa = x2 if two_view else (x2[:, :kh], x2[:, kh:])
    y = _qmm(xa, data, s_bits, jnp.dtype(out_dtype), block_o, interpret,
             two_view, block, codebook)
    return y[:M].reshape(*lead, O)
