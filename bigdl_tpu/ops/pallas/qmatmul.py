"""Pallas fused dequant-matmul for packed low-bit weights.

TPU-native counterpart of the reference's low-bit GEMM/GEMV kernels
(`xe_linear.forward_new` for prefill, `xe_batch.batch_forward` for
decode; dispatch in low_bit_linear.py:606-716 of /root/reference).

The decode step is HBM-bandwidth-bound: y = x @ W^T with x [M, K],
M <= ~32. The win over the XLA fallback (dequantize to bf16, then
matmul) is that W crosses HBM packed — e.g. 0.5 byte/weight + one f16
scale per 32 for nibble formats — i.e. up to ~6x less weight traffic
than bf16, which is the entire cost of a GEMV. Four kernel families
cover EVERY decodable qtype (coverage matrix: docs/kernels.md):
nibble (sym/asym_int4, nf4/fp4), byte-code (sym_int8, asym_int5, fp8),
packed multi-plane (sym_int5, fp6, nf3, q2_k, q5_k), and two-level
planar k-quant (q4_k, q6_k — q3_k shares q6_k's kernel).

Layout contract (quant/numerics.py pack_nibbles): byte j of a row packs
element j in its low nibble and element j + K/2 in its high nibble. The
kernel therefore needs x's first and second halves — two *contiguous*
blocks of the same array, delivered by two BlockSpecs over x with no
data movement. (The previous interleaved layout needed a strided
even/odd deinterleave of x per call: ~40us of XLA prologue x 224 calls
per decode step — measured on v5e, round 3 — which dominated the kernel
itself.)

Mosaic constraints found on real TPU (the CPU interpreter accepts all of
these, silently):

* no f16 vector type -> scales cross as uint16 bits and are decoded to
  f32 with integer ops in-kernel (r03);
* no lane-collapsing reshape -> per-block scales expand to per-element
  via a one-hot matmul (iota compare + MXU dot), not broadcast+reshape
  (r03);
* the last two dims of every BlockSpec must be (sublane, 128)-aligned
  UNLESS the block covers the whole array dim (r05). This outlaws both
  the old VMEM fix (shrinking block_o below 128 put a 32/64-lane tile
  on the OUTPUT spec) and any lane-tiling of the skinny scale arrays
  (K/32 columns: tiles of 112/224 lanes). The design that satisfies the
  rule at every real shape: grid over O only, every operand block FULL
  in the lane dim (full-dim blocks are always legal), and VMEM bounded
  by an in-kernel statically-unrolled chunk loop over K — per-chunk
  dequant temporaries are dead after their dot, so live VMEM is
  O(block_o * chunk) regardless of K.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.utils import round_up

BLOCK = 32  # quant block (elements per scale) for sym_int4; nf4/fp4 use 64
_VMEM_BUDGET = 10 * 1024 * 1024  # leave scoped-VMEM headroom under 16 MiB

from bigdl_tpu.ops.pallas._compat import CompilerParams as _CompilerParams


def _params_parallel():
    return _CompilerParams(dimension_semantics=("parallel",))


def _f16_bits_to_f32(bits):
    """uint16 float16 bit pattern -> f32, integer ops only (Mosaic has no
    f16 vectors). Subnormal f16 decodes exactly as sign * mant * 2^-24 —
    NOT flushed: k-quant super-scales d = max|sub_scale|/127 routinely
    land below 6.1e-5 for real checkpoint magnitudes (caught by the q6_k
    kernel equivalence test: flushing zeroed whole super-blocks)."""
    b = bits.astype(jnp.int32)
    sign = (b >> 15) & 1
    exp = (b >> 10) & 0x1F
    mant = b & 0x3FF
    f32_bits = (sign << 31) | ((exp + 127 - 15) << 23) | (mant << 13)
    val = jax.lax.bitcast_convert_type(f32_bits, jnp.float32)
    sub = (1.0 - 2.0 * sign.astype(jnp.float32)) * (
        mant.astype(jnp.float32) * jnp.float32(2.0 ** -24)
    )
    return jnp.where(exp == 0, sub, val)


def _expand_scales(s, ck: int, block: int):
    """[rows, nbc] per-block scales -> [rows, ck] per-element for one
    chunk whose start is block-aligned: element j belongs to local block
    j // block. One-hot matmul: iota/compare/dot only."""
    nbc = s.shape[-1]
    sel = (
        jax.lax.broadcasted_iota(jnp.int32, (nbc, ck), 1) // block
        == jax.lax.broadcasted_iota(jnp.int32, (nbc, ck), 0)
    ).astype(jnp.float32)
    return jax.lax.dot_general(
        s, sel, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _expand_super(d, n_sub: int, offset_sub: int, per_super: int):
    """[bo, nb_super] f32 super-scales -> [bo, n_sub] per-sub-block:
    sub-block s (global index s + offset_sub) belongs to super-block
    (s + offset_sub) // per_super. One-hot matmul (iota/compare/dot);
    the offset form handles chunks that start mid-super-block (odd
    super-block counts, e.g. llama2's K=11008 -> 43 blocks per row)."""
    nb = d.shape[-1]
    sel = (
        (jax.lax.broadcasted_iota(jnp.int32, (nb, n_sub), 1) + offset_sub)
        // per_super
        == jax.lax.broadcasted_iota(jnp.int32, (nb, n_sub), 0)
    ).astype(jnp.float32)
    return jax.lax.dot_general(
        d, sel, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _decode_nibbles(w, codebook):
    """Packed bytes -> (lo, hi) f32 code values. codebook=None is the
    arithmetic sym_int4 map (v - 8); otherwise a static 16-entry LUT
    realized as a compare/select tree (Mosaic has no vector gather)."""
    lo_c = w & 0xF
    hi_c = w >> 4
    if codebook is None:
        return (lo_c - 8).astype(jnp.float32), (hi_c - 8).astype(jnp.float32)

    def lut(c):
        v = jnp.zeros(c.shape, jnp.float32)
        for i, ci in enumerate(codebook):
            if ci != 0.0:
                v = jnp.where(c == i, jnp.float32(ci), v)
        return v

    return lut(lo_c), lut(hi_c)


def _chunks(total: int, target: int):
    """Static chunk spans (start, size) covering [0, total); every
    boundary is a multiple of 128 (x/w lane alignment) and therefore
    aligned to the 16/32/64-element scale blocks. 256-element
    SUPER-block boundaries are NOT respected (128-multiples can start
    mid-super-block, e.g. c0=6144 in kh=7168) — super-scale expansion
    must use the offset form of _expand_super."""
    spans = []
    c0 = 0
    while c0 < total:
        ck = min(target, total - c0)
        spans.append((c0, ck))
        c0 += ck
    return spans


def _slc(a, c0: int, ck: int):
    """Static lane-dim slice of a loaded rank-2 array."""
    return jax.lax.slice(a, (0, c0), (a.shape[0], c0 + ck))


def _pick_block_o(O: int, persist_per_row: int, cap: int = 256) -> int:
    """Largest lane-legal O tile: a multiple of 128 dividing O (256
    preferred, 128 if the per-row persistent footprint is large or the
    caller caps it), else the full dim (always legal — Mosaic pads)."""
    for bo in (256, 128):
        if bo <= cap and O % bo == 0 and (
            bo * persist_per_row <= _VMEM_BUDGET // 2
        ):
            return bo
    if O % 128 == 0:
        return 128
    return O


def _chunk_target(block_o: int, persist_bytes: int, kh: int,
                  temp_bpe: int = 12) -> int:
    """Largest chunk whose per-chunk temporaries (temp_bpe B/element of
    dequant intermediates — ~12 for the sym nibble kernel's lo/hi f32 +
    wl/wh bf16, ~28 for asym/q4k whose stacked 4-way expansion adds
    [4*bo, ck] f32 — plus the one-hot sel) fit beside the persistent
    blocks in the scoped-VMEM budget."""
    for ck in (2048, 1024, 512, 256, 128):
        if ck > kh:
            continue
        temp = block_o * ck * temp_bpe + (ck // 16) * ck * 4
        if persist_bytes + temp <= _VMEM_BUDGET:
            return ck
    return 128


# ---------------------------------------------------------------------------
# sym_int4 / nf4 / fp4: packed nibbles, single-level per-block scales
# ---------------------------------------------------------------------------

def _kernel(xl_ref, xh_ref, w_ref, s_ref, o_ref, *, kh: int, ck: int,
            block: int = BLOCK, codebook=None):
    """One O-tile: o = x_lo @ dq(lo)^T + x_hi @ dq(hi)^T, accumulated
    over statically-unrolled K chunks so live dequant temporaries stay
    O(block_o * ck)."""
    M = xl_ref.shape[0]
    bo = w_ref.shape[0]
    nbp = kh // block  # scale blocks per nibble plane
    w = w_ref[:]  # [bo, kh] packed bytes — upcast PER CHUNK, not here:
    # a hoisted full-row int32 copy would keep 4 B/packed-byte live
    # across the whole unrolled loop and defeat the O(bo*ck) VMEM bound
    s = _f16_bits_to_f32(s_ref[:])  # [bo, 2*nbp]
    xl = xl_ref[:].astype(jnp.bfloat16)
    xh = xh_ref[:].astype(jnp.bfloat16)

    acc = jnp.zeros((M, bo), jnp.float32)
    for c0, c in _chunks(kh, ck):
        lo, hi = _decode_nibbles(_slc(w, c0, c).astype(jnp.int32), codebook)
        sb0, nbc = c0 // block, c // block
        wl = (lo * _expand_scales(_slc(s, sb0, nbc), c, block)
              ).astype(jnp.bfloat16)
        wh = (hi * _expand_scales(_slc(s, nbp + sb0, nbc), c, block)
              ).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            _slc(xl, c0, c), wl, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc += jax.lax.dot_general(
            _slc(xh, c0, c), wh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    o_ref[:] = acc.astype(o_ref.dtype)


def _x_specs(x2, two_view: bool):
    """x delivered as two half-lane views of one array (two_view) or as
    two pre-sliced halves; both are full-lane blocks."""
    if two_view:
        M, K = x2.shape
        kh = K // 2
        return (x2, x2), [
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 1), memory_space=pltpu.VMEM),
        ], M, kh
    xl, xh = x2
    M, kh = xl.shape
    return (xl, xh), [
        pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
    ], M, kh


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "ck", "interpret",
                              "two_view", "block", "codebook")
)
def _qmm(x2, w, s_bits, out_dtype, block_o: int, ck: int, interpret: bool,
         two_view: bool, block: int = BLOCK, codebook=None):
    x_args, x_specs, M, kh = _x_specs(x2, two_view)
    O = w.shape[0]
    nb = s_bits.shape[1]  # == K // block, full row (lane-legal: full dim)
    return pl.pallas_call(
        functools.partial(_kernel, kh=kh, ck=ck, block=block,
                          codebook=codebook),
        grid=(O // block_o,),
        in_specs=x_specs + [
            pl.BlockSpec((block_o, kh), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), lambda o: (o, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(*x_args, w, s_bits)


def qmatmul_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (sym_int4, half-split)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int4 QTensor's fields."""
    return _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                           block=BLOCK, codebook=None)


def qmatmul_codebook(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split nibbles)
    scales: jax.Array,  # [O, K // block] f16
    codebook,  # 16 static floats: value = codebook[code] * scale
    block: int = 64,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for LUT nibble formats (nf4 / fp4).

    Same HBM story as qmatmul_int4 (weights cross as packed nibbles,
    ~4x less traffic than bf16); the in-kernel decode is a 16-way
    compare/select tree over the static codebook instead of (v - 8) —
    Mosaic has no vector gather, and at GEMV arithmetic intensity the
    extra VPU selects stay under the HBM bound. Without this, nf4/fp4
    decode fell back to dequantize-then-matmul, giving up the entire
    bandwidth win (VERDICT r02 weak #5).
    """
    return _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                           block=block, codebook=tuple(float(c) for c in codebook))


def _qmatmul_nibble(x, data, scales, out_dtype, block_o, interpret,
                    block, codebook):
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    O, kh = data.shape
    # K % (2*block): with half-split packing each nibble plane must cover
    # whole quant blocks, or the chunked scale slicing is wrong
    assert kh * 2 == K and K % (2 * block) == 0

    M = 1
    for d in lead:
        M *= d
    Mp = round_up(max(M, 1), 8)
    x2 = x.reshape(M, K)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))

    # persistent VMEM per O row: w bytes (kh) + scale bits (K/block * 2)
    persist_row = kh + (K // block) * 2
    block_o = _pick_block_o(O, persist_row, cap=block_o)
    ck = _chunk_target(block_o, block_o * persist_row + Mp * K * 2, kh)

    if scales.dtype == jnp.float16:
        s_bits = jax.lax.bitcast_convert_type(scales, jnp.uint16)
    else:  # bf16/f32 scales: round-trip through f16 bits (test paths)
        s_bits = jax.lax.bitcast_convert_type(
            scales.astype(jnp.float16), jnp.uint16
        )
    two_view = kh % 128 == 0
    xa = x2 if two_view else (x2[:, :kh], x2[:, kh:])
    y = _qmm(xa, data, s_bits, jnp.dtype(out_dtype), block_o, ck, interpret,
             two_view, block, codebook)
    return y[:M].reshape(*lead, O)


# ---------------------------------------------------------------------------
# sym_int8 (served by the generic byte-code kernel below)
# ---------------------------------------------------------------------------

def qmatmul_int8(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] int8 (sym_int8 / imported q8_0)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int8 QTensor's fields:
    weights cross HBM as int8 — half the traffic of bf16, which is the
    whole cost of a decode GEMV."""
    return qmatmul_bytes(x, data, scales, None, "i8", BLOCK, out_dtype,
                         block_o, interpret)


# ---------------------------------------------------------------------------
# asym_int4 / q4_k / q6_k fused GEMV (two-level scales, min terms)
# ---------------------------------------------------------------------------

def _gemv_prep(x, block_o: int, O: int, interpret):
    """Shared wrapper plumbing: flatten/pad x rows, resolve interpret."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    M = 1
    for d in lead:
        M *= d
    Mp = round_up(max(M, 1), 8)
    x2 = x.reshape(M, K)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))
    return x2, lead, M, K, Mp, interpret


def _f16_bits(a: jax.Array) -> jax.Array:
    if a.dtype != jnp.float16:
        a = a.astype(jnp.float16)
    return jax.lax.bitcast_convert_type(a, jnp.uint16)


def _kernel_asym(xl_ref, xh_ref, w_ref, s_ref, m_ref, o_ref, *, kh: int,
                 ck: int, block: int):
    """asym_int4 O-tile: w = q*d + m (q in 0..15, per-block f16 d/m,
    mins stored as the raw block minimum — the `+ m` convention of
    quant/numerics). Per chunk, the four expansions (s/m x lo/hi) share
    one (nbc, ck) sel via a single stacked dot."""
    M = xl_ref.shape[0]
    bo = w_ref.shape[0]
    nbp = kh // block
    w = w_ref[:]  # packed bytes; upcast per chunk (VMEM bound)
    s = _f16_bits_to_f32(s_ref[:])  # [bo, 2*nbp]
    m = _f16_bits_to_f32(m_ref[:])
    xl = xl_ref[:].astype(jnp.bfloat16)
    xh = xh_ref[:].astype(jnp.bfloat16)

    acc = jnp.zeros((M, bo), jnp.float32)
    for c0, c in _chunks(kh, ck):
        wc = _slc(w, c0, c).astype(jnp.int32)
        lo = (wc & 0xF).astype(jnp.float32)
        hi = (wc >> 4).astype(jnp.float32)
        sb0, nbc = c0 // block, c // block
        stacked = jnp.concatenate([
            _slc(s, sb0, nbc), _slc(m, sb0, nbc),
            _slc(s, nbp + sb0, nbc), _slc(m, nbp + sb0, nbc),
        ], axis=0)  # [4*bo, nbc]
        exp = _expand_scales(stacked, c, block)  # [4*bo, c]
        wl = (lo * exp[:bo] + exp[bo:2 * bo]).astype(jnp.bfloat16)
        wh = (hi * exp[2 * bo:3 * bo] + exp[3 * bo:]).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            _slc(xl, c0, c), wl, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc += jax.lax.dot_general(
            _slc(xh, c0, c), wh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "ck", "interpret",
                              "two_view", "block")
)
def _qmm_asym(x2, w, s_bits, m_bits, out_dtype, block_o: int, ck: int,
              interpret: bool, two_view: bool, block: int):
    x_args, x_specs, M, kh = _x_specs(x2, two_view)
    O = w.shape[0]
    nb = s_bits.shape[1]
    row = lambda o: (o, 0)
    return pl.pallas_call(
        functools.partial(_kernel_asym, kh=kh, ck=ck, block=block),
        grid=(O // block_o,),
        in_specs=x_specs + [
            pl.BlockSpec((block_o, kh), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(*x_args, w, s_bits, m_bits)


def qmatmul_asym_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split)
    scales: jax.Array,  # [O, K // 32] f16
    mins: jax.Array,  # [O, K // 32] f16 (raw block minimum; w = q*d + m)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for asym_int4: the per-block min adds one
    rank-1-per-block term, folded into the bf16 weight expansion before
    the dot (same HBM story as sym_int4 + 0.5 bit/weight for mins)."""
    O, kh = data.shape
    x2, lead, M, K, Mp, interpret = _gemv_prep(x, block_o, O, interpret)
    assert kh * 2 == K and K % (2 * BLOCK) == 0 and (K // BLOCK) % 2 == 0
    persist_row = kh + (K // BLOCK) * 4
    block_o = _pick_block_o(O, persist_row, cap=block_o)
    ck = _chunk_target(block_o, block_o * persist_row + Mp * K * 2, kh,
                       temp_bpe=28)
    two_view = kh % 128 == 0
    xa = x2 if two_view else (x2[:, :kh], x2[:, kh:])
    y = _qmm_asym(xa, data, _f16_bits(scales), _f16_bits(mins),
                  jnp.dtype(out_dtype), block_o, ck, interpret, two_view,
                  BLOCK)
    return y[:M].reshape(*lead, O)


def _kernel_q4k(xl_ref, xh_ref, w_ref, d_ref, dmin_ref, sc_ref, mn_ref,
                o_ref, *, kh: int, ck: int):
    """q4_k O-tile: w = (d*sc)*q - (dmin*mn) per 32-element sub-block.
    d/dmin are per-super-block rows [bo, nb] (f16 bits); sc/mn are full
    global sub-block rows [bo, K/32] uint8. Per chunk the super-scale
    expansion uses the offset one-hot (chunks may start mid-super-block
    when nb is odd), and all four per-element expansions share one
    (nsc, ck) sel via a stacked dot."""
    M = xl_ref.shape[0]
    bo = w_ref.shape[0]
    nsp = kh // 32  # sub-blocks per nibble plane
    per_super = 8  # 256-element super-block = 8 sub-blocks of 32
    w = w_ref[:]  # packed bytes; upcast per chunk (VMEM bound)
    d32 = _f16_bits_to_f32(d_ref[:])  # [bo, nb]
    dmin32 = _f16_bits_to_f32(dmin_ref[:])
    sc = sc_ref[:].astype(jnp.float32)  # [bo, 2*nsp]
    mn = mn_ref[:].astype(jnp.float32)
    xl = xl_ref[:].astype(jnp.bfloat16)
    xh = xh_ref[:].astype(jnp.bfloat16)

    acc = jnp.zeros((M, bo), jnp.float32)
    for c0, c in _chunks(kh, ck):
        wc = _slc(w, c0, c).astype(jnp.int32)
        lo = (wc & 0xF).astype(jnp.float32)
        hi = (wc >> 4).astype(jnp.float32)
        sb0, nsc = c0 // 32, c // 32
        s_lo = _expand_super(d32, nsc, sb0, per_super) * (
            _slc(sc, sb0, nsc))
        s_hi = _expand_super(d32, nsc, nsp + sb0, per_super) * (
            _slc(sc, nsp + sb0, nsc))
        m_lo = _expand_super(dmin32, nsc, sb0, per_super) * (
            _slc(mn, sb0, nsc))
        m_hi = _expand_super(dmin32, nsc, nsp + sb0, per_super) * (
            _slc(mn, nsp + sb0, nsc))
        stacked = jnp.concatenate([s_lo, m_lo, s_hi, m_hi], axis=0)
        exp = _expand_scales(stacked, c, 32)  # [4*bo, c]
        wl = (lo * exp[:bo] - exp[bo:2 * bo]).astype(jnp.bfloat16)
        wh = (hi * exp[2 * bo:3 * bo] - exp[3 * bo:]).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            _slc(xl, c0, c), wl, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc += jax.lax.dot_general(
            _slc(xh, c0, c), wh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "ck", "interpret",
                              "two_view")
)
def _qmm_q4k(x2, w, d_bits, dmin_bits, sc, mn, out_dtype, block_o: int,
             ck: int, interpret: bool, two_view: bool):
    x_args, x_specs, M, kh = _x_specs(x2, two_view)
    O, nb = d_bits.shape  # nb = K/256 super-blocks
    nsub = sc.shape[1]  # K/32 global sub-blocks
    row = lambda o: (o, 0)
    return pl.pallas_call(
        functools.partial(_kernel_q4k, kh=kh, ck=ck),
        grid=(O // block_o,),
        in_specs=x_specs + [
            pl.BlockSpec((block_o, kh), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),  # d
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),  # dmin
            pl.BlockSpec((block_o, nsub), row, memory_space=pltpu.VMEM),  # sc
            pl.BlockSpec((block_o, nsub), row, memory_space=pltpu.VMEM),  # mn
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(*x_args, w, d_bits, dmin_bits, sc, mn)


def qmatmul_q4k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split)
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    mins: jax.Array,  # [O, K // 256] f16 super-scale dmin
    sub_scales: jax.Array,  # [O, K // 32] uint8 6-bit sc
    sub_mins: jax.Array,  # [O, K // 32] uint8 6-bit mn
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for planar q4_k (quant/kq_planar.py):
    w = (d*sc)*q - (dmin*mn). Weights cross HBM at 4.625 bits/weight —
    the reference's recommended quality format (README ppl table) served
    at sym_int4-class bandwidth instead of the 2.7x dequant fallback."""
    O, kh = data.shape
    x2, lead, M, K, Mp, interpret = _gemv_prep(x, block_o, O, interpret)
    # whole super-blocks per row and whole 32-element sub-blocks per
    # nibble plane; odd super-block counts are fine (offset expansion)
    assert kh * 2 == K and K % 256 == 0
    persist_row = kh + (K // 256) * 4 + (K // 32) * 2
    block_o = _pick_block_o(O, persist_row, cap=block_o)
    ck = _chunk_target(block_o, block_o * persist_row + Mp * K * 2, kh,
                       temp_bpe=28)
    two_view = kh % 128 == 0
    xa = x2 if two_view else (x2[:, :kh], x2[:, kh:])
    y = _qmm_q4k(xa, data, _f16_bits(scales), _f16_bits(mins),
                 sub_scales, sub_mins, jnp.dtype(out_dtype), block_o, ck,
                 interpret, two_view)
    return y[:M].reshape(*lead, O)


def _kernel_q6k(x_ref, w_ref, d_ref, sc_ref, o_ref, *, ck: int):
    """q6_k O-tile: w = (d*sc)*q per 16-element sub-block, codes already
    centered int8, chunked over K in-kernel (chunks may start mid-
    super-block: offset one-hot)."""
    M = x_ref.shape[0]
    bo = w_ref.shape[0]
    K = w_ref.shape[1]
    w = w_ref[:]
    d32 = _f16_bits_to_f32(d_ref[:])  # [bo, K/256]
    scf = sc_ref[:].astype(jnp.float32)  # [bo, K/16]
    x = x_ref[:].astype(jnp.bfloat16)

    acc = jnp.zeros((M, bo), jnp.float32)
    for c0, c in _chunks(K, ck):
        wc = _slc(w, c0, c).astype(jnp.float32)
        sb0, nsc = c0 // 16, c // 16
        s_sub = _expand_super(d32, nsc, sb0, 16) * _slc(scf, sb0, nsc)
        wd = (wc * _expand_scales(s_sub, c, 16)).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            _slc(x, c0, c), wd, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "ck", "interpret")
)
def _qmm_q6k(x2, w, d_bits, sc, out_dtype, block_o: int, ck: int,
             interpret: bool):
    M, K = x2.shape
    O = w.shape[0]
    row = lambda o: (o, 0)
    return pl.pallas_call(
        functools.partial(_kernel_q6k, ck=ck),
        grid=(O // block_o,),
        in_specs=[
            pl.BlockSpec((M, K), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, K), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, K // 256), row,
                         memory_space=pltpu.VMEM),  # d
            pl.BlockSpec((block_o, K // 16), row,
                         memory_space=pltpu.VMEM),  # sc
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(x2, w, d_bits, sc)


def qmatmul_q6k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] int8 centered codes
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    sub_scales: jax.Array,  # [O, K // 16] int8 sc
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused GEMV for planar q6_k: w = (d*sc)*q per 16-element
    sub-block, K chunked in-kernel."""
    O, Kw = data.shape
    x2, lead, M, K, Mp, interpret = _gemv_prep(x, block_o, O, interpret)
    assert Kw == K and K % 256 == 0

    persist_row = K + (K // 256) * 2 + (K // 16)
    block_o = _pick_block_o(O, persist_row, cap=block_o)
    ck = _chunk_target(block_o, block_o * persist_row + Mp * K * 2, K)
    y = _qmm_q6k(x2, data, _f16_bits(scales), sub_scales,
                 jnp.dtype(out_dtype), block_o, ck, interpret)
    return y[:M].reshape(*lead, O)


# ---------------------------------------------------------------------------
# byte-code GEMV: sym_int8 / asym_int5 / fp8_e4m3 / fp8_e5m2
# ---------------------------------------------------------------------------
#
# One kernel for every format that stores one code byte per element:
# int8 codes decode as identity, fp8 bytes decode arithmetically from
# their bit fields (a 256-entry codebook realized with integer ops —
# Mosaic has no vector gather, and a 256-way select tree would dwarf
# the dequant math). Weights cross HBM at 1 byte/weight — half of bf16
# — and the optional per-block mins fold in as a rank-1 term exactly
# like the asym_int4 nibble kernel.

def _fp8_bits_to_f32(b, exp_bits: int, mant_bits: int, bias: int):
    """uint8 fp8 bit pattern (as int32) -> f32, integer ops only.
    Exact for every finite pattern; the encoder saturates, so inf/nan
    patterns never occur in stored weights. Subnormals decode exactly as
    sign * mant * 2^(1 - bias - mant_bits)."""
    sign = (b >> 7) & 1
    exp = (b >> mant_bits) & ((1 << exp_bits) - 1)
    mant = b & ((1 << mant_bits) - 1)
    f32_bits = (sign << 31) | ((exp + 127 - bias) << 23) | (
        mant << (23 - mant_bits))
    val = jax.lax.bitcast_convert_type(f32_bits, jnp.float32)
    sub = (1.0 - 2.0 * sign.astype(jnp.float32)) * (
        mant.astype(jnp.float32)
        * jnp.float32(2.0 ** (1 - bias - mant_bits))
    )
    return jnp.where(exp == 0, sub, val)


def _decode_bytes(wc, decode: str):
    """[bo, c] raw code bytes -> f32 values, per the static decode tag."""
    if decode == "i8":
        return wc.astype(jnp.float32)
    if decode == "e4m3":
        return _fp8_bits_to_f32(wc.astype(jnp.int32), 4, 3, 7)
    if decode == "e5m2":
        return _fp8_bits_to_f32(wc.astype(jnp.int32), 5, 2, 15)
    raise ValueError(decode)


def _kernel_bytes(x_ref, w_ref, s_ref, *rest, ck: int, block: int,
                  decode: str, has_mins: bool):
    """One O-tile of the byte-code GEMV: o = x @ (dec(w) * scale [+ m])^T,
    chunked over K in-kernel (same VMEM story as _kernel_i8)."""
    if has_mins:
        m_ref, o_ref = rest
    else:
        (o_ref,) = rest
    M = x_ref.shape[0]
    bo = w_ref.shape[0]
    K = w_ref.shape[1]
    w = w_ref[:]
    s = _f16_bits_to_f32(s_ref[:])  # [bo, K/block]
    mm = _f16_bits_to_f32(m_ref[:]) if has_mins else None
    x = x_ref[:].astype(jnp.bfloat16)

    acc = jnp.zeros((M, bo), jnp.float32)
    for c0, c in _chunks(K, ck):
        vals = _decode_bytes(_slc(w, c0, c), decode)
        sb0, nbc = c0 // block, c // block
        if has_mins:
            stacked = jnp.concatenate(
                [_slc(s, sb0, nbc), _slc(mm, sb0, nbc)], axis=0)
            exp = _expand_scales(stacked, c, block)  # [2*bo, c]
            wd = (vals * exp[:bo] + exp[bo:]).astype(jnp.bfloat16)
        else:
            wd = (vals * _expand_scales(_slc(s, sb0, nbc), c, block)
                  ).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            _slc(x, c0, c), wd, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "ck", "interpret",
                              "block", "decode", "has_mins")
)
def _qmm_bytes(x2, w, s_bits, m_bits, out_dtype, block_o: int, ck: int,
               interpret: bool, block: int, decode: str, has_mins: bool):
    M, K = x2.shape
    O = w.shape[0]
    nb = s_bits.shape[1]
    row = lambda o: (o, 0)
    in_specs = [
        pl.BlockSpec((M, K), lambda o: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((block_o, K), row, memory_space=pltpu.VMEM),
        pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),
    ]
    args = [x2, w, s_bits]
    if has_mins:
        in_specs.append(
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM))
        args.append(m_bits)
    return pl.pallas_call(
        functools.partial(_kernel_bytes, ck=ck, block=block, decode=decode,
                          has_mins=has_mins),
        grid=(O // block_o,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(*args)


def qmatmul_bytes(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] one code byte per element
    scales: jax.Array,  # [O, K // block] f16
    mins: jax.Array | None = None,  # [O, K // block] f16 (w = dec(q)*d + m)
    decode: str = "i8",  # i8 | e4m3 | e5m2
    block: int = BLOCK,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for byte-per-element formats: asym_int5
    (decode="i8" + mins) and fp8_e4m3/fp8_e5m2 (pass data bitcast to
    uint8; the 256-entry byte codebook is realized arithmetically from
    the fp8 bit fields)."""
    O, Kw = data.shape
    x2, lead, M, K, Mp, interpret = _gemv_prep(x, block_o, O, interpret)
    assert Kw == K and K % block == 0
    assert scales.shape[-1] * block == K, (scales.shape, block, K)

    has_mins = mins is not None
    persist_row = K + (K // block) * (4 if has_mins else 2)
    block_o = _pick_block_o(O, persist_row, cap=block_o)
    ck = _chunk_target(block_o, block_o * persist_row + Mp * K * 2, K,
                       temp_bpe=16 if has_mins else 12)
    y = _qmm_bytes(x2, data, _f16_bits(scales),
                   _f16_bits(mins) if has_mins else None,
                   jnp.dtype(out_dtype), block_o, ck, interpret, block,
                   decode, has_mins)
    return y[:M].reshape(*lead, O)


def qmatmul_fp8(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] float8_e4m3fn / float8_e5m2
    scales: jax.Array,  # [O, K // block] f16
    block: int = 128,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for fp8 weights: bytes cross HBM as stored
    (half the traffic of the bf16 dequant fallback) and decode in-kernel
    from the bit fields."""
    decode = "e4m3" if data.dtype == jnp.float8_e4m3fn else "e5m2"
    bits = jax.lax.bitcast_convert_type(data, jnp.uint8)
    return qmatmul_bytes(x, bits, scales, None, decode, block, out_dtype,
                         block_o, interpret)


# ---------------------------------------------------------------------------
# packed multi-plane GEMV: fp6 (4+2) / sym_int5 (4+1) / nf3 (2+1)
# and the two-level k-quants q2_k (2) / q5_k (4+1)
# ---------------------------------------------------------------------------
#
# Generalization of the nibble half-split trick (module docstring): a
# b-bit plane over N elements stores byte j = elements j + m*(N*b/8) at
# bit offset b*m, so the m-th split of every plane is a *contiguous*
# byte range unpacked with one static shift — never a strided
# deinterleave. The kernel walks chunks WITHIN the finest split (all
# coarser splits are multiples of it), so each chunk reads one
# contiguous, 128-aligned slice per plane and one slice of x.
# Eligibility (ops/linear.py table): K % (128 * finest_split_count) == 0
# — the same Mosaic lane-alignment economics that put q6_k's codes in
# int8 planes; misaligned shapes fall back to the XLA dequant path.

def _plane_layout(K: int, planes: tuple):
    """Static per-plane (data col offset, bits, splits, split elems)."""
    out = []
    off = 0
    for bits in planes:
        s = 8 // bits
        out.append((off, bits, s, K // s))
        off += K // s
    return out


def _plane_chunk_code(w, layout, e0: int, c: int):
    """Decode elements [e0, e0+c) of every plane from the concatenated
    plane array `w` [bo, total_bytes] -> int32 codes [bo, c]. e0 must not
    cross a split boundary of any plane (guaranteed by chunking within
    the finest split)."""
    code = None
    shift = 0
    for off, bits, _s, q in layout:
        mp = e0 // q
        piece = (
            _slc(w, off + e0 - mp * q, c).astype(jnp.int32) >> (bits * mp)
        ) & ((1 << bits) - 1)
        code = piece if code is None else code | (piece << shift)
        shift += bits
    return code


def _decode_code(code, decode):
    """int32 codes -> f32 values, per the static decode spec:
    ("offset", o) -> code - o; ("lut", codebook) -> select tree;
    ("e2m3",) -> fp6 arithmetic decode (exact FP6_CODEBOOK values)."""
    kind = decode[0]
    if kind == "offset":
        return (code - decode[1]).astype(jnp.float32)
    if kind == "lut":
        v = jnp.zeros(code.shape, jnp.float32)
        for i, ci in enumerate(decode[1]):
            if ci != 0.0:
                v = jnp.where(code == i, jnp.float32(ci), v)
        return v
    if kind == "e2m3":
        sign = 1.0 - 2.0 * ((code >> 5) & 1).astype(jnp.float32)
        e = (code >> 3) & 3
        m = (code & 7).astype(jnp.float32)
        pow2 = jnp.where(e == 3, 4.0, jnp.where(e == 2, 2.0, 1.0))
        mag = jnp.where(e == 0, m, (8.0 + m) * pow2) * jnp.float32(1 / 16)
        return sign * mag
    raise ValueError(decode)


def _kernel_planes(x_ref, w_ref, s_ref, o_ref, *, K: int, ck: int,
                   planes: tuple, decode: tuple, block: int):
    """One O-tile of the multi-plane GEMV with single-level per-block
    scales, chunked within the finest plane split."""
    M = x_ref.shape[0]
    bo = w_ref.shape[0]
    layout = _plane_layout(K, planes)
    qmin = min(q for _, _, _, q in layout)
    w = w_ref[:]  # concatenated plane bytes; upcast per chunk (VMEM bound)
    s = _f16_bits_to_f32(s_ref[:])  # [bo, K/block]
    x = x_ref[:].astype(jnp.bfloat16)

    acc = jnp.zeros((M, bo), jnp.float32)
    for m0 in range(K // qmin):
        for c0, c in _chunks(qmin, ck):
            e0 = m0 * qmin + c0
            vals = _decode_code(_plane_chunk_code(w, layout, e0, c), decode)
            sb0, nbc = e0 // block, c // block
            wd = (vals * _expand_scales(_slc(s, sb0, nbc), c, block)
                  ).astype(jnp.bfloat16)
            acc += jax.lax.dot_general(
                _slc(x, e0, c), wd, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "ck", "interpret",
                              "planes", "decode", "block")
)
def _qmm_planes(x2, w, s_bits, out_dtype, block_o: int, ck: int,
                interpret: bool, planes: tuple, decode: tuple, block: int):
    M, K = x2.shape
    O = w.shape[0]
    nb = s_bits.shape[1]
    wb = w.shape[1]
    row = lambda o: (o, 0)
    return pl.pallas_call(
        functools.partial(_kernel_planes, K=K, ck=ck, planes=planes,
                          decode=decode, block=block),
        grid=(O // block_o,),
        in_specs=[
            pl.BlockSpec((M, K), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, wb), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(x2, w, s_bits)


def qmatmul_planes(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K*bits/8] concatenated packed planes
    scales: jax.Array,  # [O, K // block] f16
    planes: tuple,  # per-plane bit widths, low bits first
    decode: tuple,  # ("offset", o) | ("lut", codebook) | ("e2m3",)
    block: int,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant-GEMV for packed multi-plane formats (fp6 at 6,
    sym_int5 at 5, nf3 at 3 bits/weight of HBM traffic vs 16 for the
    dequant fallback)."""
    O, wb = data.shape
    x2, lead, M, K, Mp, interpret = _gemv_prep(x, block_o, O, interpret)
    bits = sum(planes)
    assert wb * 8 == K * bits and K % (8 // min(planes)) == 0 \
        and K % block == 0

    qmin = K // max(8 // b for b in planes)
    persist_row = wb + (K // block) * 2
    block_o = _pick_block_o(O, persist_row, cap=block_o)
    ck = _chunk_target(block_o, block_o * persist_row + Mp * K * 2, qmin)
    y = _qmm_planes(x2, data, _f16_bits(scales), jnp.dtype(out_dtype),
                    block_o, ck, interpret, tuple(planes), decode, block)
    return y[:M].reshape(*lead, O)


def _kernel_planes_kq(x_ref, w_ref, d_ref, dmin_ref, sc_ref, mn_ref, o_ref,
                      *, K: int, ck: int, planes: tuple, sub: int):
    """One O-tile of the two-level asym multi-plane GEMV (q2_k / q5_k):
    w = (d*sc)*q - (dmin*mn) per `sub`-element sub-block. Same stacked
    expansion as _kernel_q4k, same plane walk as _kernel_planes."""
    M = x_ref.shape[0]
    bo = w_ref.shape[0]
    per_super = 256 // sub
    layout = _plane_layout(K, planes)
    qmin = min(q for _, _, _, q in layout)
    w = w_ref[:]
    d32 = _f16_bits_to_f32(d_ref[:])  # [bo, K/256]
    dmin32 = _f16_bits_to_f32(dmin_ref[:])
    scf = sc_ref[:].astype(jnp.float32)  # [bo, K/sub]
    mnf = mn_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.bfloat16)

    acc = jnp.zeros((M, bo), jnp.float32)
    for m0 in range(K // qmin):
        for c0, c in _chunks(qmin, ck):
            e0 = m0 * qmin + c0
            vals = _plane_chunk_code(w, layout, e0, c).astype(jnp.float32)
            sb0, nsc = e0 // sub, c // sub
            s_eff = _expand_super(d32, nsc, sb0, per_super) * (
                _slc(scf, sb0, nsc))
            m_eff = _expand_super(dmin32, nsc, sb0, per_super) * (
                _slc(mnf, sb0, nsc))
            stacked = jnp.concatenate([s_eff, m_eff], axis=0)  # [2*bo, nsc]
            exp = _expand_scales(stacked, c, sub)
            wd = (vals * exp[:bo] - exp[bo:]).astype(jnp.bfloat16)
            acc += jax.lax.dot_general(
                _slc(x, e0, c), wd, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "ck", "interpret",
                              "planes", "sub")
)
def _qmm_planes_kq(x2, w, d_bits, dmin_bits, sc, mn, out_dtype,
                   block_o: int, ck: int, interpret: bool, planes: tuple,
                   sub: int):
    M, K = x2.shape
    O = w.shape[0]
    nb = d_bits.shape[1]
    nsub = sc.shape[1]
    wb = w.shape[1]
    row = lambda o: (o, 0)
    return pl.pallas_call(
        functools.partial(_kernel_planes_kq, K=K, ck=ck, planes=planes,
                          sub=sub),
        grid=(O // block_o,),
        in_specs=[
            pl.BlockSpec((M, K), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, wb), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nb), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nsub), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, nsub), row, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(x2, w, d_bits, dmin_bits, sc, mn)


def _qmatmul_kq_planes(x, data, scales, mins, sub_scales, sub_mins,
                       planes, sub, out_dtype, block_o, interpret):
    O, wb = data.shape
    x2, lead, M, K, Mp, interpret = _gemv_prep(x, block_o, O, interpret)
    assert wb * 8 == K * sum(planes) and K % 256 == 0

    qmin = K // max(8 // b for b in planes)
    persist_row = wb + (K // 256) * 4 + (K // sub) * 2
    block_o = _pick_block_o(O, persist_row, cap=block_o)
    ck = _chunk_target(block_o, block_o * persist_row + Mp * K * 2, qmin,
                       temp_bpe=20)
    y = _qmm_planes_kq(x2, data, _f16_bits(scales), _f16_bits(mins),
                       sub_scales, sub_mins, jnp.dtype(out_dtype), block_o,
                       ck, interpret, tuple(planes), sub)
    return y[:M].reshape(*lead, O)


def qmatmul_q2k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 4] quarter-split packed 2-bit codes
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    mins: jax.Array,  # [O, K // 256] f16 super-scale dmin
    sub_scales: jax.Array,  # [O, K // 16] uint8 4-bit sc
    sub_mins: jax.Array,  # [O, K // 16] uint8 4-bit mn
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused GEMV for planar q2_k: w = (d*sc)*q - (dmin*mn) per
    16-element sub-block, 2.625 bits/weight of HBM traffic."""
    return _qmatmul_kq_planes(x, data, scales, mins, sub_scales, sub_mins,
                              (2,), 16, out_dtype, block_o, interpret)


def qmatmul_q5k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, 5K/8] half-split nibbles ++ 1-bit plane
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    mins: jax.Array,  # [O, K // 256] f16 super-scale dmin
    sub_scales: jax.Array,  # [O, K // 32] uint8 6-bit sc
    sub_mins: jax.Array,  # [O, K // 32] uint8 6-bit mn
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused GEMV for planar q5_k: q4_k's two-level math with the 5th
    code bit read from an extra packed plane (5.625 bits/weight)."""
    return _qmatmul_kq_planes(x, data, scales, mins, sub_scales, sub_mins,
                              (4, 1), 32, out_dtype, block_o, interpret)
