"""Pallas fused dequant matmul (GEMV + tiled GEMM) for packed low-bit
weights.

TPU-native counterpart of the reference's low-bit GEMM/GEMV kernels
(`xe_linear.forward_new` for prefill, `xe_batch.batch_forward` for
decode; dispatch in low_bit_linear.py:606-716 of /root/reference).

ONE kernel body serves every registered qtype and every shape class:

* decode GEMV (rows <= 32): HBM-bandwidth-bound — the win over the XLA
  fallback (dequantize to bf16, then matmul) is that W crosses HBM
  packed, e.g. 0.5 byte/weight + one f16 scale per 32 for nibble
  formats, up to ~6x less weight traffic than bf16 (measured 2.7x
  end-to-end on v5e, BENCH_NOTES r03);
* prefill / batched / QLoRA GEMM (rows > 32): the same weight tiles are
  dequantized ONCE per [block_m, block_o] tile in VMEM and fed straight
  to the MXU — no in-graph bf16 weight materialization, no HBM round
  trip of the dequantized copy.

The per-format bit decode lives in `ops/pallas/qdecode.py` (one shared
decoder for GEMV, GEMM and, later, flash epilogues — a format is a
static `DecodeSpec`); tile/chunk policy lives in `ops/pallas/tiling.py`
(pure Python, shared with `benchmark/roofline.py`'s analytic cost
model). This module is tiling + epilogue: grid over (M tiles, O tiles),
an in-kernel statically-unrolled chunk loop over K bounds live dequant
temporaries to O(block_o * chunk) regardless of K.

Layout contract (quant/numerics.py pack_nibbles / pack_planes): the
m-th split of a b-bit plane is a *contiguous* byte range unpacked with
one static shift — chunks walk logical elements within the finest plane
split, so every chunk reads one contiguous, lane-aligned slice per
plane and one slice of x (never a strided deinterleave: ~40us of XLA
prologue per call on the old interleaved layout, v5e round 3).

Mosaic constraints found on real TPU (the CPU interpreter accepts all of
these, silently):

* no f16 vector type -> scales cross as uint16 bits and are decoded to
  f32 with integer ops in-kernel (r03);
* no lane-collapsing reshape -> per-block scales expand to per-element
  via a one-hot matmul (iota compare + MXU dot), not broadcast+reshape
  (r03);
* the last two dims of every BlockSpec must be (sublane, 128)-aligned
  UNLESS the block covers the whole array dim (r05). This outlaws any
  lane-tiling of the skinny scale arrays (K/32 columns: tiles of
  112/224 lanes). The design that satisfies the rule at every real
  shape: grid over (M, O) with every operand block FULL in the lane
  dim (full-dim blocks are always legal), M tiles a multiple of 8
  sublanes, O tiles a multiple of 128 lanes, and VMEM bounded by the
  in-kernel chunk loop — per-chunk dequant temporaries are dead after
  their dot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.pallas import qdecode
from bigdl_tpu.ops.pallas.qdecode import DecodeSpec
from bigdl_tpu.ops.pallas.tiling import (
    chunk_target, finest_split, lora_operand_bytes, pick_block_m,
    pick_block_o, round_up,
)

BLOCK = 32  # quant block (elements per scale) for sym_int4; nf4/fp4 use 64

from bigdl_tpu.ops.pallas._compat import CompilerParams as _CompilerParams


def _params_parallel():
    return _CompilerParams(dimension_semantics=("parallel", "parallel"))


def _f16_bits(a: jax.Array) -> jax.Array:
    if a.dtype != jnp.float16:
        # bf16/f32 scales round-trip through f16 bits (test paths)
        a = a.astype(jnp.float16)
    return jax.lax.bitcast_convert_type(a, jnp.uint16)


# ---------------------------------------------------------------------------
# the unified kernel: one O x M tile, any DecodeSpec
# ---------------------------------------------------------------------------

def _kernel(x_ref, w_ref, *rest, K: int, ck: int, spec: DecodeSpec,
            lora: bool = False):
    """One [block_m, block_o] output tile: acc += x_chunk @ dq(W_chunk)^T
    over statically-unrolled chunks of the logical contraction axis.
    The weight tile is loaded packed and upcast PER CHUNK inside
    qdecode.decode_chunk — a hoisted full-row int32 copy would keep
    4 B/packed-byte live across the whole unrolled loop and defeat the
    O(block_o * ck) VMEM bound.

    With ``lora`` the multi-tenant LoRA epilogue folds into the same
    tile before writeback (the S-LoRA/Punica batched-adapter GEMM,
    ISSUE 18): the x tile is already in VMEM, so
    ``(x @ A_cat^T) * gate @ B_cat^T`` adds ZERO activation HBM round
    trips — the XLA fallback (ops/linear.lora_epilogue) pays two
    (re-read x, round-trip the delta). ``gate [block_m, R]`` carries the
    per-row adapter selection AND scale: row m holds scale_m in its own
    adapter group's rank-bucket columns and 0 elsewhere, which is how
    one dot pair serves a heterogeneous multi-tenant batch."""
    o_ref = rest[-1]
    if lora:
        a_ref, b_ref, g_ref = rest[-4:-1]
        side_refs = rest[:-4]
    else:
        side_refs = rest[:-1]
    side = qdecode.load_side(spec, side_refs)
    w = w_ref[:]  # packed codes [block_o, row_bytes]
    x = x_ref[:].astype(jnp.bfloat16)  # [block_m, K]

    acc = jnp.zeros((x_ref.shape[0], w_ref.shape[0]), jnp.float32)
    for e0, c in qdecode.walk(K, spec.planes, ck):
        wd = qdecode.decode_chunk(spec, K, w, side, e0, c)  # bf16 [bo, c]
        acc += jax.lax.dot_general(
            qdecode.slc(x, e0, c), wd, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    if lora:
        xa = jax.lax.dot_general(  # [block_m, R]
            x, a_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        xa = xa * g_ref[:].astype(jnp.float32)
        acc += jax.lax.dot_general(  # [block_m, block_o]
            xa.astype(jnp.bfloat16), b_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("spec", "out_dtype", "block_m", "block_o",
                              "ck", "interpret", "lora")
)
def _qmm(spec, out_dtype, block_m: int, block_o: int, ck: int,
         interpret: bool, lora: bool, x2, w, *rest):
    Mp, K = x2.shape
    O = w.shape[0]
    if lora:
        *side, la, lb, lg = rest
    else:
        side = rest
    row = lambda m, o: (o, 0)  # weight-side blocks follow the O grid dim
    in_specs = [
        pl.BlockSpec((block_m, K), lambda m, o: (m, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_o, w.shape[1]), row, memory_space=pltpu.VMEM),
    ] + [
        pl.BlockSpec((block_o, a.shape[1]), row, memory_space=pltpu.VMEM)
        for a in side
    ]
    if lora:
        # LoRA epilogue operands: A_cat rides as a FULL block (resident
        # across the whole o sweep, like the x tile), B_cat tiles follow
        # the O grid, the gate follows the M grid. Full-dim blocks keep
        # every spec legal at any rank bucket (R need not be
        # lane/sublane aligned when the block covers the whole dim).
        in_specs += [
            pl.BlockSpec((la.shape[0], K), lambda m, o: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, lb.shape[1]), row,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, lg.shape[1]), lambda m, o: (m, 0),
                         memory_space=pltpu.VMEM),
        ]
    # grid order (m, o): o innermost, so the x tile stays resident across
    # a full sweep of weight tiles and packed weights are re-fetched only
    # once per M tile (the roofline model in benchmark/roofline.py
    # assumes exactly this fetch pattern)
    return pl.pallas_call(
        functools.partial(_kernel, K=K, ck=ck, spec=spec, lora=lora),
        grid=(Mp // block_m, O // block_o),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (block_m, block_o), lambda m, o: (m, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Mp, O), out_dtype),
        compiler_params=_params_parallel(),
        interpret=interpret,
    )(x2, w, *rest)


def _validate(spec: DecodeSpec, K: int, data) -> None:
    if spec.planes:
        bits = sum(spec.planes)
        assert data.shape[-1] * 8 == K * bits, (data.shape, K, spec)
        for b in spec.planes:
            # each plane split must cover whole quant blocks, or the
            # chunked scale slicing is wrong
            assert (K // (8 // b)) % spec.block == 0, (K, spec)
    else:
        assert data.shape[-1] == K, (data.shape, K)
    assert K % spec.block == 0, (K, spec)
    if spec.super_block:
        assert K % spec.super_block == 0, (K, spec)


def _side_arrays(spec: DecodeSpec, scales, mins, sub_scales, sub_mins):
    """Wrapper-side prep of the scale arrays, in kernel argument order
    (matches qdecode.load_side). f16 scales cross as uint16 bits;
    integer sub-scales cross as stored."""
    if spec.super_block:
        if spec.mins:
            return (_f16_bits(scales), _f16_bits(mins), sub_scales, sub_mins)
        return (_f16_bits(scales), sub_scales)
    if spec.mins:
        return (_f16_bits(scales), _f16_bits(mins))
    return (_f16_bits(scales),)


def _fused(x, data, spec: DecodeSpec, side, out_dtype, block_o, interpret,
           lora=None):
    """Shared wrapper: flatten/pad rows, pick tiles, run the kernel.

    ``lora`` (optional) is the fused-epilogue operand triple
    ``(a_cat [R, K], b_cat [O, R], gate [M, R])`` — see _kernel; the
    gate is padded alongside x (zero rows contribute exactly 0)."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    O = data.shape[0]
    _validate(spec, K, data)

    M = 1
    for d in lead:
        M *= d
    block_m = pick_block_m(M, K)
    Mp = round_up(max(M, 1), block_m)
    # cast to bf16 HERE (the kernel's compute dtype anyway): halves the
    # [block_m, K] VMEM slab for GEMM row tiles
    x2 = x.reshape(M, K).astype(jnp.bfloat16)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))

    extra = ()
    lora_bytes = 0
    if lora is not None:
        a_cat, b_cat, gate = lora
        R = a_cat.shape[0]
        assert a_cat.shape == (R, K), (a_cat.shape, K)
        assert b_cat.shape == (O, R), (b_cat.shape, O, R)
        assert gate.shape == (M, R), (gate.shape, M, R)
        gate2 = gate.astype(jnp.bfloat16)
        if Mp != M:
            gate2 = jnp.pad(gate2, ((0, Mp - M), (0, 0)))
        extra = (a_cat.astype(jnp.bfloat16), b_cat.astype(jnp.bfloat16),
                 gate2)
        lora_bytes = lora_operand_bytes(R, K, 256, block_m)

    persist_row = data.shape[1] * data.dtype.itemsize + sum(
        a.shape[1] * a.dtype.itemsize for a in side)
    block_o = pick_block_o(O, persist_row, cap=block_o)
    persist = (block_o * persist_row + block_m * K * 2
               + block_m * block_o * 4 + lora_bytes)
    ck = chunk_target(block_o, persist, finest_split(K, spec.planes),
                      temp_bpe=20 if spec.mins else 14)
    y = _qmm(spec, jnp.dtype(out_dtype), block_m, block_o, ck,
             bool(interpret), lora is not None, x2, data, *side, *extra)
    return y[:M].reshape(*lead, O)


# ---------------------------------------------------------------------------
# generic QTensor entry point
# ---------------------------------------------------------------------------

def qmatmul(
    x: jax.Array,  # [..., K]
    w,  # QTensor (any registered non-dense qtype)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T, fused, for any QTensor — GEMV and
    tiled GEMM shapes alike. The decode recipe comes straight from the
    qtype registry (qdecode.spec_for), so a newly registered format with
    standard storage gets a fused kernel with no new kernel code."""
    spec = qdecode.spec_for(w.spec)
    data = w.data
    if w.spec.storage.startswith("fp8"):
        # fp8 bytes cross as stored; the kernel decodes the 256-entry
        # byte codebook arithmetically from the bit fields
        data = jax.lax.bitcast_convert_type(data, jnp.uint8)
    side = _side_arrays(spec, w.scales, w.mins, w.sub_scales, w.sub_mins)
    return _fused(x, data, spec, side, out_dtype, block_o, interpret)


def qmatmul_lora(
    x: jax.Array,  # [..., K]
    w,  # QTensor (any registered non-dense qtype)
    a_cat: jax.Array,  # [R, K] concatenated adapter A rows (bf16-able)
    b_cat: jax.Array,  # [O, R] concatenated adapter B columns
    gate: jax.Array,  # [M, R] per-row scale-in-own-group selection mask
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """``qmatmul`` with the multi-tenant LoRA epilogue fused into the
    writeback: y = x @ dq(W)^T + ((x @ A_cat^T) * gate) @ B_cat^T.

    R concatenates the rank-bucket columns of every adapter group in the
    batch (Punica's batched-adapter GEMM realized with two plain dots +
    a gate, no vector gather); ``gate[m, j] = scale_g`` iff column j
    belongs to row m's group g, else 0 — so each row receives exactly
    its own adapter's delta and adapter-less rows (gate row 0) ride
    along unchanged. Parity oracle: ops/linear.lora_epilogue added to
    the unfused qmatmul."""
    spec = qdecode.spec_for(w.spec)
    data = w.data
    if w.spec.storage.startswith("fp8"):
        data = jax.lax.bitcast_convert_type(data, jnp.uint8)
    side = _side_arrays(spec, w.scales, w.mins, w.sub_scales, w.sub_mins)
    return _fused(x, data, spec, side, out_dtype, block_o, interpret,
                  lora=(a_cat, b_cat, gate))


# ---------------------------------------------------------------------------
# per-format wrappers (stable public API; all delegate to the unified
# kernel with an explicit DecodeSpec)
# ---------------------------------------------------------------------------

def qmatmul_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (sym_int4, half-split)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int4 QTensor's fields."""
    spec = DecodeSpec(planes=(4,), value=("offset", 8), block=BLOCK)
    return _fused(x, data, spec, (_f16_bits(scales),), out_dtype, block_o,
                  interpret)


def qmatmul_codebook(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split nibbles)
    scales: jax.Array,  # [O, K // block] f16
    codebook,  # 16 static floats: value = codebook[code] * scale
    block: int = 64,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant matmul for LUT nibble formats (nf4 / fp4).

    Same HBM story as qmatmul_int4 (weights cross as packed nibbles,
    ~4x less traffic than bf16); the in-kernel decode is a 16-way
    compare/select tree over the static codebook instead of (v - 8) —
    Mosaic has no vector gather, and at GEMV arithmetic intensity the
    extra VPU selects stay under the HBM bound."""
    spec = DecodeSpec(
        planes=(4,), value=("lut", tuple(float(c) for c in codebook)),
        block=block,
    )
    return _fused(x, data, spec, (_f16_bits(scales),), out_dtype, block_o,
                  interpret)


def qmatmul_int8(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] int8 (sym_int8 / imported q8_0)
    scales: jax.Array,  # [O, K // 32] f16 (or bf16)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int8 QTensor's fields:
    weights cross HBM as int8 — half the traffic of bf16."""
    return qmatmul_bytes(x, data, scales, None, "i8", BLOCK, out_dtype,
                         block_o, interpret)


def qmatmul_asym_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split)
    scales: jax.Array,  # [O, K // 32] f16
    mins: jax.Array,  # [O, K // 32] f16 (raw block minimum; w = q*d + m)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant matmul for asym_int4: the per-block min adds one
    rank-1-per-block term, folded into the bf16 weight expansion before
    the dot (same HBM story as sym_int4 + 0.5 bit/weight for mins)."""
    spec = DecodeSpec(planes=(4,), value=("offset", 0), block=BLOCK,
                      mins=True)
    return _fused(x, data, spec, (_f16_bits(scales), _f16_bits(mins)),
                  out_dtype, block_o, interpret)


def qmatmul_q4k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (half-split)
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    mins: jax.Array,  # [O, K // 256] f16 super-scale dmin
    sub_scales: jax.Array,  # [O, K // 32] uint8 6-bit sc
    sub_mins: jax.Array,  # [O, K // 32] uint8 6-bit mn
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant matmul for planar q4_k (quant/kq_planar.py):
    w = (d*sc)*q - (dmin*mn). Weights cross HBM at 4.625 bits/weight —
    the reference's recommended quality format (README ppl table) served
    at sym_int4-class bandwidth instead of the 2.7x dequant fallback."""
    spec = DecodeSpec(planes=(4,), value=("offset", 0), block=32,
                      mins=True, super_block=256)
    return _fused(
        x, data, spec,
        (_f16_bits(scales), _f16_bits(mins), sub_scales, sub_mins),
        out_dtype, block_o, interpret)


def qmatmul_q6k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] int8 centered codes
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    sub_scales: jax.Array,  # [O, K // 16] int8 sc
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused matmul for planar q6_k: w = (d*sc)*q per 16-element
    sub-block. Planar q3_k is structurally identical (int8 centered
    codes, int8 sc per 16, f16 d per 256) and shares this wrapper."""
    spec = DecodeSpec(planes=(), value=("offset", 0), block=16,
                      super_block=256)
    return _fused(x, data, spec, (_f16_bits(scales), sub_scales),
                  out_dtype, block_o, interpret)


_BYTE_VALUES = {"i8": ("offset", 0), "e4m3": ("e4m3",), "e5m2": ("e5m2",)}


def qmatmul_bytes(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] one code byte per element
    scales: jax.Array,  # [O, K // block] f16
    mins: jax.Array | None = None,  # [O, K // block] f16 (w = dec(q)*d + m)
    decode: str = "i8",  # i8 | e4m3 | e5m2
    block: int = BLOCK,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant matmul for byte-per-element formats: sym_int8,
    asym_int5 (decode="i8" + mins) and fp8_e4m3/fp8_e5m2 (pass data
    bitcast to uint8; the 256-entry byte codebook is realized
    arithmetically from the fp8 bit fields)."""
    assert scales.shape[-1] * block == x.shape[-1], (scales.shape, block)
    spec = DecodeSpec(planes=(), value=_BYTE_VALUES[decode], block=block,
                      mins=mins is not None)
    side = ((_f16_bits(scales), _f16_bits(mins)) if mins is not None
            else (_f16_bits(scales),))
    return _fused(x, data, spec, side, out_dtype, block_o, interpret)


def qmatmul_fp8(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K] float8_e4m3fn / float8_e5m2
    scales: jax.Array,  # [O, K // block] f16
    block: int = 128,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant matmul for fp8 weights: bytes cross HBM as stored
    (half the traffic of the bf16 dequant fallback) and decode in-kernel
    from the bit fields."""
    decode = "e4m3" if data.dtype == jnp.float8_e4m3fn else "e5m2"
    bits = jax.lax.bitcast_convert_type(data, jnp.uint8)
    return qmatmul_bytes(x, bits, scales, None, decode, block, out_dtype,
                         block_o, interpret)


def qmatmul_planes(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K*bits/8] concatenated packed planes
    scales: jax.Array,  # [O, K // block] f16
    planes: tuple,  # per-plane bit widths, low bits first
    decode: tuple,  # ("offset", o) | ("lut", codebook) | ("e2m3",)
    block: int,
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused dequant matmul for packed multi-plane formats (fp6 at 6,
    sym_int5 at 5, nf3 at 3 bits/weight of HBM traffic vs 16 for the
    dequant fallback). `decode` is the qdecode value tag as-is."""
    spec = DecodeSpec(planes=tuple(planes), value=tuple(decode), block=block)
    return _fused(x, data, spec, (_f16_bits(scales),), out_dtype, block_o,
                  interpret)


def qmatmul_q2k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 4] quarter-split packed 2-bit codes
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    mins: jax.Array,  # [O, K // 256] f16 super-scale dmin
    sub_scales: jax.Array,  # [O, K // 16] uint8 4-bit sc
    sub_mins: jax.Array,  # [O, K // 16] uint8 4-bit mn
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused matmul for planar q2_k: w = (d*sc)*q - (dmin*mn) per
    16-element sub-block, 2.625 bits/weight of HBM traffic."""
    spec = DecodeSpec(planes=(2,), value=("offset", 0), block=16,
                      mins=True, super_block=256)
    return _fused(
        x, data, spec,
        (_f16_bits(scales), _f16_bits(mins), sub_scales, sub_mins),
        out_dtype, block_o, interpret)


def qmatmul_q5k(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, 5K/8] half-split nibbles ++ 1-bit plane
    scales: jax.Array,  # [O, K // 256] f16 super-scale d
    mins: jax.Array,  # [O, K // 256] f16 super-scale dmin
    sub_scales: jax.Array,  # [O, K // 32] uint8 6-bit sc
    sub_mins: jax.Array,  # [O, K // 32] uint8 6-bit mn
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused matmul for planar q5_k: q4_k's two-level math with the 5th
    code bit read from an extra packed plane (5.625 bits/weight)."""
    spec = DecodeSpec(planes=(4, 1), value=("offset", 0), block=32,
                      mins=True, super_block=256)
    return _fused(
        x, data, spec,
        (_f16_bits(scales), _f16_bits(mins), sub_scales, sub_mins),
        out_dtype, block_o, interpret)
