"""Pallas fused dequant-matmul for packed int4 weights.

TPU-native counterpart of the reference's low-bit GEMM/GEMV kernels
(`xe_linear.forward_new` for prefill, `xe_batch.batch_forward` for
decode; dispatch in low_bit_linear.py:606-716 of /root/reference).

The decode step is HBM-bandwidth-bound: y = x @ W^T with x [M, K],
M <= ~32. The win over the XLA fallback (dequantize to bf16, then
matmul) is that W crosses HBM as packed nibbles — 0.5 byte/weight + one
f16 scale per 32 — i.e. ~4x less weight traffic than bf16, which is the
entire cost of a GEMV.

Nibble layout trick: QTensor packs elements (2i, 2i+1) into one byte
(low, high nibble). Instead of re-interleaving inside the kernel (an
awkward layout change on TPU), the caller splits x into its even and odd
K columns once (x is tiny), and the kernel computes
    y = x_even @ dq(lo).T + x_odd @ dq(hi).T
so unpacked nibbles are used in the layout they already have.

Scales: one f16 per 32 contiguous weights -> per 16 packed bytes. The
kernel expands them with a broadcast+reshape (VMEM-local, no HBM cost).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.utils import round_up

BLOCK = 32  # quant block (elements per scale), fixed for sym_int4
_PACKED_PER_SCALE = BLOCK // 2


def _kernel(xe_ref, xo_ref, w_ref, s_ref, o_ref, *, block_o: int, kh: int):
    """One O-tile: o_ref[M, block_o] = xe @ lo^T + xo @ hi^T, dequantized."""
    # Mosaic can't cast uint8 directly to float; widen to int32 first.
    w = w_ref[:].astype(jnp.int32)  # [block_o, kh]
    lo = ((w & 0xF) - 8).astype(jnp.float32)
    hi = ((w >> 4) - 8).astype(jnp.float32)

    s = s_ref[:].astype(jnp.float32)  # [block_o, kh // 16]
    s = jnp.broadcast_to(
        s[:, :, None], (block_o, kh // _PACKED_PER_SCALE, _PACKED_PER_SCALE)
    ).reshape(block_o, kh)

    wl = (lo * s).astype(jnp.bfloat16)
    wh = (hi * s).astype(jnp.bfloat16)
    xe = xe_ref[:].astype(jnp.bfloat16)  # [M, kh]
    xo = xo_ref[:].astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        xe, wl, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    acc += jax.lax.dot_general(
        xo, wh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_o", "interpret")
)
def _qmm(xe, xo, w, s, out_dtype, block_o: int, interpret: bool):
    M, kh = xe.shape
    O = w.shape[0]
    grid = (O // block_o,)
    return pl.pallas_call(
        functools.partial(_kernel, block_o=block_o, kh=kh),
        grid=grid,
        in_specs=[
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((M, kh), lambda o: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_o, kh), lambda o: (o, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (block_o, kh // _PACKED_PER_SCALE), lambda o: (o, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (M, block_o), lambda o: (0, o), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((M, O), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(xe, xo, w, s)


def qmatmul_int4(
    x: jax.Array,  # [..., K]
    data: jax.Array,  # [O, K // 2] packed uint8 (sym_int4)
    scales: jax.Array,  # [O, K // 32] f16
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """y[..., O] = x @ dequant(W)^T for a sym_int4 QTensor's fields."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead, K = x.shape
    O, kh = data.shape
    assert kh * 2 == K and K % BLOCK == 0

    M = 1
    for d in lead:
        M *= d
    x2 = x.reshape(M, K)
    xe, xo = x2[:, 0::2], x2[:, 1::2]  # [M, K//2] each; tiny, XLA-side

    Mp = round_up(max(M, 1), 8)
    xe = jnp.pad(xe, ((0, Mp - M), (0, 0)))
    xo = jnp.pad(xo, ((0, Mp - M), (0, 0)))

    block_o = min(block_o, O)
    assert O % block_o == 0, f"O={O} not divisible by block_o={block_o}"

    y = _qmm(xe, xo, data, scales, jnp.dtype(out_dtype), block_o, interpret)
    return y[:M].reshape(*lead, O)
