"""Shared in-kernel dequant decoder for every packed low-bit format.

One implementation of the per-format bit decode, consumed by BOTH the
fused dequant-GEMV and the tiled dequant-GEMM kernels in
`ops/pallas/qmatmul.py` (and, later, by flash-attention epilogues) — the
format decode lives here exactly once, the matmul kernels are tiling +
epilogue.

A format is described by a static, hashable `DecodeSpec`:

* how codes are STORED — `planes=()` means one code byte per element
  (int8 codes, or fp8 bitcast to uint8) read directly from the weight
  tile; a non-empty `planes` tuple is the multi-split packed-plane
  layout of `quant/numerics.pack_planes` (half-split nibbles are just
  `planes=(4,)`);
* how codes become VALUES — `value` tag: `("offset", n)` integer codes
  minus n, `("lut", codebook)` compare/select tree (Mosaic has no
  vector gather), `("e2m3",)` fp6 arithmetic decode, `("e4m3",)` /
  `("e5m2",)` fp8 bit-field decode;
* how values are SCALED — single-level per-`block` f16 scales
  (+ optional per-block mins: w = v*d + m), or two-level k-quant
  factorization (`super_block`=256): w = (d*sc)*v [- (dmin*mn)] per
  `block`-element sub-block.

Mosaic constraints baked in (found on real TPU — the CPU interpreter
accepts everything, silently; see qmatmul.py's module docstring for the
measurement history):

* no f16 vector type -> f16 scales cross as uint16 bits, decoded to f32
  with integer ops (`f16_bits_to_f32`); subnormals decode exactly — NOT
  flushed (k-quant super-scales routinely land below 6.1e-5);
* no lane-collapsing reshape -> per-block scales expand to per-element
  via a one-hot matmul (iota compare + MXU dot), not broadcast+reshape;
* no vector gather -> codebooks are compare/select trees, fp8/fp6 decode
  arithmetically from their bit fields.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.pallas.tiling import chunk_spans, finest_split


@dataclasses.dataclass(frozen=True)
class DecodeSpec:
    """Static decode recipe for one qtype (hashable: jit/kernel key)."""
    planes: tuple  # () = byte-per-element codes; else packed bit planes
    value: tuple  # ("offset", n) | ("lut", codes) | ("e2m3",) | ("e4m3",) | ("e5m2",)
    block: int  # scale block (single-level) or sub-block (two-level)
    mins: bool = False  # per-(sub-)block min/offset term
    super_block: int = 0  # 256 for k-quants, 0 = single-level scales

    @property
    def n_side(self) -> int:
        """Number of scale-side arrays accompanying the weight tile."""
        if self.super_block:
            return 4 if self.mins else 2
        return 2 if self.mins else 1


def spec_for(qspec) -> DecodeSpec:
    """DecodeSpec for a `quant.qtypes.QTypeSpec` — the one mapping from
    storage metadata to in-kernel decode recipe."""
    if qspec.storage == "packed_u8":
        planes = (4,)
    elif qspec.storage == "packed_planes":
        planes = tuple(qspec.planes)
    else:  # int8 / fp8_* byte codes
        planes = ()
    if qspec.storage == "fp8_e4m3":
        value = ("e4m3",)
    elif qspec.storage == "fp8_e5m2":
        value = ("e5m2",)
    elif qspec.name == "fp6":
        value = ("e2m3",)  # exact arithmetic form of FP6_CODEBOOK
    elif qspec.codebook is not None:  # nf4 / fp4 / nf3
        value = ("lut", tuple(float(c) for c in qspec.codebook))
    elif qspec.name == "sym_int4":
        value = ("offset", 8)
    elif qspec.name == "sym_int5":
        value = ("offset", 16)
    else:  # raw codes: asym (mins carry the offset) / centered int8
        value = ("offset", 0)
    return DecodeSpec(
        planes=planes, value=value, block=qspec.block_size,
        mins=qspec.asymmetric, super_block=qspec.superblock or 0,
    )


# ---------------------------------------------------------------------------
# bit-level helpers (integer ops only — Mosaic vector-type constraints)
# ---------------------------------------------------------------------------

def f16_bits_to_f32(bits):
    """uint16 float16 bit pattern -> f32, integer ops only (Mosaic has no
    f16 vectors). Subnormal f16 decodes exactly as sign * mant * 2^-24 —
    NOT flushed: k-quant super-scales d = max|sub_scale|/127 routinely
    land below 6.1e-5 for real checkpoint magnitudes (caught by the q6_k
    kernel equivalence test: flushing zeroed whole super-blocks)."""
    b = bits.astype(jnp.int32)
    sign = (b >> 15) & 1
    exp = (b >> 10) & 0x1F
    mant = b & 0x3FF
    f32_bits = (sign << 31) | ((exp + 127 - 15) << 23) | (mant << 13)
    val = jax.lax.bitcast_convert_type(f32_bits, jnp.float32)
    sub = (1.0 - 2.0 * sign.astype(jnp.float32)) * (
        mant.astype(jnp.float32) * jnp.float32(2.0 ** -24)
    )
    return jnp.where(exp == 0, sub, val)


def fp8_bits_to_f32(b, exp_bits: int, mant_bits: int, bias: int):
    """uint8 fp8 bit pattern (as int32) -> f32, integer ops only.
    Exact for every finite pattern; the encoder saturates, so inf/nan
    patterns never occur in stored weights. Subnormals decode exactly as
    sign * mant * 2^(1 - bias - mant_bits)."""
    sign = (b >> 7) & 1
    exp = (b >> mant_bits) & ((1 << exp_bits) - 1)
    mant = b & ((1 << mant_bits) - 1)
    f32_bits = (sign << 31) | ((exp + 127 - bias) << 23) | (
        mant << (23 - mant_bits))
    val = jax.lax.bitcast_convert_type(f32_bits, jnp.float32)
    sub = (1.0 - 2.0 * sign.astype(jnp.float32)) * (
        mant.astype(jnp.float32)
        * jnp.float32(2.0 ** (1 - bias - mant_bits))
    )
    return jnp.where(exp == 0, sub, val)


def expand_scales(s, ck: int, block: int):
    """[rows, nbc] per-block scales -> [rows, ck] per-element for one
    chunk whose start is block-aligned: element j belongs to local block
    j // block. One-hot matmul: iota/compare/dot only."""
    nbc = s.shape[-1]
    sel = (
        jax.lax.broadcasted_iota(jnp.int32, (nbc, ck), 1) // block
        == jax.lax.broadcasted_iota(jnp.int32, (nbc, ck), 0)
    ).astype(jnp.float32)
    return jax.lax.dot_general(
        s, sel, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def expand_super(d, n_sub: int, offset_sub: int, per_super: int):
    """[bo, nb_super] f32 super-scales -> [bo, n_sub] per-sub-block:
    sub-block s (global index s + offset_sub) belongs to super-block
    (s + offset_sub) // per_super. One-hot matmul (iota/compare/dot);
    the offset form handles chunks that start mid-super-block (odd
    super-block counts, e.g. llama2's K=11008 -> 43 blocks per row)."""
    nb = d.shape[-1]
    sel = (
        (jax.lax.broadcasted_iota(jnp.int32, (nb, n_sub), 1) + offset_sub)
        // per_super
        == jax.lax.broadcasted_iota(jnp.int32, (nb, n_sub), 0)
    ).astype(jnp.float32)
    return jax.lax.dot_general(
        d, sel, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def slc(a, c0: int, ck: int):
    """Static lane-dim slice of a loaded rank-2 array."""
    return jax.lax.slice(a, (0, c0), (a.shape[0], c0 + ck))


# ---------------------------------------------------------------------------
# packed-plane layout (the multi-split generalization of pack_nibbles)
# ---------------------------------------------------------------------------
#
# A b-bit plane over N elements stores byte j = elements j + m*(N*b/8)
# at bit offset b*m, so the m-th split of every plane is a *contiguous*
# byte range unpacked with one static shift — never a strided
# deinterleave. Chunk walks stay WITHIN the finest split (all coarser
# splits are multiples of it), so each chunk reads one contiguous,
# lane-aligned slice per plane and one slice of x.

def plane_layout(K: int, planes: tuple):
    """Static per-plane (data col offset, bits, splits, split elems)."""
    out = []
    off = 0
    for bits in planes:
        s = 8 // bits
        out.append((off, bits, s, K // s))
        off += K // s
    return out


def plane_chunk_code(w, layout, e0: int, c: int):
    """Decode elements [e0, e0+c) of every plane from the concatenated
    plane array `w` [bo, total_bytes] -> int32 codes [bo, c]. e0 must not
    cross a split boundary of any plane (guaranteed by chunking within
    the finest split)."""
    code = None
    shift = 0
    for off, bits, _s, q in layout:
        mp = e0 // q
        piece = (
            slc(w, off + e0 - mp * q, c).astype(jnp.int32) >> (bits * mp)
        ) & ((1 << bits) - 1)
        code = piece if code is None else code | (piece << shift)
        shift += bits
    return code


def walk(K: int, planes: tuple, ck: int):
    """Static (e0, c) chunk spans over the logical element axis, never
    crossing a plane-split boundary."""
    qmin = finest_split(K, planes)
    for m0 in range(K // qmin):
        for c0, c in chunk_spans(qmin, ck):
            yield m0 * qmin + c0, c


# ---------------------------------------------------------------------------
# code -> value decode
# ---------------------------------------------------------------------------

def decode_values(code, value: tuple):
    """Codes (int32 plane codes, or raw int8/uint8 byte codes) -> f32
    values, per the static `value` tag."""
    kind = value[0]
    if kind == "offset":
        if value[1] == 0:
            return code.astype(jnp.float32)
        return (code.astype(jnp.int32) - value[1]).astype(jnp.float32)
    if kind == "lut":  # select tree: Mosaic has no vector gather
        c = code.astype(jnp.int32)
        v = jnp.zeros(c.shape, jnp.float32)
        for i, ci in enumerate(value[1]):
            if ci != 0.0:
                v = jnp.where(c == i, jnp.float32(ci), v)
        return v
    if kind == "e2m3":  # fp6: exact arithmetic form of FP6_CODEBOOK
        c = code.astype(jnp.int32)
        sign = 1.0 - 2.0 * ((c >> 5) & 1).astype(jnp.float32)
        e = (c >> 3) & 3
        m = (c & 7).astype(jnp.float32)
        pow2 = jnp.where(e == 3, 4.0, jnp.where(e == 2, 2.0, 1.0))
        mag = jnp.where(e == 0, m, (8.0 + m) * pow2) * jnp.float32(1 / 16)
        return sign * mag
    if kind == "e4m3":
        return fp8_bits_to_f32(code.astype(jnp.int32), 4, 3, 7)
    if kind == "e5m2":
        return fp8_bits_to_f32(code.astype(jnp.int32), 5, 2, 15)
    raise ValueError(value)


# ---------------------------------------------------------------------------
# the decoder: weight tile + side arrays -> bf16 weight chunk
# ---------------------------------------------------------------------------

def load_side(spec: DecodeSpec, refs):
    """Load + bit-decode the scale-side refs once per kernel invocation
    (persistent across the chunk loop). Returns the in-VMEM f32 arrays
    `decode_chunk` slices per chunk."""
    if spec.super_block:
        if spec.mins:
            d, dmin, sc, mn = refs
            return (f16_bits_to_f32(d[:]), f16_bits_to_f32(dmin[:]),
                    sc[:].astype(jnp.float32), mn[:].astype(jnp.float32))
        d, sc = refs
        return (f16_bits_to_f32(d[:]), sc[:].astype(jnp.float32))
    if spec.mins:
        s, m = refs
        return (f16_bits_to_f32(s[:]), f16_bits_to_f32(m[:]))
    (s,) = refs
    return (f16_bits_to_f32(s[:]),)


def decode_kv(codes, scale=None, value: tuple = ("e5m2",)):
    """The ONE attention-epilogue KV decode body, shared by
    flash_attention / paged_attention / flash_backward (the in-kernel
    fp8 dequant used to be duplicated in each kernel; graftlint's
    dispatch-consistency family guards against it reappearing).

    `codes` is a loaded KV tile in any layout:

    * uint8 — fp8 bit patterns (the flash wrapper bitcasts the fp8 cache
      before pallas_call, the same move qmatmul makes for fp8 weight
      storage): decoded through `decode_values`/`fp8_bits_to_f32`, the
      SAME bit decoder the fused GEMM/GEMV/backward kernels use for fp8
      weights, so attention and GEMM formats cannot drift;
    * typed fp8 — decoded by dtype conversion (paged attention keeps the
      pool typed: bitcasting [L, n_pages, ...] per decode step would
      copy the whole pool in HBM). Both arms are EXACT on every finite
      fp8 pattern, so they are bit-identical by construction (asserted
      by tests/test_qbackward.py's unification parity test);
    * anything else (bf16 cache) — f32 passthrough, `scale` normally
      None.

    `scale` broadcasts against the decoded tile (trailing singleton
    conventions are the caller's); None skips the multiply entirely, so
    unquantized paths pay nothing."""
    if codes.dtype == jnp.uint8:
        vals = decode_values(codes.astype(jnp.int32), value)
    else:
        vals = codes.astype(jnp.float32)
    if scale is None:
        return vals
    return vals * scale


def decode_chunk(spec: DecodeSpec, K: int, w, side, e0: int, c: int):
    """bf16 weight chunk [bo, c] for logical elements [e0, e0+c) of an
    O-tile: codes from the weight tile, values per the decode tag,
    scales expanded per-element via one-hot dots. e0 is block-aligned
    (walk() chunks within plane splits at 128-multiples)."""
    if spec.planes:
        code = plane_chunk_code(w, plane_layout(K, spec.planes), e0, c)
    else:
        code = slc(w, e0, c)
    vals = decode_values(code, spec.value)
    bo = w.shape[0]
    sb0, nsc = e0 // spec.block, c // spec.block

    if spec.super_block:
        per_super = spec.super_block // spec.block
        d32 = side[0]
        if spec.mins:
            _, dmin32, scf, mnf = side
            s_eff = expand_super(d32, nsc, sb0, per_super) * slc(scf, sb0, nsc)
            m_eff = expand_super(dmin32, nsc, sb0, per_super) * slc(mnf, sb0, nsc)
            # the two per-element expansions share one (nsc, c) sel via a
            # single stacked dot
            exp = expand_scales(
                jnp.concatenate([s_eff, m_eff], axis=0), c, spec.block)
            return (vals * exp[:bo] - exp[bo:]).astype(jnp.bfloat16)
        scf = side[1]
        s_eff = expand_super(d32, nsc, sb0, per_super) * slc(scf, sb0, nsc)
        return (vals * expand_scales(s_eff, c, spec.block)
                ).astype(jnp.bfloat16)

    if spec.mins:  # w = v*d + m (raw block minimum, `+ m` convention)
        s, m = side
        exp = expand_scales(
            jnp.concatenate([slc(s, sb0, nsc), slc(m, sb0, nsc)], axis=0),
            c, spec.block)
        return (vals * exp[:bo] + exp[bo:]).astype(jnp.bfloat16)
    (s,) = side
    return (vals * expand_scales(slc(s, sb0, nsc), c, spec.block)
            ).astype(jnp.bfloat16)
