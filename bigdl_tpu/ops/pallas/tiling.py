"""Tile / chunk policy for the fused dequant matmul family — pure
Python, no jax use at module level.

Shared by two consumers that must never disagree:

* `ops/pallas/qmatmul.py` picks its real Pallas block shapes here;
* `benchmark/roofline.py` evaluates the analytic bytes-moved / FLOPs
  model **at the same block shapes** on any machine, no device (and no
  jax) required — the first increment of the ROADMAP
  "hardware-independent perf gate".

The policy encodes the Mosaic rules the kernels were built around
(module docstring of qmatmul.py): output tiles never below 128 lanes,
full-lane operand blocks, and live VMEM bounded by an in-kernel
statically-unrolled chunk loop over K.
"""

from __future__ import annotations

from bigdl_tpu.utils import round_up  # noqa: F401  (re-exported policy dep)

VMEM_BUDGET = 10 * 1024 * 1024  # leave scoped-VMEM headroom under 16 MiB

# x row-tile slab cap: the [block_m, K] activation block must leave room
# for the weight tile + per-chunk dequant temporaries in the budget
_X_SLAB_BYTES = 3 * 1024 * 1024 + 512 * 1024


def finest_split(K: int, planes: tuple) -> int:
    """Elements per split of the finest packed plane — the chunk-walk
    period of the dequant kernels. Byte-per-element storage (planes=())
    has a single 'split' covering all of K."""
    if not planes:
        return K
    return K // max(8 // b for b in planes)


def chunk_spans(total: int, target: int):
    """Static chunk spans (start, size) covering [0, total); every
    boundary is a multiple of 128 (x/w lane alignment) when total is,
    and therefore aligned to the 16/32/64-element scale blocks.
    256-element SUPER-block boundaries are NOT respected (128-multiples
    can start mid-super-block, e.g. c0=6144 in kh=7168) — super-scale
    expansion must use the offset form of `qdecode.expand_super`."""
    spans = []
    c0 = 0
    while c0 < total:
        ck = min(target, total - c0)
        spans.append((c0, ck))
        c0 += ck
    return spans


def pick_block_o(O: int, persist_per_row: int, cap: int = 256) -> int:
    """Largest lane-legal O tile: a multiple of 128 dividing O (256
    preferred, 128 if the per-row persistent footprint is large or the
    caller caps it), else the full dim (always legal — Mosaic pads)."""
    for bo in (256, 128):
        if bo <= cap and O % bo == 0 and (
            bo * persist_per_row <= VMEM_BUDGET // 2
        ):
            return bo
    if O % 128 == 0:
        return 128
    return O


def pick_block_m(M: int, K: int, x_bpe: int = 2) -> int:
    """Row tile for the M grid dimension.

    Decode shapes (M <= ~32) keep the established GEMV contract: the
    whole padded-M extent as ONE block (grid_m == 1), identical to the
    silicon-validated 1-D-grid kernels. Above that, the largest
    MXU-friendly power-of-two tile whose [block_m, K] x-slab fits the
    VMEM allowance — weights are re-fetched once per M tile, so bigger
    tiles amortize packed-weight HBM traffic."""
    mp8 = round_up(max(M, 1), 8)
    if mp8 <= 256 and mp8 * K * x_bpe <= _X_SLAB_BYTES:
        return mp8
    for bm in (256, 128, 64, 32, 16):
        if bm < mp8 and bm * K * x_bpe <= _X_SLAB_BYTES:
            return bm
    return 8


def chunk_target(block_o: int, persist_bytes: int, kh: int,
                 temp_bpe: int = 12) -> int:
    """Largest chunk whose per-chunk temporaries (temp_bpe B/element of
    dequant intermediates — decoded codes + expanded scales in f32 plus
    the bf16 weight tile — plus the one-hot sel) fit beside the
    persistent blocks in the scoped-VMEM budget."""
    for ck in (2048, 1024, 512, 256, 128):
        if ck > kh:
            continue
        temp = block_o * ck * temp_bpe + (ck // 16) * ck * 4
        if persist_bytes + temp <= VMEM_BUDGET:
            return ck
    return 128
