"""Tile / chunk policy for the fused dequant matmul family — pure
Python, no jax use at module level.

Shared by two consumers that must never disagree:

* `ops/pallas/qmatmul.py` picks its real Pallas block shapes here;
* `benchmark/roofline.py` evaluates the analytic bytes-moved / FLOPs
  model **at the same block shapes** on any machine, no device (and no
  jax) required — the first increment of the ROADMAP
  "hardware-independent perf gate".

The policy encodes the Mosaic rules the kernels were built around
(module docstring of qmatmul.py): output tiles never below 128 lanes,
full-lane operand blocks, and live VMEM bounded by an in-kernel
statically-unrolled chunk loop over K.
"""

from __future__ import annotations

from bigdl_tpu.utils import round_up  # noqa: F401  (re-exported policy dep)

VMEM_BUDGET = 10 * 1024 * 1024  # leave scoped-VMEM headroom under 16 MiB

# x row-tile slab cap: the [block_m, K] activation block must leave room
# for the weight tile + per-chunk dequant temporaries in the budget
_X_SLAB_BYTES = 3 * 1024 * 1024 + 512 * 1024


def finest_split(K: int, planes: tuple) -> int:
    """Elements per split of the finest packed plane — the chunk-walk
    period of the dequant kernels. Byte-per-element storage (planes=())
    has a single 'split' covering all of K."""
    if not planes:
        return K
    return K // max(8 // b for b in planes)


def chunk_spans(total: int, target: int):
    """Static chunk spans (start, size) covering [0, total); every
    boundary is a multiple of 128 (x/w lane alignment) when total is,
    and therefore aligned to the 16/32/64-element scale blocks.
    256-element SUPER-block boundaries are NOT respected (128-multiples
    can start mid-super-block, e.g. c0=6144 in kh=7168) — super-scale
    expansion must use the offset form of `qdecode.expand_super`."""
    spans = []
    c0 = 0
    while c0 < total:
        ck = min(target, total - c0)
        spans.append((c0, ck))
        c0 += ck
    return spans


def pick_block_o(O: int, persist_per_row: int, cap: int = 256) -> int:
    """Largest lane-legal O tile: a multiple of 128 dividing O (256
    preferred, 128 if the per-row persistent footprint is large or the
    caller caps it), else the full dim (always legal — Mosaic pads)."""
    for bo in (256, 128):
        if bo <= cap and O % bo == 0 and (
            bo * persist_per_row <= VMEM_BUDGET // 2
        ):
            return bo
    if O % 128 == 0:
        return 128
    return O


def pick_block_m(M: int, K: int, x_bpe: int = 2) -> int:
    """Row tile for the M grid dimension.

    Decode shapes (M <= ~32) keep the established GEMV contract: the
    whole padded-M extent as ONE block (grid_m == 1), identical to the
    silicon-validated 1-D-grid kernels. Above that, the largest
    MXU-friendly power-of-two tile whose [block_m, K] x-slab fits the
    VMEM allowance — weights are re-fetched once per M tile, so bigger
    tiles amortize packed-weight HBM traffic."""
    mp8 = round_up(max(M, 1), 8)
    if mp8 <= 256 and mp8 * K * x_bpe <= _X_SLAB_BYTES:
        return mp8
    for bm in (256, 128, 64, 32, 16):
        if bm < mp8 and bm * K * x_bpe <= _X_SLAB_BYTES:
            return bm
    return 8


# ---------------------------------------------------------------------------
# backward tile policy — shared by ops/pallas/qbackward.py (the fused
# low-bit dx/dW kernels) and benchmark/roofline.py's analytic backward
# costs. The dx kernel's transposed access pattern (contract over the
# weight's O rows, accumulate a full-K output row tile across the o
# sweep) keeps a [block_m, K] f32 accumulator PLUS the bf16 output
# block resident per grid cell, so its row-tile slab is priced at
# DX_ACC_BPE, not the forward's 2 B/element x slab.
# ---------------------------------------------------------------------------

#: resident bytes per dx element per grid cell: the f32 accumulator the
#: o sweep updates (4) + the bf16 output block written on the last step
#: (2). The forward's bf16 x slab has no cross-step accumulator.
DX_ACC_BPE = 6

#: dx accumulator-slab allowance: larger than the forward's x slab
#: (the acc IS the kernel's working set — weight tiles and dequant
#: temporaries are the small residents here), but strictly inside
#: VMEM_BUDGET so the chunk loop always has headroom (DSP005 audits
#: this invariant).
_DX_SLAB_BYTES = 6 * 1024 * 1024 + 512 * 1024


def pick_block_m_dx(M: int, K: int) -> int:
    """Row tile of the fused dx kernel's (m, o) grid.

    Same shape rules as `pick_block_m` (8-sublane multiples, prefer the
    whole padded extent for decode-class M, else the largest power of
    two) but sized against the [block_m, K] f32-accumulator + bf16-out
    slab at DX_ACC_BPE. Bigger tiles matter MORE here than in the
    forward: packed weights are re-fetched once per M tile, and the
    backward's weight sweep is the traffic the fusion exists to kill."""
    mp8 = round_up(max(M, 1), 8)
    if mp8 <= 256 and mp8 * K * DX_ACC_BPE <= _DX_SLAB_BYTES:
        return mp8
    for bm in (256, 128, 64, 32, 16):
        if bm < mp8 and bm * K * DX_ACC_BPE <= _DX_SLAB_BYTES:
            return bm
    return 8


def chunk_target_dx(block_o: int, block_m: int, persist_bytes: int,
                    kh: int, temp_bpe: int = 14) -> int:
    """`chunk_target` for the dx kernel: the per-chunk temporaries gain
    the [block_m, ck] f32 partial-product tile (the dot's result before
    it folds into the accumulator) on top of the dequant intermediates,
    so the chunk budget must charge both."""
    for ck in (2048, 1024, 512, 256, 128):
        if ck > kh:
            continue
        temp = (block_o * ck * temp_bpe + (ck // 16) * ck * 4
                + block_m * ck * 4)
        if persist_bytes + temp <= VMEM_BUDGET:
            return ck
    return 128


def pick_block_o_dw(O: int, K: int) -> int:
    """Output-row tile of the fused dW kernel's (o, m) grid: dW[O, K] =
    g^T @ x accumulates a [block_o, K] f32 tile across the m sweep —
    the same accumulator-slab shape as dx with O in the row seat."""
    op8 = round_up(max(O, 1), 8)
    if op8 <= 256 and op8 * K * DX_ACC_BPE <= _DX_SLAB_BYTES:
        return op8
    for bo in (256, 128, 64, 32, 16):
        if bo < op8 and bo * K * DX_ACC_BPE <= _DX_SLAB_BYTES:
            return bo
    return 8


# ---------------------------------------------------------------------------
# LoRA epilogue policy — shared by ops/pallas/qmatmul.py (the fused
# epilogue's operand blocks) and benchmark/roofline.py / sim/cost.py's
# analytic LoRA cost, extending the "never disagree" contract to the
# S-LoRA serving path (ISSUE 18)
# ---------------------------------------------------------------------------

#: bytes/element of the LoRA operands inside the kernel (A/B/gate cross
#: as bf16; the xa intermediate is f32)
LORA_BPE = 2

#: persistent-VMEM allowance for the fused epilogue's operands: they
#: ride INSIDE the dequant-GEMM's existing budget, so they must stay a
#: small fraction of it or the chunk loop collapses to its floor
LORA_VMEM_CAP = 4 * 1024 * 1024


def lora_operand_bytes(R: int, K: int, O_block: int, M_block: int) -> int:
    """Persistent VMEM the fused LoRA epilogue adds to one grid step:
    A_cat [R, K] (full block, resident across the o sweep), one B_cat
    tile [O_block, R], the per-row gate tile [M_block, R], and the f32
    xa intermediate [M_block, R]."""
    return (R * K * LORA_BPE + O_block * R * LORA_BPE
            + M_block * R * LORA_BPE + M_block * R * 4)


def lora_fused_ok(R: int, K: int) -> bool:
    """Eligibility of the fused-epilogue path for a total LoRA width R
    (= sum of rank-bucket columns across the batch's adapter groups):
    the operands must fit the epilogue allowance at the largest tiles
    the GEMM can pick (256 x 256)."""
    return R > 0 and lora_operand_bytes(R, K, 256, 256) <= LORA_VMEM_CAP


# ---------------------------------------------------------------------------
# attention tile policy — shared by ops/pallas/flash_attention.py (the
# kernel's default block shapes) and benchmark/roofline.py's analytic
# attention costs, so the sim's cost model and the implementation cannot
# drift (the qmatmul/roofline contract, extended to attention; ISSUE 13)
# ---------------------------------------------------------------------------

#: Mosaic lane width: flash pads head_dim to a multiple of this, and no
#: operand tile goes below it in the lane dimension
MOSAIC_LANES = 128

#: flash attention default q/k block edge (clamped to the padded
#: sequence extents by `flash_blocks`)
FLASH_BLOCK_Q = 128
FLASH_BLOCK_K = 128


def flash_blocks(T: int, S: int,
                 block_q: int = FLASH_BLOCK_Q,
                 block_k: int = FLASH_BLOCK_K) -> tuple:
    """The (block_q, block_k) flash_attention actually runs at for a
    [T] x [S] problem: the policy default clamped to the 16-padded
    sequence extents (short prefills run one small block per axis)."""
    return (min(block_q, round_up(T, 16)), min(block_k, round_up(S, 16)))


def flash_live_blocks(T: int, S: int, block_q: int, block_k: int,
                      q_offset: int = 0, causal: bool = True,
                      window=None) -> int:
    """Number of (i, j) grid blocks the flash kernel COMPUTES (the rest
    are skipped via pl.when) — the same liveness predicate as
    flash_attention._kernel, evaluated statically. q slot t attends kv
    slot j iff j <= q_offset + t (causal) and j > q_offset + t - window.
    Per-row `start` padding is ignored (it masks lanes, not blocks)."""
    Tp, Sp = round_up(T, block_q), round_up(S, block_k)
    n_q, n_k = Tp // block_q, Sp // block_k
    live = 0
    for i in range(n_q):
        for j in range(n_k):
            ok = True
            if causal:
                row_max = q_offset + (i + 1) * block_q - 1
                ok = j * block_k <= row_max
            if ok and window is not None:
                row_min = q_offset + i * block_q
                ok = (j + 1) * block_k - 1 > row_min - window
            live += bool(ok)
    return live


def chunk_target(block_o: int, persist_bytes: int, kh: int,
                 temp_bpe: int = 12) -> int:
    """Largest chunk whose per-chunk temporaries (temp_bpe B/element of
    dequant intermediates — decoded codes + expanded scales in f32 plus
    the bf16 weight tile — plus the one-hot sel) fit beside the
    persistent blocks in the scoped-VMEM budget."""
    for ck in (2048, 1024, 512, 256, 128):
        if ck > kh:
            continue
        temp = block_o * ck * temp_bpe + (ck // 16) * ck * 4
        if persist_bytes + temp <= VMEM_BUDGET:
            return ck
    return 128
