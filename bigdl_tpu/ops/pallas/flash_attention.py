"""Pallas flash attention (TPU) with online softmax.

TPU-native replacement for the reference's fused SDP kernels
(`xe_addons.sdp / sdp_causal / sdp_non_causal`, call sites
models/common.py:222-258 in /root/reference): one kernel covers causal
attention over a left-padded KV cache, GQA head grouping, optional
sliding window and logit softcap (gemma2), without ever materializing
the [T, S] score matrix in HBM.

Layout: q [B, T, Hq, D]; k, v [B, S, Hkv, D] (the KV-cache layout).
`start[b]` is the first valid cache slot of row b (left padding);
`q_offset` is the global cache slot of q position 0 (= cache.pos at
entry). Query slot t attends kv slot j iff
    start[b] <= j <= q_offset + t          (causal)
    and j > q_offset + t - window          (if sliding window).

Grid is (B, Hq, nQ, nK) with the K axis innermost ("arbitrary"
semantics); m/l/acc accumulators live in VMEM scratch and the output
block is written once on the last K step. K blocks entirely above the
causal diagonal are skipped via `pl.when`, so causal costs ~half of
full attention, matching a hand-scheduled kernel.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.pallas import qdecode
from bigdl_tpu.ops.pallas.tiling import (
    FLASH_BLOCK_K, FLASH_BLOCK_Q, MOSAIC_LANES, flash_blocks,
)
from bigdl_tpu.utils import round_up

_NEG_INF = -1e30
# lane width + block policy live in tiling.py (jax-free) so the
# analytic attention roofline evaluates at the kernel's REAL tiles
_LANES = MOSAIC_LANES

from bigdl_tpu.ops.pallas._compat import CompilerParams as _CompilerParams


def _kernel(
    start_ref,  # SMEM [B] int32: per-row pad offsets (indexed by program_id)
    qoff_ref,  # SMEM [1] int32: global slot of q position 0
    q_ref,  # VMEM [1, 1, BQ, D]
    k_ref,  # VMEM [1, 1, BK, D]
    v_ref,  # VMEM [1, 1, BK, D]
    *refs,  # (+ ks/vs VMEM [1, 1, BK, 1] f32 when quantized) o, scratch
    scale: float,
    block_q: int,
    block_k: int,
    causal: bool,
    window: Optional[int],
    softcap: Optional[float],
    quantized: bool,
    kv_value: tuple,
):
    if quantized:  # fp8 KV: per-(slot, head) f32 scales ride alongside
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    b = pl.program_id(0)
    i, j = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qoff = qoff_ref[0]
    row_max = qoff + (i + 1) * block_q - 1  # largest global q slot in block
    # K block is live unless entirely above the causal diagonal / outside
    # the sliding window of every query row in this Q block.
    live = jnp.bool_(True)
    if causal:
        live = live & (j * block_k <= row_max)
    if window is not None:
        row_min = qoff + i * block_q
        live = live & ((j + 1) * block_k - 1 > row_min - window)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, D]
        # shared KV decode body (fp8 codes cross as uint8 bits and go
        # through the same qdecode bit decoder as fp8 GEMM weights);
        # the [BK, 1] scale broadcasts over D
        k = qdecode.decode_kv(
            k_ref[0, 0], ks_ref[0, 0] if quantized else None, kv_value
        )  # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap

        rows = qoff + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = cols >= start_ref[b]
        if causal:
            valid = valid & (cols <= rows)
        if window is not None:
            valid = valid & (cols > rows - window)
        s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_scr[:, :1]  # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # exp(-1e30 - (-1e30)) = 1 on fully-masked rows; zero explicitly.
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)  # [BQ, BK]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = qdecode.decode_kv(
            v_ref[0, 0], vs_ref[0, 0] if quantized else None, kv_value
        )  # [BK, D]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_k - 1)
    def _finalize():
        l = l_scr[:, :1]
        out = acc_scr[:] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "window", "softcap", "scale", "block_q", "block_k", "interpret"
    ),
)
def _flash(
    q, k, v, start, q_offset, k_scale, v_scale,
    causal: bool, window: Optional[int], softcap: Optional[float],
    scale: float, block_q: int, block_k: int, interpret: bool,
):
    B, Hq, T, D = q.shape
    _, Hkv, S, _ = k.shape
    group = Hq // Hkv
    n_q, n_k = T // block_q, S // block_k
    quantized = k_scale is not None
    kv_value = ("e4m3",) if k.dtype == jnp.float8_e4m3fn else ("e5m2",)
    if quantized:
        # fp8 codes cross the pallas_call boundary as uint8 bit patterns
        # (the qmatmul fp8-weight move): in-kernel they decode through
        # the shared qdecode body, exactly the GEMM formats' decoder
        k = jax.lax.bitcast_convert_type(k, jnp.uint8)
        v = jax.lax.bitcast_convert_type(v, jnp.uint8)

    grid = (B, Hq, n_q, n_k)
    kernel = functools.partial(
        _kernel,
        scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, softcap=softcap, quantized=quantized,
        kv_value=kv_value,
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, i, j: (b, h // group, j, 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [
        pl.BlockSpec((B,), lambda b, h, i, j: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((1,), lambda b, h, i, j: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0),
            memory_space=pltpu.VMEM,
        ),
        kv_spec, kv_spec,
    ]
    args = [start, q_offset, q, k, v]
    if quantized:
        # [B, Hkv, S, 1] f32: a trailing singleton keeps the block rank-2
        # in (sublane, lane) with a full-dim lane (always legal)
        sc_spec = pl.BlockSpec(
            (1, 1, block_k, 1), lambda b, h, i, j: (b, h // group, j, 0),
            memory_space=pltpu.VMEM,
        )
        in_specs += [sc_spec, sc_spec]
        args += [k_scale, v_scale]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)


def flash_attention(
    q: jax.Array,  # [B, T, Hq, D]
    k: jax.Array,  # [B, S, Hkv, D] (fp8 codes when k_scale is given)
    v: jax.Array,  # [B, S, Hkv, D]
    start: Optional[jax.Array] = None,  # [B] int32 left-pad offsets
    q_offset: Optional[jax.Array] = None,  # scalar int32 global slot of q[0]
    causal: bool = True,
    window: Optional[int] = None,
    softcap: Optional[float] = None,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,  # [B, S, Hkv] fp8 dequant scales
    v_scale: Optional[jax.Array] = None,
    block_q: int = FLASH_BLOCK_Q,
    block_k: int = FLASH_BLOCK_K,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Returns [B, T, Hq, D] in q.dtype. Pads T/S/D to tile multiples
    internally; padding key slots are excluded by the causal mask (they
    lie beyond every query's global slot).

    With k_scale/v_scale, k/v are fp8 codes from a quantized KV cache
    and dequantize per block IN-KERNEL (the paged kernel's fp8 story):
    the cache never materializes as a dense bf16 copy in HBM, which is
    the entire point of fp8 KV. Scales cross as f32 — Mosaic has no f16
    vectors — at 1/D the footprint of the codes."""
    from bigdl_tpu.ops.pallas import interpret_mode

    B, T, Hq, D = q.shape
    _, S, Hkv, _ = k.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = interpret_mode()
    if start is None:
        start = jnp.zeros((B,), jnp.int32)
    if q_offset is None:
        q_offset = jnp.zeros((), jnp.int32)
    assert causal, "non-causal path uses ops.attention (bidirectional encoders)"

    block_q, block_k = flash_blocks(T, S, block_q, block_k)
    Tp, Sp, Dp = round_up(T, block_q), round_up(S, block_k), round_up(D, _LANES)

    qt = jnp.transpose(q, (0, 2, 1, 3))  # [B, Hq, T, D]
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    qt = jnp.pad(qt, ((0, 0), (0, 0), (0, Tp - T), (0, Dp - D)))
    kt = jnp.pad(kt, ((0, 0), (0, 0), (0, Sp - S), (0, Dp - D)))
    vt = jnp.pad(vt, ((0, 0), (0, 0), (0, Sp - S), (0, Dp - D)))

    def prep_scale(s):
        if s is None:
            return None
        st = jnp.transpose(s.astype(jnp.float32), (0, 2, 1))  # [B, Hkv, S]
        return jnp.pad(st, ((0, 0), (0, 0), (0, Sp - S)))[..., None]

    out = _flash(
        qt, kt, vt,
        start.astype(jnp.int32),
        q_offset.astype(jnp.int32).reshape(1),
        prep_scale(k_scale), prep_scale(v_scale),
        causal, window, softcap, scale, block_q, block_k, interpret,
    )
    return jnp.transpose(out[:, :, :T, :D], (0, 2, 1, 3))
