"""Pallas paged-attention decode kernel: attention reads KV pages IN
PLACE through the block table.

Parity target: the reference's vLLM paged attention
(/root/reference/python/llm/src/ipex_llm/vllm/xpu/model_convert.py:65-127,
backed by its SYCL paged kernels). The XLA fallback (kvpaged.read_layer)
gathers every allocated page back into a dense [B, S] view per decode
step — the bytes paging saves are spent on the gather, tripling HBM
traffic (page read + dense write + attention read). Here the kernel DMAs
each row's pages straight from the pool:

- grid (B, max_pages); the block table, per-row pos/start and the layer
  index ride as SCALAR-PREFETCH operands so the KV BlockSpec index maps
  can pick the physical page (and layer) per step — no dense copy, no
  per-layer slice of the pool;
- online softmax accumulates across the page axis in VMEM scratch
  (m/l/acc), exactly the flash-attention recurrence with pages as the
  K blocks;
- GQA: q reshapes to [Hkv, G, D] and both dots batch over the kv head
  axis, so all query heads of a row are served by one page DMA.

Stale pages (entries past the row's allocation point at physical page 0,
the engine's scratch sink) are read but fully masked; a fully-masked
page contributes exp-weights of exactly 0, not a poisoned max.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.pallas import qdecode
from bigdl_tpu.ops.pallas._compat import CompilerParams as _CompilerParams

_NEG_INF = -1e30


def _kernel(bt_ref, meta_ref, q_ref, k_ref, v_ref, *refs,
            n_kv: int, group: int, page: int,
            n_batch: int, softcap: float | None, quantized: bool):
    if quantized:  # fp8 pages: per-vector f32 scales ride alongside
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    p = pl.program_id(1)
    mp = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].reshape(n_kv, group, -1).astype(jnp.float32)
    # shared KV decode body (qdecode.decode_kv): pages stay TYPED fp8
    # here — bitcasting the [L, n_pages, ...] pool per decode step would
    # copy it in HBM — so decode_kv takes its typed-fp8 arm, exact and
    # bit-identical to the uint8 bit-decode arm the flash wrapper uses
    k = qdecode.decode_kv(
        k_ref[0, 0], ks_ref[0, 0][..., None] if quantized else None
    )  # [page, Hkv, D]
    v = qdecode.decode_kv(
        v_ref[0, 0], vs_ref[0, 0][..., None] if quantized else None
    )

    # scores [Hkv, G, page], both dots batched over the kv-head axis
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    )
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap

    # validity of this page's slots for row b: start <= slot <= pos
    # (pos is the slot the current token was just written to)
    pos_b = meta_ref[2 + b]
    start_b = meta_ref[2 + n_batch + b]
    win = meta_ref[1]  # traced per-layer sliding window (2**30 = none)
    slot = p * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    valid = (slot >= start_b) & (slot <= pos_b) & (slot > pos_b - win)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:]  # [Hkv, G, 1-padded lanes]
    m_cur = jnp.max(s, axis=2, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # exp-weights of masked slots are exactly 0 (a fully-masked page
    # must contribute nothing, even while m is still -inf)
    pexp = jnp.where(valid, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)

    l_ref[:] = l_ref[:] * alpha + jnp.sum(pexp, axis=2, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        pexp, v, (((2,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new

    @pl.when(p == mp - 1)
    def _finish():
        l = l_ref[:]
        out = acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = out.reshape(n_kv * group, -1).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "softcap", "interpret"),
)
def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D] current-token queries
    k_pages: jax.Array,  # [L, n_pages, page, Hkv, D] the FULL pool
    v_pages: jax.Array,
    block_tables: jax.Array,  # [B, max_pages] int32
    layer: jax.Array,  # scalar int32
    pos: jax.Array,  # [B] slot holding the current token
    start: jax.Array,  # [B]
    k_scale: jax.Array | None = None,  # [L, n_pages, page, Hkv] f32 (fp8)
    v_scale: jax.Array | None = None,
    scale: float | None = None,
    softcap: float | None = None,
    window=None,  # traced per-layer sliding window; None = unbounded
    interpret: bool | None = None,
) -> jax.Array:
    """Returns [B, Hq, D] attention over each row's pages, in place."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    B, Hq, D = q.shape
    L, NP, page, Hkv, _ = k_pages.shape
    G = Hq // Hkv
    mp = block_tables.shape[1]

    sc = scale if scale is not None else D ** -0.5
    q = q.astype(jnp.float32) * sc  # q block is tiny; keep full precision

    win = jnp.asarray(2 ** 30 if window is None else window, jnp.int32)
    meta = jnp.concatenate([
        jnp.reshape(layer, (1,)).astype(jnp.int32), win[None],
        pos.astype(jnp.int32), start.astype(jnp.int32),
    ])

    quantized = k_scale is not None
    kv_spec = pl.BlockSpec(
        (1, 1, page, Hkv, D),
        lambda b, p, bt, meta: (meta[0], bt[b, p], 0, 0, 0),
    )
    in_specs = [
        pl.BlockSpec((1, Hq, D), lambda b, p, bt, meta: (b, 0, 0)),
        kv_spec, kv_spec,
    ]
    args = [block_tables, meta, q, k_pages, v_pages]
    if quantized:
        sc_spec = pl.BlockSpec(
            (1, 1, page, Hkv),
            lambda b, p, bt, meta: (meta[0], bt[b, p], 0, 0),
        )
        in_specs += [sc_spec, sc_spec]
        args += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, p, bt, meta: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, D), jnp.float32),
            pltpu.VMEM((Hkv, G, 1), jnp.float32),
            pltpu.VMEM((Hkv, G, 1), jnp.float32),
        ],
    )
    out_dtype = jnp.bfloat16
    return pl.pallas_call(
        functools.partial(
            _kernel, n_kv=Hkv, group=G, page=page, n_batch=B,
            softcap=softcap, quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), out_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
