"""Pallas TPU kernels — the framework's native kernel layer.

These are the TPU counterparts of the reference's prebuilt SYCL/C++ kernel
wheels (`bigdl-core-xe*` / `xe_linear` / `xe_addons`, SURVEY.md §2.1): real
on-chip kernels for the hot ops, not Python stand-ins. Unlike the
reference (which ships opaque binaries), the kernels are source in-tree
and compile through Mosaic for the local chip.

Dispatch policy (`use_pallas()`):
- on TPU backends the kernels are used automatically;
- on CPU they run only when `BIGDL_TPU_PALLAS=interpret` (tests exercise
  the kernel logic via the Pallas interpreter);
- `BIGDL_TPU_PALLAS=0` force-disables (XLA fallback everywhere).
"""

from __future__ import annotations

import os

import jax


def _mode() -> str:
    return os.environ.get("BIGDL_TPU_PALLAS", "auto")


def use_pallas() -> bool:
    mode = _mode()
    if mode == "0":
        return False
    if mode == "interpret":
        return True
    return jax.default_backend() == "tpu"


def interpret_mode() -> bool:
    """Run kernels through the Pallas interpreter (CPU testing)."""
    return _mode() == "interpret" or jax.default_backend() != "tpu"


from bigdl_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402
from bigdl_tpu.ops.pallas.flash_backward import (  # noqa: E402
    flash_attention_trainable,
)
from bigdl_tpu.ops.pallas.paged_attention import (  # noqa: E402
    paged_decode_attention,
)
from bigdl_tpu.ops.pallas.qbackward import (  # noqa: E402
    dw_matmul, qmatmul_dx,
)
from bigdl_tpu.ops.pallas.qmatmul import (  # noqa: E402
    qmatmul, qmatmul_asym_int4, qmatmul_bytes, qmatmul_codebook,
    qmatmul_fp8, qmatmul_int4, qmatmul_int8, qmatmul_lora, qmatmul_planes,
    qmatmul_q2k, qmatmul_q4k, qmatmul_q5k, qmatmul_q6k,
)

__all__ = ["use_pallas", "interpret_mode", "flash_attention",
           "flash_attention_trainable",
           "paged_decode_attention", "qmatmul", "qmatmul_int4",
           "qmatmul_codebook",
           "qmatmul_int8", "qmatmul_asym_int4", "qmatmul_q4k",
           "qmatmul_q6k", "qmatmul_bytes", "qmatmul_fp8",
           "qmatmul_planes", "qmatmul_q2k", "qmatmul_q5k",
           "qmatmul_lora", "qmatmul_dx", "dw_matmul"]
