"""Pallas fused low-bit backward: dx and dW kernels for the dequant
matmul family.

PR 9 fused the FORWARD dequant-GEMM behind a custom_vjp but left the
backward on the XLA rematerialized-dequant path: dx = g @ dequant(W)
re-materializes a full bf16 copy of W in HBM every train step — the
exact bytes cliff the forward fusion killed ("Training Transformers
with 4-bit Integers", arxiv 2306.11987; the INT4 composability analysis
of arxiv 2301.12017 makes the same bytes-bound argument). This module
closes the loop:

* ``qmatmul_dx``: dx[M, K] = g[M, O] @ dequant(W)[O, K], dequantizing
  weight tiles per-chunk in VMEM straight into the MXU. The access
  pattern is the TRANSPOSE of the forward's (the contraction runs over
  the weight's O rows, not its K columns), which needs its own tile
  policy (`tiling.pick_block_m_dx` / `chunk_target_dx`): the kernel
  grids over (M tiles, O tiles) with o innermost as the reduction axis
  and keeps a [block_m, K] f32 accumulator in VMEM scratch across the
  whole o sweep — packed weights cross HBM once per M tile, g and dx
  exactly once, and the dequantized copy never exists in HBM.
* ``dw_matmul``: dW[O, K] = g^T @ x as a tiled accumulation (grid over
  (O tiles, M tiles), m innermost), the dW-shaped grad any
  unfrozen/bf16-shadow path needs. No dequant is involved — the value
  is pricing and fusing the train step's third GEMM on the same tile
  policy the roofline model imports.

Both kernels are driven by the same table-driven decoder
(`qdecode.DecodeSpec` / `spec_for`) as the forward, so every registered
format gets a fused backward with ZERO per-format kernel code — the
registry in ops/linear.py asserts at import time that no qtype silently
falls back to the XLA remat path (the `bwd_exempt` column is the only
sanctioned exit).

Decode chunks accumulate into the [block_m, K] scratch through static
lane slices; chunk boundaries come from `qdecode.walk`, which aligns
them to the format's plane splits (128-multiples at every real shape),
the same alignment contract the forward kernel's x-slices rely on.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.pallas import qdecode
from bigdl_tpu.ops.pallas.qdecode import DecodeSpec
from bigdl_tpu.ops.pallas.tiling import (
    DX_ACC_BPE, chunk_target_dx, finest_split, pick_block_m,
    pick_block_m_dx, pick_block_o, pick_block_o_dw, round_up,
)
from bigdl_tpu.ops.pallas._compat import CompilerParams as _CompilerParams


def _params_reduce():
    # the innermost grid axis is a sequential reduction into VMEM
    # scratch — it must not be parallelized/reordered
    return _CompilerParams(dimension_semantics=("parallel", "arbitrary"))


# ---------------------------------------------------------------------------
# dx = g @ dequant(W): one [block_m, K] output row tile, any DecodeSpec
# ---------------------------------------------------------------------------

def _dx_kernel(g_ref, w_ref, *rest, K: int, ck: int, spec: DecodeSpec):
    """One (m, o) grid cell: acc[:, chunk] += g_tile @ dq(W_chunk) over
    statically-unrolled chunks of the logical K axis. The [block_m, K]
    accumulator lives in VMEM scratch across the whole o sweep (o is the
    reduction axis here — the transpose of the forward's contract);
    dequant temporaries stay O(block_o * ck), same bound as the forward,
    because each decoded chunk is dead after its dot."""
    side_refs = rest[:-2]
    o_ref, acc_ref = rest[-2], rest[-1]
    o = pl.program_id(1)
    n_o = pl.num_programs(1)

    @pl.when(o == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    side = qdecode.load_side(spec, side_refs)
    w = w_ref[:]  # packed codes [block_o, row_bytes]
    g = g_ref[:].astype(jnp.bfloat16)  # [block_m, block_o]
    for e0, c in qdecode.walk(K, spec.planes, ck):
        wd = qdecode.decode_chunk(spec, K, w, side, e0, c)  # bf16 [bo, c]
        acc_ref[:, e0:e0 + c] += jax.lax.dot_general(
            g, wd, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(o == n_o - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("spec", "out_dtype", "block_m", "block_o",
                              "ck", "K", "interpret")
)
def _dxmm(spec, out_dtype, block_m: int, block_o: int, ck: int, K: int,
          interpret: bool, g2, w, *side):
    Mp = g2.shape[0]
    O = w.shape[0]
    row = lambda m, o: (o, 0)  # weight-side blocks follow the O grid dim
    in_specs = [
        pl.BlockSpec((block_m, block_o), lambda m, o: (m, o),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_o, w.shape[1]), row, memory_space=pltpu.VMEM),
    ] + [
        pl.BlockSpec((block_o, a.shape[1]), row, memory_space=pltpu.VMEM)
        for a in side
    ]
    # grid order (m, o): o innermost is the REDUCTION sweep — the dx row
    # tile accumulates in scratch while weight tiles stream through, so
    # packed weights are re-fetched once per M tile (the same fetch
    # pattern benchmark/roofline.bwd_dx_cost prices)
    return pl.pallas_call(
        functools.partial(_dx_kernel, K=K, ck=ck, spec=spec),
        grid=(Mp // block_m, O // block_o),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (block_m, K), lambda m, o: (m, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Mp, K), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, K), jnp.float32)],
        compiler_params=_params_reduce(),
        interpret=interpret,
    )(g2, w, *side)


def qmatmul_dx(
    g: jax.Array,  # [..., O] upstream cotangent
    w,  # QTensor (any registered non-dense qtype)
    out_dtype=jnp.bfloat16,
    block_o: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """dx[..., K] = g @ dequant(W), fused, for any QTensor whose format
    the registry covers — the backward twin of `qmatmul.qmatmul`. The
    decode recipe comes from the same `qdecode.spec_for` table, so a
    newly registered format gets a fused backward with no kernel code.

    Parity oracle: the XLA rematerialized dequant
    ``g @ w.dequantize(...)`` (ops/linear._fused_bwd's fallback arm)."""
    from bigdl_tpu.ops.pallas import interpret_mode
    from bigdl_tpu.ops.pallas.qmatmul import _side_arrays, _validate

    if interpret is None:
        interpret = interpret_mode()
    spec = qdecode.spec_for(w.spec)
    data = w.data
    if w.spec.storage.startswith("fp8"):
        data = jax.lax.bitcast_convert_type(data, jnp.uint8)
    side = _side_arrays(spec, w.scales, w.mins, w.sub_scales, w.sub_mins)

    *lead, O = g.shape
    K = w.shape[-1]
    assert data.shape[0] == O, (data.shape, g.shape)
    _validate(spec, K, data)

    M = 1
    for d in lead:
        M *= d
    block_m = pick_block_m_dx(M, K)
    Mp = round_up(max(M, 1), block_m)
    g2 = g.reshape(M, O).astype(jnp.bfloat16)
    if Mp != M:
        g2 = jnp.pad(g2, ((0, Mp - M), (0, 0)))

    persist_row = data.shape[1] * data.dtype.itemsize + sum(
        a.shape[1] * a.dtype.itemsize for a in side)
    bo = pick_block_o(O, persist_row, cap=block_o)
    persist = (block_m * K * DX_ACC_BPE + bo * persist_row
               + block_m * bo * 2)
    ck = chunk_target_dx(bo, block_m, persist,
                         finest_split(K, spec.planes),
                         temp_bpe=20 if spec.mins else 14)
    dx = _dxmm(spec, jnp.dtype(out_dtype), block_m, bo, ck, K,
               bool(interpret), g2, data, *side)
    return dx[:M].reshape(*lead, K)


# ---------------------------------------------------------------------------
# dW = g^T @ x: tiled accumulation for unfrozen / bf16-shadow paths
# ---------------------------------------------------------------------------

def _dw_kernel(g_ref, x_ref, o_ref, acc_ref):
    """One (o, m) grid cell: acc += g_tile^T @ x_tile. The [block_o, K]
    accumulator persists across the m sweep (m innermost = reduction);
    the output is written once on the last m step."""
    m = pl.program_id(1)
    n_m = pl.num_programs(1)

    @pl.when(m == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    g = g_ref[:].astype(jnp.bfloat16)  # [block_m, block_o]
    x = x_ref[:].astype(jnp.bfloat16)  # [block_m, K]
    acc_ref[:] += jax.lax.dot_general(
        g, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(m == n_m - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "block_m", "block_o",
                              "interpret")
)
def _dwmm(out_dtype, block_m: int, block_o: int, interpret: bool, g2, x2):
    Mp, Op = g2.shape
    K = x2.shape[1]
    return pl.pallas_call(
        _dw_kernel,
        grid=(Op // block_o, Mp // block_m),
        in_specs=[
            pl.BlockSpec((block_m, block_o), lambda o, m: (m, o),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, K), lambda o, m: (m, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_o, K), lambda o, m: (o, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Op, K), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_o, K), jnp.float32)],
        compiler_params=_params_reduce(),
        interpret=interpret,
    )(g2, x2)


def dw_matmul(
    g: jax.Array,  # [..., O] upstream cotangent
    x: jax.Array,  # [..., K] saved forward activations
    out_dtype=jnp.bfloat16,
    interpret: bool | None = None,
) -> jax.Array:
    """dW[O, K] = g^T @ x, tiled f32 accumulation over the row axis —
    the weight-shaped grad of y = x @ W^T for any unfrozen or
    bf16-shadow weight. Leading dims of g and x must match (they flatten
    to the shared row axis). Parity oracle: ``jnp.einsum('mo,mk->ok')``
    in f32."""
    from bigdl_tpu.ops.pallas import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    *lead_g, O = g.shape
    *lead_x, K = x.shape
    assert lead_g == lead_x, (g.shape, x.shape)
    M = 1
    for d in lead_g:
        M *= d

    block_m = pick_block_m(M, max(K, O))
    Mp = round_up(max(M, 1), block_m)
    block_o = pick_block_o_dw(O, K)
    Op = round_up(O, block_o)
    g2 = g.reshape(M, O).astype(jnp.bfloat16)
    x2 = x.reshape(M, K).astype(jnp.bfloat16)
    if Mp != M:  # zero rows contribute exactly 0 to the accumulation
        g2 = jnp.pad(g2, ((0, Mp - M), (0, 0)))
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))
    if Op != O:
        g2 = jnp.pad(g2, ((0, 0), (0, Op - O)))
    dw = _dwmm(jnp.dtype(out_dtype), block_m, block_o, bool(interpret),
               g2, x2)
    return dw[:O]
