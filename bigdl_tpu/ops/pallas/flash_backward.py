"""Trainable Pallas flash attention: forward with logsumexp residuals +
dq / dkv backward kernels under jax.custom_vjp.

The inference kernel (flash_attention.py) has no backward, so training
(cache=None) previously fell back to XLA attention, which materializes
the [T, S] probability matrix for the backward pass — at T=4096 that is
~2 GB/layer of saved activations, the reason long-context single-chip
QLoRA OOMs. This module recomputes attention blockwise in the backward
(the standard flash recipe): the forward additionally emits per-row
logsumexp, the backward recomputes P = exp(S - lse) per block and
accumulates

    dV = P^T dO
    dS = P * (dO V^T - rowsum(dO * O))
    dQ = dS K * scale        (one kernel, grid over Q blocks)
    dK = dS^T Q * scale      (one kernel, grid over K blocks, inner
                              loop over (q-head-in-group, Q block) so
                              GQA head groups accumulate without racing)

Scope: causal attention with left padding and optional sliding window —
the training shapes (llama-family QLoRA/LoRA/full finetune). Softcap
(gemma2) stays on the XLA path. The forward math duplicates
flash_attention._kernel deliberately: that kernel is silicon-validated
for inference and is not touched; this one adds the lse output (written
as an [.., 8]-lane block to satisfy the Mosaic lane rule,
BENCH_NOTES.md r05 finding #4).

Layouts follow the inference kernel: kernels run on [B, H, T, D] with
T/S/D padded to block multiples; the public wrapper takes/returns the
model's [B, T, H, D].
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.pallas import qdecode
from bigdl_tpu.ops.pallas._compat import CompilerParams as _CompilerParams
from bigdl_tpu.ops.pallas.tiling import MOSAIC_LANES
from bigdl_tpu.utils import round_up

_NEG_INF = -1e30
# one source for the lane width (tiling.py), shared with the forward
# kernel and the analytic roofline — the policies cannot drift
_LANES = MOSAIC_LANES
_LSE_LANES = 8  # full-dim lane block: satisfies the (sublane, 128) rule


def _masks(start_b, qoff, i, j, block_q, block_k, causal, window):
    rows = qoff + i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    valid = cols >= start_b
    if causal:
        valid = valid & (cols <= rows)
    if window is not None:
        valid = valid & (cols > rows - window)
    return valid


def _block_live(qoff, i, j, block_q, block_k, causal, window):
    live = jnp.bool_(True)
    if causal:
        live = live & (j * block_k <= qoff + (i + 1) * block_q - 1)
    if window is not None:
        live = live & ((j + 1) * block_k - 1 > qoff + i * block_q - window)
    return live


def _fwd_kernel(
    start_ref, qoff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
    m_scr, l_scr, acc_scr,
    *, scale, block_q, block_k, causal, window,
):
    b = pl.program_id(0)
    i, j = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qoff = qoff_ref[0]

    @pl.when(_block_live(qoff, i, j, block_q, block_k, causal, window))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = qdecode.decode_kv(k_ref[0, 0])
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        valid = _masks(start_ref[b], qoff, i, j, block_q, block_k,
                       causal, window)
        s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = qdecode.decode_kv(v_ref[0, 0])
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_k - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse = m + log(l); fully-masked rows get -inf-ish, exp() -> 0
        lse = m_scr[:, :1] + jnp.log(safe_l)
        lse = jnp.where(l == 0.0, _NEG_INF, lse)
        lse_ref[0, 0] = jnp.broadcast_to(lse, (block_q, _LSE_LANES))


def _dq_kernel(
    start_ref, qoff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_scr,
    *, scale, block_q, block_k, causal, window,
):
    b = pl.program_id(0)
    i, j = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    qoff = qoff_ref[0]

    @pl.when(_block_live(qoff, i, j, block_q, block_k, causal, window))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = qdecode.decode_kv(k_ref[0, 0])
        s = jax.lax.dot_general(
            q * scale, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        valid = _masks(start_ref[b], qoff, i, j, block_q, block_k,
                       causal, window)
        lse = lse_ref[0, 0][:, :1]
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)  # [BQ, BK]

        do = do_ref[0, 0].astype(jnp.float32)
        v = qdecode.decode_kv(v_ref[0, 0])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = delta_ref[0, 0][:, :1]
        ds = p * (dp - delta)
        dq_scr[:] = dq_scr[:] + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == n_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    start_ref, qoff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale, block_q, block_k, causal, window, n_q,
):
    b = pl.program_id(0)
    j = pl.program_id(2)
    gi = pl.program_id(3)  # inner loop over (q-head-in-group, Q block)
    n_gi = pl.num_programs(3)
    i = jax.lax.rem(gi, n_q)

    @pl.when(gi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    qoff = qoff_ref[0]

    @pl.when(_block_live(qoff, i, j, block_q, block_k, causal, window))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = qdecode.decode_kv(k_ref[0, 0])
        s = jax.lax.dot_general(
            q * scale, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        valid = _masks(start_ref[b], qoff, i, j, block_q, block_k,
                       causal, window)
        lse = lse_ref[0, 0][:, :1]
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)  # [BQ, BK]

        do = do_ref[0, 0].astype(jnp.float32)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        v = qdecode.decode_kv(v_ref[0, 0])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = delta_ref[0, 0][:, :1]
        ds = p * (dp - delta)
        dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(gi == n_gi - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _smem(shape):
    return pl.BlockSpec(
        shape, lambda *idx: tuple(0 for _ in shape), memory_space=pltpu.SMEM,
    )


def _fwd(q, k, v, start, qoff, scale, block_q, block_k, causal, window,
         interpret):
    B, Hq, Tp, D = q.shape
    _, Hkv, Sp, _ = k.shape
    group = Hq // Hkv
    n_q, n_k = Tp // block_q, Sp // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window,
    )
    return pl.pallas_call(
        kernel,
        grid=(B, Hq, n_q, n_k),
        in_specs=[
            _smem((B,)), _smem((1,)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Tp, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, Tp, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(start, qoff, q, k, v)


def _bwd(q, k, v, do, lse, delta, start, qoff, scale, block_q, block_k,
         causal, window, interpret):
    B, Hq, Tp, D = q.shape
    _, Hkv, Sp, _ = k.shape
    group = Hq // Hkv
    n_q, n_k = Tp // block_q, Sp // block_k

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, Hq, n_q, n_k),
        in_specs=[
            _smem((B,)), _smem((1,)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hq, Tp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(start, qoff, q, k, v, do, lse, delta)

    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, n_q=n_q,
    )
    h_of = lambda h, gi: h * group + gi // n_q
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, Hkv, n_k, group * n_q),
        in_specs=[
            _smem((B,)), _smem((1,)),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, j, gi: (b, h_of(h, gi), gi % n_q, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, gi: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, gi: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, j, gi: (b, h_of(h, gi), gi % n_q, 0)),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                         lambda b, h, j, gi: (b, h_of(h, gi), gi % n_q, 0)),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                         lambda b, h, j, gi: (b, h_of(h, gi), gi % n_q, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, gi: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, gi: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, Sp, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, Sp, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(start, qoff, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9),
)
def flash_attention_train(
    q, k, v, start,
    causal: bool = True,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Differentiable flash attention. q [B,T,Hq,D]; k,v [B,S,Hkv,D];
    start [B] int32 left-pad offsets. Returns [B,T,Hq,D] in q.dtype.
    Training shapes only: q positions are 0..T-1 (no cache offset)."""
    out, _ = _train_fwd(
        q, k, v, start, causal, window, scale, block_q, block_k, interpret
    )
    return out


def _prep(q, k, v, start, scale, block_q, block_k, interpret):
    from bigdl_tpu.ops.pallas import interpret_mode

    B, T, Hq, D = q.shape
    _, S, Hkv, _ = k.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = interpret_mode()
    if start is None:
        start = jnp.zeros((B,), jnp.int32)
    block_q = min(block_q, round_up(T, 16))
    block_k = min(block_k, round_up(S, 16))
    Tp, Sp, Dp = round_up(T, block_q), round_up(S, block_k), round_up(D, _LANES)
    tr = lambda x, P, Dp_: jnp.pad(
        jnp.transpose(x, (0, 2, 1, 3)),
        ((0, 0), (0, 0), (0, P - x.shape[1]), (0, Dp_ - x.shape[3])),
    )
    qt, kt, vt = tr(q, Tp, Dp), tr(k, Sp, Dp), tr(v, Sp, Dp)
    qoff = jnp.zeros((1,), jnp.int32)
    return (qt, kt, vt, start.astype(jnp.int32), qoff, float(scale),
            block_q, block_k, bool(interpret), (B, T, Hq, D, S, Hkv))


def _train_fwd(q, k, v, start, causal, window, scale, block_q, block_k,
               interpret):
    (qt, kt, vt, start_i, qoff, scale_f, bq, bk, interp,
     (B, T, Hq, D, S, Hkv)) = _prep(
        q, k, v, start, scale, block_q, block_k, interpret)
    out_p, lse = _fwd(qt, kt, vt, start_i, qoff, scale_f, bq, bk,
                      causal, window, interp)
    out = jnp.transpose(out_p[:, :, :T, :D], (0, 2, 1, 3))
    residuals = (qt, kt, vt, start_i, qoff, out_p, lse,
                 (T, D, S, scale_f, bq, bk, interp))
    return out, residuals


def _train_bwd(causal, window, scale, block_q, block_k, interpret,
               residuals, g):
    qt, kt, vt, start_i, qoff, out_p, lse, shapes = residuals
    T, D, S, scale_f, bq, bk, interp = shapes
    B, Hq, Tp, Dp = qt.shape

    do = jnp.pad(
        jnp.transpose(g, (0, 2, 1, 3)),
        ((0, 0), (0, 0), (0, Tp - T), (0, Dp - D)),
    )
    # delta = rowsum(dO * O) per (b, h, q row) — cheap, computed in XLA
    delta = jnp.sum(do.astype(jnp.float32) * out_p.astype(jnp.float32),
                    axis=-1)  # [B, Hq, Tp]
    delta = jnp.broadcast_to(delta[..., None], (B, Hq, Tp, _LSE_LANES))

    dq_p, dk_p, dv_p = _bwd(
        qt, kt, vt, do, lse, delta, start_i, qoff, scale_f, bq, bk,
        causal, window, interp,
    )
    un = lambda x, L, like: jnp.transpose(
        x[:, :, :L, :D], (0, 2, 1, 3)
    ).astype(like)
    dq = un(dq_p, T, g.dtype)
    dk = un(dk_p, S, g.dtype)
    dv = un(dv_p, S, g.dtype)
    # start is int32: cotangent space is float0
    import numpy as np

    dstart = np.zeros(start_i.shape, jax.dtypes.float0)
    return dq, dk, dv, dstart


flash_attention_train.defvjp(_train_fwd, _train_bwd)


def flash_attention_trainable(
    q, k, v, start=None, causal: bool = True, window=None, scale=None,
    block_q: int = 128, block_k: int = 128, interpret=None,
):
    """start-defaulting wrapper (custom_vjp needs a concrete array for
    every differentiable positional arg)."""
    # without the causal term the mask has no `cols < S` bound, so padded
    # phantom key columns would leak softmax mass (same guard as the
    # inference kernel, flash_attention.py)
    assert causal, "non-causal path uses ops.attention (bidirectional)"
    if start is None:
        start = jnp.zeros((q.shape[0],), jnp.int32)
    return flash_attention_train(
        q, k, v, start, causal, window, scale, block_q, block_k, interpret
    )
