"""Pallas API-spelling compat for the pinned jax.

jax 0.4.37 spells it TPUCompilerParams; newer jax renamed it to
CompilerParams. One alias here so every kernel module agrees. qmatmul
and flash_backward import it (flash_backward since the tiled-GEMM PR:
its 5 grad-parity tests now execute, ~11 s, and the trainable flash
path works on the pinned jax). paged_attention still uses the bare
newer spelling deliberately — see the comment there (tier-1 budget +
an unresolved token-parity divergence).
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)
