"""Pallas API-spelling compat for the pinned jax.

jax 0.4.37 spells it TPUCompilerParams; newer jax renamed it to
CompilerParams. One alias here so every kernel module agrees
(paged_attention / flash_backward still use the bare newer spelling
deliberately — flipping them adds interpret-mode CPU cost against the
tier-1 time budget; import from here when migrating them).
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)
