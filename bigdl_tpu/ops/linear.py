"""Quantized / dense linear op.

Equivalent of `LowBitLinear.forward` in the reference
(low_bit_linear.py:606-716): one entry point that dispatches on weight
type and shape. The prefill/decode split the reference implements with
two SYCL kernels (`xe_linear.forward_new` vs `xe_batch.batch_forward`)
maps to: decode-shaped (few rows) matmuls go to the fused Pallas
dequant-GEMV (packed weights cross HBM as stored), larger shapes —
prefill, continuous batches, speculative verify, QLoRA training — go to
the fused tiled dequant-GEMM (weight tiles decode once in VMEM and feed
the MXU; the dequantized copy never round-trips HBM). Only ineligible
shapes (odd O/K, exempt formats) take the in-graph XLA dequant that XLA
fuses into the matmul.

The fused paths are wrapped in a custom_vjp so training (QLoRA's frozen
low-bit base) can differentiate through them. The backward is fused
too: dx = g @ dequant(W) routes to the Pallas dx kernel
(ops/pallas/qbackward.py), which dequantizes weight tiles per-chunk in
VMEM straight into the MXU — the bf16 rematerialized copy of W the XLA
remat path writes to HBM every train step never exists ("Training
Transformers with 4-bit Integers", arxiv 2306.11987). The registry's
`bwd` column drives it through the same shared decoder as the forward,
with an import-time assert that no qtype silently falls back; the XLA
remat stays available under `fused_backward_scope(False)` as the parity
oracle.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.quant import QTensor

# Decode GEMV threshold, same role as the reference's `use_batch_forward`
# heuristic (low_bit_linear.py:272-309): below this many rows the matmul
# is weight-bandwidth-bound and the whole-M-block GEMV contract wins;
# above it the tiled GEMM amortizes each decoded weight tile over a
# [block_m, K] row tile.
_GEMV_MAX_ROWS = 32


def _rows(shape) -> int:
    n = 1
    for d in shape[:-1]:
        n *= d
    return n


def _run_sym_int4(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_int4

    return qmatmul_int4(x, w.data, w.scales, out_dtype=x.dtype, block_o=bo)


def _run_asym_int4(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_asym_int4

    return qmatmul_asym_int4(x, w.data, w.scales, w.mins, out_dtype=x.dtype,
                             block_o=bo)


def _run_codebook(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_codebook

    return qmatmul_codebook(x, w.data, w.scales, codebook=w.spec.codebook,
                            block=w.spec.block_size, out_dtype=x.dtype,
                            block_o=bo)


def _run_int8(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_int8

    return qmatmul_int8(x, w.data, w.scales, out_dtype=x.dtype, block_o=bo)


def _run_asym_int5(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_bytes

    return qmatmul_bytes(x, w.data, w.scales, w.mins, decode="i8",
                         block=w.spec.block_size, out_dtype=x.dtype,
                         block_o=bo)


def _run_fp8(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_fp8

    return qmatmul_fp8(x, w.data, w.scales, block=w.spec.block_size,
                       out_dtype=x.dtype, block_o=bo)


def _run_planes(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_planes

    spec = w.spec
    if spec.name == "fp6":  # exact arithmetic e2m3 decode
        decode = ("e2m3",)
    elif spec.codebook is not None:  # nf3: 8-entry select tree
        decode = ("lut", tuple(float(c) for c in spec.codebook))
    else:  # sym_int5: v - 16
        decode = ("offset", 16)
    return qmatmul_planes(x, w.data, w.scales, spec.planes, decode,
                          spec.block_size, out_dtype=x.dtype, block_o=bo)


def _run_q4k(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_q4k

    return qmatmul_q4k(x, w.data, w.scales, w.mins, w.sub_scales,
                       w.sub_mins, out_dtype=x.dtype, block_o=bo)


def _run_q5k(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_q5k

    return qmatmul_q5k(x, w.data, w.scales, w.mins, w.sub_scales,
                       w.sub_mins, out_dtype=x.dtype, block_o=bo)


def _run_q2k(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_q2k

    return qmatmul_q2k(x, w.data, w.scales, w.mins, w.sub_scales,
                       w.sub_mins, out_dtype=x.dtype, block_o=bo)


def _run_dx(g, w, bo):
    # shared fused backward: dx = g @ dequant(W), table-driven through
    # qdecode.spec_for — one kernel body serves every registered format
    from bigdl_tpu.ops.pallas import qmatmul_dx

    return qmatmul_dx(g, w, out_dtype=g.dtype, block_o=bo)


def _run_q6k(x, w, bo):
    # planar q3_k is structurally identical to q6_k (int8 centered
    # codes, int8 sub-scales per 16, f16 d per 256) and shares its kernel
    from bigdl_tpu.ops.pallas import qmatmul_q6k

    return qmatmul_q6k(x, w.data, w.scales, w.sub_scales, out_dtype=x.dtype,
                       block_o=bo)


class _GemvEntry(NamedTuple):
    """Eligibility + kernels for one qtype, registered in one place.

    k_multiple folds every per-format shape rule into one divisibility
    check on the LOGICAL contraction dim: whole quant blocks per packed
    plane (sym/asym_int4 64, nf4/fp4 128), whole super-blocks (k-quants
    256), and 128-lane alignment of the finest plane split for the
    multi-plane kernels (fp6/q2_k 512; sym_int5/nf3/q5_k 1024 — the
    eighth-split 1-bit plane slices at K/8-byte offsets).

    `run` serves decode shapes (rows <= _GEMV_MAX_ROWS, whole-M block),
    `gemm` serves everything above (M-tiled; both resolve to the unified
    kernel in ops/pallas/qmatmul.py, which reads the format through the
    shared decoder in ops/pallas/qdecode.py). A format without a fused
    GEMM path MUST say why in `gemm_exempt` — the dispatch-coverage test
    fails any entry that silently leaves prefill shapes on the XLA
    dequant path.

    `bwd` is the fused backward dx kernel (ops/pallas/qbackward.py,
    same table-driven decoder); a format without one MUST say why in
    `bwd_exempt` — a silent XLA-remat fallback rewrites a full bf16
    dequant of W to HBM every train step, the backward twin of the
    forward cliff. `bwd_k_multiple` optionally coarsens the contraction
    alignment the backward needs (None inherits k_multiple; the dx
    kernel's chunk walk has the same plane-split period as the
    forward's, so every current format inherits)."""
    k_multiple: int
    run: Callable  # (x [M, K] compute dtype, w, block_o) -> y [M, O]
    gemm: Optional[Callable] = None  # rows > _GEMV_MAX_ROWS kernel
    gemm_exempt: Optional[str] = None  # stated reason when gemm is None
    bwd: Optional[Callable] = None  # (g [M, O], w, block_o) -> dx [M, K]
    bwd_exempt: Optional[str] = None  # stated reason when bwd is None
    bwd_k_multiple: Optional[int] = None  # None = inherit k_multiple


def _entry(k_multiple: int, run: Callable) -> _GemvEntry:
    # every current format's kernel is M-tiled, so the same callable
    # serves both shape classes, and the table-driven dx kernel serves
    # every format's backward; a future format that can only GEMV (or
    # cannot decode in the transposed access pattern) must pass an
    # explicit gemm_exempt / bwd_exempt reason instead
    return _GemvEntry(k_multiple, run, gemm=run, bwd=_run_dx)


# every qtype with a decode path dispatches to a fused Pallas kernel —
# the in-kernel decode mirrors QTensor.dequantize exactly
_QGEMV_QTYPES = {
    "sym_int4": _entry(64, _run_sym_int4),
    "asym_int4": _entry(64, _run_asym_int4),
    "nf4": _entry(128, _run_codebook),
    "fp4": _entry(128, _run_codebook),
    "sym_int8": _entry(32, _run_int8),
    "asym_int5": _entry(32, _run_asym_int5),
    "fp8_e4m3": _entry(128, _run_fp8),
    "fp8_e5m2": _entry(128, _run_fp8),
    "sym_int5": _entry(1024, _run_planes),
    "fp6": _entry(512, _run_planes),
    "nf3": _entry(1024, _run_planes),
    "q2_k": _entry(512, _run_q2k),
    "q3_k": _entry(256, _run_q6k),
    "q4_k": _entry(256, _run_q4k),
    "q5_k": _entry(1024, _run_q5k),
    "q6_k": _entry(256, _run_q6k),
}

for _name, _e in _QGEMV_QTYPES.items():
    assert _e.gemm is not None or _e.gemm_exempt, (
        f"{_name}: declare a fused GEMM kernel or an explicit gemm_exempt "
        "reason (silent XLA-dequant fallback above _GEMV_MAX_ROWS is the "
        "2.7x cliff class this registry exists to prevent)"
    )
    assert _e.bwd is not None or _e.bwd_exempt, (
        f"{_name}: declare a fused backward kernel or an explicit "
        "bwd_exempt reason — a silent XLA-remat dx writes a full bf16 "
        "dequant of W to HBM every train step, the backward twin of the "
        "forward cliff"
    )


def _fused_kernel(x: jax.Array, w: QTensor) -> Optional[Callable]:
    """The fused kernel this (x, w) pair dispatches to, or None for the
    XLA dequant path. Shape guards are shared by both shape classes."""
    from bigdl_tpu.ops.pallas import use_pallas
    from bigdl_tpu.ops.pallas.tiling import VMEM_BUDGET

    entry = _QGEMV_QTYPES.get(w.qtype)
    if entry is None or w.data.ndim != 2:
        return None
    out, kw_ = w.data.shape
    if out % 128 != 0:
        return None
    # the kernels tile O at >= 128 rows (Mosaic lane rule forbids
    # smaller output tiles); if even a 128-row tile's persistent weight
    # block cannot fit half the scoped-VMEM budget (the other half is
    # the x/acc slabs), fall back to the XLA dequant path rather than
    # compile a kernel that overflows vmem
    row_bytes = kw_ * w.data.dtype.itemsize
    if 128 * row_bytes > VMEM_BUDGET // 2:
        return None
    if w.shape[-1] % entry.k_multiple != 0:
        return None
    if not use_pallas():
        return None
    if _rows(x.shape) <= _GEMV_MAX_ROWS:
        return entry.run
    return entry.gemm  # None for gemm_exempt formats


def _use_qgemv(x: jax.Array, w: QTensor) -> bool:
    """Decode-shaped dispatch to the fused GEMV contract."""
    return (_rows(x.shape) <= _GEMV_MAX_ROWS
            and _fused_kernel(x, w) is not None)


def _use_qgemm(x: jax.Array, w: QTensor) -> bool:
    """Prefill/batch/training dispatch to the fused tiled GEMM."""
    return (_rows(x.shape) > _GEMV_MAX_ROWS
            and _fused_kernel(x, w) is not None)


# Backward-path selector, read at TRACE time inside the custom_vjp bwd
# rules: True routes dx through the fused Pallas kernel whenever the
# entry has one, False keeps the XLA rematerialized dequant (the parity
# oracle, and the pre-PR behavior). Trace-time means the flag is baked
# into the jaxpr — flipping it under an already-jitted train step does
# nothing until retrace, which is exactly the semantics a per-run knob
# (train/qlora.make_train_step(fused_backward=...)) needs.
_FUSED_BACKWARD = True


def fused_backward_enabled() -> bool:
    """Whether custom_vjp backward rules traced now use the fused dx."""
    return _FUSED_BACKWARD


@contextlib.contextmanager
def fused_backward_scope(enabled: bool = True):
    """Scope the backward-path selector around a trace (the train-step
    builder wraps its value_and_grad in this)."""
    global _FUSED_BACKWARD
    prev = _FUSED_BACKWARD
    _FUSED_BACKWARD = bool(enabled)
    try:
        yield
    finally:
        _FUSED_BACKWARD = prev


def _fused_dx(g: jax.Array, w: QTensor, qtype: str, block_o: int):
    """dx = g @ dequant(W) for the custom_vjp bwd rules: the fused
    Pallas kernel when the registry + selector allow it, else the XLA
    rematerialized dequant. Forward eligibility (O % 128, weight-tile
    VMEM fit, K % k_multiple, use_pallas) already held — the vjp only
    wraps fused forwards — so the only fresh check is the backward's own
    alignment column."""
    entry = _QGEMV_QTYPES[qtype]
    km = entry.bwd_k_multiple or entry.k_multiple
    if (_FUSED_BACKWARD and entry.bwd is not None
            and w.shape[-1] % km == 0):
        return entry.bwd(g, w, block_o)
    wd = w.dequantize(g.dtype)
    return jnp.einsum("...o,ok->...k", g, wd, preferred_element_type=g.dtype)


def _zero_cotangent(w: QTensor) -> QTensor:
    """Symbolic-zero cotangent for the frozen quantized weight: float
    leaves get typed zeros, integer code/sub-scale leaves get float0
    (the tangent type jax assigns non-differentiable dtypes)."""
    def z(a):
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return jnp.zeros(a.shape, a.dtype)
        return np.zeros(a.shape, jax.dtypes.float0)

    return w.map_arrays(z)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_matmul(x: jax.Array, w: QTensor, qtype: str, block_o: int):
    entry = _QGEMV_QTYPES[qtype]
    run = entry.run if _rows(x.shape) <= _GEMV_MAX_ROWS else entry.gemm
    return run(x, w, block_o)


def _fused_fwd(x, w, qtype, block_o):
    return _fused_matmul(x, w, qtype, block_o), w


def _fused_bwd(qtype, block_o, w, g):
    # dx = g @ dequant(W) through the fused Pallas kernel (or the XLA
    # remat oracle under fused_backward_scope(False)); W itself is
    # frozen, so its cotangent is a symbolic zero
    return _fused_dx(g, w, qtype, block_o), _zero_cotangent(w)


_fused_matmul.defvjp(_fused_fwd, _fused_bwd)


def _lora_cat_operands(x: jax.Array, lora, compute_dtype):
    """Canonicalize a lora triple (a, b, scale) — shared [r, K]/[O, r]
    or batched per-row [B, rb, K]/[B, O, rb]/[B] — into the fused
    epilogue's concatenated operand form (a_cat [R, K], b_cat [O, R],
    gate [M, R]), or None when the shape is ineligible (rank columns
    would blow the epilogue's VMEM allowance, or the batched form does
    not line up with x's rows). Column order is group-major, rank
    within; gate row m carries scale_g in its own group g's columns and
    0 elsewhere, so each row receives exactly its adapter's delta."""
    from bigdl_tpu.ops.pallas.tiling import lora_fused_ok

    a, b, scale = lora
    K = x.shape[-1]
    M = _rows(x.shape)
    if a.ndim == 3:  # batched per-row adapters (serving)
        if x.ndim != 3 or a.shape[0] != x.shape[0]:
            return None
        B, rb, ka = a.shape
        R = B * rb
        if ka != K or rb == 0 or not lora_fused_ok(R, K):
            return None
        T = x.shape[1]
        a_cat = a.reshape(R, K)
        b_cat = jnp.moveaxis(b, 0, 1).reshape(b.shape[1], R)
        grp = jnp.repeat(jnp.arange(B, dtype=jnp.int32), T)  # row -> group
        col = jnp.repeat(jnp.arange(B, dtype=jnp.int32), rb)  # col -> group
        sc = jnp.asarray(scale).astype(compute_dtype)
        gate = ((grp[:, None] == col[None, :]).astype(compute_dtype)
                * sc[grp][:, None])
        return a_cat, b_cat, gate
    r, ka = a.shape
    if ka != K or r == 0 or not lora_fused_ok(r, K):
        return None
    sc = jnp.asarray(scale).astype(compute_dtype)
    gate = jnp.broadcast_to(sc, (M, r))
    return a, b, gate


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused_lora_matmul(x: jax.Array, w: QTensor, a_cat, b_cat, gate,
                       qtype: str, block_o: int):
    from bigdl_tpu.ops.pallas import qmatmul_lora

    return qmatmul_lora(x, w, a_cat, b_cat, gate, out_dtype=x.dtype,
                        block_o=block_o)


def _fused_lora_fwd(x, w, a_cat, b_cat, gate, qtype, block_o):
    y = _fused_lora_matmul(x, w, a_cat, b_cat, gate, qtype, block_o)
    return y, (x, w, a_cat, b_cat, gate)


def _fused_lora_bwd(qtype, block_o, res, g):
    # the base-weight dx term routes through the fused kernel exactly
    # like _fused_bwd; the epilogue's product-rule terms stay on XLA
    # (rank-R operands are far below 128-lane tile economics). For
    # v = (x @ A^T) * gate, y = x @ dq(W)^T + v @ B^T
    x, w, a, b, gt = res
    cd = g.dtype
    K = x.shape[-1]
    O = g.shape[-1]
    xf = x.reshape(-1, K).astype(cd)
    gf = g.reshape(-1, O)
    ac, bc, gtc = a.astype(cd), b.astype(cd), gt.astype(cd)
    u = xf @ ac.T  # [M, R]
    dv = gf @ bc  # [M, R]
    du = dv * gtc
    dxw = _fused_dx(gf, w, qtype, block_o).astype(cd)
    dx = (dxw + du @ ac).reshape(x.shape).astype(x.dtype)
    da = (du.T @ xf).astype(a.dtype)
    db = (gf.T @ (u * gtc)).astype(b.dtype)
    dgate = (dv * u).astype(gt.dtype)
    return dx, _zero_cotangent(w), da, db, dgate


_fused_lora_matmul.defvjp(_fused_lora_fwd, _fused_lora_bwd)


def lora_epilogue(x: jax.Array, a: jax.Array, b: jax.Array,
                  scale: jax.Array, compute_dtype=jnp.bfloat16) -> jax.Array:
    """The multi-tenant LoRA epilogue ``(x @ A^T) @ B^T * scale`` added
    to a (fused dequant-)GEMM's output — the base weight stays packed
    and shared while the adapter applies unquantized on top
    (serving/adapters.py; arxiv 2301.12017's composability argument
    against merge-and-requantize per tenant).

    Two shapes, one contract:

    - shared adapter (training / single-tenant): ``a [r, in]``,
      ``b [out, r]``, scalar ``scale`` — every row of ``x [..., in]``
      goes through the same pair;
    - batched per-row adapters (the serving engine's heterogeneous
      decode batch): ``a [B, r, in]``, ``b [B, out, r]``, ``scale [B]``
      against ``x [B, T, in]`` — slot ``i`` applies ITS adapter; rank
      rows/columns zero-padded to the batch's rank bucket contribute
      exactly 0, so adapter-less slots ride along unchanged and one
      compiled program serves any mix at or below the bucket.
    """
    xc = x.astype(compute_dtype)
    ac, bc = a.astype(compute_dtype), b.astype(compute_dtype)
    if a.ndim == 3:  # batched per-row adapters
        xa = jnp.einsum("btk,brk->btr", xc, ac)
        y = jnp.einsum("btr,bor->bto", xa, bc)
        return y * scale.astype(compute_dtype)[:, None, None]
    xa = jnp.einsum("...k,rk->...r", xc, ac)
    # scale is cast to the compute dtype, never the other way: an f32
    # scale leaf (adapter artifacts store it as f32) must not promote
    # the delta — a promoted residual changes the scan carry's dtype
    # on wo/w_down targets and breaks the layer scan outright
    return (jnp.einsum("...r,or->...o", xa, bc)
            * jnp.asarray(scale).astype(compute_dtype))


def linear(
    x: jax.Array,
    w: Union[QTensor, jax.Array],
    bias: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
    lora=None,
) -> jax.Array:
    """y = x @ W^T (+ bias) (+ LoRA delta). W has logical shape
    [out_features, in_features].

    QTensor weights route to the fused Pallas dequant kernels whenever
    the shape is eligible (GEMV below `_GEMV_MAX_ROWS` rows, tiled GEMM
    above); otherwise the dequantization is expressed in-graph so XLA
    fuses unpack+scale into the matmul's operand read. Weights stay
    packed in HBM either way.

    ``lora`` is an optional (a, b, scale) triple in either
    `lora_epilogue` shape. On the fused path it folds into the kernel's
    writeback (`ops/pallas/qmatmul.qmatmul_lora` — zero extra
    activation HBM round trips); everywhere else — XLA fallback, exempt
    formats, dense weights, operand shapes past the epilogue's VMEM
    allowance — it applies as the `lora_epilogue` einsum pair, which
    doubles as the fused path's parity oracle.
    """
    if isinstance(w, QTensor):
        if _fused_kernel(x, w) is not None:
            block_o = 256 if w.data.shape[0] % 256 == 0 else 128
            xc = x.astype(compute_dtype)
            if lora is not None:
                ops = _lora_cat_operands(x, lora, compute_dtype)
                if ops is not None:
                    y = _fused_lora_matmul(xc, w, *ops, w.qtype, block_o)
                    if bias is not None:
                        y = y + bias.astype(compute_dtype)
                    return y
            y = _fused_matmul(xc, w, w.qtype, block_o)
            if lora is not None:
                y = y + lora_epilogue(x, *lora, compute_dtype)
            if bias is not None:
                y = y + bias.astype(compute_dtype)
            return y
        wd = w.dequantize(compute_dtype)
    else:
        wd = w.astype(compute_dtype)
    y = jnp.einsum(
        "...k,ok->...o",
        x.astype(compute_dtype),
        wd,
        preferred_element_type=compute_dtype,
    )
    if lora is not None:
        y = y + lora_epilogue(x, *lora, compute_dtype)
    if bias is not None:
        y = y + bias.astype(compute_dtype)
    return y


def _half_split_perm(a: jax.Array, n: int) -> jax.Array:
    """Reorder the last axis from half-split to shard-major order.

    `pack_nibbles` stores column j and column j + K/2 in the same byte,
    so shard s of the packed axis holds columns [s*h, (s+1)*h) of EACH
    half (h = K/(2n)). [..., 2, n, h] -> [..., n, 2, h]: after this, a
    contiguous 1/n slice of the last axis is exactly the column set the
    matching packed-byte slice carries. Applied to x and to the
    per-block scales/mins (whose last axis has the same half-block
    structure at K/block granularity)."""
    m = a.shape[-1] // (2 * n)
    a = a.reshape(*a.shape[:-1], 2, n, m)
    return a.swapaxes(-3, -2).reshape(*a.shape[:-3], 2 * n * m)


def row_parallel_linear(
    x: jax.Array,
    w: Union[QTensor, jax.Array],
    comm,
    bias: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """`linear` for a row-parallel (contraction-sharded) weight with an
    EXPLICIT quantized all-reduce epilogue (parallel/qcollectives.py).

    Under plain GSPMD the psum behind wo / w_down is implicit — XLA
    inserts it from the shardings, fp32/bf16 on the wire. A
    `CommConfig` with a quantized `comm_qtype` replaces that one
    epilogue with a shard_map partial matmul + block-scaled ring
    all-reduce with error feedback; ``comm.enabled == False`` (qtype
    "none" or a 1-wide axis) falls straight back to `linear`, leaving
    the implicit-psum path bit-identical to today's.

    The shard_map's in_specs shard only `comm.axis_name` (x's
    contraction dim, W's K dim); other mesh axes see the operands
    replicated at this boundary, which is the decode-epilogue regime the
    quantized ring targets (tiny M, weight-stationary). Bias is added
    AFTER the reduce, once.

    QTensor weights need care: unlike GSPMD (where sharding is pure
    layout and XLA sees the whole dequant+matmul), shard_map hands each
    shard a literal byte slice. `pack_nibbles`' half-split layout means
    byte j of the packed axis carries logical columns j AND j + K/2, so
    a contiguous byte slice is a NON-contiguous column set — x and the
    per-block scales are permuted into that same shard-major order
    before slicing (`_half_split_perm`), which keeps every shard's
    sub-QTensor self-consistent and the fused dequant-GEMM path intact.
    Layouts that cannot be sliced consistently (bit planes, k-quant
    superblocks, shards that straddle a scale block) dequantize once and
    take the dense partial-matmul path instead."""
    if comm is None or not comm.enabled:
        return linear(x, w, bias, compute_dtype)
    import dataclasses

    from bigdl_tpu.parallel import qcollectives as qc
    from bigdl_tpu.parallel._compat import shard_map

    from jax.sharding import PartitionSpec as P

    ax = comm.axis_name
    n = comm.axis_size
    if isinstance(w, QTensor):
        spec = w.spec
        K = w.shape[-1]
        h = K // (2 * n)  # columns per nibble plane per shard
        if (spec.storage == "packed_u8" and not spec.superblock
                and w.sub_scales is None
                and K % (2 * n) == 0 and h % spec.block_size == 0):
            x = _half_split_perm(x, n)
            w = dataclasses.replace(
                w, scales=_half_split_perm(w.scales, n),
                mins=(None if w.mins is None
                      else _half_split_perm(w.mins, n)),
            )
        elif (spec.storage in ("int8", "fp8_e4m3", "fp8_e5m2")
                and not spec.superblock and w.sub_scales is None
                and K % n == 0 and (K // n) % spec.block_size == 0):
            pass  # unpacked codes: contiguous K slices self-consistent
        else:
            w = w.dequantize(compute_dtype)
    if not isinstance(w, QTensor) and x.shape[-1] % n:
        # contraction dim not shardable: keep the exact implicit psum
        return linear(x, w, bias, compute_dtype)
    xspec = P(*([None] * (x.ndim - 1) + [ax]))
    wspec = P(None, ax)  # [O, K/n]; QTensor leaves take it as a prefix

    def part(xs, ws):
        y = linear(xs, ws, None, compute_dtype)
        return qc.quantized_psum(
            y, ax, qtype=comm.qtype, axis_size=n,
            block_size=comm.block_size,
            error_feedback=comm.error_feedback,
        )

    f = shard_map(part, mesh=comm.mesh, in_specs=(xspec, wspec),
                  out_specs=P(), check_vma=False)
    y = f(x, w)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y
