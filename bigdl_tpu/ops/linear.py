"""Quantized / dense linear op.

Equivalent of `LowBitLinear.forward` in the reference
(low_bit_linear.py:606-716): one entry point that dispatches on weight
type and shape. On TPU the prefill/decode split the reference implements
with two SYCL kernels (`xe_linear.forward_new` vs `xe_batch.batch_forward`)
is handled by XLA specializing the same fused dequant+matmul graph per
input shape; a Pallas kernel path covers the memory-bound decode GEMV.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from bigdl_tpu.quant import QTensor


def linear(
    x: jax.Array,
    w: Union[QTensor, jax.Array],
    bias: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """y = x @ W^T (+ bias). W has logical shape [out_features, in_features].

    For QTensor weights the dequantization is expressed in-graph so XLA
    fuses unpack+scale into the matmul's operand read; weights stay packed
    in HBM.
    """
    if isinstance(w, QTensor):
        wd = w.dequantize(compute_dtype)
    else:
        wd = w.astype(compute_dtype)
    y = jnp.einsum(
        "...k,ok->...o",
        x.astype(compute_dtype),
        wd,
        preferred_element_type=compute_dtype,
    )
    if bias is not None:
        y = y + bias.astype(compute_dtype)
    return y
