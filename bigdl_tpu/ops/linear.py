"""Quantized / dense linear op.

Equivalent of `LowBitLinear.forward` in the reference
(low_bit_linear.py:606-716): one entry point that dispatches on weight
type and shape. The prefill/decode split the reference implements with
two SYCL kernels (`xe_linear.forward_new` vs `xe_batch.batch_forward`)
maps to: decode-shaped (few rows) sym_int4 matmuls go to the Pallas
fused dequant-GEMV kernel (packed weights cross HBM as nibbles); other
shapes use an in-graph dequant that XLA fuses into the matmul.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from bigdl_tpu.quant import QTensor

# Decode GEMV threshold, same role as the reference's `use_batch_forward`
# heuristic (low_bit_linear.py:272-309): below this many rows the matmul
# is weight-bandwidth-bound and the packed kernel wins.
_GEMV_MAX_ROWS = 32


def _rows(shape) -> int:
    n = 1
    for d in shape[:-1]:
        n *= d
    return n


def _run_sym_int4(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_int4

    return qmatmul_int4(x, w.data, w.scales, out_dtype=x.dtype, block_o=bo)


def _run_asym_int4(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_asym_int4

    return qmatmul_asym_int4(x, w.data, w.scales, w.mins, out_dtype=x.dtype,
                             block_o=bo)


def _run_codebook(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_codebook

    return qmatmul_codebook(x, w.data, w.scales, codebook=w.spec.codebook,
                            block=w.spec.block_size, out_dtype=x.dtype,
                            block_o=bo)


def _run_int8(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_int8

    return qmatmul_int8(x, w.data, w.scales, out_dtype=x.dtype, block_o=bo)


def _run_asym_int5(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_bytes

    return qmatmul_bytes(x, w.data, w.scales, w.mins, decode="i8",
                         block=w.spec.block_size, out_dtype=x.dtype,
                         block_o=bo)


def _run_fp8(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_fp8

    return qmatmul_fp8(x, w.data, w.scales, block=w.spec.block_size,
                       out_dtype=x.dtype, block_o=bo)


def _run_planes(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_planes

    spec = w.spec
    if spec.name == "fp6":  # exact arithmetic e2m3 decode
        decode = ("e2m3",)
    elif spec.codebook is not None:  # nf3: 8-entry select tree
        decode = ("lut", tuple(float(c) for c in spec.codebook))
    else:  # sym_int5: v - 16
        decode = ("offset", 16)
    return qmatmul_planes(x, w.data, w.scales, spec.planes, decode,
                          spec.block_size, out_dtype=x.dtype, block_o=bo)


def _run_q4k(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_q4k

    return qmatmul_q4k(x, w.data, w.scales, w.mins, w.sub_scales,
                       w.sub_mins, out_dtype=x.dtype, block_o=bo)


def _run_q5k(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_q5k

    return qmatmul_q5k(x, w.data, w.scales, w.mins, w.sub_scales,
                       w.sub_mins, out_dtype=x.dtype, block_o=bo)


def _run_q2k(x, w, bo):
    from bigdl_tpu.ops.pallas import qmatmul_q2k

    return qmatmul_q2k(x, w.data, w.scales, w.mins, w.sub_scales,
                       w.sub_mins, out_dtype=x.dtype, block_o=bo)


def _run_q6k(x, w, bo):
    # planar q3_k is structurally identical to q6_k (int8 centered
    # codes, int8 sub-scales per 16, f16 d per 256) and shares its kernel
    from bigdl_tpu.ops.pallas import qmatmul_q6k

    return qmatmul_q6k(x, w.data, w.scales, w.sub_scales, out_dtype=x.dtype,
                       block_o=bo)


class _GemvEntry(NamedTuple):
    """Eligibility + kernel for one qtype, registered in one place.

    k_multiple folds every per-format shape rule into one divisibility
    check on the LOGICAL contraction dim: whole quant blocks per packed
    plane (sym/asym_int4 64, nf4/fp4 128), whole super-blocks (k-quants
    256), and 128-lane alignment of the finest plane split for the
    multi-plane kernels (fp6/q2_k 512; sym_int5/nf3/q5_k 1024 — the
    eighth-split 1-bit plane slices at K/8-byte offsets)."""
    k_multiple: int
    run: Callable  # (x [M, K] compute dtype, w, block_o) -> y [M, O]


# every qtype with a decode path dispatches to a fused Pallas kernel —
# the in-kernel decode mirrors QTensor.dequantize exactly
_QGEMV_QTYPES = {
    "sym_int4": _GemvEntry(64, _run_sym_int4),
    "asym_int4": _GemvEntry(64, _run_asym_int4),
    "nf4": _GemvEntry(128, _run_codebook),
    "fp4": _GemvEntry(128, _run_codebook),
    "sym_int8": _GemvEntry(32, _run_int8),
    "asym_int5": _GemvEntry(32, _run_asym_int5),
    "fp8_e4m3": _GemvEntry(128, _run_fp8),
    "fp8_e5m2": _GemvEntry(128, _run_fp8),
    "sym_int5": _GemvEntry(1024, _run_planes),
    "fp6": _GemvEntry(512, _run_planes),
    "nf3": _GemvEntry(1024, _run_planes),
    "q2_k": _GemvEntry(512, _run_q2k),
    "q3_k": _GemvEntry(256, _run_q6k),
    "q4_k": _GemvEntry(256, _run_q4k),
    "q5_k": _GemvEntry(1024, _run_q5k),
    "q6_k": _GemvEntry(256, _run_q6k),
}


def _use_qgemv(x: jax.Array, w: QTensor) -> bool:
    from bigdl_tpu.ops.pallas import use_pallas

    entry = _QGEMV_QTYPES.get(w.qtype)
    if entry is None or w.data.ndim != 2:
        return False
    out, kw_ = w.data.shape
    if out % 128 != 0:
        return False
    # the kernels tile O at >= 128 rows (Mosaic lane rule forbids
    # smaller output tiles); if even a 128-row tile's persistent weight
    # block cannot fit the scoped-VMEM budget half, fall back to the
    # XLA dequant path rather than compile a kernel that overflows vmem
    row_bytes = kw_ * w.data.dtype.itemsize
    if 128 * row_bytes > 5 * 1024 * 1024:
        return False
    if w.shape[-1] % entry.k_multiple != 0:
        return False
    return _rows(x.shape) <= _GEMV_MAX_ROWS and use_pallas()


def linear(
    x: jax.Array,
    w: Union[QTensor, jax.Array],
    bias: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """y = x @ W^T (+ bias). W has logical shape [out_features, in_features].

    For QTensor weights the dequantization is expressed in-graph so XLA
    fuses unpack+scale into the matmul's operand read; weights stay packed
    in HBM.
    """
    if isinstance(w, QTensor):
        if _use_qgemv(x, w):
            block_o = 256 if w.data.shape[0] % 256 == 0 else 128
            y = _QGEMV_QTYPES[w.qtype].run(
                x.astype(compute_dtype), w, block_o
            )
            if bias is not None:
                y = y + bias.astype(compute_dtype)
            return y
        wd = w.dequantize(compute_dtype)
    else:
        wd = w.astype(compute_dtype)
    y = jnp.einsum(
        "...k,ok->...o",
        x.astype(compute_dtype),
        wd,
        preferred_element_type=compute_dtype,
    )
    if bias is not None:
        y = y + bias.astype(compute_dtype)
    return y
