"""Quantized / dense linear op.

Equivalent of `LowBitLinear.forward` in the reference
(low_bit_linear.py:606-716): one entry point that dispatches on weight
type and shape. The prefill/decode split the reference implements with
two SYCL kernels (`xe_linear.forward_new` vs `xe_batch.batch_forward`)
maps to: decode-shaped (few rows) sym_int4 matmuls go to the Pallas
fused dequant-GEMV kernel (packed weights cross HBM as nibbles); other
shapes use an in-graph dequant that XLA fuses into the matmul.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from bigdl_tpu.quant import QTensor

# Decode GEMV threshold, same role as the reference's `use_batch_forward`
# heuristic (low_bit_linear.py:272-309): below this many rows the matmul
# is weight-bandwidth-bound and the packed kernel wins.
_GEMV_MAX_ROWS = 32


def _rows(shape) -> int:
    n = 1
    for d in shape[:-1]:
        n *= d
    return n


# formats the fused GEMV kernel decodes in-kernel: sym/asym_int4
# arithmetically, nf4/fp4 via their static codebooks, q4_k/q6_k via
# factored two-level scales (planar layout, quant/kq_planar.py)
_QGEMV_QTYPES = ("sym_int4", "asym_int4", "nf4", "fp4", "sym_int8",
                 "q4_k", "q6_k")


def _use_qgemv(x: jax.Array, w: QTensor) -> bool:
    from bigdl_tpu.ops.pallas import use_pallas

    if w.qtype not in _QGEMV_QTYPES or w.data.ndim != 2:
        return False
    out, kw_ = w.data.shape
    block = w.spec.block_size
    if out % 128 != 0:
        return False
    # the kernels tile O at >= 128 rows (Mosaic lane rule forbids
    # smaller output tiles); if even a 128-row tile's persistent weight
    # block cannot fit the scoped-VMEM budget half, fall back to the
    # XLA dequant path rather than compile a kernel that overflows vmem
    row_bytes = kw_ * w.data.dtype.itemsize
    if 128 * row_bytes > 5 * 1024 * 1024:
        return False
    if w.qtype == "sym_int8":  # unpacked: K = data's last dim directly
        if kw_ % block != 0:
            return False
    elif w.qtype == "q6_k":  # unpacked; K tiles align to super-blocks
        if kw_ % 256 != 0:
            return False
    elif w.qtype == "q4_k":
        if (kw_ * 2) % 256 != 0:  # whole super-blocks per row
            return False
    # each half-split nibble plane must cover whole quant blocks; asym
    # additionally needs an even per-plane block count for the scale views
    elif (kw_ * 2) % (2 * block) != 0 or (
        w.qtype == "asym_int4" and (kw_ * 2 // block) % 2 != 0
    ):
        return False
    return _rows(x.shape) <= _GEMV_MAX_ROWS and use_pallas()


def linear(
    x: jax.Array,
    w: Union[QTensor, jax.Array],
    bias: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """y = x @ W^T (+ bias). W has logical shape [out_features, in_features].

    For QTensor weights the dequantization is expressed in-graph so XLA
    fuses unpack+scale into the matmul's operand read; weights stay packed
    in HBM.
    """
    if isinstance(w, QTensor):
        if _use_qgemv(x, w):
            from bigdl_tpu.ops.pallas import qmatmul_codebook, qmatmul_int4

            block_o = 256 if w.data.shape[0] % 256 == 0 else 128
            if w.qtype == "sym_int4":
                y = qmatmul_int4(
                    x.astype(compute_dtype), w.data, w.scales,
                    out_dtype=compute_dtype, block_o=block_o,
                )
            elif w.qtype == "asym_int4":
                from bigdl_tpu.ops.pallas import qmatmul_asym_int4

                y = qmatmul_asym_int4(
                    x.astype(compute_dtype), w.data, w.scales, w.mins,
                    out_dtype=compute_dtype, block_o=block_o,
                )
            elif w.qtype == "q4_k":
                from bigdl_tpu.ops.pallas import qmatmul_q4k

                y = qmatmul_q4k(
                    x.astype(compute_dtype), w.data, w.scales, w.mins,
                    w.sub_scales, w.sub_mins,
                    out_dtype=compute_dtype, block_o=block_o,
                )
            elif w.qtype == "q6_k":
                from bigdl_tpu.ops.pallas import qmatmul_q6k

                y = qmatmul_q6k(
                    x.astype(compute_dtype), w.data, w.scales, w.sub_scales,
                    out_dtype=compute_dtype, block_o=block_o,
                )
            elif w.qtype == "sym_int8":
                from bigdl_tpu.ops.pallas import qmatmul_int8

                y = qmatmul_int8(
                    x.astype(compute_dtype), w.data, w.scales,
                    out_dtype=compute_dtype, block_o=block_o,
                )
            else:  # nf4 / fp4: static-codebook decode in-kernel
                y = qmatmul_codebook(
                    x.astype(compute_dtype), w.data, w.scales,
                    codebook=w.spec.codebook, block=w.spec.block_size,
                    out_dtype=compute_dtype, block_o=block_o,
                )
            if bias is not None:
                y = y + bias.astype(compute_dtype)
            return y
        wd = w.dequantize(compute_dtype)
    else:
        wd = w.astype(compute_dtype)
    y = jnp.einsum(
        "...k,ok->...o",
        x.astype(compute_dtype),
        wd,
        preferred_element_type=compute_dtype,
    )
    if bias is not None:
        y = y + bias.astype(compute_dtype)
    return y
