"""Scaled dot-product attention with GQA.

Equivalent of the reference's `scaled_dot_product_attention` dispatch
(models/common.py:222-270) over the `xe_addons.sdp / sdp_causal /
sdp_fp8*` fused kernels. Here one jnp implementation covers all mask
shapes (XLA fuses it well on TPU); a Pallas flash-attention kernel is
planned as the long-sequence prefill fast path.

Softmax is computed in float32 (the reference kernels likewise accumulate
at higher precision).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
) -> jax.Array:
    """q [B,T,Hq,D]; k,v [B,S,Hkv,D]; mask broadcastable to [B,Hkv,G,T,S]
    (bool: True = attend). Returns [B,T,Hq,D] in q.dtype.

    Hq must be a multiple of Hkv (grouped-query attention); kv heads are
    never materialized repeated — the grouping happens in the einsum.
    """
    b, t, hq, d = q.shape
    _, s, hkv, _ = k.shape
    assert hq % hkv == 0, f"GQA needs Hq % Hkv == 0, got {hq} % {hkv}"
    g = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    qg = q.reshape(b, t, hkv, g, d)
    scores = jnp.einsum(
        "bthgd,bshd->bhgts", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores.astype(jnp.float32) * scale
    if softcap is not None:
        scores = jnp.tanh(scores / softcap) * softcap
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, _NEG_INF)
        else:
            scores = scores + mask.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgts,bshd->bthgd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, hq, d).astype(q.dtype)


def causal_mask(t: int, s: int, offset: int = 0) -> jax.Array:
    """[T, S] bool mask: query i attends kv j iff j <= i + offset."""
    qi = jnp.arange(t)[:, None] + offset
    kj = jnp.arange(s)[None, :]
    return kj <= qi
