"""User-facing API.

Mirrors the reference's two entry points (SURVEY.md §3.1):
- `AutoModelForCausalLM.from_pretrained(path, load_in_low_bit=...)`
  (reference transformers/model.py:111) — load an HF checkpoint directory
  and quantize on the fly;
- `optimize_model(...)` (reference optimize.py:197) — quantize an
  already-built dense param tree;
plus `save_low_bit`/`load_low_bit` fast reload (model.py:58-104).

The returned `TpuModel` wraps (config, params, qtype) with a
`generate()` that compiles one XLA program per (bucket, max_new_tokens)
and runs the whole decode loop on device.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.generate import GenerationConfig, generate_tokens, pad_prompts
from bigdl_tpu.models import get_family
from bigdl_tpu.models.config import ModelConfig


def optimize_model(
    params: dict,
    config: ModelConfig,
    low_bit: str = "sym_int4",
    lm_head_qtype: Optional[str] = None,
    merge_fused: bool = True,
) -> dict:
    """Quantize a dense param tree in place of the reference's module
    surgery (optimize.py:197 → ggml_convert_low_bit). merge_fused fuses
    qkv and gate/up into single linears (the reference's merge_qkv,
    models/common.py:22-53) — bit-identical outputs, fewer kernel calls
    on the decode hot path."""
    family = get_family(config.model_type)
    out = family.quantize_params(params, low_bit, lm_head_qtype)
    if merge_fused and hasattr(family, "merge_fused_params"):
        out = family.merge_fused_params(out, config)
    return out


@dataclasses.dataclass
class TpuModel:
    config: ModelConfig
    params: dict
    qtype: str
    # set by to_mesh(): params are sharded over this jax.sharding.Mesh and
    # every generate/serving entry point runs SPMD under it
    mesh: Optional[Any] = None
    # set by to_mesh(comm_qtype=...): parallel/qcollectives.CommConfig —
    # routes the TP row-parallel epilogues through the block-quantized
    # ring all-reduce; None keeps GSPMD's implicit fp32 psum
    comm: Optional[Any] = None

    @property
    def family(self):
        return get_family(self.config.model_type)

    @property
    def pp_size(self) -> int:
        if self.mesh is not None and "pp" in getattr(self.mesh, "axis_names", ()):
            return self.mesh.shape["pp"]
        return 1

    @property
    def forward_fn(self):
        """The forward used by generate()/the serving engine: the plain
        family forward, or — when the mesh has a pp axis — the pipeline
        step with per-stage KV caches (parallel/pipeline.py), which keeps
        the same (config, params, tokens, cache, mode, last_logits_only)
        call shape so callers don't branch."""
        if self.pp_size <= 1:
            fwd = self.family.forward
            if self.comm is not None and self.comm.enabled:
                if getattr(self, "_comm_fwd", None) is None:
                    import functools
                    import inspect

                    if "comm" not in inspect.signature(fwd).parameters:
                        raise NotImplementedError(
                            f"{self.config.model_type}'s forward does not "
                            "take comm= — quantized TP collectives are "
                            "wired for the llama family only"
                        )
                    # cached: a stable callable identity keeps the jit
                    # caches in generate/serving warm across calls
                    self._comm_fwd = functools.partial(fwd, comm=self.comm)
                return self._comm_fwd
            return fwd
        if getattr(self, "_pp_step", None) is None:
            from bigdl_tpu.parallel.pipeline import make_pipeline_step

            step = make_pipeline_step(self.config, self.family.forward,
                                      self.mesh)

            def pp_forward(config, params, tokens, cache,
                           mode="prefill", last_logits_only=False,
                           collect_obs: int = 0, **kw):
                # features beyond the cached prefill/decode step (plus
                # SnapKV's collect_obs) must fail loudly, not silently
                # drop their kwargs (array-safe: no truthiness on arrays)
                unsupported = sorted(
                    k for k, v in kw.items()
                    if v is not None and (
                        not isinstance(v, (bool, int, float)) or v
                    )
                )
                if cache is None or unsupported:
                    raise NotImplementedError(
                        "pipeline-parallel forward supports the cached "
                        "prefill/decode step only; got cache=None or "
                        f"kwargs {unsupported} — run this path on "
                        "a tp/dp mesh (pp=1) instead"
                    )
                return step(params, tokens, cache, mode=mode,
                            last_logits_only=last_logits_only,
                            collect_obs=collect_obs)

            self._pp_step = pp_forward
        return self._pp_step

    def to_mesh(self, mesh=None, tp: Optional[int] = None,
                dp: Optional[int] = None, sp: int = 1,
                pp: int = 1,
                comm_qtype: Optional[str] = None) -> "TpuModel":
        """Shard the params for multi-chip inference and make generate()
        / the serving engine run SPMD over the mesh.

        Megatron-style TP: column-parallel qkv/gate/up, row-parallel
        o/down, vocab-sharded embed+head (parallel/sharding.py). The
        reference reaches the same point via DeepSpeed-AutoTP module
        detection + an explicit mp_group.all_reduce
        (convert.py:152-234, low_bit_linear.py:675-682); here the
        PartitionSpecs make XLA insert the psums over ICI.

        pp > 1 (or a mesh with a 'pp' axis) additionally shards the layer
        stacks across pipeline stages — models bigger than one slice's
        HBM serve via make_pipeline_step (the reference's
        pipeline_parallel_stages=N, model.py:352-365).

        mesh=None builds a (pp, dp, sp, tp) mesh over all visible devices
        (tp defaulting to every device).

        comm_qtype ("none"|"int8"|"fp8_e4m3", default "none" — or the
        model's `default_comm_qtype` attribute, which `serve
        --comm-qtype` sets) quantizes the wire format of the per-layer
        TP all-reduce epilogues (parallel/qcollectives.py,
        docs/parallelism.md): block-scaled payloads with error feedback
        replace the implicit fp32 psum behind wo / w_down.
        """
        from bigdl_tpu.parallel import make_mesh, shard_params
        from bigdl_tpu.parallel.mesh import mesh_shape_for
        from bigdl_tpu.parallel.sharding import param_specs

        if mesh is None:
            n = len(jax.devices())
            if pp > 1:
                # pp requires a 4-axis mesh; fill unspecified axes so
                # to_mesh(pp=2) works on its own instead of silently
                # building a pp-less mesh
                dp = dp or 1
                tp = tp or max(1, n // (pp * dp * sp))
                if pp * dp * sp * tp > n:
                    raise ValueError(
                        f"pp*dp*sp*tp = {pp * dp * sp * tp} exceeds {n} devices"
                    )
                mesh = make_mesh(
                    (pp, dp, sp, tp),
                    devices=jax.devices()[: pp * dp * sp * tp],
                    axes=("pp", "dp", "sp", "tp"),
                )
            elif tp is not None and dp is not None:
                # fully specified: use exactly dp*sp*tp devices (a
                # subset of the host's devices is fine)
                if dp * sp * tp > n:
                    raise ValueError(
                        f"dp*sp*tp = {dp * sp * tp} exceeds {n} devices"
                    )
                mesh = make_mesh(
                    (dp, sp, tp), devices=jax.devices()[: dp * sp * tp]
                )
            else:
                mesh = make_mesh(mesh_shape_for(n, tp=tp, dp=dp, sp=sp))
        if "tp" not in mesh.axis_names:
            raise ValueError(
                f"mesh axes {mesh.axis_names} lack 'tp' — param_specs "
                "shard weights over a 'tp' axis (use make_mesh(..., "
                "axes=('dp','sp','tp')))"
            )
        if (
            self.config.num_key_value_heads % (tp_size := mesh.shape["tp"])
            and not hasattr(self.family, "init_cache")
        ):
            # families with their own cache (rwkv's recurrent state,
            # MLA's latent) don't shard a KV pool over kv heads — the
            # divisibility requirement applies to the standard KVCache
            # layout only
            raise ValueError(
                f"num_key_value_heads={self.config.num_key_value_heads} "
                f"not divisible by tp={tp_size}"
            )
        self.mesh = mesh
        if mesh.shape["tp"] > 1 and hasattr(self.family, "unmerge_fused_params"):
            # fused qkv/gate-up boundaries don't align with tp shard
            # boundaries (GQA), which would force GSPMD resharding every
            # layer — split back before sharding (lossless)
            self.params = self.family.unmerge_fused_params(
                self.params, self.config
            )
        specs = param_specs(self.config)
        if "pp" in mesh.axis_names and mesh.shape["pp"] > 1:
            from bigdl_tpu.parallel.pipeline import pp_param_specs

            if self.config.num_hidden_layers % mesh.shape["pp"]:
                raise ValueError(
                    f"num_hidden_layers={self.config.num_hidden_layers} "
                    f"not divisible by pp={mesh.shape['pp']}"
                )
            if self.config.learned_positions or self.config.embed_layernorm:
                # the pipeline stage embeds with embed_tokens only; gpt2's
                # wpe table and bloom's embedding layernorm would be
                # silently skipped — refuse rather than generate garbage
                raise NotImplementedError(
                    f"pipeline parallelism does not yet support "
                    f"{self.config.model_type} (learned positions / "
                    "embedding layernorm)"
                )
            specs = pp_param_specs(self.config, specs)
        self.params = shard_params(self.params, specs, mesh)
        self._pp_step = None  # rebuilt for the new mesh on next use
        self._comm_fwd = None
        from bigdl_tpu.parallel.qcollectives import (
            CommConfig, resolve_comm_qtype,
        )

        cq = resolve_comm_qtype(
            comm_qtype if comm_qtype is not None
            else getattr(self, "default_comm_qtype", None)
        )
        self.comm = None
        if cq != "none":
            if self.pp_size > 1:
                raise NotImplementedError(
                    "comm_qtype is wired for the tp epilogues of the "
                    "single-stage forward; pipeline stages keep fp32 "
                    "collectives (pp=1 to quantize comms)"
                )
            self.comm = CommConfig(mesh=mesh, axis_name="tp", qtype=cq)
        return self

    def _mesh_ctx(self):
        import contextlib

        if self.mesh is None:
            return contextlib.nullcontext()
        from bigdl_tpu.parallel._compat import set_mesh

        return set_mesh(self.mesh)

    def save_low_bit(self, path: str, *, faults=None) -> None:
        """Atomic, digest-manifested save (convert/low_bit.py): a kill
        mid-save leaves any previous checkpoint at `path` bit-identical,
        and the written artifact carries per-tensor crc32/sha256 digests
        for load-time verification."""
        from bigdl_tpu.convert import save_low_bit

        save_low_bit(path, self.config, self.params, self.qtype,
                     faults=faults)

    def generate(
        self,
        prompts: Union[Sequence[Sequence[int]], np.ndarray],
        max_new_tokens: int = 32,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        repetition_penalty: float = 1.0,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        seed: int = 0,
        quantize_kv: bool = False,
        compress_kv: Optional[int] = None,  # SnapKV budget (slots kept)
        compress_window: int = 32,
        streaming_window: Optional[int] = None,  # attention-sink ring size
        streaming_sink: int = 4,
    ) -> np.ndarray:
        """prompts: ragged list of token-id lists (or [B, T] array).
        Returns [B, max_new_tokens] generated ids.

        quantize_kv is the reference's IPEX_LLM_QUANTIZE_KV_CACHE (FP8 KV);
        compress_kv the reference's IPEX_LLM_COMPRESS_KV_CACHE (SnapKV) —
        applied only when the prompt is longer than the budget.
        streaming_window enables StreamingLLM-style attention sinks
        (reference example/GPU/Applications/streaming-llm): the cache is
        a fixed `streaming_window` slots — the first `streaming_sink`
        tokens plus a rolling recent region — so max_new_tokens may
        exceed the cache and generation runs in constant memory."""
        from bigdl_tpu.utils import flags

        if isinstance(prompts, np.ndarray):
            prompts = [list(row) for row in prompts]
        if not prompts:
            raise ValueError("prompts is empty — nothing to generate")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if top_k is not None:
            # HF semantics: top_k <= 0 disables the filter (the serving
            # kernel's "<=0 disables" convention); larger than vocab caps
            top_k = (None if top_k <= 0
                     else min(top_k, self.config.vocab_size))
        if any(len(p) == 0 for p in prompts):
            raise ValueError(
                "empty prompt row — every prompt needs at least one token"
            )
        lo = min(min(p) for p in prompts)
        hi = max(max(p) for p in prompts)
        if lo < 0 or hi >= self.config.vocab_size:
            raise ValueError(
                f"prompt token ids must be in [0, {self.config.vocab_size}); "
                f"got range [{lo}, {hi}] — wrong tokenizer for this model?"
            )
        # env-flag defaults (reference IPEX_LLM_QUANTIZE_KV_CACHE /
        # IPEX_LLM_COMPRESS_KV_CACHE / IPEX_LLM_PERFORMANCE_MODE)
        explicit_quantize_kv = quantize_kv
        explicit_compress_kv = compress_kv
        if not quantize_kv:
            quantize_kv = flags.quantize_kv_default()
        if compress_kv is None:
            compress_kv = flags.compress_kv_budget()
        cache_init = getattr(self.family, "init_cache", None)
        if cache_init is not None and compress_kv is not None:
            # recurrent-state families (rwkv) have no KV cache to compress
            compress_kv = None
        if (
            compress_kv is not None
            and max(len(p) for p in prompts) > compress_kv  # would apply
            and (self.config.sliding_window or self.config.alibi)
        ):
            # After SnapKV compression cache slots no longer correspond to
            # token positions, so sliding-window masks and ALiBi
            # slot-distance biases become incoherent (the reference gates
            # DynamicCompressCache by model type the same way —
            # models/utils.py:317-331).
            warnings.warn(
                "SnapKV compress_kv skipped: incompatible with "
                "sliding-window/ALiBi attention for this config"
            )
            compress_kv = None
        if (
            flags.performance_mode()
            and streaming_window is None  # lookup has no eviction support
            and cache_init is None  # lookup verify needs a rewindable KV cache
            and not do_sample
            and compress_kv is None  # lookup path has no SnapKV support
            and repetition_penalty == 1.0  # lookup has no penalty support
            and max(len(p) for p in prompts) >= 256
        ):
            return self.generate_lookup(
                prompts, max_new_tokens=max_new_tokens,
                eos_token_id=eos_token_id, pad_token_id=pad_token_id,
                seed=seed, quantize_kv=quantize_kv,
            )
        streaming = None
        if streaming_window is not None:
            from bigdl_tpu.streaming import validate_streaming

            validate_streaming(self.config, streaming_window, streaming_sink)
            if explicit_quantize_kv or explicit_compress_kv is not None:
                raise ValueError(
                    "streaming_window is incompatible with quantize_kv/"
                    "compress_kv — the evicted keys are re-based in place"
                )
            if quantize_kv or compress_kv is not None:
                # env-flag defaults (BIGDL_TPU_QUANTIZE_KV_CACHE /
                # _COMPRESS_KV_CACHE), not a caller choice: disable for
                # this call rather than make streaming unusable under them
                warnings.warn(
                    "streaming_window: ignoring env-default "
                    "quantize_kv/compress_kv for this call"
                )
                quantize_kv, compress_kv = False, None
            if cache_init is not None:
                raise ValueError(
                    "streaming_window supports the standard KV cache only; "
                    f"the {self.config.model_type} family uses a custom "
                    "cache layout (family init_cache hook)"
                )
            lens = {len(p) for p in prompts}
            if len(lens) > 1:
                raise ValueError(
                    "streaming_window needs equal-length prompts (the sink "
                    "slots must hold real tokens in every row) — batch "
                    "equal lengths or generate per prompt"
                )
            if max(lens) >= streaming_window:
                raise ValueError(
                    f"prompt ({max(lens)} tokens) must be shorter than "
                    f"streaming_window ({streaming_window}); raise the "
                    "window or pre-truncate the prompt"
                )
            streaming = (streaming_sink, streaming_window)
        # streaming: pad to the exact (equal) prompt length, not a
        # power-of-two bucket — the sink slots must hold real tokens,
        # and a bucket as large as the window would leave no decode room
        tokens, start = pad_prompts(
            prompts, pad_token_id,
            bucket=(len(prompts[0]) if streaming is not None else None),
        )
        gen = GenerationConfig(
            max_new_tokens=max_new_tokens,
            do_sample=do_sample,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            repetition_penalty=repetition_penalty,
            eos_token_id=eos_token_id,
            pad_token_id=pad_token_id,
        )
        from bigdl_tpu.utils import cache_len_for

        cache_len = (
            streaming_window if streaming is not None
            else cache_len_for(tokens.shape[1], max_new_tokens)
        )
        budget = 0
        if compress_kv is not None and tokens.shape[1] > compress_kv:
            budget = compress_kv
        with self._mesh_ctx():
            out = generate_tokens(
                self.config,
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(start),
                jax.random.PRNGKey(seed),
                gen,
                self.forward_fn,
                cache_len=cache_len,
                quantize_kv=quantize_kv,
                compress_budget=budget,
                compress_window=min(compress_window, max(budget - 1, 1)),
                last_logits=flags.last_lm_head_default(),
                cache_init=cache_init,
                streaming=streaming,
            )
        return np.asarray(out)


    def generate_lookup(
        self,
        prompts,
        max_new_tokens: int = 32,
        lookahead: int = 4,
        max_ngram: int = 3,
        **kw,
    ) -> np.ndarray:
        """Prompt-lookup decoding (reference lookup.py:274 /
        IPEX_LLM_PERFORMANCE_MODE): n-gram candidates, one verify forward."""
        from bigdl_tpu.decode import lookup_generate

        # under a pp mesh the verify forward is the pipeline step
        # (forward_fn keeps the family-forward call shape, so the lookup
        # while_loop runs unchanged with per-stage KV caches)
        with self._mesh_ctx():
            return lookup_generate(
                self.config, self.params, prompts, self.forward_fn,
                max_new_tokens=max_new_tokens, lookahead=lookahead,
                max_ngram=max_ngram, **kw,
            )

    def self_draft_params(self):
        """The sym_int4 self-draft of this model's weights (the
        reference's self-speculative draft, model.py:366-379), built once
        and cached. Only meaningful when the model holds higher-precision
        weights — a draft equal to the target is all cost, no speedup."""
        from bigdl_tpu.quant.qtypes import resolve_qtype

        try:
            is_dense = resolve_qtype(self.qtype).is_dense
        except ValueError:  # e.g. "gguf_native" mixed trees
            is_dense = False
        if not is_dense:
            # re-quantizing already-quantized weights is a no-op
            # (quantize_params skips QTensor leaves) — the "draft" would
            # be weight-identical to the target: all cost, no speedup.
            raise ValueError(
                f"model qtype {self.qtype!r} is already quantized; a "
                "sym_int4 self-draft would equal the target. Pass "
                "explicit draft_params or load the target as fp16/bf16."
            )
        draft_params = getattr(self, "_draft_params", None)
        if draft_params is None:
            draft_params = optimize_model(self.params, self.config, "sym_int4")
            object.__setattr__(self, "_draft_params", draft_params)
        return draft_params

    def generate_speculative(
        self,
        prompts,
        draft_params=None,
        max_new_tokens: int = 32,
        draft_k: int = 4,
        **kw,
    ) -> np.ndarray:
        """Self-speculative decoding (reference speculative.py:803). With
        draft_params=None the draft is a sym_int4 re-quantization of this
        model's weights (the reference's self-draft, model.py:366-379) —
        only meaningful when this model holds higher-precision weights.
        The self-draft is built once and cached on the model."""
        from bigdl_tpu.decode import speculative_generate

        if self.pp_size > 1:
            raise NotImplementedError(
                "speculative decoding jits the family forward directly "
                "and would gather pp-sharded layer stacks onto every "
                "stage; use plain generate() under pipeline parallelism"
            )

        if draft_params is None:
            draft_params = self.self_draft_params()
        return speculative_generate(
            self.config, self.params, draft_params, prompts,
            self.family.forward, max_new_tokens=max_new_tokens,
            draft_k=draft_k, **kw,
        )


def _merged_model(config, params, qtype, merge_fused: bool = True) -> TpuModel:
    """Shared loader tail: fuse qkv/gate-up when the family supports it
    (lossless, reference merge_qkv) before wrapping. merge_fused=False
    keeps the split layout — the gguf export path consumes it directly
    and would otherwise pay a full merge+unmerge round trip."""
    family = get_family(config.model_type)
    if merge_fused and hasattr(family, "merge_fused_params"):
        params = family.merge_fused_params(params, config)
    return TpuModel(config=config, params=params, qtype=qtype)


class AutoModelForCausalLM:
    """Loader namespace, reference-compatible spelling
    (ipex_llm.transformers.AutoModelForCausalLM)."""

    @classmethod
    def from_pretrained(
        cls,
        model_path: str,
        load_in_low_bit: str = "sym_int4",
        load_in_4bit: bool = False,
        merge_fused: bool = True,
        **_ignored,
    ) -> TpuModel:
        from bigdl_tpu.convert import load_hf_checkpoint

        qtype = "sym_int4" if load_in_4bit else load_in_low_bit
        config, params, qtype = load_hf_checkpoint(model_path, qtype=qtype)
        return _merged_model(config, params, qtype, merge_fused)

    @classmethod
    def load_low_bit(cls, path: str, verify: str = "fast",
                     salvage: bool = False) -> TpuModel:
        """Load a save_low_bit checkpoint with integrity verification
        (convert/low_bit.py): verify="off"|"fast" (crc32)|"full" (sha256
        + NaN/inf + scale-range validation). Corruption raises a
        structured IntegrityError naming every bad tensor; salvage=True
        loads the valid subset instead and leaves the quarantine report
        on the returned model as `model.salvage_report` (None = clean).
        A salvaged model is for inspection/weight recovery — forward
        passes will fail on the quarantined tensors."""
        from bigdl_tpu.convert import load_low_bit

        if salvage:
            config, params, qtype, report = load_low_bit(
                path, verify=verify, salvage=True,
            )
        else:
            config, params, qtype = load_low_bit(path, verify=verify)
            report = None
        # a quarantined (partial) tree can't run the fused merge — the
        # missing tensors would KeyError mid-surgery
        model = _merged_model(config, params, qtype,
                              merge_fused=report is None)
        model.salvage_report = report
        return model

    @classmethod
    def from_gguf(cls, path: str, qtype: Optional[str] = None) -> TpuModel:
        """Load a llama.cpp GGUF file (reference transformers/model.py:391
        `from_gguf`). qtype=None keeps the file's native low-bit formats
        (q4_0→sym_int4 etc., repacked without dequantization)."""
        from bigdl_tpu.convert.gguf import load_gguf

        config, params = load_gguf(path, qtype=qtype)
        return _merged_model(config, params, qtype or "gguf_native")
